"""Sidecar agent: proxy lifecycle with hot-restart epochs.

Reference: pilot/pkg/proxy/agent.go (design doc :34-58): the agent
reconciles desired config against the set of running proxy epochs.
Every config change starts epoch N+1 (`envoy --restart-epoch N+1`
drains the old process); a crashed epoch is retried with an
exponential-backoff budget (Retry :102); agent shutdown aborts all
epochs (:300). The Proxy is injectable (tests use an in-process fake;
production wraps the envoy binary exactly like envoy.go + the
per-epoch config files watcher.go:233 writes).

Cert watcher (envoy/watcher.go:84-210): hashes the watched cert paths
and schedules a reconcile when the hash changes.
"""
from __future__ import annotations

import dataclasses
import hashlib
import logging
import os
import threading
import time
from typing import Any, Callable, Mapping

log = logging.getLogger("istio_tpu.pilot.agent")

MAX_RETRIES = 10
INITIAL_BACKOFF_S = 0.2


class Proxy:
    """envoy.go Proxy contract: run/cleanup/panic per epoch."""

    def run(self, config: Any, epoch: int,
            abort: threading.Event) -> None:
        """Blocks until the epoch exits; raise on abnormal exit."""
        raise NotImplementedError

    def cleanup(self, epoch: int) -> None:
        pass


@dataclasses.dataclass
class _Epoch:
    config: Any
    epoch: int
    abort: threading.Event
    thread: threading.Thread


class Agent:
    """agent.go NewAgent/Run/ScheduleConfigUpdate."""

    def __init__(self, proxy: Proxy):
        self.proxy = proxy
        self._lock = threading.Lock()
        self._desired: Any = None
        self._epochs: dict[int, _Epoch] = {}
        self._current_config: Any = object()   # sentinel ≠ any config
        self._retries = 0
        self._retry_timer: threading.Timer | None = None
        self._shutdown = False

    # -- public --

    def schedule_config_update(self, config: Any) -> None:
        """watcher → agent: desired config changed (agent.go:92). A new
        desired config gets a FRESH retry budget (agent.go resets the
        budget per reconcile; a crash-looping old config must not
        exhaust retries for its replacement)."""
        with self._lock:
            if config != self._desired:
                self._retries = 0
            self._desired = config
        self._reconcile()

    def active_epochs(self) -> list[int]:
        with self._lock:
            return sorted(e for e, ep in self._epochs.items()
                          if ep.thread.is_alive())

    def close(self) -> None:
        with self._lock:
            self._shutdown = True
            if self._retry_timer is not None:
                self._retry_timer.cancel()
            epochs = list(self._epochs.values())
        for ep in epochs:      # abortAll (agent.go:300)
            ep.abort.set()
        for ep in epochs:
            ep.thread.join(timeout=5)

    # -- internals --

    def _reconcile(self) -> None:
        """agent.go:259 reconcile: spawn a new epoch iff the desired
        config differs from the latest running epoch's config."""
        with self._lock:
            if self._shutdown:
                return
            if self._desired == self._current_config:
                return
            epoch = (max(self._epochs) + 1) if self._epochs else 0
            abort = threading.Event()
            config = self._desired
            ep = _Epoch(config=config, epoch=epoch, abort=abort,
                        thread=threading.Thread(
                            target=self._run_epoch,
                            args=(config, epoch, abort),
                            daemon=True, name=f"proxy-epoch-{epoch}"))
            self._epochs[epoch] = ep
            self._current_config = config
        log.info("starting proxy epoch %d", epoch)
        ep.thread.start()

    def _run_epoch(self, config: Any, epoch: int,
                   abort: threading.Event) -> None:
        crashed = False
        try:
            self.proxy.run(config, epoch, abort)
            with self._lock:
                self._retries = 0
        except Exception as exc:
            crashed = True
            log.warning("epoch %d died: %s", epoch, exc)
        finally:
            self.proxy.cleanup(epoch)
            with self._lock:
                self._epochs.pop(epoch, None)
                # agent.go:199 semantics: the effective config is the
                # latest SURVIVING epoch's; with none left, nothing runs
                if self._epochs:
                    latest = max(self._epochs)
                    self._current_config = self._epochs[latest].config
                else:
                    self._current_config = object()
            if crashed:
                self._schedule_retry(epoch)
            elif not abort.is_set():
                # normal non-abort exit (external kill): respawn iff the
                # desired config is no longer effectively running
                self._reconcile()

    def _schedule_retry(self, epoch: int) -> None:
        """Exponential backoff restart budget (agent.go:102 Retry).
        The epoch-exit handler already recomputed _current_config, so
        the delayed reconcile only respawns when the crash actually
        took down the desired config (an old draining epoch's crash is
        a no-op because a newer epoch still carries it)."""
        with self._lock:
            if self._shutdown:
                return
            if self._retries >= MAX_RETRIES:
                log.error("retry budget exhausted for epoch %d", epoch)
                return
            delay = INITIAL_BACKOFF_S * (2 ** self._retries)
            self._retries += 1
            self._retry_timer = threading.Timer(delay, self._reconcile)
            self._retry_timer.daemon = True
            self._retry_timer.start()
        log.info("retry %d for proxy in %.1fs", self._retries, delay)


class CertWatcher:
    """envoy/watcher.go:84-210: poll cert paths, SHA-256 the contents,
    fire the callback (agent.ScheduleConfigUpdate) on change."""

    def __init__(self, paths: list[str], on_change: Callable[[str], None],
                 poll_s: float = 0.5):
        self.paths = list(paths)
        self.on_change = on_change
        self.poll_s = poll_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="cert-watcher")
        self._last = self.hash_certs()

    def start(self) -> None:
        self._thread.start()

    def hash_certs(self) -> str:
        h = hashlib.sha256()
        for path in sorted(self.paths):
            h.update(path.encode())
            try:
                if os.path.isdir(path):
                    for name in sorted(os.listdir(path)):
                        with open(os.path.join(path, name), "rb") as f:
                            h.update(f.read())
                else:
                    with open(path, "rb") as f:
                        h.update(f.read())
            except OSError:
                h.update(b"<missing>")
        return h.hexdigest()

    def _loop(self) -> None:
        while not self._stop.wait(self.poll_s):
            current = self.hash_certs()
            if current != self._last:
                self._last = current
                log.info("certs changed; scheduling proxy update")
                self.on_change(current)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
