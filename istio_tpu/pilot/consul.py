"""Consul service registry.

Reference: pilot/pkg/serviceregistry/consul/{controller,conversion,
monitor}.go — a ServiceDiscovery backend over Consul's HTTP catalog
API (`/v1/catalog/services`, `/v1/catalog/service/<name>`), plus a
polling monitor that diffs successive catalog snapshots and fires
service/instance change handlers (monitor.go:49-76).

Conversion semantics preserved (conversion.go):
  - tags of the form ``key|value`` become labels; malformed tags are
    ignored (conversion.go:33-45),
  - node-meta ``protocol`` selects the port protocol, default name
    "http" (conversion.go:47-57),
  - node-meta ``external`` marks mesh-external services,
  - ServiceAddress falls back to the node Address (conversion.go:100),
  - hostname is ``<name>.service.consul`` (parseHostname inverse).

This image has no consul agent, so the client speaks the real HTTP
API against :class:`FakeConsulServer` — an in-process catalog that
serves the same JSON shapes (the hermetic-registry testing lesson,
SURVEY.md §4).
"""
from __future__ import annotations

import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Mapping, Sequence

from istio_tpu.pilot.model import (NetworkEndpoint, Port, Service,
                                   ServiceInstance)
from istio_tpu.pilot.registry import ServiceDiscovery

import logging

log = logging.getLogger("istio_tpu.pilot.consul")

PROTOCOL_TAG = "protocol"
EXTERNAL_TAG = "external"
DOMAIN_SUFFIX = ".service.consul"


def service_hostname(name: str) -> str:
    return f"{name}{DOMAIN_SUFFIX}"


def parse_hostname(hostname: str) -> str:
    """controller.go parseHostname: strip the .service.consul suffix."""
    if not hostname.endswith(DOMAIN_SUFFIX):
        raise ValueError(f"not a consul hostname: {hostname!r}")
    return hostname[: -len(DOMAIN_SUFFIX)]


def convert_labels(tags: Sequence[str]) -> dict[str, str]:
    """conversion.go:33-45 — only ``key|value`` tags become labels."""
    out: dict[str, str] = {}
    for tag in tags:
        vals = tag.split("|")
        if len(vals) > 1:
            out[vals[0]] = vals[1]
        else:
            log.warning("consul tag %r ignored (not key|value)", tag)
    return out


def convert_port(port: int, name: str) -> Port:
    name = name or "http"
    from istio_tpu.kube.registry import protocol_from_port_name
    return Port(name=name, port=port,
                protocol=protocol_from_port_name(name))


def convert_service(endpoints: Sequence[Mapping[str, Any]]) -> Service:
    """conversion.go:59-97 — merge catalog entries into one Service."""
    name, external = "", ""
    ports: dict[int, Port] = {}
    for ep in endpoints:
        name = ep["ServiceName"]
        meta = ep.get("NodeMeta") or {}
        port = convert_port(ep["ServicePort"], meta.get(PROTOCOL_TAG, ""))
        prev = ports.get(port.port)
        if prev is not None and prev.protocol != port.protocol:
            log.warning("consul service %s port %d has conflicting "
                     "protocols (%s, %s)", name, port.port,
                     prev.protocol, port.protocol)
        else:
            ports[port.port] = port
        if meta.get(EXTERNAL_TAG):
            external = meta[EXTERNAL_TAG]
    return Service(hostname=service_hostname(name), address="",
                   ports=tuple(ports[p] for p in sorted(ports)),
                   external_name=external)


def convert_instance(ep: Mapping[str, Any]) -> ServiceInstance:
    """conversion.go:99-130."""
    meta = ep.get("NodeMeta") or {}
    labels = convert_labels(ep.get("ServiceTags") or [])
    port = convert_port(ep["ServicePort"], meta.get(PROTOCOL_TAG, ""))
    addr = ep.get("ServiceAddress") or ep.get("Address") or ""
    svc = Service(hostname=service_hostname(ep["ServiceName"]),
                  address=ep.get("ServiceAddress") or "",
                  ports=(port,),
                  external_name=meta.get(EXTERNAL_TAG, ""))
    return ServiceInstance(
        endpoint=NetworkEndpoint(address=addr, port=ep["ServicePort"],
                                 service_port=port),
        service=svc, labels=labels,
        availability_zone=ep.get("Datacenter", ""))


class ConsulClient:
    """Minimal Consul catalog HTTP client (hashicorp/consul/api role)."""

    def __init__(self, addr: str, timeout_s: float = 10.0):
        self.base = f"http://{addr}" if "://" not in addr else addr
        self.timeout_s = timeout_s

    def _get(self, path: str) -> Any:
        with urllib.request.urlopen(self.base + path,
                                    timeout=self.timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def services(self) -> dict[str, list[str]]:
        return self._get("/v1/catalog/services")

    def service(self, name: str) -> list[dict]:
        return self._get(f"/v1/catalog/service/{name}")


class ConsulRegistry(ServiceDiscovery):
    """controller.go Controller + monitor.go polling diff.

    Queries go straight to the catalog (the reference controller is
    uncached too); the monitor thread polls at `poll_s`, diffs the
    snapshot, and fires service handlers so the discovery cache
    invalidates exactly like the kube registry does.
    """

    def __init__(self, addr: str, poll_s: float = 2.0,
                 client: ConsulClient | None = None):
        self.client = client or ConsulClient(addr)
        self.poll_s = poll_s
        self._svc_handlers: list[Callable[[Service, str], None]] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._snapshot: dict[str, list[str]] = {}

    # -- ServiceDiscovery --

    def services(self) -> list[Service]:
        out = []
        for name in sorted(self.client.services()):
            eps = self.client.service(name)
            if eps:
                out.append(convert_service(eps))
        return out

    def get_service(self, hostname: str) -> Service | None:
        try:
            name = parse_hostname(hostname)
        except ValueError:
            return None
        eps = self.client.service(name)
        return convert_service(eps) if eps else None

    def instances(self, hostname, ports=(), labels=None):
        try:
            name = parse_hostname(hostname)
        except ValueError:
            return []
        want_ports = set(ports)
        out = []
        for ep in self.client.service(name):
            inst = convert_instance(ep)
            if want_ports and inst.endpoint.service_port.name not in want_ports:
                continue
            if labels and any(inst.labels.get(k) != v
                              for k, v in labels.items()):
                continue
            out.append(inst)
        return out

    def host_instances(self, addrs: set[str]) -> list[ServiceInstance]:
        out = []
        for name in self.client.services():
            for ep in self.client.service(name):
                inst = convert_instance(ep)
                if inst.endpoint.address in addrs:
                    out.append(inst)
        return out

    # -- monitor (monitor.go) --

    def append_service_handler(self, fn: Callable[[Service, str], None]
                               ) -> None:
        self._svc_handlers.append(fn)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._snapshot = dict(self.client.services())
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="consul-monitor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            self._poll_once()

    def _poll_once(self) -> None:
        try:
            now = dict(self.client.services())
        except Exception as exc:   # monitor.go logs and keeps polling
            log.warning("consul poll failed: %s", exc)
            return
        before = self._snapshot
        self._snapshot = now
        for name in now:
            if name not in before:
                self._fire(name, "add")
            elif now[name] != before[name]:
                self._fire(name, "update")
        for name in before:
            if name not in now:
                self._fire(name, "delete")

    def _fire(self, name: str, event: str) -> None:
        svc = Service(hostname=service_hostname(name))
        for fn in list(self._svc_handlers):
            try:
                fn(svc, event)
            except Exception:
                log.exception("consul service handler failed")


# ---------------------------------------------------------------------------
# in-process fake (hermetic test backbone, SURVEY §4 lesson (e))
# ---------------------------------------------------------------------------

class FakeConsulServer:
    """Serves the two catalog endpoints the registry consumes, with the
    real API's JSON shapes, over a loopback HTTP server."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._catalog: dict[str, list[dict]] = {}
        fake = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):   # silence
                pass

            def do_GET(self):
                path = self.path.split("?")[0]
                if path == "/v1/catalog/services":
                    body = fake._services_json()
                elif path.startswith("/v1/catalog/service/"):
                    body = fake._service_json(path.rsplit("/", 1)[1])
                else:
                    self.send_error(404)
                    return
                raw = json.dumps(body).encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="fake-consul")
        self._thread.start()

    @property
    def addr(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"{host}:{port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def register(self, name: str, *, address: str, port: int,
                 service_address: str = "", tags: Sequence[str] = (),
                 node_meta: Mapping[str, str] | None = None,
                 datacenter: str = "dc1") -> None:
        entry = {"ServiceName": name, "Address": address,
                 "ServiceAddress": service_address, "ServicePort": port,
                 "ServiceTags": list(tags),
                 "NodeMeta": dict(node_meta or {}),
                 "Datacenter": datacenter}
        with self._lock:
            self._catalog.setdefault(name, []).append(entry)

    def deregister(self, name: str) -> None:
        with self._lock:
            self._catalog.pop(name, None)

    def _services_json(self) -> dict[str, list[str]]:
        with self._lock:
            return {n: sorted({t for e in eps
                               for t in e["ServiceTags"]})
                    for n, eps in self._catalog.items()}

    def _service_json(self, name: str) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._catalog.get(name, [])]
