"""Pilot — the proxy-config control plane (reference: pilot/, SURVEY.md
§2.6): an abstract service/routing model populated by platform
registries, compiled into per-sidecar Envoy v1 JSON configuration and
served over the v1 REST discovery API (SDS/CDS/RDS/LDS) with a
wholesale-invalidated response cache; plus the sidecar agent that
manages Envoy hot-restart epochs.

TPU tie-in (BASELINE.json shared-automaton requirement): route-rule
header/URI matches are ALSO compiled into the same ruleset tensors the
policy engine runs (pilot/route_nfa.py), so L7 route selection for a
batch of requests is one device step.
"""
from istio_tpu.pilot.model import (Config, ConfigMeta, ConfigStore,
                                   IstioConfigStore, MemoryConfigStore,
                                   NetworkEndpoint, Port, Service,
                                   ServiceInstance, ValidationError)
from istio_tpu.pilot.registry import (AggregateRegistry, MemoryRegistry,
                                      ServiceDiscovery)

__all__ = ["Config", "ConfigMeta", "ConfigStore", "IstioConfigStore",
           "MemoryConfigStore", "NetworkEndpoint", "Port", "Service",
           "ServiceInstance", "ValidationError", "AggregateRegistry",
           "MemoryRegistry", "ServiceDiscovery"]
