"""Cloud Foundry service registry (copilot-backed).

Reference: pilot/pkg/serviceregistry/cloudfoundry/{servicediscovery,
controller,config}.go — a ServiceDiscovery over CF's *copilot* gRPC
API: one ``Routes()`` RPC returns a map of hostname → backend set
(address, port), and every model query is a view over that response.
CF apps expose a single HTTP port (typically 8080), so every service
gets exactly one ServicePort (servicediscovery.go:20-23).

The copilot wire contract is reduced to :class:`CopilotClient`
(``routes() -> {hostname: [(address, port), ...]}``); production
would back it with the copilot gRPC stub + client TLS from
config.go, tests use :class:`InProcessCopilot`. The reference's
controller has no watch — Routes() is polled per query and a ticker
fires cache invalidation (controller.go); the same ticker drives
`append_service_handler` here.
"""
from __future__ import annotations

import threading
from typing import Callable, Mapping, Sequence

from istio_tpu.pilot.model import (NetworkEndpoint, Port, Service,
                                   ServiceInstance)
from istio_tpu.pilot.registry import ServiceDiscovery

import logging

log = logging.getLogger("istio_tpu.pilot.cloudfoundry")

DEFAULT_SERVICE_PORT = 8080


class CopilotClient:
    """copilotapi.IstioCopilotClient, reduced to the one used RPC."""

    def routes(self) -> Mapping[str, Sequence[tuple[str, int]]]:
        raise NotImplementedError


class InProcessCopilot(CopilotClient):
    """Test/fake copilot (mockcopilotclient_test.go role)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._backends: dict[str, list[tuple[str, int]]] = {}

    def set_route(self, hostname: str,
                  backends: Sequence[tuple[str, int]]) -> None:
        with self._lock:
            self._backends[hostname] = list(backends)

    def delete_route(self, hostname: str) -> None:
        with self._lock:
            self._backends.pop(hostname, None)

    def routes(self) -> dict[str, list[tuple[str, int]]]:
        with self._lock:
            return {h: list(b) for h, b in self._backends.items()}


class CloudFoundryRegistry(ServiceDiscovery):
    """servicediscovery.go over a CopilotClient."""

    def __init__(self, client: CopilotClient,
                 service_port: int = DEFAULT_SERVICE_PORT,
                 poll_s: float = 2.0):
        self.client = client
        self.service_port = service_port
        self.poll_s = poll_s
        self._svc_handlers: list[Callable[[Service, str], None]] = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._snapshot: set[str] = set()

    def _port(self) -> Port:
        return Port(name="http", port=self.service_port, protocol="HTTP")

    def _service(self, hostname: str) -> Service:
        return Service(hostname=hostname, address="",
                       ports=(self._port(),))

    def _routes(self) -> Mapping[str, Sequence[tuple[str, int]]]:
        try:
            return self.client.routes()
        except Exception as exc:
            log.warning("copilot Routes() failed: %s", exc)
            return {}

    # -- ServiceDiscovery --

    def services(self) -> list[Service]:
        return [self._service(h) for h in sorted(self._routes())]

    def get_service(self, hostname: str) -> Service | None:
        return (self._service(hostname)
                if hostname in self._routes() else None)

    def instances(self, hostname, ports=(), labels=None):
        if labels:   # CF has no instance labels (servicediscovery.go)
            return []
        backends = self._routes().get(hostname)
        if not backends:
            return []
        port = self._port()
        if ports and port.name not in set(ports):
            return []
        svc = self._service(hostname)
        return [ServiceInstance(
                    endpoint=NetworkEndpoint(address=addr, port=p,
                                             service_port=port),
                    service=svc)
                for addr, p in backends]

    def host_instances(self, addrs: set[str]) -> list[ServiceInstance]:
        out = []
        port = self._port()
        for hostname, backends in self._routes().items():
            svc = self._service(hostname)
            for addr, p in backends:
                if addr in addrs:
                    out.append(ServiceInstance(
                        endpoint=NetworkEndpoint(address=addr, port=p,
                                                 service_port=port),
                        service=svc))
        return out

    # -- controller.go ticker --

    def append_service_handler(self, fn: Callable[[Service, str], None]
                               ) -> None:
        self._svc_handlers.append(fn)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._snapshot = set(self._routes())
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cf-monitor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            now = set(self._routes())
            before, self._snapshot = self._snapshot, now
            for host in now - before:
                self._fire(host, "add")
            for host in before - now:
                self._fire(host, "delete")

    def _fire(self, hostname: str, event: str) -> None:
        svc = self._service(hostname)
        for fn in list(self._svc_handlers):
            try:
                fn(svc, event)
            except Exception:
                log.exception("cf service handler failed")
