"""Pilot abstract model: services, instances, config resources.

Reference: pilot/pkg/model — Service (service.go:44), NetworkEndpoint
(:170), ServiceInstance (:211), Config/ConfigMeta (config.go:34-108),
ConfigStore (:110), ProtoSchema registry `IstioConfigTypes`
(config.go:407-418), IstioConfigStore queries (:227-265), and per-kind
validation (validation.go). Specs are plain dicts validated per kind
(the reference validates protobufs; the shapes match the v1alpha1/2
route-rule schemas so reference YAML translates 1:1).
"""
from __future__ import annotations

import dataclasses
import fnmatch
import re
import threading
from typing import Any, Callable, Iterable, Mapping, Sequence


class ValidationError(ValueError):
    pass


# ---------------------------------------------------------------------------
# services
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Port:
    """service.go:96 Port{Name, Port, Protocol}."""
    name: str
    port: int
    protocol: str = "HTTP"   # HTTP|HTTPS|HTTP2|GRPC|TCP|UDP|MONGO|REDIS

    @property
    def is_http(self) -> bool:
        return self.protocol in ("HTTP", "HTTP2", "GRPC", "HTTPS")


@dataclasses.dataclass(frozen=True)
class Service:
    """service.go:44 Service{Hostname, Address, Ports, ...}."""
    hostname: str
    address: str = "0.0.0.0"
    ports: tuple[Port, ...] = ()
    external_name: str = ""       # ExternalName for mesh-external
    service_account: str = ""

    @property
    def namespace(self) -> str:
        parts = self.hostname.split(".")
        return parts[1] if len(parts) > 1 else ""

    def port_by_name(self, name: str) -> Port | None:
        for p in self.ports:
            if p.name == name:
                return p
        return None

    def key(self, port: Port) -> str:
        return f"{self.hostname}|{port.name}"


@dataclasses.dataclass(frozen=True)
class NetworkEndpoint:
    """service.go:170 — one addressable instance port."""
    address: str
    port: int
    service_port: Port


@dataclasses.dataclass(frozen=True)
class ServiceInstance:
    """service.go:211 — endpoint + owning service + labels."""
    endpoint: NetworkEndpoint
    service: Service
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    availability_zone: str = ""
    service_account: str = ""


# ---------------------------------------------------------------------------
# config resources
# ---------------------------------------------------------------------------

NODE_SIDECAR = "sidecar"
NODE_INGRESS = "ingress"
NODE_ROUTER = "router"


@dataclasses.dataclass(frozen=True)
class Node:
    """context.go:51 Node{Type, IPAddress, ID, Domain} — the proxy
    role; the discovery node-id convention is `type~ip~id~domain`."""
    type: str = NODE_SIDECAR
    ip_address: str = ""
    id: str = ""
    domain: str = "cluster.local"

    @classmethod
    def parse(cls, service_node: str) -> "Node":
        parts = service_node.split("~")
        if parts[0] in (NODE_SIDECAR, NODE_INGRESS, NODE_ROUTER):
            return cls(type=parts[0],
                       ip_address=parts[1] if len(parts) > 1 else "",
                       id=parts[2] if len(parts) > 2 else "",
                       domain=parts[3] if len(parts) > 3
                       else "cluster.local")
        # legacy bare-IP node ids read as sidecars
        return cls(type=NODE_SIDECAR, ip_address=parts[0])

    @property
    def service_node(self) -> str:
        return "~".join([self.type, self.ip_address, self.id,
                         self.domain])


@dataclasses.dataclass(frozen=True)
class ConfigMeta:
    """config.go:34 ConfigMeta."""
    type: str
    name: str
    namespace: str = ""
    domain: str = "cluster.local"
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    annotations: Mapping[str, str] = dataclasses.field(default_factory=dict)
    resource_version: str = ""


@dataclasses.dataclass(frozen=True)
class Config:
    meta: ConfigMeta
    spec: Mapping[str, Any]

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.meta.type, self.meta.namespace, self.meta.name)


@dataclasses.dataclass(frozen=True)
class ProtoSchema:
    """config.go:181 — type descriptor + validator."""
    type: str
    plural: str
    validate: Callable[[Mapping[str, Any]], None]


def _check_percent(value: Any, what: str) -> None:
    try:
        p = float(value)
    except (TypeError, ValueError):
        raise ValidationError(f"{what}: percent not a number: {value!r}")
    if not 0 <= p <= 100:
        raise ValidationError(f"{what}: percent {p} out of [0, 100]")


def _check_duration(value: Any, what: str) -> None:
    """Go-style duration strings ('5s', '100ms') or plain seconds."""
    if isinstance(value, (int, float)):
        seconds = float(value)
    else:
        s = str(value)
        try:
            if s.endswith("ms"):
                seconds = float(s[:-2]) / 1000
            elif s.endswith("s"):
                seconds = float(s[:-1])
            else:
                seconds = float(s)
        except ValueError:
            raise ValidationError(f"{what}: bad duration {value!r}")
    if seconds < 0:
        raise ValidationError(f"{what}: negative duration {value!r}")


def _check_port_number(value: Any, what: str) -> None:
    try:
        port = int(value)
    except (TypeError, ValueError):
        raise ValidationError(f"{what}: port not a number: {value!r}")
    if not 1 <= port <= 65535:
        raise ValidationError(f"{what}: port {port} out of [1, 65535]")


_MATCH_SCHEMES = {"exact", "prefix", "regex", "presence"}


def _check_match(match: Mapping[str, Any], what: str) -> None:
    """validation.go ValidateMatchCondition: each header condition uses
    exactly one known scheme; conflicting URI schemes rejected."""
    if not match:
        return
    headers = (match.get("request", {}) or {}).get("headers", {}) \
        if "request" in match else match.get("headers", {}) or {}
    if not isinstance(headers, Mapping):
        raise ValidationError(f"{what}: match headers must be a map")
    for name, cond in headers.items():
        if cond in (None, {}):
            continue   # presence match
        if not isinstance(cond, Mapping):
            raise ValidationError(
                f"{what}: header {name} condition must be a map")
        schemes = set(cond) & _MATCH_SCHEMES
        unknown = set(cond) - _MATCH_SCHEMES
        if unknown:
            raise ValidationError(
                f"{what}: header {name} unknown scheme(s) "
                f"{sorted(unknown)}")
        if len(schemes) > 1:
            raise ValidationError(
                f"{what}: header {name} has conflicting schemes "
                f"{sorted(schemes)} (exactly one allowed)")


def _validate_route_rule(spec: Mapping[str, Any]) -> None:
    """validation.go ValidateRouteRule (v1alpha1 shape): the rejection
    set covers weights, percentages, durations, conflicting match
    schemes, redirect/route exclusivity, and port semantics."""
    if not spec.get("destination"):
        raise ValidationError("route-rule: destination required")
    _check_match(spec.get("match") or {}, "route-rule match")
    if spec.get("redirect") and spec.get("route"):
        raise ValidationError(
            "route-rule: redirect and route are mutually exclusive")
    if spec.get("redirect") and spec.get("httpFault"):
        raise ValidationError(
            "route-rule: redirect cannot carry httpFault")
    total = 0
    for r in spec.get("route", ()):
        w = int(r.get("weight", 0))
        if w < 0 or w > 100:
            raise ValidationError("route-rule: weight must be 0-100")
        total += w
    routes = spec.get("route", ())
    if len(routes) > 1 and total != 100:
        raise ValidationError(
            f"route-rule: weights sum to {total}, not 100")
    if len(routes) == 1 and total not in (0, 100):
        raise ValidationError(
            f"route-rule: single-route weight must be 0 or 100, "
            f"got {total}")
    fault = spec.get("httpFault", {})
    if fault:
        abort = fault.get("abort", {})
        if abort:
            _check_percent(abort.get("percent", 0), "route-rule abort")
            status = int(abort.get("httpStatus",
                                   abort.get("http_status", 503)))
            if not 200 <= status <= 599:
                raise ValidationError(
                    f"route-rule: abort httpStatus {status} invalid")
        delay = fault.get("delay", {})
        if delay:
            _check_percent(delay.get("percent", 0), "route-rule delay")
            _check_duration(delay.get("fixedDelay", "0s"),
                            "route-rule delay")
    timeout = spec.get("httpReqTimeout", {}).get("simpleTimeout", {})
    if timeout.get("timeout") is not None:
        _check_duration(timeout["timeout"], "route-rule timeout")
    retries = spec.get("httpReqRetries", {}).get("simpleRetry", {})
    if retries and int(retries.get("attempts", 0)) < 0:
        raise ValidationError("route-rule: negative retry attempts")
    if "precedence" in spec and int(spec["precedence"]) < 0:
        raise ValidationError("route-rule: negative precedence")
    mirror = spec.get("mirror")
    if mirror is not None and not isinstance(mirror, Mapping):
        raise ValidationError("route-rule: mirror must be a message")


def _validate_v1alpha2_route_rule(spec: Mapping[str, Any]) -> None:
    """v1alpha2 RouteRule (hosts + http routes — the VirtualService
    precursor, config.go:312)."""
    if not spec.get("hosts"):
        raise ValidationError("v1alpha2 route-rule: hosts required")
    for http in spec.get("http", ()):
        total = 0
        for route in http.get("route", ()):
            if not route.get("destination"):
                raise ValidationError("v1alpha2: route needs destination")
            total += int(route.get("weight", 0))
        if len(http.get("route", ())) > 1 and total != 100:
            raise ValidationError(
                f"v1alpha2: weights sum to {total}, not 100")


def _validate_destination_policy(spec: Mapping[str, Any]) -> None:
    if not spec.get("destination"):
        raise ValidationError("destination-policy: destination required")
    lb = spec.get("loadBalancing", {})
    if lb.get("name") and lb["name"] not in ("ROUND_ROBIN", "LEAST_CONN",
                                             "RANDOM"):
        raise ValidationError(
            f"destination-policy: unknown LB policy {lb['name']!r}")
    cb = spec.get("circuitBreaker", {}).get("simpleCb", {})
    for k in ("maxConnections", "httpMaxPendingRequests",
              "httpMaxRequests", "httpMaxRetries",
              "httpConsecutiveErrors"):
        if k in cb and int(cb[k]) < 0:
            raise ValidationError(f"destination-policy: negative {k}")
    for k in ("httpDetectionInterval", "sleepWindow"):
        if k in cb:
            _check_duration(cb[k], f"destination-policy {k}")


def _validate_destination_rule(spec: Mapping[str, Any]) -> None:
    if not spec.get("host") and not spec.get("name"):
        raise ValidationError("destination-rule: host required")
    seen = set()
    for subset in spec.get("subsets", ()):
        name = subset.get("name")
        if not name:
            raise ValidationError("destination-rule: subset needs a name")
        if name in seen:
            raise ValidationError(
                f"destination-rule: duplicate subset {name!r}")
        seen.add(name)
        if not subset.get("labels"):
            raise ValidationError(
                f"destination-rule: subset {name!r} needs labels")


def _validate_gateway(spec: Mapping[str, Any]) -> None:
    if not spec.get("servers"):
        raise ValidationError("gateway: servers required")
    for server in spec["servers"]:
        port = server.get("port", {})
        if not port:
            raise ValidationError("gateway: server needs a port")
        _check_port_number(port.get("number", port.get("port")),
                           "gateway server")
        if not server.get("hosts"):
            raise ValidationError("gateway: server needs hosts")


def _validate_ingress_rule(spec: Mapping[str, Any]) -> None:
    if not spec.get("destination"):
        raise ValidationError("ingress-rule: destination required")
    port = spec.get("port")
    if port is None:
        raise ValidationError("ingress-rule: port required")
    # numeric ports (including numeric strings) must be in range;
    # non-numeric strings are named service ports
    if not isinstance(port, str) or port.isdigit():
        _check_port_number(port, "ingress-rule")
    _check_match(spec.get("match") or {}, "ingress-rule match")


def _validate_egress_rule(spec: Mapping[str, Any]) -> None:
    dest = spec.get("destination", {})
    service = str(dest.get("service", "") or "")
    if not service:
        raise ValidationError("egress-rule: destination.service required")
    if "*" in service[1:]:
        raise ValidationError(
            "egress-rule: wildcard only allowed as a leading label")
    if not spec.get("ports"):
        raise ValidationError("egress-rule: ports required")
    for p in spec["ports"]:
        _check_port_number(p.get("port"), "egress-rule")
        proto = str(p.get("protocol", "http")).lower()
        if proto not in ("http", "http2", "grpc", "https", "tcp"):
            raise ValidationError(
                f"egress-rule: unsupported protocol {proto!r}")


def _validate_spec_binding(spec: Mapping[str, Any]) -> None:
    return None


# config.go:407-418 IstioConfigTypes
IstioConfigTypes: dict[str, ProtoSchema] = {s.type: s for s in [
    ProtoSchema("route-rule", "route-rules", _validate_route_rule),
    ProtoSchema("v1alpha2-route-rule", "v1alpha2-route-rules",
                _validate_v1alpha2_route_rule),
    ProtoSchema("gateway", "gateways", _validate_gateway),
    ProtoSchema("ingress-rule", "ingress-rules", _validate_ingress_rule),
    ProtoSchema("egress-rule", "egress-rules", _validate_egress_rule),
    ProtoSchema("destination-policy", "destination-policies",
                _validate_destination_policy),
    ProtoSchema("destination-rule", "destination-rules",
                _validate_destination_rule),
    ProtoSchema("http-api-spec", "http-api-specs", _validate_spec_binding),
    ProtoSchema("http-api-spec-binding", "http-api-spec-bindings",
                _validate_spec_binding),
    ProtoSchema("quota-spec", "quota-specs", _validate_spec_binding),
    ProtoSchema("quota-spec-binding", "quota-spec-bindings",
                _validate_spec_binding),
    ProtoSchema("end-user-authentication-policy-spec",
                "end-user-authentication-policy-specs",
                _validate_spec_binding),
    ProtoSchema("end-user-authentication-policy-spec-binding",
                "end-user-authentication-policy-spec-bindings",
                _validate_spec_binding),
]}


class ConfigStore:
    """config.go:110 ConfigStore: typed CRUD with validation."""

    def get(self, typ: str, name: str, namespace: str) -> Config | None:
        raise NotImplementedError

    def list(self, typ: str, namespace: str | None = None) -> list[Config]:
        raise NotImplementedError

    def create(self, config: Config) -> None:
        raise NotImplementedError

    def update(self, config: Config) -> None:
        raise NotImplementedError

    def delete(self, typ: str, name: str, namespace: str) -> None:
        raise NotImplementedError


class MemoryConfigStore(ConfigStore):
    """pilot/pkg/config/memory — the hermetic test backbone; also the
    ConfigStoreCache (config.go:162): handlers fire on changes."""

    def __init__(self) -> None:
        self._data: dict[tuple[str, str, str], Config] = {}
        self._lock = threading.Lock()
        self._handlers: list[Callable[[Config, str], None]] = []

    def register_handler(self, fn: Callable[[Config, str], None]) -> None:
        self._handlers.append(fn)

    def snapshot(self) -> dict[tuple[str, str, str], Config]:
        """One consistent copy of the full store (the discovery
        snapshot builder's freeze point — a single lock acquisition,
        never a per-type scan racing concurrent writers)."""
        with self._lock:
            return dict(self._data)

    def _notify(self, config: Config, event: str) -> None:
        for fn in list(self._handlers):
            fn(config, event)

    def _validate(self, config: Config) -> None:
        schema = IstioConfigTypes.get(config.meta.type)
        if schema is None:
            raise ValidationError(f"unknown config type {config.meta.type}")
        schema.validate(config.spec)

    def get(self, typ, name, namespace=""):
        with self._lock:
            return self._data.get((typ, namespace, name))

    def list(self, typ, namespace=None):
        with self._lock:
            return [c for (t, ns, _), c in sorted(self._data.items())
                    if t == typ and (namespace is None or ns == namespace)]

    def create(self, config: Config) -> None:
        self._validate(config)
        with self._lock:
            if config.key in self._data:
                raise ValidationError(f"{config.key} already exists")
            self._data[config.key] = config
        self._notify(config, "add")

    def update(self, config: Config) -> None:
        self._validate(config)
        with self._lock:
            if config.key not in self._data:   # reference Update errors
                raise ValidationError(f"{config.key} not found")
            self._data[config.key] = config
        self._notify(config, "update")

    def delete(self, typ, name, namespace="") -> None:
        with self._lock:
            config = self._data.pop((typ, namespace, name), None)
        if config is not None:
            self._notify(config, "delete")


def _match_source(spec: Mapping[str, Any], source: str | None,
                  labels: Mapping[str, str] | None) -> bool:
    want = spec.get("match", {}).get("source", None)
    if want and source and want != source:
        return False
    want_labels = spec.get("match", {}).get("sourceTags") or \
        spec.get("match", {}).get("source_labels") or {}
    if want_labels and labels is not None:
        if any(labels.get(k) != v for k, v in want_labels.items()):
            return False
    return True


class IstioConfigStore:
    """config.go:227 query facade over a ConfigStore."""

    def __init__(self, store: ConfigStore):
        self.store = store

    @staticmethod
    def _destination_hostname(c: Config) -> str:
        """Resolve a rule's destination to an FQDN: short names qualify
        against the RULE's namespace + domain (the reference resolves
        names in the config's namespace, model.ResolveHostname)."""
        dest = c.spec.get("destination", {})
        if isinstance(dest, str):
            name = dest
        elif dest.get("service"):
            # IstioService.service: an FQDN, used verbatim
            return str(dest["service"])
        else:
            name = str(dest.get("name", ""))
        if "." in name or not name:
            return name
        ns = c.meta.namespace or "default"
        domain = c.meta.domain or "cluster.local"
        return f"{name}.{ns}.svc.{domain}"

    def route_rules(self, destination: str, source: str | None = None,
                    source_labels: Mapping[str, str] | None = None
                    ) -> list[Config]:
        """RouteRules by destination (+optional source filter), sorted
        by precedence DESC then name (route.go sorting)."""
        out = []
        for c in self.store.list("route-rule"):
            if self._destination_hostname(c) != destination:
                continue
            if not _match_source(c.spec, source, source_labels):
                continue
            out.append(c)
        out.sort(key=lambda c: (-int(c.spec.get("precedence", 0)),
                                c.meta.name))
        return out

    def destination_policy(self, destination: str,
                           labels: Mapping[str, str] | None = None
                           ) -> Config | None:
        for c in self.store.list("destination-policy"):
            if self._destination_hostname(c) != destination:
                continue
            dest = c.spec.get("destination", {})
            want = (dest.get("tags") or dest.get("labels") or {}) \
                if isinstance(dest, Mapping) else {}
            if want and labels is not None and \
                    any(labels.get(k) != v for k, v in want.items()):
                continue
            return c
        return None

    def egress_rules(self) -> list[Config]:
        return self.store.list("egress-rule")

    def ingress_rules(self) -> list[Config]:
        return self.store.list("ingress-rule")

    def http_api_specs(self, service: str) -> list[Config]:
        """HTTPAPISpecByDestination (config.go:265 family)."""
        bound = []
        for b in self.store.list("http-api-spec-binding"):
            for s in b.spec.get("services", ()):
                sname = s.get("name") if isinstance(s, Mapping) else s
                if sname == service or service.startswith(f"{sname}."):
                    bound.extend(r.get("name") for r in
                                 b.spec.get("api_specs", ()))
        return [c for c in self.store.list("http-api-spec")
                if c.meta.name in bound]

    def quota_specs(self, service: str) -> list[Config]:
        bound = []
        for b in self.store.list("quota-spec-binding"):
            for s in b.spec.get("services", ()):
                sname = s.get("name") if isinstance(s, Mapping) else s
                if sname == service or service.startswith(f"{sname}."):
                    bound.extend(r.get("name") for r in
                                 b.spec.get("quota_specs", ()))
        return [c for c in self.store.list("quota-spec")
                if c.meta.name in bound]
