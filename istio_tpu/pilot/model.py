"""Pilot abstract model: services, instances, config resources.

Reference: pilot/pkg/model — Service (service.go:44), NetworkEndpoint
(:170), ServiceInstance (:211), Config/ConfigMeta (config.go:34-108),
ConfigStore (:110), ProtoSchema registry `IstioConfigTypes`
(config.go:407-418), IstioConfigStore queries (:227-265), and per-kind
validation (validation.go). Specs are plain dicts validated per kind
(the reference validates protobufs; the shapes match the v1alpha1/2
route-rule schemas so reference YAML translates 1:1).
"""
from __future__ import annotations

import dataclasses
import fnmatch
import re
import threading
from typing import Any, Callable, Iterable, Mapping, Sequence


class ValidationError(ValueError):
    pass


# ---------------------------------------------------------------------------
# services
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Port:
    """service.go:96 Port{Name, Port, Protocol}."""
    name: str
    port: int
    protocol: str = "HTTP"   # HTTP|HTTPS|HTTP2|GRPC|TCP|UDP|MONGO|REDIS

    @property
    def is_http(self) -> bool:
        return self.protocol in ("HTTP", "HTTP2", "GRPC", "HTTPS")


@dataclasses.dataclass(frozen=True)
class Service:
    """service.go:44 Service{Hostname, Address, Ports, ...}."""
    hostname: str
    address: str = "0.0.0.0"
    ports: tuple[Port, ...] = ()
    external_name: str = ""       # ExternalName for mesh-external
    service_account: str = ""

    @property
    def namespace(self) -> str:
        parts = self.hostname.split(".")
        return parts[1] if len(parts) > 1 else ""

    def port_by_name(self, name: str) -> Port | None:
        for p in self.ports:
            if p.name == name:
                return p
        return None

    def key(self, port: Port) -> str:
        return f"{self.hostname}|{port.name}"


@dataclasses.dataclass(frozen=True)
class NetworkEndpoint:
    """service.go:170 — one addressable instance port."""
    address: str
    port: int
    service_port: Port


@dataclasses.dataclass(frozen=True)
class ServiceInstance:
    """service.go:211 — endpoint + owning service + labels."""
    endpoint: NetworkEndpoint
    service: Service
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    availability_zone: str = ""
    service_account: str = ""


# ---------------------------------------------------------------------------
# config resources
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ConfigMeta:
    """config.go:34 ConfigMeta."""
    type: str
    name: str
    namespace: str = ""
    domain: str = "cluster.local"
    labels: Mapping[str, str] = dataclasses.field(default_factory=dict)
    annotations: Mapping[str, str] = dataclasses.field(default_factory=dict)
    resource_version: str = ""


@dataclasses.dataclass(frozen=True)
class Config:
    meta: ConfigMeta
    spec: Mapping[str, Any]

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.meta.type, self.meta.namespace, self.meta.name)


@dataclasses.dataclass(frozen=True)
class ProtoSchema:
    """config.go:181 — type descriptor + validator."""
    type: str
    plural: str
    validate: Callable[[Mapping[str, Any]], None]


def _validate_route_rule(spec: Mapping[str, Any]) -> None:
    """validation.go ValidateRouteRule (v1alpha1 shape)."""
    if not spec.get("destination"):
        raise ValidationError("route-rule: destination required")
    total = 0
    for r in spec.get("route", ()):
        w = int(r.get("weight", 0))
        if w < 0 or w > 100:
            raise ValidationError("route-rule: weight must be 0-100")
        total += w
    if spec.get("route") and total not in (0, 100):
        raise ValidationError(f"route-rule: weights sum to {total}, not 100")
    fault = spec.get("httpFault", {})
    if fault:
        abort = fault.get("abort", {})
        if abort and not (100 >= float(abort.get("percent", 0)) >= 0):
            raise ValidationError("route-rule: abort percent out of range")
    if "precedence" in spec and int(spec["precedence"]) < 0:
        raise ValidationError("route-rule: negative precedence")


def _validate_v1alpha2_route_rule(spec: Mapping[str, Any]) -> None:
    """v1alpha2 RouteRule (hosts + http routes — the VirtualService
    precursor, config.go:312)."""
    if not spec.get("hosts"):
        raise ValidationError("v1alpha2 route-rule: hosts required")
    for http in spec.get("http", ()):
        for route in http.get("route", ()):
            if not route.get("destination"):
                raise ValidationError("v1alpha2: route needs destination")


def _validate_destination_policy(spec: Mapping[str, Any]) -> None:
    if not spec.get("destination"):
        raise ValidationError("destination-policy: destination required")
    cb = spec.get("circuitBreaker", {}).get("simpleCb", {})
    for k in ("maxConnections", "httpMaxPendingRequests"):
        if k in cb and int(cb[k]) < 0:
            raise ValidationError(f"destination-policy: negative {k}")


def _validate_destination_rule(spec: Mapping[str, Any]) -> None:
    if not spec.get("host") and not spec.get("name"):
        raise ValidationError("destination-rule: host required")


def _validate_gateway(spec: Mapping[str, Any]) -> None:
    if not spec.get("servers"):
        raise ValidationError("gateway: servers required")


def _validate_ingress_rule(spec: Mapping[str, Any]) -> None:
    if not spec.get("destination"):
        raise ValidationError("ingress-rule: destination required")


def _validate_egress_rule(spec: Mapping[str, Any]) -> None:
    dest = spec.get("destination", {})
    if not dest.get("service"):
        raise ValidationError("egress-rule: destination.service required")
    if not spec.get("ports"):
        raise ValidationError("egress-rule: ports required")


def _validate_spec_binding(spec: Mapping[str, Any]) -> None:
    return None


# config.go:407-418 IstioConfigTypes
IstioConfigTypes: dict[str, ProtoSchema] = {s.type: s for s in [
    ProtoSchema("route-rule", "route-rules", _validate_route_rule),
    ProtoSchema("v1alpha2-route-rule", "v1alpha2-route-rules",
                _validate_v1alpha2_route_rule),
    ProtoSchema("gateway", "gateways", _validate_gateway),
    ProtoSchema("ingress-rule", "ingress-rules", _validate_ingress_rule),
    ProtoSchema("egress-rule", "egress-rules", _validate_egress_rule),
    ProtoSchema("destination-policy", "destination-policies",
                _validate_destination_policy),
    ProtoSchema("destination-rule", "destination-rules",
                _validate_destination_rule),
    ProtoSchema("http-api-spec", "http-api-specs", _validate_spec_binding),
    ProtoSchema("http-api-spec-binding", "http-api-spec-bindings",
                _validate_spec_binding),
    ProtoSchema("quota-spec", "quota-specs", _validate_spec_binding),
    ProtoSchema("quota-spec-binding", "quota-spec-bindings",
                _validate_spec_binding),
    ProtoSchema("end-user-authentication-policy-spec",
                "end-user-authentication-policy-specs",
                _validate_spec_binding),
    ProtoSchema("end-user-authentication-policy-spec-binding",
                "end-user-authentication-policy-spec-bindings",
                _validate_spec_binding),
]}


class ConfigStore:
    """config.go:110 ConfigStore: typed CRUD with validation."""

    def get(self, typ: str, name: str, namespace: str) -> Config | None:
        raise NotImplementedError

    def list(self, typ: str, namespace: str | None = None) -> list[Config]:
        raise NotImplementedError

    def create(self, config: Config) -> None:
        raise NotImplementedError

    def update(self, config: Config) -> None:
        raise NotImplementedError

    def delete(self, typ: str, name: str, namespace: str) -> None:
        raise NotImplementedError


class MemoryConfigStore(ConfigStore):
    """pilot/pkg/config/memory — the hermetic test backbone; also the
    ConfigStoreCache (config.go:162): handlers fire on changes."""

    def __init__(self) -> None:
        self._data: dict[tuple[str, str, str], Config] = {}
        self._lock = threading.Lock()
        self._handlers: list[Callable[[Config, str], None]] = []

    def register_handler(self, fn: Callable[[Config, str], None]) -> None:
        self._handlers.append(fn)

    def _notify(self, config: Config, event: str) -> None:
        for fn in list(self._handlers):
            fn(config, event)

    def _validate(self, config: Config) -> None:
        schema = IstioConfigTypes.get(config.meta.type)
        if schema is None:
            raise ValidationError(f"unknown config type {config.meta.type}")
        schema.validate(config.spec)

    def get(self, typ, name, namespace=""):
        with self._lock:
            return self._data.get((typ, namespace, name))

    def list(self, typ, namespace=None):
        with self._lock:
            return [c for (t, ns, _), c in sorted(self._data.items())
                    if t == typ and (namespace is None or ns == namespace)]

    def create(self, config: Config) -> None:
        self._validate(config)
        with self._lock:
            if config.key in self._data:
                raise ValidationError(f"{config.key} already exists")
            self._data[config.key] = config
        self._notify(config, "add")

    def update(self, config: Config) -> None:
        self._validate(config)
        with self._lock:
            if config.key not in self._data:   # reference Update errors
                raise ValidationError(f"{config.key} not found")
            self._data[config.key] = config
        self._notify(config, "update")

    def delete(self, typ, name, namespace="") -> None:
        with self._lock:
            config = self._data.pop((typ, namespace, name), None)
        if config is not None:
            self._notify(config, "delete")


def _match_source(spec: Mapping[str, Any], source: str | None,
                  labels: Mapping[str, str] | None) -> bool:
    want = spec.get("match", {}).get("source", None)
    if want and source and want != source:
        return False
    want_labels = spec.get("match", {}).get("sourceTags") or \
        spec.get("match", {}).get("source_labels") or {}
    if want_labels and labels is not None:
        if any(labels.get(k) != v for k, v in want_labels.items()):
            return False
    return True


class IstioConfigStore:
    """config.go:227 query facade over a ConfigStore."""

    def __init__(self, store: ConfigStore):
        self.store = store

    @staticmethod
    def _destination_hostname(c: Config) -> str:
        """Resolve a rule's destination to an FQDN: short names qualify
        against the RULE's namespace + domain (the reference resolves
        names in the config's namespace, model.ResolveHostname)."""
        dest = c.spec.get("destination", {})
        name = dest if isinstance(dest, str) else str(dest.get("name", ""))
        if "." in name or not name:
            return name
        ns = c.meta.namespace or "default"
        domain = c.meta.domain or "cluster.local"
        return f"{name}.{ns}.svc.{domain}"

    def route_rules(self, destination: str, source: str | None = None,
                    source_labels: Mapping[str, str] | None = None
                    ) -> list[Config]:
        """RouteRules by destination (+optional source filter), sorted
        by precedence DESC then name (route.go sorting)."""
        out = []
        for c in self.store.list("route-rule"):
            if self._destination_hostname(c) != destination:
                continue
            if not _match_source(c.spec, source, source_labels):
                continue
            out.append(c)
        out.sort(key=lambda c: (-int(c.spec.get("precedence", 0)),
                                c.meta.name))
        return out

    def destination_policy(self, destination: str,
                           labels: Mapping[str, str] | None = None
                           ) -> Config | None:
        for c in self.store.list("destination-policy"):
            if self._destination_hostname(c) != destination:
                continue
            dest = c.spec.get("destination", {})
            want = (dest.get("tags") or dest.get("labels") or {}) \
                if isinstance(dest, Mapping) else {}
            if want and labels is not None and \
                    any(labels.get(k) != v for k, v in want.items()):
                continue
            return c
        return None

    def egress_rules(self) -> list[Config]:
        return self.store.list("egress-rule")

    def ingress_rules(self) -> list[Config]:
        return self.store.list("ingress-rule")

    def http_api_specs(self, service: str) -> list[Config]:
        """HTTPAPISpecByDestination (config.go:265 family)."""
        bound = []
        for b in self.store.list("http-api-spec-binding"):
            for s in b.spec.get("services", ()):
                sname = s.get("name") if isinstance(s, Mapping) else s
                if sname == service or service.startswith(f"{sname}."):
                    bound.extend(r.get("name") for r in
                                 b.spec.get("api_specs", ()))
        return [c for c in self.store.list("http-api-spec")
                if c.meta.name in bound]

    def quota_specs(self, service: str) -> list[Config]:
        bound = []
        for b in self.store.list("quota-spec-binding"):
            for s in b.spec.get("services", ()):
                sname = s.get("name") if isinstance(s, Mapping) else s
                if sname == service or service.startswith(f"{sname}."):
                    bound.extend(r.get("name") for r in
                                 b.spec.get("quota_specs", ()))
        return [c for c in self.store.list("quota-spec")
                if c.meta.name in bound]
