"""Route compiler: route-rule configs → Envoy v1 route JSON.

Reference: pilot/pkg/proxy/envoy/route.go (buildHTTPRouteV1 :192,
virtual hosts :553, weighted clusters, shadow :463, CORS :484, retry
:443), header.go (buildHTTPRouteMatch :27 — URI exact/prefix/regex +
header matches), fault.go (:28-139), policy.go (applyClusterPolicy
:39). Output dicts serialize to the Envoy v1 JSON API shapes
(resources.go:264 HTTPRoute, :386 VirtualHost, :401 HTTPRouteConfig,
:695 Cluster).
"""
from __future__ import annotations

from typing import Any, Mapping, Sequence

from istio_tpu.pilot.model import (Config, IstioConfigStore, Port, Service)

DEFAULT_TIMEOUT_MS = 15_000


# ---------------------------------------------------------------------------
# cluster naming (route.go buildClusterName discipline)
# ---------------------------------------------------------------------------

def cluster_name(hostname: str, port: Port,
                 labels: Mapping[str, str] | None = None) -> str:
    tag = ",".join(f"{k}={v}" for k, v in sorted((labels or {}).items()))
    base = f"out.{hostname}|{port.name}"
    return f"{base}|{tag}" if tag else base


def inbound_cluster_name(port: int) -> str:
    return f"in.{port}"


# ---------------------------------------------------------------------------
# match translation (header.go:27 buildHTTPRouteMatch)
# ---------------------------------------------------------------------------

def build_route_match(match: Mapping[str, Any] | None) -> dict[str, Any]:
    """Translate a route-rule match block to HTTPRoute match fields.
    URI schemes: exact/prefix/regex; headers likewise (exact value is
    `value`, regex via `regex: true`)."""
    out: dict[str, Any] = {"prefix": "/"}
    headers: list[dict[str, Any]] = []
    if not match:
        return out
    request = match.get("request", {}).get("headers", {}) \
        if "request" in match else match.get("headers", {}) or {}
    for name, cond in sorted(request.items()):
        cond = cond or {}   # null header condition = presence match
        if name == "uri":
            # exactly one of prefix/path/regex must survive — a bare
            # presence match keeps the default catch-all prefix
            if "exact" in cond:
                out.pop("prefix", None)
                out["path"] = cond["exact"]
            elif "prefix" in cond:
                out["prefix"] = cond["prefix"]
            elif "regex" in cond:
                out.pop("prefix", None)
                out["regex"] = cond["regex"]
        else:
            h: dict[str, Any] = {"name": name}
            if "exact" in cond:
                h["value"] = cond["exact"]
            elif "prefix" in cond:
                h["value"] = f"^{_re_escape(cond['prefix'])}.*"
                h["regex"] = True
            elif "regex" in cond:
                h["value"] = cond["regex"]
                h["regex"] = True
            elif "presence" in cond or cond in ({}, None):
                h["value"] = ".*"
                h["regex"] = True
            headers.append(h)
    if headers:
        out["headers"] = headers
    return out


def _re_escape(s: str) -> str:
    import re
    return re.escape(s)


# ---------------------------------------------------------------------------
# faults (fault.go:28-139)
# ---------------------------------------------------------------------------

def build_fault_filter(fault: Mapping[str, Any],
                       headers: Sequence[Mapping[str, Any]] = ()
                       ) -> dict[str, Any] | None:
    if not fault:
        return None
    config: dict[str, Any] = {"upstream_cluster": ""}
    abort = fault.get("abort", {})
    if abort:
        config["abort"] = {
            "abort_percent": int(float(abort.get("percent", 100))),
            "http_status": int(abort.get("httpStatus",
                                         abort.get("http_status", 503)))}
    delay = fault.get("delay", {})
    if delay:
        seconds = delay.get("fixedDelay",
                            delay.get("fixed_delay_seconds", "0s"))
        if isinstance(seconds, str) and seconds.endswith("s"):
            ms = int(float(seconds[:-1]) * 1000)
        else:
            ms = int(float(seconds) * 1000)
        config["delay"] = {"type": "fixed",
                           "fixed_delay_percent":
                               int(float(delay.get("percent", 100))),
                           "fixed_duration_ms": ms}
    if headers:
        config["headers"] = list(headers)
    return {"type": "decoder", "name": "fault", "config": config} \
        if ("abort" in config or "delay" in config) else None


# ---------------------------------------------------------------------------
# routes (route.go:192 buildHTTPRouteV1)
# ---------------------------------------------------------------------------

def build_http_route(rule: Config, service: Service,
                     port: Port) -> dict[str, Any]:
    spec = rule.spec
    route: dict[str, Any] = dict(build_route_match(spec.get("match")))
    route["timeout_ms"] = _timeout_ms(spec)

    blocks = spec.get("route", ())
    if spec.get("redirect"):
        rd = spec["redirect"]
        if rd.get("uri"):
            route["path_redirect"] = rd["uri"]
        if rd.get("authority"):
            route["host_redirect"] = rd["authority"]
    elif len(blocks) == 1 or not blocks:
        block = blocks[0] if blocks else {}
        route["cluster"] = cluster_name(service.hostname, port,
                                        block.get("labels") or
                                        block.get("tags"))
    else:
        route["weighted_clusters"] = {"clusters": [
            {"name": cluster_name(service.hostname, port,
                                  b.get("labels") or b.get("tags")),
             "weight": int(b.get("weight", 0))} for b in blocks]}

    if spec.get("rewrite"):
        rw = spec["rewrite"]
        if rw.get("uri"):
            route["prefix_rewrite"] = rw["uri"]
        if rw.get("authority"):
            route["host_rewrite"] = rw["authority"]
    if spec.get("httpReqRetries"):
        attempts = spec["httpReqRetries"].get("simpleRetry", {}) \
            .get("attempts", 0)
        route["retry_policy"] = {"retry_on": "5xx,connect-failure,refused-stream",
                                 "num_retries": int(attempts)}
    if spec.get("mirror"):
        route["shadow"] = {"cluster": cluster_name(
            service.hostname, port, spec["mirror"].get("labels"))}
    if spec.get("corsPolicy"):
        cp = spec["corsPolicy"]
        route["cors"] = {k: v for k, v in {
            "allow_origin": cp.get("allowOrigin"),
            "allow_methods": ",".join(cp.get("allowMethods", ())) or None,
            "allow_headers": ",".join(cp.get("allowHeaders", ())) or None,
            "allow_credentials": cp.get("allowCredentials"),
            "max_age": cp.get("maxAge"),
        }.items() if v is not None}
    if spec.get("websocketUpgrade"):
        route["use_websocket"] = True
    if spec.get("appendHeaders"):
        route["request_headers_to_add"] = [
            {"key": k, "value": v}
            for k, v in sorted(spec["appendHeaders"].items())]
    return route


def _timeout_ms(spec: Mapping[str, Any]) -> int:
    t = spec.get("httpReqTimeout", {}).get("simpleTimeout", {}) \
        .get("timeout")
    if t is None:
        return DEFAULT_TIMEOUT_MS
    if isinstance(t, str) and t.endswith("s"):
        return int(float(t[:-1]) * 1000)
    return int(float(t) * 1000)


def default_route(service: Service, port: Port) -> dict[str, Any]:
    return {"prefix": "/", "cluster": cluster_name(service.hostname, port),
            "timeout_ms": DEFAULT_TIMEOUT_MS}


# ---------------------------------------------------------------------------
# virtual hosts + route config (route.go:553 buildVirtualHost, :314)
# ---------------------------------------------------------------------------

def service_domains(service: Service, port: Port,
                    domain_suffix: str = "cluster.local") -> list[str]:
    """All names a sidecar may use for the service (short name, fqdn,
    with/without port — route.go buildVirtualHost domain set)."""
    host = service.hostname
    parts = host.split(".")
    domains = [host, f"{host}:{port.port}"]
    if len(parts) > 2 and host.endswith(domain_suffix):
        short = parts[0]
        ns = f"{parts[0]}.{parts[1]}"
        svc_ns = f"{parts[0]}.{parts[1]}.svc"
        for d in (short, ns, svc_ns):
            domains += [d, f"{d}:{port.port}"]
    if service.address and service.address != "0.0.0.0":
        domains += [service.address, f"{service.address}:{port.port}"]
    return domains


def build_virtual_host_from_rules(service: Service, port: Port,
                                  rules: Sequence[Config]
                                  ) -> dict[str, Any]:
    """Virtual-host assembly from an ALREADY-FILTERED, precedence-
    sorted rule list — the single home shared by the live query path
    (build_virtual_host) and the snapshot serving plane
    (pilot/discovery.py), so scoped/batched generation stays
    byte-identical to direct generation by construction."""
    routes = [build_http_route(rule, service, port) for rule in rules]
    routes.append(default_route(service, port))
    return {"name": f"{service.hostname}|{port.name}",
            "domains": service_domains(service, port),
            "routes": routes}


def build_virtual_host(service: Service, port: Port,
                       config_store: IstioConfigStore,
                       source: str | None = None,
                       source_labels: Mapping[str, str] | None = None
                       ) -> dict[str, Any]:
    return build_virtual_host_from_rules(
        service, port,
        config_store.route_rules(service.hostname, source,
                                 source_labels))


def build_route_config(services: Sequence[Service], port_num: int,
                       config_store: IstioConfigStore,
                       source: str | None = None) -> dict[str, Any]:
    """RDS payload for one outbound port (config.go:288 buildRDSRoute);
    egress virtual hosts for the port ride the same route table
    (config.go:849-1026 — external domains resolve per-sidecar)."""
    vhosts = []
    for service in services:
        for port in service.ports:
            if port.port == port_num and port.is_http:
                vhosts.append(build_virtual_host(service, port,
                                                 config_store, source))
    vhosts.extend(build_egress_virtual_hosts(config_store, port_num))
    vhosts.sort(key=lambda v: v["name"])
    return {"virtual_hosts": vhosts,
            "validate_clusters": False}


# ---------------------------------------------------------------------------
# egress (config.go:849-1026)
# ---------------------------------------------------------------------------

def egress_cluster_name(host: str, port_num: int) -> str:
    return f"egress.{host}|{port_num}"


def _egress_rule_ports(rule: Config) -> list[tuple[int, str]]:
    return [(int(p.get("port", 80)),
             str(p.get("protocol", "http")).lower())
            for p in rule.spec.get("ports", ())]


def build_egress_virtual_hosts(config_store: IstioConfigStore,
                               port_num: int) -> list[dict[str, Any]]:
    """One virtual host per egress rule exposing `port_num` over http:
    external domains route to the rule's egress cluster with the
    authority preserved (auto host rewrite for exact hosts)."""
    vhosts: dict[str, dict[str, Any]] = {}
    for rule in config_store.egress_rules():
        host = str(rule.spec.get("destination", {}).get("service", ""))
        for pnum, proto in _egress_rule_ports(rule):
            if pnum != port_num or proto not in ("http", "http2", "grpc"):
                continue
            name = f"egress|{host}|{pnum}"
            if name in vhosts:
                continue   # rules sharing host+port: envoy rejects
                #            duplicate domains, so dedupe here
            route: dict[str, Any] = {
                "prefix": "/",
                "cluster": egress_cluster_name(host, pnum),
                "timeout_ms": DEFAULT_TIMEOUT_MS,
            }
            if not host.startswith("*"):
                route["auto_host_rewrite"] = True
            vhosts[name] = {"name": name,
                            "domains": [host, f"{host}:{pnum}"],
                            "routes": [route]}
    return [vhosts[k] for k in sorted(vhosts)]


# ---------------------------------------------------------------------------
# ingress (pilot/pkg/proxy/envoy/ingress.go)
# ---------------------------------------------------------------------------

def build_ingress_route_config(config_store: IstioConfigStore,
                               registry) -> dict[str, Any]:
    """Route config for an ingress proxy: ingress-rule configs (as
    emitted by the kube ingress controller or written directly) grouped
    into per-authority virtual hosts routing to the backend service's
    outbound cluster."""
    by_host: dict[str, list[dict[str, Any]]] = {}
    for rule in config_store.ingress_rules():
        spec = rule.spec
        dest = str(spec.get("destination", {}).get("service", ""))
        service = registry.get_service(dest) if registry else None
        port = _resolve_ingress_port(service, spec.get("port"))
        if port is None:
            continue
        match = build_route_match(spec.get("match"))
        authority = "*"
        headers = []
        for h in match.pop("headers", ()):
            if h["name"] == "authority" and not h.get("regex"):
                authority = h["value"]
            else:
                headers.append(h)
        route = dict(match)
        if headers:
            route["headers"] = headers
        route["cluster"] = cluster_name(dest, port)
        route["timeout_ms"] = _timeout_ms(spec)
        by_host.setdefault(authority, []).append(route)
    vhosts = []
    for authority in sorted(by_host):
        domains = ["*"] if authority == "*" else [authority,
                                                  f"{authority}:80",
                                                  f"{authority}:443"]
        # exact-path routes sort before prefix routes (first match wins)
        routes = sorted(by_host[authority],
                        key=lambda r: (0 if "path" in r else 1,
                                       -len(r.get("prefix", ""))))
        vhosts.append({"name": f"ingress|{authority}",
                       "domains": domains, "routes": routes})
    return {"virtual_hosts": vhosts, "validate_clusters": False}


def _resolve_ingress_port(service: Service | None,
                          port_ref: Any) -> Port | None:
    if service is None:
        return None
    if isinstance(port_ref, str) and not port_ref.isdigit():
        return service.port_by_name(port_ref)
    try:
        num = int(port_ref)
    except (TypeError, ValueError):
        return None
    for p in service.ports:
        if p.port == num:
            return p
    return None
