"""Shadow replay: drive a recorded corpus through a candidate plan.

The replay path is the REAL serving path — a `Dispatcher` built over
the candidate snapshot + `FusedPlan` — run in observe-off mode: no
stage histograms, no live-p99 window, no rule-telemetry folds, no
chaos seam, no recorder tap (the canary must never pollute the
metrics it is judged against, and a candidate's telemetry must start
clean when it publishes). Handlers are deliberately EMPTY: host
overlay adapter calls have side effects (quota consumption, exporter
writes) a shadow replay must not cause, so the decision surface
compared is the device-decidable one — fused deny/list/rbac statuses,
TTL/use-count folds, host-fallback predicates (oracle-patched, pure)
and quota-rule activity bits. Recorded decisions come off the same
surface, so identical semantics replay to identical decisions.

Batches chunk and pad to the serving buckets (prewarmed before the
swap), so a replay never compiles a fresh XLA program in-band.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Any, Sequence

from istio_tpu.canary.recorder import CanaryEntry


@dataclasses.dataclass
class ReplayResult:
    """Per-row candidate decisions for one corpus replay."""
    status: list[int]
    valid_duration_s: list[float]
    valid_use_count: list[int]
    deny_rule: list[str]           # qualified names; "" = no deny
    quota_rules: list[tuple]       # qualified QUOTA-rule names per row
    n_rows: int = 0
    wall_s: float = 0.0

    @property
    def rows_per_s(self) -> float:
        return self.n_rows / self.wall_s if self.wall_s > 0 else 0.0


def allow_everything_replay(n: int) -> ReplayResult:
    """The synthetic replay of a RULE-LESS snapshot: every check
    answers OK at the CheckResponse default TTL/use-count caps.
    Shared by the controller gate and the admission webhook so a rule
    wipe is judged identically on both surfaces — recorded denies
    register as status flips instead of bypassing the diff."""
    from istio_tpu.runtime.dispatcher import CheckResponse

    ok = CheckResponse()
    return ReplayResult(
        status=[0] * n,
        valid_duration_s=[ok.valid_duration_s] * n,
        valid_use_count=[ok.valid_use_count] * n,
        deny_rule=[""] * n, quota_rules=[()] * n,
        n_rows=n, wall_s=0.0)


def replay_entries(snapshot: Any, plan: Any,
                   entries: Sequence[CanaryEntry],
                   buckets: tuple[int, ...] = (),
                   identity_attr: str | None = None) -> ReplayResult:
    """Batch-replay `entries` through `plan` on device → ReplayResult
    aligned index-for-index with `entries`. `buckets` should be the
    serving bucket shapes the plan was prewarmed for; empty buckets
    replay at the corpus' own chunk shape (tests / offline CLI, where
    an in-band trace is acceptable)."""
    from istio_tpu.runtime.batcher import pad_to_bucket
    from istio_tpu.runtime.dispatcher import (DEFAULT_IDENTITY_ATTR,
                                              Dispatcher)

    if plan is None:
        raise ValueError("shadow replay requires a fused plan "
                         "(candidate snapshot compiled with fused=True)")
    buckets = tuple(sorted(buckets))
    d = Dispatcher(snapshot, {},
                   identity_attr or DEFAULT_IDENTITY_ATTR,
                   fused=plan, buckets=buckets, observe=False)
    names = snapshot.qualified_rule_names()
    out = ReplayResult(status=[], valid_duration_s=[],
                       valid_use_count=[], deny_rule=[],
                       quota_rules=[])
    bags = [e.bag() for e in entries]
    cap = buckets[-1] if buckets else (len(bags) or 1)
    t0 = time.perf_counter()
    for lo in range(0, len(bags), cap):
        chunk = bags[lo:lo + cap]
        padded = pad_to_bucket(chunk, buckets) if buckets else chunk
        responses = d.check(padded)
        for resp in responses[:len(chunk)]:
            out.status.append(int(resp.status_code))
            out.valid_duration_s.append(float(resp.valid_duration_s))
            out.valid_use_count.append(int(resp.valid_use_count))
            ridx = getattr(resp, "deny_rule", -1)
            out.deny_rule.append(
                names[ridx] if 0 <= ridx < len(names) else "")
            out.quota_rules.append(tuple(
                names[r] for r in (resp.active_quota_rules or ())
                if 0 <= r < len(names)))
    out.n_rows = len(bags)
    out.wall_s = time.perf_counter() - t0
    return out
