"""Divergence classification over (recorded, replayed) decision pairs.

Three divergence kinds, mirroring the decision surface the recorder
captures:

  status_flip   — the google.rpc status code changed (OK→deny,
                  deny→OK, or a different non-OK code);
  precondition  — same status, but the TTL / use-count budget the
                  client may cache the verdict under changed;
  quota         — the set of active QUOTA-variety rules changed (a
                  quota rule newly gating, or silently dropping out).

Divergences aggregate per qualified rule name (the rulestats naming),
with bounded reservoir exemplars carrying the replayable compressed
bag + the recorded trace id (joins /debug/traces). `confirm_exemplars`
re-evaluates exemplar bags through BOTH snapshots' CPU oracles
(compiler/ruleset.SnapshotOracle + the fused action semantics) so a
reported flip is independently confirmed off-device — the same
replay-the-witness bar the PR 3 analyzer holds its findings to.
"""
from __future__ import annotations

import dataclasses
import random
from typing import Any, Iterable, Sequence

from istio_tpu.canary.recorder import CanaryEntry, _ca_to_json
from istio_tpu.canary.replay import ReplayResult

UNATTRIBUTED = "(unattributed)"


@dataclasses.dataclass
class Divergence:
    kind: str                 # status_flip | precondition | quota
    rule: str                 # attributed qualified rule name
    entry_index: int
    recorded: dict
    replayed: dict


@dataclasses.dataclass
class CanaryReport:
    """JSON-able diff report for one candidate replay."""
    n_rows: int = 0
    n_divergent: int = 0              # non-waived divergent rows
    n_waived: int = 0
    by_kind: dict = dataclasses.field(default_factory=dict)
    # rule name → {"total", "status_flip", "precondition", "quota",
    #              "waived", "exemplars": [...]}
    per_rule: dict = dataclasses.field(default_factory=dict)
    divergence_rate: float = 0.0      # non-waived rows / replayed rows
    replay_rows_per_s: float = 0.0
    replay_wall_s: float = 0.0
    candidate_revision: int | None = None
    # filled by the gate
    mode: str = ""
    verdict: str = ""                 # publish | warn | veto
    threshold: float = 0.0
    waivers: tuple = ()
    # filled by /debug/canary: diverging rules the static analyzer
    # ALSO flags (shadow/overlap/plane findings) — config drift with
    # independent static evidence
    analyzer_overlap: list = dataclasses.field(default_factory=list)
    note: str = ""

    def diverging_rules(self) -> list[str]:
        """Non-waived diverging rule names, worst-first."""
        ranked = sorted(
            ((name, c) for name, c in self.per_rule.items()
             if not c.get("waived")),
            key=lambda kv: (-kv[1]["total"], kv[0]))
        return [name for name, _ in ranked]

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# exemplar attribute rendering is shared with /debug/rulestats — one
# contract, one helper (runtime/rulestats.preview_attributes)
from istio_tpu.runtime.rulestats import preview_attributes


def diff_decisions(entries: Sequence[CanaryEntry], replay: ReplayResult,
                   waivers: Iterable[str] = (),
                   exemplars_per_rule: int = 4,
                   seed: int = 0) -> CanaryReport:
    """Classify per-row divergence between recorded and replayed
    decisions → CanaryReport. `waivers` are qualified rule names whose
    divergences are reported but excluded from the gating rate (the
    operator's "yes, this rule is SUPPOSED to change" escape hatch)."""
    if len(entries) != replay.n_rows:
        raise ValueError(f"corpus/replay row mismatch: {len(entries)} "
                         f"entries vs {replay.n_rows} replayed")
    waived = frozenset(waivers)
    rng = random.Random(seed)
    rep = CanaryReport(n_rows=len(entries),
                       replay_rows_per_s=round(replay.rows_per_s, 1),
                       replay_wall_s=replay.wall_s,
                       waivers=tuple(sorted(waived)))
    seen_per_rule: dict[str, int] = {}
    for i, e in enumerate(entries):
        r_status = replay.status[i]
        r_dur = replay.valid_duration_s[i]
        r_uses = replay.valid_use_count[i]
        r_deny = replay.deny_rule[i]
        r_quota = replay.quota_rules[i]
        kind = None
        rule = UNATTRIBUTED
        if r_status != e.status:
            kind = "status_flip"
            # attribute to the side that denies: the candidate's deny
            # rule when it answers non-OK, else the rule whose recorded
            # deny the candidate no longer produces
            rule = (r_deny if r_status != 0 and r_deny else
                    e.deny_rule or r_deny or UNATTRIBUTED)
        elif abs(r_dur - e.valid_duration_s) > 1e-6 or \
                r_uses != e.valid_use_count:
            kind = "precondition"
            rule = r_deny or e.deny_rule or UNATTRIBUTED
        elif frozenset(r_quota) != frozenset(e.quota_rules):
            kind = "quota"
            delta = sorted(frozenset(r_quota) ^
                           frozenset(e.quota_rules))
            rule = delta[0] if delta else UNATTRIBUTED
        if kind is None:
            continue
        is_waived = rule in waived
        if is_waived:
            rep.n_waived += 1
        else:
            rep.n_divergent += 1
            rep.by_kind[kind] = rep.by_kind.get(kind, 0) + 1
        c = rep.per_rule.setdefault(rule, {
            "total": 0, "status_flip": 0, "precondition": 0,
            "quota": 0, "waived": is_waived, "exemplars": []})
        c["total"] += 1
        c[kind] += 1
        seen = seen_per_rule.get(rule, 0) + 1
        seen_per_rule[rule] = seen
        # reservoir slot FIRST: a candidate flipping every replayed
        # row must not decode+re-encode every bag just to keep K
        # exemplars — exemplar construction is O(kept), not O(rows)
        bucket = c["exemplars"]
        slot = len(bucket) if len(bucket) < exemplars_per_rule \
            else rng.randrange(seen)
        if slot >= exemplars_per_rule:
            continue
        ex = {
            "kind": kind,
            "entry_index": i,
            "attributes": preview_attributes(e.bag()),
            "trace_id": e.trace_id,
            "recorded": {"status": e.status,
                         "valid_duration_s": e.valid_duration_s,
                         "valid_use_count": e.valid_use_count,
                         "deny_rule": e.deny_rule,
                         "quota_rules": list(e.quota_rules)},
            "replayed": {"status": r_status,
                         "valid_duration_s": r_dur,
                         "valid_use_count": r_uses,
                         "deny_rule": r_deny,
                         "quota_rules": list(r_quota)},
            # the replayable bag itself: `mixs canary --corpus` can
            # re-run exactly this request against any candidate
            "bag": _ca_to_json(e.ca),
        }
        if slot == len(bucket):
            bucket.append(ex)
        else:
            bucket[slot] = ex
    n = max(rep.n_rows, 1)
    rep.divergence_rate = round(rep.n_divergent / n, 6)
    return rep


# ---------------------------------------------------------------------------
# oracle re-evaluation (exemplar confirmation)
# ---------------------------------------------------------------------------

def oracle_decision(snapshot: Any, plan: Any, bag: Any,
                    identity_attr: str = "destination.service"
                    ) -> tuple[int, str]:
    """(status_code, winning qualified rule name) for one bag, derived
    entirely on CPU: SnapshotOracle rule resolution in device combine
    order (lowest rule index wins) + `fused_check_status` per active
    rule. Independent of the device path being judged — the
    confirmation bar for canary exemplars."""
    from istio_tpu.compiler.ruleset import (SnapshotOracle,
                                            fused_check_status)
    from istio_tpu.runtime.dispatcher import _namespace_of

    rs = snapshot.ruleset
    n_cfg = len(snapshot.rules)
    oracle = getattr(snapshot, "_canary_oracle", None)
    if oracle is None:
        oracle = SnapshotOracle(
            rs.rules[:n_cfg], snapshot.finder,
            seed={r: p for r, p in rs.host_fallback.items()
                  if r < n_cfg})
        snapshot._canary_oracle = oracle
    names = snapshot.qualified_rule_names()
    req_ns = _namespace_of(bag, identity_attr)
    active, _visible, _errs = oracle.resolve(bag, req_ns)
    for ridx in active:
        st = fused_check_status(snapshot, plan, ridx, bag)
        if st != 0:
            return st, names[ridx] if ridx < len(names) else ""
    return 0, ""


def confirm_exemplars(report: CanaryReport,
                      base_snapshot: Any, base_plan: Any,
                      cand_snapshot: Any, cand_plan: Any,
                      identity_attr: str = "destination.service"
                      ) -> None:
    """Mark every status-flip exemplar with `oracle_confirmed`: the
    recorded status re-derives from the BASE snapshot's oracle and the
    replayed status from the CANDIDATE's — both off-device. A
    confirmed exemplar proves the flip is a semantic config change,
    not device noise. Mutates the report in place."""
    from istio_tpu.canary.recorder import _ca_from_json
    from istio_tpu.attribute.compressed import decode

    for c in report.per_rule.values():
        for ex in c["exemplars"]:
            if ex.get("kind") != "status_flip":
                continue
            try:
                bag = decode(_ca_from_json(ex["bag"]))
                base_st, _ = oracle_decision(base_snapshot, base_plan,
                                             bag, identity_attr)
                cand_st, _ = oracle_decision(cand_snapshot, cand_plan,
                                             bag, identity_attr)
            except Exception as exc:
                ex["oracle_confirmed"] = False
                ex["oracle_error"] = f"{type(exc).__name__}: {exc}"
                continue
            ex["oracle_confirmed"] = (
                base_st == ex["recorded"]["status"]
                and cand_st == ex["replayed"]["status"])
            ex["oracle_status"] = {"base": base_st,
                                   "candidate": cand_st}
