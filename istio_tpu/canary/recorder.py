"""Live-traffic recorder: the canary's record stage.

A lock-light sampling ring buffer tapped at the dispatcher boundary
(`Dispatcher._check_fused` / the generic check path) — the SAME spot
whose verdict the caller receives, so a recorded decision is exactly
what was served. The tap runs inside the serving hot sections
(scripts/hotpath_lint.py HOT_SECTIONS covers it): per request it costs
one stride-counter check, and for SAMPLED rows only, a bounded tuple
append under a short-held lock. No device work, no bag decode, no
encoding — compression to `CompressedAttributes` (the rulestats
exemplar compression, attribute/compressed.py) happens at corpus-build
time, which runs at config-swap / admission / CLI time, never on the
batch critical path.

Recorded per sample: the attribute bag (compressed at corpus build),
the served decision (status, valid_duration/use_count, winning device
deny rule, active quota rules, namespace) and the active trace id so a
canary exemplar joins /debug/traces.
"""
from __future__ import annotations

import base64
import dataclasses
import datetime
import json
import threading
import time
from typing import Any, Sequence

from istio_tpu.attribute.compressed import (CompressedAttributes, decode,
                                            encode)


@dataclasses.dataclass
class CanaryEntry:
    """One replayable recorded request: compressed attribute bag +
    the decision the live plan served for it."""
    ca: CompressedAttributes
    status: int = 0
    valid_duration_s: float = 5.0
    valid_use_count: int = 10_000
    deny_rule: str = ""            # qualified rule name; "" = no deny
    namespace: str = ""
    quota_rules: tuple = ()        # qualified QUOTA-rule names active
    trace_id: str | None = None
    t: float = 0.0

    def bag(self):
        """Decode the compressed bag for replay / oracle runs."""
        return decode(self.ca)


class TrafficRecorder:
    """Bounded sampling ring over live Check() traffic.

    `sample_every=k` keeps every k-th request (stride over a global
    counter, so sampling is uniform across batches); `capacity` bounds
    memory — the ring overwrites oldest. The raw ring holds bag REFS
    plus already-decoded decision scalars; `corpus()` materializes
    immutable `CanaryEntry` records (bags compressed) off the hot
    path. Rows keep a reference to the snapshot that served them so
    rule indices resolve to names even across config swaps."""

    def __init__(self, capacity: int = 2048, sample_every: int = 1):
        self.capacity = max(int(capacity), 1)
        self.sample_every = max(int(sample_every), 1)
        self._lock = threading.Lock()
        self._ring: list[tuple] = []
        self._w = 0                     # oldest slot once the ring fills
        self._counter = 0               # global request stride counter
        self._sampled = 0
        self._evicted = 0
        self._encode_errors = 0
        self._identity_attr = "destination.service"
        # CheckResponse's TTL/use-count field defaults — the caps the
        # dispatcher min-folds device planes under; resolved lazily
        # (import cost off __init__) so recorded rows clamp EXACTLY
        # like replayed responses even if the defaults are retuned
        self._resp_caps: tuple | None = None

    # ------------------------------------------------------------------
    # hot path (scripts/hotpath_lint.py HOT_SECTIONS covers tap)
    # ------------------------------------------------------------------

    def tap(self, bags: Sequence, responses: Sequence, snapshot: Any,
            identity_attr: str, span: Any = None,
            device: tuple | None = None) -> None:
        """Record one served batch's sampled rows. `bags`/`responses`
        are the dispatcher's real (padding-trimmed) rows; `span` is the
        batch's active trace span dict (or None). `device` is the
        fused path's (status, valid_duration_s, valid_use_count,
        deny_rule) decoded packed rows: when present, the DEVICE
        surface is recorded instead of the final merged response —
        host-overlay adapter statuses are invisible to the shadow
        replay (it runs with empty handlers, side effects must not
        fire), so recording them would make an UNCHANGED config with a
        host-overlay deny look permanently divergent. Dispatch-side
        cost: a stride check per batch plus a tuple append per SAMPLED
        row — the counter increment races benignly under concurrent
        batch workers (sampling is a sample, not an exact stride)."""
        n = len(bags)
        if not n:
            return
        self._identity_attr = identity_attr
        stride = self.sample_every
        base = self._counter
        self._counter = base + n
        first = (-base) % stride
        if first >= n:
            return
        # only the index→name list is kept per row (memoized on the
        # snapshot) — holding the snapshot itself would pin superseded
        # config generations in memory for the life of the ring
        names = snapshot.qualified_rule_names() \
            if snapshot is not None else []
        tid = span.get("traceId") if span else None
        now = time.time()
        rows = []
        if device is not None:
            if self._resp_caps is None:
                from istio_tpu.runtime.dispatcher import CheckResponse
                blank = CheckResponse()
                self._resp_caps = (blank.valid_duration_s,
                                   blank.valid_use_count)
            dur_cap, uses_cap = self._resp_caps
            dstat, ddur, duses, ddeny = device
            for i in range(first, n, stride):
                st = int(dstat[i])
                rows.append((bags[i], st,
                             min(dur_cap, float(ddur[i])),
                             min(uses_cap, int(duses[i])),
                             int(ddeny[i]) if st else -1,
                             responses[i].active_quota_rules,
                             names, tid, now))
        else:
            for i in range(first, n, stride):
                resp = responses[i]
                rows.append((bags[i], resp.status_code,
                             resp.valid_duration_s,
                             resp.valid_use_count,
                             getattr(resp, "deny_rule", -1),
                             resp.active_quota_rules, names, tid, now))
        with self._lock:
            for row in rows:
                if len(self._ring) < self.capacity:
                    self._ring.append(row)
                else:
                    self._ring[self._w] = row
                    self._w = (self._w + 1) % self.capacity
                    self._evicted += 1
            self._sampled += len(rows)

    # ------------------------------------------------------------------
    # corpus build (config-swap / admission / CLI time — NOT hot)
    # ------------------------------------------------------------------

    def _snapshot_rows(self) -> list[tuple]:
        with self._lock:
            if len(self._ring) < self.capacity:
                return list(self._ring)
            return self._ring[self._w:] + self._ring[:self._w]

    def corpus(self, limit: int | None = None) -> list[CanaryEntry]:
        """Materialize the ring (oldest→newest, newest kept under
        `limit`) as immutable replayable entries: bags compressed via
        the rulestats exemplar codec, rule indices resolved to
        qualified names against the snapshot that served each row."""
        from istio_tpu.runtime.dispatcher import _namespace_of

        rows = self._snapshot_rows()
        if limit is not None and len(rows) > limit:
            rows = rows[-limit:]
        out: list[CanaryEntry] = []
        for (bag, status, dur, uses, deny_rule, quota_rules, names,
             tid, t) in rows:
            try:
                ca = encode(bag)
            except Exception:
                self._encode_errors += 1
                continue
            deny_name = names[deny_rule] \
                if 0 <= deny_rule < len(names) else ""
            qnames = tuple(names[r] for r in (quota_rules or ())
                           if 0 <= r < len(names))
            out.append(CanaryEntry(
                ca=ca, status=int(status),
                valid_duration_s=float(dur),
                valid_use_count=int(uses), deny_rule=deny_name,
                namespace=_namespace_of(bag, self._identity_attr),
                quota_rules=qnames, trace_id=tid, t=t))
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring = []
            self._w = 0

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "sample_every": self.sample_every,
                "entries": len(self._ring),
                "seen": self._counter,
                "sampled": self._sampled,
                "evicted": self._evicted,
                "encode_errors": self._encode_errors,
            }


# ---------------------------------------------------------------------------
# corpus file codec — `mixs canary` offline replay + admission fixtures
# ---------------------------------------------------------------------------

def _ca_to_json(ca: CompressedAttributes) -> dict:
    return {
        "words": list(ca.words),
        "strings": {str(k): v for k, v in ca.strings.items()},
        "int64s": {str(k): v for k, v in ca.int64s.items()},
        "doubles": {str(k): v for k, v in ca.doubles.items()},
        "bools": {str(k): v for k, v in ca.bools.items()},
        "timestamps": {str(k): v.isoformat()
                       for k, v in ca.timestamps.items()},
        "durations": {str(k): v.total_seconds()
                      for k, v in ca.durations.items()},
        "bytes": {str(k): base64.b64encode(v).decode("ascii")
                  for k, v in ca.bytes_.items()},
        "string_maps": {str(k): {str(mk): mv for mk, mv in m.items()}
                        for k, m in ca.string_maps.items()},
    }


def _ca_from_json(d: dict) -> CompressedAttributes:
    return CompressedAttributes(
        words=list(d.get("words") or ()),
        strings={int(k): int(v)
                 for k, v in (d.get("strings") or {}).items()},
        int64s={int(k): int(v)
                for k, v in (d.get("int64s") or {}).items()},
        doubles={int(k): float(v)
                 for k, v in (d.get("doubles") or {}).items()},
        bools={int(k): bool(v)
               for k, v in (d.get("bools") or {}).items()},
        timestamps={int(k): datetime.datetime.fromisoformat(v)
                    for k, v in (d.get("timestamps") or {}).items()},
        durations={int(k): datetime.timedelta(seconds=float(v))
                   for k, v in (d.get("durations") or {}).items()},
        bytes_={int(k): base64.b64decode(v)
                for k, v in (d.get("bytes") or {}).items()},
        string_maps={int(k): {int(mk): int(mv)
                              for mk, mv in m.items()}
                     for k, m in (d.get("string_maps") or {}).items()})


def entry_to_json(e: CanaryEntry) -> dict:
    return {
        "ca": _ca_to_json(e.ca),
        "status": e.status,
        "valid_duration_s": e.valid_duration_s,
        "valid_use_count": e.valid_use_count,
        "deny_rule": e.deny_rule,
        "namespace": e.namespace,
        "quota_rules": list(e.quota_rules),
        "trace_id": e.trace_id,
        "t": e.t,
    }


def entry_from_json(d: dict) -> CanaryEntry:
    return CanaryEntry(
        ca=_ca_from_json(d.get("ca") or {}),
        status=int(d.get("status", 0)),
        valid_duration_s=float(d.get("valid_duration_s", 5.0)),
        valid_use_count=int(d.get("valid_use_count", 10_000)),
        deny_rule=str(d.get("deny_rule", "")),
        namespace=str(d.get("namespace", "")),
        quota_rules=tuple(d.get("quota_rules") or ()),
        trace_id=d.get("trace_id"),
        t=float(d.get("t", 0.0)))


def save_corpus(path: str, entries: Sequence[CanaryEntry]) -> int:
    """Write a replayable corpus file (JSON; versioned)."""
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1,
                   "entries": [entry_to_json(e) for e in entries]}, f)
    return len(entries)


def load_corpus(path: str) -> list[CanaryEntry]:
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if int(doc.get("version", 0)) != 1:
        raise ValueError(f"unsupported corpus version "
                         f"{doc.get('version')!r}")
    return [entry_from_json(d) for d in doc.get("entries") or ()]
