"""Config canary — record live Check() traffic, shadow-replay
prospective snapshots on device, gate the swap.

The shadow-deployment / canary-analysis pattern from production
inference stacks applied to the policy plane: PR 3's analyzer rejects
config that is wrong *by construction*, but a statically clean
snapshot can still flip decisions for real users (a tightened match
clause, a reordered ALLOW/DENY overlap the analyzer only WARNs on).
Before the Controller's atomic publish swaps a rebuilt snapshot in,
the candidate `FusedPlan` is validated against RECORDED live traffic
on the same hardware:

  record  — `TrafficRecorder` (recorder.py): a lock-light sampling
            ring buffer tapped at the dispatcher boundary captures
            recent Check() traffic as compressed attribute bags (the
            rulestats exemplar compression) plus the served decision.
  replay  — `replay_entries` (replay.py): the corpus batch-replays
            through the candidate plan in observe-off mode (no
            rulestats / stage-metric / chaos pollution) on device.
  diff    — `diff_decisions` (differ.py): per-request divergence
            classification (status flip, precondition TTL/use-count
            change, quota delta) aggregated per rule, with reservoir
            exemplars (bag + trace id) and oracle re-confirmation.
  gate    — `ConfigCanary` (gate.py): --canary={off,warn,gate}; `gate`
            vetoes the publish (typed `CanaryRejected`, old dispatcher
            keeps serving), `warn` publishes but records the report.

Surfaces: /debug/canary (introspect), `mixer_canary_*` metric
families, kube/admission.register_canary_admission, the `canary` CLI
subcommand, and bench.py `canary_*` keys.
"""
from istio_tpu.canary.differ import (CanaryReport, Divergence,
                                     diff_decisions, oracle_decision)
from istio_tpu.canary.gate import (CanaryConfig, CanaryRejected,
                                   ConfigCanary)
from istio_tpu.canary.recorder import (CanaryEntry, TrafficRecorder,
                                       entry_from_json, entry_to_json,
                                       load_corpus, save_corpus)
from istio_tpu.canary.replay import ReplayResult, replay_entries

__all__ = [
    "CanaryConfig", "CanaryEntry", "CanaryRejected", "CanaryReport",
    "ConfigCanary", "Divergence", "ReplayResult", "TrafficRecorder",
    "diff_decisions", "entry_from_json", "entry_to_json",
    "load_corpus", "oracle_decision", "replay_entries", "save_corpus",
]
