"""The canary gate: decide a candidate snapshot's fate before publish.

`ConfigCanary` owns the recorder, runs record→replay→diff when the
Controller rebuilds, and renders the verdict per the configured mode:

  off   — the RuntimeServer builds no canary at all: no recorder tap,
          no replay, publishes proceed untouched (a ConfigCanary
          constructed directly with mode="off" records but never
          gates);
  warn  — replay + diff, report recorded (metrics, /debug/canary),
          publish proceeds even on divergence;
  gate  — divergence rate beyond the threshold VETOES the publish: the
          Controller keeps the OLD dispatcher serving and surfaces a
          typed `CanaryRejected` (on_canary_reject / introspect).

A broken canary must never take config updates down with it: any
internal replay/diff failure fails OPEN (logged, counted, published) —
the gate only ever vetoes on an actual measured divergence.
"""
from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Any, Iterable

from istio_tpu.canary.differ import (CanaryReport, confirm_exemplars,
                                     diff_decisions)
from istio_tpu.canary.recorder import TrafficRecorder
from istio_tpu.canary.replay import replay_entries
from istio_tpu.utils import metrics as hostmetrics
from istio_tpu.utils.log import scope

log = scope("canary.gate")

MODES = ("off", "warn", "gate")


def register_families(reg: hostmetrics.Registry) -> dict:
    """mixer_canary_* metric families (zero-touched so the exposition
    distinguishes "canary idle" from "canary missing")."""
    fams = {
        "replays": reg.counter(
            "mixer_canary_replays_total",
            "candidate snapshots shadow-replayed against recorded "
            "live traffic"),
        "rows": reg.counter(
            "mixer_canary_replay_rows_total",
            "recorded requests replayed through candidate plans"),
        "divergences": reg.counter(
            "mixer_canary_divergences_total",
            "non-waived recorded-vs-candidate decision divergences, "
            "by kind (status_flip/precondition/quota)"),
        "verdicts": reg.counter(
            "mixer_canary_verdicts_total",
            "gate outcomes by verdict (publish/warn/veto)"),
        "errors": reg.counter(
            "mixer_canary_errors_total",
            "internal canary failures (failed OPEN: publish "
            "proceeded)"),
        "rate": reg.gauge(
            "mixer_canary_last_divergence_rate",
            "divergence rate of the most recent replay"),
        "recorder_entries": reg.gauge(
            "mixer_canary_recorder_entries",
            "recorded requests currently held in the sampling ring"),
        "replay_seconds": reg.histogram(
            "mixer_canary_replay_seconds",
            "shadow-replay wall time per candidate (device steps "
            "included)"),
        "publish_delay_seconds": reg.histogram(
            "mixer_canary_publish_delay_seconds",
            "publish latency the whole canary evaluation added "
            "(corpus build + replay + diff + oracle confirm)"),
    }
    for key in ("replays", "rows", "divergences", "verdicts", "errors"):
        fams[key].inc(0.0)
    return fams


FAMILIES = register_families(hostmetrics.default_registry)


@dataclasses.dataclass
class CanaryConfig:
    """ServerArgs.canary_* mirrors these; mixs exposes them as
    --canary / --canary-* flags."""
    mode: str = "off"                  # off | warn | gate
    # non-waived divergent rows / replayed rows beyond which `gate`
    # vetoes (strictly greater-than: 0.0 = any divergence vetoes)
    max_divergence_rate: float = 0.0
    # qualified rule names whose divergences never count toward the
    # gating rate (reported + counted separately)
    waivers: tuple = ()
    capacity: int = 2048               # recorder ring size
    sample_every: int = 1              # keep every k-th request
    replay_limit: int = 1024           # newest rows replayed per gate
    # below this many recorded rows the gate abstains (publishes with
    # a note): an empty corpus proves nothing
    min_rows: int = 1
    exemplars_per_rule: int = 4
    keep_reports: int = 8              # /debug/canary history depth

    def __post_init__(self) -> None:
        if self.mode not in MODES:
            raise ValueError(f"canary mode must be one of {MODES}, "
                             f"got {self.mode!r}")


class CanaryRejected(RuntimeError):
    """Typed publish veto: the candidate snapshot flipped recorded
    live decisions beyond the configured threshold. Carries the diff
    report plus the candidate (snapshot, plan) so callers — the smoke
    gate, admission, an operator shell — can re-derive evidence."""

    def __init__(self, message: str, report: CanaryReport,
                 candidate_snapshot: Any = None,
                 candidate_plan: Any = None):
        super().__init__(message)
        self.report = report
        self.candidate_snapshot = candidate_snapshot
        self.candidate_plan = candidate_plan


class ConfigCanary:
    """Record → shadow-replay → diff → gate, owned by the
    RuntimeServer and consulted by the Controller before every
    non-initial publish."""

    def __init__(self, config: CanaryConfig | None = None,
                 metrics: dict | None = None):
        self.config = config or CanaryConfig()
        self.recorder = TrafficRecorder(
            capacity=self.config.capacity,
            sample_every=self.config.sample_every)
        self._metrics = metrics if metrics is not None else FAMILIES
        self._lock = threading.Lock()
        self._reports: collections.deque = collections.deque(
            maxlen=max(self.config.keep_reports, 1))
        self.evaluations = 0
        self.vetoes = 0
        # set by gate() when a DIVERGENT candidate is allowed through
        # (warn mode / sub-threshold / waived); consumed by
        # on_published() after the dispatcher swap
        self._rebaseline_on_publish = False

    # -- gate ----------------------------------------------------------

    def gate(self, active_dispatcher: Any, candidate_snapshot: Any,
             candidate_plan: Any,
             buckets: tuple[int, ...] = ()) -> CanaryRejected | None:
        """Evaluate the candidate against recorded traffic. Returns a
        `CanaryRejected` when the publish must be vetoed (mode=gate
        and divergence beyond threshold), else None (publish — the
        report, if any, is recorded either way). Never raises."""
        cfg = self.config
        if cfg.mode == "off":
            return None
        # fresh decision per evaluation: a flag left by a publish that
        # failed mid-rebuild must not wipe the ring on a later,
        # unrelated publish
        self._rebaseline_on_publish = False
        t0 = time.perf_counter()
        try:
            report = self._evaluate(active_dispatcher,
                                    candidate_snapshot,
                                    candidate_plan, buckets)
        except Exception:
            log.exception("canary evaluation failed; publishing "
                          "WITHOUT shadow validation (fail-open)")
            self._metrics["errors"].inc()
            return None
        finally:
            self._metrics["publish_delay_seconds"].observe(
                time.perf_counter() - t0)
        if report is None:     # abstained (no corpus / no plan)
            return None
        veto = (cfg.mode == "gate"
                and report.divergence_rate > cfg.max_divergence_rate)
        report.verdict = "veto" if veto else (
            "warn" if report.n_divergent else "publish")
        self._metrics["verdicts"].inc(1, verdict=report.verdict)
        self._record(report)
        if not veto:
            if report.n_divergent or report.n_waived:
                log.warning(
                    "canary: candidate rev %s diverges on %d/%d "
                    "recorded rows (+%d waived) (%s) — mode=%s, "
                    "publishing", report.candidate_revision,
                    report.n_divergent, report.n_rows,
                    report.n_waived, report.diverging_rules()[:5],
                    cfg.mode)
                # a DIVERGENT candidate is about to become the live
                # config: rows recorded under the old one now claim
                # decisions the new config legitimately changed, and
                # keeping them would re-report the accepted divergence
                # against every later candidate (an identical swap
                # must stay zero-divergence). Re-baseline — but only
                # AFTER the dispatcher swap (on_published): the old
                # dispatcher keeps tapping old-config rows until then,
                # and clearing here would let them survive the clear.
                self._rebaseline_on_publish = True
            return None
        self.vetoes += 1
        top = report.diverging_rules()
        msg = (f"canary veto: candidate config rev "
               f"{report.candidate_revision} flips "
               f"{report.n_divergent}/{report.n_rows} recorded live "
               f"decisions (rate {report.divergence_rate:.4f} > "
               f"{cfg.max_divergence_rate}) — diverging rules: "
               f"{', '.join(top[:5]) or '(none attributed)'}")
        return CanaryRejected(msg, report,
                              candidate_snapshot=candidate_snapshot,
                              candidate_plan=candidate_plan)

    def _evaluate(self, active_dispatcher, candidate_snapshot,
                  candidate_plan, buckets) -> CanaryReport | None:
        cfg = self.config
        self.evaluations += 1
        entries = self.recorder.corpus(limit=cfg.replay_limit)
        # ring OCCUPANCY, not the limit-capped replay subset — the
        # gauge's help text promises the former
        self._metrics["recorder_entries"].set(
            self.recorder.stats()["entries"])
        if len(entries) < cfg.min_rows:
            log.info("canary: %d recorded rows < min_rows=%d — "
                     "abstaining", len(entries), cfg.min_rows)
            return None
        identity = getattr(active_dispatcher, "identity_attr",
                           "destination.service")
        if candidate_plan is not None:
            replay = replay_entries(candidate_snapshot,
                                    candidate_plan, entries,
                                    buckets=buckets,
                                    identity_attr=identity)
        elif not getattr(candidate_snapshot, "rules", ()):
            # a RULE WIPE compiles to no plan at all — the most
            # catastrophic swap must not bypass the gate. Zero rules
            # means every check answers OK: diff against the shared
            # synthetic allow-everything replay (the admission hook's
            # rule-less baseline) so recorded denies register as
            # status flips.
            from istio_tpu.canary.replay import allow_everything_replay
            replay = allow_everything_replay(len(entries))
        else:
            # rules exist but no plan (non-fused server / plan-build
            # failure): shadow replay is device-side — abstain
            log.info("canary: candidate has no fused plan — "
                     "abstaining (shadow replay is device-side)")
            return None
        self._metrics["replays"].inc()
        self._metrics["rows"].inc(replay.n_rows)
        self._metrics["replay_seconds"].observe(replay.wall_s)
        report = diff_decisions(
            entries, replay, waivers=cfg.waivers,
            exemplars_per_rule=cfg.exemplars_per_rule)
        report.mode = cfg.mode
        report.threshold = cfg.max_divergence_rate
        report.candidate_revision = getattr(candidate_snapshot,
                                            "revision", None)
        for kind, n in report.by_kind.items():
            self._metrics["divergences"].inc(n, kind=kind)
        self._metrics["rate"].set(report.divergence_rate)
        if report.n_divergent and active_dispatcher is not None:
            try:
                confirm_exemplars(
                    report,
                    active_dispatcher.snapshot,
                    active_dispatcher.fused,
                    candidate_snapshot, candidate_plan,
                    identity_attr=identity)
            except Exception:
                log.exception("canary exemplar oracle confirm failed")
        return report

    def on_published(self, dispatcher: Any = None) -> None:
        """Controller hook, called right AFTER the atomic dispatcher
        swap: when the published candidate was divergent, re-baseline
        the recorder — rows recorded under the superseded config claim
        decisions the new config legitimately changed, and replaying
        them would re-report the accepted divergence against every
        later candidate. Cleared post-swap so the old dispatcher's
        final taps land before the wipe (batches already in flight on
        it may still tap a stale row afterwards — a bounded, self-
        healing residue, same in-flight grace the rulestats retire
        sweep covers). Never raises."""
        if not self._rebaseline_on_publish:
            return
        self._rebaseline_on_publish = False
        try:
            self.recorder.clear()
            log.info("canary: recorder re-baselined after divergent "
                     "publish")
        except Exception:
            log.exception("canary recorder re-baseline failed")

    # -- views ---------------------------------------------------------

    def _record(self, report: CanaryReport) -> None:
        with self._lock:
            self._reports.append(report)

    def reports(self) -> list[CanaryReport]:
        with self._lock:
            return list(self._reports)

    def snapshot(self) -> dict:
        """JSON-able /debug/canary payload."""
        with self._lock:
            reports = [r.to_dict() for r in self._reports]
        return {
            "mode": self.config.mode,
            "max_divergence_rate": self.config.max_divergence_rate,
            "waivers": list(self.config.waivers),
            "replay_limit": self.config.replay_limit,
            "evaluations": self.evaluations,
            "vetoes": self.vetoes,
            "recorder": self.recorder.stats(),
            "reports": reports,
        }
