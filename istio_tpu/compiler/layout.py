"""Batch layout, value interning, and tensorization.

The TPU data model for attribute bags (SURVEY.md §2.2 translation note):
the wire protocol already dictionary-codes attribute names and string
values as int32 indices, so a batch of requests tensorizes naturally into
dense int32 arrays.

Key design decision — IDENTITY SEMANTICS: the expression language has
no arithmetic over attribute values (intrinsics: EQ/NEQ/OR/LOR/LAND/
INDEX plus the ordered comparisons, reference func.go:39-72), so every
non-boolean scalar value is interned into one opaque int32 id space and
equality becomes id comparison. Byte tensors serve string slots
consumed by byte-level predicates (glob/regex/prefix/suffix) AND
ordered comparisons: numeric slots (INT64/DOUBLE/DURATION/TIMESTAMP)
store an 8-byte ORDER-PRESERVING key (sign-flipped big-endian; IEEE
bit-trick for doubles), so `<`/`>` lower to the same lexicographic
byte compare as strings (bytes_ops.lex_cmp). IP addresses are
normalized to 16-byte form before interning so `ip_equal` semantics
(v4 == v4-in-v6, externs.go:88) hold under id equality; timestamps and
durations normalize to epoch-/total-nanoseconds.

String-map indexing with CONSTANT keys becomes "derived slots": the
tensorizer extracts ``bag["request.header"]["host"]`` into its own id +
present column, so INDEX costs nothing on device.
"""
from __future__ import annotations

import dataclasses
import datetime
import threading
from typing import Any, Hashable, Mapping, Sequence

import jax
import numpy as np

from istio_tpu.attribute.bag import Bag
from istio_tpu.attribute.types import ValueType

# Reserved intern ids.
ID_INVALID = 0
ID_FALSE = 1
ID_TRUE = 2

DEFAULT_MAX_STR_LEN = 128

# types whose byte slots carry order-preserving keys (BOOL is NOT
# orderable — the oracle raises on it, expr/oracle.py _ordered)
ORDER_KEY_TYPES = frozenset({ValueType.INT64, ValueType.DOUBLE,
                             ValueType.DURATION, ValueType.TIMESTAMP})

_EPOCH = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)
_I64_FLIP = 0x8000_0000_0000_0000
_U64_MASK = 0xFFFF_FFFF_FFFF_FFFF
# 1-byte marker for a numeric slot whose value could not be encoded
# (wrong wire type): real keys are 8 bytes, NaN is 0 bytes, this is 1
ORDER_KEY_ERROR = b"\x00"


def order_key_bytes(v: Any, vtype: ValueType) -> bytes:
    """8-byte big-endian key whose unsigned lexicographic order equals
    the value order — `<` on device is then bytes_ops.lex_cmp over the
    same planes string predicates use. Returns b"" (present-but-empty =
    undecidable marker) for values with no total-order embedding (NaN:
    every ordered comparison is False in the reference, which no key
    can encode)."""
    import struct

    if vtype == ValueType.INT64:
        if isinstance(v, (str, bytes)):
            raise ValueError("non-numeric INT64 payload")
        return struct.pack(">Q", (int(v) ^ _I64_FLIP) & _U64_MASK)
    if vtype == ValueType.DOUBLE:
        if isinstance(v, (str, bytes)):
            raise ValueError("non-numeric DOUBLE payload")
        d = float(v)
        if d != d:   # NaN
            return b""
        if d == 0.0:
            d = 0.0   # -0.0 == +0.0 must share one key (IEEE order)
        bits = struct.unpack(">Q", struct.pack(">d", d))[0]
        bits = (bits ^ _U64_MASK) if (bits >> 63) else (bits | _I64_FLIP)
        return struct.pack(">Q", bits)
    if vtype == ValueType.DURATION:
        if isinstance(v, (str, bytes)):
            raise ValueError("non-duration payload")
        ns = (v // datetime.timedelta(microseconds=1)) * 1000 \
            if isinstance(v, datetime.timedelta) else int(v)
        return struct.pack(">Q", (ns ^ _I64_FLIP) & _U64_MASK)
    if vtype == ValueType.TIMESTAMP:
        if isinstance(v, datetime.datetime):
            if v.tzinfo is None:
                v = v.replace(tzinfo=datetime.timezone.utc)
            ns = int((v - _EPOCH) // datetime.timedelta(microseconds=1)
                     ) * 1000
        elif isinstance(v, (str, bytes)):
            raise ValueError("non-timestamp payload")
        else:
            ns = int(v)
        return struct.pack(">Q", (ns ^ _I64_FLIP) & _U64_MASK)
    raise ValueError(f"no order key for {vtype}")


def _normalize(value: Any) -> tuple[str, Hashable]:
    """Map a runtime value to its (type_tag, canonical) intern key."""
    if isinstance(value, bool):
        return ("b", value)
    if isinstance(value, int):
        return ("i", value)
    if isinstance(value, float):
        return ("d", value)
    if isinstance(value, str):
        return ("s", value)
    if isinstance(value, bytes):
        if len(value) == 4:  # v4 → v4-in-v6 canonical form (net.IP.Equal)
            value = b"\x00" * 10 + b"\xff\xff" + value
        return ("p", value)
    if isinstance(value, datetime.timedelta):
        return ("D", round(value.total_seconds() * 1e9))
    if isinstance(value, datetime.datetime):
        return ("t", round(value.timestamp() * 1e9))
    raise TypeError(f"cannot intern value of type {type(value)}")


def canonical_bytes(norm: tuple[str, Hashable]) -> bytes:
    """_normalize key → canonical byte encoding (shared with the C++
    shim's intern `Key`; shim.cpp builds the identical bytes)."""
    import struct
    tag, v = norm
    t = tag.encode()
    if tag == "b":
        return t + (b"\x01" if v else b"\x00")
    if tag in ("i", "D", "t"):
        return t + struct.pack("<q", int(v))
    if tag == "d":
        return t + struct.pack("<d", float(v))
    if tag == "s":
        return t + str(v).encode("utf-8")
    if tag == "p":
        return t + bytes(v)
    raise ValueError(f"unknown intern tag {tag}")


def stable_hash31(value: Any) -> int:
    """Content-stable 31-bit hash of a value (FNV-1a over the canonical
    key bytes — the shim computes the identical function). Used for
    quota bucketing: unlike intern/ephemeral ids it never depends on
    encounter order or snapshot, so a key maps to the same bucket for
    the life of the counter window."""
    h = 0x811C9DC5
    for b in canonical_bytes(_normalize(value)):
        h = ((h ^ b) * 0x01000193) & 0xFFFFFFFF
    return h & 0x7FFFFFFF


class InternTable:
    """Grow-only value ↔ int32-id table for COMPILE-TIME constants
    (bounded by config size; shared across snapshots so constant ids
    stay stable). Runtime-observed values never enter this table — the
    tensorizer assigns them negative per-batch ephemeral ids
    (AttributeBatch.ephemeral_values), so a long-running server's
    memory does not grow with distinct request values. Thread-safe;
    ids are stable for the life of the table."""

    def __init__(self) -> None:
        self._by_key: dict[tuple[str, Hashable], int] = {
            ("b", False): ID_FALSE, ("b", True): ID_TRUE,
        }
        self._values: list[Any] = [None, False, True]
        self._lock = threading.Lock()
        # longest byte-plane CONSTANT any compile using this table has
        # materialized (tensor_expr._compile_bytes). The latency-tier
        # gate (fused.str_tiers) must not narrow batches below it: a
        # constant row sliced to the tier loses real tail bytes, which
        # flips suffix-window verdicts. Grow-only like the table, so
        # conservative across config swaps on a shared table.
        self.max_byte_const_len = 0

    def note_byte_const(self, n: int) -> None:
        with self._lock:
            if n > self.max_byte_const_len:
                self.max_byte_const_len = n

    def intern(self, value: Any) -> int:
        key = _normalize(value)
        with self._lock:
            idx = self._by_key.get(key)
            if idx is None:
                idx = len(self._values)
                self._by_key[key] = idx
                self._values.append(value)
            return idx

    def lookup(self, value: Any) -> int:
        """Id of a value WITHOUT interning; ID_INVALID if unseen."""
        key = _normalize(value)
        with self._lock:
            return self._by_key.get(key, ID_INVALID)

    def reader(self) -> Mapping[tuple[str, Hashable], int]:
        """Lock-free read view for hot loops. Sound because the table
        only ever GROWS (ids are never reassigned or removed) — a
        reader that misses an in-flight insert sees a strict subset,
        which callers must tolerate (the tensorizer does: a missed
        constant becomes a batch ephemeral). This method is the
        contract; do not reach into _by_key directly."""
        return self._by_key

    def value_of(self, idx: int) -> Any:
        if idx < 0:
            raise KeyError(
                f"id {idx} is a per-batch ephemeral id; resolve it via "
                "AttributeBatch.value_of(id, interner)")
        with self._lock:
            return self._values[idx]

    def __len__(self) -> int:
        with self._lock:
            return len(self._values)


@dataclasses.dataclass(frozen=True)
class BatchLayout:
    """Static slot assignment for a config snapshot.

    scalar slots cover every non-map attribute in the manifest plus one
    derived slot per (map attribute, constant key) pair the compiled
    expressions need. Byte slots exist per string source consumed by a
    byte-level predicate.
    """
    manifest: Mapping[str, ValueType]
    slots: Mapping[str, int]                       # scalar attr → column
    derived_slots: Mapping[tuple[str, str], int]   # (map, key) → column
    map_slots: Mapping[str, int]                   # map attr → map column
    byte_slots: Mapping[Any, int]                  # attr | (map,key) → byte col
    max_str_len: int = DEFAULT_MAX_STR_LEN
    # extern-converted columns: ("ip"|"timestamp", operand-key) → id
    # column. The TENSORIZER runs the conversion at ingest (normalize
    # at the edge — the TPU-native home for string parsing) and interns
    # the result; id ID_INVALID with present=True marks a conversion/
    # lookup error (tensor_expr reads it back as err).
    extern_slots: Mapping[tuple[str, str], int] = \
        dataclasses.field(default_factory=dict)
    # operand ASTs per extern slot key (for the tensorizer's oracle)
    extern_defs: Mapping[tuple[str, str], Any] = \
        dataclasses.field(default_factory=dict)

    @property
    def n_columns(self) -> int:
        return (len(self.slots) + len(self.derived_slots)
                + len(self.extern_slots))

    @property
    def n_maps(self) -> int:
        return len(self.map_slots)

    @property
    def n_byte_slots(self) -> int:
        return len(self.byte_slots)

    def slot_of(self, name: str) -> int:
        return self.slots[name]

    def derived_slot_of(self, map_name: str, key: str) -> int:
        return self.derived_slots[(map_name, key)]


def build_layout(manifest: Mapping[str, ValueType],
                 derived_keys: Sequence[tuple[str, str]] = (),
                 byte_sources: Sequence[Any] = (),
                 max_str_len: int = DEFAULT_MAX_STR_LEN,
                 extern_sources: Sequence[tuple[str, str, Any]] = ()
                 ) -> BatchLayout:
    """Assign columns. `derived_keys`, `byte_sources` and
    `extern_sources` ((extern name, operand key, operand AST) triples)
    are collected by the expression/ruleset compilers (a compile →
    layout → recompile fixpoint is avoided by collecting requirements
    in a pre-pass)."""
    slots: dict[str, int] = {}
    map_slots: dict[str, int] = {}
    for name in sorted(manifest):
        if manifest[name] == ValueType.STRING_MAP:
            map_slots[name] = len(map_slots)
        else:
            slots[name] = len(slots)
    derived: dict[tuple[str, str], int] = {}
    col = len(slots)
    for mk in sorted(set(derived_keys)):
        if mk not in derived:
            derived[mk] = col
            col += 1
    externs: dict[tuple[str, str], int] = {}
    defs: dict[tuple[str, str], Any] = {}
    for name, key, ast in sorted(extern_sources,
                                 key=lambda t: (t[0], t[1])):
        k = (name, key)
        if k not in externs:
            externs[k] = col
            defs[k] = ast
            col += 1
    bytes_: dict[Any, int] = {}
    for src in byte_sources:
        if src not in bytes_:
            bytes_[src] = len(bytes_)
    return BatchLayout(manifest=dict(manifest), slots=slots,
                       derived_slots=derived, map_slots=map_slots,
                       byte_slots=dict(bytes_), max_str_len=max_str_len,
                       extern_slots=externs, extern_defs=defs)


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class AttributeBatch:
    """A batch of attribute bags as device arrays.

    ids        int32 [B, n_columns]   interned value per scalar/derived slot
    present    bool  [B, n_columns]   slot has a value
    map_present bool [B, n_maps]      map attribute itself present
    str_bytes  uint8 [B, n_byte_slots, L]
    str_lens   int32 [B, n_byte_slots]
    """
    ids: Any
    present: Any
    map_present: Any
    str_bytes: Any
    str_lens: Any
    # stable 31-bit content hash per present scalar slot (stable_hash31)
    # — quota bucketing keys on this, not on ids, because ephemeral ids
    # vary with encounter order while a quota window outlives batches
    hash_ids: Any = None
    # host-only: values behind negative ephemeral ids, index (-1 - id).
    # Deliberately NOT part of the pytree (neither leaf nor aux): it
    # must not retrace jits or ride to the device; id -1-k ↔ entry k.
    ephemeral_values: Any = None

    @property
    def batch_size(self) -> int:
        return self.ids.shape[0]

    def value_of(self, vid: int, interner: InternTable) -> Any:
        """Resolve an id from THIS batch: non-negative ids live in the
        compile-time intern table, negative ids in the batch's own
        ephemeral side table."""
        if vid >= 0:
            return interner.value_of(vid)
        return self.ephemeral_values[-1 - vid]

    def tree_flatten(self):
        return ((self.ids, self.present, self.map_present,
                 self.str_bytes, self.str_lens, self.hash_ids), None)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


class Tensorizer:
    """Host-side bag-batch → AttributeBatch conversion.

    This is the Python reference implementation of the ingest path; the
    C++ shim (SURVEY.md §7 layer 8) will produce identical arrays
    straight from the wire format.
    """

    def __init__(self, layout: BatchLayout, interner: InternTable,
                 hash_slots: Any = None):
        """`hash_slots` selects which columns get the stable content
        hash (quota bucketing): an iterable of column indices, "all",
        or None (none — hashing every cell in Python costs ~10× the
        tensorize itself; only quota key slots need it, and
        PolicyEngine.tensorizer passes exactly those). The C++ shim
        hashes every cell for free. The plane is always an array so
        every producer yields the same pytree treedef."""
        self.layout = layout
        self.interner = interner
        if hash_slots == "all":
            self.hash_slots: frozenset[int] = frozenset(
                range(layout.n_columns))
        else:
            self.hash_slots = frozenset(hash_slots or ())
        # extern-converted columns: operand oracle + converter, built
        # once (layout.extern_defs carries the operand ASTs)
        self._externs: list[tuple[int, Any, Any]] = []
        if layout.extern_slots:
            from istio_tpu.expr.checker import AttributeDescriptorFinder
            from istio_tpu.expr.externs import (extern_ip,
                                                extern_timestamp)
            from istio_tpu.expr.oracle import OracleProgram
            finder = AttributeDescriptorFinder(dict(layout.manifest))
            conv = {"ip": extern_ip, "timestamp": extern_timestamp}
            for (name, key), col in layout.extern_slots.items():
                prog = OracleProgram.from_ast(
                    layout.extern_defs[(name, key)], finder)
                self._externs.append((col, prog, conv[name]))

    def tensorize(self, bags: Sequence[Bag]) -> AttributeBatch:
        lay = self.layout
        b = len(bags)
        ncol = lay.n_columns
        ids = np.zeros((b, ncol), dtype=np.int32)
        hash_ids = np.zeros((b, ncol), dtype=np.int32)
        present = np.zeros((b, ncol), dtype=bool)
        map_present = np.zeros((b, max(lay.n_maps, 1)), dtype=bool)
        nbyte = max(lay.n_byte_slots, 1)
        str_bytes = np.zeros((b, nbyte, lay.max_str_len), dtype=np.uint8)
        str_lens = np.zeros((b, nbyte), dtype=np.int32)
        # values unseen at compile time get negative per-batch ids —
        # consistent within the batch (slot-vs-slot EQ still works),
        # never equal to any constant, never retained after the batch
        eph_ids: dict[tuple[str, Hashable], int] = {}
        eph_values: list[Any] = []

        # lock-free constant lookup (see InternTable.reader): a
        # concurrently-added constant we miss simply becomes a batch
        # ephemeral, which this snapshot's programs never compare
        # against anyway
        by_key = self.interner.reader()
        eph_get, eph_set = eph_ids.get, eph_ids.__setitem__

        def rid(v: Any) -> int:
            key = _normalize(v)
            idx = by_key.get(key)
            if idx is not None:
                return idx
            neg = eph_get(key)
            if neg is None:
                neg = -1 - len(eph_values)
                eph_set(key, neg)
                eph_values.append(v)
            return neg

        hash_slots = self.hash_slots
        for i, bag in enumerate(bags):
            for name, col in lay.slots.items():
                v, ok = bag.get(name)
                if not ok:
                    continue
                present[i, col] = True
                ids[i, col] = rid(v)
                if col in hash_slots:
                    hash_ids[i, col] = stable_hash31(v)
            for name, mcol in lay.map_slots.items():
                v, ok = bag.get(name)
                if ok:
                    map_present[i, mcol] = True
            for (mname, key), col in lay.derived_slots.items():
                m, ok = bag.get(mname)
                if ok and isinstance(m, Mapping) and key in m:
                    present[i, col] = True
                    ids[i, col] = rid(m[key])
                    if col in hash_slots:
                        hash_ids[i, col] = stable_hash31(m[key])
            for src, bcol in lay.byte_slots.items():
                raw = self._byte_source_value(bag, src)
                if raw is None:
                    continue
                enc = raw[:lay.max_str_len]
                if enc:
                    str_bytes[i, bcol, :len(enc)] = np.frombuffer(
                        enc, dtype=np.uint8)
                str_lens[i, bcol] = len(enc)
            for col, prog, convert in self._externs:
                # normalize-at-ingest: run the extern over the operand
                # oracle; a lookup or conversion error marks the column
                # present-with-ID_INVALID (read back as err on device —
                # externs are hard contexts, oracle.py)
                try:
                    converted = convert(prog.evaluate(bag))
                except Exception:
                    present[i, col] = True
                    ids[i, col] = ID_INVALID
                    continue
                present[i, col] = True
                ids[i, col] = rid(converted)
                if col in hash_slots:
                    hash_ids[i, col] = stable_hash31(converted)

        return AttributeBatch(ids=ids, present=present,
                              map_present=map_present,
                              str_bytes=str_bytes, str_lens=str_lens,
                              hash_ids=hash_ids,
                              ephemeral_values=eph_values)

    def _byte_source_value(self, bag: Bag, src: Any) -> bytes | None:
        if isinstance(src, tuple):
            mname, key = src
            m, ok = bag.get(mname)
            if ok and isinstance(m, Mapping) and key in m:
                v = m[key]
                return v.encode("utf-8") if isinstance(v, str) else None
            return None
        v, ok = bag.get(src)
        if not ok:
            return None
        vt = self.layout.manifest.get(src)
        if vt is not None and vt in ORDER_KEY_TYPES:
            # numeric slots carry the 8-byte order-preserving key so
            # ordered comparisons ride the SAME lexicographic compare
            # as strings (bytes_ops.lex_cmp). Markers (tensor_expr
            # _compile_cmp): b"" = NaN (compares False, never err);
            # b"\x00" = malformed value (bags are untyped wire data —
            # the oracle raises per row, so the device reads err;
            # raising here would poison the whole batch)
            try:
                return order_key_bytes(v, vt)
            except Exception:
                return ORDER_KEY_ERROR
        if isinstance(v, str):
            return v.encode("utf-8")
        if isinstance(v, (bytes, bytearray)):
            # IP/bytes values ride their raw bytes (CIDR list lowering
            # compares them in v6-mapped space, models/policy_engine)
            return bytes(v)
        return None
