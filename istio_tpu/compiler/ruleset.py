"""Ruleset compiler: N match predicates → one batched tensor program.

This is the batched replacement for the reference resolver's per-request
loop (mixer/pkg/runtime/resolver.go:202-238 filterActions — which calls
the IL interpreter once per rule per request, 100-600ns each per
bench.baseline). Here a whole config snapshot compiles ONCE into device
tensors and every request batch is matched against ALL rules in one
fused XLA program:

    atoms:   evaluate every unique primitive predicate once per request
             → m[B, A] "definitely true", n[B, A] "definitely false"
    conj:    lit = [m ‖ n ‖ TRUE];  sat[B, n_conj] = AND over each
             conjunction's padded literal indices (gather + all)
    rules:   matched = OR over each rule's M-conjunction indices;
             not_matched likewise over N; err = ~matched & ~not_matched

The conj/rule stages are padded index gathers + reductions rather than
one-hot [2A, n_conj] / [n_conj, R] matmuls: conjunctions average only a
few literals, so the dense matmul burns ~1000× the useful FLOPs
(measured 23ms vs ~1ms per 2048×10k-rule step on v5e). The index
tensors ride HBM bandwidth and shard over a rule axis ("mp") for
VMEM-bound snapshots (istio_tpu/parallel/mesh.py).

Exactness: each predicate's AST is decomposed over its top-level
LAND/LOR skeleton into a pair of monotone DNFs over per-atom literals
{m_a, n_a}, where m_a = val∧¬err ("definitely true") and
n_a = ¬val∧¬err ("definitely false"):

    M(atom)      = {{m_a}}                 N(atom)      = {{n_a}}
    M(a && b)    = M(a)∧M(b)               N(a && b)    = N(a) ∨ (M(a)∧N(b))
    M(a || b)    = M(a) ∨ (N(a)∧M(b))      N(a || b)    = N(a)∧N(b)

These recurrences are provably equivalent to the short-circuit +
error-propagation semantics of the oracle (istio_tpu/expr/oracle.py,
mirroring IL generateLand/generateLor compiler.go:373/:354): e.g. a
short-circuited `false && err` is N(a)∧anything ⇒ not-matched, while
`true && err` is neither M nor N ⇒ error. The conformance tests
(tests/test_ruleset.py) check every corpus predicate against the oracle.

Atoms are deduplicated ACROSS rules (10k istio rules share a few hundred
distinct predicates in practice) and evaluated in three tiers:
  1. a vectorized gather-compare for EQ/NEQ(slot, const) — covers the
     overwhelming majority of real istio match clauses;
  2. a vectorized slot-vs-slot compare;
  3. per-atom compiled closures from tensor_expr for everything else
     (byte predicates, `|` fallback chains, nested EQ of booleans).

Rules whose predicate cannot lower (dynamic patterns, DNF blowup past
`dnf_cap`) are marked host-fallback and carry an OracleProgram; the
runtime dispatcher overlays their verdicts on the device result.

ReferencedAttributes (protoBag.go:117 semantics) become compile-time
per-rule attribute bitmaps (SURVEY.md §2.2 translation note).
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Any, Callable, Mapping, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from istio_tpu.attribute.types import ValueType
from istio_tpu.compiler.layout import (AttributeBatch, BatchLayout,
                                       ID_FALSE, ID_TRUE, InternTable,
                                       build_layout)
from istio_tpu.compiler import tensor_expr
from istio_tpu.compiler.tensor_expr import (HostFallback, Requirements,
                                            collect_requirements)
from istio_tpu.expr.checker import (AttributeDescriptorFinder, DEFAULT_FUNCS,
                                    TypeError_, eval_type)
from istio_tpu.expr.exprs import Expression, const_expr
from istio_tpu.expr.externs import ExternError, extern_ip, extern_timestamp
from istio_tpu.expr.oracle import OracleProgram
from istio_tpu.expr.parser import parse

V = ValueType

# A literal is (atom_index, kind): kind 'm' = definitely-true,
# 'n' = definitely-false. A conjunction is a frozenset of literals; a DNF
# a set of conjunctions.
Literal = tuple[int, str]
Conj = frozenset
Dnf = set

DEFAULT_DNF_CAP = 128


class DnfBlowup(HostFallback):
    """Predicate's DNF exceeded dnf_cap conjunctions."""


def _contradicts(c: Conj) -> bool:
    idxs = {}
    for idx, kind in c:
        prev = idxs.get(idx)
        if prev is not None and prev != kind:
            return True
        idxs[idx] = kind
    return False


def _dnf_and(a: Dnf, b: Dnf, cap: int) -> Dnf:
    out: Dnf = set()
    for x in a:
        for y in b:
            c = x | y
            if not _contradicts(c):
                out.add(c)
    if len(out) > cap:
        raise DnfBlowup(f"DNF exceeded {cap} conjunctions")
    return _prune(out)


def _prune(d: Dnf) -> Dnf:
    """Drop subsumed conjunctions (c2 ⊇ c1 is redundant)."""
    by_size = sorted(d, key=len)
    kept: list[Conj] = []
    for c in by_size:
        if not any(k <= c for k in kept):
            kept.append(c)
    return set(kept)


@dataclasses.dataclass
class Rule:
    """A policy rule's match clause (reference: the `match:` field of a
    mixer rule, config.proto; resolver.go:34 Rule)."""
    name: str
    match: str = ""          # empty = always matches (resolver.go:219)
    namespace: str = ""
    # pre-built predicate AST (synthesized pseudo-rules, e.g. the rbac
    # lowering compiler/rbac_lower.py) — used instead of parsing `match`
    ast: Expression | None = None


def _rule_ast(rule: Rule) -> Expression:
    if rule.ast is not None:
        return rule.ast
    return parse(rule.match.strip() or "true")


def _rule_oracle(rule: Rule,
                 finder: AttributeDescriptorFinder) -> OracleProgram:
    if rule.ast is not None:
        return OracleProgram.from_ast(rule.ast, finder)
    return OracleProgram(rule.match.strip() or "true", finder)


@dataclasses.dataclass
class _AtomTable:
    """Deduplicated primitive predicates across all rules. Append-only
    with O(added) rollback: mark() before a speculative decompose,
    revert(mark) drops only the atoms added since — copying the whole
    table per rule made snapshot compile quadratic in rule count."""
    asts: list[Expression] = dataclasses.field(default_factory=list)
    by_key: dict[str, int] = dataclasses.field(default_factory=dict)
    _keys: list[str] = dataclasses.field(default_factory=list)

    def index_of(self, e: Expression) -> int:
        key = str(e)
        idx = self.by_key.get(key)
        if idx is None:
            idx = len(self.asts)
            self.by_key[key] = idx
            self.asts.append(e)
            self._keys.append(key)
        return idx

    def mark(self) -> int:
        return len(self.asts)

    def revert(self, mark: int) -> None:
        for key in self._keys[mark:]:
            del self.by_key[key]
        del self._keys[mark:]
        del self.asts[mark:]


def _decompose(e: Expression, atoms: _AtomTable, cap: int) -> tuple[Dnf, Dnf]:
    """→ (M, N): DNFs for definitely-matched / definitely-not-matched."""
    if e.const_ is not None and e.const_.vtype == V.BOOL:
        if e.const_.value:
            return ({frozenset()}, set())
        return (set(), {frozenset()})
    if e.fn is not None and e.fn.name in ("LAND", "LOR"):
        name = e.fn.name
        args = e.fn.args
        m, n = _decompose(args[0], atoms, cap)
        for arg in args[1:]:
            ma, na = _decompose(arg, atoms, cap)
            if name == "LAND":
                m, n = _dnf_and(m, ma, cap), _prune(n | _dnf_and(m, na, cap))
            else:
                m, n = _prune(m | _dnf_and(n, ma, cap)), _dnf_and(n, na, cap)
        return m, n
    idx = atoms.index_of(e)
    return ({frozenset([(idx, "m")])}, {frozenset([(idx, "n")])})


def _fold_time_const(e: Expression) -> Any | None:
    """Fold ip("c")/timestamp("c") over a constant into a value;
    None if not that shape. ExternError propagates (oracle parity: the
    atom then always errors — handled by the general path)."""
    f = e.fn
    if f is None or f.name not in ("ip", "timestamp"):
        return None
    if not f.args or f.args[0].const_ is None:
        return None
    raw = f.args[0].const_.value
    return extern_ip(raw) if f.name == "ip" else extern_timestamp(raw)


@dataclasses.dataclass
class _SlotRef:
    col: int


def _slot_ref(e: Expression, layout: BatchLayout,
              finder: AttributeDescriptorFinder) -> _SlotRef | None:
    """Variable or INDEX(map, const-key) → its scalar/derived column."""
    if e.var is not None:
        vt = finder.get_attribute(e.var.name)
        if vt is None or vt == V.STRING_MAP:
            return None
        return _SlotRef(layout.slot_of(e.var.name))
    f = e.fn
    if (f is not None and f.name == "INDEX" and f.args[0].var is not None
            and f.args[1].const_ is not None
            and isinstance(f.args[1].const_.value, str)):
        pair = (f.args[0].var.name, f.args[1].const_.value)
        if pair in layout.derived_slots:
            return _SlotRef(layout.derived_slots[pair])
    return None


def _const_id(e: Expression, interner: InternTable) -> int | None:
    """Constant operand (or foldable ip()/timestamp()) → intern id."""
    if e.const_ is not None:
        v = e.const_.value
        if isinstance(v, bool):
            return ID_TRUE if v else ID_FALSE
        return interner.intern(v)
    try:
        folded = _fold_time_const(e)
    except ExternError:
        return None
    if folded is None:
        return None
    return interner.intern(folded)


@dataclasses.dataclass
class RuleSetProgram:
    """The compiled snapshot. `fn(batch)` → (matched, not_matched, err)
    each bool[B, n_rows], where n_rows = n_rules rounded up to
    `rule_pad` (mp-sharding padding; pad rows read False/True/False and
    belong to an unmatchable namespace — size consumers off
    rule_ns.shape[0], NOT n_rules). Host-fallback rules read
    False/False/True on device; overlay with `host_eval`."""
    rules: list[Rule]
    layout: BatchLayout
    interner: InternTable
    fn: Callable[..., tuple[Any, Any, Any]]   # fn(params, batch)
    params: Mapping[str, Any]   # device index tensors (lit_idx/conj_*_idx)
    n_atoms: int
    n_conjs: int
    host_fallback: dict[int, OracleProgram]   # rule idx → oracle
    fallback_reason: dict[int, str]
    attr_mask: np.ndarray                     # bool [n_rows, n_columns]
    attr_names: list[set]                     # per REAL rule (n_rules)
    rule_ns: np.ndarray                       # int32 [n_rows]
    ns_ids: dict[str, int]
    # ---- debugging surface (compiler/disasm.py — the il/text +
    #      Stepper role). Retained source structure, not device state:
    atom_asts: list[Any] = dataclasses.field(default_factory=list)
    atom_tier: dict[int, str] = dataclasses.field(default_factory=dict)
    per_rule_dnf: list[Any] = dataclasses.field(default_factory=list)
    # ---- compiled-shape geometry (atom tier counts, conjunction split,
    #      padded index widths) — the roofline accounting layer
    #      (compiler/roofline.py) derives per-step bytes/op counts from
    #      THESE shapes, never from hand constants
    geometry: dict = dataclasses.field(default_factory=dict)

    @property
    def n_rules(self) -> int:
        return len(self.rules)

    def __call__(self, batch: AttributeBatch) -> tuple[Any, Any, Any]:
        return self.fn(self.params, batch)

    def namespace_id(self, ns: str) -> int:
        """Id for a request namespace; unknown namespaces match only
        default-namespace ('') rules."""
        return self.ns_ids.get(ns, -1)

    def namespace_mask(self, req_ns_ids: Any) -> Any:
        """bool[B, n_rules]: rule visible to the request's namespace —
        default-namespace rules apply to everyone (resolver.go:110
        default + destination-namespace rule lists)."""
        rns = jnp.asarray(self.rule_ns)
        req = jnp.asarray(req_ns_ids)
        return (rns[None, :] == self.ns_ids[""]) | (rns[None, :] == req[:, None])

    def host_eval(self, rule_idx: int, bag) -> tuple[bool, bool, bool]:
        """(matched, not_matched, err) for one host-fallback rule."""
        prog = self.host_fallback[rule_idx]
        try:
            v = bool(prog.evaluate(bag))
            return v, not v, False
        except Exception:
            return False, False, True


def fused_check_status(snapshot, plan, ridx: int, bag) -> int:
    """The status the FUSED device lowering of rule `ridx`'s check
    actions produces for `bag`, re-derived host-side from the
    snapshot's action metadata: denier codes via plan.deny_info,
    STRINGS-list membership with the blacklist→PERMISSION_DENIED /
    whitelist-miss→NOT_FOUND / absent→INTERNAL codes of
    models/policy_engine. THE shared decision-status derivation —
    next to SnapshotOracle because both are the host-side semantic
    truth device paths are judged against: the rulestats smoke gate's
    oracle recount (scripts/rulestats_smoke.py) and the config
    canary's exemplar confirmation (istio_tpu/canary/differ.py) both
    import it, so the two verification surfaces can never silently
    disagree."""
    from istio_tpu.templates import Variety

    info = plan.deny_info.get(ridx) if plan is not None else None
    if info is not None:
        return info[0]
    if plan is not None and ridx in plan.list_rules:
        for hc, _template, inst_names in snapshot.actions_for(
                ridx, Variety.CHECK):
            if hc.adapter != "list":
                continue
            entries = set(map(str, hc.params.get("overrides", ())))
            blacklist = bool(hc.params.get("blacklist", False))
            for iname in inst_names:
                ref = snapshot.instances[iname].value_attr_ref()
                if isinstance(ref, tuple):
                    c, ok = bag.get(ref[0])
                    v = c.get(ref[1]) if ok and \
                        isinstance(c, Mapping) else None
                    ok = v is not None
                else:
                    v, ok = bag.get(ref)
                if not ok or not isinstance(v, str):
                    return 13            # INTERNAL: absent value
                member = v in entries
                if member and blacklist:
                    return 7             # PERMISSION_DENIED
                if not member and not blacklist:
                    return 5             # NOT_FOUND
    return 0


class SnapshotOracle:
    """Whole-snapshot CPU oracle executor — the graceful-degradation
    resolve path the device circuit breaker falls back to
    (runtime/resilience.py).

    Per-rule OracleProgram evaluation with the same namespace-targeting
    semantics as the device RuleSetProgram (default-namespace rules
    apply to everyone; rules in other namespaces only to requests
    addressed there). Correctness over speed by design: every rule runs
    interpreted python per request, which is exactly the conformance
    oracle the compiler tests pin the device programs against — so a
    tripped breaker degrades latency, never answers.

    Oracle programs compile lazily per rule (a breaker trip must not
    pay a whole-snapshot compile before answering its first batch) and
    are seeded with the ruleset's existing host-fallback programs.
    Thread-safe: fallback batches run concurrently on the batcher's
    worker pool."""

    def __init__(self, rules: Sequence[Rule],
                 finder: AttributeDescriptorFinder,
                 seed: Mapping[int, OracleProgram] | None = None):
        self.rules = list(rules)
        self.finder = finder
        self._progs: dict[int, OracleProgram] = dict(seed or {})
        self._lock = threading.Lock()

    def _prog(self, ridx: int) -> OracleProgram:
        prog = self._progs.get(ridx)
        if prog is None:
            prog = _rule_oracle(self.rules[ridx], self.finder)
            with self._lock:
                self._progs.setdefault(ridx, prog)
        return prog

    def resolve(self, bag, request_ns: str
                ) -> tuple[list[int], list[int], int]:
        """→ (active rule idxs, namespace-visible rule idxs, n_errors)
        for one request — the per-bag shape Dispatcher._check_one
        consumes. A predicate that raises counts as not-matched plus
        one resolve error (host_eval parity)."""
        active: list[int] = []
        visible: list[int] = []
        errs = 0
        for ridx, rule in enumerate(self.rules):
            if rule.namespace and rule.namespace != request_ns:
                continue
            visible.append(ridx)
            try:
                matched = bool(self._prog(ridx).evaluate(bag))
            except Exception:
                errs += 1
                continue
            if matched:
                active.append(ridx)
        return active, visible, errs


def compile_ruleset(rules: Sequence[Rule], finder: AttributeDescriptorFinder,
                    *, interner: InternTable | None = None,
                    max_str_len: int | None = None,
                    dnf_cap: int = DEFAULT_DNF_CAP,
                    jit: bool = True,
                    extra_derived_keys: Sequence[tuple[str, str]] = (),
                    extra_byte_sources: Sequence[Any] = (),
                    extra_extern_sources: Sequence[tuple[str, str, Any]] = (),
                    rule_pad: int = 1,
                    decomp_cache=None
                    ) -> RuleSetProgram:
    """Compile a rule snapshot. Never raises for individual bad rules —
    un-lowerable predicates fall back to the oracle; predicates that do
    not even type-check to BOOL raise TypeError_ (config validation's
    job, store/validator.go analog).

    `extra_derived_keys` adds (map, key) columns consumers outside the
    predicates need — e.g. listentry instances the fused engine turns
    into id-membership scans (runtime/fused.py). `extra_byte_sources`
    likewise adds byte slots (attr name or (map, key)) for consumers
    that match VALUE BYTES rather than interned ids — REGEX/CIDR list
    entries lowered to device DFA/prefix scans. `extra_extern_sources`
    adds ip()/timestamp() ingest columns the same way (REPORT instance
    field expressions lowered by runtime/report_lower.py).

    `rule_pad` rounds the RULE-AXIS arrays (conj index matrices,
    rule_ns, attr_mask — and therefore the matched/err planes) up to a
    multiple, so the axis can shard evenly over an mp mesh dimension
    (parallel/mesh.py). Pad rows are definitely-not-matched, never
    error, and belong to an unmatchable namespace; `n_rules` still
    counts real rules only.

    `decomp_cache` (compiler/cache.DecompCache) memoizes the parse +
    DNF decomposition per match string ACROSS compiles: a config delta
    re-presents almost every predicate unchanged, and parse+decompose
    dominate the host-side compile at fleet scale. Replay re-interns
    the cached atom ASTs into this compile's _AtomTable (cross-rule
    dedup preserved) and skips eval_type — entries only exist for
    rules that already validated under the same manifest digest (the
    cache clears itself when the finder or dnf_cap changes)."""
    from istio_tpu.compiler.cache import DecompEntry

    interner = interner or InternTable()
    atoms = _AtomTable()
    per_rule: list[tuple[Dnf, Dnf] | None] = []   # None = host fallback
    host_fallback: dict[int, OracleProgram] = {}
    fallback_reason: dict[int, str] = {}
    parsed: list[Expression] = []

    if decomp_cache is not None:
        decomp_cache.begin(finder, dnf_cap)
    for ridx, rule in enumerate(rules):
        # synthesized pseudo-rules (pre-built ast, e.g. rbac lowering)
        # bypass the cache: they never parse, and keying them would
        # need an ast rendering that costs what it saves
        ckey = rule.match if rule.ast is None else None
        ent = decomp_cache.get(ckey) \
            if decomp_cache is not None and ckey is not None else None
        if ent is not None:
            parsed.append(ent.ast)
            if ent.is_fallback:
                per_rule.append(None)
                host_fallback[ridx] = ent.oracle
                fallback_reason[ridx] = ent.reason
            else:
                idxs = [atoms.index_of(a) for a in ent.atom_asts]
                per_rule.append((
                    {frozenset((idxs[p], k) for p, k in conj)
                     for conj in ent.m},
                    {frozenset((idxs[p], k) for p, k in conj)
                     for conj in ent.n}))
            continue
        ast = _rule_ast(rule)
        rtype = eval_type(ast, finder, DEFAULT_FUNCS)
        if rtype != V.BOOL:
            raise TypeError_(
                f"rule {rule.name}: match must be BOOL, got {rtype.name}")
        parsed.append(ast)
        try:
            mark = atoms.mark()
            mn = _decompose(ast, atoms, dnf_cap)
            per_rule.append(mn)
            if decomp_cache is not None and ckey is not None:
                used = sorted({i for conj in (mn[0] | mn[1])
                               for i, _ in conj})
                pos = {i: p for p, i in enumerate(used)}
                decomp_cache.put(ckey, DecompEntry(
                    ast=ast,
                    atom_asts=tuple(atoms.asts[i] for i in used),
                    m=tuple(tuple(sorted((pos[i], k) for i, k in conj))
                            for conj in mn[0]),
                    n=tuple(tuple(sorted((pos[i], k) for i, k in conj))
                            for conj in mn[1])))
        except HostFallback as exc:
            atoms.revert(mark)              # undo partial atom adds
            per_rule.append(None)
            oracle = _rule_oracle(rule, finder)
            host_fallback[ridx] = oracle
            fallback_reason[ridx] = str(exc)
            if decomp_cache is not None and ckey is not None:
                decomp_cache.put(ckey, DecompEntry(
                    ast=ast, oracle=oracle, reason=str(exc)))

    # Requirements for every device atom; atoms that cannot lower demote
    # every rule that references them to host fallback.
    reqs = Requirements()
    bad_atoms: set[int] = set()
    for aidx, ast in enumerate(atoms.asts):
        try:
            r = Requirements()
            collect_requirements(ast, finder, r)
        except HostFallback as exc:
            bad_atoms.add(aidx)
            continue
        reqs.merge(r)
    if bad_atoms:
        for ridx, mn in enumerate(per_rule):
            if mn is None:
                continue
            used = {i for conj in (mn[0] | mn[1]) for i, _ in conj}
            if used & bad_atoms:
                per_rule[ridx] = None
                host_fallback[ridx] = _rule_oracle(rules[ridx], finder)
                fallback_reason[ridx] = "atom not lowerable"

    manifest = {n: finder.get_attribute(n) for n in finder.names()}
    kwargs = {} if max_str_len is None else {"max_str_len": max_str_len}
    ext = dict(reqs.extern_sources)
    for n, k, east in extra_extern_sources:
        ext.setdefault((n, k), east)
    layout = build_layout(
        manifest,
        sorted(set(reqs.derived_keys) | set(extra_derived_keys)),
        sorted(set(reqs.byte_sources) | set(extra_byte_sources), key=str),
        extern_sources=[(n, k, ast) for (n, k), ast
                        in ext.items()], **kwargs)

    # ---- classify atoms into vectorizable tiers ----
    # An atom can still refuse to lower here (e.g. STRING_MAP equality
    # has no device view even though its requirements collected fine);
    # demote every rule using it to host fallback and reclassify.
    ctx = tensor_expr._Ctx(layout, interner, finder)
    while True:
        live_atoms = sorted({i for mn in per_rule if mn
                             for conj in (mn[0] | mn[1]) for i, _ in conj})
        eq_cols: list[int] = []; eq_cids: list[int] = []
        eq_neg: list[bool] = []
        eq_atom_idx: list[int] = []
        ss_a: list[int] = []; ss_b: list[int] = []; ss_neg: list[bool] = []
        ss_atom_idx: list[int] = []
        # constant-pattern regex atoms grouped by subject: one packed
        # multi-DFA scan per subject instead of one scan per atom
        # (tensor_expr.compile_dfa_group)
        dfa_groups: dict[str, dict] = {}
        gen_fns: list[Callable] = []
        gen_atom_idx: list[int] = []
        unlowerable: set[int] = set()

        for aidx in live_atoms:
            ast = atoms.asts[aidx]
            done = False
            f = ast.fn
            if ast.var is not None \
                    and finder.get_attribute(ast.var.name) == V.BOOL:
                eq_cols.append(layout.slot_of(ast.var.name))
                eq_cids.append(ID_TRUE); eq_neg.append(False)
                eq_atom_idx.append(aidx); done = True
            elif f is not None and f.name in ("EQ", "NEQ") \
                    and len(f.args) == 2:
                neg = f.name == "NEQ"
                for x, y in ((f.args[0], f.args[1]),
                             (f.args[1], f.args[0])):
                    sref = _slot_ref(x, layout, finder)
                    if sref is None:
                        continue
                    cid = _const_id(y, interner)
                    if cid is not None:
                        eq_cols.append(sref.col); eq_cids.append(cid)
                        eq_neg.append(neg); eq_atom_idx.append(aidx)
                        done = True
                        break
                if not done:
                    ra = _slot_ref(f.args[0], layout, finder)
                    rb = _slot_ref(f.args[1], layout, finder)
                    if ra is not None and rb is not None:
                        ss_a.append(ra.col); ss_b.append(rb.col)
                        ss_neg.append(neg); ss_atom_idx.append(aidx)
                        done = True
            if not done and f is not None and f.name == "matches" \
                    and f.target is not None \
                    and f.target.const_ is not None:
                try:
                    from istio_tpu.ops.regex_dfa import compile_regex
                    pattern = f.target.const_.value
                    dfa = compile_regex(pattern)
                    # probe the subject NOW so an un-viewable subject
                    # falls through to the generic path's fallback
                    tensor_expr._compile_bytes(f.args[0], ctx)
                except Exception:
                    dfa = None
                if dfa is not None:
                    g = dfa_groups.setdefault(
                        str(f.args[0]),
                        {"subject": f.args[0], "atoms": [],
                         "patterns": [], "dfas": []})
                    g["atoms"].append(aidx)
                    g["patterns"].append(pattern)
                    g["dfas"].append(dfa)
                    done = True
            if not done:
                try:
                    gen_fns.append(tensor_expr._compile_node(ast, ctx))
                except HostFallback:
                    unlowerable.add(aidx)   # keep scanning: one pass
                    continue                # collects every bad atom
                gen_atom_idx.append(aidx)

        if not unlowerable:
            break
        for ridx, mn in enumerate(per_rule):
            if mn is None:
                continue
            used = {i for conj in (mn[0] | mn[1]) for i, _ in conj}
            if used & unlowerable:
                per_rule[ridx] = None
                host_fallback[ridx] = _rule_oracle(rules[ridx], finder)
                fallback_reason[ridx] = "atom not lowerable"

    dfa_group_fns = [tensor_expr.compile_dfa_group(
        g["subject"], g["patterns"], g["dfas"], ctx)
        for g in dfa_groups.values()]
    dfa_atom_idx = [a for g in dfa_groups.values() for a in g["atoms"]]

    n_atoms = len(atoms.asts)
    ss_a_a = np.asarray(ss_a, np.int32)
    ss_b_a = np.asarray(ss_b, np.int32)
    ss_neg_a = np.asarray(ss_neg, bool)

    # ---- conjunction + rule matrices ----
    conj_list: list[Conj] = []
    conj_key: dict[Conj, int] = {}
    rule_m_cols: list[list[int]] = []
    rule_n_cols: list[list[int]] = []
    for mn in per_rule:
        if mn is None:
            rule_m_cols.append([]); rule_n_cols.append([])
            continue
        cols_mn = []
        for dnf in mn:
            cols = []
            for conj in dnf:
                j = conj_key.get(conj)
                if j is None:
                    j = len(conj_list)
                    conj_key[conj] = j
                    conj_list.append(conj)
                cols.append(j)
            cols_mn.append(cols)
        rule_m_cols.append(cols_mn[0]); rule_n_cols.append(cols_mn[1])

    n_conjs = len(conj_list)
    n_rules = len(rules)
    # rule-axis padding for even mp sharding (see docstring)
    n_rows = max(-(-max(n_rules, 1) // rule_pad) * rule_pad, 1)

    # ---- fused gather–compare fast path ----
    # Conjunctions whose EVERY literal is a tier-1 EQ/NEQ(slot, const)
    # atom skip the two-stage evaluation (atom planes → literal
    # gather): their sat column gathers the slot ids/present bits
    # DIRECTLY and compares against the interned constants in the same
    # pass — one fused gather-compare over the slot tensor instead of
    # materializing the m/n literal planes and re-gathering them.
    # Literal truth for an EQ atom: m = cmp∧present, n = ¬cmp∧present,
    # so a (atom, kind) literal is ((ids==cid) ^ neg ^ (kind=='n')) ∧
    # present, and padding lanes read True (AND identity). EQ atoms
    # dominate real istio configs, so most snapshots evaluate entirely
    # here and the legacy literal-gather stage compiles away.
    # Conjunction columns permute fused-first; the rule-stage index
    # matrices are remapped through the permutation.
    eq_info = {aidx: (eq_cols[i], eq_cids[i], eq_neg[i])
               for i, aidx in enumerate(eq_atom_idx)}
    fused_j = [j for j, conj in enumerate(conj_list)
               if all(aidx in eq_info for aidx, _ in conj)]
    fused_set = set(fused_j)
    legacy_j = [j for j in range(n_conjs) if j not in fused_set]
    n_fused = len(fused_j)
    n_legacy = n_conjs - n_fused
    new_of_old = np.zeros(max(n_conjs, 1), np.int32)
    for newj, oldj in enumerate(fused_j + legacy_j):
        new_of_old[oldj] = newj
    conj_list = [conj_list[j] for j in fused_j + legacy_j]
    rule_m_cols = [[int(new_of_old[j]) for j in cols]
                   for cols in rule_m_cols]
    rule_n_cols = [[int(new_of_old[j]) for j in cols]
                   for cols in rule_n_cols]
    # the legacy block only exists for conjunctions it still owns (or
    # as the placeholder column of an empty ruleset)
    use_legacy = n_legacy > 0 or n_fused == 0

    l_max_f = max((len(conj_list[j]) for j in range(n_fused)),
                  default=1) or 1
    l_max = max((len(conj_list[j]) for j in range(n_fused, n_conjs)),
                default=1) or 1
    k_max = max((max(len(m), len(n)) for m, n in
                 ((rule_m_cols[r], rule_n_cols[r]) for r in range(n_rules))),
                default=1) or 1

    eqc_col = np.zeros((max(n_fused, 1), l_max_f), np.int32)
    eqc_cid = np.zeros((max(n_fused, 1), l_max_f), np.int32)
    eqc_xor = np.zeros((max(n_fused, 1), l_max_f), bool)
    eqc_pad = np.ones((max(n_fused, 1), l_max_f), bool)
    for j in range(n_fused):
        for s, (aidx, kind) in enumerate(sorted(conj_list[j])):
            col, cid, neg = eq_info[aidx]
            eqc_col[j, s] = col
            eqc_cid[j, s] = cid
            eqc_xor[j, s] = bool(neg) ^ (kind == "n")
            eqc_pad[j, s] = False

    # The legacy m/n planes carry ONLY the EQ atoms some legacy
    # conjunction still references — an EQ atom every referencing
    # conjunction of which went fused would be gathered/compared into
    # lanes no lit_idx row ever reads (XLA cannot DCE them: lit_idx is
    # a traced param, not a constant). ss/dfa/gen atoms are legacy by
    # construction (any conjunction holding one is non-fusable).
    legacy_atom_set = {aidx for conj in conj_list[n_fused:]
                       for aidx, _ in conj}
    eq_keep = [i for i, aidx in enumerate(eq_atom_idx)
               if aidx in legacy_atom_set]
    eq_live_idx = [eq_atom_idx[i] for i in eq_keep]
    order = eq_live_idx + ss_atom_idx + dfa_atom_idx + gen_atom_idx
    n_live = max(len(order), 1)   # width of the m/n literal blocks
    # inverse permutation: position of atom i in the concatenated output
    pos_of = np.full(max(n_atoms, 1), 0, dtype=np.int32)
    for pos, aidx in enumerate(order):
        pos_of[aidx] = pos
    eq_cols_a = np.asarray([eq_cols[i] for i in eq_keep], np.int32)
    eq_cids_a = np.asarray([eq_cids[i] for i in eq_keep], np.int32)
    eq_neg_a = np.asarray([eq_neg[i] for i in eq_keep], bool)

    # Sparse (gather) formulation. Conjunctions average only a few
    # literals and rules a few conjunctions, so dense [2A, n_conj] /
    # [n_conj, R] one-hot matmuls waste ~1000× the FLOPs (measured
    # 23ms/step at 10k rules on v5e); padded index gathers + AND/OR
    # reductions are pure HBM-bandwidth ops (<2ms). Sentinel columns:
    # literal index 2·n_live is always-TRUE (AND identity), conjunction
    # index n_conjs is always-FALSE (OR identity).
    LIT_TRUE = 2 * n_live
    CONJ_FALSE = max(n_conjs, 1)   # sat has max(n_conjs,1) real columns
    CONJ_TRUE = CONJ_FALSE + 1     # pad rows: definitely-not-matched
    # legacy literal gather rows: only the conjunctions the fused
    # gather-compare path above did NOT absorb (an all-EQ snapshot
    # compiles no literal gather at all)
    lit_idx = np.full((max(n_legacy, 1), l_max), LIT_TRUE, np.int32)
    for jj, conj in enumerate(conj_list[n_fused:]):
        for s, (aidx, kind) in enumerate(sorted(conj)):
            lit_idx[jj, s] = pos_of[aidx] + (0 if kind == "m" else n_live)
    conj_m_idx = np.full((n_rows, k_max), CONJ_FALSE, np.int32)
    conj_n_idx = np.full((n_rows, k_max), CONJ_FALSE, np.int32)
    # padding rows read not_matched=True (never "err"): their N gather
    # points at the always-TRUE sentinel column
    conj_n_idx[n_rules:, 0] = CONJ_TRUE
    for ridx in range(n_rules):
        for s, j in enumerate(rule_m_cols[ridx]):
            conj_m_idx[ridx, s] = j
        for s, j in enumerate(rule_n_cols[ridx]):
            conj_n_idx[ridx, s] = j

    # Index tensors are ARGUMENTS, not closure constants: 10k-rule
    # snapshots would otherwise embed MBs of literals in the HLO (the
    # serialized program must stay small for remote compilation).
    params = {"lit_idx": jnp.asarray(lit_idx),
              "conj_m_idx": jnp.asarray(conj_m_idx),
              "conj_n_idx": jnp.asarray(conj_n_idx),
              "eqc_col": jnp.asarray(eqc_col),
              "eqc_cid": jnp.asarray(eqc_cid),
              "eqc_xor": jnp.asarray(eqc_xor),
              "eqc_pad": jnp.asarray(eqc_pad)}

    def run(params: Mapping[str, Any],
            batch: AttributeBatch) -> tuple[Any, Any, Any]:
        b = batch.ids.shape[0]
        sat_parts = []
        if n_fused:
            # fused gather-compare: one pass over the slot tensor
            # computes every all-EQ conjunction's sat bit — no literal
            # planes, no second gather
            iv = batch.ids[:, params["eqc_col"]]        # [B, F, Lf]
            pv = batch.present[:, params["eqc_col"]]
            hit = ((iv == params["eqc_cid"][None]) ^
                   params["eqc_xor"][None]) & pv
            sat_parts.append(jnp.all(hit | params["eqc_pad"][None],
                                     axis=2))
        if use_legacy:
            parts_m, parts_n = [], []
            if eq_cols_a.size:
                ids = batch.ids[:, eq_cols_a]
                pres = batch.present[:, eq_cols_a]
                cmp = (ids == eq_cids_a[None, :]) ^ eq_neg_a[None, :]
                parts_m.append(cmp & pres)
                parts_n.append(~cmp & pres)
            if ss_a_a.size:
                pres = batch.present[:, ss_a_a] & batch.present[:, ss_b_a]
                cmp = (batch.ids[:, ss_a_a] == batch.ids[:, ss_b_a]) \
                    ^ ss_neg_a[None, :]
                parts_m.append(cmp & pres)
                parts_n.append(~cmp & pres)
            for gfn in dfa_group_fns:
                gval, gee = gfn(batch)
                parts_m.append(gval)           # already masked by ~ee
                parts_n.append(~gval & ~gee)
            for fn in gen_fns:
                t = fn(batch)
                ee = t.err | ~t.ok
                parts_m.append((t.val & ~ee)[:, None])
                parts_n.append((~t.val & ~ee)[:, None])
            if parts_m:
                m_all = jnp.concatenate(parts_m, axis=1)
                n_all = jnp.concatenate(parts_n, axis=1)
            else:
                m_all = jnp.zeros((b, 1), bool)
                n_all = jnp.zeros((b, 1), bool)
            # lit[:, LIT_TRUE] is the AND-identity sentinel
            lit = jnp.concatenate(
                [m_all, n_all, jnp.ones((b, 1), bool)], axis=1)
            sat_parts.append(
                jnp.all(lit[:, params["lit_idx"]], axis=2))
        sat = sat_parts[0] if len(sat_parts) == 1 \
            else jnp.concatenate(sat_parts, axis=1)   # [B, n_conjs]
        # sat[:, CONJ_FALSE] is the OR-identity sentinel;
        # sat[:, CONJ_TRUE] the always-true column rule-axis padding
        # points its N gather at
        sat_ext = jnp.concatenate(
            [sat, jnp.zeros((b, 1), bool), jnp.ones((b, 1), bool)],
            axis=1)
        matched = jnp.any(sat_ext[:, params["conj_m_idx"]], axis=2)
        not_matched = jnp.any(sat_ext[:, params["conj_n_idx"]], axis=2)
        # empty-M rules (incl. host fallback): matched stays False; the
        # err bit below correctly reads True only for device rules whose
        # DNF pair is inconclusive on this input.
        err = ~matched & ~not_matched
        return matched, not_matched, err

    # ---- per-rule attribute bitmaps (compile-time ReferencedAttributes) ----
    attr_mask = np.zeros((n_rows, max(layout.n_columns, 1)), bool)
    attr_names: list[set] = []
    for ridx in range(n_rules):
        names: set = set()
        _collect_attr_names(parsed[ridx], finder, names)
        attr_names.append(names)
        for item in names:
            if isinstance(item, tuple):
                if item in layout.derived_slots:
                    attr_mask[ridx, layout.derived_slots[item]] = True
            elif item in layout.slots:
                attr_mask[ridx, layout.slots[item]] = True

    ns_ids: dict[str, int] = {"": 0}
    # pad rows carry an unmatchable namespace (ids are ≥ 0, unknown
    # request namespaces are -1) so they are invisible everywhere
    rule_ns = np.full(n_rows, -7, np.int32)
    if n_rules == 0:
        rule_ns[:] = 0   # placeholder row of an empty ruleset
    for ridx, rule in enumerate(rules):
        ns = rule.namespace
        if ns not in ns_ids:
            ns_ids[ns] = len(ns_ids)
        rule_ns[ridx] = ns_ids[ns]

    atom_tier = {aidx: "id-eq" for aidx in eq_atom_idx}
    atom_tier.update({aidx: "slot-eq" for aidx in ss_atom_idx})
    atom_tier.update({aidx: "dfa-pack" for aidx in dfa_atom_idx})
    atom_tier.update({aidx: "tensor" for aidx in gen_atom_idx})

    geometry = {
        # EQ atoms the LEGACY stage materializes planes for (fused-only
        # EQ atoms are excluded above) — the roofline model sizes the
        # legacy stage from this; the total is n_eq_atoms_total
        "n_eq_atoms": len(eq_keep),
        "n_eq_atoms_total": len(eq_atom_idx),
        "n_ss_atoms": len(ss_atom_idx),
        "n_dfa_atoms": len(dfa_atom_idx),
        "n_gen_atoms": len(gen_atom_idx),
        "n_dfa_groups": len(dfa_group_fns),
        "n_live": n_live,
        "n_conjs": n_conjs,
        "n_fused_conjs": n_fused,
        "n_legacy_conjs": n_legacy,
        "use_legacy": use_legacy,
        "l_max_fused": int(eqc_col.shape[1]) if n_fused else 0,
        "l_max_legacy": int(lit_idx.shape[1]) if use_legacy else 0,
        "k_max": k_max,
        "n_rows": n_rows,
    }

    return RuleSetProgram(
        rules=list(rules), layout=layout, interner=interner,
        fn=jax.jit(run) if jit else run, params=params,
        n_atoms=n_atoms, n_conjs=n_conjs,
        host_fallback=host_fallback, fallback_reason=fallback_reason,
        attr_mask=attr_mask, attr_names=attr_names,
        rule_ns=rule_ns, ns_ids=ns_ids,
        atom_asts=list(atoms.asts), atom_tier=atom_tier,
        per_rule_dnf=list(per_rule), geometry=geometry)


def _collect_attr_names(e: Expression, finder: AttributeDescriptorFinder,
                        out: set) -> None:
    if e.var is not None:
        out.add(e.var.name)
        return
    f = e.fn
    if f is None:
        return
    if (f.name == "INDEX" and f.args[0].var is not None
            and f.args[1].const_ is not None):
        out.add(f.args[0].var.name)
        out.add((f.args[0].var.name, f.args[1].const_.value))
        return
    if f.target is not None:
        _collect_attr_names(f.target, finder, out)
    for a in f.args:
        _collect_attr_names(a, finder, out)
