"""Roofline accounting for the fused Check() device step.

Every perf claim before this layer was relative to the 2018 Go
interpreter ("N× baseline"); nothing said how far the device step sits
from what the chip can actually do — the discipline the reference's
own perf doctrine demands (DEV-PERF.md: name the binding resource,
then spend the headroom). This module derives per-step BYTES TOUCHED
and OP COUNTS from the compiled program's OWN shapes — the ruleset's
index-tensor params (`RuleSetProgram.params` + `.geometry`), the
engine's action/bank tensors (`PolicyEngine.geometry`), and the batch
layout — never from hand constants, then judges a measured step time
against platform peaks:

    hbm_s  = bytes / HBM_peak      mxu_s = mxu_ops / MXU_peak
    roof_s = max(hbm_s, mxu_s)     fraction_of_roof = roof_s / measured
    bound  = hbm | mxu  (whichever model time is larger)
           | host       (fraction < HOST_BOUND_FRACTION: the measured
                         wall is dominated by dispatch/transport/host
                         work the device model cannot see)

Two components are EXACT by construction and pinned by the smoke gate
(scripts/roofline_smoke.py): `h2d_batch` equals the tensorized
AttributeBatch's summed nbytes, and `d2h_packed` equals the packed
pull's nbytes. Index/bank/mask component bytes read the live device
arrays' nbytes. Intermediate-plane traffic (literal gathers, verdict
folds) is a documented first-order model: each plane counted once per
read/write at its dtype width, no cache modeling — good enough to name
the binding resource, which is the job.

Consumers: bench.py (per-section `*_fraction_of_roof` / `*_bound`
fields for the headline, capacity, rbac and full_mesh sections) and
the introspect server's /debug/roofline view.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

# ---------------------------------------------------------------------------
# platform peaks
# ---------------------------------------------------------------------------

# TPU v5e single-chip peaks: 819 GB/s HBM2E bandwidth, 394.7 int8
# TOPS / 197 bf16 TFLOPS on the MXU (public v5e spec). The one-hot /
# int8 formulations used here are judged against the int8 rate.
V5E_PEAKS = {"hbm_gbps": 819.0, "mxu_tops": 394.7,
             "label": "tpu-v5e (HBM2E 819 GB/s, int8 394.7 TOPS)"}
# nominal single-socket CPU reference for CI-smoke runs: the absolute
# fractions are not the point off-TPU — the smoke gate checks model
# consistency and key presence, not silicon efficiency.
CPU_PEAKS = {"hbm_gbps": 25.0, "mxu_tops": 0.25,
             "label": "cpu (nominal 25 GB/s, 0.25 int8 TOPS)"}

# below this fraction of roof the measured wall is dominated by
# something the device-work model cannot see (dispatch latency, the
# transport, host python) — name it honestly instead of pretending
# the chip is 2% efficient
HOST_BOUND_FRACTION = 0.02


def peaks_for(platform: str) -> dict:
    return V5E_PEAKS if platform == "tpu" else CPU_PEAKS


# ---------------------------------------------------------------------------
# model
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class Component:
    """One stage's per-step cost, derived from compiled shapes.

    bytes   — HBM bytes touched (reads + writes, each plane once)
    vec_ops — elementwise lane ops (VPU): compares, masks, selects
    mxu_ops — matmul multiply-accumulates ×2 (the MXU's unit)
    """
    name: str
    bytes: int
    vec_ops: int = 0
    mxu_ops: int = 0


@dataclasses.dataclass
class StepModel:
    """Per-step cost model for one compiled engine at one batch size."""
    batch: int
    components: tuple
    notes: tuple = ()

    @property
    def bytes_per_step(self) -> int:
        return int(sum(c.bytes for c in self.components))

    @property
    def vec_ops_per_step(self) -> int:
        return int(sum(c.vec_ops for c in self.components))

    @property
    def mxu_ops_per_step(self) -> int:
        return int(sum(c.mxu_ops for c in self.components))

    def component(self, name: str) -> Component | None:
        for c in self.components:
            if c.name == name:
                return c
        return None

    def asdict(self) -> dict:
        return {
            "batch": self.batch,
            "bytes_per_step": self.bytes_per_step,
            "vec_ops_per_step": self.vec_ops_per_step,
            "mxu_ops_per_step": self.mxu_ops_per_step,
            "components": {c.name: {"bytes": c.bytes,
                                    "vec_ops": c.vec_ops,
                                    "mxu_ops": c.mxu_ops}
                           for c in self.components},
            "notes": list(self.notes),
        }

    def report(self, measured_step_s: float,
               peaks: dict | None = None) -> dict:
        """Judge a measured step wall against the platform roof."""
        if peaks is None:
            import jax
            peaks = peaks_for(jax.devices()[0].platform)
        measured = max(float(measured_step_s), 1e-9)
        hbm_s = self.bytes_per_step / (peaks["hbm_gbps"] * 1e9)
        mxu_s = self.mxu_ops_per_step / (peaks["mxu_tops"] * 1e12)
        roof_s = max(hbm_s, mxu_s, 1e-12)
        fraction = roof_s / measured
        bound = "hbm" if hbm_s >= mxu_s else "mxu"
        if fraction < HOST_BOUND_FRACTION:
            bound = "host"
        out = {
            "bytes_per_step": self.bytes_per_step,
            "mxu_ops_per_step": self.mxu_ops_per_step,
            "vec_ops_per_step": self.vec_ops_per_step,
            "achieved_gbps": round(
                self.bytes_per_step / measured / 1e9, 3),
            "achieved_tops": round(
                self.mxu_ops_per_step / measured / 1e12, 4),
            "roof_step_ms": round(roof_s * 1e3, 4),
            "fraction_of_roof": round(min(fraction, 1.0), 4),
            "bound": bound,
            "roof_platform": peaks["label"],
        }
        if fraction > 1.0:
            # a raw ratio above 1 means the model claims more device
            # work than the measured wall could have done — a model
            # bug, not a perfect chip. Surface it instead of letting
            # the clamp report an indistinguishable 1.0.
            out["fraction_of_roof_raw"] = round(fraction, 4)
            out["model_exceeds_roof"] = True
        return out


def batch_plane_bytes(layout, batch: int,
                      str_len: int | None = None) -> int:
    """EXACT nbytes of an AttributeBatch at this layout — mirrors the
    tensorizer's allocations field by field (incl. the max(·,1)
    placeholder planes and the always-present hash plane). The smoke
    gate pins this against a real tensorized batch's summed nbytes."""
    c = layout.n_columns
    m = max(layout.n_maps, 1)
    s = max(layout.n_byte_slots, 1)
    length = layout.max_str_len if str_len is None else str_len
    return int(batch * (c * 4      # ids int32
                        + c        # present bool
                        + m        # map_present bool
                        + s * length   # str_bytes uint8
                        + s * 4    # str_lens int32
                        + c * 4))  # hash_ids int32


def _param_nbytes(params: Any, key: str) -> int:
    a = params.get(key)
    return 0 if a is None else int(np.asarray(a).nbytes)


def model_check_step(engine, batch: int, plan: Any = None,
                     str_len: int | None = None) -> StepModel:
    """Build the per-step cost model for a compiled PolicyEngine at
    batch size `batch`. `plan` (a runtime FusedPlan) additionally
    models the packed-pull packer + D2H rows — bench's raw-step
    sections pass None. `str_len`: byte-plane width actually served
    (a narrowed latency tier); None = layout.max_str_len."""
    rs = engine.ruleset
    lay = rs.layout
    g = dict(rs.geometry)
    eg = dict(getattr(engine, "geometry", {}))
    b = batch
    R = int(eg.get("n_rows", rs.rule_ns.shape[0]))
    length = lay.max_str_len if str_len is None else str_len
    comps: list[Component] = []
    notes: list[str] = []

    # --- H2D: the request planes the step reads ---
    comps.append(Component("h2d_batch",
                           bytes=batch_plane_bytes(lay, b, length)))

    # --- atom eval + conjunction sat ---
    n_fused = int(g.get("n_fused_conjs", 0))
    l_f = int(g.get("l_max_fused", 0))
    if n_fused:
        idx_bytes = sum(_param_nbytes(rs.params, k) for k in
                        ("eqc_col", "eqc_cid", "eqc_xor", "eqc_pad"))
        comps.append(Component(
            "match_fused_eq",
            # index tensors + gathered ids/present lanes + sat write
            bytes=idx_bytes + b * n_fused * l_f * (4 + 1) + b * n_fused,
            vec_ops=b * n_fused * l_f * 3))
    if g.get("use_legacy", True):
        n_eq = int(g.get("n_eq_atoms", 0))
        n_ss = int(g.get("n_ss_atoms", 0))
        n_live = int(g.get("n_live", 1))
        n_legacy = max(int(g.get("n_legacy_conjs", 0)), 1)
        l_l = max(int(g.get("l_max_legacy", 1)), 1)
        comps.append(Component(
            "match_atoms_legacy",
            bytes=b * n_eq * (4 + 1 + 2) + b * n_ss * (8 + 2 + 2)
            + 2 * b * n_live,          # m/n plane write + lit read
            vec_ops=b * (n_eq * 2 + n_ss * 3)))
        comps.append(Component(
            "match_conj_legacy",
            bytes=_param_nbytes(rs.params, "lit_idx")
            + b * n_legacy * l_l + b * n_legacy,
            vec_ops=b * n_legacy * l_l))
        if g.get("n_dfa_atoms", 0) or g.get("n_gen_atoms", 0):
            notes.append(
                f"{g.get('n_dfa_atoms', 0)} dfa-group + "
                f"{g.get('n_gen_atoms', 0)} generic tensor atoms are "
                "not sized (compiled closures); model understates")

    # --- rule-stage gathers ---
    k_max = max(int(g.get("k_max", 1)), 1)
    comps.append(Component(
        "match_rules",
        bytes=_param_nbytes(rs.params, "conj_m_idx")
        + _param_nbytes(rs.params, "conj_n_idx")
        + 2 * b * R * k_max          # gathered sat lanes (m + n)
        + 3 * b * R,                 # matched/not_matched/err writes
        vec_ops=2 * b * R * k_max + b * R))

    # --- namespace mask + active plane ---
    comps.append(Component(
        "ns_mask",
        bytes=R * 4 + b * 4 + 2 * b * R,   # rule_ns + req_ns + masks
        vec_ops=3 * b * R))

    # --- verdict fold (deny keys, min/argmin reductions, TTLs) ---
    comps.append(Component(
        "verdict_fold",
        bytes=int(eg.get("deny_bytes", 0)) + b * R * (1 + 4)
        + b * 4 * 4,                       # per-request outputs
        vec_ops=b * R * 6))

    # --- list membership ---
    n_lists = int(eg.get("n_lists", 0))
    if n_lists:
        e_max = int(eg.get("list_max_entries", 1))
        comps.append(Component(
            "list_scan",
            bytes=int(eg.get("list_table_bytes", 0))
            + b * n_lists * (4 + 1) + b * n_lists,
            vec_ops=b * n_lists * e_max))
        for i, bank in enumerate(eg.get("rx_banks", ())):
            kind = bank.get("kind")
            n_cls = int(bank.get("n_cls", 1) or 1)
            if kind == "dense":
                s = int(bank["s_tot"])
                per_mxu = 2 * b * (256 * n_cls + s * n_cls * s)
                per_bytes = s * n_cls * s * 2 + b * s * n_cls * 2 \
                    + b * s * 2
            elif kind == "blocked":
                s = int(bank["s_max"])
                n_p = int(bank["n_pats"])
                per_mxu = 2 * b * (256 * n_cls + n_p * s * n_cls * s)
                per_bytes = n_p * s * n_cls * s * 2 \
                    + b * n_p * s * n_cls * 2 + b * n_p * s * 2
            else:   # flat gather scan
                s = int(bank.get("s_max", 1))
                n_p = int(bank.get("n_pats", 1))
                per_mxu = 0
                per_bytes = b * n_p * 8
            comps.append(Component(
                f"dfa_bank_{i}",
                # packed bit lanes read once + per-byte-step traffic
                # over the scan length (worst case: the byte plane
                # width; the while_loop stops at the batch's longest
                # string)
                bytes=int(bank.get("step_bytes", 0))
                + int(bank.get("m_bytes", 0)) + length * per_bytes,
                mxu_ops=length * per_mxu,
                vec_ops=length * b * 256))
        if eg.get("cidr_entries", 0):
            n_e = int(eg["cidr_entries"])
            comps.append(Component(
                "cidr_scan",
                bytes=int(eg.get("cidr_bytes", 0)) + b * n_e * 16,
                vec_ops=b * n_e * 16 * 2))

    # --- rbac pseudo-rule fold ---
    if eg.get("n_rbac", 0):
        n_rb = int(eg["n_rbac"])
        k_a = int(eg.get("rbac_k_allow", 1))
        comps.append(Component(
            "rbac_fold",
            bytes=n_rb * (k_a + 2) * 4 + b * n_rb * (k_a + 2),
            vec_ops=b * n_rb * (k_a + 4)))

    # --- device quota alloc ---
    if eg.get("n_quotas", 0):
        n_q = int(eg["n_quotas"])
        counts_bytes = n_q * int(eg.get("quota_buckets", 1)) * 4
        comps.append(Component(
            "quota_alloc",
            bytes=2 * counts_bytes + b * n_q * (4 + 4 + 1 + 4),
            vec_ops=b * n_q * 12))
        notes.append("quota rank kernel (sort / pairwise tier) not "
                     "sized; model understates at high quota counts")

    # --- referenced-attr bitmap (bit-packed mask, int8 matmul) ---
    n_cols = int(eg.get("n_attr_cols", max(lay.n_columns, 1)))
    comps.append(Component(
        "referenced",
        bytes=int(eg.get("attr_mask_bits_bytes", 0)) + R * n_cols
        + b * R + b * n_cols * 4,
        mxu_ops=2 * b * R * n_cols))

    # --- packer + D2H (serving path only) ---
    if plan is not None:
        n_items = len(plan.item_names)
        w = plan.n_ref_words
        n_ov = int(len(plan.overlay_cols))
        ov_w = plan.n_overlay_words
        if n_items:
            inst_bits = (n_items + 31) // 32 * 4 * R
            comps.append(Component(
                "packer_masks",
                bytes=2 * inst_bits + 2 * b * R + b * n_items,
                mxu_ops=2 * b * R * n_items
                + 2 * b * R * int(plan.pred_map_mask.shape[1])))
        rows = 5 + w + ov_w
        comps.append(Component(
            "pack_bits",
            bytes=b * (w + ov_w) * 32 + b * rows * 4,
            vec_ops=b * (w + ov_w) * 32 * 2))
        comps.append(Component("d2h_packed", bytes=rows * b * 4))
        if n_ov:
            comps.append(Component(
                "overlay_gather", bytes=b * n_ov + n_ov * 8,
                vec_ops=b * n_ov))

    return StepModel(batch=b, components=tuple(comps),
                     notes=tuple(notes))


def packed_pull_rows(plan) -> int:
    """Row count of FusedPlan.packed_check's pull — the d2h_packed
    component models rows*B*4 bytes; the smoke gate pins it against a
    real pull's nbytes."""
    return 5 + plan.n_ref_words + plan.n_overlay_words


def latency_floor(engine, batch: int, plan: Any = None, *,
                  frame_ms: float = 0.05,
                  pcie_gbps: float = 12.0,
                  dispatch_ms: float = 0.05,
                  str_len: int | None = None,
                  peaks: dict | None = None) -> dict:
    """The IRREDUCIBLE wire-to-verdict latency floor for one
    latency-tier batch — what remains when every software overhead is
    gone, so a measured p99 can be judged as "X ms above physics"
    instead of against an aspiration:

        frame — per-request wire framing cost (caller supplies the
                measured echo-server per-request wall; the default is
                a placeholder)
        h2d   — the batch's EXACT plane bytes over the host↔device
                link (PCIe model; a colocated chip pays this, the
                tunnel pays ~100ms more) + one dispatch overhead
        step  — the compiled step's roofline time: max(bytes/HBM_peak,
                mxu_ops/MXU_peak) from the program's own shapes
        d2h   — the packed pull's exact bytes back + one dispatch

    Everything above this floor is queueing, batching policy, python,
    or response build — attackable; the floor itself moves only with
    hardware or a smaller compiled program."""
    if peaks is None:
        import jax
        peaks = peaks_for(jax.devices()[0].platform)
    model = model_check_step(engine, batch, plan=plan,
                             str_len=str_len)
    h2d_bytes = batch_plane_bytes(engine.ruleset.layout, batch,
                                  str_len=str_len)
    h2d_ms = h2d_bytes / (pcie_gbps * 1e9) * 1e3 + dispatch_ms
    step_ms = max(model.bytes_per_step / (peaks["hbm_gbps"] * 1e9),
                  model.mxu_ops_per_step
                  / (peaks["mxu_tops"] * 1e12)) * 1e3
    d2h = model.component("d2h_packed")
    d2h_bytes = d2h.bytes if d2h is not None else batch * 4
    d2h_ms = d2h_bytes / (pcie_gbps * 1e9) * 1e3 + dispatch_ms
    floor = frame_ms + h2d_ms + step_ms + d2h_ms
    return {
        "floor_ms": round(floor, 4),
        "breakdown": {
            "frame_ms": round(frame_ms, 4),
            "h2d_ms": round(h2d_ms, 4),
            "device_step_ms": round(step_ms, 4),
            "d2h_ms": round(d2h_ms, 4),
        },
        "batch": batch,
        "h2d_bytes": int(h2d_bytes),
        "d2h_bytes": int(d2h_bytes),
        "pcie_gbps": pcie_gbps,
        "roof_platform": peaks["label"],
        "derivation": (
            "frame (measured echo per-request wire cost) + h2d "
            "(exact batch plane bytes / PCIe + dispatch) + device "
            "step (compiled-shape roofline: max(bytes/HBM, ops/MXU)) "
            "+ d2h (exact packed-pull bytes / PCIe + dispatch) — "
            "the irreducible floor; measured p99 minus this is the "
            "attackable software gap"),
    }


def bench_fields(engine, batch: int, step_s: float, prefix: str,
                 plan: Any = None,
                 str_len: int | None = None) -> dict:
    """BENCH-artifact fields for one perf section: the model summary +
    the measured step judged against the platform roof. Fail-soft by
    contract — a modeling error must never take a section's measured
    numbers down."""
    try:
        model = model_check_step(engine, batch, plan=plan,
                                 str_len=str_len)
        rep = model.report(step_s)
        out = {prefix + k: v for k, v in rep.items()}
        if model.notes:
            out[prefix + "roof_notes"] = list(model.notes)
        return out
    except Exception as exc:
        return {prefix + "roofline_error":
                f"{type(exc).__name__}: {exc}"}
