"""Compiled-ruleset debugging: disassembler + single-bag stepper.

The il/text + interpreter/Stepper role (mixer/pkg/il/text/write.go,
il/interpreter/stepper.go:1-152): at 10k rules nobody can reason about
a compiled snapshot from its index tensors, so `disassemble` renders
the retained source structure — the deduplicated atom table with each
atom's lowering tier, every rule's match/not-match DNFs over those
atoms, host-fallback reasons, namespaces, and referenced-attribute
bitmaps — and `Stepper` replays ONE attribute bag through the same
decomposition on the host oracle, showing exactly which atoms fired,
which conjunctions satisfied, and why each rule matched or not.
"""
from __future__ import annotations

from typing import Any

from istio_tpu.attribute.bag import Bag
from istio_tpu.expr.oracle import EvalError, OracleProgram
from istio_tpu.compiler.ruleset import RuleSetProgram


def _dnf_str(dnf, kind: str) -> str:
    """{frozenset((atom,'m'|'n'))...} → '(a0 ∧ ¬a2) ∨ (a3)'."""
    if not dnf:
        return "⊥"
    parts = []
    for conj in sorted(dnf, key=lambda c: sorted(c)):
        lits = [("¬" if k == "n" else "") + f"a{i}"
                for i, k in sorted(conj)]
        parts.append("(" + " ∧ ".join(lits) + ")" if lits else "(⊤)")
    return " ∨ ".join(parts)


def disassemble(prog: RuleSetProgram) -> str:
    """Human-readable dump of a compiled ruleset."""
    lay = prog.layout
    lines = [
        f"ruleset: {prog.n_rules} rules, {prog.n_atoms} atoms, "
        f"{prog.n_conjs} conjunctions, {len(prog.ns_ids)} namespaces, "
        f"{len(prog.host_fallback)} host-fallback",
        f"layout: {len(lay.slots)} scalar + {len(lay.derived_slots)} "
        f"derived columns, {lay.n_maps} maps, {lay.n_byte_slots} byte "
        f"slots (max_str_len={lay.max_str_len})",
        "",
        "atoms:",
    ]
    for aidx, ast in enumerate(prog.atom_asts):
        tier = prog.atom_tier.get(aidx, "dead")
        lines.append(f"  a{aidx}: {ast}   [{tier}]")
    lines.append("")
    lines.append("rules:")
    ns_by_id = {v: k for k, v in prog.ns_ids.items()}
    for ridx, rule in enumerate(prog.rules):
        ns = ns_by_id.get(int(prog.rule_ns[ridx]), "?") or "<default>"
        lines.append(f"  r{ridx} {rule.name}  ns={ns}")
        lines.append(f"      match: {rule.match.strip() or 'true'}")
        if ridx in prog.host_fallback:
            lines.append(f"      HOST FALLBACK: "
                         f"{prog.fallback_reason.get(ridx, '?')}")
        else:
            mn = prog.per_rule_dnf[ridx]
            if mn is not None:
                lines.append(f"      M: {_dnf_str(mn[0], 'm')}")
                lines.append(f"      N: {_dnf_str(mn[1], 'n')}")
        refs = sorted(prog.attr_names[ridx], key=str)
        if refs:
            shown = ", ".join(
                f"{m}[{k}]" if isinstance(r, tuple) else str(r)
                for r in refs
                for m, k in [(r if isinstance(r, tuple) else (r, ""))])
            lines.append(f"      refs: {shown}")
    return "\n".join(lines) + "\n"


class Stepper:
    """Step one bag through the compiled decomposition on the host
    oracle (stepper.go's instruction-level trace, at atom granularity —
    the tensor program has no instructions, atoms are its opcodes)."""

    def __init__(self, prog: RuleSetProgram, finder):
        self.prog = prog
        self.finder = finder
        self._atom_progs = [OracleProgram.from_ast(ast, finder)
                            for ast in prog.atom_asts]

    def eval_atom(self, aidx: int, bag: Bag) -> tuple[Any, str | None]:
        try:
            return self._atom_progs[aidx].evaluate(bag), None
        except EvalError as exc:
            return None, str(exc)

    def explain(self, bag: Bag, rule: int | None = None) -> str:
        """Trace: atom values → conjunction sat → rule verdicts."""
        prog = self.prog
        rule_idxs = [rule] if rule is not None else range(prog.n_rules)
        used: set[int] = set()
        for ridx in rule_idxs:
            mn = prog.per_rule_dnf[ridx] \
                if ridx not in prog.host_fallback else None
            if mn is not None:
                for dnf in mn:
                    for conj in dnf:
                        used |= {i for i, _ in conj}
        lines = ["atoms:"]
        results: dict[int, tuple[Any, str | None]] = {}
        for aidx in sorted(used):
            value, err = self.eval_atom(aidx, bag)
            results[aidx] = (value, err)
            shown = f"ERROR: {err}" if err is not None else repr(value)
            lines.append(f"  a{aidx} = {shown}    "
                         f"# {prog.atom_asts[aidx]}")
        lines.append("rules:")
        for ridx in rule_idxs:
            name = prog.rules[ridx].name
            if ridx in prog.host_fallback:
                m, _, e = prog.host_eval(ridx, bag)
                verdict = "ERROR" if e else ("MATCH" if m else "NO MATCH")
                lines.append(f"  r{ridx} {name}: {verdict} "
                             f"(host oracle: "
                             f"{prog.fallback_reason.get(ridx, '?')})")
                continue
            mn = prog.per_rule_dnf[ridx]
            m_sat = self._dnf_sat(mn[0], results)
            n_sat = self._dnf_sat(mn[1], results)
            if m_sat is not None:
                lines.append(f"  r{ridx} {name}: MATCH via {m_sat}")
            elif n_sat is not None:
                lines.append(f"  r{ridx} {name}: NO MATCH via {n_sat}")
            else:
                lines.append(f"  r{ridx} {name}: ERROR "
                             f"(neither DNF conclusive — an operand "
                             f"errored or was absent)")
        return "\n".join(lines) + "\n"

    @staticmethod
    def _dnf_sat(dnf, results) -> str | None:
        """First satisfied conjunction's rendering, or None."""
        for conj in sorted(dnf, key=lambda c: sorted(c)):
            ok = True
            for aidx, kind in sorted(conj):
                value, err = results[aidx]
                if err is not None or value is None:
                    ok = False
                    break
                if kind == "m" and not value:
                    ok = False
                    break
                if kind == "n" and value:
                    ok = False
                    break
            if ok:
                lits = [("¬" if k == "n" else "") + f"a{i}"
                        for i, k in sorted(conj)] or ["⊤"]
                return "(" + " ∧ ".join(lits) + ")"
        return None
