"""RBAC → device ruleset lowering (the fused NFA authz showcase).

Reference semantics: mixer/adapter/rbac/rbac.go:181 HandleAuthorization —
a request is ALLOWED iff some ServiceRoleBinding in the action's
namespace binds the request's subject to a ServiceRole with an access
rule matching the action; otherwise "RBAC: permission denied". Every
comparison is stringMatch (rbac.go: exact, `*`, prefix `ab*`, suffix
`*ab`) over values the authorization-template instance computed from
attributes.

Instead of running that nested host loop per request, the policy is
compiled into the SAME monotone-DNF ruleset machinery that matches rule
predicates (compiler/ruleset.py): each (binding, subject, role-rule)
triple becomes one PSEUDO-RULE whose match expression is the
conjunction of its subject/action clauses — built by substituting the
instance's field expressions into the pattern atoms:

    user == "alice"            →  EQ(<subject.user expr>, "alice")
    services: ["*.prod.svc"]   →  endsWith(<action.service expr>, ...)
    constraint k in {v1, v2}   →  LOR(EQ(props[k], v1), EQ(props[k], v2))

A request is allowed iff ANY pseudo-rule matches — a row-wise OR the
PolicyEngine evaluates with one gather (models/policy_engine.RbacSpec).
This is the TPU-shaped formulation: 1k role rules are 1k extra ROWS in
the one batched match program, not 1k host loop iterations per request.

Host/device parity for evaluation errors: the host path builds the
whole instance first and any field-expression error (missing attribute
without `|` fallback) aborts the action with INTERNAL
(runtime/dispatcher.py _safe_check). The lowering therefore also emits
one GUARD pseudo-rule per instance — the conjunction of EQ(e, e) for
every field expression e, which is definitely-true iff every field
evaluates and inconclusive iff any errors — and the engine maps
guard-not-matched to INTERNAL, exactly mirroring the host path.

Host-oracle conformance: adapters/rbac.py remains the semantics oracle;
tests/test_rbac_lower.py checks device == host verdict over a
property-rich corpus. Constructs outside the lowerable subset (non-
string property expressions, patterns against empty-string sentinel
semantics the host computes differently) raise RbacLowerError and the
whole action stays on the host overlay — never a silent divergence.
"""
from __future__ import annotations

import dataclasses
import json
from typing import Any, Mapping, Sequence

from istio_tpu.attribute.types import ValueType
from istio_tpu.expr.checker import (AttributeDescriptorFinder,
                                    DEFAULT_FUNCS, eval_type)
from istio_tpu.expr.exprs import Expression, const_expr, fn_expr

V = ValueType


class RbacLowerError(ValueError):
    """Policy/instance shape the device lowering does not cover —
    callers keep the action on the host adapter."""


@dataclasses.dataclass
class LoweredRbac:
    """Synthesized predicates for one (policy set, instance) pair."""
    allow_asts: list[Expression]     # pseudo-rule per (binding,subject,rule)
    guard_ast: Expression | None     # None: instance has no expressions
    n_triples: int                   # diagnostics: triples considered


# --- tiny AST builders (constant-folded where the value is known) ----

_TRUE = object()    # sentinel: clause statically true → drop from AND
_FALSE = object()   # sentinel: clause statically false → kill the conj


def _sconst(v: str) -> Expression:
    # json.dumps text keeps the dedup key (str(ast), see
    # ruleset._AtomTable) collision-free for values with quotes
    return const_expr(v, V.STRING, text=json.dumps(v))


def _land(clauses: list) -> Any:
    real = [c for c in clauses if c is not _TRUE]
    if any(c is _FALSE for c in real):
        return _FALSE
    if not real:
        return _TRUE
    out = real[0]
    for c in real[1:]:   # binary left-nesting: the checker's LAND is 2-ary
        out = fn_expr("LAND", out, c)
    return out


def _lor(alts: list) -> Any:
    real = [a for a in alts if a is not _FALSE]
    if any(a is _TRUE for a in real):
        return _TRUE
    if not real:
        return _FALSE
    out = real[0]
    for a in real[1:]:
        out = fn_expr("LOR", out, a)
    return out


def _string_match_clause(pattern: str, field: Expression | str) -> Any:
    """stringMatch(pattern, field) as an expression clause; `field` is
    the instance expression AST, or a python string when the instance
    omits the field (the host then compares against "", rbac.go's
    zero-value read) — folded to a constant verdict here."""
    if pattern == "*":
        return _TRUE
    if isinstance(field, str):   # constant fold against the known value
        if pattern.endswith("*"):
            ok = field.startswith(pattern[:-1])
        elif pattern.startswith("*"):
            ok = field.endswith(pattern[1:])
        else:
            ok = pattern == field
        return _TRUE if ok else _FALSE
    if pattern.endswith("*"):
        return fn_expr("startsWith", _sconst(pattern[:-1]), target=field)
    if pattern.startswith("*"):
        return fn_expr("endsWith", _sconst(pattern[1:]), target=field)
    return fn_expr("EQ", field, _sconst(pattern))


def _any_match_clause(patterns: Sequence[str], field) -> Any:
    """rbac.go _any_match: empty pattern list matches everything."""
    if not patterns:
        return _TRUE
    return _lor([_string_match_clause(str(p), field) for p in patterns])


def _eq_clause(field: Expression | str, value: str) -> Any:
    if isinstance(field, str):
        return _TRUE if field == value else _FALSE
    return fn_expr("EQ", field, _sconst(value))


# --- instance expression access ------------------------------------


def _field(tree: Mapping[str, Any], *path: str) -> Expression | str:
    """Expression AST at subject/action path, or "" when omitted (the
    host handler's .get(..., "") default)."""
    node: Any = tree
    for p in path:
        if not isinstance(node, Mapping) or p not in node:
            return ""
        node = node[p]
    if isinstance(node, Expression):
        return node
    return ""


def _prop(tree: Mapping[str, Any], group: str, key: str
          ) -> Expression | str:
    props = tree.get(group, {})
    props = props.get("properties", {}) if isinstance(props, Mapping) \
        else {}
    node = props.get(key, "")
    return node if isinstance(node, Expression) else ""


def _require_string(e: Expression | str, what: str,
                    finder: AttributeDescriptorFinder) -> None:
    if isinstance(e, Expression):
        t = eval_type(e, finder, DEFAULT_FUNCS)
        if t != V.STRING:
            # host compares str(value) — a non-string device EQ would
            # compare raw intern ids and diverge (e.g. int 5 vs "5")
            raise RbacLowerError(
                f"{what}: non-STRING expression ({t.name}) — host "
                f"stringifies, device cannot")


# --- the lowering ----------------------------------------------------


def lower_rbac(roles: Sequence[Mapping[str, Any]],
               bindings: Sequence[Mapping[str, Any]],
               inst_exprs: Mapping[str, Any],
               finder: AttributeDescriptorFinder,
               max_pseudo_rules: int = 20_000) -> LoweredRbac:
    """Lower one rbac policy set against one authorization instance.

    `inst_exprs` is the instance's expression tree
    ({"subject": {"user": Expression, "properties": {k: Expression}},
    "action": {...}}, from InstanceBuilder.expr_tree()). Raises
    RbacLowerError when any construct is outside the fusable subset.
    """
    role_by_key = {(str(r.get("namespace", "")), str(r.get("name", ""))): r
                   for r in roles}

    ns_field = _field(inst_exprs, "action", "namespace")
    user_field = _field(inst_exprs, "subject", "user")
    group_field = _field(inst_exprs, "subject", "groups")
    svc_field = _field(inst_exprs, "action", "service")
    method_field = _field(inst_exprs, "action", "method")
    path_field = _field(inst_exprs, "action", "path")
    for e, what in ((ns_field, "action.namespace"),
                    (user_field, "subject.user"),
                    (group_field, "subject.groups"),
                    (svc_field, "action.service"),
                    (method_field, "action.method"),
                    (path_field, "action.path")):
        _require_string(e, what, finder)

    allow: list[Expression] = []
    n_triples = 0
    for b in bindings:
        bns = str(b.get("namespace", ""))
        ns_clause = _eq_clause(ns_field, bns)
        if ns_clause is _FALSE:
            continue
        role = role_by_key.get(
            (bns, str((b.get("roleRef") or {}).get("name", ""))))
        if role is None:
            continue
        for subj in (b.get("subjects") or ()):
            s_clauses = [ns_clause]
            # host parity (rbac.go _subject_bound): user/group compare
            # RAW config values against string instance fields — a
            # non-string value (unquoted YAML number) can never equal a
            # string, so the subject is statically unbindable
            if "user" in subj and subj["user"] != "*":
                if not isinstance(subj["user"], str):
                    continue
                s_clauses.append(_eq_clause(user_field, subj["user"]))
            if "group" in subj and subj["group"] != "*":
                if not isinstance(subj["group"], str):
                    continue
                s_clauses.append(_eq_clause(group_field, subj["group"]))
            for k, v in sorted((subj.get("properties") or {}).items()):
                pf = _prop(inst_exprs, "subject", str(k))
                _require_string(pf, f"subject.properties[{k}]", finder)
                s_clauses.append(_eq_clause(pf, str(v)))
            subj_clause = _land(s_clauses)
            if subj_clause is _FALSE:
                continue
            for rule in (role.get("rules") or ()):
                n_triples += 1
                pats = {}
                for fld in ("services", "methods", "paths"):
                    pats[fld] = list(rule.get(fld) or ())
                    for p in pats[fld]:
                        if not isinstance(p, str):
                            # host _string_match would AttributeError →
                            # adapter-panic INTERNAL; keep on host
                            raise RbacLowerError(
                                f"{fld}: non-string pattern "
                                f"{type(p).__name__}")
                clauses = [subj_clause,
                           _any_match_clause(pats["services"],
                                             svc_field),
                           _any_match_clause(pats["methods"],
                                             method_field),
                           _any_match_clause(pats["paths"], path_field)]
                ok = True
                for c in (rule.get("constraints") or ()):
                    key = str(c.get("key", ""))
                    vals = [str(v) for v in (c.get("values") or ())]
                    pf = _prop(inst_exprs, "action", key)
                    _require_string(pf, f"constraint[{key}]", finder)
                    cc = _lor([_eq_clause(pf, v) for v in vals])
                    if cc is _FALSE:
                        ok = False
                        break
                    clauses.append(cc)
                if not ok:
                    continue
                conj = _land(clauses)
                if conj is _FALSE:
                    continue
                if conj is _TRUE:
                    conj = const_expr(True, V.BOOL)
                allow.append(conj)
                if len(allow) > max_pseudo_rules:
                    raise RbacLowerError(
                        f"policy expands past {max_pseudo_rules} "
                        f"pseudo-rules")

    guard = _land([fn_expr("EQ", e, e)
                   for e in _walk_exprs(inst_exprs)])
    guard_ast = None if guard in (_TRUE, _FALSE) else guard
    return LoweredRbac(allow_asts=allow, guard_ast=guard_ast,
                       n_triples=n_triples)


def _walk_exprs(tree: Any) -> list[Expression]:
    out: list[Expression] = []
    if isinstance(tree, Expression):
        return [tree]
    if isinstance(tree, Mapping):
        for k in sorted(tree):
            out.extend(_walk_exprs(tree[k]))
    return out
