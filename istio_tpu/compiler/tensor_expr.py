"""Tensor expression compiler: AST → batched masked evaluation under jit.

This replaces the reference's IL compiler + stack-VM interpreter hot loop
(mixer/pkg/il/compiler + interpreter/interpreterRun.go:70 — O(rules)
sequential per request) with data-parallel evaluation: ONE traced program
evaluates an expression for a whole batch of requests at once.

Short-circuit + 3-valued-presence semantics are compiled into masked
boolean algebra (SURVEY.md §7 layer 3b: "no short-circuit — evaluate
everything, mask errors, reduce"). Every node lowers to a triple

    (val, ok, err)   each [B]

where `ok` means "produced a value" and `err` means "hard runtime error".
Absence (fallback-able) is `~ok & ~err`. The exact masking rules mirror
the oracle (istio_tpu/expr/oracle.py), which mirrors the IL codegen:

  eff_err(x)  = x.err | ~x.ok          # hard context turns absence → error
  LAND(a,b):   err = ea | (~ea & a.val & eb)        ; val = a.val & b.val
  LOR(a,b):    err = ea | (~ea & ~a.val & eb)       ; val = a.val | b.val
  OR(a,b):     val = a.ok ? a.val : b.val
               ok  = a.ok | (~a.err & b.ok)
               err = a.err | (~a.ok & ~a.err & b.err)
  EQ/NEQ, externs: err = OR of eff_err(operand)

A suppressed operand's garbage value can never leak: `a.val & b.val` is
False whenever the suppressing side is False, and `|` dually.

Because the language has no ordering/arithmetic (func.go:39-72), all
non-boolean values are interned int32 ids (see layout.py) and EQ is id
comparison; ip()/timestamp() normalization happens at intern time. String
byte-level predicates lower to ops/bytes_ops (+ regex_dfa).

Expressions the device path cannot lower — dynamic-key INDEX, non-constant
match/regex patterns, ip()/timestamp() over runtime strings, unsupported
regex constructs — raise HostFallback at compile time and are routed to
the oracle by the runtime dispatcher.
"""
from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from istio_tpu.attribute.types import ValueType
from istio_tpu.compiler.layout import (AttributeBatch, BatchLayout,
                                       ID_TRUE, InternTable,
                                       ORDER_KEY_TYPES, order_key_bytes)
from istio_tpu.expr.checker import (AttributeDescriptorFinder, DEFAULT_FUNCS,
                                    eval_type)
from istio_tpu.expr.exprs import Expression, FunctionCall
from istio_tpu.expr.externs import ExternError, extern_ip, extern_timestamp
from istio_tpu.expr.parser import parse
from istio_tpu.ops import bytes_ops
from istio_tpu.ops.regex_dfa import UnsupportedRegex, compile_regex

V = ValueType
_BYTE_PREDS = ("match", "matches", "startsWith", "endsWith")
_CMP_FUNCS = ("LSS", "LEQ", "GTR", "GEQ")


class HostFallback(Exception):
    """Expression cannot run on device; evaluate with the oracle."""


@dataclasses.dataclass
class TVal:
    val: Any   # bool[B] for BOOL nodes, int32[B] ids otherwise
    ok: Any    # bool[B]
    err: Any   # bool[B]


@dataclasses.dataclass
class BVal:
    """Byte-string view of a subtree (subject of a byte predicate)."""
    data: Any  # uint8[B, L]
    lens: Any  # int32[B]
    ok: Any
    err: Any


def _eff_err(t: TVal) -> Any:
    return t.err | ~t.ok


# ---------------------------------------------------------------------------
# Requirement collection (pre-pass)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class Requirements:
    """What the layout must provide for a set of expressions."""
    derived_keys: set[tuple[str, str]] = dataclasses.field(default_factory=set)
    byte_sources: set[Any] = dataclasses.field(default_factory=set)
    # (extern name, operand key) → operand AST: runtime ip()/
    # timestamp() conversions the tensorizer runs at ingest
    extern_sources: dict[tuple[str, str], Any] = \
        dataclasses.field(default_factory=dict)

    def merge(self, other: "Requirements") -> None:
        self.derived_keys |= other.derived_keys
        self.byte_sources |= other.byte_sources
        self.extern_sources.update(other.extern_sources)


def _extern_operand_ok(e: Expression) -> bool:
    """Shapes the tensorizer's ingest oracle may evaluate: constants,
    variables, constant-key INDEX, and `|` fallbacks over those."""
    if e.const_ is not None or e.var is not None:
        return True
    f = e.fn
    if f is None:
        return False
    if f.name == "INDEX":
        return (f.args[0].var is not None
                and f.args[1].const_ is not None)
    if f.name == "OR":
        return all(_extern_operand_ok(a) for a in f.args)
    return False


def collect_requirements(ast: Expression, finder: AttributeDescriptorFinder,
                         reqs: Requirements | None = None) -> Requirements:
    """Walk the AST collecting derived-slot and byte-slot needs; raises
    HostFallback for shapes the device path cannot express."""
    if reqs is None:
        reqs = Requirements()
    _collect(ast, finder, reqs, as_bytes=False)
    return reqs


def _collect(e: Expression, finder: AttributeDescriptorFinder,
             reqs: Requirements, as_bytes: bool) -> None:
    if e.const_ is not None:
        return
    if e.var is not None:
        vt = finder.get_attribute(e.var.name)
        if vt is None:
            raise HostFallback(f"unknown attribute {e.var.name}")
        if as_bytes:
            reqs.byte_sources.add(e.var.name)
        return
    f = e.fn
    assert f is not None
    if f.name == "INDEX":
        tgt = f.args[0]
        if tgt.var is not None:
            map_vars = [tgt.var.name]
        elif (tgt.fn is not None and tgt.fn.name == "OR"
              and all(a.var is not None for a in tgt.fn.args)
              and not as_bytes):
            # (mapA | mapB)[key]: both maps' derived slots + presence
            map_vars = [a.var.name for a in tgt.fn.args]
        else:
            raise HostFallback("INDEX over non-variable map")
        if f.args[1].const_ is None:
            raise HostFallback("dynamic string-map key")
        key = f.args[1].const_.value
        if not isinstance(key, str):
            raise HostFallback("non-string map key")
        for m in map_vars:
            if finder.get_attribute(m) != ValueType.STRING_MAP:
                raise HostFallback(f"INDEX over non-map {m}")
            reqs.derived_keys.add((m, key))
        if as_bytes:
            reqs.byte_sources.add((map_vars[0], key))
        return
    if f.name == "OR":
        _collect(f.args[0], finder, reqs, as_bytes)
        _collect(f.args[1], finder, reqs, as_bytes)
        return
    if f.name in _BYTE_PREDS:
        if f.name == "match":
            subject, pattern = f.args[0], f.args[1]
        elif f.name == "matches":
            subject, pattern = f.args[0], f.target
        else:  # startsWith / endsWith
            subject, pattern = f.target, f.args[0]
        if pattern is None or pattern.const_ is None or \
                not isinstance(pattern.const_.value, str):
            if f.name == "matches":
                # runtime regex compilation has no device analog
                raise HostFallback("non-constant pattern for matches")
            # dynamic prefix/suffix/glob: BOTH sides ride byte planes
            # (bytes_ops.dyn_*_match)
            _collect(pattern, finder, reqs, as_bytes=True)
            _collect(subject, finder, reqs, as_bytes=True)
            return
        if f.name == "matches":
            try:
                compile_regex(pattern.const_.value)
            except UnsupportedRegex as exc:
                import re as _re
                try:
                    _re.compile(pattern.const_.value)
                except _re.error:
                    # invalid pattern: the oracle errors on EVERY
                    # evaluation → lowers to a constant-error atom,
                    # no requirements needed
                    return
                raise HostFallback(str(exc))
        _collect(subject, finder, reqs, as_bytes=True)
        return
    if f.name in ("ip", "timestamp"):
        arg = f.args[0]
        if arg.const_ is None:
            # runtime conversion: the TENSORIZER runs it at ingest into
            # an extern column (layout.extern_slots) — string parsing
            # has no device form, so it happens at the edge, once per
            # request, not per rule
            if not _extern_operand_ok(arg):
                raise HostFallback(
                    f"{f.name}() over an un-ingestable operand")
            _collect(arg, finder, reqs, as_bytes=False)
            reqs.extern_sources[(f.name, str(arg))] = arg
        return
    if f.name in _CMP_FUNCS:
        # ordered comparisons ride the byte planes: strings as utf-8,
        # numerics as 8-byte order keys (layout.order_key_bytes) —
        # keys of DIFFERENT types are not mutually comparable, so only
        # same-type pairs lower. INT64-vs-DOUBLE is a real comparison
        # on the oracle (python int<float) → host fallback; every
        # other mixed/unorderable pair makes the oracle raise on EVERY
        # evaluation → a constant-error atom, no requirements needed.
        ta, tb = (eval_type(a, finder, DEFAULT_FUNCS) for a in f.args)
        if ta != tb:
            if {ta, tb} <= {V.INT64, V.DOUBLE}:
                raise HostFallback("mixed numeric comparison")
            return   # oracle type error every row
        if ta != V.STRING and ta not in ORDER_KEY_TYPES:
            return   # unorderable (BOOL/IP/BYTES): oracle error
        for a in f.args:
            _collect(a, finder, reqs, as_bytes=True)
        return
    if f.name in ("EQ", "NEQ", "LAND", "LOR"):
        for a in f.args:
            _collect(a, finder, reqs, as_bytes=False)
        return
    raise HostFallback(f"unsupported function on device: {f.name}")


# ---------------------------------------------------------------------------
# Compilation
# ---------------------------------------------------------------------------

class _Ctx:
    def __init__(self, layout: BatchLayout, interner: InternTable,
                 finder: AttributeDescriptorFinder):
        self.layout = layout
        self.interner = interner
        self.finder = finder

    def type_of(self, e: Expression) -> ValueType:
        return eval_type(e, self.finder, DEFAULT_FUNCS)


NodeFn = Callable[[AttributeBatch], TVal]
ByteFn = Callable[[AttributeBatch], BVal]


def _const_tval(value: Any, vtype: ValueType, ctx: _Ctx) -> NodeFn:
    if vtype == V.BOOL:
        v = bool(value)

        def fn(batch: AttributeBatch) -> TVal:
            b = batch.ids.shape[0]
            return TVal(jnp.full(b, v, bool), jnp.ones(b, bool),
                        jnp.zeros(b, bool))
        return fn
    cid = ctx.interner.intern(value)

    def fn(batch: AttributeBatch) -> TVal:
        b = batch.ids.shape[0]
        return TVal(jnp.full(b, cid, jnp.int32), jnp.ones(b, bool),
                    jnp.zeros(b, bool))
    return fn


def _error_tval() -> NodeFn:
    def fn(batch: AttributeBatch) -> TVal:
        b = batch.ids.shape[0]
        return TVal(jnp.zeros(b, jnp.int32), jnp.zeros(b, bool),
                    jnp.ones(b, bool))
    return fn


def _compile_node(e: Expression, ctx: _Ctx) -> NodeFn:
    if e.const_ is not None:
        return _const_tval(e.const_.value, e.const_.vtype, ctx)

    if e.var is not None:
        vt = ctx.finder.get_attribute(e.var.name)
        if vt is None:
            raise HostFallback(f"unknown attribute {e.var.name}")
        if vt == V.STRING_MAP:
            raise HostFallback("bare string-map variable on device")
        col = ctx.layout.slot_of(e.var.name)
        is_bool = vt == V.BOOL

        def fn(batch: AttributeBatch) -> TVal:
            ids = batch.ids[:, col]
            ok = batch.present[:, col]
            val = (ids == ID_TRUE) if is_bool else ids
            return TVal(val, ok, jnp.zeros_like(ok))
        return fn

    f = e.fn
    assert f is not None
    name = f.name

    if name == "INDEX":
        key = f.args[1].const_.value
        tgt = f.args[0]
        if tgt.var is not None:
            col = ctx.layout.derived_slot_of(tgt.var.name, key)

            def fn(batch: AttributeBatch) -> TVal:
                ok = batch.present[:, col]
                return TVal(batch.ids[:, col], ok, jnp.zeros_like(ok))
            return fn
        # (mapA | mapB)[key] — _collect validated the OR-of-vars shape:
        # soft map fallback selects by MAP presence, then the chosen
        # map's derived slot supplies value/presence (oracle: `|` soft
        # mode over map variables, then the usual INDEX encoding)
        m1 = tgt.fn.args[0].var.name
        m2 = tgt.fn.args[1].var.name
        c1 = ctx.layout.derived_slot_of(m1, key)
        c2 = ctx.layout.derived_slot_of(m2, key)
        mp1 = ctx.layout.map_slots[m1]
        mp2 = ctx.layout.map_slots[m2]

        def fn(batch: AttributeBatch) -> TVal:
            sel = batch.map_present[:, mp1]
            val = jnp.where(sel, batch.ids[:, c1], batch.ids[:, c2])
            ok = jnp.where(sel, batch.present[:, c1],
                           batch.map_present[:, mp2]
                           & batch.present[:, c2])
            return TVal(val, ok, jnp.zeros_like(ok))
        return fn

    if name == "OR":
        fa = _compile_node(f.args[0], ctx)
        fb = _compile_node(f.args[1], ctx)

        def fn(batch: AttributeBatch) -> TVal:
            a, b = fa(batch), fb(batch)
            val = jnp.where(a.ok, a.val, b.val)
            ok = a.ok | (~a.err & b.ok)
            err = a.err | (~a.ok & ~a.err & b.err)
            return TVal(val, ok, err)
        return fn

    if name in ("EQ", "NEQ"):
        fa = _compile_node(f.args[0], ctx)
        fb = _compile_node(f.args[1], ctx)
        negate = name == "NEQ"

        def fn(batch: AttributeBatch) -> TVal:
            a, b = fa(batch), fb(batch)
            cmp = a.val == b.val
            if negate:
                cmp = ~cmp
            ee = _eff_err(a) | _eff_err(b)
            return TVal(cmp, ~ee, ee)
        return fn

    if name == "LAND":
        fa = _compile_node(f.args[0], ctx)
        fb = _compile_node(f.args[1], ctx)

        def fn(batch: AttributeBatch) -> TVal:
            a, b = fa(batch), fb(batch)
            ea, eb = _eff_err(a), _eff_err(b)
            err = ea | (~ea & a.val & eb)
            val = a.val & b.val & ~err
            return TVal(val, ~err, err)
        return fn

    if name == "LOR":
        fa = _compile_node(f.args[0], ctx)
        fb = _compile_node(f.args[1], ctx)

        def fn(batch: AttributeBatch) -> TVal:
            a, b = fa(batch), fb(batch)
            ea, eb = _eff_err(a), _eff_err(b)
            err = ea | (~ea & ~a.val & eb)
            val = ((a.val & ~ea) | (b.val & ~eb)) & ~err
            return TVal(val, ~err, err)
        return fn

    if name in _BYTE_PREDS:
        return _compile_byte_pred(f, ctx)

    if name in _CMP_FUNCS:
        return _compile_cmp(f, ctx)

    if name in ("ip", "timestamp"):
        arg = f.args[0]
        if arg.const_ is None:
            # ingest-converted extern column (layout.extern_slots):
            # ID_INVALID marks a conversion/lookup error
            col = ctx.layout.extern_slots.get((name, str(arg)))
            if col is None:
                raise HostFallback(
                    f"{name}() operand missing an extern slot")

            def fn(batch: AttributeBatch) -> TVal:
                ids = batch.ids[:, col]
                pres = batch.present[:, col]
                err = pres & (ids == 0)
                ok = pres & ~err
                return TVal(ids, ok, err)
            return fn
        raw = arg.const_.value
        try:
            value = (extern_ip(raw) if name == "ip"
                     else extern_timestamp(raw))
        except ExternError:
            return _error_tval()  # runtime-error constant, oracle parity
        return _const_tval(value, V.IP_ADDRESS if name == "ip"
                           else V.TIMESTAMP, ctx)

    raise HostFallback(f"unsupported function on device: {name}")


def _compile_cmp(f: FunctionCall, ctx: _Ctx) -> NodeFn:
    """Ordered comparison (expr LSS/LEQ/GTR/GEQ, reference func.go's
    ordered intrinsics) over the byte planes.

    Strings compare as raw utf-8 (Go string order); numerics compare by
    their 8-byte order keys (layout.order_key_bytes) — both reduce to
    one lex_cmp. NaN operands arrive as present-but-EMPTY numeric rows
    and read False under every comparison (IEEE semantics, oracle
    parity). String rows at the byte-slot cap may be truncated, making
    the comparison undecidable → err, routed to the host oracle."""
    name = f.name
    ta = ctx.type_of(f.args[0])
    tb = ctx.type_of(f.args[1])
    if ta != tb:
        if {ta, tb} <= {V.INT64, V.DOUBLE}:
            raise HostFallback("mixed numeric comparison")
        return _error_tval()   # oracle type error on every row
    if ta != V.STRING and ta not in ORDER_KEY_TYPES:
        # the oracle raises "unordered operand" on every evaluation
        return _error_tval()
    numeric = ta in ORDER_KEY_TYPES
    fa = _compile_bytes(f.args[0], ctx)
    fb = _compile_bytes(f.args[1], ctx)
    max_len = ctx.layout.max_str_len

    def fn(batch: AttributeBatch) -> TVal:
        a, b = fa(batch), fb(batch)
        ee = (a.err | ~a.ok) | (b.err | ~b.ok)
        c = bytes_ops.lex_cmp(a.data, a.lens, b.data, b.lens)
        if name == "LSS":
            val = c < 0
        elif name == "LEQ":
            val = c <= 0
        elif name == "GTR":
            val = c > 0
        else:
            val = c >= 0
        if numeric:
            # NaN marker (empty key): all four comparisons read False,
            # never err. Malformed-payload marker (1-byte key,
            # layout.ORDER_KEY_ERROR): the oracle raises per row → err
            nan = (a.ok & (a.lens == 0)) | (b.ok & (b.lens == 0))
            bad = (a.ok & (a.lens == 1)) | (b.ok & (b.lens == 1))
            ee = ee | bad
            val = val & ~nan
        else:
            # either side possibly truncated → order undecidable
            ee = ee | (a.ok & (a.lens >= max_len)) \
                    | (b.ok & (b.lens >= max_len))
        val = val & ~ee
        return TVal(val, ~ee, ee)
    return fn


def _compile_byte_pred(f: FunctionCall, ctx: _Ctx) -> NodeFn:
    """Byte predicates with truncation safety.

    Strings longer than max_str_len land truncated in the byte plane
    (layout.py). Per predicate:
      * prefix checks (startsWith, `x*` globs, exact globs shorter
        than the cap) only read the head — always decidable;
      * suffix/tail checks (endsWith, `*x` globs, cap-length exact
        globs) are undecidable on a possibly-truncated row → the row
        is marked err, which the serving path routes to the host
        oracle (dispatcher._overlay_fallback);
      * unanchored regex: a hit inside the stored prefix proves a hit
        in the full string, so only a MISS on a truncated row is
        undecidable; a `$`-anchored regex could falsely anchor at the
        truncation point, so every truncated row is undecidable.
    A pattern longer than the cap can't be represented on device at
    all → HostFallback at compile time.
    """
    max_len = ctx.layout.max_str_len
    if f.name == "match":
        pattern_ast = f.args[1]
    elif f.name == "matches":
        pattern_ast = f.target
    else:
        pattern_ast = f.args[0]
    if pattern_ast.const_ is None and f.name != "matches":
        return _compile_dyn_byte_pred(f, ctx)
    # "safe": truncation can't change the result; "miss": only a False
    # on a truncated row is unreliable; "all": every truncated row is
    if f.name == "match":
        subject_ast, pattern = f.args[0], f.args[1].const_.value
        if len(pattern.encode("utf-8")) > max_len:
            raise HostFallback("glob pattern exceeds byte-slot width")
        op = partial(bytes_ops.glob_match, pattern=pattern)
        if pattern.endswith("*"):
            trunc = "safe"                      # prefix glob
        elif pattern.startswith("*"):
            trunc = "all"                       # suffix glob
        else:
            # exact: safe unless the stored prefix could equal the
            # pattern while the real string continues past the cap
            trunc = "safe" if len(pattern.encode()) < max_len else "all"
    elif f.name == "matches":
        subject_ast, pattern = f.args[0], f.target.const_.value
        try:
            dfa = compile_regex(pattern)
        except UnsupportedRegex:
            import re as _re
            try:
                _re.compile(pattern)
            except _re.error:
                return _error_tval()   # invalid pattern: always errors
            raise
        trans = jnp.asarray(dfa.transitions)
        accept = jnp.asarray(dfa.accept)
        op = lambda data, lens: bytes_ops.dfa_match(data, lens, trans, accept)
        trunc = "all" if "$" in pattern else "miss"
    elif f.name == "startsWith":
        subject_ast, pattern = f.target, f.args[0].const_.value
        if len(pattern.encode("utf-8")) > max_len:
            raise HostFallback("prefix exceeds byte-slot width")
        op = lambda data, lens: bytes_ops.prefix_match(data, lens,
                                                       pattern.encode())
        trunc = "safe"
    else:  # endsWith
        subject_ast, pattern = f.target, f.args[0].const_.value
        op = lambda data, lens: bytes_ops.suffix_match(data, lens,
                                                       pattern.encode())
        trunc = "all"

    fsub = _compile_bytes(subject_ast, ctx)

    def fn(batch: AttributeBatch) -> TVal:
        s = fsub(batch)
        ee = s.err | ~s.ok
        val = op(s.data, s.lens) & ~ee
        if trunc != "safe":
            maybe_truncated = s.ok & (s.lens >= max_len)
            undecidable = maybe_truncated if trunc == "all" \
                else (maybe_truncated & ~val)
            ee = ee | undecidable
            val = val & ~ee
        return TVal(val, ~ee, ee)
    return fn


def compile_dfa_group(subject_ast: Expression, patterns: list[str],
                      dfas: list, ctx: "_Ctx") -> Callable:
    """ALL constant-pattern `matches` atoms over ONE subject, evaluated
    in a single packed scan (ops/bytes_ops.dfa_match_many).

    Per-atom DFA scans are latency-bound: each of the L scan steps is a
    tiny [B] gather, so k separate atoms cost k·L sequential steps
    (~40 ms for the 1k-route table, VERDICT r2 weak #3). Packing turns
    that into ONE L-step scan with [B, k] gathers — the batched-NFA
    shape SURVEY §7 hard-part 1 calls for.

    Returns fn(batch) → (val [B, k], ee [B, k]) with exactly
    _compile_byte_pred's semantics per column: subject absence/error
    masks the row; truncated rows are fully undecidable for $-anchored
    patterns and miss-undecidable otherwise."""
    from istio_tpu.ops.regex_dfa import pack_dfas_tiered

    max_len = ctx.layout.max_str_len
    fsub = _compile_bytes(subject_ast, ctx)
    # tier selection shared with the engine's list banks
    # (regex_dfa.pack_dfas_tiered)
    tiers = pack_dfas_tiered(dfas)
    packed = tiers["packed"]
    packed_blk = tiers["packed_blk"]
    trans_j = None if tiers["trans"] is None \
        else jnp.asarray(tiers["trans"])
    accept_j = None if tiers["accept"] is None \
        else jnp.asarray(tiers["accept"])
    trunc_all = jnp.asarray(np.array(["$" in p for p in patterns]))

    def fn(batch: AttributeBatch):
        s = fsub(batch)
        # the MXU formulations win at EVERY serving batch size
        # (profiled r4 at B=256: 0.055 ms vs 0.279 ms for the flat
        # gather — the per-step [B, N] gather is latency-bound on TPU
        # regardless of B)
        if packed is not None:
            m = bytes_ops.dfa_match_many_onehot(s.data, s.lens, packed)
        elif packed_blk is not None:
            m = bytes_ops.dfa_match_many_onehot_blocked(
                s.data, s.lens, packed_blk)
        else:
            m = bytes_ops.dfa_match_many(s.data, s.lens, trans_j,
                                         accept_j)
        ee = (s.err | ~s.ok)[:, None] & jnp.ones_like(m)
        val = m & ~ee
        maybe = (s.ok & (s.lens >= max_len))[:, None]
        undecidable = jnp.where(trunc_all[None, :], maybe, maybe & ~val)
        ee = ee | undecidable
        val = val & ~ee
        return val, ee
    return fn


def _compile_dyn_byte_pred(f: FunctionCall, ctx: _Ctx) -> NodeFn:
    """Byte predicates whose PATTERN is itself a runtime string
    (`as.startsWith(as2)`, `match(as, as2)`): both operands ride byte
    planes and bytes_ops.dyn_*_match compares them row-wise.

    Truncation: the subject's stored prefix decides a prefix check iff
    the pattern fits under the cap; suffix/exact/glob verdicts on a
    possibly-truncated subject, and any possibly-truncated pattern,
    are undecidable → err (host oracle takes the row)."""
    max_len = ctx.layout.max_str_len
    if f.name == "match":
        subject_ast, pattern_ast = f.args[0], f.args[1]
        op, trunc_subject = bytes_ops.dyn_glob_match, "all"
    elif f.name == "startsWith":
        subject_ast, pattern_ast = f.target, f.args[0]
        op, trunc_subject = bytes_ops.dyn_prefix_match, "safe"
    else:   # endsWith
        subject_ast, pattern_ast = f.target, f.args[0]
        op, trunc_subject = bytes_ops.dyn_suffix_match, "all"
    fsub = _compile_bytes(subject_ast, ctx)
    fpat = _compile_bytes(pattern_ast, ctx)

    def fn(batch: AttributeBatch) -> TVal:
        s, p = fsub(batch), fpat(batch)
        ee = (s.err | ~s.ok) | (p.err | ~p.ok)
        val = op(s.data, s.lens, p.data, p.lens)
        undecidable = p.ok & (p.lens >= max_len)
        if trunc_subject == "all":
            undecidable = undecidable | (s.ok & (s.lens >= max_len))
        ee = ee | undecidable
        val = val & ~ee
        return TVal(val, ~ee, ee)
    return fn


def _compile_bytes(e: Expression, ctx: _Ctx) -> ByteFn:
    """Compile a STRING-typed subtree to its byte-tensor view."""
    lay = ctx.layout
    if e.const_ is not None:
        if e.const_.vtype in ORDER_KEY_TYPES:
            raw = order_key_bytes(e.const_.value, e.const_.vtype)
        else:
            raw = str(e.const_.value).encode("utf-8")[:lay.max_str_len]
        row = np.zeros(lay.max_str_len, dtype=np.uint8)
        if raw:
            row[:len(raw)] = np.frombuffer(raw, dtype=np.uint8)
        n = len(raw)
        # the latency-tier gate (fused.str_tiers) reads this back: a
        # batch plane must never narrow below the longest constant —
        # slicing a constant row loses REAL tail bytes (a >tier
        # constant subject of endsWith would silently flip verdicts;
        # the runtime str_lens check cannot see compile-time rows)
        ctx.interner.note_byte_const(n)

        def fn(batch: AttributeBatch) -> BVal:
            b = batch.ids.shape[0]
            # constant rows follow the BATCH plane's width, which a
            # narrowed latency-tier batch (fused.narrow_batch) slices
            # below max_str_len. Sound because str_tiers gates every
            # tier to >= the longest compiled constant (note_byte_const
            # above): row[:w] only ever drops zero padding, and `n`
            # keeps the TRUE length for the tiebreaks.
            w = batch.str_bytes.shape[2]
            return BVal(jnp.broadcast_to(jnp.asarray(row[:w]), (b, w)),
                        jnp.full(b, n, jnp.int32),
                        jnp.ones(b, bool), jnp.zeros(b, bool))
        return fn

    if e.var is not None:
        bcol = lay.byte_slots[e.var.name]
        col = lay.slot_of(e.var.name)

        def fn(batch: AttributeBatch) -> BVal:
            ok = batch.present[:, col]
            return BVal(batch.str_bytes[:, bcol, :], batch.str_lens[:, bcol],
                        ok, jnp.zeros_like(ok))
        return fn

    f = e.fn
    assert f is not None
    if f.name == "INDEX":
        pair = (f.args[0].var.name, f.args[1].const_.value)
        bcol = lay.byte_slots[pair]
        col = lay.derived_slot_of(*pair)

        def fn(batch: AttributeBatch) -> BVal:
            ok = batch.present[:, col]
            return BVal(batch.str_bytes[:, bcol, :], batch.str_lens[:, bcol],
                        ok, jnp.zeros_like(ok))
        return fn

    if f.name == "OR":
        fa = _compile_bytes(f.args[0], ctx)
        fb = _compile_bytes(f.args[1], ctx)

        def fn(batch: AttributeBatch) -> BVal:
            a, b = fa(batch), fb(batch)
            sel = a.ok[:, None]
            data = jnp.where(sel, a.data, b.data)
            lens = jnp.where(a.ok, a.lens, b.lens)
            ok = a.ok | (~a.err & b.ok)
            err = a.err | (~a.ok & ~a.err & b.err)
            return BVal(data, lens, ok, err)
        return fn

    raise HostFallback(f"cannot view {f.name}(...) as bytes on device")


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class TensorProgram:
    """A compiled expression: fn(batch) → (val [B], valid [B]).

    For BOOL expressions val is bool; otherwise val holds intern ids that
    `decode_value` maps back to Python values. `valid` is False exactly
    where the oracle would raise an evaluation error.
    """
    text: str
    result_type: ValueType
    fn: Callable[[AttributeBatch], tuple[Any, Any]]
    layout: BatchLayout
    interner: InternTable

    def __call__(self, batch: AttributeBatch) -> tuple[Any, Any]:
        return self.fn(batch)

    def decode_value(self, raw: Any, batch: AttributeBatch | None = None
                     ) -> Any:
        if self.result_type == V.BOOL:
            return bool(raw)
        vid = int(raw)
        if batch is not None:
            return batch.value_of(vid, self.interner)
        return self.interner.value_of(vid)


def compile_expression(text: str, finder: AttributeDescriptorFinder,
                       layout: BatchLayout,
                       interner: InternTable, jit: bool = True) -> TensorProgram:
    """Parse + type check + lower to a jitted batched evaluator.

    Raises HostFallback when the expression needs the oracle, and
    TypeError_/ParseError exactly like the oracle path."""
    ast = parse(text)
    rtype = eval_type(ast, finder, DEFAULT_FUNCS)
    ctx = _Ctx(layout, interner, finder)
    node = _compile_node(ast, ctx)

    def run(batch: AttributeBatch) -> tuple[Any, Any]:
        t = node(batch)
        return t.val, t.ok & ~t.err

    return TensorProgram(text=text, result_type=rtype,
                         fn=jax.jit(run) if jit else run,
                         layout=layout, interner=interner)


def compile_field(ast: Expression, finder: AttributeDescriptorFinder,
                  layout: BatchLayout, interner: InternTable
                  ) -> tuple[NodeFn, ValueType]:
    """Lower ONE already-parsed instance-field expression to an
    UNJITTED batched node (REPORT instance construction,
    runtime/report_lower.py — the reference evaluates these through
    the same IL hot loop as predicates, template.gen.go ProcessReport).
    The caller stacks many field nodes into a single device program
    alongside the packed check step. Raises HostFallback exactly like
    compile_expression; the returned TVal follows the same masked
    algebra (`ok & ~err` marks rows where the oracle would NOT raise).
    """
    rtype = eval_type(ast, finder, DEFAULT_FUNCS)
    ctx = _Ctx(layout, interner, finder)
    return _compile_node(ast, ctx), rtype
