"""Compilation caching — the delta-compilation plane's shared vocabulary.

A production mesh republishes config constantly; the point of this
module is that NOTHING recompiles unless its inputs changed. Three
layers, cheapest first:

  1. Content digests (`stable_digest` / `manifest_digest`) — the
     deterministic hashes the sharding plane keys its content-addressed
     bank cache on (istio_tpu/sharding/banks.bank_content_key): a bank
     whose rules, referenced handlers/instances, layout inputs and
     manifest are byte-identical across generations IS the same
     compiled artifact and is carried over, prewarmed shapes, breaker
     state and rulestats bindings included.

  2. DecompCache — per-rule parse + DNF-decomposition memo across
     snapshot builds. compile_ruleset's host cost is dominated by
     parsing and decomposing match predicates (measured ~85% of the
     build at fleet scale); a config delta re-presents almost every
     rule unchanged, so the builder replays the cached decomposition
     (atom ASTs re-interned into the new _AtomTable, conjunction sets
     re-indexed) and pays parse/DNF only for rules it has never seen.
     Guarded by the manifest digest + dnf_cap: a vocabulary change
     invalidates everything (eval_type / lowering decisions depend on
     attribute types).

  3. The JAX persistent compilation cache — XLA artifacts on disk
     (`jax_compilation_cache_dir`), so process restarts and rolling
     deploys skip the warm compile for every program whose HLO is
     unchanged. Our compiled programs take their index tensors as
     ARGUMENTS, never closure constants (compiler/ruleset.py), so a
     constant-only rule edit keeps the HLO — and therefore the cache
     key — bit-identical: only SHAPE changes (new atoms, wider
     conjunctions, different bank sizes) recompile. Wired through
     ServerArgs.jax_compile_cache_dir / `mixs --jax-compile-cache-dir`
     (env fallback MIXS_JAX_COMPILE_CACHE_DIR; JAX's own
     JAX_COMPILATION_CACHE_DIR works too, jax reads it natively).

Hit/miss accounting rides jax's monitoring events
('/jax/compilation_cache/cache_hits' / 'cache_misses') — the delta
smoke gate asserts a warm restart compiles NOTHING for unchanged
banks, and /debug/shards surfaces the counters.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
from typing import Any, Mapping

# -- content digests ---------------------------------------------------


def stable_digest(obj: Any) -> str:
    """sha256 of the canonical-JSON rendering of `obj` — deterministic
    across processes and PYTHONHASHSEED (sorted keys, no whitespace,
    default=str for the odd non-JSON leaf)."""
    blob = json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


def manifest_digest(finder) -> str:
    """Digest of an AttributeDescriptorFinder's vocabulary — the
    (name, value type) set every type-check and lowering decision
    depends on. Two finders with equal digests make identical
    eval_type / tier-classification decisions for any expression."""
    items = sorted((n, getattr(finder.get_attribute(n), "name",
                               str(finder.get_attribute(n))))
                   for n in finder.names())
    return stable_digest(items)


# -- persistent XLA compilation cache ---------------------------------

ENV_CACHE_DIR = "MIXS_JAX_COMPILE_CACHE_DIR"


def resolve_cache_dir(explicit: str | None = None) -> str | None:
    """Pick the persistent-cache directory: explicit config first
    (ServerArgs / --jax-compile-cache-dir), then the
    MIXS_JAX_COMPILE_CACHE_DIR env var. None = leave jax's own
    defaulting alone (JAX_COMPILATION_CACHE_DIR is read by jax itself
    at import, so pointing that at a directory also works without us).
    """
    if explicit:
        return explicit
    env = os.environ.get(ENV_CACHE_DIR, "").strip()
    return env or None


def configure_persistent_cache(cache_dir: str,
                               min_compile_time_s: float = 0.0) -> str:
    """Point jax's persistent compilation cache at `cache_dir`
    (created if missing) and lower the entry thresholds so every
    serving program is cached — bank programs at small shard sizes
    compile in well under jax's 1s default threshold, and they are
    exactly the artifacts a rolling deploy wants to skip. Returns the
    directory. Safe to call repeatedly (config updates are
    idempotent)."""
    import jax

    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_time_s))
    try:
        # cache entries below 0 bytes never exist; -1 = "cache all"
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          -1)
    except Exception:
        pass   # older jax: size threshold not configurable
    # jax memoizes its "is the cache used?" decision at the FIRST
    # compile of the process — a server configured after any earlier
    # compile (a long-lived test process, a REPL) would silently keep
    # the cache off forever without this reset
    reset_backend_cache_state()
    return cache_dir


def reset_backend_cache_state() -> None:
    """Drop jax's memoized cache-enabled/initialized state so the
    NEXT compile re-reads the current config. Also the correct thing
    to call after RESTORING a previous cache config (the smoke gate's
    finally) — without it the restored setting is never re-checked."""
    try:
        from jax._src import compilation_cache
        compilation_cache.reset_cache()
    except Exception:
        pass   # fail-soft: at worst the process keeps prior behavior


def persistent_cache_entries(cache_dir: str) -> int:
    """Number of compiled-artifact entries on disk (the `*-cache`
    files; jax writes a sibling `-atime` touch file per entry)."""
    try:
        return sum(1 for f in os.listdir(cache_dir)
                   if f.endswith("-cache"))
    except OSError:
        return 0


_EVENTS = {"hits": 0, "misses": 0}
_EVENTS_LOCK = threading.Lock()
_EVENTS_INSTALLED = False


def _on_event(event: str, **kw) -> None:
    if event == "/jax/compilation_cache/cache_hits":
        with _EVENTS_LOCK:
            _EVENTS["hits"] += 1
    elif event == "/jax/compilation_cache/cache_misses":
        with _EVENTS_LOCK:
            _EVENTS["misses"] += 1


def install_event_counters() -> None:
    """Register the jax monitoring listener that feeds
    cache_event_counts(). Idempotent; a jax too old to expose
    monitoring leaves the counters at zero (fail-soft — accounting
    must never break serving)."""
    global _EVENTS_INSTALLED
    if _EVENTS_INSTALLED:
        return
    try:
        from jax._src import monitoring
        monitoring.register_event_listener(_on_event)
        _EVENTS_INSTALLED = True
    except Exception:
        pass


def cache_event_counts() -> dict:
    """{"hits": n, "misses": n} persistent-cache lookups since the
    counters were installed (process-wide; snapshot-and-diff for a
    phase-scoped view)."""
    with _EVENTS_LOCK:
        return dict(_EVENTS)


# -- per-rule decomposition cache -------------------------------------


@dataclasses.dataclass
class DecompEntry:
    """One rule predicate's cached compile front half. `atom_asts`
    are the decomposition's primitive predicates in entry-local
    order; `m`/`n` are the monotone DNFs as tuples of
    ((local_atom_pos, kind), ...) literals. `oracle`/`reason` are set
    instead when the predicate host-falls-back (DNF blowup /
    unlowerable shape) — the oracle program is reused too, it is
    finder-pure and the cache is finder-guarded."""
    ast: Any
    atom_asts: tuple = ()
    m: tuple = ()
    n: tuple = ()
    oracle: Any = None
    reason: str = ""
    last_gen: int = 0

    @property
    def is_fallback(self) -> bool:
        return self.oracle is not None


class DecompCache:
    """Parse + DNF-decomposition memo across compile_ruleset calls.

    Keyed by the rule's raw match string (rules carrying a pre-built
    AST — rbac pseudo-rules — bypass the cache: they never parse and
    the sharding plane refuses them anyway). Bound to one
    (manifest digest, dnf_cap) world via begin(): a changed attribute
    vocabulary or cap clears everything, because type checking, the
    decomposition's HostFallback decisions and the cached oracles all
    depend on it.

    Writers are the controller's serialized rebuild thread (parent
    snapshot build, then each changed bank's sub-compile — the bank
    compiles are where the hits pay off twice); a lock keeps the memo
    safe for any stray concurrent compile anyway. Entries unused for
    PRUNE_AFTER_GENS begin() cycles are dropped so deleted rules do
    not accumulate forever."""

    PRUNE_AFTER_GENS = 64

    def __init__(self) -> None:
        self._entries: dict[str, DecompEntry] = {}
        self._digest: str | None = None
        self._gen = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0

    def begin(self, finder, dnf_cap: int) -> None:
        """Open a compile generation: validate the finder/cap guard
        (clearing on mismatch) and advance the pruning clock."""
        digest = manifest_digest(finder) + f":{dnf_cap}"
        with self._lock:
            if digest != self._digest:
                self._entries.clear()
                self._digest = digest
            self._gen += 1
            if self._gen % 16 == 0:
                floor = self._gen - self.PRUNE_AFTER_GENS
                stale = [k for k, e in self._entries.items()
                         if e.last_gen < floor]
                for k in stale:
                    del self._entries[k]

    def get(self, key: str) -> DecompEntry | None:
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                return None
            e.last_gen = self._gen
            self.hits += 1
            return e

    def put(self, key: str, entry: DecompEntry) -> None:
        entry.last_gen = self._gen
        with self._lock:
            self._entries[key] = entry

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "hits": self.hits,
                    "misses": self.misses, "generation": self._gen}
