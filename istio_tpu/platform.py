"""Hermetic JAX platform selection.

This container injects an `axon` TPU-tunnel PJRT plugin via
sitecustomize which pins jax_platforms="axon,cpu" at interpreter start;
plain JAX_PLATFORMS=cpu in the environment does NOT override it. Tests
and multi-chip dryruns therefore force the virtual host platform
explicitly, before any backend initializes. This module is the single
home for that dance (used by tests/conftest.py and
__graft_entry__.dryrun_multichip).
"""
from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu_platform(n_devices: int) -> None:
    """Force JAX onto an n_devices virtual CPU platform.

    Must run before any JAX backend initializes. Rewrites any existing
    xla_force_host_platform_device_count flag whose value is smaller
    than n_devices (a stale smaller count would silently win otherwise).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(_COUNT_FLAG + r"=(\d+)", flags)
    if m is None:
        flags = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
    elif int(m.group(1)) < n_devices:
        flags = flags[:m.start(1)] + str(n_devices) + flags[m.end(1):]
    os.environ["XLA_FLAGS"] = flags

    import jax

    jax.config.update("jax_platforms", "cpu")
