"""Shard planner — pack namespaces onto K banks by predicted budget.

The unit of placement is a NAMESPACE, never a rule: namespace targeting
(resolver.go:110 — default-namespace rules apply to everyone, other
rules only to requests addressed to their namespace) means a request's
visible rule set is `global ∪ rules(ns)`. Keeping each namespace whole
on one shard and replicating the global rules into every bank makes a
single bank sufficient for any request — the shard-routed check is
verdict-identical to the monolithic compile with NO cross-bank
combining per row.

Balance uses the same per-rule device-budget model the static analyzer
applies before compile (analysis/budget.py): all-EQ conjunctions cost
~2.5 int32-equivalent lanes per padded literal on the fused
gather-compare plane, everything else one int32 per literal on the
legacy plane, plus the rule's conjunction-index rows; predicates that
fall back to the host oracle carry a flat host cost (they burn python
per request, the scarcest serving resource). Namespaces are placed
LPT-greedy (largest predicted cost first onto the least-loaded shard)
— deterministic for a given rule list.
"""
from __future__ import annotations

import dataclasses
import time
import zlib
from typing import Sequence

import numpy as np

from istio_tpu.compiler.ruleset import (DEFAULT_DNF_CAP, _AtomTable,
                                        _decompose)
from istio_tpu.compiler.tensor_expr import HostFallback
from istio_tpu.expr.checker import AttributeDescriptorFinder

# flat predicted cost for a host-fallback rule: its oracle program runs
# interpreted python per request — weigh it like a fat device rule so
# fallback-heavy namespaces spread across shards instead of piling the
# host work onto one bank's overlay loop
HOST_FALLBACK_COST = 256.0
# a rule's conjunction-index rows (conj_m_idx + conj_n_idx) cost
# 2 int32 entries per conjunction column
RULE_ROW_COST = 2.0


class ShardPlanError(ValueError):
    """The requested plan cannot be built (bad shard count)."""


def predict_rule_costs(preds: Sequence, finder: AttributeDescriptorFinder,
                       dnf_cap: int = DEFAULT_DNF_CAP) -> np.ndarray:
    """Per-rule predicted device budget (float array, len(preds)) —
    the tile-entry model of analysis/budget.check_budgets applied per
    rule instead of per snapshot. `preds` are compiler Rule objects
    (ast or match string). Atoms dedup across rules exactly like the
    compiler (shared _AtomTable), so a namespace of near-identical
    predicates is priced by its real marginal index-tensor footprint,
    not a naive per-rule re-count."""
    from istio_tpu.analysis.budget import _eq_shaped
    from istio_tpu.compiler.ruleset import _rule_ast

    table = _AtomTable()
    eq_cache: dict[int, bool] = {}

    def atom_eq(aidx: int) -> bool:
        hit = eq_cache.get(aidx)
        if hit is None:
            hit = _eq_shaped(table.asts[aidx], finder)
            eq_cache[aidx] = hit
        return hit

    costs = np.zeros(max(len(preds), 1), np.float64)
    for ridx, rule in enumerate(preds):
        mark = table.mark()
        try:
            ast = _rule_ast(rule)
            m, n = _decompose(ast, table, dnf_cap)
        except HostFallback:
            table.revert(mark)
            costs[ridx] = HOST_FALLBACK_COST
            continue
        except Exception:
            table.revert(mark)
            costs[ridx] = HOST_FALLBACK_COST
            continue
        c = 0.0
        for conj in (m | n):
            lanes = max(len(conj), 1)
            if all(atom_eq(a) for a, _kind in conj):
                c += 2.5 * lanes          # fused eqc_* lanes
            else:
                c += float(lanes)         # legacy lit_idx row
        c += RULE_ROW_COST * max(len(m), len(n), 1)
        costs[ridx] = c
    return costs[:len(preds)]


def costs_from_ruleset(rs, finder: AttributeDescriptorFinder
                       ) -> np.ndarray:
    """Per-rule predicted costs from an ALREADY-COMPILED
    RuleSetProgram — the publish-path variant: compile_ruleset just
    ran the full decomposition and retained it (per_rule_dnf /
    atom_asts / host_fallback), so a 100k-rule config swap must not
    pay a second parse + DNF pass on the rebuild thread. Same cost
    model as predict_rule_costs (which remains the standalone entry
    for un-compiled rule lists)."""
    from istio_tpu.analysis.budget import _eq_shaped

    eq_cache: dict[int, bool] = {}

    def atom_eq(aidx: int) -> bool:
        hit = eq_cache.get(aidx)
        if hit is None:
            hit = _eq_shaped(rs.atom_asts[aidx], finder)
            eq_cache[aidx] = hit
        return hit

    n = len(rs.per_rule_dnf)
    costs = np.zeros(max(n, 1), np.float64)
    for ridx, mn in enumerate(rs.per_rule_dnf):
        if mn is None or ridx in rs.host_fallback:
            costs[ridx] = HOST_FALLBACK_COST
            continue
        m, nn = mn
        c = 0.0
        for conj in (m | nn):
            lanes = max(len(conj), 1)
            if all(atom_eq(a) for a, _kind in conj):
                c += 2.5 * lanes
            else:
                c += float(lanes)
        c += RULE_ROW_COST * max(len(m), len(nn), 1)
        costs[ridx] = c
    return costs[:n]


@dataclasses.dataclass
class ShardPlan:
    """A namespace → shard assignment plus its audit trail.

    `shard_rules[k]` holds the GLOBAL config-rule indices compiled
    into bank k, sorted ascending — global (default-namespace) rules
    replicated into every entry, so relative rule order (and therefore
    lowest-rule-index-wins status combining) is preserved inside each
    bank."""
    n_shards: int
    ns_to_shard: dict[str, int]
    shard_rules: list[list[int]]
    global_rules: list[int]
    shard_cost: list[float]
    ns_cost: dict[str, float]
    plan_wall_s: float = 0.0
    revision: int = 0
    # delta-planning audit trail (plan_shards(prev=...)): namespaces
    # the bounded rebalance relocated this generation (each one costs
    # a bank recompile on BOTH its old and new shard — the budget is
    # the knob that trades balance for republish latency), plus the
    # kept/new/removed accounting the stability tests pin
    moved_ns: list = dataclasses.field(default_factory=list)
    stability: dict = dataclasses.field(default_factory=dict)

    def shard_of(self, ns: str) -> int:
        """Bank for a request namespace. Namespaces the plan never saw
        (no rules configured for them — only global rules apply) hash
        stably onto a shard; crc32, not hash(), so routing agrees
        across processes/restarts regardless of PYTHONHASHSEED."""
        s = self.ns_to_shard.get(ns)
        if s is not None:
            return s
        return zlib.crc32(ns.encode("utf-8", "replace")) % self.n_shards

    def balance(self) -> dict:
        """Shard-balance summary — the fleet bench's
        `fleet_shard_balance` payload and the planner property tests'
        judged surface."""
        costs = [float(c) for c in self.shard_cost]
        mean = sum(costs) / max(len(costs), 1)
        ns_per = [0] * self.n_shards
        for s in self.ns_to_shard.values():
            ns_per[s] += 1
        return {
            "n_shards": self.n_shards,
            "rules_per_shard": [len(r) for r in self.shard_rules],
            "namespaces_per_shard": ns_per,
            "global_rules": len(self.global_rules),
            "cost_per_shard": [round(c, 1) for c in costs],
            "max_over_mean_cost": round(max(costs) / mean, 3)
            if mean > 0 else 1.0,
            "min_over_mean_cost": round(min(costs) / mean, 3)
            if mean > 0 else 1.0,
        }

    def to_json(self) -> dict:
        return {
            "revision": self.revision,
            "plan_wall_ms": round(self.plan_wall_s * 1e3, 3),
            "balance": self.balance(),
            "stability": dict(self.stability) or {"mode": "scratch"},
        }


def trivial_plan(n_lanes: int) -> ShardPlan:
    """The no-sharding plan replica-only serving routes through: K
    lane slots, no namespace assignments — shard_of() falls through to
    the stable hash, giving sticky-by-namespace lane selection without
    a compiled partition."""
    n = max(n_lanes, 1)
    return ShardPlan(n_shards=n, ns_to_shard={},
                     shard_rules=[[] for _ in range(n)],
                     global_rules=[], shard_cost=[0.0] * n, ns_cost={})


def plan_shards(preds: Sequence, finder: AttributeDescriptorFinder,
                n_shards: int,
                costs: np.ndarray | None = None,
                dnf_cap: int = DEFAULT_DNF_CAP,
                revision: int = 0,
                prev: ShardPlan | None = None,
                rebalance_budget: int = 0) -> ShardPlan:
    """Partition compiler Rule preds into an n_shards ShardPlan.

    Scratch mode (prev=None): LPT greedy — namespaces sorted by total
    predicted cost (descending, name tie-break) land on the currently
    least-loaded shard; the replicated global-rule cost is charged to
    every shard up front. Deterministic for a given (preds, n_shards).

    Delta mode (prev= a same-width plan): PLAN STABILITY is the
    contract — every namespace prev knows keeps its shard (its bank's
    content hash, and therefore the bank cache's carry-over decision,
    depends on exactly which namespaces share its bank), new
    namespaces LPT-place onto the least-loaded shard, removed ones
    simply vanish. An optional LPT rebalance then moves at most
    `rebalance_budget` namespaces (largest imbalance first, each move
    strictly reducing the max-shard cost) — every move recompiles two
    banks, so the budget is an explicit latency/balance trade, default
    0. Routing of unchanged namespaces is byte-identical to prev by
    construction (kept assignments + the same crc32 fallback)."""
    if n_shards < 1:
        raise ShardPlanError(f"n_shards must be >= 1, got {n_shards}")
    t0 = time.perf_counter()
    if costs is None:
        costs = predict_rule_costs(preds, finder, dnf_cap)
    by_ns: dict[str, list[int]] = {}
    global_rules: list[int] = []
    for ridx, rule in enumerate(preds):
        ns = getattr(rule, "namespace", "") or ""
        if ns:
            by_ns.setdefault(ns, []).append(ridx)
        else:
            global_rules.append(ridx)
    ns_cost = {ns: float(sum(costs[i] for i in idxs))
               for ns, idxs in by_ns.items()}
    global_cost = float(sum(costs[i] for i in global_rules))

    shard_cost = [global_cost] * n_shards
    shard_ns: list[list[str]] = [[] for _ in range(n_shards)]
    moved: list[str] = []
    stability: dict = {"mode": "scratch"}
    if prev is not None and prev.n_shards == n_shards \
            and prev.ns_to_shard:
        kept = {ns: prev.ns_to_shard[ns] for ns in by_ns
                if ns in prev.ns_to_shard}
        fresh = [ns for ns in by_ns if ns not in kept]
        removed = [ns for ns in prev.ns_to_shard if ns not in by_ns]
        for ns, k in kept.items():
            shard_cost[k] += ns_cost[ns]
            shard_ns[k].append(ns)
        for ns in sorted(fresh, key=lambda ns: (-ns_cost[ns], ns)):
            k = min(range(n_shards), key=lambda s: (shard_cost[s], s))
            shard_cost[k] += ns_cost[ns]
            shard_ns[k].append(ns)
        for _ in range(max(int(rebalance_budget), 0)):
            hi = max(range(n_shards), key=lambda s: (shard_cost[s], -s))
            lo = min(range(n_shards), key=lambda s: (shard_cost[s], s))
            gap = shard_cost[hi] - shard_cost[lo]
            # a move of cost c turns (hi, lo) into (hi-c, lo+c): it
            # strictly improves the pair's peak iff 0 < c < gap; the
            # best c is gap/2 (perfectly splitting the imbalance)
            cands = [ns for ns in shard_ns[hi]
                     if 0.0 < ns_cost[ns] < gap]
            if not cands:
                break
            ns = min(cands,
                     key=lambda x: (abs(ns_cost[x] - gap / 2.0), x))
            shard_ns[hi].remove(ns)
            shard_ns[lo].append(ns)
            shard_cost[hi] -= ns_cost[ns]
            shard_cost[lo] += ns_cost[ns]
            moved.append(ns)
        # a relocated FRESH namespace never sat on a shard before —
        # it costs one new-bank compile either way and must not be
        # booked as a previously-placed namespace churning off its
        # shard (only moves of KEPT namespaces cost two recompiles)
        moved_kept = [ns for ns in moved if ns in kept]
        stability = {"mode": "delta",
                     "kept": len(kept) - len(moved_kept),
                     "new": len(fresh), "removed": len(removed),
                     "moved": list(moved),
                     "moved_kept": moved_kept,
                     "rebalance_budget": int(rebalance_budget)}
    else:
        order = sorted(by_ns, key=lambda ns: (-ns_cost[ns], ns))
        for ns in order:
            k = min(range(n_shards), key=lambda s: (shard_cost[s], s))
            shard_cost[k] += ns_cost[ns]
            shard_ns[k].append(ns)
    ns_to_shard = {ns: k for k, nss in enumerate(shard_ns)
                   for ns in nss}
    shard_rules = []
    for k in range(n_shards):
        idxs = list(global_rules)
        for ns in shard_ns[k]:
            idxs.extend(by_ns[ns])
        shard_rules.append(sorted(idxs))
    return ShardPlan(n_shards=n_shards, ns_to_shard=ns_to_shard,
                     shard_rules=shard_rules,
                     global_rules=sorted(global_rules),
                     shard_cost=shard_cost, ns_cost=ns_cost,
                     plan_wall_s=time.perf_counter() - t0,
                     revision=revision,
                     moved_ns=moved, stability=stability)
