"""Shard banks — each shard compiled into a full serving stack.

A bank is NOT a thinner code path: it is a sub-Snapshot (the shard's
rules + every replicated global rule, sharing the parent's finder,
handlers, instances and InternTable) compiled through the SAME
pipeline the monolithic path uses — compile_ruleset for the predicate
program, build_fused_plan for the device engine (deny/list fusion,
host-overlay map, per-rule telemetry, canary recorder tap), a real
Dispatcher on top. Everything the serving plane learned in PRs 1-8
(stage decomposition, referenced-attribute bitmaps, oracle fallback,
quota activity bits) works per bank for free, and the oracle-parity
story reduces to the per-bank conformance the compiler tests already
pin.

Host-overlay rules are pinned to their home shard by construction
(assignment is by namespace; a rule's host actions and host-fallback
oracle program recompile inside its own bank). Quota rules route
correctly across banks because quota STATE never lives in a bank:
device quota pools are controller-owned, keyed by handler name, and
the bank's check response carries (bank dispatcher, bank-local active
quota rules) as its quota_context — exactly the contract
RuntimeServer.quota_fused already honors, so a global quota rule
replicated into every bank still allocates once per request from the
one shared pool. The in-step quota merge is the one quota shape that
CANNOT cross banks (one merged device program per pool) — the server
refuses it under sharding (instep_quota_target → None).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Mapping, Sequence

import numpy as np

from istio_tpu.compiler.layout import Tensorizer
from istio_tpu.compiler.ruleset import compile_ruleset
from istio_tpu.runtime.config import Snapshot
from istio_tpu.runtime.dispatcher import Dispatcher
from istio_tpu.sharding.planner import ShardPlan
from istio_tpu.utils.log import scope

log = scope("sharding.banks")


class ShardingUnsupported(RuntimeError):
    """The snapshot cannot shard (e.g. device-lowered rbac pseudo-rule
    rows reference absolute ruleset positions) — the server falls back
    to monolithic serving and says why."""


@dataclasses.dataclass
class ShardBank:
    """One compiled shard: sub-snapshot + dispatcher + index map."""
    shard_id: int
    snapshot: Snapshot
    dispatcher: Dispatcher
    # bank-local rule index → parent (global) config rule index; the
    # router's fold remaps deny attribution through this
    local_to_global: np.ndarray
    predicted_cost: float = 0.0
    # per-bank ResilientChecker (runtime/resilience.py): each bank is
    # its own device lease, so it carries its OWN circuit breaker +
    # CPU-oracle fallback over the bank's rules — a flapping bank
    # degrades to its oracle without touching its siblings. Wired by
    # RuntimeServer._rebuild_sharded (it owns the ResilienceConfig);
    # None = raw dispatcher.check (tests driving banks directly).
    checker: Any = None
    # delta-compilation bookkeeping (bank_content_key / the server's
    # content-addressed bank cache): the content hash this bank was
    # compiled for, and the config generation that compiled it — a
    # carried bank keeps built_revision while serving newer plans
    content_key: str = ""
    built_revision: int = 0

    def check(self, bags, deadline: float | None = None) -> list:
        """The router's per-bank entry: resilient when wired."""
        if self.checker is not None:
            return list(self.checker.run_batch(bags,
                                               deadline=deadline))
        return self.dispatcher.check(bags, deadline=deadline)

    @property
    def n_rules(self) -> int:
        return len(self.snapshot.rules)

    def bank_bytes(self) -> int:
        """Resident device bytes of the bank's compiled programs
        (ruleset index tensors + engine adapter banks) — the
        /debug/shards `bank_bytes` column."""
        total = 0
        plan = self.dispatcher.fused
        params: Mapping[str, Any] = plan.engine.params \
            if plan is not None else self.snapshot.ruleset.params
        for v in params.values():
            total += int(getattr(v, "nbytes", 0) or 0)
        return total

    def stats(self) -> dict:
        out = {
            "shard": self.shard_id,
            "rules": self.n_rules,
            "host_overlay_rules":
                len(self.dispatcher.fused.host_rule_idx)
                if self.dispatcher.fused is not None else 0,
            "bank_bytes": self.bank_bytes(),
            "predicted_cost": round(self.predicted_cost, 1),
            "built_revision": self.built_revision,
        }
        if self.content_key:
            out["content_key"] = self.content_key[:12]
        if self.checker is not None:
            out["breaker"] = self.checker.breaker.state
        return out


def snapshot_static_digest(parent: Snapshot, *, identity_attr: str,
                           buckets: Sequence[int],
                           rule_telemetry: bool) -> str:
    """Digest of the COMPILE-ENVIRONMENT inputs every bank of a
    snapshot shares: the attribute manifest (type decisions), the
    exact compile_ruleset kwargs (layout columns, byte slots, extern
    ingest, max_str_len, rule_pad), and the serving knobs baked into
    a bank's dispatcher at construction. Any change here invalidates
    EVERY bank — correct, because these are the inputs a compiled
    program cannot revalidate after the fact."""
    from istio_tpu.compiler.cache import manifest_digest, stable_digest

    return stable_digest({
        "manifest": manifest_digest(parent.finder),
        "compile_kwargs": parent.compile_kwargs,
        "identity_attr": identity_attr,
        "buckets": sorted(int(b) for b in buckets),
        "rule_telemetry": bool(rule_telemetry),
    })


def bank_content_key(parent: Snapshot, plan: ShardPlan, k: int,
                     static_digest: str) -> str:
    """Deterministic content hash of shard k's ruleset decomposition —
    THE key of the content-addressed bank cache. Covers, in bank-local
    rule order: each rule's name/namespaces/match source and its
    action wiring, plus the content digests of every handler and
    instance those actions reference, on top of the shared
    static digest (manifest + compile_kwargs + serving knobs). Global
    rules are replicated into every shard's list, so editing one
    changes every bank's key — the full-rebuild case, by design.
    Deliberately NOT covering global rule indices: a delta elsewhere
    in the config renumbers them without changing this bank's
    compiled artifact (rebind_bank refreshes the index map instead).
    """
    h = hashlib.sha256(static_digest.encode("ascii"))
    ref_handlers: set[str] = set()
    ref_instances: set[str] = set()
    for i in plan.shard_rules[k]:
        rc = parent.rules[i]
        pred = parent.ruleset.rules[i]
        h.update(json.dumps(
            [rc.name, rc.namespace, pred.namespace, rc.match,
             [[a.handler, list(a.instances)] for a in rc.actions]],
            sort_keys=True, separators=(",", ":")).encode("utf-8"))
        for a in rc.actions:
            ref_handlers.add(a.handler)
            ref_instances.update(a.instances)
    for name in sorted(ref_handlers):
        hc = parent.handlers.get(name)
        sig = hc.signature if hc is not None else "<missing>"
        h.update(f"H|{name}|{sig}".encode("utf-8"))
    for name in sorted(ref_instances):
        dig = parent.instance_digests.get(name, "<missing>")
        h.update(f"I|{name}|{dig}".encode("utf-8"))
    return h.hexdigest()


def rebind_bank(bank: ShardBank, plan: ShardPlan, k: int) -> ShardBank:
    """Carry a content-matched bank into a new generation's plan:
    the compiled artifact (sub-snapshot, dispatcher, prewarmed
    shapes, breaker, telemetry accumulators) is byte-equivalent by
    key, but the PARENT-side bookkeeping is not — global rule indices
    renumber under deltas elsewhere in the config, so the
    local→global map is rebuilt from the new plan (the bank's local
    rule order is ascending global order in both generations, and a
    matching content key pins the two sequences element-for-element).

    NOTE for banks that are LIVE on a serving generation:
    `local_to_global` is read by in-flight folds, so the server's
    rebuild path defers that one assignment until every fallible
    rebuild step is done (RuntimeServer._rebuild_sharded) — this
    convenience helper applies everything at once and is meant for
    banks not currently serving (tests, offline tools)."""
    bank.shard_id = k
    bank.local_to_global = np.asarray(plan.shard_rules[k], np.int64)
    bank.predicted_cost = float(plan.shard_cost[k]) \
        if plan.shard_cost else 0.0
    return bank


def shard_snapshot(parent: Snapshot, plan: ShardPlan,
                   k: int) -> tuple[Snapshot, np.ndarray]:
    """Compile shard k's sub-Snapshot → (snapshot, local_to_global).

    Shares the parent's finder/handlers/instances and — critically —
    its InternTable, so every bank agrees on constant ids and a bag
    tensorizes identically no matter which bank serves it. The rule
    list keeps ascending global order, so lowest-rule-index-wins
    status combining inside a bank equals the monolithic order
    restricted to the request's visible set."""
    if parent.n_config_rules != len(parent.ruleset.rules):
        raise ShardingUnsupported(
            "snapshot carries synthesized pseudo-rule rows (device-"
            "lowered rbac) that reference absolute ruleset positions; "
            "sharding such a snapshot would renumber them — serve it "
            "monolithically")
    idxs = plan.shard_rules[k]
    preds = [parent.ruleset.rules[i] for i in idxs]
    rules = [parent.rules[i] for i in idxs]
    interner = parent.ruleset.interner
    # the parent build just decomposed these exact predicates — the
    # shared DecompCache makes the sub-compile skip parse+DNF entirely
    ruleset = compile_ruleset(preds, parent.finder, interner=interner,
                              decomp_cache=parent.decomp_cache,
                              **parent.compile_kwargs)
    sub = Snapshot(
        revision=parent.revision, finder=parent.finder,
        handlers=parent.handlers, instances=parent.instances,
        instance_templates=parent.instance_templates,
        rules=rules, ruleset=ruleset,
        tensorizer=Tensorizer(ruleset.layout, interner),
        roles=[], bindings=[], errors=[],
        n_config_rules=len(rules), rbac_groups={},
        compile_kwargs=dict(parent.compile_kwargs))
    return sub, np.asarray(idxs, np.int64)


def compile_shard_bank(parent: Snapshot, handlers: Mapping[str, Any],
                       plan: ShardPlan, k: int, *,
                       identity_attr: str,
                       buckets: Sequence[int] = (),
                       rule_telemetry: bool = True,
                       recorder: Any = None,
                       executor: Any = None,
                       grants: Any = None,
                       overlap_h2d: bool = False) -> ShardBank:
    """Compile ONE shard of `plan` into a ShardBank — the unit the
    delta-compilation path pays per CHANGED shard (unchanged shards
    carry their previous bank via rebind_bank instead). `executor`:
    the server's AdapterExecutor — host-overlay rules pinned to this
    bank run their adapter work bulkheaded like the monolithic path
    (lanes are per HANDLER, shared across banks by design: the
    backend behind a handler is one resource however many banks call
    it). `grants`/`overlap_h2d`: the latency plane's GrantPolicy and
    staged-h2d flag, per-bank like the monolithic dispatcher."""
    from istio_tpu.runtime.fused import build_fused_plan

    sub, l2g = shard_snapshot(parent, plan, k)
    fused = build_fused_plan(sub, rule_telemetry=rule_telemetry)
    disp = Dispatcher(sub, handlers, identity_attr,
                      fused=fused, buckets=tuple(buckets),
                      recorder=recorder, executor=executor,
                      grants=grants, overlap_h2d=overlap_h2d)
    cost = float(plan.shard_cost[k]) if plan.shard_cost else 0.0
    return ShardBank(shard_id=k, snapshot=sub, dispatcher=disp,
                     local_to_global=l2g, predicted_cost=cost,
                     built_revision=parent.revision)


def build_shard_banks(parent: Snapshot,
                      handlers: Mapping[str, Any],
                      plan: ShardPlan, *,
                      identity_attr: str,
                      buckets: Sequence[int] = (),
                      rule_telemetry: bool = True,
                      recorder: Any = None,
                      executor: Any = None,
                      grants: Any = None,
                      overlap_h2d: bool = False) -> list[ShardBank]:
    """Compile every shard of `plan` into a ShardBank. Raises
    ShardingUnsupported when the snapshot cannot shard; individual
    bad rules never fail a bank (compile_ruleset demotes them to the
    bank's host-fallback oracle, same as monolithic)."""
    banks = [compile_shard_bank(parent, handlers, plan, k,
                                identity_attr=identity_attr,
                                buckets=buckets,
                                rule_telemetry=rule_telemetry,
                                recorder=recorder,
                                executor=executor,
                                grants=grants,
                                overlap_h2d=overlap_h2d)
             for k in range(plan.n_shards)]
    log.info("built %d shard banks (%s rules/bank, %d global rules "
             "replicated)", len(banks),
             "/".join(str(b.n_rules) for b in banks),
             len(plan.global_rules))
    return banks


def full_bank(parent: Snapshot, handlers: Mapping[str, Any],
              shard_id: int, *, identity_attr: str,
              buckets: Sequence[int] = (),
              rule_telemetry: bool = True,
              recorder: Any = None,
              dispatcher: Dispatcher | None = None,
              executor: Any = None,
              grants: Any = None,
              overlap_h2d: bool = False) -> ShardBank:
    """A bank over the WHOLE snapshot — the replica-only mode's lane
    executor (each replica owns its own FusedPlan over the full rule
    set). `dispatcher` reuses an existing one (lane 0 rides the
    controller's published dispatcher; other lanes compile their own
    plan so each owns its device lease)."""
    from istio_tpu.runtime.fused import build_fused_plan

    if dispatcher is None:
        fused = build_fused_plan(parent,
                                 rule_telemetry=rule_telemetry)
        dispatcher = Dispatcher(parent, handlers, identity_attr,
                                fused=fused, buckets=tuple(buckets),
                                recorder=recorder, executor=executor,
                                grants=grants,
                                overlap_h2d=overlap_h2d)
    return ShardBank(
        shard_id=shard_id, snapshot=parent, dispatcher=dispatcher,
        local_to_global=np.arange(len(parent.rules), dtype=np.int64),
        built_revision=parent.revision)
