"""Shard banks — each shard compiled into a full serving stack.

A bank is NOT a thinner code path: it is a sub-Snapshot (the shard's
rules + every replicated global rule, sharing the parent's finder,
handlers, instances and InternTable) compiled through the SAME
pipeline the monolithic path uses — compile_ruleset for the predicate
program, build_fused_plan for the device engine (deny/list fusion,
host-overlay map, per-rule telemetry, canary recorder tap), a real
Dispatcher on top. Everything the serving plane learned in PRs 1-8
(stage decomposition, referenced-attribute bitmaps, oracle fallback,
quota activity bits) works per bank for free, and the oracle-parity
story reduces to the per-bank conformance the compiler tests already
pin.

Host-overlay rules are pinned to their home shard by construction
(assignment is by namespace; a rule's host actions and host-fallback
oracle program recompile inside its own bank). Quota rules route
correctly across banks because quota STATE never lives in a bank:
device quota pools are controller-owned, keyed by handler name, and
the bank's check response carries (bank dispatcher, bank-local active
quota rules) as its quota_context — exactly the contract
RuntimeServer.quota_fused already honors, so a global quota rule
replicated into every bank still allocates once per request from the
one shared pool. The in-step quota merge is the one quota shape that
CANNOT cross banks (one merged device program per pool) — the server
refuses it under sharding (instep_quota_target → None).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Mapping, Sequence

import numpy as np

from istio_tpu.compiler.layout import Tensorizer
from istio_tpu.compiler.ruleset import compile_ruleset
from istio_tpu.runtime.config import Snapshot
from istio_tpu.runtime.dispatcher import Dispatcher
from istio_tpu.sharding.planner import ShardPlan
from istio_tpu.utils.log import scope

log = scope("sharding.banks")


class ShardingUnsupported(RuntimeError):
    """The snapshot cannot shard (e.g. device-lowered rbac pseudo-rule
    rows reference absolute ruleset positions) — the server falls back
    to monolithic serving and says why."""


@dataclasses.dataclass
class ShardBank:
    """One compiled shard: sub-snapshot + dispatcher + index map."""
    shard_id: int
    snapshot: Snapshot
    dispatcher: Dispatcher
    # bank-local rule index → parent (global) config rule index; the
    # router's fold remaps deny attribution through this
    local_to_global: np.ndarray
    predicted_cost: float = 0.0
    # per-bank ResilientChecker (runtime/resilience.py): each bank is
    # its own device lease, so it carries its OWN circuit breaker +
    # CPU-oracle fallback over the bank's rules — a flapping bank
    # degrades to its oracle without touching its siblings. Wired by
    # RuntimeServer._rebuild_sharded (it owns the ResilienceConfig);
    # None = raw dispatcher.check (tests driving banks directly).
    checker: Any = None

    def check(self, bags) -> list:
        """The router's per-bank entry: resilient when wired."""
        if self.checker is not None:
            return list(self.checker.run_batch(bags))
        return self.dispatcher.check(bags)

    @property
    def n_rules(self) -> int:
        return len(self.snapshot.rules)

    def bank_bytes(self) -> int:
        """Resident device bytes of the bank's compiled programs
        (ruleset index tensors + engine adapter banks) — the
        /debug/shards `bank_bytes` column."""
        total = 0
        plan = self.dispatcher.fused
        params: Mapping[str, Any] = plan.engine.params \
            if plan is not None else self.snapshot.ruleset.params
        for v in params.values():
            total += int(getattr(v, "nbytes", 0) or 0)
        return total

    def stats(self) -> dict:
        out = {
            "shard": self.shard_id,
            "rules": self.n_rules,
            "host_overlay_rules":
                len(self.dispatcher.fused.host_rule_idx)
                if self.dispatcher.fused is not None else 0,
            "bank_bytes": self.bank_bytes(),
            "predicted_cost": round(self.predicted_cost, 1),
        }
        if self.checker is not None:
            out["breaker"] = self.checker.breaker.state
        return out


def shard_snapshot(parent: Snapshot, plan: ShardPlan,
                   k: int) -> tuple[Snapshot, np.ndarray]:
    """Compile shard k's sub-Snapshot → (snapshot, local_to_global).

    Shares the parent's finder/handlers/instances and — critically —
    its InternTable, so every bank agrees on constant ids and a bag
    tensorizes identically no matter which bank serves it. The rule
    list keeps ascending global order, so lowest-rule-index-wins
    status combining inside a bank equals the monolithic order
    restricted to the request's visible set."""
    if parent.n_config_rules != len(parent.ruleset.rules):
        raise ShardingUnsupported(
            "snapshot carries synthesized pseudo-rule rows (device-"
            "lowered rbac) that reference absolute ruleset positions; "
            "sharding such a snapshot would renumber them — serve it "
            "monolithically")
    idxs = plan.shard_rules[k]
    preds = [parent.ruleset.rules[i] for i in idxs]
    rules = [parent.rules[i] for i in idxs]
    interner = parent.ruleset.interner
    ruleset = compile_ruleset(preds, parent.finder, interner=interner,
                              **parent.compile_kwargs)
    sub = Snapshot(
        revision=parent.revision, finder=parent.finder,
        handlers=parent.handlers, instances=parent.instances,
        instance_templates=parent.instance_templates,
        rules=rules, ruleset=ruleset,
        tensorizer=Tensorizer(ruleset.layout, interner),
        roles=[], bindings=[], errors=[],
        n_config_rules=len(rules), rbac_groups={},
        compile_kwargs=dict(parent.compile_kwargs))
    return sub, np.asarray(idxs, np.int64)


def build_shard_banks(parent: Snapshot,
                      handlers: Mapping[str, Any],
                      plan: ShardPlan, *,
                      identity_attr: str,
                      buckets: Sequence[int] = (),
                      rule_telemetry: bool = True,
                      recorder: Any = None) -> list[ShardBank]:
    """Compile every shard of `plan` into a ShardBank. Raises
    ShardingUnsupported when the snapshot cannot shard; individual
    bad rules never fail a bank (compile_ruleset demotes them to the
    bank's host-fallback oracle, same as monolithic)."""
    from istio_tpu.runtime.fused import build_fused_plan

    banks: list[ShardBank] = []
    for k in range(plan.n_shards):
        sub, l2g = shard_snapshot(parent, plan, k)
        fused = build_fused_plan(sub, rule_telemetry=rule_telemetry)
        disp = Dispatcher(sub, handlers, identity_attr,
                          fused=fused, buckets=tuple(buckets),
                          recorder=recorder)
        cost = float(plan.shard_cost[k]) if plan.shard_cost else 0.0
        banks.append(ShardBank(shard_id=k, snapshot=sub,
                               dispatcher=disp, local_to_global=l2g,
                               predicted_cost=cost))
    log.info("built %d shard banks (%s rules/bank, %d global rules "
             "replicated)", len(banks),
             "/".join(str(b.n_rules) for b in banks),
             len(plan.global_rules))
    return banks


def full_bank(parent: Snapshot, handlers: Mapping[str, Any],
              shard_id: int, *, identity_attr: str,
              buckets: Sequence[int] = (),
              rule_telemetry: bool = True,
              recorder: Any = None,
              dispatcher: Dispatcher | None = None) -> ShardBank:
    """A bank over the WHOLE snapshot — the replica-only mode's lane
    executor (each replica owns its own FusedPlan over the full rule
    set). `dispatcher` reuses an existing one (lane 0 rides the
    controller's published dispatcher; other lanes compile their own
    plan so each owns its device lease)."""
    from istio_tpu.runtime.fused import build_fused_plan

    if dispatcher is None:
        fused = build_fused_plan(parent,
                                 rule_telemetry=rule_telemetry)
        dispatcher = Dispatcher(parent, handlers, identity_attr,
                                fused=fused, buckets=tuple(buckets),
                                recorder=recorder)
    return ShardBank(
        shard_id=shard_id, snapshot=parent, dispatcher=dispatcher,
        local_to_global=np.arange(len(parent.rules), dtype=np.int64))
