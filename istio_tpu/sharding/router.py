"""Shard-routed dispatch + replica-parallel serving lanes.

ShardRouter is a lane's run_batch hook: trim padding, bucket each row
to its namespace's bank (planner.ShardPlan.shard_of — the SAME routing
decision the front's lane selector makes, so a row can never arrive at
a router that does not own its bank), run each bank's full fused check
on its sub-batch, then FOLD: scatter responses back into row order and
remap device deny attribution from bank-local to global rule indices.
Zero rows are ever dropped by construction — the fold raises (and the
batcher's belt resolves every future) if any bank returns short.

ReplicaRouter is the front: N CheckBatcher serving lanes behind the
one RuntimeServer.batcher attribute every wire front and introspect
surface already reads. Lane selection is sticky by namespace
(shard_of(ns) % n_replicas), so one namespace's traffic coalesces into
one lane's batches — batches arrive at the router already shard-pure
under real traffic, and a namespace's requests keep FIFO order within
their lane. Admission control (queue caps, deadlines, brownout,
drain/quiesce lifecycle) is per lane via the existing CheckBatcher.

Stage attribution (runtime/monitor.py SHARD_STAGES):
  shard_dispatch  — namespace extraction + row bucketing, per batch
  bank_check      — one observation per (batch, bank) device trip
  fold            — response scatter + deny-index remap, per batch
"""
from __future__ import annotations

import time
from typing import Any, Mapping, Sequence

from istio_tpu.runtime import monitor
from istio_tpu.runtime.batcher import (CheckBatcher, pad_to_bucket,
                                       trim_pads)
from istio_tpu.runtime.dispatcher import _namespace_of
from istio_tpu.sharding.banks import ShardBank
from istio_tpu.sharding.planner import ShardPlan


class ShardRouter:
    """Route a batch's rows to their banks, fold the verdicts."""

    def __init__(self, banks: Mapping[int, ShardBank], plan: ShardPlan,
                 identity_attr: str, replica: int = 0):
        import threading

        self.banks = dict(banks)
        self.plan = plan
        self.identity_attr = identity_attr
        self.replica = replica
        # rows served per bank — under ONE lock: a router serves a
        # lane's pipelined workers AND pre-batched callers
        # (check_many / BatchCheck) concurrently, and the smoke/bench
        # row-conservation gates are exact, so lost increments would
        # read as phantom drops (one lock acquisition per batch/bank,
        # never per row)
        self._stats_lock = threading.Lock()
        self.rows_routed: dict[int, int] = {s: 0 for s in self.banks}
        self.batches = 0
        self.misrouted = 0

    def check(self, bags: Sequence,
              deadline: float | None = None) -> list:
        """The lane's run_batch hook — returns exactly one
        CheckResponse per (non-padding) input row, in input order.
        `deadline`: the batch's min remaining absolute instant,
        threaded to each bank's host-action fold (executor plane)."""
        bags = trim_pads(list(bags))
        if not bags:
            return []
        t0 = time.perf_counter()
        groups: dict[int, list[int]] = {}
        for i, bag in enumerate(bags):
            ns = _namespace_of(bag, self.identity_attr)
            shard = self.plan.shard_of(ns)
            bank = self.banks.get(shard)
            if bank is None:
                # a row this router's bank set cannot serve: a routing
                # contract violation, never a silent drop — counted,
                # then raised so the batch resolves with a typed error
                with self._stats_lock:
                    self.misrouted += 1
                raise RuntimeError(
                    f"row routed to shard {shard} but this replica "
                    f"owns banks {sorted(self.banks)}")
            groups.setdefault(shard, []).append(i)
        monitor.observe_shard_stage("shard_dispatch",
                                    time.perf_counter() - t0)
        with self._stats_lock:
            self.batches += 1
        out: list = [None] * len(bags)
        fold_s = 0.0
        for shard in sorted(groups):
            idxs = groups[shard]
            bank = self.banks[shard]
            buckets = bank.dispatcher.buckets
            # chunk to the bank's largest prewarmed bucket: a lane can
            # form batches past it (explicit small buckets under a big
            # max_batch), and an over-bucket sub-batch would trace a
            # fresh XLA shape in-band
            cap = buckets[-1] if buckets else len(idxs) or 1
            resp: list = []
            t1 = time.perf_counter()
            for lo in range(0, len(idxs), cap):
                chunk = [bags[i] for i in idxs[lo:lo + cap]]
                padded = pad_to_bucket(chunk, buckets) \
                    if buckets else chunk
                # bank.check rides the bank's OWN ResilientChecker
                # when wired: retry → per-bank breaker → the bank's
                # CPU-oracle fallback — a faulting bank answers
                # correctly (slower) instead of failing the batch
                resp.extend(bank.check(padded, deadline=deadline))
            t2 = time.perf_counter()
            monitor.observe_shard_stage("bank_check", t2 - t1)
            if len(resp) < len(idxs):
                raise RuntimeError(
                    f"bank {shard} returned {len(resp)} responses "
                    f"for {len(idxs)} rows")
            l2g = bank.local_to_global
            for i, r in zip(idxs, resp):
                dr = r.deny_rule
                if dr >= 0 and dr < len(l2g):
                    r.deny_rule = int(l2g[dr])
                out[i] = r
            with self._stats_lock:
                self.rows_routed[shard] = \
                    self.rows_routed.get(shard, 0) + len(idxs)
            fold_s += time.perf_counter() - t2
        monitor.observe_shard_stage("fold", fold_s)
        monitor.observe_replica_batch(self.replica,
                                      time.perf_counter() - t0,
                                      len(bags))
        return out

    def stats(self) -> dict:
        with self._stats_lock:
            rows = dict(self.rows_routed)
            batches = self.batches
            misrouted = self.misrouted
        total = sum(rows.values())
        return {
            "replica": self.replica,
            "batches": batches,
            "rows": total,
            "misrouted": misrouted,
            "rows_per_shard": {str(s): n for s, n
                               in sorted(rows.items())},
            "occupancy": {str(s): round(n / total, 4) if total else 0.0
                          for s, n in sorted(rows.items())},
        }


class ReplicaRouter:
    """N serving lanes behind the one front — a drop-in for the
    RuntimeServer.batcher attribute (submit/check/stats/healthy/
    quiesce/drain/close), routing each submit to its namespace's
    sticky lane. Lanes persist across config swaps: a swap builds
    fresh banks/routers off-path and publishes them with one atomic
    list assignment (set_routers), so queued requests drain onto the
    NEW snapshot's banks and nothing is dropped mid-swap."""

    def __init__(self, n_replicas: int, identity_attr: str,
                 batcher_kwargs: dict):
        self.n_replicas = max(n_replicas, 1)
        self.identity_attr = identity_attr
        self._plan: ShardPlan | None = None
        self._routers: list[ShardRouter] = []
        kw = dict(batcher_kwargs)
        # the router re-pads per bank — lane-level padding would only
        # be trimmed again
        kw["pad_batches"] = False
        # cumulative routing counters folded from RETIRED router
        # generations (set_routers): /debug/shards' conservation and
        # misroute numbers must survive config swaps, not reset with
        # each generation's fresh routers
        self._retired_rows: dict[str, int] = {}
        self._retired_misrouted = 0
        self.lanes = [
            CheckBatcher(self._make_run(i), **kw)
            for i in range(self.n_replicas)]

    def _make_run(self, lane: int):
        def run(bags, deadline=None):
            routers = self._routers
            if not routers:
                raise RuntimeError("replica router has no published "
                                   "shard routers yet")
            return routers[lane % len(routers)].check(
                bags, deadline=deadline)
        return run

    # -- publication (config swaps fan here) --------------------------

    def set_routers(self, routers: list[ShardRouter],
                    plan: ShardPlan) -> None:
        """Atomic publish: one reference assignment (GIL) swaps every
        lane onto the new banks — a batch in flight finishes on the
        routers it started with, the next batch serves the new
        snapshot. The outgoing generation's routing counters fold
        into the cumulative retired totals first (counts from a batch
        still finishing on an old router after this fold are the only
        loss — bounded by the in-flight window, never a reset)."""
        for r in self._routers:
            st = r.stats()
            self._retired_misrouted += st["misrouted"]
            for s, n in st["rows_per_shard"].items():
                self._retired_rows[s] = \
                    self._retired_rows.get(s, 0) + n
        self._plan = plan
        self._routers = list(routers)

    @property
    def routers(self) -> list[ShardRouter]:
        return self._routers

    # -- the CheckBatcher-compatible front surface --------------------

    @property
    def buckets(self):
        return self.lanes[0].buckets

    @property
    def max_batch(self):
        return self.lanes[0].max_batch

    @property
    def window_s(self):
        return self.lanes[0].window_s

    @property
    def max_queue(self):
        return self.lanes[0].max_queue

    @property
    def _closed(self) -> bool:
        return all(lane._closed for lane in self.lanes)

    def lane_of(self, bag) -> int:
        """Sticky-by-namespace lane selection — the same shard_of
        decision the router makes, folded onto the lane count, so a
        namespace's shard and its lane never disagree."""
        plan = self._plan
        ns = _namespace_of(bag, self.identity_attr)
        if plan is None:
            return 0
        return plan.shard_of(ns) % self.n_replicas

    def submit(self, bag, trace: Any = None, deadline=None):
        return self.lanes[self.lane_of(bag)].submit(
            bag, trace=trace, deadline=deadline)

    def check(self, bag, deadline=None):
        return self.submit(bag, deadline=deadline).result()

    def healthy(self) -> tuple[bool, str]:
        for i, lane in enumerate(self.lanes):
            ok, err = lane.healthy()
            if not ok:
                return False, f"replica {i}: {err}"
        return True, ""

    def routing_stats(self) -> dict:
        """Cross-lane routing aggregate — THE single home of the
        rows-per-shard / occupancy / misroute fold every consumer
        reads (introspect /debug/shards, the fleet bench, the shard
        smoke's conservation gates)."""
        rows: dict[str, int] = dict(self._retired_rows)
        misrouted = self._retired_misrouted
        for r in self._routers:
            st = r.stats()
            misrouted += st["misrouted"]
            for s, n in st["rows_per_shard"].items():
                rows[s] = rows.get(s, 0) + n
        total = sum(rows.values())
        return {
            "rows_per_shard": dict(sorted(rows.items())),
            "occupancy": {s: round(n / total, 4) if total else 0.0
                          for s, n in sorted(rows.items())},
            "rows_total": total,
            "misrouted": misrouted,
        }

    def stats(self) -> dict:
        per = [lane.stats() for lane in self.lanes]
        ok, err = self.healthy()
        agg = {
            "depth": sum(p["depth"] for p in per),
            "oldest_wait_ms": max(p["oldest_wait_ms"] for p in per),
            "in_flight": sum(p["in_flight"] for p in per),
            "pipeline": per[0]["pipeline"],
            "hold_at": per[0]["hold_at"],
            "window_s": per[0]["window_s"],
            "max_batch": per[0]["max_batch"],
            "buckets": per[0]["buckets"],
            "closed": self._closed,
            "draining": all(p["draining"] for p in per),
            "max_queue": per[0]["max_queue"],
            "brownout": per[0]["brownout"],
            "healthy": ok,
            "health_error": err,
            "replicas": per,
            "n_replicas": self.n_replicas,
        }
        return agg

    # -- lifecycle (the PR 7 ordering: admission → drain → close) -----

    def quiesce(self) -> None:
        for lane in self.lanes:
            lane.quiesce()

    def drain(self, deadline: float | None = 5.0) -> bool:
        end = None if deadline is None \
            else time.perf_counter() + deadline
        ok = True
        for lane in self.lanes:
            left = None if end is None \
                else max(end - time.perf_counter(), 0.0)
            ok = lane.drain(left) and ok
        return ok

    def close(self) -> None:
        for lane in self.lanes:
            lane.close()
