"""Oracle parity surface for the sharded serving plane.

The shard smoke gate (scripts/shard_smoke.py), the fleet bench
(bench.py) and the sharding property tests all judge the sharded path
against the SAME independent derivation: per-rule predicate truth via
the compiler's SnapshotOracle programs (the conformance oracle every
device program is pinned against) and per-rule check statuses via
compiler/ruleset.fused_check_status (the one host-side decision-status
truth the rulestats and canary verification surfaces already share).

Namespace visibility is applied by INDEX, not by walking all N rules
per bag — at 100k+ rules the smoke's recount must stay seconds, and
`global rules ∪ rules(ns)` is exactly the visible set the resolver
semantics define — but the per-rule evaluation is the SnapshotOracle's
own OracleProgram, unchanged.
"""
from __future__ import annotations

from typing import Sequence

from istio_tpu.runtime.dispatcher import DEFAULT_IDENTITY_ATTR


def oracle_check_statuses(snapshot, plan, bags: Sequence,
                          identity_attr: str = DEFAULT_IDENTITY_ATTR
                          ) -> list[dict]:
    """Expected device-path check outcome per bag:

      {"status": int,        # lowest-active-rule non-OK fused status
       "deny_rule": int,     # that rule's GLOBAL index (-1 when OK)
       "active": [int, ...], # matched, namespace-visible rule idxs
       "errors": int}        # visible predicates that raised

    `plan` is the PARENT (monolithic) FusedPlan — its deny_info /
    list_rules are global-index keyed, which is what the sharded
    fold's remapped deny_rule must agree with."""
    from istio_tpu.compiler.ruleset import (SnapshotOracle,
                                            fused_check_status)
    from istio_tpu.runtime.dispatcher import _namespace_of

    rs = snapshot.ruleset
    n_cfg = len(snapshot.rules)
    oracle = SnapshotOracle(
        rs.rules[:n_cfg], snapshot.finder,
        seed={r: p for r, p in rs.host_fallback.items() if r < n_cfg})
    by_ns: dict[str, list[int]] = {}
    global_idx: list[int] = []
    for ridx in range(n_cfg):
        ns = oracle.rules[ridx].namespace
        if ns:
            by_ns.setdefault(ns, []).append(ridx)
        else:
            global_idx.append(ridx)

    out: list[dict] = []
    for bag in bags:
        req_ns = _namespace_of(bag, identity_attr)
        visible = sorted(global_idx + by_ns.get(req_ns, []))
        active: list[int] = []
        errors = 0
        status, deny_rule = 0, -1
        for ridx in visible:
            try:
                matched = bool(oracle._prog(ridx).evaluate(bag))
            except Exception:
                errors += 1
                continue
            if not matched:
                continue
            active.append(ridx)
            if status == 0:
                s = fused_check_status(snapshot, plan, ridx, bag)
                if s != 0:
                    status, deny_rule = s, ridx
        out.append({"status": status, "deny_rule": deny_rule,
                    "active": active, "errors": errors})
    return out
