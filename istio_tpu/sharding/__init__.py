"""Sharded serving plane — namespace-sharded compiled banks behind
replica-parallel serving lanes.

The scale-out analog of the reference resolver's namespace-scoped rule
resolution (mixer/pkg/runtime/resolver.go builds per-namespace rule
lists so a request only walks the rules that can apply to it): here a
snapshot's rules are PARTITIONED by namespace into K model-parallel
banks, each compiled through the existing compiler/ruleset.py pipeline
into its own RuleSetProgram + FusedPlan, and a shard-aware dispatch
path routes each batch row to its namespace's bank and folds the
per-shard verdicts back into row order — verdict-identical to the
monolithic compile by construction (a request's visible rule set =
default-namespace rules + its namespace's rules, and every bank holds
exactly that set for its namespaces).

Layers (each its own module):

  planner.py  ShardPlan / plan_shards — namespaces packed onto K
              shards balanced by the predicted device budget of their
              rules (the analysis/budget.py tile-entry cost model)
  banks.py    shard sub-snapshots + ShardBank — each shard compiled
              into its own Snapshot/RuleSetProgram/FusedPlan/
              Dispatcher (the full serving machinery per bank: deny/
              list fusion, host overlay, telemetry, canary tap)
  router.py   ShardRouter (per-batch route → per-bank check → fold)
              and ReplicaRouter (N CheckBatcher serving lanes behind
              one front, sticky-by-namespace)
  parity.py   SnapshotOracle-backed expected statuses — the exact
              parity surface the shard smoke gate and fleet bench
              judge the sharded path against
"""
from istio_tpu.sharding.planner import (ShardPlan, ShardPlanError,
                                        plan_shards, predict_rule_costs)
from istio_tpu.sharding.banks import (ShardBank, ShardingUnsupported,
                                      bank_content_key,
                                      build_shard_banks,
                                      compile_shard_bank, rebind_bank,
                                      shard_snapshot,
                                      snapshot_static_digest)
from istio_tpu.sharding.router import ReplicaRouter, ShardRouter
from istio_tpu.sharding.parity import oracle_check_statuses

__all__ = [
    "ShardPlan", "ShardPlanError", "plan_shards", "predict_rule_costs",
    "ShardBank", "ShardingUnsupported", "bank_content_key",
    "build_shard_banks", "compile_shard_bank", "rebind_bank",
    "shard_snapshot", "snapshot_static_digest",
    "ReplicaRouter", "ShardRouter", "oracle_check_statuses",
]
