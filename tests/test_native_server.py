"""Native C++ front-end e2e: a real python-grpcio client against
native/httpd.cpp over localhost — the interop proof that the C++
HTTP/2+HPACK+gRPC wire speaks the REAL unary istio.mixer.v1 protocol
(grpcio encodes HPACK with Huffman + dynamic-table state, so a passing
run exercises the full decoder, not just the happy literal path).

Parity oracle: MixerGrpcServer over the same snapshot must produce
byte-equal PreconditionResults for the same requests.

Reference pattern: mixer/pkg/api tests (grpcServer.go:118 Check,
:262 Report).
"""
import threading

import pytest

from istio_tpu.api import MixerClient, MixerGrpcServer
from istio_tpu.api.native_server import NativeMixerServer
from istio_tpu.models.policy_engine import NOT_FOUND, OK
from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs


def _store() -> MemStore:
    s = MemStore()
    s.set(("handler", "istio-system", "wl"), {
        "adapter": "list", "params": {"overrides": ["v1", "v2"]}})
    s.set(("handler", "istio-system", "mq"), {
        "adapter": "memquota",
        "params": {"quotas": [{"name": "rq.istio-system",
                               "max_amount": 3,
                               "valid_duration_s": 600.0}]}})
    s.set(("instance", "istio-system", "ver"), {
        "template": "listentry",
        "params": {"value": 'source.labels["version"] | "none"'}})
    s.set(("instance", "istio-system", "rq"), {
        "template": "quota", "params": {"dimensions": {}}})
    s.set(("rule", "istio-system", "r"), {
        "match": "",
        "actions": [{"handler": "wl", "instances": ["ver"]},
                    {"handler": "mq", "instances": ["rq"]}]})
    return s


@pytest.fixture(scope="module")
def rig():
    runtime = RuntimeServer(_store(), ServerArgs(batch_window_s=0.001,
                                                 max_batch=64))
    native = NativeMixerServer(runtime, min_fill=8, window_us=500)
    nport = native.start()
    oracle = MixerGrpcServer(runtime)
    oport = oracle.start()
    nclient = MixerClient(f"127.0.0.1:{nport}",
                          enable_check_cache=False)
    oclient = MixerClient(f"127.0.0.1:{oport}",
                          enable_check_cache=False)
    yield runtime, native, nclient, oclient
    nclient.close()
    oclient.close()
    native.stop()
    oracle.stop()
    runtime.close()


def test_check_allow_and_deny(rig):
    _, _, client, _ = rig
    ok = client.check({"destination.service": "a.b.svc",
                       "source.labels": {"version": "v1"}})
    assert ok.precondition.status.code == OK
    assert ok.precondition.valid_use_count > 0
    bad = client.check({"destination.service": "a.b.svc",
                        "source.labels": {"version": "v7"}})
    assert bad.precondition.status.code == NOT_FOUND
    assert "rejected" in bad.precondition.status.message


def test_parity_with_grpc_front(rig):
    _, _, nclient, oclient = rig
    for values in (
            {"destination.service": "a.b.svc",
             "source.labels": {"version": "v1"}},
            {"destination.service": "a.b.svc",
             "source.labels": {"version": "nope"}},
            {"destination.service": "x.y.svc"},
    ):
        got = nclient.check(values)
        want = oclient.check(values)
        assert got.precondition.SerializeToString() == \
            want.precondition.SerializeToString(), values


def test_quota_loop_and_dedup(rig):
    _, _, client, _ = rig
    r = client.check({"destination.service": "q.b.svc",
                      "source.labels": {"version": "v1"}},
                     quotas={"rq": 2})
    assert r.quotas["rq"].granted_amount == 2
    r2 = client.check({"destination.service": "q.b.svc",
                       "source.labels": {"version": "v1"}},
                      quotas={"rq": 5})
    assert r2.quotas["rq"].granted_amount == 1    # best-effort remainder
    r3 = client.check({"destination.service": "q.b.svc",
                       "source.labels": {"version": "v1"}},
                      quotas={"rq": 2}, dedup_id="same-rpc")
    r4 = client.check({"destination.service": "q.b.svc",
                       "source.labels": {"version": "v1"}},
                      quotas={"rq": 2}, dedup_id="same-rpc")
    assert r3.quotas["rq"].granted_amount == \
        r4.quotas["rq"].granted_amount


def test_report(rig):
    _, _, client, _ = rig
    # delta-coded Report through the native wire must not error
    client.report([
        {"destination.service": "a.b.svc", "response.code": 200},
        {"destination.service": "a.b.svc", "response.code": 404},
    ])


def test_unknown_method_unimplemented(rig):
    import grpc

    _, native, _, _ = rig
    channel = grpc.insecure_channel(f"127.0.0.1:{native.port}")
    rpc = channel.unary_unary("/istio.mixer.v1.Mixer/Nope",
                              request_serializer=lambda b: b,
                              response_deserializer=lambda b: b)
    with pytest.raises(grpc.RpcError) as exc_info:
        rpc(b"")
    assert exc_info.value.code() == grpc.StatusCode.UNIMPLEMENTED
    channel.close()


def test_concurrent_checks(rig):
    """64 concurrent unary checks from 8 threads: batches form, every
    caller gets its own verdict back (tag routing under load)."""
    _, native, client, _ = rig
    errors: list = []

    def worker(version: str, expect_ok: bool):
        try:
            for _ in range(8):
                r = client.check({"destination.service": "a.b.svc",
                                  "source.labels": {"version": version}})
                code = r.precondition.status.code
                if expect_ok:
                    assert code == OK, code
                else:
                    assert code == NOT_FOUND, code
        except Exception as exc:   # surfaced in the main thread
            errors.append(exc)

    threads = [threading.Thread(target=worker,
                                args=("v1", True) if i % 2 == 0
                                else ("bad", False))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors, errors
    c = native.counters()
    assert c["requests_decoded"] >= 64
    assert c["responses_sent"] >= 64
    assert c["in_flight"] == 0
