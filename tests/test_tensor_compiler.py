"""Tensor-compiler conformance: the SAME corpus the oracle passes, run
through the jitted device path (one table, many engines — the reference's
il/testing pattern). Also checks batched evaluation agreement on mixed
inputs."""
import numpy as np
import pytest

from istio_tpu.attribute.bag import DictBag
from istio_tpu.compiler.layout import InternTable, Tensorizer, build_layout
from istio_tpu.compiler.tensor_expr import (HostFallback, collect_requirements,
                                            compile_expression)
from istio_tpu.expr.checker import AttributeDescriptorFinder
from istio_tpu.expr.oracle import EvalError, OracleProgram
from istio_tpu.expr.parser import parse
from istio_tpu.testing.corpus import CORPUS, CORPUS_MANIFEST, Case

FINDER = AttributeDescriptorFinder(CORPUS_MANIFEST)

RUNNABLE = [c for c in CORPUS if c.compile_err is None]

# The EXPLICIT allowlist of corpus expressions the device may refuse
# (VERDICT r2 item 6: skips must be enumerated and asserted, so a
# lowering regression FAILS instead of silently skipping). Every entry
# is a construct with no device analog: dynamic string-map keys (the
# map payload never rides to the device), runtime regex patterns
# (regex→DFA compilation is host work), and whole-map equality.
ALLOWED_FALLBACK = frozenset([
    'request.header[headername] == "aaa"',   # dynamic map key
    'ar[as] | "dflt"',                        # dynamic map key
    'ar[as] | "d"',                           # dynamic map key
    'ar[as]',                                 # dynamic map key
    'as.matches("st.*")',                     # runtime regex pattern
    'ar == ar2',                              # whole-map equality
    'ar != ar2',                              # whole-map equality
])


def _try_compile(case: Case, interner: InternTable):
    reqs = collect_requirements(parse(case.e), FINDER)
    layout = build_layout(CORPUS_MANIFEST, sorted(reqs.derived_keys),
                          sorted(reqs.byte_sources, key=str),
                          extern_sources=[
                              (n, k, ast) for (n, k), ast
                              in reqs.extern_sources.items()])
    prog = compile_expression(case.e, FINDER, layout, interner, jit=False)
    return layout, prog


@pytest.mark.parametrize("case", RUNNABLE, ids=lambda c: c.id())
def test_corpus_tensor_parity(case: Case):
    interner = InternTable()
    try:
        layout, prog = _try_compile(case, interner)
    except HostFallback as exc:
        assert case.e in ALLOWED_FALLBACK, (
            f"{case.e!r} used to lower to the device but now falls "
            f"back ({exc}) — lowering regression")
        pytest.skip("allowlisted host-fallback (oracle handles it)")

    bag = DictBag(case.input)
    batch = Tensorizer(layout, interner).tensorize([bag])
    val, valid = prog(batch)

    oracle = OracleProgram(case.e, FINDER)
    try:
        want = oracle.evaluate(bag)
        want_valid = True
    except EvalError:
        want, want_valid = None, False

    assert bool(valid[0]) == want_valid, (
        f"{case.e}: device valid={bool(valid[0])}, oracle valid={want_valid}")
    if want_valid:
        got = prog.decode_value(np.asarray(val)[0], batch)
        assert got == want, f"{case.e}: device {got!r} != oracle {want!r}"


def test_ordered_compare_edge_values():
    """Review r3 repros: a malformed (string) payload under a numeric
    attr must err per-row like the oracle, never crash the batch; and
    -0.0 orders identically to +0.0 (IEEE)."""
    interner = InternTable()
    for expr, rows, wants in [
        ("x > 2", [{"x": 3}, {"x": "junk"}, {"x": 1}],
         [True, None, False]),            # None = oracle error
        ("ad < 0.0", [{"ad": -0.0}], [False]),
        ("ad >= 0.0", [{"ad": -0.0}], [True]),
        ("ad < 0.5", [{"ad": float('nan')}], [False]),
        ("ad >= 0.5", [{"ad": float('nan')}], [False]),
    ]:
        reqs = collect_requirements(parse(expr), FINDER)
        layout = build_layout(CORPUS_MANIFEST,
                              sorted(reqs.derived_keys),
                              sorted(reqs.byte_sources, key=str))
        prog = compile_expression(expr, FINDER, layout, interner,
                                  jit=False)
        batch = Tensorizer(layout, interner).tensorize(
            [DictBag(r) for r in rows])
        val, valid = prog(batch)
        oracle = OracleProgram(expr, FINDER)
        for i, (row, want) in enumerate(zip(rows, wants)):
            try:
                ow = oracle.evaluate(DictBag(row))
            except EvalError:
                ow = None
            assert ow == want, f"{expr} row {i}: oracle gave {ow}"
            if want is None:
                assert not bool(valid[i]), f"{expr} row {i}"
            else:
                assert bool(valid[i]), f"{expr} row {i}"
                assert bool(np.asarray(val)[i]) == want, f"{expr} {i}"


def test_fallback_allowlist_is_tight():
    """Every allowlist entry still genuinely falls back — entries that
    start lowering must be REMOVED so coverage claims stay honest."""
    still = set()
    for case in RUNNABLE:
        if case.e not in ALLOWED_FALLBACK:
            continue
        try:
            _try_compile(case, InternTable())
        except HostFallback:
            still.add(case.e)
    assert still == ALLOWED_FALLBACK & {c.e for c in RUNNABLE}, (
        "stale allowlist entries now lower: "
        f"{ALLOWED_FALLBACK - still}")


def test_batched_mixed_inputs():
    """One compiled program, many heterogeneous bags in one batch —
    the whole point of the TPU path."""
    expr = ('destination.service == "db.svc" && '
            '(source.labels["app"] | "none") != "blocked"')
    interner = InternTable()
    reqs = collect_requirements(parse(expr), FINDER)
    layout = build_layout(CORPUS_MANIFEST, sorted(reqs.derived_keys),
                         sorted(reqs.byte_sources, key=str))
    prog = compile_expression(expr, FINDER, layout, interner)

    bags = [
        DictBag({"destination.service": "db.svc",
                 "source.labels": {"app": "x"}}),          # True
        DictBag({"destination.service": "db.svc",
                 "source.labels": {"app": "blocked"}}),    # False
        DictBag({"destination.service": "db.svc"}),        # fallback → True
        DictBag({"source.labels": {"app": "x"}}),          # dest absent → err
        DictBag({"destination.service": "other.svc"}),     # False (short-circuit)
    ]
    batch = Tensorizer(layout, interner).tensorize(bags)
    val, valid = prog(batch)
    val, valid = np.asarray(val), np.asarray(valid)

    oracle = OracleProgram(expr, FINDER)
    for i, bag in enumerate(bags):
        try:
            want, ok = oracle.evaluate(bag), True
        except EvalError:
            want, ok = None, False
        assert bool(valid[i]) == ok, f"row {i}"
        if ok:
            assert bool(val[i]) == want, f"row {i}"


def test_regex_and_glob_on_device():
    expr = ('"^/api/v[0-9]+/.*".matches(request.path) || '
            'match(destination.service, "*.cluster.local")')
    interner = InternTable()
    reqs = collect_requirements(parse(expr), FINDER)
    layout = build_layout(CORPUS_MANIFEST, sorted(reqs.derived_keys),
                         sorted(reqs.byte_sources, key=str))
    prog = compile_expression(expr, FINDER, layout, interner)

    rows = [
        ({"request.path": "/api/v1/x", "destination.service": "a.b"}, True),
        ({"request.path": "/web", "destination.service": "a.cluster.local"},
         True),
        ({"request.path": "/web", "destination.service": "a.b"}, False),
    ]
    batch = Tensorizer(layout, interner).tensorize(
        [DictBag(r[0]) for r in rows])
    val, valid = prog(batch)
    for i, (_, want) in enumerate(rows):
        assert bool(valid[i])
        assert bool(np.asarray(val)[i]) == want, f"row {i}"


def test_host_fallback_cases_raise():
    # dynamic map keys and runtime regex patterns have no device
    # analog (match()/startsWith/endsWith with runtime patterns lower
    # via bytes_ops.dyn_*_match; runtime ip()/timestamp() lower via
    # ingest-converted extern columns)
    for text in ["request.header[headername]",
                 "as.matches(as2)",
                 "ar[as]"]:
        with pytest.raises(HostFallback):
            collect_requirements(parse(text), FINDER)


def test_truncation_routes_to_host():
    """Strings past max_str_len are truncated in the byte plane; a
    predicate whose answer depends on the missing tail must come back
    invalid (the serving path then routes the row to the host oracle)
    rather than silently answering from the truncated prefix."""
    interner = InternTable()
    reqs = collect_requirements(parse('as.endsWith("fix")'), FINDER)
    layout = build_layout(CORPUS_MANIFEST, sorted(reqs.derived_keys),
                          sorted(reqs.byte_sources, key=str),
                          max_str_len=16)
    prog = compile_expression('as.endsWith("fix")', FINDER, layout,
                              interner, jit=False)
    tz = Tensorizer(layout, interner)
    long_hit = "x" * 40 + "fix"          # truncated at 16 bytes
    short_hit = "prefix"
    batch = tz.tensorize([DictBag({"as": long_hit}),
                          DictBag({"as": short_hit}),
                          DictBag({"as": "nope"})])
    val, valid = prog(batch)
    assert not bool(np.asarray(valid)[0])      # undecidable → host
    assert bool(np.asarray(valid)[1]) and bool(np.asarray(val)[1])
    assert bool(np.asarray(valid)[2]) and not bool(np.asarray(val)[2])
    # the oracle (full string) stays the source of truth for row 0
    assert OracleProgram('as.endsWith("fix")', FINDER).evaluate(
        DictBag({"as": long_hit})) is True


def test_truncation_safe_for_prefix_checks():
    """startsWith and prefix globs only read the head — truncation
    never invalidates them."""
    interner = InternTable()
    text = 'as.startsWith("xx")'
    reqs = collect_requirements(parse(text), FINDER)
    layout = build_layout(CORPUS_MANIFEST, sorted(reqs.derived_keys),
                          sorted(reqs.byte_sources, key=str),
                          max_str_len=16)
    prog = compile_expression(text, FINDER, layout, interner, jit=False)
    tz = Tensorizer(layout, interner)
    batch = tz.tensorize([DictBag({"as": "xx" + "y" * 40}),
                          DictBag({"as": "zz" + "y" * 40})])
    val, valid = prog(batch)
    assert bool(np.asarray(valid)[0]) and bool(np.asarray(val)[0])
    assert bool(np.asarray(valid)[1]) and not bool(np.asarray(val)[1])


def test_truncation_regex_hit_is_reliable_miss_is_not():
    """Unanchored regex: a hit inside the stored prefix proves a hit
    in the full string; a miss on a truncated row is undecidable; a
    $-anchored regex is undecidable on every truncated row."""
    interner = InternTable()
    text = '"ab".matches(as)'
    reqs = collect_requirements(parse(text), FINDER)
    layout = build_layout(CORPUS_MANIFEST, sorted(reqs.derived_keys),
                          sorted(reqs.byte_sources, key=str),
                          max_str_len=16)
    prog = compile_expression(text, FINDER, layout, interner, jit=False)
    tz = Tensorizer(layout, interner)
    batch = tz.tensorize([DictBag({"as": "ab" + "z" * 40}),   # hit, trunc
                          DictBag({"as": "z" * 40}),          # miss, trunc
                          DictBag({"as": "zz"})])             # miss, short
    val, valid = prog(batch)
    assert bool(np.asarray(valid)[0]) and bool(np.asarray(val)[0])
    assert not bool(np.asarray(valid)[1])
    assert bool(np.asarray(valid)[2]) and not bool(np.asarray(val)[2])

    anchored = '"ab$".matches(as)'
    prog2 = compile_expression(anchored, FINDER, layout, interner,
                               jit=False)
    batch2 = tz.tensorize([DictBag({"as": "z" * 14 + "ab"})])  # 16 bytes
    _, valid2 = prog2(batch2)
    assert not bool(np.asarray(valid2)[0])  # could anchor at trunc point
