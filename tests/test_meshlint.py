"""Unit tests for istio_tpu/analysis/meshlint: call-graph resolution,
lock-graph extraction (with Condition aliasing and witness chains),
pragma honoring, inferred hot-path reachability, metric discipline,
and the typed-rejection escape analysis — all on synthetic in-memory
module sets, the same surface the fixture corpus rides."""
import textwrap

import pytest

from istio_tpu.analysis.findings import Severity
from istio_tpu.analysis.meshlint import (callgraph, hotpath, lockorder,
                                         metricspass, model,
                                         rejections, run_meshlint)


def _universe(**mods):
    return callgraph.Universe.from_sources(
        {name: textwrap.dedent(src) for name, src in mods.items()})


# ---------------------------------------------------------------------------
# call graph


class TestCallGraph:
    def test_self_method_and_module_function_resolution(self):
        u = _universe(m='''
            def helper():
                pass

            class C:
                def a(self):
                    self.b()
                    helper()

                def b(self):
                    pass
        ''')
        fi = u.find("C.a")
        callees = {u.functions[c].qual for _, c in u.calls_in(fi)}
        assert callees == {"C.b", "helper"}

    def test_attr_type_inference_from_constructor(self):
        u = _universe(m='''
            class Inner:
                def work(self):
                    pass

            class Outer:
                def __init__(self):
                    self.inner = Inner()

                def go(self):
                    self.inner.work()
        ''')
        callees = {u.functions[c].qual
                   for _, c in u.calls_in(u.find("Outer.go"))}
        assert callees == {"Inner.work"}

    def test_cross_module_import_resolution(self):
        u = _universe(
            a='''
                def shared():
                    pass
            ''',
            b='''
                from a import shared

                def caller():
                    shared()
            ''')
        callees = {c for _, c in u.calls_in(u.find("caller"))}
        assert callees == {"a:shared"}

    def test_local_variable_constructor_type(self):
        u = _universe(m='''
            class Worker:
                def run(self):
                    pass

            def main():
                w = Worker()
                w.run()
        ''')
        callees = {u.functions[c].qual
                   for _, c in u.calls_in(u.find("main"))}
        assert "Worker.run" in callees

    def test_nested_class_in_function_indexed(self):
        # the discovery/introspect stdlib-Handler pattern
        u = _universe(m='''
            class Server:
                def start(self):
                    class Handler:
                        def do_GET(self):
                            pass
                    return Handler
        ''')
        assert u.find("Server.start.Handler.do_GET") is not None

    def test_base_class_method_resolution(self):
        u = _universe(m='''
            class Base:
                def shared(self):
                    pass

            class Child(Base):
                def go(self):
                    self.shared()
        ''')
        callees = {u.functions[c].qual
                   for _, c in u.calls_in(u.find("Child.go"))}
        assert callees == {"Base.shared"}


# ---------------------------------------------------------------------------
# lock-order pass


class TestLockOrder:
    def _report(self, **mods):
        u = _universe(**mods)
        report = model.MeshlintReport()
        graph = lockorder.run(u, report)
        return u, report, graph

    def test_declaration_extraction_and_condition_alias(self):
        u, _, g = self._report(m='''
            import threading

            class P:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._wake = threading.Condition(self._lock)
                    self._own_cv = threading.Condition()
        ''')
        assert "P._lock" in g.decls
        assert g.decls["P._wake"].alias_of == "P._lock"
        assert g.canonical("P._wake") == "P._lock"
        assert g.decls["P._own_cv"].alias_of is None

    def test_nested_acquisition_produces_edge_with_chain(self):
        _, report, g = self._report(m='''
            import threading

            class P:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def go(self):
                    with self._a:
                        self._grab()

                def _grab(self):
                    with self._b:
                        pass
        ''')
        pairs = {(e.outer, e.inner) for e in g.edges}
        assert ("P._a", "P._b") in pairs
        edge = next(e for e in g.edges
                    if (e.outer, e.inner) == ("P._a", "P._b"))
        # the witness replays the cross-function acquisition chain
        assert len(edge.chain) == 2
        assert "calls P._grab" in edge.chain[0]
        assert "acquires P._b" in edge.chain[1]

    def test_cycle_detected(self):
        _, report, _ = self._report(m='''
            import threading

            class X:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def fwd(self):
                    with self._a:
                        with self._b:
                            pass

                def rev(self):
                    with self._b:
                        with self._a:
                            pass
        ''')
        assert model.LOCK_CYCLE in report.codes()

    def test_inversion_of_declared_order(self):
        _, report, _ = self._report(m='''
            import threading

            class DeviceQuotaPool:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._counts_lock = threading.Lock()

                def bad(self):
                    with self._lock:
                        with self._counts_lock:
                            pass
        ''')
        inv = [f for f in report.findings
               if f.code == model.LOCK_INVERSION]
        assert inv and inv[0].severity == Severity.ERROR
        assert inv[0].line > 0

    def test_leaf_lock_violation(self):
        _, report, _ = self._report(m='''
            import threading

            class ShardRouter:
                def __init__(self):
                    self._stats_lock = threading.Lock()
                    self._other = threading.Lock()

                def bad(self):
                    with self._stats_lock:
                        with self._other:
                            pass
        ''')
        assert model.LOCK_LEAF in report.codes()

    def test_self_deadlock_lexical_only(self):
        _, report, _ = self._report(m='''
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad(self):
                    with self._lock:
                        with self._lock:
                            pass

                def fine_cross_instance(self, other: "W"):
                    with self._lock:
                        other.touch()

                def touch(self):
                    with self._lock:
                        pass
        ''')
        selfs = [f for f in report.findings
                 if f.code == model.LOCK_SELF]
        # the lexical re-entry in bad() — and ONLY it (the
        # cross-frame edge is usually another instance)
        assert len(selfs) == 1
        assert selfs[0].func == "W.bad"

    def test_rlock_reentry_allowed(self):
        _, report, _ = self._report(m='''
            import threading

            class R:
                def __init__(self):
                    self._lock = threading.RLock()

                def fine(self):
                    with self._lock:
                        with self._lock:
                            pass
        ''')
        assert model.LOCK_SELF not in report.codes()

    def test_lock_ok_pragma_suppresses(self):
        _, report, _ = self._report(m='''
            import threading

            class ShardRouter:
                def __init__(self):
                    self._stats_lock = threading.Lock()
                    self._other = threading.Lock()

                def annotated(self):
                    with self._stats_lock:
                        with self._other:   # meshlint: lock-ok test
                            pass
        ''')
        assert model.LOCK_LEAF not in report.codes()

    def test_manual_acquire_release_pairs(self):
        _, report, g = self._report(m='''
            import threading

            class Q:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def go(self):
                    self._a.acquire()
                    with self._b:
                        pass
                    self._a.release()
                    with self._b:
                        pass
        ''')
        pairs = {(e.outer, e.inner) for e in g.edges}
        assert ("Q._a", "Q._b") in pairs
        # after release, the second `with` holds nothing
        assert len([e for e in g.edges
                    if (e.outer, e.inner) == ("Q._a", "Q._b")]) == 1


# ---------------------------------------------------------------------------
# hot-path pass


class TestHotpath:
    def test_reachability_covers_transitive_callees(self):
        u = _universe(m='''
            import time

            class E:
                def entry(self):
                    self._mid()

                def _mid(self):
                    self._leaf()

                def _leaf(self):
                    time.sleep(1)
        ''')
        report = model.MeshlintReport()
        hotpath.run(u, report, roots=("E.entry",), dynamic_edges=(),
                    cold=frozenset())
        syncs = [f for f in report.findings
                 if f.code == model.HOTPATH_SYNC]
        assert len(syncs) == 1
        assert syncs[0].func == "E._leaf"
        # witness chain: entry → _mid → _leaf
        assert len(syncs[0].chain) == 3

    def test_sync_ok_pragma_honored(self):
        u = _universe(m='''
            import numpy as np

            class E:
                def entry(self, dev):
                    return np.asarray(dev)   # hotpath: sync-ok pull
        ''')
        report = model.MeshlintReport()
        hotpath.run(u, report, roots=("E.entry",), dynamic_edges=(),
                    cold=frozenset())
        assert model.HOTPATH_SYNC not in report.codes()

    def test_dynamic_edge_extends_reachability(self):
        u = _universe(m='''
            class A:
                def entry(self):
                    cb = self._cb
                    cb()

                def hidden(self):
                    print("boom")
        ''')
        report = model.MeshlintReport()
        hotpath.run(u, report, roots=("A.entry",),
                    dynamic_edges=(("A.entry", "A.hidden"),),
                    cold=frozenset())
        assert model.HOTPATH_SYNC in report.codes()

    def test_missing_root_is_config_error(self):
        u = _universe(m="def real(): pass")
        report = model.MeshlintReport()
        hotpath.run(u, report, roots=("gone",), dynamic_edges=(),
                    cold=frozenset())
        assert model.HOTPATH_ROOT_MISSING in report.codes()

    def test_host_accessor_casts_allowed(self):
        u = _universe(m='''
            class E:
                def entry(self, spec, dev):
                    ok = int(spec.get("port", 80))
                    bad = float(dev.sum())
                    return ok, bad
        ''')
        report = model.MeshlintReport()
        hotpath.run(u, report, roots=("E.entry",), dynamic_edges=(),
                    cold=frozenset())
        msgs = [f.message for f in report.findings]
        assert any("float(<call>)" in m for m in msgs)
        assert not any("int(<call>)" in m for m in msgs)


# ---------------------------------------------------------------------------
# metric pass


class TestMetrics:
    def _report(self, src):
        u = _universe(mx=src)
        report = model.MeshlintReport()
        metricspass.run(u, report)
        return report

    def test_unshaped_labeled_prom_family_flagged(self):
        report = self._report('''
            import prometheus_client
            BAD = prometheus_client.Counter(
                "bad_total", "h", ["reason"])
        ''')
        assert model.METRIC_ZERO_SHAPE in report.codes()

    def test_pretouch_loop_over_module_constant_satisfies(self):
        report = self._report('''
            import prometheus_client
            REASONS = ("a", "b")
            GOOD = prometheus_client.Counter(
                "good_total", "h", ["reason"])
            for _r in REASONS:
                GOOD.labels(reason=_r)
        ''')
        assert model.METRIC_ZERO_SHAPE not in report.codes()

    def test_unlabeled_prom_and_gauges_exempt(self):
        report = self._report('''
            import prometheus_client
            from istio_tpu.utils import metrics as hostmetrics
            PLAIN = prometheus_client.Counter("plain_total", "h")
            G = prometheus_client.Gauge("g", "h", ["x"])
            HG = hostmetrics.default_registry.gauge("hg", "h")
            HH = hostmetrics.default_registry.histogram("hh", "h")
        ''')
        assert model.METRIC_ZERO_SHAPE not in report.codes()

    def test_host_counter_needs_zero_touch(self):
        report = self._report('''
            from istio_tpu.utils import metrics as hostmetrics
            NAKED = hostmetrics.default_registry.counter("n", "h")
        ''')
        assert model.METRIC_ZERO_SHAPE in report.codes()

    def test_label_mismatch_flagged(self):
        report = self._report('''
            import prometheus_client
            FAM = prometheus_client.Counter("f", "h", ["reason"])
            FAM.labels(reason="x")

            def use():
                FAM.labels(cause="y").inc()
        ''')
        mism = [f for f in report.findings
                if f.code == model.METRIC_LABEL_MISMATCH]
        assert len(mism) == 1

    def test_unregistered_receiver_flagged(self):
        report = self._report('''
            THING = object()

            def use():
                THING.inc(1)
        ''')
        assert model.METRIC_UNREGISTERED in report.codes()

    def test_unshaped_series_warning(self):
        report = self._report('''
            import prometheus_client
            FAM = prometheus_client.Counter("f", "h", ["reason"])
            for _r in ("a", "b"):
                FAM.labels(reason=_r)

            def use():
                FAM.labels(reason="zzz").inc()
        ''')
        series = [f for f in report.findings
                  if f.code == model.METRIC_UNSHAPED_SERIES]
        assert len(series) == 1
        assert series[0].severity == Severity.WARNING

    def test_metric_ok_pragma_suppresses(self):
        report = self._report('''
            import prometheus_client
            DYN = prometheus_client.Counter(   # meshlint: metric-ok dyn
                "dyn_total", "h", ["path"])
        ''')
        assert model.METRIC_ZERO_SHAPE not in report.codes()


# ---------------------------------------------------------------------------
# rejection pass


class TestRejections:
    def _report(self, src, boundaries):
        u = _universe(front=src)
        report = model.MeshlintReport()
        rejections.run(u, report, boundaries=boundaries)
        return report

    def test_untyped_in_universe_escape_flagged_with_chain(self):
        report = self._report('''
            class CheckRejected(RuntimeError):
                grpc_code = 2

            class Bad(Exception):
                pass

            class F:
                def handler(self, req):
                    try:
                        return self._serve(req)
                    except CheckRejected:
                        return None

                def _serve(self, req):
                    raise Bad("nope")
        ''', boundaries=(("front", "F.handler"),))
        esc = [f for f in report.findings
               if f.code == model.UNTYPED_ESCAPE]
        assert len(esc) == 1
        assert "Bad" in esc[0].message
        assert len(esc[0].chain) == 2       # handler → _serve raise

    def test_typed_escape_is_fine(self):
        report = self._report('''
            class CheckRejected(RuntimeError):
                grpc_code = 2

            class Shed(CheckRejected):
                grpc_code = 8

            class F:
                def handler(self, req):
                    raise Shed("over quota")
        ''', boundaries=(("front", "F.handler"),))
        assert model.UNTYPED_ESCAPE not in report.codes()

    def test_catch_all_swallows(self):
        report = self._report('''
            class Bad(Exception):
                pass

            class F:
                def handler(self, req):
                    try:
                        raise Bad("x")
                    except Exception:
                        return None
        ''', boundaries=(("front", "F.handler"),))
        assert model.UNTYPED_ESCAPE not in report.codes()

    def test_catch_by_base_class_swallows(self):
        report = self._report('''
            class Bad(RuntimeError):
                pass

            class F:
                def handler(self, req):
                    try:
                        raise Bad("x")
                    except RuntimeError:
                        return None
        ''', boundaries=(("front", "F.handler"),))
        assert model.UNTYPED_ESCAPE not in report.codes()

    def test_bare_reraise_inside_handler_tracked(self):
        report = self._report('''
            class Bad(Exception):
                pass

            class F:
                def handler(self, req):
                    try:
                        raise Bad("x")
                    except Bad:
                        raise
        ''', boundaries=(("front", "F.handler"),))
        assert model.UNTYPED_ESCAPE in report.codes()

    def test_raise_ok_pragma_suppresses(self):
        report = self._report('''
            class F:
                def handler(self, req):
                    raise ValueError("on purpose")   # meshlint: raise-ok t
        ''', boundaries=(("front", "F.handler"),))
        assert model.UNTYPED_ESCAPE not in report.codes()

    def test_deep_builtin_not_judged_at_boundary(self):
        # builtins are only flagged raised DIRECTLY in the boundary
        report = self._report('''
            class F:
                def handler(self, req):
                    return self._deep(req)

                def _deep(self, req):
                    raise ValueError("programming error path")
        ''', boundaries=(("front", "F.handler"),))
        assert model.UNTYPED_ESCAPE not in report.codes()

    def test_missing_boundary_is_config_error(self):
        report = self._report("def f(): pass",
                              boundaries=(("front", "Gone.handler"),))
        assert model.BOUNDARY_MISSING in report.codes()


# ---------------------------------------------------------------------------
# driver


class TestDriver:
    def test_run_meshlint_requires_input(self):
        with pytest.raises(ValueError):
            run_meshlint()

    def test_report_json_roundtrip(self):
        report = run_meshlint(
            sources={"m": "def f():\n    pass\n"},
            passes=("lock",))
        d = report.to_dict()
        assert d["n_errors"] == 0
        assert "findings" in d and "stats" in d

    def test_findings_sorted_errors_first(self):
        report = run_meshlint(sources={"m": textwrap.dedent('''
            import threading

            class ShardRouter:
                def __init__(self):
                    self._stats_lock = threading.Lock()
                    self._x = threading.Lock()
                    self._y = threading.Lock()

                def bad(self):
                    with self._stats_lock:
                        with self._x:
                            pass

                def meh(self):
                    with self._x:
                        with self._y:
                            pass
        ''')}, passes=("lock",))
        sevs = [f.severity for f in report.findings]
        assert sevs == sorted(sevs, key=lambda s: -int(s))
        assert report.has_errors
