"""Broker OSB API, sidecar injection, tracing, CLI surface."""
import json
import urllib.request
import urllib.error

import pytest
import yaml

from istio_tpu.broker import BrokerServer
from istio_tpu.pilot.inject import (InjectParams, inject_pod,
                                    inject_required, into_resource_file)
from istio_tpu.utils.tracing import MemoryReporter, Tracer


CATALOG = [{"id": "svc-1", "name": "reviews", "bindable": True,
            "plans": [{"id": "plan-1", "name": "default"}]}]


def _req(method, url, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=5) as r:
            return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_broker_osb_lifecycle():
    broker = BrokerServer(CATALOG)
    port = broker.start()
    base = f"http://127.0.0.1:{port}"
    try:
        code, cat = _req("GET", f"{base}/v2/catalog")
        assert code == 200 and cat["services"][0]["name"] == "reviews"
        code, _ = _req("PUT", f"{base}/v2/service_instances/i1",
                       {"service_id": "svc-1", "plan_id": "plan-1"})
        assert code == 201
        code, _ = _req("PUT", f"{base}/v2/service_instances/i1",
                       {"service_id": "svc-1", "plan_id": "plan-1"})
        assert code == 200                      # idempotent re-provision
        code, _ = _req("PUT", f"{base}/v2/service_instances/i2",
                       {"service_id": "nope"})
        assert code == 400
        code, _ = _req("PUT",
                       f"{base}/v2/service_instances/i1/service_bindings/b1",
                       {"service_id": "svc-1"})
        assert code == 201
        code, _ = _req("DELETE",
                       f"{base}/v2/service_instances/i1/service_bindings/b1")
        assert code == 200
        code, _ = _req("DELETE", f"{base}/v2/service_instances/i1")
        assert code == 200
        code, _ = _req("DELETE", f"{base}/v2/service_instances/i1")
        assert code == 410
    finally:
        broker.stop()


POD = {"kind": "Pod",
       "metadata": {"name": "web", "namespace": "default"},
       "spec": {"containers": [{"name": "app", "image": "web:1"}]}}


def test_inject_policy():
    params = InjectParams()
    assert inject_required(params, POD["spec"], POD["metadata"])
    assert not inject_required(params, {"hostNetwork": True}, {})
    assert not inject_required(
        params, POD["spec"],
        {"annotations": {"sidecar.istio.io/inject": "false"}})
    opt_in = InjectParams(policy="disabled")
    assert not inject_required(opt_in, POD["spec"], POD["metadata"])
    assert inject_required(
        opt_in, POD["spec"],
        {"annotations": {"sidecar.istio.io/inject": "true"}})


def test_inject_pod_idempotent():
    out = inject_pod(InjectParams(), POD)
    names = [c["name"] for c in out["spec"]["containers"]]
    assert names == ["app", "istio-proxy"]
    assert out["spec"]["initContainers"][0]["name"] == "istio-init"
    assert out["metadata"]["annotations"][
        "sidecar.istio.io/status"] == "injected"
    again = inject_pod(InjectParams(), out)
    assert len(again["spec"]["containers"]) == 2    # no double inject
    # original untouched
    assert [c["name"] for c in POD["spec"]["containers"]] == ["app"]


def test_into_resource_file_deployment():
    deployment = {"kind": "Deployment",
                  "metadata": {"name": "web"},
                  "spec": {"template": dict(POD, kind=None)}}
    out_yaml = into_resource_file(InjectParams(),
                                  yaml.safe_dump(deployment))
    out = list(yaml.safe_load_all(out_yaml))[0]
    tmpl = out["spec"]["template"]
    assert any(c["name"] == "istio-proxy"
               for c in tmpl["spec"]["containers"])


def test_tracer_spans_nest():
    rep = MemoryReporter()
    tracer = Tracer(reporter=rep)
    with tracer.span("check", rpc="Check"):
        with tracer.span("resolve"):
            pass
    assert len(rep.spans) == 2
    child, parent = rep.spans
    assert child["name"] == "resolve"
    assert child["parentId"] == parent["id"]
    assert child["traceId"] == parent["traceId"]
    assert parent["tags"]["rpc"] == "Check"


def test_cli_parser_covers_all_tools():
    from istio_tpu.cmd.__main__ import build_parser
    parser = build_parser()
    for argv in (["mixc", "check"],
                 ["istioctl", "get", "route-rule"],
                 ["mixs"], ["pilot-discovery"], ["brks"],
                 ["brkcol", "--config-store", "/tmp/x"],
                 ["node-agent", "--identity", "spiffe://c/ns/a/sa/b"]):
        args = parser.parse_args(argv)
        assert callable(args.fn)


def test_brkcol_collects_catalog(tmp_path, capsys):
    """brkcol (broker/cmd/brkcol): the collector assembles the same
    OSB catalog a serving brks would from the store's service-class /
    service-plan kinds — smoke the CLI end to end over an FsStore."""
    import json as _json

    from istio_tpu.cmd.__main__ import main
    docs = [
        {"kind": "service-class",
         "metadata": {"name": "db", "namespace": "default"},
         "spec": {"deployment": {"instance": "postgres"},
                  "entry": {"name": "db", "id": "svc-1",
                            "description": "managed db"}}},
        {"kind": "service-plan",
         "metadata": {"name": "small", "namespace": "default"},
         "spec": {"plan": {"name": "small", "id": "plan-1",
                           "description": "1 cpu"},
                  "services": ["db"]}},
    ]
    (tmp_path / "broker.yaml").write_text(
        yaml.safe_dump_all(docs))
    assert main(["brkcol", "--config-store", str(tmp_path),
                 "--json"]) == 0
    out = _json.loads(capsys.readouterr().out)
    assert out["service_classes"] == ["default/db"]
    assert out["service_plans"] == ["default/small"]
    [svc] = out["catalog"]["services"]
    assert svc["id"] == "svc-1"
    assert [p["id"] for p in svc.get("plans", [])] == ["plan-1"]
    # human-readable mode exits 0 too
    assert main(["brkcol", "--config-store", str(tmp_path)]) == 0
    assert "1 catalog service(s)" in capsys.readouterr().out


def test_istioctl_create_get_delete(tmp_path):
    from istio_tpu.cmd.__main__ import main
    rule = {"kind": "route-rule",
            "metadata": {"name": "r1", "namespace": "default"},
            "spec": {"destination": {"name": "reviews"},
                     "route": [{"labels": {"version": "v1"}}]}}
    f = tmp_path / "rule.yaml"
    f.write_text(yaml.safe_dump(rule))
    assert main(["istioctl", "create", "-f", str(f),
                 "--config-dir", str(tmp_path)]) == 0
    assert (tmp_path / "route-rule-default-r1.yaml").exists()
    assert main(["istioctl", "create", "-f", str(f),
                 "--config-dir", str(tmp_path)]) == 1   # already exists
    assert main(["istioctl", "delete", "--config-dir", str(tmp_path),
                 "route-rule", "r1", "-n", "default"]) == 0
    # invalid config rejected
    bad = {"kind": "route-rule", "metadata": {"name": "bad"},
           "spec": {"route": [{"weight": 50}]}}
    fb = tmp_path / "bad.yaml"
    fb.write_text(yaml.safe_dump(bad))
    assert main(["istioctl", "create", "-f", str(fb),
                 "--config-dir", str(tmp_path)]) == 1


def test_istioctl_register_deregister(tmp_path):
    from istio_tpu.cmd.__main__ import main
    reg = tmp_path / "registry.yaml"
    # create-on-register with explicit ports
    assert main(["istioctl", "register", "--registry-file", str(reg),
                 "--ports", "http:9080", "reviews.default.svc",
                 "10.0.0.9"]) == 0
    world = yaml.safe_load(reg.read_text())
    svc = world["services"][0]
    assert svc["hostname"] == "reviews.default.svc"
    assert svc["ports"] == [{"name": "http", "port": 9080}]
    assert svc["endpoints"] == [{"address": "10.0.0.9"}]
    # endpoint dedup + port reconcile on existing service
    assert main(["istioctl", "register", "--registry-file", str(reg),
                 "--ports", "grpc:9090", "reviews.default.svc",
                 "10.0.0.9"]) == 0
    svc = yaml.safe_load(reg.read_text())["services"][0]
    assert len(svc["endpoints"]) == 1
    assert {p["name"] for p in svc["ports"]} == {"http", "grpc"}
    # deregister removes the endpoint; unknown service errors
    assert main(["istioctl", "deregister", "--registry-file", str(reg),
                 "reviews.default.svc", "10.0.0.9"]) == 0
    assert yaml.safe_load(reg.read_text())["services"][0]["endpoints"] \
        == []
    assert main(["istioctl", "deregister", "--registry-file", str(reg),
                 "nope.svc", "10.0.0.9"]) == 1
    # malformed port spec is a usage error, not a traceback
    assert main(["istioctl", "register", "--registry-file", str(reg),
                 "--ports", "http80", "x.svc", "10.0.0.1"]) == 2
    # null-valued keys tolerated
    reg.write_text("services:\n")
    assert main(["istioctl", "register", "--registry-file", str(reg),
                 "x.svc", "10.0.0.1"]) == 0


def test_generate_cert_and_csr(tmp_path):
    from istio_tpu.cmd.__main__ import main
    from istio_tpu.security import pki
    ident = "spiffe://cluster.local/ns/d/sa/x"
    assert main(["generate-cert", "--identity", ident,
                 "--out-key", str(tmp_path / "k.pem"),
                 "--out-cert", str(tmp_path / "c.pem"),
                 "--out-root", str(tmp_path / "r.pem")]) == 0
    key = (tmp_path / "k.pem").read_bytes()
    cert = (tmp_path / "c.pem").read_bytes()
    root = (tmp_path / "r.pem").read_bytes()
    assert pki.key_cert_pair_ok(key, cert)
    assert pki.verify_chain(cert, root)
    assert ident in str(pki.san_uris(pki.load_cert(cert)))
    assert main(["generate-csr", "--identity", ident,
                 "--out-key", str(tmp_path / "k2.pem"),
                 "--out-cert", str(tmp_path / "csr.pem")]) == 0
    csr = pki.load_csr((tmp_path / "csr.pem").read_bytes())
    assert ident in str(pki.san_uris(csr))
