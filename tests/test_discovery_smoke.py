"""Tier-1 hook for scripts/discovery_smoke.py: the CI gate that the
snapshot-served Pilot discovery plane serves a Zipf sidecar fleet
over REAL HTTP with byte-exact parity against the unscoped
single-node generation path, that a one-namespace churn invalidates
only the scoped node groups (unrelated RDS/SDS entries stay live and
serve as cache hits), that delta push wakes only the churned
namespace's shard, that /debug/discovery agrees with the live
counters on both the discovery front and the introspect server, and
that draining is a typed UNAVAILABLE with a clean stop/start cycle."""
import importlib.util
import os
import sys


def _load():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "discovery_smoke.py")
    spec = importlib.util.spec_from_file_location("discovery_smoke",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def test_discovery_smoke_main():
    mod = _load()
    try:
        rc = mod.main(n_services=48, n_namespaces=8, replicas=3,
                      seed=7)
    finally:
        sys.modules.pop("discovery_smoke", None)
    assert rc == 0
