"""In-process runtime e2e — the reference's spy-adapter pattern
(mixer/test/e2e + mixer/test/spyAdapter): a full server (store →
controller → dispatcher → batcher) driven with real config kinds and
attribute bags, asserting adapter-visible effects and responses."""
import threading
import time

import pytest

from istio_tpu.adapters.sdk import QuotaArgs
from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.models.policy_engine import (NOT_FOUND, OK,
                                            PERMISSION_DENIED,
                                            RESOURCE_EXHAUSTED)
from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs


def _bookinfo_store() -> MemStore:
    """Bookinfo-style config: whitelist + denier + metric + quota
    (reference testdata mixer/testdata/config)."""
    s = MemStore()
    s.set(("handler", "istio-system", "whitelist"), {
        "adapter": "list",
        "params": {"overrides": ["v1", "v2"], "blacklist": False}})
    s.set(("handler", "istio-system", "denyall"), {
        "adapter": "denier", "params": {"status_code": PERMISSION_DENIED}})
    s.set(("handler", "istio-system", "prom"), {
        "adapter": "prometheus",
        "params": {"metrics": [{"name": "requestcount.istio-system",
                                "kind": "COUNTER",
                                "label_names": ["destination"]}]}})
    s.set(("handler", "istio-system", "mq"), {
        "adapter": "memquota",
        "params": {"quotas": [{"name": "requestcount_quota.istio-system",
                               "max_amount": 2,
                               "valid_duration_s": 60.0}]}})
    s.set(("instance", "istio-system", "appversion"), {
        "template": "listentry",
        "params": {"value": 'source.labels["version"] | "none"'}})
    s.set(("instance", "istio-system", "nothing"), {
        "template": "checknothing", "params": {}})
    s.set(("instance", "istio-system", "requestcount"), {
        "template": "metric",
        "params": {"value": "1",
                   "dimensions": {"destination": "destination.service"}}})
    s.set(("instance", "istio-system", "requestcount_quota"), {
        "template": "quota",
        "params": {"dimensions": {"source": 'source.labels["version"] | "u"'}}})
    s.set(("rule", "istio-system", "checkversion"), {
        "match": 'destination.service == "ratings.default.svc.cluster.local"',
        "actions": [{"handler": "whitelist",
                     "instances": ["appversion"]}]})
    s.set(("rule", "istio-system", "denyadmin"), {
        "match": 'request.path.startsWith("/admin")',
        "actions": [{"handler": "denyall", "instances": ["nothing"]}]})
    s.set(("rule", "istio-system", "tally"), {
        "match": "",
        "actions": [{"handler": "prom", "instances": ["requestcount"]},
                    {"handler": "mq",
                     "instances": ["requestcount_quota"]}]})
    return s


@pytest.fixture(scope="module")
def server():
    srv = RuntimeServer(_bookinfo_store(),
                        ServerArgs(batch_window_s=0.002, max_batch=64))
    yield srv
    srv.close()


def test_check_whitelist_allows_and_denies(server):
    ok = server.check(bag_from_mapping({
        "destination.service": "ratings.default.svc.cluster.local",
        "source.labels": {"version": "v1"},
        "request.path": "/ratings/1"}))
    assert ok.status_code == OK
    bad = server.check(bag_from_mapping({
        "destination.service": "ratings.default.svc.cluster.local",
        "source.labels": {"version": "v9"},
        "request.path": "/ratings/1"}))
    assert bad.status_code == NOT_FOUND
    # non-matching destination: whitelist rule inert
    other = server.check(bag_from_mapping({
        "destination.service": "reviews.default.svc.cluster.local",
        "source.labels": {"version": "v9"},
        "request.path": "/reviews/1"}))
    assert other.status_code == OK


def test_check_denier_rule(server):
    r = server.check(bag_from_mapping({
        "destination.service": "productpage.default.svc.cluster.local",
        "request.path": "/admin/settings"}))
    assert r.status_code == PERMISSION_DENIED


def test_referenced_attributes(server):
    r = server.check(bag_from_mapping({
        "destination.service": "ratings.default.svc.cluster.local",
        "source.labels": {"version": "v1"},
        "request.path": "/x"}))
    assert "destination.service" in r.referenced
    assert "request.path" in r.referenced


def test_concurrent_checks_batch(server):
    """Many threads issue checks; the batcher must coalesce and every
    caller must get ITS OWN verdict back."""
    results = {}

    def call(i):
        ver = "v1" if i % 2 == 0 else "v9"
        results[i] = server.check(bag_from_mapping({
            "destination.service": "ratings.default.svc.cluster.local",
            "source.labels": {"version": ver},
            "request.path": f"/r/{i}"})).status_code

    threads = [threading.Thread(target=call, args=(i,)) for i in range(32)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i, code in results.items():
        assert code == (OK if i % 2 == 0 else NOT_FOUND), (i, code)


def test_report_feeds_prometheus(server):
    server.report([bag_from_mapping({
        "destination.service": "reviews.default.svc.cluster.local"})] * 3)
    handler = server.controller.dispatcher.handlers["prom.istio-system"]
    val = handler.registry.get_sample_value(
        "istio_tpu_requestcount_istio_system_total",
        {"destination": "reviews.default.svc.cluster.local"})
    assert val == 3.0


def test_quota_dispatch(server):
    bag = bag_from_mapping({
        "destination.service": "details.default.svc.cluster.local",
        "source.labels": {"version": "vq"}})
    r1 = server.quota(bag, "requestcount_quota", QuotaArgs(quota_amount=2))
    assert r1.granted_amount == 2
    r2 = server.quota(bag, "requestcount_quota", QuotaArgs(quota_amount=1))
    assert r2.granted_amount == 0
    assert r2.status_code == RESOURCE_EXHAUSTED
    # unknown quota name: freely granted
    r3 = server.quota(bag, "nonexistent", QuotaArgs(quota_amount=5))
    assert r3.granted_amount == 5


def test_config_swap_takes_effect(server):
    """Runtime controller rebuild on store change (controller.go:115
    atomic publish): flip the whitelist to blacklist semantics."""
    store = server.controller.store
    store.set(("handler", "istio-system", "whitelist"), {
        "adapter": "list",
        "params": {"overrides": ["v1", "v2"], "blacklist": True}})
    deadline = time.time() + 5
    while time.time() < deadline:
        r = server.check(bag_from_mapping({
            "destination.service": "ratings.default.svc.cluster.local",
            "source.labels": {"version": "v1"},
            "request.path": "/x"}))
        if r.status_code == PERMISSION_DENIED:
            break
        time.sleep(0.05)
    assert r.status_code == PERMISSION_DENIED
    # restore
    store.set(("handler", "istio-system", "whitelist"), {
        "adapter": "list",
        "params": {"overrides": ["v1", "v2"], "blacklist": False}})
    time.sleep(0.3)


def test_apa_preprocess():
    """kubernetesenv APA fills pod attributes before resolution."""
    s = MemStore()
    s.set(("handler", "", "kube"), {
        "adapter": "kubernetesenv",
        "params": {"pods": {"web.default": {
            "pod_name": "web-1", "namespace": "default",
            "pod_ip": "10.0.0.9", "service_account_name": "web-sa"}}}})
    s.set(("instance", "", "kubeattrs"), {
        "template": "kubernetes",
        "params": {"source_ip": "source.ip",
                   "attribute_bindings": {
                       "source.name": "$out.source_pod_name",
                       "source.namespace": "$out.source_namespace"}}})
    s.set(("rule", "", "kubeapa"), {
        "match": "",
        "actions": [{"handler": "kube", "instances": ["kubeattrs"]}]})
    s.set(("handler", "", "deny-default-ns"), {
        "adapter": "denier", "params": {}})
    s.set(("instance", "", "nothing2"), {
        "template": "checknothing", "params": {}})
    s.set(("rule", "", "denypod"), {
        "match": 'source.name == "web-1"',
        "actions": [{"handler": "deny-default-ns",
                     "instances": ["nothing2"]}]})
    srv = RuntimeServer(s, ServerArgs(batch_window_s=0.001, max_batch=8))
    try:
        import ipaddress
        r = srv.check(bag_from_mapping({
            "source.ip": ipaddress.ip_address("10.0.0.9").packed,
            "destination.service": "x.default.svc"}))
        assert r.status_code == PERMISSION_DENIED   # APA filled source.name
        r2 = srv.check(bag_from_mapping({
            "source.ip": ipaddress.ip_address("10.0.0.7").packed,
            "destination.service": "x.default.svc"}))
        assert r2.status_code == OK
    finally:
        srv.close()


def test_fs_store_roundtrip(tmp_path):
    (tmp_path / "cfg.yaml").write_text("""
kind: handler
metadata: {name: d, namespace: ns}
spec:
  adapter: denier
  params: {}
---
kind: instance
metadata: {name: n, namespace: ns}
spec:
  template: checknothing
  params: {}
---
kind: rule
metadata: {name: r, namespace: ns}
spec:
  match: ""
  actions:
  - handler: d
    instances: [n]
""")
    from istio_tpu.runtime import FsStore
    store = FsStore(str(tmp_path))
    srv = RuntimeServer(store, ServerArgs(batch_window_s=0.001))
    try:
        r = srv.check(bag_from_mapping(
            {"destination.service": "svc.ns.svc.cluster.local"}))
        assert r.status_code == PERMISSION_DENIED
        # deleting the rule on disk + reload clears the deny
        (tmp_path / "cfg.yaml").write_text("""
kind: handler
metadata: {name: d, namespace: ns}
spec:
  adapter: denier
  params: {}
""")
        assert store.reload() > 0
        time.sleep(0.3)
        r2 = srv.check(bag_from_mapping(
            {"destination.service": "svc.ns.svc.cluster.local"}))
        assert r2.status_code == OK
    finally:
        srv.close()
