"""Platform credential fetchers + workload secret delivery.

Mirrors security/pkg/platform/{onprem,gcp,aws}_test.go and the
flexvolume driver tests (security/cmd/node_agent_k8s)."""
import base64
import json
import stat

import pytest

from istio_tpu.secure.backend import available_backends

if not available_backends():
    pytest.skip("istio_tpu.security needs a PKI backend "
                "(cryptography or the openssl CLI)",
                allow_module_level=True)

from istio_tpu.security import pki
from istio_tpu.security.ca import IstioCA
from istio_tpu.security.platform import (AwsClient, GcpClient,
                                         OnPremClient, PlatformError,
                                         new_platform_client)
from istio_tpu.security.workload import (SECRET_FILE, WORKLOAD_API,
                                         FlexVolumeDriver, SecretConfig,
                                         SecretFileServer, WorkloadError,
                                         new_secret_server,
                                         parse_mount_opts)


class FakeMetadata:
    def __init__(self, data, up=True):
        self.data = dict(data)
        self.up = up
        self.audiences = []

    def available(self):
        return self.up

    def fetch(self, path, audience=""):
        if audience:
            self.audiences.append(audience)
        return self.data.get(path, "")


# ---------------------------------------------------------------- onprem

def _workload_cert(tmp_path, identity="spiffe://cluster.local/ns/d/sa/x"):
    ca = IstioCA.new_self_signed()
    key = pki.generate_key()
    csr = pki.generate_csr(key, identity)
    cert = ca.sign(csr)
    root = tmp_path / "root.pem"
    root.write_bytes(ca.get_root_certificate())
    kf = tmp_path / "key.pem"
    kf.write_bytes(pki.key_to_pem(key))
    cf = tmp_path / "cert.pem"
    cf.write_bytes(cert)
    return str(root), str(kf), str(cf)


def test_onprem_client(tmp_path):
    root, key, cert = _workload_cert(tmp_path)
    c = OnPremClient(root, key, cert)
    assert c.is_proper_platform()
    assert c.get_credential_type() == "onprem"
    # identity comes from the cert's single SPIFFE SAN (onprem.go)
    assert c.get_service_identity() == "spiffe://cluster.local/ns/d/sa/x"
    assert c.get_agent_credential().startswith(b"-----BEGIN CERTIFICATE")
    opts = c.get_dial_options()
    assert opts.secure and opts.client_key_pem and opts.client_cert_pem


def test_onprem_client_missing_files(tmp_path):
    c = OnPremClient(str(tmp_path / "no.pem"), str(tmp_path / "no.key"),
                     str(tmp_path / "no.crt"))
    with pytest.raises(PlatformError):
        c.get_agent_credential()
    with pytest.raises(PlatformError):
        c.get_dial_options()


# ---------------------------------------------------------------- gcp

def test_gcp_client():
    md = FakeMetadata({
        GcpClient.TOKEN_PATH: "jwt-token-abc",
        GcpClient.SA_PATH: "svc@proj.iam.gserviceaccount.com"})
    c = GcpClient("ca.example:8060", md)
    assert c.is_proper_platform()
    assert c.get_credential_type() == "gcp"
    assert c.get_agent_credential() == b"jwt-token-abc"
    # audience is the CA address (gcp.go NewGcpClientImpl)
    assert md.audiences[-1] == "grpc://ca.example:8060"
    assert c.get_service_identity() == ("spiffe://cluster.local/ns/"
                                        "default/sa/"
                                        "svc@proj.iam.gserviceaccount.com")
    assert c.get_dial_options().bearer_token == "jwt-token-abc"


def test_gcp_client_not_on_gce():
    md = FakeMetadata({}, up=False)
    c = GcpClient("ca:1", md)
    assert not c.is_proper_platform()
    with pytest.raises(PlatformError):
        c.get_agent_credential()


# ---------------------------------------------------------------- aws

def test_aws_client_identity_document():
    doc = {"instanceId": "i-0abc", "region": "us-west-2",
           "accountId": "123"}
    sig = base64.b64encode(b"pkcs7-blob").decode()
    md = FakeMetadata({AwsClient.DOC_PATH: json.dumps(doc),
                       AwsClient.SIG_PATH: sig})
    seen = []
    c = AwsClient(md, verify=lambda d, s: seen.append((d, s)) or True)
    assert c.is_proper_platform()
    cred = json.loads(c.get_agent_credential())
    assert cred["document"]["instanceId"] == "i-0abc"
    assert seen, "verify() must run before the credential is used"
    assert c.get_service_identity() == ""     # resolved server-side
    assert c.get_credential_type() == "aws"


def test_aws_client_rejects_bad_signature():
    md = FakeMetadata({AwsClient.DOC_PATH: "{}",
                       AwsClient.SIG_PATH:
                       base64.b64encode(b"x").decode()})
    c = AwsClient(md, verify=lambda d, s: False)
    with pytest.raises(PlatformError):
        c.get_agent_credential()
    md2 = FakeMetadata({AwsClient.DOC_PATH: "{}",
                        AwsClient.SIG_PATH: "!!! not base64 !!!"})
    with pytest.raises(PlatformError):
        AwsClient(md2, verify=False).get_agent_credential()


def test_aws_client_fails_closed_without_verifier():
    """aws.go always verifies the PKCS7 signature; no verifier must
    mean rejection, not silent acceptance (ADVICE r2). Skipping takes
    the explicit opt-out verify=False."""
    sig = base64.b64encode(b"pkcs7-blob").decode()
    md = FakeMetadata({AwsClient.DOC_PATH: "{}",
                       AwsClient.SIG_PATH: sig})
    with pytest.raises(PlatformError, match="verify"):
        AwsClient(md).get_agent_credential()
    # explicit opt-out still works (tests/fakes, airgapped rigs)
    assert json.loads(
        AwsClient(md, verify=False).get_agent_credential())


def test_new_platform_client_factory(tmp_path):
    root, key, cert = _workload_cert(tmp_path)
    assert isinstance(new_platform_client("onprem", {
        "root_ca_cert_file": root, "key_file": key,
        "cert_chain_file": cert}), OnPremClient)
    assert isinstance(new_platform_client("gcp", {
        "ca_addr": "x", "metadata": FakeMetadata({})}), GcpClient)
    assert isinstance(new_platform_client("aws", {
        "metadata": FakeMetadata({})}), AwsClient)
    with pytest.raises(PlatformError):
        new_platform_client("azure", {})


def test_gcp_credential_signs_via_token_authenticator():
    """A gcp bearer credential must be able to obtain a cert from the
    secure CA: the operator provisions a trusted token→identity map
    (token_authenticator), composed with the onprem cert path."""
    from istio_tpu.security.ca_service import (CAClient, CAGrpcServer,
                                               NodeAgent,
                                               cert_authenticator,
                                               composite_authenticator,
                                               token_authenticator)
    ca = IstioCA.new_self_signed()
    ident = "spiffe://cluster.local/ns/default/sa/gce-sa"
    auth = composite_authenticator(
        cert_authenticator(ca.get_root_certificate()),
        token_authenticator({"jwt-token-abc": ident}))
    server = CAGrpcServer(ca, authenticator=auth)
    port = server.start()
    try:
        md = FakeMetadata({GcpClient.TOKEN_PATH: "jwt-token-abc",
                           GcpClient.SA_PATH: "gce-sa"})
        pc = GcpClient(f"127.0.0.1:{port}", md)
        client = CAClient(f"127.0.0.1:{port}",
                          root_cert_pem=ca.get_root_certificate())
        got = {}
        agent = NodeAgent(client, ident,
                          lambda k, c, r: got.update(key=k, cert=c),
                          credential=pc.get_agent_credential(),
                          credential_type=pc.get_credential_type())
        agent.rotate_once()
        assert pki.key_cert_pair_ok(got["key"], got["cert"])
        assert pki.verify_chain(got["cert"], ca.get_root_certificate())
        # an untrusted token is rejected
        bad = NodeAgent(client, ident, lambda *a: None,
                        credential=b"forged", credential_type="gcp")
        with pytest.raises(RuntimeError):
            bad.rotate_once()
        client.close()
    finally:
        server.stop()


# ---------------------------------------------------------------- workload

def test_secret_file_server_modes(tmp_path):
    cfg = SecretConfig(
        mode=SECRET_FILE,
        service_identity_cert_file=str(tmp_path / "sub" / "cert.pem"),
        service_identity_private_key_file=str(tmp_path / "sub" / "key.pem"))
    server = new_secret_server(cfg)
    assert isinstance(server, SecretFileServer)
    server.set_service_identity_private_key(b"KEY")
    server.set_service_identity_cert(b"CERT")
    key_path = tmp_path / "sub" / "key.pem"
    cert_path = tmp_path / "sub" / "cert.pem"
    assert key_path.read_bytes() == b"KEY"
    assert cert_path.read_bytes() == b"CERT"
    # secretfileserver.go: key 0600, cert 0644
    assert stat.S_IMODE(key_path.stat().st_mode) == 0o600
    assert stat.S_IMODE(cert_path.stat().st_mode) == 0o644
    with pytest.raises(WorkloadError):
        new_secret_server(SecretConfig(mode=WORKLOAD_API))
    with pytest.raises(WorkloadError):
        new_secret_server(SecretConfig(mode=42))


def test_flexvolume_mount_lifecycle(tmp_path):
    drv = FlexVolumeDriver(nodeagent_home=str(tmp_path / "nodeagent"))
    assert drv.init()["status"] == "Success"

    opts = json.dumps({"kubernetes.io/pod.uid": "uid-1",
                       "kubernetes.io/pod.name": "web-1",
                       "kubernetes.io/pod.namespace": "default",
                       "kubernetes.io/serviceAccount.name": "sa-web"})
    kubelet_dir = ("/var/lib/kubelet/pods/uid-1/volumes/"
                   "istio~flexvolume/creds")
    resp = drv.mount(kubelet_dir, opts)
    assert resp["status"] == "Success", resp
    attrs = drv.workloads["uid-1"]
    assert attrs.workload == "web-1" and attrs.service_account == "sa-web"
    assert (tmp_path / "nodeagent" / "uid-1" / "attrs.json").exists()

    # the node agent delivers rotated credentials into the mount
    sink = drv.secret_server_for("uid-1")
    sink.set_service_identity_private_key(b"K")
    sink.set_service_identity_cert(b"C")
    assert (tmp_path / "nodeagent" / "uid-1" / "key.pem").read_bytes() \
        == b"K"

    # unmount (pod uid parsed from the kubelet path, driver.go Unmount)
    resp = drv.unmount(kubelet_dir)
    assert resp["status"] == "Success"
    assert "uid-1" not in drv.workloads
    assert not (tmp_path / "nodeagent" / "uid-1").exists()
    with pytest.raises(WorkloadError):
        drv.secret_server_for("uid-1")


def test_flexvolume_bad_inputs(tmp_path):
    drv = FlexVolumeDriver(nodeagent_home=str(tmp_path))
    assert drv.mount("/x", "not json")["status"] == "Failure"
    assert drv.mount("/x", json.dumps({
        "kubernetes.io/pod.name": "p"}))["status"] == "Failure"
    assert drv.unmount("/too/short")["status"] == "Failure"
    assert parse_mount_opts("{}") is None
