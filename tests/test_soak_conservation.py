"""Satellite of the whole-mesh soak: the client-side conservation
identity in isolation. With the server up the whole time (no restart
window), the per-sidecar outcome ledgers must sum EXACTLY to the
server-side mixer_* front accounting — across an adapter-wedge window
AND a mixer config swap — on both the gRPC and the native front:

    wire_checks                       == requests_decoded delta
    ok + denied (wire-answered)       == responses_sent delta
    shed + expired + unavailable + err == decoded - responded
"""
import time

import pytest

from istio_tpu.runtime import RuntimeServer, ServerArgs, monitor
from istio_tpu.runtime.audit import INJECTIONS, SEAMS
from istio_tpu.runtime.resilience import CHAOS
from istio_tpu.testing import workloads

WEDGED = "cilist.istio-system"


@pytest.fixture
def mesh():
    CHAOS.reset()
    INJECTIONS.reset()
    SEAMS.reset()
    store = workloads.make_store(24, host_overlay_every=5, seed=3)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=16, buckets=(8, 16),
        default_check_deadline_ms=600.0,
        host_breaker_failures=2, host_breaker_reset_s=0.4,
        default_manifest=workloads.MESH_MANIFEST))
    plan = srv.controller.dispatcher.fused
    if plan is not None:
        plan.prewarm((8, 16))
    try:
        yield store, srv
    finally:
        srv.close()
        CHAOS.reset()
        INJECTIONS.reset()
        SEAMS.reset()


def _drain(base):
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and \
            monitor.report_conservation(since=base)["in_flight"]:
        time.sleep(0.02)


def _run_fleet(front_start, front_stop, store, n_sidecars=2,
               seed=11):
    from istio_tpu.soak.fleet import FleetSimulator

    base_serving = monitor.serving_counters()
    base_report = monitor.report_conservation()
    port = front_start()
    reqs = workloads.make_request_dicts(16, seed=seed)
    fleet = FleetSimulator(lambda: f"127.0.0.1:{port}", reqs,
                           n_sidecars=n_sidecars, seed=seed,
                           pace_s=0.001, report_every=9,
                           enable_check_cache=False)
    try:
        fleet.start()
        time.sleep(0.4)
        # wedge window: typed rejections, not lost requests
        CHAOS.wedge_adapter(WEDGED)
        time.sleep(0.5)
        CHAOS.unwedge_adapter(WEDGED)
        # mixer config swap mid-run: the rebuilt snapshot must not
        # double- or drop-count in-flight fronts
        key = ("rule", "istio-system", "report-all")
        store.set(key, dict(store.get(key)))
        time.sleep(0.6)
    finally:
        totals = fleet.stop()
        front_stop()
    _drain(base_report)

    sc = monitor.serving_counters()
    decoded = sc["requests_decoded"] - base_serving["requests_decoded"]
    responded = sc["responses_sent"] - base_serving["responses_sent"]
    oc = totals["outcomes"]
    assert totals["checks"] > 100, "fleet barely ran"
    assert totals["cache_hits"] == 0
    assert oc["misrouted"] == 0
    assert totals["wire_checks"] == decoded, (totals, decoded)
    assert oc["ok"] + oc["denied"] == responded, (oc, responded)
    assert (oc["shed"] + oc["expired"] + oc["unavailable"]
            + oc["error"]) == decoded - responded, (
        oc, decoded, responded)
    return totals


def test_conservation_grpc_front(mesh):
    store, srv = mesh
    from istio_tpu.api.grpc_server import MixerGrpcServer
    g = MixerGrpcServer(runtime=srv)
    _run_fleet(g.start, g.stop, store)


def test_conservation_native_front(mesh):
    store, srv = mesh
    from istio_tpu.api.native_server import NativeMixerServer
    native = NativeMixerServer(srv, min_fill=8, window_us=500)
    _run_fleet(native.start, native.stop, store)
