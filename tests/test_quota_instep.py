"""In-step quota: alloc rides the packed check program (gated,
ServerArgs.quota_in_step).

The classic served path pays check-trip + pool-flush-trip serialized
on the transport per quota-carrying batch; the in-step path allocates
in the SAME program, gated on the device's own ns-masked matched bit
(FusedPlan.packed_check_instep + device_quota.InlineQuotaSession).
Semantics must be EXACTLY the pool path's: memquota rolling windows,
dedup replay (cache, in-batch first_of, cross-wave), best-effort
partials, grant-freely on rule-inactive rows, INTERNAL on instance
eval errors. At most ONE quota per check row by design — multi-quota
requests keep the classic defer path. Reference:
mixer/adapter/memquota/memquota.go:107-118,259;
mixer/pkg/runtime/dispatcher.go:242.
"""
import pytest

from istio_tpu.adapters.sdk import QuotaArgs
from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs

OK, RESOURCE_EXHAUSTED, INTERNAL = 0, 8, 13


def _store() -> MemStore:
    s = MemStore()
    s.set(("handler", "istio-system", "mq"), {
        "adapter": "memquota",
        "params": {"quotas": [
            {"name": "rq.istio-system", "max_amount": 40,
             "valid_duration_s": 10.0},
            {"name": "eq.istio-system", "max_amount": 10}]}})
    s.set(("instance", "istio-system", "rq"), {
        "template": "quota",
        "params": {"dimensions": {"user": 'source.user | "anon"'}}})
    s.set(("instance", "istio-system", "eq"), {
        "template": "quota",
        "params": {"dimensions": {"svc": "destination.service"}}})
    # rq gated on method; eq unconditional
    s.set(("rule", "istio-system", "rq-rule"), {
        "match": 'request.method == "GET"',
        "actions": [{"handler": "mq", "instances": ["rq"]}]})
    s.set(("rule", "istio-system", "eq-rule"), {
        "match": "",
        "actions": [{"handler": "mq", "instances": ["eq"]}]})
    return s


class Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def _server(instep: bool, clock) -> RuntimeServer:
    srv = RuntimeServer(_store(), ServerArgs(
        fused=True, max_batch=8, buckets=(8,), quota_in_step=instep))
    for pool in set(srv.controller.device_quotas.values()):
        pool._clock = clock
    return srv


def _classic_round(srv, bags, qrows):
    """The served defer path: check, then (status-gated, like the
    gRPC fronts — grpcServer.go:188) quota_fused per row."""
    d = srv.controller.dispatcher
    resps = d.check(bags)
    out = {}
    for slot, name, args in qrows:
        if resps[slot].status_code != OK:
            continue   # denied checks never reach the quota loop
        qr = srv.quota_fused(bags[slot], name, args, resps[slot])
        if qr is None:
            qr = srv.quota(bags[slot], name, args, preprocessed=True)
        if hasattr(qr, "result"):
            qr = qr.result()
        out[slot] = qr
    return resps, out


def _instep_round(srv, bags, qrows):
    target = srv.instep_quota_target()
    assert target is not None
    return srv.check_batch_quota_instep(bags, qrows, target)


def _bags():
    return [bag_from_mapping(c) for c in (
        {"request.method": "GET", "source.user": "alice",
         "destination.service": "a.svc"},
        {"request.method": "GET", "source.user": "bob",
         "destination.service": "a.svc"},
        # gate-off for rq (POST): grant freely without consuming
        {"request.method": "POST", "source.user": "alice",
         "destination.service": "b.svc"},
        # defaulted dims: no source.user → "anon"
        {"request.method": "GET",
         "destination.service": "b.svc"},
        {"request.method": "GET", "source.user": "alice",
         "destination.service": "a.svc"},
        {"request.method": "GET", "source.user": "alice",
         "destination.service": "c.svc"},
    )]


def _run_waves(waves, clock_moves=None):
    """Drive the same waves through both paths; compare grants."""
    clock_a, clock_b = Clock(), Clock()
    srv_a = _server(True, clock_a)    # in-step
    srv_b = _server(False, clock_b)   # classic pool path
    try:
        for wi, wave in enumerate(waves):
            if clock_moves and wi in clock_moves:
                clock_a.t += clock_moves[wi]
                clock_b.t += clock_moves[wi]
            bags = _bags()
            ra, qa = _instep_round(srv_a, bags, wave)
            rb, qb = _classic_round(srv_b, bags, wave)
            for slot, name, _args in wave:
                if rb[slot].status_code != OK:
                    # denied check: the fronts attach no quota result
                    # (and the device gate consumed nothing) — the
                    # in-step result for the row is discarded
                    assert slot not in qb
                    continue
                a, b = qa[slot], qb[slot]
                assert (a.granted_amount, a.status_code) == \
                    (b.granted_amount, b.status_code), \
                    (wi, slot, name, a, b)
            for x, y in zip(ra, rb):
                assert x.status_code == y.status_code
    finally:
        srv_a.close()
        srv_b.close()


def test_grants_defaults_gating_and_contention():
    """Gated/ungated rows, defaulted dims, mixed amounts contending
    on one bucket, best-effort partials, window exhaustion."""
    _run_waves([
        # alice 5 + 5 (slots 0,4 same bucket, contended), anon 3,
        # POST freely, eq consumption on a.svc
        [(0, "rq", QuotaArgs(quota_amount=5, best_effort=True)),
         (4, "rq", QuotaArgs(quota_amount=5, best_effort=True)),
         (2, "rq", QuotaArgs(quota_amount=7, best_effort=True)),
         (3, "rq", QuotaArgs(quota_amount=3, best_effort=True)),
         (1, "eq", QuotaArgs(quota_amount=6, best_effort=True))],
        # alice 40 → partial 30 left; eq 6 → partial 4 left; then zero
        [(0, "rq", QuotaArgs(quota_amount=40, best_effort=True)),
         (1, "eq", QuotaArgs(quota_amount=6, best_effort=True))],
        [(4, "rq", QuotaArgs(quota_amount=1, best_effort=True)),
         (1, "eq", QuotaArgs(quota_amount=6, best_effort=True))],
    ])


def test_rolling_window_reclaim_parity():
    """Consume the whole window, advance past the consuming tick,
    re-consume — tick math must match the host adapter exactly on
    both paths."""
    _run_waves([
        [(0, "rq", QuotaArgs(quota_amount=40, best_effort=True))],
        [(0, "rq", QuotaArgs(quota_amount=1, best_effort=True))],
        [(0, "rq", QuotaArgs(quota_amount=40, best_effort=True))],
    ], clock_moves={1: 5.0, 2: 6.0})   # half window, then past it


def test_dedup_replay_in_batch_and_across_waves():
    """Same dedup id twice in one trip replays the first outcome
    without consuming; resends within min_dedup replay from cache;
    a fresh id sees single consumption."""
    _run_waves([
        [(0, "rq", QuotaArgs(quota_amount=5, best_effort=True,
                             dedup_id="d1")),
         (4, "rq", QuotaArgs(quota_amount=5, best_effort=True,
                             dedup_id="d1"))],       # in-batch replay
        [(0, "rq", QuotaArgs(quota_amount=5, best_effort=True,
                             dedup_id="d1"))],       # cache replay
        [(5, "rq", QuotaArgs(quota_amount=40, best_effort=True))],
        # only 5 of 40 were consumed → 35 granted proves single
        # consumption on both paths
    ])


def test_denied_checks_never_consume():
    """A fused denier matching /admin: denied rows allocate NOTHING
    on either path (grpcServer.go:188) — proven by the follow-up
    wave still seeing the full window."""
    s = _store()
    s.set(("handler", "istio-system", "deny"), {
        "adapter": "denier", "params": {"status_code": 7}})
    s.set(("instance", "istio-system", "nothing"), {
        "template": "checknothing", "params": {}})
    s.set(("rule", "istio-system", "deny-admin"), {
        "match": 'request.path.startsWith("/admin")',
        "actions": [{"handler": "deny", "instances": ["nothing"]}]})
    clock = Clock()
    srv = RuntimeServer(s, ServerArgs(fused=True, max_batch=8,
                                      buckets=(8,),
                                      quota_in_step=True))
    for pool in set(srv.controller.device_quotas.values()):
        pool._clock = clock
    try:
        bags = [bag_from_mapping(
            {"request.method": "GET", "source.user": "alice",
             "request.path": "/admin/x",
             "destination.service": "a.svc"})]
        resps, q = _instep_round(
            srv, bags,
            [(0, "rq", QuotaArgs(quota_amount=40, best_effort=True))])
        assert resps[0].status_code == 7
        # nothing consumed: a clean row gets the FULL window
        bags2 = [bag_from_mapping(
            {"request.method": "GET", "source.user": "alice",
             "request.path": "/ok",
             "destination.service": "a.svc"})]
        _, q2 = _instep_round(
            srv, bags2,
            [(0, "rq", QuotaArgs(quota_amount=40, best_effort=True))])
        assert q2[0].granted_amount == 40
    finally:
        srv.close()


def test_instance_eval_error_is_internal():
    """eq dims read destination.service with NO default: a bag missing
    it must yield INTERNAL without touching counters (quota_fused /
    dispatcher.quota parity)."""
    clock = Clock()
    srv = _server(True, clock)
    try:
        bags = [bag_from_mapping({"request.method": "GET"})]
        _, q = _instep_round(
            srv, bags,
            [(0, "eq", QuotaArgs(quota_amount=3, best_effort=True))])
        assert q[0].status_code == INTERNAL
    finally:
        srv.close()


def test_target_rejects_ambiguous_names():
    """A quota name served by TWO rules is ineligible for in-step
    (activity picks the handler at runtime); others stay eligible."""
    s = _store()
    s.set(("rule", "istio-system", "rq-rule-2"), {
        "match": 'request.method == "PUT"',
        "actions": [{"handler": "mq", "instances": ["rq"]}]})
    srv = RuntimeServer(s, ServerArgs(fused=True, max_batch=8,
                                      buckets=(8,),
                                      quota_in_step=True))
    try:
        target = srv.instep_quota_target()
        assert target is not None
        _, by_name = target
        assert "rq.istio-system" not in by_name and "rq" not in by_name
        assert "eq" in by_name
    finally:
        srv.close()


def test_flag_off_means_no_target():
    srv = _server(False, Clock())
    try:
        assert srv.instep_quota_target() is None
    finally:
        srv.close()


def test_native_wire_instep_end_to_end():
    """The native front with quota_in_step on: grants at the real wire
    match the classic semantics, the in-step target is live, and the
    pool's OWN flush worker never runs (no second device trip)."""
    pytest.importorskip("grpc")
    from istio_tpu.api.client import MixerClient
    from istio_tpu.api.native_server import NativeMixerServer

    clock = Clock()
    srv = _server(True, clock)
    flushes = []
    for pool in set(srv.controller.device_quotas.values()):
        orig = pool._flush
        pool._flush = lambda b, _o=orig: (flushes.append(len(b)),
                                          _o(b))[1]
    native = NativeMixerServer(srv, min_fill=8, window_us=500)
    port = native.start()
    cli = MixerClient(f"127.0.0.1:{port}", enable_check_cache=False)
    try:
        assert srv.instep_quota_target() is not None
        values = {"request.method": "GET", "source.user": "alice",
                  "destination.service": "a.svc"}
        r1 = cli.check(values, quotas={"rq": 5}, dedup_id="w1")
        assert r1.precondition.status.code == OK
        assert r1.quotas["rq"].granted_amount == 5
        # dedup replay at the wire
        r2 = cli.check(values, quotas={"rq": 5}, dedup_id="w1")
        assert r2.quotas["rq"].granted_amount == 5
        # fresh id: window had 40, 5 consumed once
        r3 = cli.check(values, quotas={"rq": 40}, dedup_id="w2")
        assert r3.quotas["rq"].granted_amount == 35
        # POST: quota rule inactive → freely granted, nothing consumed
        r4 = cli.check({**values, "request.method": "POST"},
                       quotas={"rq": 9}, dedup_id="w3")
        assert r4.quotas["rq"].granted_amount == 9
        r5 = cli.check(values, quotas={"rq": 1}, dedup_id="w4")
        assert r5.quotas["rq"].granted_amount == 0   # exhausted
        assert flushes == [], "pool flush trip ran despite in-step"
    finally:
        cli.close()
        native.stop()
        srv.close()
