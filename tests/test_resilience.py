"""Overload-resilience suite: deadlines, admission control, the device
circuit breaker with oracle fallback, fail-open/closed policy, the
batcher watchdog and the submit()-vs-close() race.

Most tests drive a raw CheckBatcher with a stub run_batch (no device
anywhere — the admission/deadline machinery is pure host logic); the
breaker/fallback integration tests share one small RuntimeServer and
inject faults through the ChaosHooks seam (runtime/resilience.py), so
they exercise the production unwind path end to end.
"""
import threading
import time
from concurrent.futures import Future

import pytest

from istio_tpu.runtime import monitor
from istio_tpu.runtime.batcher import CheckBatcher, PadBag
from istio_tpu.runtime.resilience import (CHAOS, CircuitBreaker,
                                          DeadlineExceededError,
                                          ResilienceConfig,
                                          ResilientChecker,
                                          ResourceExhaustedError,
                                          UnavailableError)


@pytest.fixture(autouse=True)
def _clean_chaos():
    CHAOS.reset()
    yield
    CHAOS.reset()
    monitor.reset_latency_window()


# ---------------------------------------------------------------------
# circuit breaker state machine
# ---------------------------------------------------------------------

def test_breaker_trips_after_threshold_and_recovers():
    b = CircuitBreaker(failures=3, reset_s=0.05)
    assert b.state == "closed"
    for _ in range(2):
        b.record_failure()
    assert b.state == "closed" and b.allow_device()
    b.record_failure()
    assert b.state == "open"
    assert not b.allow_device()          # open, reset window not over
    time.sleep(0.06)
    assert b.allow_device()              # the single half-open probe
    assert b.state == "half_open"
    assert not b.allow_device()          # probe in flight: no second
    b.record_success()
    assert b.state == "closed" and b.allow_device()


def test_breaker_probe_failure_reopens():
    b = CircuitBreaker(failures=1, reset_s=0.05)
    b.record_failure()
    assert b.state == "open"
    time.sleep(0.06)
    assert b.allow_device()
    b.record_failure()                   # probe failed
    assert b.state == "open"
    assert not b.allow_device()          # fresh reset window


# ---------------------------------------------------------------------
# ResilientChecker (stub device/oracle — no jax anywhere)
# ---------------------------------------------------------------------

def _fast_config(**kw):
    kw.setdefault("retry_backoff_s", 0.001)
    kw.setdefault("retry_jitter_s", 0.001)
    return ResilienceConfig(**kw)


def test_retry_absorbs_transient_device_fault():
    calls = {"n": 0}

    def device(bags):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("transient")
        return ["dev"] * len(bags)

    rc = ResilientChecker(device, lambda bags: ["oracle"] * len(bags),
                          config=_fast_config())
    before = int(monitor.CHECK_DEVICE_RETRIES._value.get())
    assert rc.run_batch(["a", "b"]) == ["dev", "dev"]
    assert calls["n"] == 2
    assert rc.breaker.state == "closed"
    assert int(monitor.CHECK_DEVICE_RETRIES._value.get()) == before + 1


def test_double_failure_falls_back_to_oracle_and_counts():
    from istio_tpu.runtime.batcher import trim_pads

    def device(bags):
        raise RuntimeError("down")

    def oracle(bags):
        # the real check_host_oracle answers per REAL row (pads
        # trimmed, like the fused path)
        return ["oracle"] * len(trim_pads(list(bags)))

    rc = ResilientChecker(device, oracle,
                          config=_fast_config(breaker_failures=2))
    fb0 = monitor.resilience_counters()["fallback"]
    assert rc.run_batch(["a", "b", PadBag()]) == ["oracle", "oracle"]
    fb = monitor.resilience_counters()["fallback"]
    # pad rows carry no caller: the per-request counter must not
    # count them
    assert fb["device_error"] - fb0["device_error"] == 2
    assert rc.breaker.state == "closed"   # 1 failure < threshold 2
    rc.run_batch(["c"])
    assert rc.breaker.state == "open"
    # breaker open: device never called, straight to oracle
    assert rc.run_batch(["d"]) == ["oracle"]
    fb2 = monitor.resilience_counters()["fallback"]
    assert fb2["breaker_open"] - fb0["breaker_open"] == 1


def test_half_open_probe_released_on_typed_rejection():
    """A typed rejection riding out of the device call during the
    half-open probe must release the probe slot — otherwise the
    breaker wedges in half_open with probe_inflight set and never
    tries the device again."""
    calls = {"n": 0}

    def device(bags):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("down")
        if calls["n"] == 2:
            raise UnavailableError("typed rejection mid-probe")
        return ["dev"] * len(bags)

    rc = ResilientChecker(device, lambda bags: ["oracle"] * len(bags),
                          config=_fast_config(breaker_failures=1,
                                              breaker_reset_s=0.05,
                                              retry=False))
    assert rc.run_batch(["a"]) == ["oracle"]     # failure -> open
    assert rc.breaker.state == "open"
    time.sleep(0.06)
    with pytest.raises(UnavailableError):
        rc.run_batch(["b"])                      # probe raises typed
    assert rc.breaker.state == "half_open"
    # the slot was released: the next batch gets a fresh probe and
    # closes the breaker
    assert rc.run_batch(["c"]) == ["dev"]
    assert rc.breaker.state == "closed"


def test_fail_open_short_ttls():
    """Fail-open allows must not be cached like a healthy success —
    1s/1-use TTLs close the policy-bypass window with the outage."""
    def broken(bags):
        raise RuntimeError("down")

    rc = ResilientChecker(broken, broken,
                          config=_fast_config(fail_policy="open"))
    out = rc.run_batch(["a"])
    assert out[0].status_code == 0
    assert out[0].valid_duration_s <= 1.0
    assert out[0].valid_use_count == 1


def test_fail_closed_raises_unavailable():
    def broken(bags):
        raise RuntimeError("down")

    rc = ResilientChecker(broken, broken,
                          config=_fast_config(fail_policy="closed"))
    with pytest.raises(UnavailableError):
        rc.run_batch(["a"])


def test_fail_open_answers_ok():
    def broken(bags):
        raise RuntimeError("down")

    rc = ResilientChecker(broken, broken,
                          config=_fast_config(fail_policy="open"))
    out = rc.run_batch(["a", "b", PadBag()])
    assert len(out) == 2                 # per REAL row, pads trimmed
    assert all(r.status_code == 0 for r in out)


# ---------------------------------------------------------------------
# batcher admission control + deadlines
# ---------------------------------------------------------------------

def _blocked_batcher(release: threading.Event, max_batch: int = 1,
                     **kw):
    """pipeline=1 + a run_batch that blocks: the first batch occupies
    the worker, the second wedges the flusher in _flush's semaphore,
    and everything after queues — deterministic depth for the
    admission tests."""
    seen: list = []

    def run_batch(bags):
        seen.append(list(bags))
        release.wait(timeout=30)
        return [("ok", i) for i in range(len(bags))]

    b = CheckBatcher(run_batch, window_s=0.0005, max_batch=max_batch,
                     pipeline=1, buckets=(max_batch,),
                     pad_batches=False, **kw)
    return b, seen


def test_queue_cap_sheds_resource_exhausted():
    release = threading.Event()
    b, _ = _blocked_batcher(release, max_queue=2)
    try:
        shed0 = monitor.resilience_counters()["shed"]["queue_full"]
        futs = [b.submit(f"bag{i}") for i in range(8)]
        shed = [f for f in futs
                if f.done() and isinstance(f.exception(),
                                           ResourceExhaustedError)]
        assert shed, "no submit shed despite a full queue"
        assert b.stats()["depth"] <= 2
        release.set()
        for f in futs:
            if f not in shed:
                assert f.result(timeout=10)[0] == "ok"
        c = monitor.resilience_counters()
        assert c["shed"]["queue_full"] - shed0 == len(shed)
    finally:
        release.set()
        b.close()


def test_brownout_sheds_newest_when_p99_over_target():
    release = threading.Event()
    b, _ = _blocked_batcher(release, max_queue=4, brownout=True)
    try:
        # an SLO-breaching live window (p99 >> 1ms target)
        for _ in range(64):
            monitor.observe_check_e2e(0.100)
        shed0 = monitor.resilience_counters()["shed"]["brownout"]
        futs = [b.submit(f"bag{i}") for i in range(8)]
        brown = [f for f in futs
                 if f.done() and isinstance(f.exception(),
                                            ResourceExhaustedError)
                 and "brownout" in str(f.exception())]
        assert brown, "brownout shed nothing despite p99 over target"
        assert monitor.resilience_counters()["shed"]["brownout"] \
            - shed0 == len(brown)
        release.set()
        for f in futs:
            if f not in brown:
                f.result(timeout=10)
    finally:
        release.set()
        b.close()


def test_deadline_expired_at_submit_rejects():
    b = CheckBatcher(lambda bags: [1] * len(bags), window_s=0.0005)
    try:
        exp0 = monitor.resilience_counters()["expired_total"]
        fut = b.submit("bag", deadline=time.perf_counter() - 0.1)
        with pytest.raises(DeadlineExceededError):
            fut.result(timeout=5)
        assert monitor.resilience_counters()["expired_total"] == \
            exp0 + 1
    finally:
        b.close()


def test_deadline_expired_in_queue_shed_before_run_batch():
    """A row whose deadline passes while it waits behind an in-flight
    batch must resolve DEADLINE_EXCEEDED and never reach run_batch
    (the pre-tensorize shed)."""
    release = threading.Event()
    b, seen = _blocked_batcher(release, max_batch=4)
    try:
        f1 = b.submit("first")           # trip 1 occupies the worker
        time.sleep(0.02)
        f2 = b.submit("stale", deadline=time.perf_counter() + 0.01)
        time.sleep(0.05)                 # expire behind trip 1
        release.set()
        with pytest.raises(DeadlineExceededError):
            f2.result(timeout=10)
        assert f1.result(timeout=10)[0] == "ok"
        assert all("stale" not in batch for batch in seen)
    finally:
        release.set()
        b.close()


def test_occupancy_hold_never_outlasts_deadline():
    """hold_at=1 + an in-flight trip puts the loop in its busy-hold
    accumulation; a held request must flush while its deadline still
    has a hold quantum of slack (so it can be SERVED — flushing at
    expiry would guarantee a shed), never wait out the trip."""
    release = threading.Event()
    seen: list = []

    def run_batch(bags):
        seen.append(list(bags))
        if len(seen) == 1:
            release.wait(timeout=30)
        return ["ok"] * len(bags)

    b = CheckBatcher(run_batch, window_s=0.0005, max_batch=64,
                     pipeline=2, buckets=(64,), pad_batches=False,
                     hold_at=1)
    try:
        f1 = b.submit("first")
        time.sleep(0.02)                 # trip 1 in flight -> busy
        t0 = time.perf_counter()
        deadline = time.perf_counter() + 0.05
        f2 = b.submit("held", deadline=deadline)
        # resolves around its own deadline (served via worker 2, or
        # shed if dispatch lost the race) — never after the 30s trip
        try:
            assert f2.result(timeout=10) == "ok"
            # served: the batch flushed BEFORE expiry
            assert any("held" in batch for batch in seen)
        except DeadlineExceededError:
            pass
        waited = time.perf_counter() - t0
        assert waited < 2.0, f"held {waited:.3f}s past its deadline"
        release.set()
        assert f1.result(timeout=10) == "ok"
    finally:
        release.set()
        b.close()


def test_cancelled_future_shed_at_batch_build():
    """An aio client disconnect cancels its future; the row must be
    dropped before padding/tensorize, and its batch-mates must still
    resolve."""
    gate = threading.Event()
    seen: list = []

    def run_batch(bags):
        seen.append(list(bags))
        return ["ok"] * len(bags)

    b = CheckBatcher(run_batch, window_s=0.2, max_batch=8,
                     buckets=(8,), pad_batches=False)
    try:
        c0 = int(monitor.CHECK_CANCELLED_SHED._value.get())
        f1 = b.submit("keep1")
        f2 = b.submit("gone")
        f3 = b.submit("keep2")
        assert f2.cancel()               # pending future: cancellable
        assert f1.result(timeout=10) == "ok"
        assert f3.result(timeout=10) == "ok"
        assert seen and all("gone" not in batch for batch in seen)
        assert int(monitor.CHECK_CANCELLED_SHED._value.get()) == c0 + 1
        gate.set()
    finally:
        gate.set()
        b.close()


def test_batch_failure_counter_and_typed_error():
    def run_batch(bags):
        raise RuntimeError("device exploded")

    b = CheckBatcher(run_batch, window_s=0.0005)
    try:
        n0 = int(monitor.CHECK_BATCH_FAILURES._value.get())
        fut = b.submit("bag")
        with pytest.raises(RuntimeError, match="device exploded"):
            fut.result(timeout=10)
        assert int(monitor.CHECK_BATCH_FAILURES._value.get()) == n0 + 1
    finally:
        b.close()


def test_report_batcher_does_not_pollute_check_counters():
    """The report coalescer reuses CheckBatcher with
    observe_latency=False — its failures/sheds must stay out of the
    CHECK resilience counters."""
    def run_batch(bags):
        raise RuntimeError("boom")

    b = CheckBatcher(run_batch, window_s=0.0005,
                     size_hist=monitor.REPORT_BATCH_SIZE,
                     observe_latency=False, max_queue=1)
    try:
        n0 = int(monitor.CHECK_BATCH_FAILURES._value.get())
        shed0 = monitor.resilience_counters()["shed_total"]
        fut = b.submit("bag")
        with pytest.raises(RuntimeError):
            fut.result(timeout=10)
        assert int(monitor.CHECK_BATCH_FAILURES._value.get()) == n0
        assert monitor.resilience_counters()["shed_total"] == shed0
    finally:
        b.close()


# ---------------------------------------------------------------------
# flusher-thread watchdog
# ---------------------------------------------------------------------

def test_watchdog_dead_flusher_fails_fast():
    b = CheckBatcher(lambda bags: ["ok"] * len(bags),
                     window_s=0.0005, max_batch=4, buckets=(4,),
                     pad_batches=False)
    try:
        assert b.submit("warm").result(timeout=10) == "ok"
        # kill the flusher: the next dispatch explodes inside _flush
        b._pool.submit = None
        f2 = b.submit("bag2")            # flusher dies flushing this
        deadline = time.time() + 10
        while b._dead is None and time.time() < deadline:
            time.sleep(0.005)
        assert b._dead is not None, "watchdog never marked the death"
        ok, err = b.healthy()
        assert not ok and "died" in err
        # the batch in the flusher's hands was resolved, not orphaned
        with pytest.raises(UnavailableError):
            f2.result(timeout=10)
        # new submits fail fast instead of queueing forever
        shed0 = monitor.resilience_counters()["shed"]["batcher_dead"]
        f3 = b.submit("bag3")
        with pytest.raises(UnavailableError):
            f3.result(timeout=10)
        assert monitor.resilience_counters()["shed"]["batcher_dead"] \
            == shed0 + 1
        assert "healthy" in b.stats() and not b.stats()["healthy"]
    finally:
        b._pool.submit = type(b._pool).submit.__get__(b._pool)
        b._closed = True                 # close() would join a dead
        b._pool.shutdown(wait=False)     # thread; tear down manually


def test_healthz_reports_dead_flusher(tmp_path):
    """/healthz must go 503 when the check flusher dies — the
    introspect server consults batcher.healthy() (satellite 1)."""
    import json
    import urllib.request
    from types import SimpleNamespace

    from istio_tpu.introspect import IntrospectServer

    b = CheckBatcher(lambda bags: [1] * len(bags), window_s=0.0005)
    runtime = SimpleNamespace(
        batcher=b, _report_batcher=None,
        controller=SimpleNamespace(dispatcher=SimpleNamespace(
            snapshot=SimpleNamespace(revision=7))))
    intro = IntrospectServer(runtime=runtime, trace_capacity=0)
    try:
        port = intro.start()

        def healthz():
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/healthz",
                        timeout=10) as r:
                    return r.status, json.load(r)
            except urllib.error.HTTPError as e:
                return e.code, json.load(e)

        code, body = healthz()
        assert code == 200 and body["status"] == "ok"
        b._dead = RuntimeError("flusher crashed")
        code, body = healthz()
        assert code == 503
        assert "flusher" in body["error"]
    finally:
        intro.close()
        b._dead = None
        b.close()


# ---------------------------------------------------------------------
# submit()-vs-close() race (satellite 4)
# ---------------------------------------------------------------------

def test_requests_racing_past_close_resolve_via_drain():
    """A request that lands in the queue behind the close() sentinel
    must still resolve (the _drain_on_close contract)."""
    seen: list = []

    def run_batch(bags):
        seen.append(list(bags))
        return [f"ok:{bag}" for bag in bags]

    b = CheckBatcher(run_batch, window_s=10.0, max_batch=8,
                     buckets=(8,), pad_batches=False)
    fa = b.submit("early")               # loop is collecting [early]
    time.sleep(0.02)
    # simulate the race: the sentinel enters the queue, then a request
    # that beat the _closed flag lands BEHIND it
    fb: Future = Future()
    fb._t_enq = time.perf_counter()
    b._closed = True
    b._queue.put(None)
    b._queue.put(("racer", fb))
    b._thread.join(timeout=10)
    assert not b._thread.is_alive()
    assert fa.result(timeout=5) == "ok:early"
    assert fb.result(timeout=5) == "ok:racer"
    assert any("racer" in batch for batch in seen)
    b._pool.shutdown(wait=True)


def test_drain_on_close_failing_batch_resolves_with_exception():
    """Even when the DRAIN batch itself fails, the raced-past-close
    futures must resolve (with the exception), never hang."""
    def run_batch(bags):
        if "poison" in bags:
            raise RuntimeError("drain batch failed")
        return [f"ok:{bag}" for bag in bags]

    b = CheckBatcher(run_batch, window_s=10.0, max_batch=8,
                     buckets=(8,), pad_batches=False)
    fa = b.submit("early")
    time.sleep(0.02)
    fb: Future = Future()
    fb._t_enq = time.perf_counter()
    b._closed = True
    b._queue.put(None)
    b._queue.put(("poison", fb))
    b._thread.join(timeout=10)
    assert fa.result(timeout=5) == "ok:early"
    with pytest.raises(RuntimeError, match="drain batch failed"):
        fb.result(timeout=5)
    b._pool.shutdown(wait=True)


# ---------------------------------------------------------------------
# end-to-end: RuntimeServer + ChaosHooks (shared small server)
# ---------------------------------------------------------------------

@pytest.fixture(scope="module")
def small_server():
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.testing import workloads

    store = workloads.make_store(12)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=8, buckets=(8,),
        breaker_failures=2, breaker_reset_s=0.2,
        default_manifest=workloads.MESH_MANIFEST))
    plan = srv.controller.dispatcher.fused
    if plan is not None:
        plan.prewarm((8,))
    yield srv
    CHAOS.reset()
    srv.close()


def test_breaker_fallback_parity_end_to_end(small_server):
    from istio_tpu.testing import workloads

    bags = workloads.make_bags(10)
    clean = [small_server.check(b).status_code for b in bags]
    CHAOS.device_failures = 10**9
    try:
        degraded = [small_server.check(b).status_code for b in bags]
    finally:
        CHAOS.reset()
    assert degraded == clean
    assert small_server.resilience.breaker.state == "open"
    # recovery via the half-open probe once the fault clears
    time.sleep(0.25)
    assert small_server.check(bags[0]).status_code == clean[0]
    assert small_server.resilience.breaker.state == "closed"


def test_fail_policy_end_to_end(small_server):
    from istio_tpu.testing import workloads

    bag = workloads.make_bags(1)[0]
    CHAOS.device_failures = 10**9
    CHAOS.oracle_failures = 10**9
    cfg = small_server.resilience.config
    old_policy = cfg.fail_policy
    try:
        cfg.fail_policy = "closed"
        with pytest.raises(UnavailableError):
            small_server.check(bag)
        cfg.fail_policy = "open"
        assert small_server.check(bag).status_code == 0
    finally:
        cfg.fail_policy = old_policy
        CHAOS.reset()
        small_server.resilience.breaker.record_success()


def test_chunked_front_rejects_expired_pre_tensorize(small_server):
    """The BatchCheck/native chunked entry answers DEADLINE_EXCEEDED
    for chunks its deadline can't reach — without tensorizing them."""
    from istio_tpu.api.grpc_server import MixerGrpcServer
    from istio_tpu.testing import workloads

    g = MixerGrpcServer(small_server)    # never started: direct call
    bags = workloads.make_bags(6)
    tz0 = monitor.CHECK_STAGE_SECONDS.count(stage="tensorize")
    exp0 = monitor.resilience_counters()["expired_total"]
    out = g._check_bags_chunked(list(bags),
                                deadline=time.perf_counter() - 1.0)
    assert len(out) == len(bags)
    assert all(r.status_code == 4 for r in out)
    assert all(r.valid_use_count == 0 for r in out)
    assert monitor.CHECK_STAGE_SECONDS.count(stage="tensorize") == tz0
    assert monitor.resilience_counters()["expired_total"] - exp0 == \
        len(bags)
    # a live deadline serves normally
    out = g._check_bags_chunked(list(bags),
                                deadline=time.perf_counter() + 30.0)
    assert [r.status_code for r in out] == \
        [small_server.check(b).status_code for b in bags]
