"""Tier-1 hook for scripts/chaos_smoke.py: the CI gate that overload
resilience keeps working — injected device failures trip the breaker
and the oracle fallback stays conformant, saturation sheds
RESOURCE_EXHAUSTED, expired deadlines reject pre-tensorize, and the
counters stay scrapable. Runs main() in-process (the
introspect_smoke pattern: a subprocess would pay a second jax import
for no extra coverage; the script stays runnable standalone under
JAX_PLATFORMS=cpu)."""
import importlib.util
import os
import sys


def test_chaos_smoke_main():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "chaos_smoke.py")
    spec = importlib.util.spec_from_file_location("chaos_smoke", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
        rc = mod.main(n_rules=18, n_checks=24)
    finally:
        sys.modules.pop(spec.name, None)
    assert rc == 0
