"""Snapshot analyzer (istio_tpu/analysis) unit + property tests.

The load-bearing property (ISSUE 3 satellite): every conflict/shadow
finding the analyzer reports ships a concrete witness attribute bag,
and replaying that witness through expr/oracle.py independently
confirms the verdict — over seeded worlds, not hand-picked examples.
Plus decision-procedure units (product-DFA emptiness/inclusion over
ops/regex_dfa tables, atom implication, witness solving), budget
prediction, plane divergence, namespace scoping, the route-table
precedence shadow, and the /debug/analysis introspect view.
"""
import json
import urllib.request

import numpy as np
import pytest

from istio_tpu.analysis import (Severity, analyze_route_table,
                                analyze_rules, analyze_snapshot,
                                check_plane_pairs)
from istio_tpu.analysis import atoms as A
from istio_tpu.analysis import dfa_ops
from istio_tpu.analysis.findings import (ALLOW_DENY_CONFLICT, DNF_BUDGET,
                                         NON_TOTAL, PLANE_DIVERGENCE,
                                         SHADOWED_ROUTE, SHADOWED_RULE,
                                         STATE_BUDGET)
from istio_tpu.attribute.bag import DictBag
from istio_tpu.attribute.types import ValueType as V
from istio_tpu.compiler.ruleset import Rule, _rule_ast
from istio_tpu.expr.checker import AttributeDescriptorFinder
from istio_tpu.expr.oracle import OracleProgram
from istio_tpu.expr.parser import parse
from istio_tpu.ops.regex_dfa import compile_regex
from istio_tpu.testing import corpus

FINDER = AttributeDescriptorFinder(corpus.ANALYZER_MANIFEST)


# ---------------------------------------------------------------------------
# product-DFA decision procedures
# ---------------------------------------------------------------------------

def test_product_intersect_witness_replays():
    a = compile_regex("^/api/v[0-9]+/")
    b = compile_regex("^/api/v2/items")
    r = dfa_ops.product_intersect(a, b)
    assert r.empty is False
    from istio_tpu.ops.regex_dfa import dfa_matches_host
    assert dfa_matches_host(a, r.witness)
    assert dfa_matches_host(b, r.witness)


def test_product_disjoint_and_inclusion():
    a = compile_regex("^/api/")
    b = compile_regex("^/static/")
    assert dfa_ops.languages_disjoint(a, b) is True
    narrow = compile_regex("^/api/v1/")
    assert dfa_ops.language_includes(a, narrow) is True
    assert dfa_ops.language_includes(narrow, a) is False


def test_complement_flips_membership():
    a = compile_regex("^abc$")
    na = dfa_ops.complement(a)
    from istio_tpu.ops.regex_dfa import dfa_matches_host
    assert dfa_matches_host(a, b"abc") and not dfa_matches_host(na, b"abc")
    assert not dfa_matches_host(a, b"zz") and dfa_matches_host(na, b"zz")


def test_accepted_strings_respects_forbid():
    a = compile_regex("^x[ab]$")
    ws = dfa_ops.accepted_strings(a, limit=4,
                                  forbid=frozenset({"xa"}))
    decoded = [w.decode() for w in ws]
    assert "xa" not in decoded and "xb" in decoded


# ---------------------------------------------------------------------------
# atom semantics
# ---------------------------------------------------------------------------

def _sem(text: str) -> A.AtomSem:
    return A.atom_sem(parse(text), FINDER)


def test_atom_eq_disjoint_and_implies():
    a = _sem('request.method == "GET"')
    b = _sem('request.method == "POST"')
    assert A.atoms_disjoint(a, b) is True
    assert A.atom_implies(a, a) is True
    neq = _sem('request.method != "POST"')
    assert A.atom_implies(a, neq) is True
    assert A.atoms_disjoint(b, neq) is True


def test_opaque_polarity_never_self_implies():
    """The m- and n-literals of ONE undecidable atom share a source
    but are mutually exclusive — implication across polarities would
    let a predicate shadow its own negation (unsound)."""
    sem = A.atom_sem(parse('request.path.startsWith(source.user)'),
                     FINDER)
    assert sem.kind == "opaque"
    neg = A.negate(sem)
    assert A.atom_implies(sem, neg) is None
    assert A.atom_implies(neg, sem) is None
    assert A.atom_implies(sem, sem) is True
    assert A.atoms_disjoint(sem, neg) is True
    # eqv literals: same guarantee
    ev = _sem("source.namespace == source.user")
    nev = A.negate(ev)
    assert ev.kind == "eqv"
    assert A.atom_implies(ev, nev) is None
    assert A.atom_implies(ev, ev) is True


def test_atom_eq_implies_regex():
    eq = _sem('request.path == "/api/v1/x"')
    rx = _sem('"^/api/".matches(request.path)')
    assert A.atom_implies(eq, rx) is True
    assert A.atom_implies(rx, eq) is None      # not decidable that way


def test_probe_subject_default_semantics():
    sem = _sem('(request.headers["k"] | "dflt") == "v"')
    assert sem.kind == "eq" and sem.subject.kind == "map"
    assert sem.subject.has_default and sem.subject.default == "dflt"
    bag = A.solve_subjects([sem], FINDER)
    assert bag == {"request.headers": {"k": "v"}}
    # satisfying eq-to-the-default keeps the key ABSENT
    sem2 = _sem('(request.headers["k"] | "dflt") == "dflt"')
    assert A.solve_subjects([sem2], FINDER) == {}


def test_solve_unsat_and_slot_slot():
    a = _sem('request.method == "GET"')
    b = _sem('request.method == "POST"')
    with pytest.raises(A.WitnessUnsat):
        A.solve_subjects([a, b], FINDER)
    eqv = _sem("source.namespace == source.user")
    bag = A.solve_subjects([eqv, _sem('source.user == "sa1"')], FINDER)
    assert bag["source.namespace"] == bag["source.user"] == "sa1"


# ---------------------------------------------------------------------------
# the witness property (seeded, satellite requirement)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [3, 11, 20260803])
def test_every_shadow_conflict_finding_ships_replayable_witness(seed):
    for case in corpus.make_analyzer_faults(seed):
        rep = analyze_rules(case.rules, FINDER,
                            deny_idx=case.deny_idx,
                            allow_idx=case.allow_idx,
                            check_totality=False)
        sem_findings = [f for f in rep.findings
                        if f.code in (SHADOWED_RULE,
                                      ALLOW_DENY_CONFLICT)]
        if case.kind in (SHADOWED_RULE, ALLOW_DENY_CONFLICT):
            assert sem_findings, f"seed {seed}: {case.kind} missed"
        by_name = {r.name: r for r in case.rules}
        for f in sem_findings:
            assert f.witness is not None and f.confirmed
            for rname in f.rules:
                prog = OracleProgram.from_ast(
                    _rule_ast(by_name[rname]), FINDER)
                assert prog.evaluate(DictBag(dict(f.witness))) is True, \
                    f"witness does not replay for {rname}"


def test_clean_world_raises_nothing():
    rules = corpus.make_analyzer_clean_rules(5)
    rep = analyze_rules(rules, FINDER,
                        deny_idx=tuple(range(len(rules))),
                        check_totality=False)
    assert rep.findings == []


# ---------------------------------------------------------------------------
# scoping, totality, budget
# ---------------------------------------------------------------------------

def test_namespace_scoping_blocks_cross_ns_shadow():
    r1 = Rule(name="a", match='request.method == "GET"',
              namespace="ns1")
    r2 = Rule(name="b", match='request.method == "GET"',
              namespace="ns2")
    rep = analyze_rules([r1, r2], FINDER, check_totality=False)
    assert not [f for f in rep.findings if f.code == SHADOWED_RULE]
    # default-ns rule covers every namespace → shadow fires
    r0 = Rule(name="g", match='request.method == "GET"')
    rep = analyze_rules([r0, r1], FINDER, check_totality=False)
    hits = [f for f in rep.findings if f.code == SHADOWED_RULE]
    assert hits and hits[0].rules == ("g", "a")


def test_non_total_flagged_and_guarded_predicate_clean():
    hard = Rule(name="hard", match='request.method == "GET"')
    guarded = Rule(name="soft",
                   match='(request.method | "GET") == "GET"')
    rep = analyze_rules([hard, guarded], FINDER)
    nt = {f.rules[0] for f in rep.findings if f.code == NON_TOTAL}
    assert nt == {"hard"}


def test_budget_findings():
    boom = Rule(name="boom",
                match='"(a|b)*a(a|b){13}$".matches(request.path)')
    rep = analyze_rules([boom], FINDER, check_totality=False)
    assert [f.code for f in rep.errors] == [STATE_BUDGET]

    # DNF blowup: (a1||b1)&&(a2||b2)&&... doubles conjunctions per
    # clause — 9 clauses > the cap of 128 → WARNING + host fallback
    clause = '(request.method == "m{i}" || source.namespace == "n{i}")'
    match = " && ".join(clause.replace("{i}", str(i)) for i in range(9))
    rep = analyze_rules([Rule(name="wide", match=match)], FINDER,
                        check_totality=False)
    assert DNF_BUDGET in {f.code for f in rep.warnings}


# ---------------------------------------------------------------------------
# planes
# ---------------------------------------------------------------------------

def test_plane_equivalence_proved_for_reordered_conjuncts():
    p1 = ('destination.service == "a.ns1.svc" && '
          'request.method == "GET"')
    p2 = ('request.method == "GET" && '
          'destination.service == "a.ns1.svc"')
    assert check_plane_pairs([("r", p1, p2)], FINDER) == []


def test_plane_divergence_isolated_with_witness():
    pairs, diverge_at = corpus.make_plane_divergence_pairs(17)
    fs = check_plane_pairs(pairs, FINDER)
    div = [f for f in fs if f.code == PLANE_DIVERGENCE]
    assert len(div) == 1
    assert f"route{diverge_at}" in div[0].rules
    assert div[0].witness is not None and div[0].confirmed


# ---------------------------------------------------------------------------
# route table + snapshot orchestration
# ---------------------------------------------------------------------------

def _route_world(specs):
    from istio_tpu.pilot.model import Config, ConfigMeta, Port, Service
    from istio_tpu.pilot.route_nfa import RouteTable

    host = "svc0.default.svc.cluster.local"
    services = [Service(hostname=host, address="10.9.1.1",
                        ports=(Port("http", 9080, "HTTP"),))]
    rules = [Config(ConfigMeta(type="route-rule", name=f"rr{i}",
                               namespace="default"), spec)
             for i, spec in enumerate(specs)]
    return RouteTable(services, {host: rules})


def test_route_precedence_shadow_detected():
    rt = _route_world([
        {"destination": {"name": "svc0"}, "precedence": 2,
         "match": {"request": {"headers": {
             "uri": {"prefix": "/api/"}}}},
         "route": [{"labels": {"version": "v1"}}]},
        {"destination": {"name": "svc0"}, "precedence": 1,
         "match": {"request": {"headers": {
             "uri": {"prefix": "/api/v1/"}}}},
         "route": [{"labels": {"version": "v2"}}]},
    ])
    rep = analyze_route_table(rt)
    hits = [f for f in rep.findings if f.code == SHADOWED_ROUTE]
    assert len(hits) == 1 and "rr1" in hits[0].rules[1]
    assert hits[0].witness is not None
    # disjoint prefixes at equal precedence: clean
    rt2 = _route_world([
        {"destination": {"name": "svc0"}, "precedence": 1,
         "match": {"request": {"headers": {
             "uri": {"prefix": "/api/"}}}},
         "route": [{"labels": {"version": "v1"}}]},
        {"destination": {"name": "svc0"}, "precedence": 1,
         "match": {"request": {"headers": {
             "uri": {"prefix": "/static/"}}}},
         "route": [{"labels": {"version": "v2"}}]},
    ])
    assert analyze_route_table(rt2).findings == []


def test_snapshot_analysis_action_aware():
    """A narrower rule with DIFFERENT actions is layered policy (no
    shadow); with the SAME action it is dead config (shadow)."""
    from istio_tpu.runtime.config import SnapshotBuilder
    from istio_tpu.runtime.store import MemStore
    from istio_tpu.testing.workloads import MESH_MANIFEST

    def build(narrow_handler):
        s = MemStore()
        s.set(("handler", "istio-system", "denyall"),
              {"adapter": "denier", "params": {}})
        s.set(("handler", "istio-system", "prom"),
              {"adapter": "prometheus", "params": {"metrics": []}})
        s.set(("rule", "istio-system", "broad"), {
            "match": 'destination.service == "a.ns1.svc"',
            "actions": [{"handler": "denyall", "instances": []}]})
        s.set(("rule", "istio-system", "narrow"), {
            "match": 'destination.service == "a.ns1.svc" && '
                     'connection.mtls',
            "actions": [{"handler": narrow_handler, "instances": []}]})
        return SnapshotBuilder(MESH_MANIFEST).build(s)

    same = analyze_snapshot(build("denyall"))
    assert SHADOWED_RULE in same.codes()
    layered = analyze_snapshot(build("prom"))
    assert SHADOWED_RULE not in layered.codes()


def test_admission_delta_not_masked_by_preexisting_error():
    """A pre-existing config error (landed before the hook) must not
    mask NEW errors: the delta key includes the finding message, so
    two distinct ill-typed rules never collapse to one key."""
    from istio_tpu.kube.admission import (register_analysis_admission,
                                          register_istio_admission)
    from istio_tpu.kube.fake import AdmissionDenied, FakeKubeCluster

    cluster = FakeKubeCluster()
    # 'old-bad' lands UNGATED (before the analyzer hook registers)
    cluster.create({"kind": "rule",
                    "metadata": {"name": "old-bad",
                                 "namespace": "istio-system"},
                    "spec": {"match": 'ghost.attr == "x"',
                             "actions": []}})
    register_istio_admission(cluster)
    register_analysis_admission(
        cluster, default_manifest=corpus.ANALYZER_MANIFEST)
    with pytest.raises(AdmissionDenied):
        cluster.create({"kind": "rule",
                        "metadata": {"name": "new-bad",
                                     "namespace": "istio-system"},
                        "spec": {"match": 'other.attr == "y"',
                                 "actions": []}})
    # and a clean write still passes despite the pre-existing error
    cluster.create({"kind": "rule",
                    "metadata": {"name": "fine",
                                 "namespace": "istio-system"},
                    "spec": {"match": 'request.method == "GET"',
                             "actions": []}})


def test_debug_analysis_endpoint():
    from istio_tpu.introspect import IntrospectServer
    from istio_tpu.runtime import RuntimeServer, ServerArgs
    from istio_tpu.testing import workloads
    from istio_tpu.utils import tracing

    store = workloads.make_store(18)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=16,
        default_manifest=workloads.MESH_MANIFEST))
    intro = IntrospectServer(runtime=srv, trace_capacity=0)
    intro.start()
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{intro.port}/debug/analysis",
                timeout=30) as r:
            payload = json.loads(r.read())
        assert payload["generation"] >= 1
        assert payload["n_errors"] == 0 and payload["n_warnings"] == 0
        assert "findings" in payload and "wall_ms" in payload
        # memoized per revision: second scrape is the cached report
        with urllib.request.urlopen(
                f"http://127.0.0.1:{intro.port}/debug/analysis",
                timeout=30) as r:
            assert json.loads(r.read()) == payload
    finally:
        intro.close()
        srv.close()
        tracing.shutdown()
