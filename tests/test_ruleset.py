"""Ruleset compiler conformance: the batched DNF/matmul matcher must
agree with the oracle (3-valued: matched / not-matched / error) on every
boolean corpus predicate, evaluated as one batch over many bags.

Mirrors the reference pattern of one shared table driving multiple
engines (mixer/pkg/il/testing/tests.go consumed by compiler, interpreter
and evaluator tests).
"""
import numpy as np
import pytest

from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.attribute.types import ValueType
from istio_tpu.compiler.layout import InternTable, Tensorizer
from istio_tpu.compiler.ruleset import (Rule, RuleSetProgram, compile_ruleset)
from istio_tpu.expr.checker import AttributeDescriptorFinder, TypeError_
from istio_tpu.expr.oracle import EvalError, OracleProgram
from istio_tpu.expr.parser import ParseError
from istio_tpu.testing.corpus import CORPUS, CORPUS_MANIFEST

FINDER = AttributeDescriptorFinder(CORPUS_MANIFEST)


def _bool_cases():
    """Corpus cases whose expression type-checks to BOOL."""
    out = []
    for c in CORPUS:
        if c.compile_err is not None:
            continue
        try:
            prog = OracleProgram(c.e, FINDER)
        except (ParseError, TypeError_):
            continue
        if prog.result_type == ValueType.BOOL:
            out.append(c)
    return out


BOOL_CASES = _bool_cases()
ALL_INPUTS = [c.input for c in CORPUS if c.compile_err is None]


def oracle_verdict(text, bag):
    try:
        v = bool(OracleProgram(text, FINDER).evaluate(bag))
        return (v, not v, False)
    except EvalError:
        return (False, False, True)


def eval_ruleset(prog: RuleSetProgram, bags):
    tz = Tensorizer(prog.layout, prog.interner)
    batch = tz.tensorize(bags)
    m, n, e = prog(batch)
    m, n, e = np.array(m), np.array(n), np.array(e)
    # overlay host-fallback rules exactly as the dispatcher does
    for ridx in prog.host_fallback:
        for b, bag in enumerate(bags):
            m[b, ridx], n[b, ridx], e[b, ridx] = prog.host_eval(ridx, bag)
    return m, n, e


def test_corpus_predicates_as_one_ruleset():
    """All boolean corpus predicates as one ruleset × all corpus inputs
    as one batch; every (rule, bag) cell must match the oracle."""
    rules = [Rule(name=f"r{i}", match=c.e) for i, c in enumerate(BOOL_CASES)]
    prog = compile_ruleset(rules, FINDER)
    bags = [bag_from_mapping(inp) for inp in ALL_INPUTS]
    m, n, e = eval_ruleset(prog, bags)
    for ridx, c in enumerate(BOOL_CASES):
        for b, inp in enumerate(ALL_INPUTS):
            want = oracle_verdict(c.e, bag_from_mapping(inp))
            got = (bool(m[b, ridx]), bool(n[b, ridx]), bool(e[b, ridx]))
            assert got == want, (
                f"rule {c.e!r} on input {inp!r}: got {got}, want {want} "
                f"(fallback={prog.fallback_reason.get(ridx)}")


def test_empty_match_always_matches():
    prog = compile_ruleset([Rule(name="r", match="")], FINDER)
    bags = [bag_from_mapping({}), bag_from_mapping({"a": 1})]
    m, n, e = eval_ruleset(prog, bags)
    assert m.all() and not n.any() and not e.any()


def test_const_false_never_matches():
    prog = compile_ruleset([Rule(name="r", match="false")], FINDER)
    m, n, e = eval_ruleset(prog, [bag_from_mapping({})])
    assert not m.any() and n.all() and not e.any()


def test_non_bool_match_rejected():
    with pytest.raises(TypeError_):
        compile_ruleset([Rule(name="r", match='"str"')], FINDER)


def test_short_circuit_error_suppression():
    """false && <error> must be not-matched, true || <error> matched —
    the M/N recurrences encode IL short-circuit (compiler.go:373/:354)."""
    rules = [
        Rule(name="a", match='a == 3 && as == "nope"'),   # a=2 → def false
        Rule(name="b", match='a == 2 || as == "nope"'),   # as absent, a=2
        Rule(name="c", match='a == 2 && as == "nope"'),   # as absent → err
        Rule(name="d", match='as == "x" || a == 2'),      # as absent → err
    ]
    prog = compile_ruleset(rules, FINDER)
    m, n, e = eval_ruleset(prog, [bag_from_mapping({"a": 2})])
    assert (bool(m[0, 0]), bool(e[0, 0])) == (False, False)
    assert (bool(m[0, 1]), bool(e[0, 1])) == (True, False)
    assert (bool(m[0, 2]), bool(e[0, 2])) == (False, True)
    assert (bool(m[0, 3]), bool(e[0, 3])) == (False, True)


def test_namespace_masking():
    rules = [Rule(name="default", match="", namespace=""),
             Rule(name="ns1", match="", namespace="ns1"),
             Rule(name="ns2", match="", namespace="ns2")]
    prog = compile_ruleset(rules, FINDER)
    req = np.asarray([prog.namespace_id("ns1"), prog.namespace_id("other")])
    mask = np.asarray(prog.namespace_mask(req))
    assert mask.tolist() == [[True, True, False], [True, False, False]]


def test_attribute_masks():
    rules = [Rule(name="r0", match='a == 2 && request.header["host"] == "x"')]
    prog = compile_ruleset(rules, FINDER)
    names = prog.attr_names[0]
    assert "a" in names and "request.header" in names
    assert ("request.header", "host") in names
    cols = [prog.layout.slot_of("a"),
            prog.layout.derived_slot_of("request.header", "host")]
    assert all(prog.attr_mask[0, c] for c in cols)


def test_atom_dedup_across_rules():
    rules = [Rule(name=f"r{i}", match=f'a == 2 && b == {i}') for i in range(20)]
    prog = compile_ruleset(rules, FINDER)
    # `a == 2` shared: 1 + 20 atoms, not 40
    assert prog.n_atoms == 21


def test_fallback_rule_is_isolated():
    """A rule needing host eval must not poison device rules."""
    rules = [Rule(name="dev", match="a == 2"),
             Rule(name="host", match='ar[as] == "v"')]  # dynamic key
    prog = compile_ruleset(rules, FINDER)
    assert 1 in prog.host_fallback and 0 not in prog.host_fallback
    m, n, e = eval_ruleset(prog, [bag_from_mapping(
        {"a": 2, "as": "k", "ar": {"k": "v"}})])
    assert bool(m[0, 0]) and bool(m[0, 1])


def test_large_ruleset_matches_oracle_spot():
    """1k synthetic rules in the Bookinfo style; spot-check agreement."""
    rng = np.random.default_rng(0)
    rules = []
    for i in range(1000):
        svc = f"svc{i % 50}.ns.svc.cluster.local"
        parts = [f'destination.service == "{svc}"']
        if i % 3 == 0:
            parts.append(f'source.namespace != "ns{i % 7}"')
        if i % 5 == 0:
            parts.append(f'request.header["cookie"] == "user{i % 11}"')
        rules.append(Rule(name=f"r{i}", match=" && ".join(parts)))
    prog = compile_ruleset(rules, FINDER)
    assert not prog.host_fallback
    bags = []
    for b in range(32):
        bag = {"destination.service":
               f"svc{rng.integers(0, 60)}.ns.svc.cluster.local",
               "source.namespace": f"ns{rng.integers(0, 8)}"}
        if rng.random() < 0.7:
            bag["request.header"] = {"cookie": f"user{rng.integers(0, 12)}"}
        bags.append(bag_from_mapping(bag))
    m, n, e = eval_ruleset(prog, bags)
    idx = rng.integers(0, 1000, size=60)
    for ridx in idx:
        for b in range(32):
            want = oracle_verdict(rules[ridx].match, bags[b])
            got = (bool(m[b, ridx]), bool(n[b, ridx]), bool(e[b, ridx]))
            assert got == want, (rules[ridx].match, b)
