"""Tier-1 hook for scripts/mtls_smoke.py: the CI gate that the secure
serving plane keeps securing — a real CA signs serving + workload
certs over the CSR wire, strict-mTLS Checks carry the VERIFIED peer
SPIFFE identity into the device-compiled RBAC plane with EXACT
SnapshotOracle parity (spoofed source.user overridden), the
authentication boundary stays typed (UNAUTHENTICATED for a SPIFFE-less
cert, handshake refusal for no cert), and the serving identity rotates
under live closed-loop traffic with zero dropped requests plus
identity_rotate forensics. Runs main() in-process (the audit_smoke
pattern); skips only when the rig has no PKI backend at all."""
import importlib.util
import os
import sys

import pytest

from istio_tpu.secure.backend import available_backends

if not available_backends():
    pytest.skip("mtls smoke needs a PKI backend (cryptography or the "
                "openssl CLI)", allow_module_level=True)


def test_mtls_smoke_main():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "mtls_smoke.py")
    spec = importlib.util.spec_from_file_location("mtls_smoke", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
        rc = mod.main(n_checks=16, rotations=2, workers=2)
    finally:
        sys.modules.pop(spec.name, None)
    assert rc == 0
