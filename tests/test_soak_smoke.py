"""Tier-1 wrapper for scripts/soak_smoke.py — the whole-mesh chaos
soak must pass its recovery gates in-process, twice, with the SAME
seed producing the SAME injection schedule and the SAME gate verdicts
(the seed/replay contract), inside a hard wall-clock budget."""
import importlib.util
import os
import sys
import time

import pytest

SCRIPT = os.path.join(os.path.dirname(__file__), os.pardir,
                      "scripts", "soak_smoke.py")
WALL_BUDGET_S = 90.0


def _run(seed: int) -> dict:
    spec = importlib.util.spec_from_file_location(
        "soak_smoke", SCRIPT)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["soak_smoke"] = mod
    sink: dict = {}
    try:
        spec.loader.exec_module(mod)
        rc = mod.main(seed=seed, result_sink=sink)
    finally:
        sys.modules.pop("soak_smoke", None)
    assert rc == 0, f"soak smoke failed (seed {seed})"
    return sink


@pytest.mark.filterwarnings("ignore")
def test_soak_smoke_deterministic():
    t0 = time.monotonic()
    a = _run(0)
    b = _run(0)
    wall = time.monotonic() - t0

    # seed/replay contract: same seed -> byte-identical injection
    # schedule and identical gate verdicts
    assert a["schedule"] == b["schedule"], \
        "same seed produced different injection schedules"
    assert a["gates"] == b["gates"], (
        f"same seed produced different gate verdicts: "
        f"{a['gates']} vs {b['gates']}")
    assert a["all_ok"] and b["all_ok"]

    # >= 3 distinct fault kinds injected AND explained
    assert len(a["metrics"]["soak_fault_kinds"]) >= 3, \
        a["metrics"]["soak_fault_kinds"]
    assert a["metrics"]["soak_violations_after_recovery"] == 0
    assert a["metrics"]["soak_explainability_rate"] == 1.0
    assert a["restarts"] == 1

    assert wall <= WALL_BUDGET_S, (
        f"soak smoke pair took {wall:.1f}s "
        f"(budget {WALL_BUDGET_S}s)")
