"""Pilot: model validation, route compilation, shared route-NFA parity,
discovery REST + cache invalidation, agent hot-restart epochs.

Reference patterns: pilot/pkg/proxy/envoy/config_test.go golden files,
pilot/pkg/proxy/envoy/mock/discovery.go, agent tests.
"""
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from istio_tpu.pilot import (AggregateRegistry, Config, ConfigMeta,
                             IstioConfigStore, MemoryConfigStore,
                             MemoryRegistry, Port, Service,
                             ValidationError)
from istio_tpu.pilot.agent import Agent, CertWatcher, Proxy
from istio_tpu.pilot.discovery import DiscoveryService
from istio_tpu.pilot.envoy_config import (build_bootstrap,
                                          build_outbound_clusters,
                                          build_outbound_listeners)
from istio_tpu.pilot.route_nfa import RouteTable
from istio_tpu.pilot.routes import (build_http_route, build_route_match,
                                    build_virtual_host, cluster_name)

HTTP = Port("http", 80, "HTTP")
GRPC = Port("grpc", 7070, "GRPC")
MONGO = Port("mongo", 27017, "MONGO")


def _svc(name: str, ports=(HTTP,), addr="10.1.0.1") -> Service:
    return Service(hostname=f"{name}.default.svc.cluster.local",
                   address=addr, ports=tuple(ports))


def _rule(name, spec, ns="default") -> Config:
    return Config(ConfigMeta(type="route-rule", name=name, namespace=ns),
                  spec)


@pytest.fixture()
def world():
    registry = MemoryRegistry()
    reviews = _svc("reviews", (HTTP, GRPC))
    ratings = _svc("ratings", addr="10.1.0.2")
    db = _svc("db", (MONGO,), addr="10.1.0.3")
    registry.add_service(reviews, [("10.0.0.1", {"version": "v1"},
                                    "us-central1-a"),
                                   ("10.0.0.2", {"version": "v2"})])
    registry.add_service(ratings, [("10.0.0.3", {})])
    registry.add_service(db, [("10.0.0.4", {})])
    store = MemoryConfigStore()
    return registry, store, reviews, ratings


def test_config_validation():
    store = MemoryConfigStore()
    with pytest.raises(ValidationError):
        store.create(_rule("bad", {"route": [{"weight": 50}]}))  # no dest
    with pytest.raises(ValidationError):
        store.create(_rule("bad2", {"destination": {"name": "x"},
                                    "route": [{"weight": 55},
                                              {"weight": 25}]}))
    store.create(_rule("ok", {"destination": {"name": "x"},
                              "route": [{"weight": 75}, {"weight": 25}]}))
    with pytest.raises(ValidationError):
        store.create(Config(ConfigMeta(type="nope", name="x"), {}))


def test_route_match_translation():
    m = build_route_match({"request": {"headers": {
        "uri": {"prefix": "/api"},
        "cookie": {"regex": "^(.*?;)?(user=jason)(;.*)?$"},
        "x-flag": {"exact": "on"}}}})
    assert m["prefix"] == "/api"
    assert {"name": "x-flag", "value": "on"} in m["headers"]
    assert any(h.get("regex") for h in m["headers"])


def test_weighted_route_and_policies(world):
    registry, store, reviews, _ = world
    store.create(_rule("split", {
        "destination": {"name": "reviews"},
        "precedence": 2,
        "route": [{"labels": {"version": "v1"}, "weight": 80},
                  {"labels": {"version": "v2"}, "weight": 20}],
        "httpReqRetries": {"simpleRetry": {"attempts": 3}},
        "mirror": {"labels": {"version": "v2"}}}))
    cfg = IstioConfigStore(store)
    rules = cfg.route_rules(reviews.hostname)
    route = build_http_route(rules[0], reviews, HTTP)
    wc = route["weighted_clusters"]["clusters"]
    assert [c["weight"] for c in wc] == [80, 20]
    assert "version=v1" in wc[0]["name"]
    assert route["retry_policy"]["num_retries"] == 3
    assert route["shadow"]["cluster"].endswith("version=v2")
    vh = build_virtual_host(reviews, HTTP, cfg)
    assert vh["routes"][-1]["cluster"] == cluster_name(reviews.hostname,
                                                       HTTP)
    assert "reviews" in vh["domains"]
    assert f"{reviews.hostname}:80" in vh["domains"]


def test_clusters_and_circuit_breaker(world):
    registry, store, reviews, ratings = world
    store.create(_rule("split", {
        "destination": {"name": "reviews"},
        "route": [{"labels": {"version": "v1"}, "weight": 100}]}))
    store.create(Config(ConfigMeta(type="destination-policy",
                                   name="cb", namespace="default"),
                        {"destination": {"name":
                                         ratings.hostname},
                         "loadBalancing": {"name": "LEAST_CONN"},
                         "circuitBreaker": {"simpleCb": {
                             "maxConnections": 10,
                             "httpConsecutiveErrors": 3,
                             "httpDetectionInterval": "5s"}}}))
    cfg = IstioConfigStore(store)
    clusters = build_outbound_clusters(registry.services(), cfg)
    names = [c["name"] for c in clusters]
    assert cluster_name(reviews.hostname, HTTP,
                        {"version": "v1"}) in names
    ratings_cluster = next(c for c in clusters
                           if c["name"] ==
                           "out.ratings.default.svc.cluster.local|http")
    assert ratings_cluster["lb_type"] == "least_request"
    assert ratings_cluster["circuit_breakers"]["default"][
        "max_connections"] == 10
    assert ratings_cluster["outlier_detection"]["consecutive_5xx"] == 3


def test_listeners_and_bootstrap(world):
    registry, store, *_ = world
    cfg = IstioConfigStore(store)
    listeners = build_outbound_listeners(registry.services(), cfg,
                                         {"mixer_address": "mixer:9091"})
    by_name = {l["name"]: l for l in listeners}
    assert "http_0.0.0.0_80" in by_name
    assert "tcp_0.0.0.0_27017" in by_name      # mongo is TCP
    hcm = by_name["http_0.0.0.0_80"]["filters"][0]["config"]
    assert hcm["rds"]["route_config_name"] == "80"
    assert [f["name"] for f in hcm["filters"]] == ["mixer", "router"]
    boot = build_bootstrap({"discovery_address": "pilot:8080",
                            "mixer_address": "mixer:9091",
                            "zipkin_address": "zipkin:9411"})
    cnames = [c["name"] for c in boot["cluster_manager"]["clusters"]]
    assert {"rds", "lds", "mixer_server", "zipkin"} <= set(cnames)
    assert boot["tracing"]["http"]["driver"]["type"] == "zipkin"


def test_route_nfa_matches_host_oracle(world):
    registry, store, reviews, ratings = world
    store.create(_rule("jason", {
        "destination": {"name": "reviews"}, "precedence": 2,
        "match": {"request": {"headers": {
            "cookie": {"regex": "^(.*?;)?(user=jason)(;.*)?$"}}}},
        "route": [{"labels": {"version": "v2"}}]}))
    store.create(_rule("api", {
        "destination": {"name": "reviews"}, "precedence": 1,
        "match": {"request": {"headers": {"uri": {"prefix": "/api/"}}}},
        "route": [{"labels": {"version": "v1"}}]}))
    store.create(_rule("exact", {
        "destination": {"name": "ratings"},
        "match": {"request": {"headers": {
            "uri": {"exact": "/healthz"},
            "x-debug": {"presence": True}}}},
        "route": [{"labels": {}}]}))
    cfg = IstioConfigStore(store)
    table = RouteTable(registry.services(), {
        reviews.hostname: cfg.route_rules(reviews.hostname),
        ratings.hostname: cfg.route_rules(ratings.hostname)})

    rng = np.random.default_rng(3)
    requests = []
    for i in range(64):
        req = {"destination.service":
               (reviews if i % 2 else ratings).hostname,
               "request.path": rng.choice(
                   ["/api/v1/reviews", "/healthz", "/other"]),
               "request.headers": {}}
        if rng.random() < 0.5:
            req["request.headers"]["cookie"] = rng.choice(
                ["user=jason", "s=1;user=jason;x=2", "user=mary"])
        if rng.random() < 0.5:
            req["request.headers"]["x-debug"] = "1"
        requests.append(req)
    got = table.select(requests)
    for b, req in enumerate(requests):
        assert got[b] == table.select_host(req), (b, req)
    # spot semantic checks
    jason = table.select([{
        "destination.service": reviews.hostname,
        "request.path": "/api/x",
        "request.headers": {"cookie": "a;user=jason"}}])[0]
    assert table.route_for(jason).rule.meta.name == "jason"
    api = table.select([{
        "destination.service": reviews.hostname,
        "request.path": "/api/x", "request.headers": {}}])[0]
    assert table.route_for(api).rule.meta.name == "api"


def test_discovery_rest_and_cache(world):
    registry, store, reviews, _ = world
    ds = DiscoveryService(registry, store)
    port = ds.start()
    try:
        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5) as r:
                return json.loads(r.read())

        sds = get(f"/v1/registration/{reviews.hostname}|http")
        assert {h["ip_address"] for h in sds["hosts"]} == \
            {"10.0.0.1", "10.0.0.2"}
        node = "sidecar~10.0.0.1~pod~cluster.local"
        cds = get(f"/v1/clusters/istio-proxy/{node}")
        assert any(c["name"].startswith("in.") for c in cds["clusters"])
        lds = get(f"/v1/listeners/istio-proxy/{node}")
        assert lds["listeners"]
        rds = get(f"/v1/routes/80/istio-proxy/{node}")
        assert any(vh["name"].startswith("reviews")
                   for vh in rds["virtual_hosts"])
        # cache: repeated call is a hit; a config change runs the
        # SCOPED publish sweep — in this single-namespace world every
        # entry depends on the changed scope, so all drop (the
        # scoped-retention cases live in tests/test_discovery.py)
        n = ds.cache_size
        get(f"/v1/routes/80/istio-proxy/{node}")
        assert ds.cache_size == n
        store.create(_rule("newrule", {
            "destination": {"name": "reviews"},
            "route": [{"labels": {"version": "v1"}}]}))
        assert ds.cache_size == 0
        rds2 = get(f"/v1/routes/80/istio-proxy/{node}")
        vh = next(v for v in rds2["virtual_hosts"]
                  if v["name"].startswith("reviews"))
        assert "version=v1" in vh["routes"][0]["cluster"]
        # /v1/az/{cluster}/{node} (discovery.go:601)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/az/istio-proxy/{node}",
                timeout=5) as r:
            assert r.read() == b"us-central1-a"
    finally:
        ds.stop()


class FakeProxy(Proxy):
    def __init__(self, fail_epochs=()):
        self.fail_epochs = set(fail_epochs)
        self.started = []
        self.cleaned = []

    def run(self, config, epoch, abort):
        self.started.append((epoch, config))
        if epoch in self.fail_epochs:
            raise RuntimeError("boom")
        abort.wait()

    def cleanup(self, epoch):
        self.cleaned.append(epoch)


def test_agent_epochs_and_retry():
    proxy = FakeProxy()
    agent = Agent(proxy)
    agent.schedule_config_update({"v": 1})
    time.sleep(0.1)
    assert agent.active_epochs() == [0]
    agent.schedule_config_update({"v": 1})   # no change → no new epoch
    time.sleep(0.1)
    assert agent.active_epochs() == [0]
    agent.schedule_config_update({"v": 2})   # hot restart → epoch 1
    time.sleep(0.1)
    assert 1 in agent.active_epochs()
    agent.close()
    assert agent.active_epochs() == []
    assert set(proxy.cleaned) >= {0, 1}

    crashy = FakeProxy(fail_epochs={0})
    agent2 = Agent(crashy)
    agent2.schedule_config_update({"v": 1})
    deadline = time.time() + 5
    while time.time() < deadline and len(crashy.started) < 2:
        time.sleep(0.05)
    assert len(crashy.started) >= 2           # backoff retry respawned
    agent2.close()


def test_cert_watcher(tmp_path):
    cert = tmp_path / "cert.pem"
    cert.write_text("AAA")
    changes = []
    w = CertWatcher([str(tmp_path)], changes.append, poll_s=0.05)
    w.start()
    time.sleep(0.15)
    assert changes == []
    cert.write_text("BBB")
    deadline = time.time() + 5
    while time.time() < deadline and not changes:
        time.sleep(0.05)
    assert len(changes) == 1
    w.stop()


def test_aggregate_registry(world):
    registry, *_ = world
    extra = MemoryRegistry()
    extra.add_service(_svc("external", addr="10.9.9.9"), [("10.2.0.1", {})])
    agg = AggregateRegistry([registry, extra])
    names = [s.hostname for s in agg.services()]
    assert "external.default.svc.cluster.local" in names
    assert len(names) == 4
    assert agg.get_service("external.default.svc.cluster.local")
    assert agg.host_instances({"10.2.0.1"})


# ---------------------------------------------------------------------------
# mesh config bootstrap (model.DefaultMeshConfig + bootstrap initMesh)
# ---------------------------------------------------------------------------

def test_mesh_defaults_and_yaml_overlay():
    from istio_tpu.pilot.mesh import (apply_mesh_config_defaults,
                                      default_mesh_config)
    mesh = default_mesh_config()
    assert mesh["proxy_listen_port"] == 15001
    assert mesh["ingress_controller_mode"] == "STRICT"
    assert mesh["default_config"]["proxy_admin_port"] == 15000

    overlaid = apply_mesh_config_defaults("""
mixer_address: mixer:9091
rds_refresh_delay_s: 10
default_config:
  discovery_address: pilot:15003
  drain_duration_s: 45
""")
    assert overlaid["mixer_address"] == "mixer:9091"
    assert overlaid["rds_refresh_delay_s"] == 10
    assert overlaid["default_config"]["drain_duration_s"] == 45
    # untouched fields keep defaults
    assert overlaid["proxy_listen_port"] == 15001
    assert overlaid["default_config"]["binary_path"] == \
        "/usr/local/bin/envoy"


def test_mesh_config_rejections():
    import pytest
    from istio_tpu.pilot.mesh import (MeshConfigError,
                                      apply_mesh_config_defaults)
    with pytest.raises(MeshConfigError, match="unknown mesh config"):
        apply_mesh_config_defaults("not_a_field: 1")
    with pytest.raises(MeshConfigError, match="unknown proxy config"):
        apply_mesh_config_defaults("default_config:\n  nope: 1")
    with pytest.raises(MeshConfigError, match="invalid port"):
        apply_mesh_config_defaults("proxy_listen_port: 99999")
    with pytest.raises(MeshConfigError, match="invalid duration"):
        apply_mesh_config_defaults("connect_timeout_s: -1")
    with pytest.raises(MeshConfigError, match="ingress_controller_mode"):
        apply_mesh_config_defaults("ingress_controller_mode: SOMETIMES")
    with pytest.raises(MeshConfigError, match="auth_policy"):
        apply_mesh_config_defaults("auth_policy: MAYBE")


def test_mesh_init_chain_and_watch(tmp_path):
    import time
    from istio_tpu.pilot.mesh import MeshWatcher, init_mesh

    # missing file → defaults + warning (server.go:250-252)
    warnings = []
    mesh = init_mesh(config_file=str(tmp_path / "absent.yaml"),
                     overrides={"mixer_address": "m:9091"},
                     on_warn=warnings.append)
    assert mesh["mixer_address"] == "m:9091"
    assert warnings and "using default" in warnings[0]

    # live reload: good edit applies, bad edit keeps the old config
    cfg = tmp_path / "mesh.yaml"
    cfg.write_text("mixer_address: a:1\n")
    seen, errors = [], []
    w = MeshWatcher(str(cfg), seen.append, poll_s=0.05,
                    on_error=errors.append)
    w.start()
    try:
        cfg.write_text("mixer_address: b:2\n")
        deadline = time.time() + 5
        while not seen and time.time() < deadline:
            time.sleep(0.02)
        assert seen and seen[-1]["mixer_address"] == "b:2"
        cfg.write_text("proxy_listen_port: 999999\n")
        deadline = time.time() + 5
        while not errors and time.time() < deadline:
            time.sleep(0.02)
        assert errors and "invalid port" in errors[0]
        assert seen[-1]["mixer_address"] == "b:2"   # old config stays
    finally:
        w.stop()


def test_route_nfa_synthetic_world_parity():
    """1k-ish synthetic route rules: the device NFA and host oracle
    must select identical winning routes for a request batch (the
    bench workload is conformance-tested, not just timed)."""
    from istio_tpu.testing import workloads
    services, rules = workloads.make_route_world(300)
    rt = RouteTable(services, rules)
    reqs = workloads.make_route_requests(128, n_services=len(services))
    sel = rt.select(reqs)
    assert (sel != rt.default_index).sum() > 10   # workload exercises it
    for i, req in enumerate(reqs):
        assert rt.select_host(req) == sel[i], i


def test_route_select_wire_host_fallback_parity():
    """A route rule whose regex exceeds the DFA subset demotes to the
    host oracle; select_wire must fall back to the bag path and still
    agree with select_host on every request."""
    from istio_tpu.api import mixer_pb2 as pb
    from istio_tpu.api.wire import bag_to_compressed
    from istio_tpu.pilot.model import (Config, ConfigMeta, Port,
                                       Service)

    svc = Service(hostname="svc0.default.svc.cluster.local",
                  address="10.0.0.1", ports=(Port("http", 80, "HTTP"),))
    rules = {svc.hostname: [
        Config(ConfigMeta(type="route-rule", name="rr-backref",
                          namespace="default"),
               {"destination": {"name": "svc0"},
                # backreference: unsupported by the DFA compiler
                "match": {"request": {"headers": {
                    "uri": {"regex": r"^/(a+)\1$"}}}},
                "route": [{"labels": {"version": "v2"}}]}),
        Config(ConfigMeta(type="route-rule", name="rr-plain",
                          namespace="default"),
               {"destination": {"name": "svc0"},
                "match": {"request": {"headers": {
                    "uri": {"prefix": "/api/"}}}},
                "route": [{"labels": {"version": "v1"}}]}),
    ]}
    rt = RouteTable([svc], rules)
    assert rt.program.host_fallback      # the backref rule demoted
    reqs = [{"destination.service": svc.hostname, "request.path": p}
            for p in ("/aaaa", "/aaa", "/api/x", "/other")]
    wires = []
    for r in reqs:
        msg = pb.CompressedAttributes()
        bag_to_compressed(r, msg=msg)
        wires.append(msg.SerializeToString())
    got = rt.select_wire(wires)
    for i, r in enumerate(reqs):
        assert got[i] == rt.select_host(r), (i, r)


def test_route_select_wire_without_native_shim():
    """With the native tensorizer unavailable, select_wire serves the
    python decode path — same winners."""
    from istio_tpu.api import mixer_pb2 as pb
    from istio_tpu.api.wire import bag_to_compressed
    from istio_tpu.testing import workloads

    services, rules = workloads.make_route_world(60)
    rt = RouteTable(services, rules)
    rt.native = None          # overwrite the cached_property
    reqs = workloads.make_route_requests(32, n_services=len(services))
    wires = []
    for r in reqs:
        msg = pb.CompressedAttributes()
        bag_to_compressed(r, msg=msg)
        wires.append(msg.SerializeToString())
    got = rt.select_wire(wires)
    want = rt.select(reqs)
    assert (got == want).all()


def test_route_select_wire_matches_select():
    """select_wire (C++ decode + device argmax, the sidecar-facing
    fast path) selects the same winners as select() over dict bags,
    and block=False returns a pipelineable device array."""
    import jax

    from istio_tpu.api import mixer_pb2 as pb
    from istio_tpu.api.wire import bag_to_compressed
    from istio_tpu.testing import workloads

    services, rules = workloads.make_route_world(120)
    rt = RouteTable(services, rules)
    reqs = workloads.make_route_requests(64, n_services=len(services))
    wires = []
    for r in reqs:
        msg = pb.CompressedAttributes()
        bag_to_compressed(r, msg=msg)
        wires.append(msg.SerializeToString())
    got = rt.select_wire(wires)
    want = rt.select(reqs)
    assert (got == want).all()
    async_out = rt.select_wire(wires, block=False)
    jax.block_until_ready(async_out)
    assert (np.asarray(async_out) == want).all()
