"""Delta compilation + content-addressed bank cache (ISSUE 11):

- planner stability: a single added/removed/edited namespace on a
  512-namespace plan moves at most that namespace, the bounded LPT
  rebalance honors its explicit budget, and routing of unchanged
  namespaces is byte-identical;
- DecompCache: replayed decompositions are verdict-identical, the
  cache is guarded by the manifest digest, and host-fallback entries
  replay their oracle;
- bank content keys: deterministic across rebuilds, a one-rule
  constant edit changes exactly the owning shard's key, an instance
  edit invalidates exactly the banks that reference it;
- the persistent-cache directory plumbing: resolve order (explicit →
  env), jax config round-trip, and the mixs flags.
"""
import dataclasses
import os

import numpy as np
import pytest

from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.attribute.types import ValueType
from istio_tpu.compiler.cache import DecompCache
from istio_tpu.compiler.layout import InternTable, Tensorizer
from istio_tpu.compiler.ruleset import Rule, compile_ruleset
from istio_tpu.expr.checker import AttributeDescriptorFinder
from istio_tpu.runtime.config import SnapshotBuilder
from istio_tpu.sharding.banks import (bank_content_key,
                                      snapshot_static_digest)
from istio_tpu.sharding.planner import plan_shards
from istio_tpu.testing import workloads
from istio_tpu.testing.workloads import MESH_FINDER, MESH_MANIFEST


def _preds(n: int, n_ns: int) -> list[Rule]:
    return [Rule(name=f"r{i}",
                 match=f'destination.service == "s{i}.cluster"',
                 namespace=f"ns{i % n_ns}")
            for i in range(n)]


# ---------------------------------------------------------------- plan


def test_delta_plan_pure_edit_moves_nothing():
    preds = _preds(1024, 512)
    base = plan_shards(preds, MESH_FINDER, 8)
    edited = list(preds)
    edited[0] = Rule(name="r0",
                     match='destination.service == "other.cluster" '
                           '&& request.method == "GET"',
                     namespace="ns0")
    p2 = plan_shards(edited, MESH_FINDER, 8, prev=base)
    assert p2.stability["mode"] == "delta"
    assert p2.moved_ns == []
    assert p2.ns_to_shard == base.ns_to_shard
    # routing byte-identical, known and unknown namespaces alike
    for ns in list(base.ns_to_shard)[:64] + ["ghost-a", "ghost-b"]:
        assert p2.shard_of(ns) == base.shard_of(ns)


def test_delta_plan_single_add_and_remove():
    preds = _preds(1024, 512)
    base = plan_shards(preds, MESH_FINDER, 8)
    added = preds + [Rule(name="newr", match="connection.mtls",
                          namespace="brand-new-ns")]
    p2 = plan_shards(added, MESH_FINDER, 8, prev=base)
    for ns, k in base.ns_to_shard.items():
        assert p2.ns_to_shard[ns] == k
    assert "brand-new-ns" in p2.ns_to_shard
    assert p2.stability["new"] == 1 and p2.moved_ns == []

    removed = [p for p in preds if p.namespace != "ns5"]
    p3 = plan_shards(removed, MESH_FINDER, 8, prev=base)
    assert "ns5" not in p3.ns_to_shard
    for ns, k in p3.ns_to_shard.items():
        assert base.ns_to_shard[ns] == k
    assert p3.stability["removed"] == 1 and p3.moved_ns == []


def test_delta_plan_rebalance_budget_is_bounded():
    preds = _preds(256, 32)
    base = plan_shards(preds, MESH_FINDER, 4)
    skew = dataclasses.replace(
        base, ns_to_shard={ns: 0 for ns in base.ns_to_shard})
    p0 = plan_shards(preds, MESH_FINDER, 4, prev=skew,
                     rebalance_budget=0)
    assert p0.moved_ns == []      # perfect stability at budget 0
    p3 = plan_shards(preds, MESH_FINDER, 4, prev=skew,
                     rebalance_budget=3)
    assert 0 < len(p3.moved_ns) <= 3
    assert p3.stability["moved"] == p3.moved_ns
    # every move here relocated a previously-placed namespace, and
    # the kept count books exactly those (a relocated FRESH namespace
    # must never be counted as churn — it never sat on a shard)
    assert p3.stability["moved_kept"] == p3.moved_ns
    assert p3.stability["kept"] == \
        len(skew.ns_to_shard) - len(p3.moved_ns)
    assert max(p3.shard_cost) < max(p0.shard_cost)
    # only the moved namespaces changed shard
    drift = {ns for ns in skew.ns_to_shard
             if p3.ns_to_shard[ns] != skew.ns_to_shard[ns]}
    assert drift == set(p3.moved_ns)


def test_delta_plan_shard_width_change_replans_from_scratch():
    preds = _preds(128, 16)
    base = plan_shards(preds, MESH_FINDER, 4)
    p2 = plan_shards(preds, MESH_FINDER, 8, prev=base)
    assert p2.stability.get("mode") != "delta"
    assert p2.n_shards == 8


# -------------------------------------------------------- decomp cache


def test_decomp_cache_replay_is_verdict_identical():
    rules = [Rule(name="a",
                  match='request.method == "GET" || connection.mtls'),
             Rule(name="b",
                  match='destination.service == "x" && '
                        'request.method != "POST"')]
    dc = DecompCache()
    interner = InternTable()
    rs1 = compile_ruleset(rules, MESH_FINDER, interner=interner,
                          decomp_cache=dc)
    assert dc.stats()["misses"] == 2 and dc.stats()["hits"] == 0
    rs2 = compile_ruleset(rules, MESH_FINDER, interner=interner,
                          decomp_cache=dc)
    assert dc.stats()["hits"] == 2
    bags = [bag_from_mapping({"request.method": "GET"}),
            bag_from_mapping({"destination.service": "x",
                              "request.method": "POST",
                              "connection.mtls": False}),
            bag_from_mapping({"connection.mtls": True})]
    ab1 = Tensorizer(rs1.layout, interner).tensorize(bags)
    ab2 = Tensorizer(rs2.layout, interner).tensorize(bags)
    for x, y in zip(rs1(ab1), rs2(ab2)):
        assert (np.asarray(x) == np.asarray(y)).all()


def test_decomp_cache_clears_on_manifest_change():
    dc = DecompCache()
    f1 = AttributeDescriptorFinder({"a": ValueType.BOOL})
    compile_ruleset([Rule(name="r", match="a")], f1, decomp_cache=dc)
    assert dc.stats()["entries"] == 1
    f2 = AttributeDescriptorFinder({"a": ValueType.BOOL,
                                    "b": ValueType.STRING})
    compile_ruleset([Rule(name="r", match="a")], f2, decomp_cache=dc)
    st = dc.stats()
    assert st["entries"] == 1 and st["hits"] == 0 and st["misses"] == 2


def test_decomp_cache_host_fallback_replays_oracle():
    # dnf_cap=1 blows up on the product of sums (the AND distributes
    # to 4 conjunctions) → host fallback, cached
    rules = [Rule(name="blow",
                  match='(connection.mtls || '
                        'request.method == "GET") && '
                        '(destination.service == "x" || '
                        'source.namespace == "y")')]
    dc = DecompCache()
    rs1 = compile_ruleset(rules, MESH_FINDER, dnf_cap=1,
                          decomp_cache=dc)
    assert 0 in rs1.host_fallback
    rs2 = compile_ruleset(rules, MESH_FINDER, dnf_cap=1,
                          decomp_cache=dc)
    assert 0 in rs2.host_fallback
    assert rs2.host_fallback[0] is rs1.host_fallback[0]   # reused
    assert rs2.fallback_reason[0] == rs1.fallback_reason[0]
    bag = bag_from_mapping({"connection.mtls": True,
                            "destination.service": "x"})
    assert rs2.host_eval(0, bag) == (True, False, False)


# ----------------------------------------------------------- bank keys


def _snapshot(store):
    return SnapshotBuilder(MESH_MANIFEST, InternTable()).build(store)


def _keys(snap, plan):
    static = snapshot_static_digest(
        snap, identity_attr="destination.service", buckets=(16,),
        rule_telemetry=False)
    return [bank_content_key(snap, plan, k, static)
            for k in range(plan.n_shards)]


def test_bank_content_keys_deterministic_and_delta_scoped():
    store = workloads.make_fleet_store(240, 8, seed=3)
    s1 = _snapshot(store)
    preds1 = s1.ruleset.rules[:s1.n_config_rules]
    plan1 = plan_shards(preds1, s1.finder, 4)
    keys1 = _keys(s1, plan1)
    assert len(set(keys1)) == 4

    # same store, fresh build → identical plan + keys
    s2 = _snapshot(store)
    preds2 = s2.ruleset.rules[:s2.n_config_rules]
    plan2 = plan_shards(preds2, s2.finder, 4, prev=plan1)
    assert plan2.ns_to_shard == plan1.ns_to_shard
    assert _keys(s2, plan2) == keys1

    # constant-only edit of one rule → exactly its shard's key flips
    key = next(k for k in store.list("rule") if k[1] == "ns1")
    spec = dict(store.get(key))
    spec["match"] = spec["match"].replace('"svc', '"edited-svc', 1)
    store.set(key, spec)
    s3 = _snapshot(store)
    preds3 = s3.ruleset.rules[:s3.n_config_rules]
    plan3 = plan_shards(preds3, s3.finder, 4, prev=plan1)
    keys3 = _keys(s3, plan3)
    changed = [k for k in range(4) if keys3[k] != keys1[k]]
    assert changed == [plan1.shard_of("ns1")]


def test_bank_content_keys_track_instance_edits():
    store = workloads.make_fleet_store(240, 8, seed=3)
    s1 = _snapshot(store)
    plan = plan_shards(s1.ruleset.rules[:s1.n_config_rules],
                       s1.finder, 4)
    keys1 = _keys(s1, plan)
    # the denier's checknothing instance is referenced from every
    # bank (i%3==0 rules everywhere) — editing it must invalidate all
    store.set(("instance", "istio-system", "nothing"),
              {"template": "checknothing", "params": {"x": 1}})
    s2 = _snapshot(store)
    keys2 = _keys(s2, plan)
    assert all(a != b for a, b in zip(keys1, keys2))


# ------------------------------------------------ cache dir round-trip


def test_cache_dir_resolution_and_jax_roundtrip(tmp_path, monkeypatch):
    import jax

    from istio_tpu.compiler import cache as cc

    assert cc.resolve_cache_dir("/explicit/dir") == "/explicit/dir"
    monkeypatch.setenv(cc.ENV_CACHE_DIR, str(tmp_path / "envdir"))
    assert cc.resolve_cache_dir(None) == str(tmp_path / "envdir")
    assert cc.resolve_cache_dir("/explicit/dir") == "/explicit/dir"
    monkeypatch.delenv(cc.ENV_CACHE_DIR)
    assert cc.resolve_cache_dir(None) is None

    prev_dir = jax.config.jax_compilation_cache_dir
    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        d = cc.configure_persistent_cache(str(tmp_path / "cache"),
                                          min_compile_time_s=0.25)
        assert os.path.isdir(d)
        assert jax.config.jax_compilation_cache_dir == d
        assert jax.config \
            .jax_persistent_cache_min_compile_time_secs == 0.25
        assert cc.persistent_cache_entries(d) == 0
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prev_min)


def test_mixs_flags_reach_server_args():
    from istio_tpu.cmd.__main__ import build_parser

    args = build_parser().parse_args(
        ["mixs", "--jax-compile-cache-dir", "/tmp/ccc",
         "--shards", "2", "--replicas", "3", "--no-delta-compile",
         "--shard-rebalance-budget", "5"])
    assert args.jax_compile_cache_dir == "/tmp/ccc"
    assert args.shards == 2 and args.replicas == 3
    assert args.no_delta_compile is True
    assert args.shard_rebalance_budget == 5

    from istio_tpu.runtime.server import ServerArgs
    sa = ServerArgs()
    assert sa.delta_compile is True
    assert sa.shard_rebalance_budget == 0
    assert sa.jax_compile_cache_dir is None
