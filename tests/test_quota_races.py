"""Quota-plane race regressions (PR 7 / ADVICE r5).

The device quota pool serves TWO concurrent mutation paths over one
counter buffer: the classic worker's `_flush` (gRPC Quota RPCs,
multi-quota rows, mixed fronts) and in-step sessions riding check
trips (`InlineQuotaSession`). The advisor's round-5 findings named
three gaps this file pins forever:

  * `_flush` built its tick/last arrays and applied roll updates
    OUTSIDE the locks — racing a session's optimistic `_last_tick`
    advance could stage a stale `last` (device re-rolls slots holding
    fresh consumption → over-grant) or regress it (under-grant). The
    fix orders the host bookkeeping under _lock inside the
    _counts_lock critical section on BOTH paths; the round-phased
    test here asserts window totals match a serialized memquota
    oracle EXACTLY while the two paths race on one bucket across
    window-tick boundaries.
  * `_flush` never consulted `_dedup_pending`: a retransmission
    routed classic while an in-step session was dispatched-but-
    uncommitted re-consumed instead of replaying (memquota's mutex
    would serialize). Now it defers and replays.
  * a pending replay whose consuming session committed GATE-OFF
    (grant-freely, nothing cached) resolved status-14 "quota trip
    failed"; now `_dedup_free` records the outcome and the replay
    grants freely.
"""
import threading
import time

import jax.numpy as jnp
import numpy as np

from istio_tpu.adapters.memquota import MemQuotaHandler
from istio_tpu.adapters.sdk import Env, QuotaArgs
from istio_tpu.runtime.device_quota import DeviceQuotaPool

OK, RESOURCE_EXHAUSTED = 0, 8


class Clock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def _inst(dims):
    return {"name": "rq", "dimensions": dims}


def _pool(clock, max_amount=10, duration=0.0):
    return DeviceQuotaPool(
        {"rq": {"name": "rq", "max_amount": max_amount,
                "valid_duration_s": duration}},
        n_buckets=32, clock=clock, batch_window_s=0.0005,
        max_batch=64)


def _run_instep_session(pool, rows):
    """Emulate one check trip's in-step quota leg exactly as the
    merged device program does (fused.packed_check_instep): roll every
    staged row's bucket, allocate with the contended-mixed seg kernel,
    swap the pool onto the successor counters at dispatch, commit in
    turn order. All staged rows gate ON (the emulated check matched)."""
    sess = pool.inline_begin(len(rows), rows, pool._clock())
    assert sess is not None
    granted, new_counts = pool._alloc_seg(
        sess.prev_counts, jnp.asarray(sess.buckets),
        jnp.asarray(sess.amounts), jnp.asarray(sess.be),
        jnp.asarray(sess.mx), jnp.asarray(sess.active),
        jnp.asarray(sess.ticks), jnp.asarray(sess.lasts),
        jnp.asarray(sess.rolling))
    sess.dispatched(new_counts)
    out = sess.commit(np.asarray(granted),
                      sess.active.astype(bool))
    out.update(sess.early)
    return out


def test_classic_flush_vs_instep_matches_serialized_oracle():
    """Classic `_flush` bursts RACING in-step sessions on the SAME
    rolling-window bucket, round-phased across window-tick boundaries:
    every round's granted total must equal the serialized memquota
    oracle exactly. Unit amounts make round totals order-independent,
    so the assertion is exact under ANY thread interleaving — an
    over-grant (stale `last` re-rolled fresh consumption) or
    under-grant (regressed `_last_tick`) shows up as a hard
    inequality."""
    clock = Clock()
    pool = _pool(clock, max_amount=30, duration=10.0)
    oracle = MemQuotaHandler(
        {"quotas": [{"name": "rq", "max_amount": 30,
                     "valid_duration_s": 10.0}]},
        Env("test"), clock=clock)
    dims = {"user": "hot"}
    try:
        for rnd in range(8):
            futs: list = []
            inres: list = []

            def classic():
                for _ in range(6):
                    futs.append(pool.alloc(
                        "rq", _inst(dims),
                        QuotaArgs(quota_amount=1, best_effort=True)))

            def instep():
                for _ in range(2):
                    rows = [(s, "rq", _inst(dims),
                             QuotaArgs(quota_amount=1,
                                       best_effort=True))
                            for s in range(3)]
                    inres.append(_run_instep_session(pool, rows))

            threads = [threading.Thread(target=classic),
                       threading.Thread(target=instep)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
                assert not t.is_alive(), "quota path wedged"
            got = sum(f.result(timeout=30).granted_amount
                      for f in futs)
            got += sum(r.granted_amount
                       for out in inres for r in out.values())
            want = sum(
                oracle.handle_quota(
                    "quota", _inst(dims),
                    QuotaArgs(quota_amount=1,
                              best_effort=True)).granted_amount
                for _ in range(12))
            assert got == want, (rnd, got, want)
            # quarter-window per round: ticks advance DURING the run,
            # crossing the window boundary — the regime where stale
            # tick staging over/under-grants
            clock.t += 2.5
    finally:
        pool.close()


def test_classic_flush_defers_dedup_held_by_uncommitted_session():
    """A retransmission on the CLASSIC path while an in-step session
    holds its dedup id dispatched-but-uncommitted must DEFER past the
    session's commit and REPLAY the cached outcome — memquota's mutex
    serializes those; re-consuming would double-book the window."""
    clock = Clock()
    pool = _pool(clock, max_amount=10, duration=0.0)
    dims = {"user": "alice"}
    try:
        rows = [(0, "rq", _inst(dims),
                 QuotaArgs(quota_amount=5, best_effort=True,
                           dedup_id="dd"))]
        sess = pool.inline_begin(1, rows, clock())
        assert sess is not None
        granted, new_counts = pool._alloc_seg(
            sess.prev_counts, jnp.asarray(sess.buckets),
            jnp.asarray(sess.amounts), jnp.asarray(sess.be),
            jnp.asarray(sess.mx), jnp.asarray(sess.active),
            jnp.asarray(sess.ticks), jnp.asarray(sess.lasts),
            jnp.asarray(sess.rolling))
        sess.dispatched(new_counts)
        # dispatched, NOT committed: the classic retransmission lands
        # in _flush, which must defer it (not consume a second 5)
        fut = pool.alloc("rq", _inst(dims),
                         QuotaArgs(quota_amount=5, best_effort=True,
                                   dedup_id="dd"))
        time.sleep(0.05)   # worker flushed, deferred, re-queued
        assert not fut.done(), \
            "classic flush resolved a dedup id still held by an " \
            "uncommitted in-step session"
        out = sess.commit(np.asarray(granted), np.array([True]))
        assert out[0].granted_amount == 5
        got = fut.result(timeout=10)
        assert got.granted_amount == 5       # replayed
        assert got.status_code == OK
        # single consumption: 5 of 10 left proves the retransmission
        # never re-consumed
        fresh = pool.alloc(
            "rq", _inst(dims),
            QuotaArgs(quota_amount=10, best_effort=True)).result(10)
        assert fresh.granted_amount == 5
    finally:
        pool.close()


def test_gate_off_commit_replays_grant_freely_to_pending_rows():
    """A pending replay whose consuming session committed GATE-OFF
    (quota rule inactive → grant freely, nothing consumed, nothing in
    the consumed-outcome cache) must resolve grant-freely with its
    OWN requested amount — the serialized outcome — not status-14
    'quota trip failed' (ADVICE r5 low)."""
    clock = Clock()
    pool = _pool(clock, max_amount=10, duration=0.0)
    dims = {"user": "bob"}
    try:
        s1 = pool.inline_begin(
            1, [(0, "rq", _inst(dims),
                 QuotaArgs(quota_amount=7, best_effort=True,
                           dedup_id="g1"))], clock())
        assert s1 is not None
        # gate-off trips consume nothing: the counter handle is
        # unchanged by the zeroed-amount alloc
        s1.dispatched(s1.prev_counts)
        s2 = pool.inline_begin(
            1, [(0, "rq", _inst(dims),
                 QuotaArgs(quota_amount=4, best_effort=True,
                           dedup_id="g1"))], clock())
        assert s2 is not None
        assert 0 in s2.pending_replay   # id held by s1, uncommitted
        s2.dispatched(s2.prev_counts)
        out1 = s1.commit(np.zeros(1, np.int32),
                         np.array([False]))   # gate OFF
        assert out1[0].granted_amount == 7
        assert out1[0].status_code == OK
        out2 = s2.commit(np.zeros(1, np.int32), np.zeros(1, bool))
        assert out2[0].status_code == OK, \
            f"pending replay degraded to {out2[0].status_message!r}"
        assert out2[0].granted_amount == 4   # ITS amount, freely
        # the CLASSIC path replays the gate-off outcome too (dedup-id
        # semantics are path-independent): granted freely, unconsumed
        classic = pool.alloc(
            "rq", _inst(dims),
            QuotaArgs(quota_amount=3, best_effort=True,
                      dedup_id="g1")).result(10)
        assert (classic.granted_amount, classic.status_code) == (3, OK)
        # none of the three consumed: the full window is intact
        fresh = pool.alloc(
            "rq", _inst(dims),
            QuotaArgs(quota_amount=10, best_effort=True)).result(10)
        assert fresh.granted_amount == 10
    finally:
        pool.close()


def test_consuming_commit_still_replays_to_pending_rows():
    """The consumed-outcome half of the same race (coverage pin): a
    pending replay whose consuming session committed GATE-ON replays
    the cached grant, and the window shows exactly one consumption."""
    clock = Clock()
    pool = _pool(clock, max_amount=10, duration=0.0)
    dims = {"user": "eve"}
    try:
        s1 = pool.inline_begin(
            1, [(0, "rq", _inst(dims),
                 QuotaArgs(quota_amount=6, best_effort=True,
                           dedup_id="c1"))], clock())
        granted, new_counts = pool._alloc_seg(
            s1.prev_counts, jnp.asarray(s1.buckets),
            jnp.asarray(s1.amounts), jnp.asarray(s1.be),
            jnp.asarray(s1.mx), jnp.asarray(s1.active),
            jnp.asarray(s1.ticks), jnp.asarray(s1.lasts),
            jnp.asarray(s1.rolling))
        s1.dispatched(new_counts)
        s2 = pool.inline_begin(
            1, [(0, "rq", _inst(dims),
                 QuotaArgs(quota_amount=6, best_effort=True,
                           dedup_id="c1"))], clock())
        assert 0 in s2.pending_replay
        s2.dispatched(pool.counts)
        out1 = s1.commit(np.asarray(granted), np.array([True]))
        assert out1[0].granted_amount == 6
        out2 = s2.commit(np.zeros(1, np.int32), np.zeros(1, bool))
        assert (out2[0].granted_amount, out2[0].status_code) == (6, OK)
        fresh = pool.alloc(
            "rq", _inst(dims),
            QuotaArgs(quota_amount=10, best_effort=True)).result(10)
        assert fresh.granted_amount == 4     # 10 - one consumption
    finally:
        pool.close()
