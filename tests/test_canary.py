"""Unit tests for the config canary (istio_tpu/canary): recorder
sampling/ring semantics, corpus codec roundtrip, divergence
classification + waivers, and gate mode behavior. The end-to-end
record→replay→veto path over real device plans lives in
tests/test_canary_smoke.py."""
import datetime

import pytest

from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.canary import (CanaryConfig, CanaryEntry, ConfigCanary,
                              ReplayResult, TrafficRecorder,
                              diff_decisions, entry_from_json,
                              entry_to_json, load_corpus, save_corpus)
from istio_tpu.attribute.compressed import encode
from istio_tpu.runtime.dispatcher import CheckResponse


class _Snap:
    """Minimal snapshot stand-in for recorder name resolution."""

    def __init__(self, names):
        self._names = list(names)

    def qualified_rule_names(self):
        return self._names


def _resp(status=0, dur=5.0, uses=10_000, deny=-1, quota=()):
    r = CheckResponse()
    r.status_code = status
    r.valid_duration_s = dur
    r.valid_use_count = uses
    r.deny_rule = deny
    r.active_quota_rules = tuple(quota)
    return r


def _bags(n):
    return [bag_from_mapping({
        "destination.service": f"svc{i}.ns1.svc.cluster.local",
        "request.method": "GET"}) for i in range(n)]


# ---------------------------------------------------------------------------
# recorder
# ---------------------------------------------------------------------------

def test_recorder_stride_sampling():
    rec = TrafficRecorder(capacity=64, sample_every=3)
    snap = _Snap(["r0"])
    rec.tap(_bags(10), [_resp() for _ in range(10)], snap, "destination.service")
    # rows 0, 3, 6, 9 sampled
    assert rec.stats()["sampled"] == 4
    assert rec.stats()["seen"] == 10
    # stride continues across batches: counter at 10 → first kept is 12
    rec.tap(_bags(4), [_resp() for _ in range(4)], snap,
            "destination.service")
    assert rec.stats()["sampled"] == 5


def test_recorder_ring_bounds_and_eviction():
    rec = TrafficRecorder(capacity=8, sample_every=1)
    snap = _Snap(["r0"])
    for k in range(4):
        rec.tap(_bags(4), [_resp(status=k) for _ in range(4)], snap,
                "destination.service")
    st = rec.stats()
    assert st["entries"] == 8
    assert st["evicted"] == 8
    # ring keeps the NEWEST rows: statuses 2 and 3 only
    statuses = {e.status for e in rec.corpus()}
    assert statuses == {2, 3}


def test_recorder_device_surface_overrides_merged_response():
    """The fused tap records the DEVICE planes, not the post-host-
    merge response: a host-overlay adapter's status must not enter
    the corpus (the shadow replay runs with empty handlers, so
    recording it would veto an UNCHANGED config forever)."""
    import numpy as np

    rec = TrafficRecorder(capacity=8)
    snap = _Snap(["ns1/r0", "ns1/r1"])
    # merged responses say DENIED (a host list adapter fired)...
    responses = [_resp(status=5, dur=0.5, uses=1, deny=-1),
                 _resp(status=7, deny=1)]
    # ...but the device surface answered OK / denied-by-rule-1
    device = (np.array([0, 7], np.int32),
              np.array([9.0, 2.5], np.float32),
              np.array([20_000, 500], np.int32),
              np.array([0, 1], np.int32))
    rec.tap(_bags(2), responses, snap, "destination.service",
            device=device)
    a, b = rec.corpus()
    assert a.status == 0 and a.deny_rule == ""
    assert a.valid_duration_s == 5.0       # clamped to the default cap
    assert a.valid_use_count == 10_000
    assert b.status == 7 and b.deny_rule == "ns1/r1"
    assert b.valid_duration_s == 2.5 and b.valid_use_count == 500


def test_recorder_corpus_resolves_names_and_namespace():
    rec = TrafficRecorder(capacity=8)
    snap = _Snap(["ns1/deny-rule", "ns1/quota-rule"])
    rec.tap(_bags(1), [_resp(status=7, deny=0, quota=(1,))], snap,
            "destination.service")
    (e,) = rec.corpus()
    assert e.deny_rule == "ns1/deny-rule"
    assert e.quota_rules == ("ns1/quota-rule",)
    assert e.namespace == "ns1"
    assert e.status == 7


# ---------------------------------------------------------------------------
# corpus codec
# ---------------------------------------------------------------------------

def test_corpus_file_roundtrip(tmp_path):
    now = datetime.datetime(2026, 8, 3, 12, 0,
                            tzinfo=datetime.timezone.utc)
    values = {
        "destination.service": "a.ns1.svc.cluster.local",
        "request.size": 123,
        "request.time": now,
        "response.duration": datetime.timedelta(milliseconds=250),
        "source.ip": b"\x00" * 10 + b"\xff\xff" + bytes([9, 8, 7, 6]),
        "request.headers": {"cookie": "session=1"},
        "connection.mtls": True,
    }
    e = CanaryEntry(ca=encode(bag_from_mapping(values)), status=7,
                    valid_duration_s=2.5, valid_use_count=42,
                    deny_rule="ns1/r1", namespace="ns1",
                    quota_rules=("ns1/q",), trace_id="t1", t=1.0)
    path = str(tmp_path / "corpus.json")
    assert save_corpus(path, [e]) == 1
    (back,) = load_corpus(path)
    assert back.status == 7 and back.deny_rule == "ns1/r1"
    assert back.quota_rules == ("ns1/q",)
    bag = back.bag()
    for name, want in values.items():
        got, ok = bag.get(name)
        assert ok, name
        assert got == want, name


def test_entry_json_is_json_safe():
    import json

    e = CanaryEntry(ca=encode(bag_from_mapping({"a": 1})))
    json.dumps(entry_to_json(e))
    assert entry_from_json(entry_to_json(e)).valid_use_count == 10_000


# ---------------------------------------------------------------------------
# differ
# ---------------------------------------------------------------------------

def _entry(status=0, dur=5.0, uses=10_000, deny="", quota=()):
    return CanaryEntry(ca=encode(bag_from_mapping({"a": 1})),
                       status=status, valid_duration_s=dur,
                       valid_use_count=uses, deny_rule=deny,
                       quota_rules=tuple(quota))


def _replay(rows):
    return ReplayResult(
        status=[r.get("status", 0) for r in rows],
        valid_duration_s=[r.get("dur", 5.0) for r in rows],
        valid_use_count=[r.get("uses", 10_000) for r in rows],
        deny_rule=[r.get("deny", "") for r in rows],
        quota_rules=[tuple(r.get("quota", ())) for r in rows],
        n_rows=len(rows), wall_s=0.01)


def test_diff_classifies_all_kinds():
    entries = [
        _entry(status=7, deny="ns/d"),            # deny → OK flip
        _entry(),                                  # OK → deny flip
        _entry(status=7, dur=2.5, deny="ns/d"),    # TTL change
        _entry(quota=("ns/q",)),                   # quota drops out
        _entry(),                                  # unchanged
    ]
    rep = diff_decisions(entries, _replay([
        {},                                        # now OK
        {"status": 7, "deny": "ns/d2"},            # now denied
        {"status": 7, "dur": 1.25, "deny": "ns/d"},
        {},                                        # quota gone
        {},
    ]))
    assert rep.n_rows == 5 and rep.n_divergent == 4
    assert rep.by_kind == {"status_flip": 2, "precondition": 1,
                           "quota": 1}
    assert rep.per_rule["ns/d"]["status_flip"] == 1
    assert rep.per_rule["ns/d2"]["status_flip"] == 1
    assert rep.per_rule["ns/d"]["precondition"] == 1
    assert rep.per_rule["ns/q"]["quota"] == 1
    assert rep.divergence_rate == pytest.approx(0.8)
    ex = rep.per_rule["ns/q"]["exemplars"][0]
    assert ex["kind"] == "quota" and ex["bag"]


def test_diff_waivers_excluded_from_gating_rate():
    entries = [_entry(status=7, deny="ns/waived"), _entry()]
    rep = diff_decisions(entries, _replay([{}, {}]),
                         waivers=("ns/waived",))
    assert rep.n_divergent == 0 and rep.n_waived == 1
    assert rep.divergence_rate == 0.0
    # reported regardless, marked waived
    assert rep.per_rule["ns/waived"]["waived"] is True
    assert "ns/waived" not in rep.diverging_rules()


def test_diff_row_mismatch_raises():
    with pytest.raises(ValueError):
        diff_decisions([_entry()], _replay([{}, {}]))


# ---------------------------------------------------------------------------
# gate
# ---------------------------------------------------------------------------

def test_canary_config_rejects_bad_mode():
    with pytest.raises(ValueError):
        CanaryConfig(mode="audit")


def test_gate_off_mode_never_replays():
    canary = ConfigCanary(CanaryConfig(mode="off"))
    assert canary.gate(None, None, None) is None
    assert canary.evaluations == 0


def test_gate_abstains_without_corpus():
    canary = ConfigCanary(CanaryConfig(mode="gate"))
    # no recorded traffic: must publish (abstain), not veto
    assert canary.gate(None, object(), object()) is None
    assert canary.reports() == []


def test_gate_threshold_is_strictly_greater_than(monkeypatch):
    canary = ConfigCanary(CanaryConfig(mode="gate",
                                       max_divergence_rate=0.5))
    entries = [_entry(status=7, deny="ns/d"), _entry()]
    monkeypatch.setattr(canary.recorder, "corpus",
                        lambda limit=None: entries)
    monkeypatch.setattr(
        "istio_tpu.canary.gate.replay_entries",
        lambda *a, **k: _replay([{}, {}]))
    monkeypatch.setattr(
        "istio_tpu.canary.gate.confirm_exemplars",
        lambda *a, **k: None)
    # rate 0.5 == threshold → publish
    assert canary.gate(None, _Snap([]), object()) is None
    assert canary.reports()[-1].verdict == "warn"
    # tighter threshold → veto
    canary2 = ConfigCanary(CanaryConfig(mode="gate",
                                        max_divergence_rate=0.25))
    monkeypatch.setattr(canary2.recorder, "corpus",
                        lambda limit=None: entries)
    rej = canary2.gate(None, _Snap([]), object())
    assert rej is not None and "ns/d" in str(rej)
    assert rej.report.verdict == "veto"


def test_divergent_publish_rebaselines_recorder(monkeypatch):
    """A divergent candidate that PUBLISHES (warn mode / sub-threshold
    gate) becomes the live config: rows recorded under the old one
    must not keep re-reporting the accepted divergence — the ring is
    cleared and refills under the new config. A zero-divergence
    publish keeps the corpus (continuity)."""
    canary = ConfigCanary(CanaryConfig(mode="warn"))
    snap = _Snap(["ns/d"])
    monkeypatch.setattr(
        "istio_tpu.canary.gate.confirm_exemplars",
        lambda *a, **k: None)

    canary.recorder.tap(_bags(2), [_resp(status=7, deny=0), _resp()],
                        snap, "destination.service")
    assert canary.recorder.stats()["entries"] == 2
    # replay matches the recorded decisions → publish, ring kept
    monkeypatch.setattr(
        "istio_tpu.canary.gate.replay_entries",
        lambda *a, **k: _replay([{"status": 7, "deny": "ns/d"}, {}]))
    assert canary.gate(None, _Snap([]), object()) is None
    assert canary.reports()[-1].verdict == "publish"
    assert canary.recorder.stats()["entries"] == 2
    canary.on_published()                  # clean publish: ring kept
    assert canary.recorder.stats()["entries"] == 2
    # replay flips the denied row → warn-mode publish; the ring is
    # cleared only AFTER the dispatcher swap (on_published) so the
    # old dispatcher's final taps land before the wipe
    monkeypatch.setattr(
        "istio_tpu.canary.gate.replay_entries",
        lambda *a, **k: _replay([{}, {}]))
    assert canary.gate(None, _Snap([]), object()) is None
    assert canary.reports()[-1].verdict == "warn"
    assert canary.recorder.stats()["entries"] == 2   # pre-swap: kept
    canary.on_published()
    assert canary.recorder.stats()["entries"] == 0   # post-swap wipe


def test_gate_vetoes_rule_wipe(monkeypatch):
    """A candidate with ZERO rules compiles to no fused plan — the
    most catastrophic swap must not slip through the abstain path:
    the gate diffs against a synthetic allow-everything replay and
    vetoes when recorded denies flip."""
    class _EmptySnap(_Snap):
        rules = ()
        revision = 9

    canary = ConfigCanary(CanaryConfig(mode="gate"))
    monkeypatch.setattr(
        canary.recorder, "corpus",
        lambda limit=None: [_entry(status=7, deny="ns/d"), _entry()])
    monkeypatch.setattr(
        "istio_tpu.canary.gate.confirm_exemplars",
        lambda *a, **k: None)
    rej = canary.gate(None, _EmptySnap([]), None)   # plan is None
    assert rej is not None and "ns/d" in str(rej)
    assert rej.report.by_kind == {"status_flip": 1}


def test_gate_fails_open_on_internal_error(monkeypatch):
    canary = ConfigCanary(CanaryConfig(mode="gate"))
    monkeypatch.setattr(canary.recorder, "corpus",
                        lambda limit=None: [_entry()])
    monkeypatch.setattr(
        "istio_tpu.canary.gate.replay_entries",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")))
    assert canary.gate(None, object(), object()) is None


def test_server_args_reject_bad_canary_mode():
    from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs

    with pytest.raises(ValueError):
        RuntimeServer(MemStore(), ServerArgs(canary="audit"))


def test_identical_config_with_host_overlay_rule_publishes():
    """Regression: a rule whose CHECK action stays host-side (a
    CASE_INSENSITIVE_STRINGS list — unfusable, runtime/fused.py) used
    to record its HOST deny status while the shadow replay (empty
    handlers) answered OK, permanently vetoing even an unchanged
    config. The recorder now captures the device surface, so an
    identical rebuild must publish with zero divergences."""
    from istio_tpu.attribute.bag import bag_from_mapping
    from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs
    from istio_tpu.runtime.batcher import pad_to_bucket
    from istio_tpu.testing import corpus

    store = MemStore()
    store.set(("handler", "istio-system", "ci"), {
        "adapter": "list",
        "params": {"overrides": ["ALLOWED"],
                   "entry_type": "CASE_INSENSITIVE_STRINGS",
                   "blacklist": False}})
    store.set(("instance", "istio-system", "srcns"), {
        "template": "listentry", "params": {"value": "source.namespace"}})
    store.set(("rule", "ns1", "host-deny"), {
        "match": 'destination.service == "a.ns1.svc.cluster.local"',
        "actions": [{"handler": "ci.istio-system",
                     "instances": ["srcns.istio-system"]}]})
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=8, buckets=(8,),
        canary="gate", rulestats_drain_s=0,
        default_manifest=corpus.ANALYZER_MANIFEST))
    srv.controller.debounce_s = 60.0
    try:
        plan = srv.controller.dispatcher.fused
        assert plan is not None and plan.host_actions, \
            "world no longer exercises a host-overlay action"
        bags = [bag_from_mapping({
            "destination.service": "a.ns1.svc.cluster.local",
            "source.namespace": "not-allowed",
            "request.method": "GET"}) for _ in range(4)]
        resps = srv.check_batch_preprocessed(pad_to_bucket(bags, (8,)))
        assert resps[0].status_code != 0     # host adapter denies live
        entries = srv.canary.recorder.corpus()
        assert entries and all(e.status == 0 for e in entries), \
            "recorder captured the host-merged status, not the " \
            "device surface"
        d0 = srv.controller.dispatcher
        d1 = srv.controller.rebuild()        # identical config
        assert d1 is not d0, "identical host-overlay config was vetoed"
        assert srv.canary.reports()[-1].n_divergent == 0
    finally:
        srv.close()
