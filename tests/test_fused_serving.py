"""Fused serving path — device engine wired into the check path.

Proves the VERDICT r1 requirement: the fused PolicyEngine serves real
config-driven checks, and its verdicts agree with the generic
host-adapter dispatcher field-by-field across denier / fused list /
host-only list / host-fallback-predicate / namespace-scoped rules.
Anchor: mixer/pkg/server/server.go:92 (the served runtime is the
benchmarked runtime)."""
import pytest

from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.models.policy_engine import (NOT_FOUND, OK,
                                            PERMISSION_DENIED)
from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs
from istio_tpu.runtime.fused import build_fused_plan


def _store() -> MemStore:
    s = MemStore()
    # fused: static case-sensitive whitelist over a bare attribute
    s.set(("handler", "istio-system", "nswhitelist"), {
        "adapter": "list",
        "params": {"overrides": ["default", "prod"], "blacklist": False,
                   "caching_ttl_s": 30.0}})
    # fused: blacklist over a map-derived slot
    s.set(("handler", "istio-system", "uablacklist"), {
        "adapter": "list",
        "params": {"overrides": ["badbot"], "blacklist": True}})
    # host: fallback expression (`|` default) keeps list.go semantics
    s.set(("handler", "istio-system", "verwhitelist"), {
        "adapter": "list",
        "params": {"overrides": ["v1", "v2"], "blacklist": False}})
    # fused since r4: static REGEX entries lower to a device DFA bank
    s.set(("handler", "istio-system", "rxlist"), {
        "adapter": "list",
        "params": {"overrides": ["^/api/"], "entry_type": "REGEX",
                   "blacklist": True}})
    # fused since r4: CIDR entries lower to device prefix compares
    s.set(("handler", "istio-system", "ipblock"), {
        "adapter": "list",
        "params": {"overrides": ["10.0.0.0/8", "2001:db8::/32"],
                   "entry_type": "IP_ADDRESSES", "blacklist": True}})
    # host: case-insensitive matching has no device lowering
    s.set(("handler", "istio-system", "cilist"), {
        "adapter": "list",
        "params": {"overrides": ["Mozilla"],
                   "entry_type": "CASE_INSENSITIVE_STRINGS",
                   "blacklist": True}})
    s.set(("handler", "istio-system", "denyall"), {
        "adapter": "denier",
        "params": {"status_code": PERMISSION_DENIED,
                   "status_message": "admin is off limits",
                   "valid_duration_s": 3.0, "valid_use_count": 100}})
    s.set(("instance", "istio-system", "srcns"), {
        "template": "listentry", "params": {"value": "source.namespace"}})
    s.set(("instance", "istio-system", "ua"), {
        "template": "listentry",
        "params": {"value": 'request.headers["user-agent"]'}})
    s.set(("instance", "istio-system", "appversion"), {
        "template": "listentry",
        "params": {"value": 'source.labels["version"] | "none"'}})
    s.set(("instance", "istio-system", "path"), {
        "template": "listentry", "params": {"value": "request.path"}})
    s.set(("instance", "istio-system", "srcip"), {
        "template": "listentry", "params": {"value": "source.ip"}})
    s.set(("instance", "istio-system", "nothing"), {
        "template": "checknothing", "params": {}})
    # global rules (config namespace = mesh-wide)
    s.set(("rule", "istio-system", "r0-denyadmin"), {
        "match": 'request.path.startsWith("/admin")',
        "actions": [{"handler": "denyall", "instances": ["nothing"]}]})
    s.set(("rule", "istio-system", "r1-nscheck"), {
        "match": 'destination.service == "ratings.default.svc.cluster.local"',
        "actions": [{"handler": "nswhitelist", "instances": ["srcns"]}]})
    s.set(("rule", "istio-system", "r2-uacheck"), {
        "match": "connection.mtls",
        "actions": [{"handler": "uablacklist", "instances": ["ua"]}]})
    s.set(("rule", "istio-system", "r3-version"), {
        "match": 'request.method == "POST"',
        "actions": [{"handler": "verwhitelist",
                     "instances": ["appversion"]}]})
    s.set(("rule", "istio-system", "r4-rx"), {
        "match": 'request.scheme == "http"',
        "actions": [{"handler": "rxlist", "instances": ["path"]}]})
    # host-fallback predicate (dynamic map key) with a fused-type action
    s.set(("rule", "istio-system", "r5-dynkey"), {
        "match": 'request.headers[request.method] == "x"',
        "actions": [{"handler": "denyall", "instances": ["nothing"]}]})
    # namespace-scoped rule: only for destination.service *.prod.*
    # (handler/instance refs are cross-namespace → fully qualified)
    s.set(("rule", "prod", "r6-prodonly"), {
        "match": 'request.size > 100',
        "actions": [{"handler": "denyall.istio-system",
                     "instances": ["nothing.istio-system"]}]})
    # same rule mixes a fused action (denier, first) and a host action
    # (fallback-expr whitelist, second): device status must win the
    # status tie, matching the generic path's config action order
    s.set(("rule", "istio-system", "r7-mixed"), {
        "match": 'request.method == "DELETE"',
        "actions": [{"handler": "denyall", "instances": ["nothing"]},
                    {"handler": "verwhitelist",
                     "instances": ["appversion"]}]})
    s.set(("rule", "istio-system", "r8-ip"), {
        "match": 'request.scheme == "https"',
        "actions": [{"handler": "ipblock", "instances": ["srcip"]}]})
    s.set(("rule", "istio-system", "r9-ci"), {
        "match": 'request.useragent == "x"',
        "actions": [{"handler": "cilist", "instances": ["ua"]}]})
    return s


def _bags():
    cases = [
        {"request.path": "/admin/keys"},                       # denier
        {"request.path": "/ratings/1"},                        # clean
        {"destination.service": "ratings.default.svc.cluster.local",
         "source.namespace": "default"},                       # wl pass
        {"destination.service": "ratings.default.svc.cluster.local",
         "source.namespace": "evil"},                          # wl miss
        {"connection.mtls": True,
         "request.headers": {"user-agent": "badbot"}},         # bl hit
        {"connection.mtls": True,
         "request.headers": {"user-agent": "chrome"}},         # bl miss
        {"request.method": "POST",
         "source.labels": {"version": "v2"}},                  # host wl pass
        {"request.method": "POST",
         "source.labels": {"version": "v9"}},                  # host wl miss
        {"request.method": "POST"},                            # fallback val
        {"request.scheme": "http", "request.path": "/api/x"},  # regex hit
        {"request.scheme": "http", "request.path": "/web/x"},  # regex miss
        {"request.method": "GET",
         "request.headers": {"GET": "x"}},                     # dyn-key deny
        {"request.method": "GET",
         "request.headers": {"GET": "y"}},                     # dyn-key pass
        {"destination.service": "api.prod.svc.cluster.local",
         "request.size": 500},                                 # ns rule hit
        {"destination.service": "api.other.svc.cluster.local",
         "request.size": 500},                                 # ns rule inert
        # combined: denier (rule 0) outranks whitelist miss (rule 1) —
        # lowest-rule-index-wins on both paths
        {"request.path": "/admin/x",
         "destination.service": "ratings.default.svc.cluster.local",
         "source.namespace": "evil"},
        # same-rule tie: fused denier action listed before a host
        # whitelist miss — denier's status wins on both paths
        {"request.method": "DELETE",
         "source.labels": {"version": "v9"}},
        # CIDR list (device prefix compare) — v4-mapped 16-byte hit,
        # 4-byte raw hit, v6 net hit, v4 miss
        {"request.scheme": "https",
         "source.ip": b"\x00" * 10 + b"\xff\xff" + bytes([10, 1, 2, 3])},
        {"request.scheme": "https", "source.ip": bytes([10, 0, 0, 1])},
        {"request.scheme": "https",
         "source.ip": bytes.fromhex("20010db8") + b"\x00" * 12},
        {"request.scheme": "https",
         "source.ip": b"\x00" * 10 + b"\xff\xff" + bytes([11, 1, 2, 3])},
        # case-insensitive list stays host-side on both paths
        {"request.useragent": "x",
         "request.headers": {"user-agent": "mozilla"}},
        # REGEX truncation contract: a $-free prefix hit on a truncated
        # value is definitive (deny on both paths); a truncated miss is
        # undecidable → device errs the rule and fails open, matching
        # the host's allow here because the full value has no match
        # either
        {"request.scheme": "http",
         "request.path": "/api/" + "x" * 200},
        {"request.scheme": "http",
         "request.path": "/web/" + "x" * 200},
    ]
    return [bag_from_mapping(c) for c in cases]


@pytest.fixture(scope="module")
def servers():
    fused = RuntimeServer(_store(), ServerArgs(batch_window_s=0.001,
                                               fused=True))
    generic = RuntimeServer(_store(), ServerArgs(batch_window_s=0.001,
                                                 fused=False))
    yield fused, generic
    fused.close()
    generic.close()


def test_plan_extraction(servers):
    fused, _ = servers
    plan = fused.controller.dispatcher.fused
    assert plan is not None
    snap = fused.controller.dispatcher.snapshot
    # r0 + r6 + r7 fuse (ordered comparisons lower via byte order
    # keys since r3); r5 (dynamic map key) stays host-fallback
    assert plan.fused_deny == 3
    # srcns + ua + rx path + cidr srcip; appversion (fallback expr)
    # and the case-insensitive list stay host
    assert plan.fused_lists == 4
    host_rules = {snap.rules[i].name for i in plan.host_actions}
    assert "r3-version" in host_rules    # `|` fallback expr
    assert "r4-rx" not in host_rules     # REGEX fuses since r4
    assert "r8-ip" not in host_rules     # CIDR fuses since r4
    assert "r9-ci" in host_rules         # case-insensitive: host
    assert "r5-dynkey" in host_rules     # predicate host fallback
    assert "r6-prodonly" not in host_rules   # GTR now on device
    assert "CASE_INSENSITIVE_STRINGS" in plan.unfused_list_kinds
    assert "STRINGS:value-not-lowerable" in plan.unfused_list_kinds


def test_fused_matches_generic(servers):
    fused, generic = servers
    bags = _bags()
    rf = fused.check_many(bags)
    rg = generic.check_many(bags)
    for i, (a, b) in enumerate(zip(rf, rg)):
        assert a.status_code == b.status_code, \
            f"case {i}: fused={a.status_code} generic={b.status_code}"
        assert a.valid_duration_s == pytest.approx(b.valid_duration_s), i
        assert a.valid_use_count == b.valid_use_count, i
        assert a.referenced == b.referenced, i


def test_fused_statuses(servers):
    fused, _ = servers
    r = fused.check_many(_bags())
    assert r[0].status_code == PERMISSION_DENIED
    assert r[0].status_message == "admin is off limits"
    assert r[0].valid_duration_s == pytest.approx(3.0)
    assert r[0].valid_use_count == 100
    assert r[1].status_code == OK
    assert r[2].status_code == OK
    assert r[3].status_code == NOT_FOUND
    assert r[4].status_code == PERMISSION_DENIED   # blacklist hit
    assert r[5].status_code == OK
    assert r[11].status_code == PERMISSION_DENIED  # host-fallback deny
    assert r[13].status_code == PERMISSION_DENIED  # prod-ns rule
    assert r[14].status_code == OK                 # other ns: inert
    assert r[15].status_code == PERMISSION_DENIED  # lowest rule wins
    assert r[15].status_message == "admin is off limits"


def test_fused_list_edge_values_match_generic():
    """Device-lowered REGEX/CIDR lists on edge inputs — absent values,
    malformed IP byte lengths, unparseable addresses — must agree with
    the host adapter."""
    def store() -> MemStore:
        s = MemStore()
        s.set(("handler", "istio-system", "rx"), {
            "adapter": "list",
            "params": {"overrides": ["^/blocked/"],
                       "entry_type": "REGEX", "blacklist": True}})
        s.set(("handler", "istio-system", "cidr"), {
            "adapter": "list",
            "params": {"overrides": ["10.0.0.0/8"],
                       "entry_type": "IP_ADDRESSES",
                       "blacklist": False}})
        s.set(("instance", "istio-system", "path"), {
            "template": "listentry", "params": {"value": "request.path"}})
        s.set(("instance", "istio-system", "ip"), {
            "template": "listentry", "params": {"value": "source.ip"}})
        s.set(("rule", "istio-system", "r0"), {
            "match": 'request.scheme == "http"',
            "actions": [{"handler": "rx", "instances": ["path"]}]})
        s.set(("rule", "istio-system", "r1"), {
            "match": 'request.scheme == "https"',
            "actions": [{"handler": "cidr", "instances": ["ip"]}]})
        return s

    bags = [bag_from_mapping(c) for c in (
        {"request.scheme": "http"},                       # path absent
        {"request.scheme": "http", "request.path": ""},   # empty value
        {"request.scheme": "https"},                      # ip absent
        {"request.scheme": "https",
         "source.ip": b"\x01\x02\x03"},                   # 3-byte junk
        {"request.scheme": "https",
         "source.ip": bytes([10, 0, 0, 1])},              # in CIDR
    )]
    fused = RuntimeServer(store(), ServerArgs(fused=True))
    generic = RuntimeServer(store(), ServerArgs(fused=False))
    try:
        rf = fused.check_many(bags)
        rg = generic.check_many(bags)
        for i, (a, b) in enumerate(zip(rf, rg)):
            assert a.status_code == b.status_code, \
                (i, a.status_code, b.status_code)
    finally:
        fused.close()
        generic.close()


def test_ip_typed_values_keep_host_semantics():
    """Two configs that LOOK fusable but must stay host-side: a STRINGS
    list over an IP_ADDRESS-typed value (host normalizes bytes to a
    textual IP before matching — the id scan never would), and an
    IP_ADDRESSES list over a map-derived TEXT value (the device
    compares raw bytes against binary CIDR prefixes — text would flip
    verdicts). Both were reproduced as fused-vs-generic divergences in
    the r4 review."""
    def store() -> MemStore:
        s = MemStore()
        s.set(("handler", "istio-system", "strlist"), {
            "adapter": "list",
            "params": {"overrides": ["10.0.0.1"], "blacklist": False}})
        s.set(("handler", "istio-system", "iptext"), {
            "adapter": "list",
            "params": {"overrides": ["10.0.0.0/8"],
                       "entry_type": "IP_ADDRESSES",
                       "blacklist": False}})
        s.set(("instance", "istio-system", "ipinst"), {
            "template": "listentry", "params": {"value": "source.ip"}})
        s.set(("instance", "istio-system", "hdrip"), {
            "template": "listentry",
            "params": {"value": 'request.headers["x-ip"]'}})
        s.set(("rule", "istio-system", "r0"), {
            "match": 'request.scheme == "http"',
            "actions": [{"handler": "strlist", "instances": ["ipinst"]}]})
        s.set(("rule", "istio-system", "r1"), {
            "match": 'request.scheme == "https"',
            "actions": [{"handler": "iptext", "instances": ["hdrip"]}]})
        return s

    fused = RuntimeServer(store(), ServerArgs(fused=True))
    generic = RuntimeServer(store(), ServerArgs(fused=False))
    try:
        plan = fused.controller.dispatcher.fused
        assert plan.fused_lists == 0
        assert "STRINGS:value-not-lowerable" in plan.unfused_list_kinds
        assert "IP_ADDRESSES:value-not-lowerable" in \
            plan.unfused_list_kinds
        bags = [bag_from_mapping(c) for c in (
            {"request.scheme": "http",
             "source.ip": bytes([10, 0, 0, 1])},      # listed (as text)
            {"request.scheme": "http",
             "source.ip": bytes([10, 9, 9, 9])},      # not listed
            {"request.scheme": "https",
             "request.headers": {"x-ip": "10.1.2.3"}},   # in CIDR
            {"request.scheme": "https",
             "request.headers": {"x-ip": "11.1.2.3"}},   # outside
        )]
        rf = fused.check_many(bags)
        rg = generic.check_many(bags)
        assert [r.status_code for r in rg] == [OK, NOT_FOUND,
                                               OK, NOT_FOUND]
        for i, (a, g) in enumerate(zip(rf, rg)):
            assert a.status_code == g.status_code, i
    finally:
        fused.close()
        generic.close()


def test_report_parity_fused_vs_generic():
    """dispatcher.report rides the fused packed step (one bitpacked
    overlay pull) when a plan exists; adapter effects must equal the
    generic full-plane path — including namespace-scoped report rules
    and predicate-gated ones."""
    def store() -> MemStore:
        s = MemStore()
        s.set(("handler", "istio-system", "prom"), {
            "adapter": "prometheus",
            "params": {"metrics": [{"name": "hits.istio-system",
                                    "kind": "COUNTER",
                                    "label_names": ["dest"]}]}})
        s.set(("instance", "istio-system", "hits"), {
            "template": "metric",
            "params": {"value": "1",
                       "dimensions": {"dest": "destination.service"}}})
        s.set(("rule", "istio-system", "tally"), {
            "match": 'request.method == "GET"',
            "actions": [{"handler": "prom", "instances": ["hits"]}]})
        # namespace-scoped report rule: only prod-destined requests
        s.set(("rule", "prod", "tally-prod"), {
            "match": "",
            "actions": [{"handler": "prom.istio-system",
                         "instances": ["hits.istio-system"]}]})
        return s

    bags = [bag_from_mapping(c) for c in (
        {"request.method": "GET",
         "destination.service": "a.default.svc"},
        {"request.method": "POST",
         "destination.service": "a.default.svc"},   # predicate miss
        {"request.method": "GET",
         "destination.service": "b.default.svc"},
        {"request.method": "GET",
         "destination.service": "b.default.svc"},
        # prod namespace: BOTH the global rule (GET) and the prod rule
        # fire → +2; POST hits only the prod rule → +1
        {"request.method": "GET",
         "destination.service": "c.prod.svc"},
        {"request.method": "POST",
         "destination.service": "c.prod.svc"},
    )]
    want = {"a.default.svc": 1.0, "b.default.svc": 2.0,
            "c.prod.svc": 3.0}
    samples = {}
    for fused in (True, False):
        # tiny buckets: the 6-bag report must CHUNK (4+2) and pad on
        # the fused path — oversize report batches never reach the
        # device at arbitrary shapes
        srv = RuntimeServer(store(), ServerArgs(fused=fused,
                                                max_batch=4,
                                                buckets=(4,)))
        try:
            d = srv.controller.dispatcher
            assert (d.fused is not None) == fused
            d.report(bags)
            h = d.handlers["prom.istio-system"]
            samples[fused] = {
                dest: h.registry.get_sample_value(
                    "istio_tpu_hits_istio_system_total",
                    {"dest": dest})
                for dest in want}
        finally:
            srv.close()
    assert samples[True] == samples[False] == want


def test_wire_fast_path_zero_decode():
    """gRPC → C++ tensorize → device step → response, with NO python
    wire decode when every matched rule is fully fused (the mixerclient
    contract, SURVEY §2.9(a); VERDICT r1 item 4)."""
    import grpc  # noqa: F401 (skip gracefully if grpcio missing)
    from istio_tpu.api.grpc_server import MixerGrpcServer
    from istio_tpu.api.client import MixerClient
    from istio_tpu.api.wire import LazyWireBag
    from istio_tpu.runtime import MemStore

    s = MemStore()
    s.set(("handler", "istio-system", "denyall"), {
        "adapter": "denier", "params": {"status_code": PERMISSION_DENIED}})
    s.set(("instance", "istio-system", "nothing"), {
        "template": "checknothing", "params": {}})
    s.set(("rule", "istio-system", "deny-admin"), {
        "match": 'request.path.startsWith("/admin")',
        "actions": [{"handler": "denyall", "instances": ["nothing"]}]})
    srv = RuntimeServer(s, ServerArgs(batch_window_s=0.001))
    plan = srv.controller.dispatcher.fused
    if plan.native is None:
        srv.close()
        pytest.skip("native toolchain unavailable")

    parses = []
    orig = LazyWireBag._decode

    def spy(self):
        if self._values is None:
            parses.append(1)
        return orig(self)

    LazyWireBag._decode = spy
    try:
        g = MixerGrpcServer(srv)
        port = g.start()
        c = MixerClient(f"127.0.0.1:{port}")
        deny = c.check({"request.path": "/admin/x",
                        "destination.service": "a.default.svc"})
        ok = c.check({"request.path": "/ok",
                      "request.headers": {"x": "y"}})
        g.stop()
    finally:
        LazyWireBag._decode = orig
        srv.close()
    assert deny.precondition.status.code == PERMISSION_DENIED
    assert ok.precondition.status.code == OK
    # referenced attributes still populated (from device planes)
    assert len(deny.precondition.referenced_attributes.attribute_matches)
    assert parses == []


def test_short_global_dict_falls_back_to_python_path(servers):
    """A client with a shortened global-dictionary prefix can't ride
    the C++ decoder; the server must still answer correctly via the
    python wire path (grpcServer.go global dict plumbing)."""
    import grpc
    from istio_tpu.api import mixer_pb2 as pb
    from istio_tpu.api.grpc_server import MixerGrpcServer
    from istio_tpu.api.wire import bag_to_compressed

    fused, _ = servers
    g = MixerGrpcServer(fused)
    port = g.start()
    try:
        chan = grpc.insecure_channel(f"127.0.0.1:{port}")
        call = chan.unary_unary(
            "/istio.mixer.v1.Mixer/Check",
            request_serializer=pb.CheckRequest.SerializeToString,
            response_deserializer=pb.CheckResponse.FromString)
        req = pb.CheckRequest(global_word_count=10)
        bag_to_compressed({"request.path": "/admin/keys"}, 10,
                          msg=req.attributes)
        resp = call(req)
        assert resp.precondition.status.code == PERMISSION_DENIED
        chan.close()
    finally:
        g.stop()


def test_batch_check_short_global_dict(servers):
    """BatchCheck with a shortened global-dictionary prefix: every bag
    decodes through the python wire path and per-item verdicts match
    the unary short-dict behavior."""
    import grpc
    from istio_tpu.api.grpc_server import MixerGrpcServer
    from istio_tpu.api import mixer_pb2 as pb
    from istio_tpu.api.wire import (bag_to_compressed,
                                    decode_batch_check_response,
                                    encode_batch_check_request)

    fused, _ = servers
    g = MixerGrpcServer(fused)
    port = g.start()
    try:
        blobs = []
        for path in ("/admin/keys", "/ratings/1"):
            msg = pb.CompressedAttributes()
            bag_to_compressed({"request.path": path}, 10, msg=msg)
            blobs.append(msg.SerializeToString())
        chan = grpc.insecure_channel(f"127.0.0.1:{port}")
        call = chan.unary_unary(
            "/istio.mixer.v1.Mixer/BatchCheck",
            request_serializer=lambda b: b,
            response_deserializer=lambda b: b)
        raw = call(encode_batch_check_request(blobs, 10))
        codes = [pb.CheckResponse.FromString(b).precondition.status.code
                 for b in decode_batch_check_response(raw)]
        assert codes == [PERMISSION_DENIED, OK]
        chan.close()
    finally:
        g.stop()


def test_snapshot_swap_under_load():
    """A config swap must never surface compile time in-band: the old
    snapshot serves while the new one's jit buckets pre-warm (SURVEY
    hard-part #5; resolver refcount swap, resolver.go:240-247)."""
    import threading
    import time as _time

    from istio_tpu.testing import workloads

    store = workloads.make_store(300)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.001, max_batch=64, buckets=(16, 64),
        default_manifest=workloads.MESH_MANIFEST))
    try:
        bags = workloads.make_bags(64)
        srv.check_many(bags[:16])        # warm initial snapshot buckets
        srv.check_many(bags[:64])

        latencies: list[float] = []
        stop = threading.Event()

        def stream():
            i = 0
            while not stop.is_set():
                t0 = _time.perf_counter()
                srv.check(bags[i % len(bags)])
                latencies.append(_time.perf_counter() - t0)
                i += 1

        threads = [threading.Thread(target=stream, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        _time.sleep(0.3)
        baseline_n = len(latencies)
        # config change → debounce → rebuild + prewarm → atomic swap.
        # The pre-swap warm covers ONLY the (bucket, byte-tier) shapes
        # live traffic is serving (the old plan's observed set), with
        # a serving-latency backoff between compiles; the remaining
        # shapes warm post-swap in the background with the host-oracle
        # bridge covering any batch that races onto them — so the swap
        # completes in a couple of compiles' time by construction,
        # even on a loaded single core.
        store.set(("rule", "istio-system", "swap-deny"), {
            "match": 'request.path.startsWith("/swapped")',
            "actions": [{"handler": "denyall.istio-system",
                         "instances": ["nothing.istio-system"]}]})
        deadline = _time.time() + 30
        while _time.time() < deadline:
            r = srv.check(bag_from_mapping(
                {"request.path": "/swapped/x"}))
            if r.status_code == PERMISSION_DENIED:
                break
            _time.sleep(0.05)
        else:
            raise AssertionError("swap never took effect")
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert len(latencies) > baseline_n   # streaming continued
        worst = max(latencies)
        # Without prewarm a post-swap request pays the full in-band
        # trace+compile (the whole ~10s rebuild). With prewarm the
        # worst case is GIL starvation while the controller thread
        # traces the new snapshot's jaxprs (pure-Python, seconds at
        # 300 rules) — real but bounded, and well under the in-band
        # compile cost this test exists to catch.
        assert worst < 4.0, f"request saw {worst:.2f}s during swap"
        fast = sorted(latencies)[int(len(latencies) * 0.95)]
        assert fast < 0.5, f"p95 {fast:.2f}s during swap"
    finally:
        srv.close()


def test_swap_warm_bridge_serves_oracle_without_device():
    """While a warm is pending, a batch at a not-yet-compiled shape
    must serve through the CPU oracle (same verdicts, zero device
    packer calls — no in-band XLA trace); once the warm ends the
    device path resumes. The mechanism behind swap-under-load's ≤30s
    completion: un-warmed shapes never block or compile in-band."""
    from istio_tpu.runtime.batcher import pad_to_bucket

    srv = RuntimeServer(_store(), ServerArgs(
        batch_window_s=0.001, max_batch=8, buckets=(8,),
        initial_prewarm=False))
    try:
        d = srv.controller.dispatcher
        plan = d.fused
        bags = pad_to_bucket(
            [bag_from_mapping({"request.path": "/admin/keys"}),
             bag_from_mapping({"request.path": "/ratings/1"})], (8,))
        baseline = d.check(bags)        # compiles + registers shape
        calls: list = []
        orig = plan.packed_check
        plan.packed_check = \
            lambda *a, **k: (calls.append(1), orig(*a, **k))[1]
        plan._warmed_shapes.clear()     # shape "not yet compiled"
        plan.begin_warm()
        try:
            bridged = d.check(bags)
            assert not calls, "bridged batch still hit the device"
            assert [r.status_code for r in bridged] == \
                [r.status_code for r in baseline]
        finally:
            plan.end_warm()
        resumed = d.check(bags)
        assert calls, "device path did not resume after the warm"
        assert [r.status_code for r in resumed] == \
            [r.status_code for r in baseline]
    finally:
        srv.close()


def test_map_served_shapes_prioritizes_live_traffic():
    """The pre-swap warm set: live-served (bucket, width) pairs map
    onto the candidate plan's tiers (width → smallest holding tier);
    no observed traffic falls back to the full shape product."""
    srv = RuntimeServer(_store(), ServerArgs(
        batch_window_s=0.001, max_batch=32, buckets=(8, 32),
        initial_prewarm=False))
    try:
        plan = srv.controller.dispatcher.fused
        pairs = plan.all_warm_shapes((8, 32))
        assert plan.map_served_shapes((8, 32), set()) == pairs
        small_tier = pairs[0][1]
        sel = plan.map_served_shapes((8, 32), {(8, small_tier)})
        assert sel == [(8, small_tier)]
        # a width no tier holds maps to the largest; foreign buckets
        # are dropped
        big = max(t for _, t in pairs)
        sel = plan.map_served_shapes((8, 32), {(8, big + 1),
                                               (999, small_tier)})
        assert sel == [(8, max(t for _, t in pairs))]
    finally:
        srv.close()


def test_prewarm_treedef_matches_serving():
    """The prewarm dummy batch and every real tensorizer's batches
    must flatten to the SAME pytree treedef — a mismatch compiles a
    jit cache entry serving never hits, silently re-introducing
    in-band compile on the first real request (the exact failure the
    prewarm exists to prevent)."""
    import jax
    import numpy as np
    from istio_tpu.compiler.layout import AttributeBatch, Tensorizer
    from istio_tpu.testing import workloads

    eng = workloads.make_engine(n_rules=8, jit=False)
    lay = eng.ruleset.layout
    b = 4
    dummy = AttributeBatch(
        ids=np.zeros((b, lay.n_columns), np.int32),
        present=np.zeros((b, lay.n_columns), bool),
        map_present=np.zeros((b, max(lay.n_maps, 1)), bool),
        str_bytes=np.zeros((b, max(lay.n_byte_slots, 1),
                            lay.max_str_len), np.uint8),
        str_lens=np.zeros((b, max(lay.n_byte_slots, 1)), np.int32),
        hash_ids=np.zeros((b, lay.n_columns), np.int32))
    real = eng.tensorizer.tensorize(workloads.make_bags(b))
    plain = Tensorizer(lay, eng.ruleset.interner).tensorize(
        workloads.make_bags(b))
    td = lambda x: jax.tree_util.tree_structure(x)
    assert td(dummy) == td(real) == td(plain)


def test_fused_config_swap(servers):
    """A store change rebuilds the plan (new engine) atomically."""
    fused, _ = servers
    store = fused.controller.store
    plan_before = fused.controller.dispatcher.fused
    store.set(("rule", "istio-system", "r9-extra"), {
        "match": 'request.path.startsWith("/secret")',
        "actions": [{"handler": "denyall", "instances": ["nothing"]}]})
    fused.controller.rebuild()
    plan_after = fused.controller.dispatcher.fused
    assert plan_after is not plan_before
    r = fused.check(bag_from_mapping({"request.path": "/secret/x"}))
    assert r.status_code == PERMISSION_DENIED
    store.delete(("rule", "istio-system", "r9-extra"))
    fused.controller.rebuild()
