"""Discovery serving plane unit tests (pilot/discovery.py +
pilot/snapshot.py): versioned snapshots, scoped cache invalidation
(the regression ISSUE 15 satellite 1 pins: a one-service change must
leave unrelated node groups' entries LIVE — clear_cache is no longer
the only invalidation path), node-group config sharing, batched
pending RDS generation, publish coalescing, shard-scoped delta push,
typed draining and start/stop cycles."""
import json
import threading
import time

import pytest

from istio_tpu.pilot.discovery import DiscoveryService
from istio_tpu.pilot.model import (Config, ConfigMeta, Port, Service)
from istio_tpu.pilot.snapshot import MESH_SCOPE, changed_scopes
from istio_tpu.testing import workloads


@pytest.fixture()
def world():
    return workloads.make_discovery_world(
        n_services=24, n_namespaces=6, replicas=2, source_ns=2,
        seed=3)


def _poll_all(ds, nodes, meta, replicas=2):
    for idx, n in enumerate(nodes):
        k = meta["ns_of"][idx // replicas]
        ds.list_routes(str(8000 + k), "c", n)
        ds.list_clusters("c", n)


def test_one_service_change_keeps_unrelated_entries_live(world):
    """ISSUE 15 satellite 1: registry churn in one namespace must NOT
    drop other namespaces' scoped cache entries (the old
    clear_cache-on-event design repaid full generation fleet-wide for
    any single-service change)."""
    registry, store, nodes, meta = world
    ds = DiscoveryService(registry, store)
    _poll_all(ds, nodes, meta)
    ks = sorted(meta["nodes_by_ns"])
    churn_k, victim_k = ks[-1], ks[-2]
    victim_node = meta["nodes_by_ns"][victim_k][0]
    victim_host = meta["hosts_by_ns"][victim_k][0]
    ds.list_endpoints(f"{victim_host}|http")    # sds entry for victim

    # one-SERVICE change: a new service appears in churn_k
    registry.add_service(
        Service(hostname=f"late.ns{churn_k}.svc.cluster.local",
                address="10.9.9.9",
                ports=(Port("http", 8000 + churn_k, "HTTP"),)),
        [("10.9.9.10", {})])
    assert ds.generation == 2

    stats = ds._cache.stats()
    # unrelated RDS group entry survived the sweep and serves as a hit
    h0 = stats["hits"]
    ds.list_routes(str(8000 + victim_k), "c", victim_node)
    assert ds._cache.stats()["hits"] == h0 + 1
    # unrelated SDS entry likewise
    m0 = ds._cache.stats()["misses"]
    ds.list_endpoints(f"{victim_host}|http")
    assert ds._cache.stats()["misses"] == m0
    # the churned namespace's RDS regenerated with the new service
    churn_node = meta["nodes_by_ns"][churn_k][0]
    body = json.loads(ds.list_routes(str(8000 + churn_k), "c",
                                     churn_node))
    names = [v["name"] for v in body["virtual_hosts"]]
    assert any(n.startswith(f"late.ns{churn_k}") for n in names)
    # parity with the unscoped single-node path after the change
    path = f"/v1/routes/{8000 + churn_k}/c/{churn_node}"
    assert ds._route(path)[0] == ds.reference_bytes(path)


def test_identical_sidecars_share_one_generated_config(world):
    """Replicas of one service hit the same RDS group: the second
    sidecar's first poll is already a cache hit, and both serve the
    same bytes."""
    registry, store, nodes, meta = world
    ds = DiscoveryService(registry, store)
    k = meta["ns_of"][0]
    a, b = nodes[0], nodes[1]          # replicas of svc0
    body_a = ds.list_routes(str(8000 + k), "c", a)
    h0 = ds._cache.stats()["hits"]
    body_b = ds.list_routes(str(8000 + k), "c", b)
    assert ds._cache.stats()["hits"] == h0 + 1
    assert body_a == body_b


def test_batched_pending_generation_fills_all_groups(world):
    """After a publish invalidates several RDS groups, the FIRST miss
    regenerates every pending group in one batch — subsequent polls
    of the other churned groups are hits."""
    registry, store, nodes, meta = world
    ds = DiscoveryService(registry, store)
    _poll_all(ds, nodes, meta)
    # source-ns 0 has per-source groups: churn it so several RDS
    # groups invalidate at once
    src_k = 0 if meta["rules_by_ns"].get(0) else 1
    n_groups_before = ds._cache.stats()["by_endpoint"]["rds"]
    workloads.churn_discovery_rule(store, meta, src_k, 0)
    pending = len(ds._pending_rds)
    assert pending >= 1
    first = meta["nodes_by_ns"][src_k][0]
    ds.list_routes(str(8000 + src_k), "c", first)   # one miss...
    assert not ds._pending_rds                      # ...fills ALL
    h0 = ds._cache.stats()["hits"]
    for n in meta["nodes_by_ns"][src_k][1:]:
        ds.list_routes(str(8000 + src_k), "c", n)
    assert ds._cache.stats()["hits"] - h0 == \
        len(meta["nodes_by_ns"][src_k]) - 1
    assert ds._cache.stats()["by_endpoint"]["rds"] == n_groups_before


def test_source_scoped_rds_groups_differ_and_match_reference(world):
    """Source-constrained route rules give different sidecars
    different RDS bytes — each byte-exact against the unscoped
    single-node path (the batched device admission must reproduce the
    host _match_source filter exactly)."""
    registry, store, nodes, meta = world
    ds = DiscoveryService(registry, store)
    src_k = 0 if meta["rules_by_ns"].get(0) else 1
    ns_nodes = meta["nodes_by_ns"][src_k]
    port = 8000 + src_k
    bodies = set()
    for n in ns_nodes:
        path = f"/v1/routes/{port}/c/{n}"
        got = ds._route(path)[0]
        assert got == ds.reference_bytes(path), n
        bodies.add(got)
    # the world seeds source constraints in this namespace; if every
    # node saw identical routes the admission plane did nothing
    has_src = any(
        (store.get("route-rule", name, f"ns{src_k}").spec
         .get("match") or {}).get("source")
        for name in meta["rules_by_ns"][src_k])
    if has_src and len(ns_nodes) > 2:
        assert len(bodies) > 1


def test_hold_publishes_coalesces_a_churn_batch(world):
    registry, store, nodes, meta = world
    ds = DiscoveryService(registry, store)
    g0 = ds.generation
    with ds.hold_publishes():
        for tick in range(4):
            workloads.churn_discovery_rule(
                store, meta, max(meta["rules_by_ns"]), tick)
    assert ds.generation == g0 + 1


def test_changed_scopes_and_plan_stability(world):
    registry, store, nodes, meta = world
    ds = DiscoveryService(registry, store)
    snap1 = ds.snapshot
    churn_k = max(meta["rules_by_ns"])
    workloads.churn_discovery_rule(store, meta, churn_k, 0)
    snap2 = ds.snapshot
    assert changed_scopes(snap1, snap2) == {f"ns{churn_k}"}
    # namespaces keep their shards across generations (watch scope
    # keys are stable — the planner's delta-mode contract)
    for ns, shard in snap1.plan.ns_to_shard.items():
        assert snap2.plan.ns_to_shard[ns] == shard
    assert snap2.scope_reused        # no source constraint moved


def test_watch_scoped_wake_and_drain_release(world):
    registry, store, nodes, meta = world
    ds = DiscoveryService(registry, store)
    churn_k = max(meta["rules_by_ns"])
    snap = ds.snapshot
    churn_shard = snap.plan.shard_of(f"ns{churn_k}")
    other = next(ns_nodes[0] for k, ns_nodes
                 in sorted(meta["nodes_by_ns"].items())
                 if snap.plan.shard_of(f"ns{k}") != churn_shard)
    results = {}

    def park(tag, node, timeout):
        results[tag] = ds.watch(node, ds.generation, timeout)

    t1 = threading.Thread(target=park, args=(
        "scoped", meta["nodes_by_ns"][churn_k][0], 10.0))
    t2 = threading.Thread(target=park, args=("other", other, 1.0))
    t1.start()
    t2.start()
    time.sleep(0.2)
    workloads.churn_discovery_rule(store, meta, churn_k, 0)
    t1.join()
    t2.join()
    assert results["scoped"]["changed"] is True
    assert results["other"]["changed"] is False

    # draining releases parked watchers promptly
    hang = threading.Thread(target=park, args=(
        "drain", meta["nodes_by_ns"][churn_k][0], 30.0))
    hang.start()
    time.sleep(0.1)
    t0 = time.perf_counter()
    ds.begin_drain()
    hang.join(timeout=5)
    assert not hang.is_alive()
    assert time.perf_counter() - t0 < 5
    assert results["drain"]["draining"] is True


def test_start_stop_cycles(world):
    """ISSUE 15 satellite 2: the concurrent front survives repeated
    start/stop cycles, serving between each."""
    import urllib.request
    registry, store, nodes, meta = world
    ds = DiscoveryService(registry, store)
    for cycle in range(10):
        port = ds.start()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/clusters/c/{nodes[0]}",
                timeout=10) as r:
            assert r.status == 200, cycle
        ds.stop()


def test_events_during_drain_republish_on_restart(world):
    """Review regression: config/registry events landing while
    drained must not be lost — start() catches the snapshot up, so a
    restarted server never serves the pre-drain world forever."""
    registry, store, nodes, meta = world
    ds = DiscoveryService(registry, store)
    port = ds.start()
    gen = ds.generation
    ds.stop()
    registry.add_service(
        Service(hostname="late.ns0.svc.cluster.local",
                address="10.9.9.9",
                ports=(Port("http", 8000, "HTTP"),)),
        [("10.9.9.10", {})])
    assert ds.generation == gen          # generation off while drained
    port = ds.start()
    try:
        assert ds.generation == gen + 1  # caught up before serving
        node = nodes[0]
        body = json.loads(ds.list_clusters("c", node))
        assert any("late.ns0" in c["name"] for c in body["clusters"])
    finally:
        ds.stop()


def test_cross_namespace_port_join_invalidates_rds():
    """Review regression: an RDS entry's deps record the namespaces
    on its port AT BUILD TIME — a service from a NEW namespace joining
    the port must still invalidate it (port-membership diff), or the
    carried entry serves routes missing the new virtual host."""
    from istio_tpu.pilot.registry import MemoryRegistry
    from istio_tpu.pilot.model import MemoryConfigStore

    registry = MemoryRegistry()
    store = MemoryConfigStore()
    registry.add_service(
        Service(hostname="a.ns1.svc.cluster.local", address="10.0.0.1",
                ports=(Port("http", 9000, "HTTP"),)),
        [("10.1.0.1", {})])
    ds = DiscoveryService(registry, store)
    node = "sidecar~10.1.0.1~a-0.ns1~cluster.local"
    path = "/v1/routes/9000/c/" + node
    before = ds._route(path)[0]
    # cross-namespace join of the SAME port
    registry.add_service(
        Service(hostname="b.ns2.svc.cluster.local", address="10.0.0.2",
                ports=(Port("http", 9000, "HTTP"),)),
        [("10.1.0.2", {})])
    assert 9000 in set(ds._last_publish["changed_ports"])
    after = ds._route(path)[0]
    assert after != before
    assert b"b.ns2.svc.cluster.local" in after
    assert after == ds.reference_bytes(path)


def test_multi_service_node_canonical_instance_order():
    """Review regression: one node IP hosting several services must
    generate identical bytes regardless of service REGISTRATION order
    (live registries return insertion order; the snapshot path and
    the parity reference both canonicalize), and LDS/CDS stay
    byte-exact against the reference."""
    from istio_tpu.pilot.registry import MemoryRegistry
    from istio_tpu.pilot.model import MemoryConfigStore

    def build(order):
        registry = MemoryRegistry()
        store = MemoryConfigStore()
        svcs = {
            "zeta": Service(hostname="zeta.ns1.svc.cluster.local",
                            address="10.0.0.1",
                            ports=(Port("tcp", 9000, "TCP"),)),
            "alpha": Service(hostname="alpha.ns1.svc.cluster.local",
                             address="10.0.0.2",
                             ports=(Port("http", 9001, "HTTP"),)),
        }
        for name in order:
            az = "zone-" + name
            registry.add_service(svcs[name], [("10.1.0.9", {}, az)])
        return DiscoveryService(registry, store)

    node = "sidecar~10.1.0.9~multi.ns1~cluster.local"
    a = build(("zeta", "alpha"))
    b = build(("alpha", "zeta"))
    for path in (f"/v1/listeners/c/{node}", f"/v1/clusters/c/{node}"):
        ba = a._route(path)[0]
        bb = b._route(path)[0]
        assert ba == bb, path                 # order-independent
        assert ba == a.reference_bytes(path)  # and parity-exact
        assert bb == b.reference_bytes(path)
    # az picks the canonical first instance on both
    assert a.availability_zone("c", node) == \
        b.availability_zone("c", node)


def test_watch_over_capacity_degrades_to_polling(world):
    """Review regression: parked watchers hold front threads —
    watch_cap bounds them; over-capacity watchers return immediately
    (typed over_capacity) instead of parking."""
    registry, store, nodes, meta = world
    ds = DiscoveryService(registry, store, watch_cap=2)
    done = []

    def park(node):
        done.append(ds.watch(node, ds.generation, 3.0))

    threads = [threading.Thread(target=park, args=(n,))
               for n in nodes[:2]]
    for t in threads:
        t.start()
    time.sleep(0.2)
    t0 = time.perf_counter()
    third = ds.watch(nodes[2], ds.generation, 30.0)
    assert time.perf_counter() - t0 < 1.0
    assert third["over_capacity"] is True
    assert third["changed"] is False
    ds.begin_drain()
    for t in threads:
        t.join(timeout=5)
    assert len(done) == 2


def test_deleted_namespace_wakes_its_old_shard(world):
    """Review regression: a fully-deleted namespace vanishes from the
    NEW plan (shard_of falls back to the crc32 hash), but its
    watchers parked on the PREVIOUS plan's shard — the publish must
    bump both or those sidecars never learn their services
    vanished."""
    registry, store, nodes, meta = world
    ds = DiscoveryService(registry, store)
    victim_k = max(meta["hosts_by_ns"])
    node = meta["nodes_by_ns"][victim_k][0]
    results = {}

    def park():
        results["w"] = ds.watch(node, ds.generation, 10.0)

    t = threading.Thread(target=park)
    t.start()
    time.sleep(0.2)
    with ds.hold_publishes():
        # delete the namespace's rules AND services entirely
        for name in meta["rules_by_ns"].get(victim_k, ()):
            store.delete("route-rule", name, f"ns{victim_k}")
        for host in meta["hosts_by_ns"][victim_k]:
            registry.remove_service(host)
    t.join(timeout=10)
    assert results["w"]["changed"] is True
    assert f"ns{victim_k}" not in ds.snapshot.plan.ns_to_shard


def test_hold_during_drain_keeps_dirty_for_restart(world):
    """Review regression: a hold_publishes() block exiting while
    drained must LEAVE the dirty flag set so start()'s catch-up
    publish replays it (the registry-file reload path runs under
    hold and can race a stop())."""
    registry, store, nodes, meta = world
    ds = DiscoveryService(registry, store)
    ds.start()
    gen = ds.generation
    ds.stop()
    with ds.hold_publishes():
        workloads.churn_discovery_rule(
            store, meta, max(meta["rules_by_ns"]), 0)
    assert ds.generation == gen          # still drained: no publish
    ds.start()
    try:
        assert ds.generation == gen + 1  # caught up before serving
    finally:
        ds.stop()


def test_clear_cache_still_available(world):
    registry, store, nodes, meta = world
    ds = DiscoveryService(registry, store)
    ds.list_clusters("c", nodes[0])
    assert ds.cache_size > 0
    ds.clear_cache()
    assert ds.cache_size == 0


def test_mesh_scope_changes_invalidate_mesh_entries(world):
    """An egress rule rides every RDS/CDS/LDS — mesh-scoped churn
    honestly drops mesh-dependent entries AND wakes every shard."""
    registry, store, nodes, meta = world
    ds = DiscoveryService(registry, store)
    _poll_all(ds, nodes, meta)
    store.create(Config(
        ConfigMeta(type="egress-rule", name="eg", namespace="default"),
        {"destination": {"service": "httpbin.org"},
         "ports": [{"port": 8000, "protocol": "http"}]}))
    assert MESH_SCOPE in set(ds._last_publish["changed_scopes"])
    assert ds._last_publish["shards_notified"] == \
        list(range(ds._scope_shards))
    assert ds.cache_size == 0          # every entry was mesh-affected
