"""Tier-1 hook for scripts/meshlint.py — the repo-wide concurrency &
discipline gate. Running main() exercises all three legs in one shot:

  1. the seeded fixture corpus (every violation class — lock-order
     cycle/inversion/leaf/self-deadlock, hot-path host-sync, missing
     hot root, unregistered/unshaped/mislabeled metric, untyped front
     escape — is caught with a file:line witness, pragmas honored,
     clean fixture silent);
  2. the real tree is ERROR-silent;
  3. the inferred hot-path coverage is a superset of the retired
     hand-maintained HOT_SECTIONS baseline.

A second test pins leg 3's guarantee directly (acceptance criterion:
inferred coverage ⊇ HOT_SECTIONS), so a refactor of the gate script
cannot silently drop the pin."""
import importlib.util
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    path = os.path.join(REPO, "scripts", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        sys.modules.pop(spec.name, None)
        raise
    return mod


@pytest.fixture(scope="module")
def gate():
    mod = _load_script("meshlint")
    yield mod
    sys.modules.pop("meshlint", None)


def test_meshlint_gate_green(gate, capsys):
    rc = gate.main(root=REPO)
    out = capsys.readouterr().out
    assert rc == 0, f"meshlint gate failed:\n{out}"
    assert "all legs green" in out


def test_inferred_coverage_superset_of_hot_sections(gate):
    """Acceptance pin: reachability from the hot roots must cover
    every (file, function) the old hand-maintained list named."""
    from istio_tpu.analysis.meshlint import run_meshlint

    shim = _load_script("hotpath_lint")
    try:
        report = run_meshlint(root=REPO, passes=("hotpath",))
        coverage = report.stats["hot_coverage"]
        missing = [
            f"{path}::{name}"
            for path, names in sorted(shim.HOT_SECTIONS.items())
            for name in sorted(names)
            if name not in set(coverage.get(path, ()))]
        assert not missing, (
            "inferred hot coverage dropped baseline functions: "
            + ", ".join(missing))
        baseline = sum(len(v) for v in shim.HOT_SECTIONS.values())
        assert report.stats["hot_reachable"] >= baseline
    finally:
        sys.modules.pop("hotpath_lint", None)
