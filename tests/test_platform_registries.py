"""Consul / Eureka / CloudFoundry registry backends.

Mirrors the reference's hermetic registry tests
(pilot/pkg/serviceregistry/{consul,eureka,cloudfoundry}/*_test.go):
each backend is driven against an in-process fake speaking the real
wire shapes, and the conversion rules are asserted table-style.
"""
from __future__ import annotations

import time

import pytest

from istio_tpu.pilot import cloudfoundry, consul, eureka
from istio_tpu.pilot.registry import AggregateRegistry


# ---------------------------------------------------------------------------
# consul
# ---------------------------------------------------------------------------

@pytest.fixture()
def consul_pair():
    fake = consul.FakeConsulServer()
    reg = consul.ConsulRegistry(fake.addr, poll_s=0.05)
    yield fake, reg
    reg.stop()
    fake.close()


def test_consul_services_and_conversion(consul_pair):
    fake, reg = consul_pair
    fake.register("reviews", address="10.0.0.1", port=9080,
                  tags=["version|v1", "notalabel"],
                  node_meta={"protocol": "grpc"})
    fake.register("reviews", address="10.0.0.2", port=9080,
                  service_address="172.16.0.2",
                  tags=["version|v2"], node_meta={"protocol": "grpc"})
    svcs = reg.services()
    assert [s.hostname for s in svcs] == ["reviews.service.consul"]
    assert svcs[0].ports[0].protocol == "GRPC"

    svc = reg.get_service("reviews.service.consul")
    assert svc is not None and svc.ports[0].port == 9080
    assert reg.get_service("nope.service.consul") is None
    assert reg.get_service("not-a-consul-name") is None


def test_consul_instances_labels_and_address_fallback(consul_pair):
    fake, reg = consul_pair
    fake.register("ratings", address="10.1.1.1", port=8080,
                  tags=["version|v1"])
    fake.register("ratings", address="10.1.1.2",
                  service_address="172.16.5.5", port=8080,
                  tags=["version|v2"])
    insts = reg.instances("ratings.service.consul")
    assert len(insts) == 2
    # ServiceAddress wins; node Address is the fallback (conversion.go:100)
    addrs = sorted(i.endpoint.address for i in insts)
    assert addrs == ["10.1.1.1", "172.16.5.5"]
    # malformed tags were dropped; key|value became labels
    v2 = reg.instances("ratings.service.consul",
                       labels={"version": "v2"})
    assert [i.endpoint.address for i in v2] == ["172.16.5.5"]

    host = reg.host_instances({"10.1.1.1"})
    assert len(host) == 1 and host[0].labels == {"version": "v1"}


def test_consul_monitor_fires_on_change(consul_pair):
    fake, reg = consul_pair
    events = []
    reg.append_service_handler(lambda svc, ev: events.append((svc.hostname, ev)))
    reg.start()
    fake.register("newsvc", address="10.9.9.9", port=80)
    deadline = time.time() + 3.0
    while not events and time.time() < deadline:
        time.sleep(0.02)
    assert ("newsvc.service.consul", "add") in events
    fake.deregister("newsvc")
    deadline = time.time() + 3.0
    while len(events) < 2 and time.time() < deadline:
        time.sleep(0.02)
    assert ("newsvc.service.consul", "delete") in events


# ---------------------------------------------------------------------------
# eureka
# ---------------------------------------------------------------------------

@pytest.fixture()
def eureka_pair():
    fake = eureka.FakeEurekaServer()
    reg = eureka.EurekaRegistry(fake.url, poll_s=0.05)
    yield fake, reg
    reg.stop()
    fake.close()


def test_eureka_conversion_rules(eureka_pair):
    fake, reg = eureka_pair
    fake.register("PRODUCTPAGE", hostname="productpage.default",
                  ip="10.0.0.1", port=9080,
                  metadata={"istio.protocol": "http2", "version": "v1"})
    fake.register("PRODUCTPAGE", hostname="productpage.default",
                  ip="10.0.0.2", port=9080, secure_port=9443,
                  metadata={"istio.protocol": "http2", "version": "v2"})
    # DOWN instances are ignored (conversion.go statusUp filter)
    fake.register("PRODUCTPAGE", hostname="productpage.default",
                  ip="10.0.0.3", port=9080, status="DOWN")

    svcs = reg.services()
    assert [s.hostname for s in svcs] == ["productpage.default"]
    assert sorted(p.port for p in svcs[0].ports) == [9080, 9443]
    assert svcs[0].ports[0].protocol == "HTTP2"

    insts = reg.instances("productpage.default")
    # instance 1 exposes one port, instance 2 exposes two
    assert len(insts) == 3
    # istio.* metadata keys are NOT labels
    assert all("istio.protocol" not in i.labels for i in insts)
    v2 = reg.instances("productpage.default", labels={"version": "v2"})
    assert sorted(i.endpoint.port for i in v2) == [9080, 9443]

    host = reg.host_instances({"10.0.0.1"})
    assert len(host) == 1 and host[0].endpoint.port == 9080
    assert reg.get_service("missing.host") is None


def test_eureka_monitor_and_aggregate(eureka_pair):
    fake, reg = eureka_pair
    events = []
    reg.append_service_handler(lambda svc, ev: events.append((svc.hostname, ev)))
    reg.start()
    fake.register("DETAILS", hostname="details.default", ip="10.2.0.1",
                  port=8080)
    deadline = time.time() + 3.0
    while not events and time.time() < deadline:
        time.sleep(0.02)
    assert ("details.default", "add") in events

    # plugs into the aggregate exactly like kube/memory registries
    agg = AggregateRegistry([reg])
    assert [s.hostname for s in agg.services()] == ["details.default"]


# ---------------------------------------------------------------------------
# cloudfoundry
# ---------------------------------------------------------------------------

def test_cloudfoundry_routes_view():
    copilot = cloudfoundry.InProcessCopilot()
    reg = cloudfoundry.CloudFoundryRegistry(copilot)
    copilot.set_route("app1.apps.internal",
                      [("10.255.0.1", 61001), ("10.255.0.2", 61002)])
    copilot.set_route("app2.apps.internal", [("10.255.9.9", 61009)])

    svcs = reg.services()
    assert [s.hostname for s in svcs] == ["app1.apps.internal",
                                          "app2.apps.internal"]
    # CF services expose a single fixed HTTP service port
    assert all(s.ports[0].port == 8080 and s.ports[0].protocol == "HTTP"
               for s in svcs)

    insts = reg.instances("app1.apps.internal")
    assert [(i.endpoint.address, i.endpoint.port) for i in insts] == \
        [("10.255.0.1", 61001), ("10.255.0.2", 61002)]
    assert reg.instances("app1.apps.internal", labels={"a": "b"}) == []
    assert reg.get_service("gone.apps.internal") is None

    host = reg.host_instances({"10.255.9.9"})
    assert [i.service.hostname for i in host] == ["app2.apps.internal"]


def test_cloudfoundry_ticker_events():
    copilot = cloudfoundry.InProcessCopilot()
    reg = cloudfoundry.CloudFoundryRegistry(copilot, poll_s=0.05)
    events = []
    reg.append_service_handler(lambda svc, ev: events.append((svc.hostname, ev)))
    reg.start()
    try:
        copilot.set_route("new.apps.internal", [("10.255.1.1", 61001)])
        deadline = time.time() + 3.0
        while not events and time.time() < deadline:
            time.sleep(0.02)
        assert ("new.apps.internal", "add") in events
        copilot.delete_route("new.apps.internal")
        deadline = time.time() + 3.0
        while len(events) < 2 and time.time() < deadline:
            time.sleep(0.02)
        assert ("new.apps.internal", "delete") in events
    finally:
        reg.stop()
