"""Tier-1 hook for scripts/forensics_smoke.py: the CI gate that the
tail-latency forensics plane attributes slow requests end to end —
clean traffic under threshold captures zero exemplars, a chaos-wedged
adapter and a config swap under live load each produce a slow
exemplar whose stage timeline names the guilty stage AND the
overlapping control-plane event, /debug/slow + /debug/events +
/metrics agree over real HTTP, exemplars deep-link into /debug/traces
by trace id (and ?min_ms= filters by duration), /debug/profile and
/debug/threads serve, and the recorder's clean-traffic overhead stays
under the 2% gate. Runs main() in-process (the introspect_smoke
pattern)."""
import importlib.util
import os
import sys


def test_forensics_smoke_main():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "forensics_smoke.py")
    spec = importlib.util.spec_from_file_location("forensics_smoke",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
        rc = mod.main(n_rules=60, n_checks=8)
    finally:
        sys.modules.pop(spec.name, None)
    assert rc == 0
