"""Device quota parity — batched scatter-add alloc vs memquota oracle.

VERDICT r2 item 3: the served quota loop rides device counters with a
host dedup-replay cache in front; the host MemQuotaHandler
(mixer/adapter/memquota semantics) is the conformance oracle. The
kernel (models/quota_alloc.py) must reproduce memquota.go:118 alloc
sequentially-within-batch under contention, including the subtlety
that a denied all-or-nothing alloc consumes nothing.
"""
import numpy as np
import pytest

from istio_tpu.adapters.memquota import MemQuotaHandler
from istio_tpu.adapters.sdk import Env, QuotaArgs
from istio_tpu.models.policy_engine import RESOURCE_EXHAUSTED
from istio_tpu.models.quota_alloc import make_alloc_step
from istio_tpu.runtime.device_quota import DeviceQuotaPool


# ---------------------------------------------------------------- kernel

def _seq_reference(counts, buckets, amounts, be, mx, active):
    """memquota.go:118 alloc applied one request at a time."""
    counts = counts.copy()
    granted = np.zeros(len(buckets), np.int64)
    for i in range(len(buckets)):
        if not active[i]:
            continue
        avail = mx[i] - counts[buckets[i]]
        if be[i]:
            g = max(min(amounts[i], avail), 0)
        else:
            g = amounts[i] if avail >= amounts[i] else 0
        granted[i] = g
        counts[buckets[i]] += g
    return granted, counts


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_scan_kernel_matches_sequential_reference(seed):
    rng = np.random.default_rng(seed)
    n_buckets, b = 32, 256   # heavy contention on purpose
    scan, fast = make_alloc_step(n_buckets, jit=False)
    counts0 = rng.integers(0, 8, n_buckets).astype(np.int32)
    buckets = rng.integers(0, n_buckets, b).astype(np.int32)
    amounts = rng.integers(0, 5, b).astype(np.int32)
    be = rng.random(b) < 0.5
    mx = np.full(b, 10, np.int32)
    active = rng.random(b) < 0.9
    g, c = scan(counts0, buckets, amounts, be, mx, active)
    # sequential order within a bucket == submission order (stable sort)
    rg, rc = _seq_reference(counts0, buckets, amounts, be, mx, active)
    np.testing.assert_array_equal(np.asarray(g), rg)
    np.testing.assert_array_equal(np.asarray(c), rc)


def test_rolling_unit_kernel_matches_scan():
    """The parallel rank kernel (unit amounts, any be/ao mix) must
    equal the sequential-parity scan on heavily contended batches with
    live rolling windows."""
    from istio_tpu.models.quota_alloc import make_rolling_alloc_step

    rng = np.random.default_rng(7)
    n_buckets, k, b = 16, 10, 256
    scan, fast, unit, seg = make_rolling_alloc_step(n_buckets, k,
                                                    jit=False)
    slots0 = rng.integers(0, 3, (n_buckets, k)).astype(np.int32)
    buckets = rng.integers(0, n_buckets, b).astype(np.int32)
    amounts = np.ones(b, np.int32)
    be = rng.random(b) < 0.5        # irrelevant at amount=1, proven so
    # per-bucket max must be consistent (same quota name per bucket)
    mx = np.take(rng.integers(5, 20, n_buckets).astype(np.int32),
                 buckets)
    active = rng.random(b) < 0.9
    ticks = np.full(b, 9, np.int32)
    lasts = np.take(rng.integers(0, 9, n_buckets).astype(np.int32),
                    buckets)
    rolling = np.take(rng.random(n_buckets) < 0.7, buckets)
    g1, s1 = scan(slots0, buckets, amounts, be, mx, active,
                  ticks, lasts, rolling)
    g2, s2 = unit(slots0, buckets, amounts, be, mx, active,
                  ticks, lasts, rolling)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s2))
    # the segmented kernel serializes ao-before-be, so WHICH unit rows
    # win can differ from submission order — but the per-bucket grant
    # totals (hence the committed slots) are order-independent
    g3, s3 = seg(slots0, buckets, amounts, be, mx, active,
                 ticks, lasts, rolling)
    np.testing.assert_array_equal(np.asarray(s1), np.asarray(s3))
    assert int(np.asarray(g3).sum()) == int(np.asarray(g1).sum())


def test_kernels_never_grant_negative_amounts():
    """A wire-supplied negative all-or-nothing amount must grant 0 and
    consume nothing (host parity: _Window/_Exact clamp to 0) — without
    the clamp it would DRAIN the counter below real usage (r4 review
    finding)."""
    from istio_tpu.models.quota_alloc import make_rolling_alloc_step

    n_buckets, k = 8, 10
    scan, fast, _unit, seg = make_rolling_alloc_step(n_buckets, k,
                                                     jit=False)
    slots0 = np.zeros((n_buckets, k), np.int32)
    slots0[2, 0] = 5
    buckets = np.array([2, 2], np.int32)
    amounts = np.array([-100, -100], np.int32)
    be = np.array([False, True])
    mx = np.full(2, 10, np.int32)
    active = np.ones(2, bool)
    z = np.zeros(2, np.int32)
    roll = np.zeros(2, bool)
    for fn in (scan, fast, seg):
        g, s = fn(slots0, buckets, amounts, be, mx, active, z, z, roll)
        assert (np.asarray(g) == 0).all(), fn
        np.testing.assert_array_equal(np.asarray(s), slots0)
    # old flat kernel keeps the same clamp
    oscan, ofast = make_alloc_step(n_buckets, jit=False)
    c0 = np.zeros(n_buckets, np.int32)
    for fn in (oscan, ofast):
        g, c = fn(c0, buckets, amounts, be, mx, active)
        assert (np.asarray(g) == 0).all(), fn
        np.testing.assert_array_equal(np.asarray(c), c0)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_seg_kernel_matches_scan_under_fixed_order(seed):
    """The segmented prefix-sum kernel (VERDICT r4 item 4) IS the
    sequential scan under the serving path's deterministic
    intra-window order — (bucket, ao-before-be, amount ascending).
    Heavily contended mixed-amount batches with live rolling windows;
    expected = scan run over the pre-lexsorted batch, unscattered."""
    from istio_tpu.models.quota_alloc import make_rolling_alloc_step

    rng = np.random.default_rng(seed)
    n_buckets, k, b = 12, 10, 256
    scan, fast, unit, seg = make_rolling_alloc_step(n_buckets, k,
                                                    jit=False)
    slots0 = rng.integers(0, 3, (n_buckets, k)).astype(np.int32)
    buckets = rng.integers(0, n_buckets, b).astype(np.int32)
    amounts = rng.integers(0, 6, b).astype(np.int32)
    be = rng.random(b) < 0.4
    mx = np.take(rng.integers(4, 30, n_buckets).astype(np.int32),
                 buckets)
    active = rng.random(b) < 0.9
    ticks = np.full(b, 9, np.int32)
    lasts = np.take(rng.integers(0, 9, n_buckets).astype(np.int32),
                    buckets)
    rolling = np.take(rng.random(n_buckets) < 0.7, buckets)

    g_seg, s_seg = seg(slots0, buckets, amounts, be, mx, active,
                       ticks, lasts, rolling)

    sent = np.where(active, buckets, np.iinfo(np.int32).max)
    order = np.lexsort((np.maximum(amounts, 0), be, sent))
    g_sorted, s_ref = scan(
        slots0, buckets[order], amounts[order], be[order], mx[order],
        active[order], ticks[order], lasts[order], rolling[order])
    g_ref = np.zeros(b, np.int32)
    g_ref[order] = np.asarray(g_sorted)

    np.testing.assert_array_equal(np.asarray(g_seg), g_ref)
    np.testing.assert_array_equal(np.asarray(s_seg),
                                  np.asarray(s_ref))


def test_pool_serving_never_selects_scan():
    """No serving-reachable input may pick the O(B) scan (VERDICT r4
    item 4): a hot bucket + mixed amounts — the exact shape that used
    to fall back — must resolve through the parallel kernels. The
    scan is booby-trapped; grants must still match the host-adapter
    oracle fed in the pool's stated (ao-asc, be-asc) order."""
    clk = _Clock()
    quotas = {"rq": {"max_amount": 12, "valid_duration_s": 60.0}}
    pool = DeviceQuotaPool(quotas, n_buckets=64, clock=clk,
                           batch_window_s=0.02, jit=False)

    def _bomb(*_a, **_k):
        raise AssertionError("O(B) scan selected on the serving path")

    pool._alloc_scan = _bomb
    try:
        args = [(5, False), (4, False), (1, False), (6, True),
                (3, True), (2, False)]
        futs = [pool.alloc("rq", _inst({}),
                           QuotaArgs(quota_amount=a, best_effort=e))
                for a, e in args]
        got = [f.result(timeout=30).granted_amount for f in futs]
    finally:
        pool.close()

    host = MemQuotaHandler({"quotas": [
        {"name": "rq", "max_amount": 12, "valid_duration_s": 60.0}]},
        Env("test"), clock=clk)
    # the pool's deterministic intra-window order: ao amount-asc,
    # then be amount-asc
    order = sorted(range(len(args)), key=lambda i: (args[i][1],
                                                    args[i][0]))
    want = [0] * len(args)
    for i in order:
        a, e = args[i]
        r = host.handle_quota("quota", _inst({}),
                              QuotaArgs(quota_amount=a, best_effort=e))
        want[i] = r.granted_amount
    assert got == want, (got, want)


def test_seg_kernel_adversarial_amounts_never_over_grant():
    """Wire-supplied near-INT32_MAX amounts must never wrap the
    segment cumsum into an over-grant (this repo runs jax without
    x64, so int64 casts silently truncate — the guard is the
    DOMAIN_MAX clamp + fail-closed over-domain handling)."""
    from istio_tpu.models.quota_alloc import (DOMAIN_MAX,
                                              make_rolling_alloc_step)

    n_buckets, k = 4, 10
    _scan, _fast, _unit, seg = make_rolling_alloc_step(n_buckets, k,
                                                       jit=False)
    slots0 = np.zeros((n_buckets, k), np.int32)
    big = np.int32(1_500_000_000)
    buckets = np.array([1, 1, 1], np.int32)
    amounts = np.array([big, big, 5], np.int32)
    be = np.array([False, False, True])
    mx = np.full(3, 10, np.int32)
    active = np.ones(3, bool)
    z = np.zeros(3, np.int32)
    roll = np.zeros(3, bool)
    g, s = seg(slots0, buckets, amounts, be, mx, active, z, z, roll)
    g = np.asarray(g)
    # over-domain ao rows fail closed; the small be row still grants
    assert g[0] == 0 and g[1] == 0
    assert g[2] == 5
    assert int(np.asarray(s).sum()) == 5
    # over-domain BEST-EFFORT caps at avail (never a huge commit)
    g2, s2 = seg(slots0, buckets, amounts,
                 np.array([True, True, True]), mx, active, z, z, roll)
    g2 = np.asarray(g2)
    assert g2.sum() == 10 and (g2 <= 10).all()
    assert int(np.asarray(s2).sum()) == 10
    # a DENIED over-domain ao row consumes nothing: the legit ao row
    # behind it in the run must still be granted (review r5 finding —
    # over-domain amounts must not inflate the segment cumsum)
    g4, _ = seg(slots0, buckets,
                np.array([big, 7, 2], np.int32),
                np.array([False, False, False]), mx, active, z, z,
                roll)
    assert np.asarray(g4).tolist() == [0, 7, 2]
    # deeply negative avail (limit shrunk under live usage) grants 0
    slots_over = np.zeros((n_buckets, k), np.int32)
    slots_over[1, 0] = np.iinfo(np.int32).max - 3
    g3, _ = seg(slots_over, buckets,
                np.array([3, 2, DOMAIN_MAX], np.int32),
                np.array([False, True, True]), mx, active, z, z, roll)
    assert (np.asarray(g3) == 0).all()


def test_fast_kernel_matches_on_unique_buckets():
    rng = np.random.default_rng(3)
    n_buckets, b = 512, 128
    scan, fast = make_alloc_step(n_buckets, jit=False)
    counts0 = rng.integers(0, 8, n_buckets).astype(np.int32)
    buckets = rng.permutation(n_buckets)[:b].astype(np.int32)  # unique
    amounts = rng.integers(0, 5, b).astype(np.int32)
    be = rng.random(b) < 0.5
    mx = np.full(b, 10, np.int32)
    active = np.ones(b, bool)
    g1, c1 = scan(counts0, buckets, amounts, be, mx, active)
    g2, c2 = fast(counts0, buckets, amounts, be, mx, active)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(g2))
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))


# ---------------------------------------------------------------- pool

class _Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _pool_and_oracle(max_amount=10, duration=0.0, clock=None):
    clock = clock or _Clock()
    quotas = {"rq": {"name": "rq", "max_amount": max_amount,
                     "valid_duration_s": duration}}
    pool = DeviceQuotaPool(quotas, n_buckets=64, clock=clock,
                           batch_window_s=0.0, max_batch=64)
    oracle = MemQuotaHandler(
        {"quotas": [{"name": "rq", "max_amount": max_amount,
                     "valid_duration_s": duration}]},
        Env("test"), clock=clock)
    return pool, oracle, clock


def _inst(dims):
    return {"name": "rq", "dimensions": dims}


def test_pool_matches_memquota_oracle_under_contention():
    pool, oracle, clock = _pool_and_oracle(max_amount=10, duration=0.0)
    try:
        rng = np.random.default_rng(11)
        ops = []
        for i in range(120):
            dims = {"user": f"u{int(rng.integers(4))}"}   # 4 hot cells
            amount = int(rng.integers(0, 5))
            be = bool(rng.random() < 0.5)
            dedup = f"d{i % 37}" if rng.random() < 0.3 else ""
            ops.append((dims, amount, be, dedup))
        for dims, amount, be, dedup in ops:
            args = QuotaArgs(quota_amount=amount, best_effort=be,
                             dedup_id=dedup)
            got = pool.alloc("rq", _inst(dims), args).result()
            want = oracle.handle_quota("quota", _inst(dims), args)
            assert got.granted_amount == want.granted_amount, \
                (dims, amount, be, dedup)
            assert got.status_code == want.status_code
    finally:
        pool.close()


def test_pool_burst_matches_sequential_oracle():
    """A burst submitted without waiting coalesces into one device
    batch (the contended mixed-amount path — the segmented kernel);
    grants must equal the oracle applied in the pool's STATED
    intra-window serialization: all-or-nothing rows first, then
    best-effort, amount-ascending, stable by submission (the window
    collects raced arrivals, so any deterministic order is as
    faithful to memquota's mutex as arrival order was)."""
    pool, oracle, clock = _pool_and_oracle(max_amount=5, duration=0.0)
    try:
        all_args = [QuotaArgs(quota_amount=2, best_effort=(i % 2 == 0))
                    for i in range(12)]
        futs = [pool.alloc("rq", _inst({"k": "same"}), args)
                for args in all_args]
        got = [f.result() for f in futs]
        order = sorted(range(12),
                       key=lambda i: (all_args[i].best_effort,
                                      all_args[i].quota_amount, i))
        want: list = [None] * 12
        for i in order:
            want[i] = oracle.handle_quota("quota", _inst({"k": "same"}),
                                          all_args[i])
        assert [g.granted_amount for g in got] == \
            [w.granted_amount for w in want]
    finally:
        pool.close()


def test_pool_dedup_replays_denials_too():
    pool, _, clock = _pool_and_oracle(max_amount=2, duration=0.0)
    try:
        a1 = pool.alloc("rq", _inst({}), QuotaArgs(
            quota_amount=2, dedup_id="x")).result()
        assert a1.granted_amount == 2
        # exhausted: denial cached under its dedup id
        a2 = pool.alloc("rq", _inst({}), QuotaArgs(
            quota_amount=1, dedup_id="y")).result()
        assert a2.granted_amount == 0
        assert a2.status_code == RESOURCE_EXHAUSTED
        # replay of the denial must stay a denial (never re-allocs)
        a3 = pool.alloc("rq", _inst({}), QuotaArgs(
            quota_amount=1, dedup_id="y")).result()
        assert a3.granted_amount == 0
        assert a3.status_code == RESOURCE_EXHAUSTED
        # replay of the grant returns the original without consuming
        a4 = pool.alloc("rq", _inst({}), QuotaArgs(
            quota_amount=2, dedup_id="x")).result()
        assert a4.granted_amount == 2
    finally:
        pool.close()


def test_pool_dedup_within_one_batch_window():
    """A retransmission landing in the SAME batch as its original must
    replay, not double-consume (memquota's mutex serializes these)."""
    pool, _, clock = _pool_and_oracle(max_amount=3, duration=0.0)
    try:
        f1 = pool.alloc("rq", _inst({}), QuotaArgs(
            quota_amount=2, dedup_id="dup"))
        f2 = pool.alloc("rq", _inst({}), QuotaArgs(
            quota_amount=2, dedup_id="dup"))
        r1, r2 = f1.result(), f2.result()
        assert r1.granted_amount == 2 and r2.granted_amount == 2
        # only ONE consumption happened: 1 token remains of 3
        r3 = pool.alloc("rq", _inst({}), QuotaArgs(
            quota_amount=1)).result()
        assert r3.granted_amount == 1
        r4 = pool.alloc("rq", _inst({}), QuotaArgs(
            quota_amount=1)).result()
        assert r4.granted_amount == 0
    finally:
        pool.close()


def test_pool_alloc_after_close_fails_fast():
    pool, _, _ = _pool_and_oracle()
    pool.close()
    r = pool.alloc("rq", _inst({}), QuotaArgs(quota_amount=1)).result(
        timeout=1.0)
    assert r.granted_amount == 0
    assert r.status_code == 14   # UNAVAILABLE, not a 30s hang


def test_pool_window_fully_expires():
    clock = _Clock()
    pool, _, _ = _pool_and_oracle(max_amount=3, duration=10.0,
                                  clock=clock)
    try:
        assert pool.alloc("rq", _inst({}), QuotaArgs(
            quota_amount=3)).result().granted_amount == 3
        assert pool.alloc("rq", _inst({}), QuotaArgs(
            quota_amount=1)).result().granted_amount == 0
        clock.t += 11.0   # everything left the rolling window
        assert pool.alloc("rq", _inst({}), QuotaArgs(
            quota_amount=3)).result().granted_amount == 3
    finally:
        pool.close()


def test_pool_rolling_window_reclaims_gradually():
    """THE rolling-vs-fixed distinguisher (VERDICT r3 item 5): units
    allocated at different ticks expire at different times. duration=10
    → tick_len=1; consume 5 at t0 and 5 at t0+5; at t0+11 only the
    first 5 have rolled out — avail is 5, not 0 (fixed window pinned
    to t0 would say 10) and not 10 (a reset would forget the second
    alloc). Device must agree with the MemQuotaHandler oracle at every
    step."""
    clock = _Clock()
    pool, oracle, _ = _pool_and_oracle(max_amount=10, duration=10.0,
                                       clock=clock)
    try:
        def both(amount, be=True):
            args = QuotaArgs(quota_amount=amount, best_effort=be)
            got = pool.alloc("rq", _inst({}), args).result()
            want = oracle.handle_quota("quota", _inst({}), args)
            assert got.granted_amount == want.granted_amount, \
                (clock.t, amount, got.granted_amount,
                 want.granted_amount)
            return got.granted_amount

        assert both(5) == 5          # tick T
        clock.t += 5.0
        assert both(5) == 5          # tick T+5; window full
        clock.t += 6.0               # tick T+11: first 5 rolled out
        assert both(10) == 5         # best-effort grabs exactly 5
        clock.t += 5.0               # tick T+16: second 5 rolled out
        assert both(10) == 5         # the T+11 grant still holds 5
    finally:
        pool.close()


def test_pool_rolling_contended_batch_matches_oracle():
    """Duplicate buckets within ONE flush (the scan path) + rolling
    windows + dedup replay across a roll."""
    clock = _Clock()
    pool, oracle, _ = _pool_and_oracle(max_amount=6, duration=10.0,
                                       clock=clock)
    try:
        all_args = [QuotaArgs(quota_amount=2, best_effort=(i % 2 == 0))
                    for i in range(8)]
        futs = [pool.alloc("rq", _inst({"k": "hot"}), args)
                for args in all_args]
        got = [f.result().granted_amount for f in futs]
        # oracle applied in the pool's stated intra-window order
        # (ao-before-be, amount-ascending, stable)
        order = sorted(range(8),
                       key=lambda i: (all_args[i].best_effort,
                                      all_args[i].quota_amount, i))
        want = [0] * 8
        for i in order:
            want[i] = oracle.handle_quota(
                "quota", _inst({"k": "hot"}), all_args[i]).granted_amount
        assert got == want
        # dedup recorded before the roll replays after it (mirrored
        # into the oracle so pool and oracle states stay aligned)
        args = QuotaArgs(quota_amount=1, best_effort=True,
                         dedup_id="replay-me")
        g0 = pool.alloc("rq", _inst({"k": "hot"}), args).result()
        oracle.handle_quota("quota", _inst({"k": "hot"}), args)
        clock.t += 0.5               # same dedup window, later tick
        g1 = pool.alloc("rq", _inst({"k": "hot"}), args).result()
        oracle.handle_quota("quota", _inst({"k": "hot"}), args)
        assert g1.granted_amount == g0.granted_amount
        # after a partial roll both paths agree again
        clock.t += 7.0
        args2 = QuotaArgs(quota_amount=6, best_effort=True)
        got = pool.alloc("rq", _inst({"k": "hot"}), args2).result()
        want2 = oracle.handle_quota("quota", _inst({"k": "hot"}), args2)
        assert got.granted_amount == want2.granted_amount
    finally:
        pool.close()


def test_pool_keyspace_exhaustion_fails_closed():
    clock = _Clock()
    pool = DeviceQuotaPool({"rq": {"name": "rq", "max_amount": 5}},
                           n_buckets=4, clock=clock,
                           batch_window_s=0.0, max_batch=8)
    try:
        for i in range(4):
            assert pool.alloc("rq", _inst({"k": f"u{i}"}), QuotaArgs(
                quota_amount=1)).result().granted_amount == 1
        r = pool.alloc("rq", _inst({"k": "u99"}),
                       QuotaArgs(quota_amount=1)).result()
        assert r.granted_amount == 0
        assert r.status_code == RESOURCE_EXHAUSTED
    finally:
        pool.close()


# ------------------------------------------------------- served wiring

def test_served_quota_uses_device_pool_and_activity_bits():
    """End-to-end: the fused check response carries active quota rules;
    quota_fused allocates via the device pool without re-resolving, and
    a non-matching rule grants freely (dispatcher.quota tail)."""
    from istio_tpu.attribute.bag import bag_from_mapping
    from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs

    s = MemStore()
    s.set(("handler", "istio-system", "mq"), {
        "adapter": "memquota",
        "params": {"quotas": [{"name": "rq.istio-system",
                               "max_amount": 2}]}})
    s.set(("instance", "istio-system", "rq"), {
        "template": "quota",
        "params": {"dimensions": {"user": 'source.user | "anon"'}}})
    s.set(("rule", "istio-system", "qr"), {
        "match": 'request.path.startsWith("/metered")',
        "actions": [{"handler": "mq", "instances": ["rq"]}]})
    srv = RuntimeServer(s, ServerArgs(batch_window_s=0.001))
    try:
        plan = srv.controller.dispatcher.fused
        assert plan is not None and len(plan.quota_actions) == 1
        assert srv.controller.device_quotas, "no device pool built"

        metered = bag_from_mapping({"request.path": "/metered/x",
                                    "source.user": "alice"})
        free = bag_from_mapping({"request.path": "/open/x",
                                 "source.user": "alice"})
        r_m = srv.check_many([metered])[0]
        r_f = srv.check_many([free])[0]
        assert r_m.active_quota_rules == (0,)
        assert r_f.active_quota_rules == ()

        args = QuotaArgs(quota_amount=1)
        # metered: device pool allocates (max 2)
        q1 = srv.quota_fused(metered, "rq", args, r_m)
        q2 = srv.quota_fused(metered, "rq", args, r_m)
        q3 = srv.quota_fused(metered, "rq", args, r_m)
        assert q1.result().granted_amount == 1
        assert q2.result().granted_amount == 1
        r3 = q3.result()
        assert r3.granted_amount == 0
        assert r3.status_code == RESOURCE_EXHAUSTED
        # distinct dimensions → distinct counter cell
        other = bag_from_mapping({"request.path": "/metered/x",
                                  "source.user": "bob"})
        r_o = srv.check_many([other])[0]
        q4 = srv.quota_fused(other, "rq", args, r_o)
        assert q4.result().granted_amount == 1
        # non-matching rule: grant freely, no future involved
        q5 = srv.quota_fused(free, "rq", args, r_f)
        assert q5.granted_amount == 1
    finally:
        srv.close()
