"""Tier-1 hook for scripts/analyze_gate.py: the CI gate that the
snapshot analyzer flags 100% of the seeded fault corpus (shadowed
rule, ALLOW/DENY conflict, type error, NFA state-budget blow-up,
Pilot/Mixer plane divergence) with oracle-confirmed witnesses, raises
ZERO findings on the golden/clean configs, exits `mixs analyze`
non-zero on ERROR findings, and rejects the same snapshots at kube
admission. Runs main() in-process (the introspect_smoke pattern; the
script stays runnable standalone under JAX_PLATFORMS=cpu)."""
import importlib.util
import os
import sys


def test_analyze_gate_main():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "analyze_gate.py")
    spec = importlib.util.spec_from_file_location("analyze_gate", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
        rc = mod.main(seed=20260803)
    finally:
        sys.modules.pop(spec.name, None)
    assert rc == 0
