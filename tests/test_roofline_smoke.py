"""Tier-1 hook for scripts/roofline_smoke.py: the CI gate that every
bench perf section's roofline fields (`*_fraction_of_roof`, a named
`*_bound`) stay emitted and that the model's bytes-per-step
prediction matches the compiled shapes exactly (h2d batch planes,
d2h packed pull, index-tensor params). Runs main() in-process."""
import importlib.util
import os
import sys


def test_roofline_smoke_main():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "roofline_smoke.py")
    spec = importlib.util.spec_from_file_location(
        "roofline_smoke", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
        rc = mod.main(n_rules=32)
    finally:
        sys.modules.pop(spec.name, None)
    assert rc == 0
