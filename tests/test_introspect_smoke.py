"""Tier-1 hook for scripts/introspect_smoke.py: the CI gate that the
serving stage decomposition and live p99 gauge stay scrapable. Runs
main() in-process (a subprocess would pay a second jax import for no
extra coverage; the script itself stays runnable standalone under
JAX_PLATFORMS=cpu)."""
import importlib.util
import os
import sys


def test_introspect_smoke_main():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "introspect_smoke.py")
    spec = importlib.util.spec_from_file_location(
        "introspect_smoke", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
        rc = mod.main(n_rules=24, n_checks=40)
    finally:
        sys.modules.pop(spec.name, None)
    assert rc == 0
