"""Disassembler + stepper (the il/text + Stepper tooling role,
mixer/pkg/il/text/write.go + il/interpreter/stepper.go)."""
import subprocess
import sys

from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.compiler.disasm import Stepper, disassemble
from istio_tpu.compiler.ruleset import Rule, compile_ruleset
from istio_tpu.expr.checker import AttributeDescriptorFinder
from istio_tpu.attribute.types import ValueType as V

FINDER = AttributeDescriptorFinder({
    "destination.service": V.STRING,
    "source.namespace": V.STRING,
    "request.path": V.STRING,
    "request.headers": V.STRING_MAP,
    "connection.mtls": V.BOOL,
    "key": V.STRING,
})

RULES = [
    Rule(name="svc-and-ns",
         match='destination.service == "reviews.default.svc" && '
               'source.namespace != "locked"'),
    Rule(name="path-or-mtls",
         match='request.path.startsWith("/admin") || connection.mtls',
         namespace="prod"),
    Rule(name="dyn-key", match='request.headers[key] == "x"'),   # fallback
    Rule(name="always", match=""),
]


def _prog():
    return compile_ruleset(RULES, FINDER, jit=False)


def test_disassemble_contents():
    text = disassemble(_prog())
    # header counts + layout line
    assert "4 rules" in text and "host-fallback" in text
    # atom table with (canonical) source text and tier annotations
    assert 'EQ($destination.service, "reviews.default.svc")' in text
    assert "[id-eq]" in text
    assert "[tensor]" in text      # the startsWith byte predicate
    # per-rule DNFs in both polarities
    assert "M: " in text and "N: " in text
    assert "∧" in text and "∨" in text
    # fallback rules carry the reason, namespaces render
    assert "HOST FALLBACK" in text
    assert "ns=prod" in text
    # referenced attributes line
    assert "refs: " in text and "source.namespace" in text


def test_stepper_explains_verdicts():
    prog = _prog()
    stepper = Stepper(prog, FINDER)
    trace = stepper.explain(bag_from_mapping({
        "destination.service": "reviews.default.svc",
        "source.namespace": "prod",
        "request.path": "/admin/keys",
        "request.headers": {"cookie": "x"},
        "key": "cookie",
    }))
    assert "r0 svc-and-ns: MATCH via" in trace
    assert "r1 path-or-mtls: MATCH via" in trace
    assert "r3 always: MATCH" in trace
    # the dynamic-key rule went through the host oracle (headers[key]
    # resolves to headers["cookie"] == "x" → MATCH)
    assert "r2 dyn-key: MATCH (host oracle" in trace
    # atom values are shown with their (canonical) source
    assert "= True" in trace and "# EQ($destination.service" in trace


def test_stepper_explains_absence_and_error():
    prog = _prog()
    stepper = Stepper(prog, FINDER)
    trace = stepper.explain(bag_from_mapping({}), rule=0)
    assert "ERROR" in trace          # absent operands → inconclusive
    assert "lookup failed" in trace


def test_stepper_agrees_with_device():
    """The stepper's verdicts must equal the compiled program's."""
    import numpy as np
    from istio_tpu.compiler.layout import Tensorizer

    prog = _prog()
    stepper = Stepper(prog, FINDER)
    bags = [bag_from_mapping(d) for d in (
        {"destination.service": "reviews.default.svc",
         "source.namespace": "x"},
        {"request.path": "/admin/1"},
        {"connection.mtls": True},
        {"request.headers": {"k": "x"}, "key": "k"},
        {},
    )]
    batch = Tensorizer(prog.layout, prog.interner).tensorize(bags)
    matched, _, _ = prog(batch)
    matched = np.array(matched)
    for ridx in prog.host_fallback:
        for b, bag in enumerate(bags):
            matched[b, ridx] = prog.host_eval(ridx, bag)[0]
    for b, bag in enumerate(bags):
        trace = stepper.explain(bag)
        for ridx in range(prog.n_rules):
            name = prog.rules[ridx].name
            expects_match = bool(matched[b, ridx])
            line = next(ln for ln in trace.splitlines()
                        if ln.strip().startswith(f"r{ridx} {name}:"))
            assert (": MATCH" in line) == expects_match, \
                f"bag {b} rule {ridx}: {line}"


def test_rule_dump_cli(tmp_path):
    (tmp_path / "config.yaml").write_text("""
kind: handler
metadata: {name: denyall, namespace: istio-system}
spec: {adapter: denier, params: {}}
---
kind: instance
metadata: {name: nothing, namespace: istio-system}
spec: {template: checknothing, params: {}}
---
kind: rule
metadata: {name: deny-admin, namespace: istio-system}
spec:
  match: request.path.startsWith("/admin")
  actions: [{handler: denyall, instances: [nothing]}]
""")
    out = subprocess.run(
        [sys.executable, "-m", "istio_tpu.cmd", "rule-dump",
         "--config-store", str(tmp_path),
         "--explain", "request.path=/admin/x"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "deny-admin" in out.stdout
    assert "atoms:" in out.stdout
    assert "MATCH" in out.stdout
