"""Prometheus text-exposition conformance for the homegrown registry
(utils/metrics.py) — the half of the merged /metrics surface that does
NOT come from prometheus_client and so gets no conformance for free.

Lint contract (ISSUE satellite): every Histogram family must emit
`_bucket` lines ending in le="+Inf", a `_sum` line and a `_count` line
— for every label set it has seen, AND as an explicit zero series when
it has seen none (a bare `# TYPE` line with no samples is a malformed
family to real scrapers).
"""
import re

import numpy as np

from istio_tpu.utils.metrics import (Counter, Gauge, Histogram,
                                     Registry, SlidingWindow)

_SAMPLE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$")


def _parse(text: str):
    """exposition text → {metric name: [(labels dict, float value)]}"""
    out: dict = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE.match(line)
        assert m, f"malformed sample line: {line!r}"
        labels = {}
        if m.group("labels"):
            for pair in m.group("labels").split(","):
                k, v = pair.split("=", 1)
                assert v.startswith('"') and v.endswith('"'), line
                labels[k] = v[1:-1]
        out.setdefault(m.group("name"), []).append(
            (labels, float(m.group("value"))))
    return out


def _histogram_families(samples: dict) -> set:
    return {n[:-len("_bucket")] for n in samples if n.endswith("_bucket")}


def lint_histograms(text: str, expect: set | None = None) -> None:
    """Assert the satellite's conformance contract over an exposition
    blob; `expect` adds the requirement that those families appear."""
    samples = _parse(text)
    fams = _histogram_families(samples)
    if expect is not None:
        missing = expect - fams
        assert not missing, f"histogram families absent: {missing}"
    for fam in fams:
        buckets = samples[fam + "_bucket"]
        sums = samples.get(fam + "_sum")
        counts = samples.get(fam + "_count")
        assert sums, f"{fam}: no _sum line"
        assert counts, f"{fam}: no _count line"
        # group bucket lines per label set (minus le)
        by_series: dict = {}
        for labels, value in buckets:
            le = labels.get("le")
            assert le is not None, f"{fam}: bucket without le"
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            by_series.setdefault(key, []).append((le, value))
        count_by = {tuple(sorted(lb.items())): v for lb, v in counts}
        for key, series in by_series.items():
            les = [le for le, _ in series]
            assert les[-1] == "+Inf", \
                f"{fam}{dict(key)}: bucket ladder must end at +Inf " \
                f"(got {les})"
            vals = [v for _, v in series]
            assert vals == sorted(vals), \
                f"{fam}{dict(key)}: cumulative buckets not monotone"
            assert key in count_by, f"{fam}: _count missing for {key}"
            assert vals[-1] == count_by[key], \
                f"{fam}{dict(key)}: +Inf bucket != _count"


def test_observed_histogram_conformance():
    reg = Registry()
    h = reg.histogram("lat_seconds", "latency", buckets=(0.001, 0.01, 1.0))
    for v in (0.0005, 0.005, 0.5, 2.0):
        h.observe(v)
        h.observe(v, stage="device")
    text = reg.expose_text()
    lint_histograms(text, expect={"lat_seconds"})
    samples = _parse(text)
    # per-series counts: 4 observations each for {} and {stage=device}
    counts = dict((tuple(sorted(lb.items())), v)
                  for lb, v in samples["lat_seconds_count"])
    assert counts[()] == 4
    assert counts[(("stage", "device"),)] == 4
    # sum carries through
    sums = dict((tuple(sorted(lb.items())), v)
                for lb, v in samples["lat_seconds_sum"])
    assert abs(sums[()] - 2.5055) < 1e-9


def test_unobserved_histogram_emits_zero_series():
    """The conformance fix this PR ships: an unobserved histogram used
    to expose only its # TYPE header — no samples at all."""
    reg = Registry()
    reg.histogram("never_seen_seconds", "nothing yet",
                  buckets=(0.1, 1.0))
    text = reg.expose_text()
    lint_histograms(text, expect={"never_seen_seconds"})
    samples = _parse(text)
    assert samples["never_seen_seconds_count"] == [({}, 0.0)]
    assert samples["never_seen_seconds_sum"] == [({}, 0.0)]
    inf = [v for lb, v in samples["never_seen_seconds_bucket"]
           if lb.get("le") == "+Inf"]
    assert inf == [0.0]


def test_counter_gauge_exposition_and_help():
    reg = Registry()
    c = reg.counter("reqs_total", "requests")
    g = reg.gauge("depth", "queue depth")
    c.inc(3, front="grpc")
    g.set(7.5)
    text = reg.expose_text()
    assert "# HELP reqs_total requests" in text
    assert "# TYPE reqs_total counter" in text
    assert 'reqs_total{front="grpc"} 3.0' in text
    assert "depth 7.5" in text


def test_runtime_monitor_registry_lints():
    """The real serving registry (stage decomposition, e2e, live
    gauges) passes the same lint — including before any traffic, when
    every family must still emit its zero series."""
    from istio_tpu.runtime import monitor
    from istio_tpu.utils.metrics import default_registry

    monitor.refresh_latency_gauges()
    text = default_registry.expose_text()
    lint_histograms(text, expect={"mixer_check_stage_seconds",
                                  "mixer_check_e2e_seconds"})
    assert "mixer_check_p99_ms" in text
    assert "check_p99_under_target" in text


def test_latency_snapshot_windowed_delta():
    """Per-scenario readings must delta against a baseline token —
    the histograms are process-lifetime cumulative, and a bench phase
    must not inherit the previous phase's observations."""
    from istio_tpu.runtime import monitor

    monitor.observe_stage("tensorize", 0.010)      # pre-window noise
    base = monitor.stage_baseline()
    monitor.observe_stage("tensorize", 0.020)
    monitor.observe_stage("device_step", 0.040)
    monitor.observe_check_e2e(0.050)
    snap = monitor.latency_snapshot(since=base)
    assert snap["stages"]["tensorize"]["count"] == 1
    assert abs(snap["stages"]["tensorize"]["sum_ms"] - 20.0) < 1e-6
    assert snap["stages"]["device_step"]["count"] == 1
    assert snap["e2e_count"] == 1
    # windowed quantile comes from DELTA bucket counts: the 10ms
    # pre-window observation must not drag p50 down
    assert snap["stages"]["tensorize"]["p50_ms"] >= 20.0
    # unwindowed reading still sees everything
    full = monitor.latency_snapshot()
    assert full["stages"]["tensorize"]["count"] >= 2


def test_rulestats_families_zero_series_before_first_drain():
    """The rule-telemetry counter families (runtime/rulestats.py) must
    expose a zero series BEFORE the first drain — a dashboard has to
    distinguish 'no rule ever fired' from 'telemetry missing'. Private
    registry: the module-level families may already carry traffic from
    other tests."""
    from istio_tpu.runtime import rulestats

    reg = Registry()
    rulestats.register_families(reg)
    samples = _parse(reg.expose_text())
    for fam in ("mixer_rule_check_hits_total",
                "mixer_rule_check_denies_total",
                "mixer_rule_check_errors_total",
                "mixer_rulestats_drains_total"):
        assert samples.get(fam) == [({}, 0.0)], fam
    # the drain-wall histogram emits its zero ladder too
    lint_histograms(reg.expose_text(),
                    expect={"mixer_rulestats_drain_seconds"})


def test_rulestats_families_monotone_across_drains():
    """Per-rule counters are cumulative: two successive drains with
    activity in between must only ever increase each labeled series
    (prometheus counter semantics)."""
    from istio_tpu.runtime import rulestats

    reg = Registry()
    fams = rulestats.register_families(reg)
    agg = rulestats.RuleStatsAggregator(metrics=fams)

    class _Rule:
        def __init__(self, name):
            self.name, self.namespace = name, "ns1"

    class _Tele:
        """Scripted telemetry: each drain yields one hit/deny for
        rule 0 in slot 0."""
        def __init__(self):
            self.generation = 0

        def drain(self):
            self.generation += 1
            return {"generation": self.generation,
                    "hit": np.array([[2, 0]]),
                    "deny": np.array([[1, 0]]),
                    "err": np.array([1, 0]),
                    "exemplars": {}, "exemplars_seen": {},
                    "wall_s": 0.001}

    class _Plan:
        telemetry = _Tele()

    class _Snap:
        rules = [_Rule("r0"), _Rule("r1")]
        revision = 1

        class ruleset:
            ns_ids = {"": 0}

    class _Dispatcher:
        snapshot = _Snap()
        fused = _Plan()

    # attach() drains once (old plan = none), then two live drains
    agg.attach(_Dispatcher())
    readings = []
    for _ in range(2):
        agg.drain()
        samples = _parse(reg.expose_text())
        hits = {tuple(sorted(lb.items())): v for lb, v in
                samples["mixer_rule_check_hits_total"]}
        readings.append(hits.get((("rule", "ns1/r0"),), 0.0))
    assert readings[0] == 2.0 and readings[1] == 4.0, readings
    samples = _parse(reg.expose_text())
    denies = {tuple(sorted(lb.items())): v for lb, v in
              samples["mixer_rule_check_denies_total"]}
    assert denies[(("rule", "ns1/r0"),)] == 2.0
    drains = dict((tuple(sorted(lb.items())), v) for lb, v in
                  samples["mixer_rulestats_drains_total"])
    assert drains[()] >= 2.0


def test_sliding_window_quantiles():
    w = SlidingWindow(100)
    assert w.quantile(0.99) == 0.0
    for i in range(1, 101):
        w.observe(i / 1000.0)
    p50, p99 = w.quantiles((0.5, 0.99))
    assert 0.045 <= p50 <= 0.055
    assert 0.095 <= p99 <= 0.100
    # window slides: old observations age out
    for _ in range(100):
        w.observe(1.0)
    assert w.quantile(0.5) == 1.0
    assert w.total == 200
    w.reset()
    assert len(w) == 0 and w.quantile(0.5) == 0.0


def test_audit_families_zero_shaped_before_first_evaluation():
    """The mesh-audit families (runtime/audit.py) are pre-shaped at
    import: every invariant x status series of mixer_audit_checks,
    every invariant of mixer_audit_violations, every fault kind of
    the explainability counters — all present in the prometheus
    exposition BEFORE the first evaluation, so a dashboard can tell
    'auditor never ran' from 'scrape broken'. The gauges boot to
    their healthy values (1.0), never unset."""
    import prometheus_client

    from istio_tpu.runtime import monitor

    text = prometheus_client.generate_latest(
        monitor.REGISTRY).decode()
    for inv in monitor.AUDIT_INVARIANTS:
        assert f'mixer_audit_violations_total{{invariant="{inv}"}} ' \
            in text, inv
        for st in monitor.AUDIT_STATUSES:
            assert (f'mixer_audit_checks_total{{invariant="{inv}",'
                    f'status="{st}"}} ') in text, (inv, st)
    for kind in monitor.FAULT_KINDS:
        assert ('mixer_fault_explainability_injections_total'
                f'{{kind="{kind}"}} ') in text, kind
        assert ('mixer_fault_explainability_matched_total'
                f'{{kind="{kind}"}} ') in text, kind
    # the gauges carry their boot values, not absence
    assert "mixer_audit_healthy " in text
    assert "mixer_fault_explainability_rate " in text
    assert "mixer_audit_evaluations_total " in text
    # a registry that has seen NO audit activity in this process
    # would expose all-zero counters; with sibling suites running
    # first we can only pin shape — but healthy/explainability must
    # never read below their floor absent a real violation
    counters = monitor.audit_counters()
    assert set(counters["checks"]) == set(monitor.AUDIT_INVARIANTS)
    assert 0.0 <= counters["explainability_rate"] <= 1.0
