"""Oracle interpreter conformance: runs the shared corpus against the
host-side semantics oracle (pattern: reference interpreter tests consuming
mixer/pkg/il/testing/tests.go)."""
import pytest

from istio_tpu.attribute.bag import DictBag
from istio_tpu.expr.checker import AttributeDescriptorFinder, TypeError_
from istio_tpu.expr.oracle import EvalError, OracleProgram
from istio_tpu.expr.parser import ParseError
from istio_tpu.testing.corpus import CORPUS, CORPUS_MANIFEST, Case

FINDER = AttributeDescriptorFinder(CORPUS_MANIFEST)


@pytest.mark.parametrize("case", CORPUS, ids=lambda c: c.id())
def test_corpus_oracle(case: Case):
    if case.compile_err is not None:
        with pytest.raises((ParseError, TypeError_)) as exc:
            OracleProgram(case.e, FINDER)
        assert case.compile_err in str(exc.value), (
            f"expected compile error containing {case.compile_err!r}, "
            f"got {exc.value}")
        return

    prog = OracleProgram(case.e, FINDER)
    if case.type_ is not None:
        assert prog.result_type == case.type_

    bag = DictBag(case.input)
    if case.err is not None:
        with pytest.raises(EvalError) as exc:
            _, tracking = prog.evaluate_with_tracking(bag)
        assert case.err in str(exc.value)
        if case.referenced is not None:
            # re-run to capture tracking up to the error
            from istio_tpu.attribute.bag import TrackingBag
            tb = TrackingBag(bag)
            with pytest.raises(EvalError):
                prog._eval(prog.ast, tb)
            assert tb.referenced_names() == sorted(case.referenced)
        return

    value, tracking = prog.evaluate_with_tracking(bag)
    assert value == case.result, (
        f"{case.e} with {case.input} -> {value!r}, want {case.result!r}")
    if case.referenced is not None:
        assert tracking.referenced_names() == sorted(case.referenced)


def test_extract_eq_matches():
    from istio_tpu.expr.parser import extract_eq_matches
    got = extract_eq_matches(
        'destination.service == "db.svc" && context.protocol == "tcp" '
        '&& request.size == 10 || source.name == "x"')
    # LOR at top level: nothing hoistable
    assert got == {}
    got = extract_eq_matches(
        'destination.service == "db.svc" && (context.protocol == "tcp" '
        '&& "y" == source.name)')
    assert got == {"destination.service": "db.svc",
                   "context.protocol": "tcp", "source.name": "y"}
