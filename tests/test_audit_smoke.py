"""Tier-1 hook for scripts/audit_smoke.py: the CI gate that the mesh
audit plane keeps auditing — six invariants silent under clean
two-front (gRPC + native) load, every chaos fault class matched to
named forensics evidence (explainability 1.0), and a deliberately
corrupted conservation counter flips mixer_audit_healthy with the
ledger evidence served on /debug/audit. Runs main() in-process (the
chaos_smoke pattern: a subprocess would pay a second jax import for
no extra coverage; the script stays runnable standalone under
JAX_PLATFORMS=cpu)."""
import importlib.util
import os
import sys


def test_audit_smoke_main():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "audit_smoke.py")
    spec = importlib.util.spec_from_file_location("audit_smoke", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
        rc = mod.main(n_rules=30, n_checks=16)
    finally:
        sys.modules.pop(spec.name, None)
    assert rc == 0
