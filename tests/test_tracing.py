"""Tracing: zipkin-v2 wire export + serving-stage span decomposition.

Reference: pkg/tracing/config.go:87-135 (Configure composes zipkin/log
reporters, installs a global tracer); the serving pipeline emits
per-batch stage spans so a served check's latency decomposes into
queue-wait / tensorize / device / overlay (VERDICT r2 item 9).
"""
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from istio_tpu.utils import tracing


def test_zipkin_reporter_posts_v2_json():
    posts = []
    rep = tracing.ZipkinReporter(
        "http://collector/api/v2/spans",
        post=lambda url, payload: posts.append((url, payload)),
        flush_interval_s=0.02, max_batch=10)
    tr = tracing.Tracer(service_name="svc", reporter=rep)
    with tr.span("outer", k="v"):
        with tr.span("inner"):
            pass
    rep.flush()
    rep.close()
    assert posts, "no flush happened"
    url, payload = posts[0]
    spans = json.loads(payload)
    assert url.endswith("/api/v2/spans")
    by_name = {s["name"]: s for s in spans}
    assert set(by_name) == {"outer", "inner"}
    outer, inner = by_name["outer"], by_name["inner"]
    # zipkin v2 wire fields
    assert outer["localEndpoint"]["serviceName"] == "svc"
    assert outer["tags"] == {"k": "v"}
    assert isinstance(outer["duration"], int)
    # parentage: inner under outer, one trace
    assert inner["parentId"] == outer["id"]
    assert inner["traceId"] == outer["traceId"]


def test_zipkin_reporter_against_real_http_sink():
    got = []

    class Sink(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            got.append((self.path, self.rfile.read(n)))
            self.send_response(202)
            self.end_headers()

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Sink)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}/api/v2/spans"
        rep = tracing.ZipkinReporter(url, flush_interval_s=0.02)
        tr = tracing.Tracer(reporter=rep)
        with tr.span("hello"):
            pass
        rep.flush()
        rep.close()
        assert got and got[0][0] == "/api/v2/spans"
        assert json.loads(got[0][1])[0]["name"] == "hello"
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_configure_composes_and_noop_default():
    mem: list = []
    tr = tracing.configure("t", zipkin_url="http://x/api/v2/spans",
                           log_spans=True,
                           post=lambda u, p: mem.append(p))
    try:
        assert tr.reporter is not None
        with tr.span("s"):
            pass
    finally:
        tracing.shutdown()
    assert tracing.get_tracer().reporter is None   # back to noop
    # noop tracer yields None and records nothing
    with tracing.get_tracer().span("ignored") as s:
        assert s is None


def test_parent_from_traceparent():
    """W3C trace-context parsing: valid headers become parent dicts
    span()/start_span() can chain under; malformed/all-zero ids fall
    back to None (self-generated ids, the previous behavior)."""
    tid = "4bf92f3577b34da6a3ce929d0e0e4736"
    sid = "00f067aa0ba902b7"
    p = tracing.parent_from_traceparent(f"00-{tid}-{sid}-01")
    assert p == {"traceId": tid, "id": sid}
    # case-normalized
    p = tracing.parent_from_traceparent(f"00-{tid.upper()}-{sid}-01")
    assert p["traceId"] == tid
    for bad in (None, "", "00-zz-xx-01", f"00-{tid}-{sid}",
                f"00-{tid[:-2]}-{sid}-01",
                "00-" + "0" * 32 + f"-{sid}-01",
                f"00-{tid}-" + "0" * 16 + "-01"):
        assert tracing.parent_from_traceparent(bad) is None, bad
    # a span opened under the parsed parent joins the client's trace
    tr = tracing.Tracer(reporter=tracing.MemoryReporter())
    with tr.span("rpc.check",
                 parent=tracing.parent_from_traceparent(
                     f"00-{tid}-{sid}-01")) as s:
        assert s["traceId"] == tid and s["parentId"] == sid


def test_ring_snapshot_chronological_under_wraparound():
    """The ring holds FINISH order (children land before parents, and
    wrap-around evicts arbitrary prefixes); snapshot() must return
    START-time order, newest last — the satellite fix."""
    ring = tracing.RingReporter(capacity=4)
    # spans reported out of start order (a long-lived root finishing
    # after its children), then enough to wrap the ring
    for ts, name in ((50, "child-b"), (10, "root"), (40, "child-a"),
                     (60, "late-1"), (70, "late-2")):
        ring({"timestamp": ts, "id": name, "name": name})
    snap = ring.snapshot()
    assert [s["timestamp"] for s in snap] == \
        sorted(s["timestamp"] for s in snap)
    assert ring.dropped == 1
    # limit keeps the NEWEST spans after sorting
    assert [s["name"] for s in ring.snapshot(limit=2)] == \
        ["late-1", "late-2"]


def test_serving_pipeline_stage_spans():
    """Served checks decompose: batch → queue-wait tag + tensorize /
    device / overlay child spans from the fused dispatcher."""
    from istio_tpu.attribute.bag import bag_from_mapping
    from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs

    mem = tracing.MemoryReporter()
    tracing._global = tracing.Tracer(reporter=mem)
    try:
        s = MemStore()
        s.set(("handler", "istio-system", "deny"), {
            "adapter": "denier", "params": {"status_code": 7}})
        s.set(("instance", "istio-system", "nothing"), {
            "template": "checknothing", "params": {}})
        s.set(("rule", "istio-system", "r0"), {
            "match": 'request.path.startsWith("/admin")',
            "actions": [{"handler": "deny", "instances": ["nothing"]}]})
        srv = RuntimeServer(s, ServerArgs(batch_window_s=0.001))
        try:
            r = srv.check(bag_from_mapping({"request.path": "/admin/x"}))
            assert r.status_code == 7
        finally:
            srv.close()
        names = {s["name"] for s in mem.spans}
        assert {"serve.batch", "serve.tensorize", "serve.device",
                "serve.overlay"} <= names, names
        batch_span = next(s for s in mem.spans
                          if s["name"] == "serve.batch")
        assert "queue_wait_ms" in batch_span["tags"]
        # stage spans parent under the batch span
        tens = next(s for s in mem.spans
                    if s["name"] == "serve.tensorize")
        assert tens["parentId"] == batch_span["id"]
        assert tens["traceId"] == batch_span["traceId"]
    finally:
        tracing._global = tracing.NOOP_TRACER
