"""Full-mesh fused step (BASELINE config 5) — small-scale conformance.

One ruleset carries mTLS SAN whitelists + RBAC pseudo-rules + a device
quota + route-NFA rows; one device program computes check verdicts AND
winning routes. Verified against the independent oracles: the rbac
host adapter semantics ride the pseudo-rules (tests/test_rbac_lower.py
covers that pairing); here the composition is checked — SAN whitelist
verdicts, quota consumption, and route winners vs RouteTable's host
selector over the same generated route world.
"""
import numpy as np
import pytest

from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.models.policy_engine import (NOT_FOUND, OK,
                                            RESOURCE_EXHAUSTED)
from istio_tpu.testing import workloads


@pytest.fixture(scope="module")
def world():
    engine, lo, hi, weights, meta = workloads.make_full_mesh(
        n_services=64, n_roles=16)
    return engine, lo, hi, weights, meta


def _run(engine, bags, ns=None):
    batch = engine.tensorizer.tensorize(bags)
    n = len(bags)
    req_ns = np.zeros(n, np.int32) if ns is None else ns
    return engine.check(batch, req_ns)


def test_crafted_traffic_routes_and_mixes(world):
    """The bench workload must actually exercise routing (VERDICT r3
    item 7): with the route world passed in, a majority of requests
    match a route row, and both allow and deny outcomes appear."""
    engine, lo, hi, weights, meta = world
    reqs = workloads.make_full_mesh_requests(
        256, 64, n_roles=16, rules_by_host=meta["rules_by_host"])
    bags = [bag_from_mapping(r) for r in reqs]
    v = _run(engine, bags)
    matched = np.asarray(v.matched)
    routed_frac = (matched[:, lo:hi].any(axis=1)).mean()
    assert routed_frac >= 0.5, routed_frac
    status = np.asarray(v.status)
    assert (status == 0).any() and (status != 0).any()


def test_everything_lowers(world):
    engine, lo, hi, weights, meta = world
    assert meta["host_fallback"] == 0, \
        engine.ruleset.fallback_reason
    assert meta["n_rows"] == engine.ruleset.n_rules
    assert meta["n_routes"] > 0 and meta["n_triples"] == 16


def test_san_whitelist_and_authz_verdicts(world):
    engine, *_ = world
    svc = "svc3.ns3.svc.cluster.local"
    good_user = "spiffe://cluster.local/ns/ns3/sa/sa1"
    bags = [
        # mTLS + whitelisted SAN + authz role allows GET /api/v3/*
        bag_from_mapping({"destination.service": svc,
                          "connection.mtls": True,
                          "source.user": good_user,
                          "destination.namespace": "default",
                          "request.method": "GET",
                          "request.path": "/api/v3/x"}),
        # SAN not in the service's whitelist → NOT_FOUND
        bag_from_mapping({"destination.service": svc,
                          "connection.mtls": True,
                          "source.user":
                              "spiffe://cluster.local/ns/ns9/sa/sa1",
                          "destination.namespace": "default",
                          "request.method": "GET",
                          "request.path": "/api/v3/x"}),
        # plaintext: SAN rule inert; authz still applies
        bag_from_mapping({"destination.service": svc,
                          "connection.mtls": False,
                          "source.user": good_user,
                          "destination.namespace": "default",
                          "request.method": "GET",
                          "request.path": "/api/v3/x"}),
    ]
    v = _run(engine, bags)
    status = np.asarray(v.status)
    # bag 0: whitelisted + authz rule for role3 (user sa… in ns3)
    # may allow or deny depending on binding — just require it is not
    # a whitelist miss; bag 1 must be the whitelist NOT_FOUND
    assert status[1] == NOT_FOUND
    assert status[0] != NOT_FOUND


def test_quota_consumes_on_device(world):
    engine, *_ = world
    engine.reset_quota()
    # role1/bind1: user ns1/sa1 may GET svc1.* on /api/v1/* — the
    # request must pass SAN + authz for quota to run (status==OK gate)
    bag = bag_from_mapping({"destination.service":
                            "svc1.ns1.svc.cluster.local",
                            "connection.mtls": True,
                            "source.user":
                                "spiffe://cluster.local/ns/ns1/sa/sa1",
                            "destination.namespace": "default",
                            "request.method": "GET",
                            "request.path": "/api/v1/x"})
    before = int(np.asarray(engine.quota_counts).sum())
    _run(engine, [bag] * 4)
    after = int(np.asarray(engine.quota_counts).sum())
    assert after - before > 0, "device quota did not consume"


def test_route_winner_matches_host_selector(world):
    """The fused step's route argmax must agree with RouteTable's
    sequential host selector over the same route world."""
    from istio_tpu.pilot.route_nfa import RouteTable

    engine, lo, hi, weights, meta = world
    n_services = meta["n_services"]
    services, rules_by_host = workloads.make_route_world(
        meta["n_routes"], n_services, seed=11 + 1)
    rt = RouteTable(services, rules_by_host)

    reqs = workloads.make_full_mesh_requests(32, n_services, seed=5)
    bags = [bag_from_mapping(r) for r in reqs]
    v = _run(engine, bags)
    matched = np.asarray(v.matched)[:, lo:hi]
    scores = matched * np.asarray(weights)[None, :]
    best = scores.argmax(axis=1)
    hit = scores.max(axis=1) > 0
    got = np.where(hit, best, hi - lo)
    want = np.asarray([rt.select_host(r) for r in reqs])
    np.testing.assert_array_equal(got, want)
