"""Broker OSB model + CRD-backed config store (VERDICT r2 item 8).

References: broker/pkg/model/osb/*.go (wire dataclasses with exact
JSON names), broker/pkg/model/config/{schema,store}.go (service-class/
service-plan schemas, DNS-1123 names, ServicePlansByService), and
broker/pkg/controller/controller.go:48 (catalog built from the config
store). The round-trip drives provision → bind → unbind over HTTP with
the catalog sourced from (and instances/bindings persisted to) the
store.
"""
import json
import urllib.request

import pytest

from istio_tpu.broker import (BrokerConfigStore, BrokerServer,
                              ServiceBinding, ServiceInstance)
from istio_tpu.broker.model import BrokerConfigError
from istio_tpu.runtime.store import MemStore


def _store() -> BrokerConfigStore:
    cfg = BrokerConfigStore(MemStore())
    cfg.set("service-class", "default", "reviews", {
        "deployment": {"instance": "productpage"},
        "entry": {"name": "reviews-dashboard",
                  "id": "svc-1",
                  "description": "A book review service"}})
    cfg.set("service-plan", "default", "default-plan", {
        "plan": {"name": "istio-yearly", "id": "plan-1",
                 "description": "yearly plan"},
        "services": ["default/reviews"]})
    # a plan for a DIFFERENT service must not leak into reviews
    cfg.set("service-class", "default", "ratings", {
        "entry": {"name": "ratings", "id": "svc-2",
                  "description": "ratings"}})
    cfg.set("service-plan", "default", "ratings-plan", {
        "plan": {"name": "ratings-monthly", "id": "plan-2",
                 "description": "monthly"},
        "services": ["default/ratings"]})
    return cfg


def test_schema_validation():
    cfg = BrokerConfigStore(MemStore())
    with pytest.raises(BrokerConfigError, match="DNS-1123"):
        cfg.set("service-class", "default", "Bad_Name", {
            "entry": {"name": "x", "id": "1"}})
    with pytest.raises(BrokerConfigError, match="entry"):
        cfg.set("service-class", "default", "ok", {"entry": {}})
    with pytest.raises(BrokerConfigError, match="plan"):
        cfg.set("service-plan", "default", "ok", {"plan": {}})
    with pytest.raises(BrokerConfigError, match="unknown"):
        cfg.set("rule", "default", "ok", {})


def test_catalog_from_config_store():
    """controller.go:48: classes + their plans, per-service binding."""
    cat = _store().catalog().to_wire()
    by_name = {s["name"]: s for s in cat["services"]}
    assert set(by_name) == {"reviews-dashboard", "ratings"}
    rv = by_name["reviews-dashboard"]
    assert rv["id"] == "svc-1" and rv["bindable"] is False
    assert [p["id"] for p in rv["plans"]] == ["plan-1"]
    assert [p["id"] for p in by_name["ratings"]["plans"]] == ["plan-2"]
    # OSB wire field names exactly (osb/service.go json tags)
    assert "dashboard_client" in rv
    assert rv["plans"][0]["name"] == "istio-yearly"


def test_osb_wire_shapes():
    inst = ServiceInstance.from_request("i1", {
        "service_id": "svc-1", "plan_id": "plan-1",
        "organization_guid": "org", "space_guid": "space",
        "parameters": {"size": "small"}})
    w = inst.to_wire()
    assert w["id"] == "i1" and w["organization_guid"] == "org"
    assert w["parameters"] == {"size": "small"}
    assert set(inst.provision_response()) == {"dashboard_url"}
    b = ServiceBinding.from_request("i1", "b1", {
        "service_id": "svc-1", "plan_id": "plan-1", "app_guid": "app"})
    assert b.to_wire()["service_instance_id"] == "i1"
    assert b.to_wire()["app_id"] == "app"
    assert b.bind_response() == {"credentials": {}}


def _req(method, url, body=None):
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    try:
        with urllib.request.urlopen(req, timeout=5) as r:
            return r.status, json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_roundtrip_against_crd_store():
    """provision → bind → unbind → deprovision over HTTP, catalog from
    the config store, instances/bindings persisted into it."""
    cfg = _store()
    broker = BrokerServer(config_store=cfg)
    port = broker.start()
    base = f"http://127.0.0.1:{port}"
    try:
        code, cat = _req("GET", f"{base}/v2/catalog")
        assert code == 200 and len(cat["services"]) == 2

        code, resp = _req("PUT", f"{base}/v2/service_instances/i1",
                          {"service_id": "svc-1", "plan_id": "plan-1",
                           "organization_guid": "o",
                           "space_guid": "s"})
        assert code == 201 and "dashboard_url" in resp
        # persisted into the store
        assert ("service-instance", "", "i1") in \
            cfg.store.list("service-instance")

        code, resp = _req(
            "PUT", f"{base}/v2/service_instances/i1/service_bindings/b1",
            {"service_id": "svc-1", "plan_id": "plan-1"})
        assert code == 201 and "credentials" in resp
        assert ("service-binding", "", "i1.b1") in \
            cfg.store.list("service-binding")

        code, _ = _req(
            "DELETE",
            f"{base}/v2/service_instances/i1/service_bindings/b1")
        assert code == 200
        assert not cfg.store.list("service-binding")
        code, _ = _req("DELETE", f"{base}/v2/service_instances/i1")
        assert code == 200
        assert not cfg.store.list("service-instance")

        # unknown service id rejected against the store-backed catalog
        code, _ = _req("PUT", f"{base}/v2/service_instances/i9",
                       {"service_id": "nope"})
        assert code == 400

        # GET returns the typed instance on the wire
        _req("PUT", f"{base}/v2/service_instances/i2",
             {"service_id": "svc-2", "plan_id": "plan-2"})
        code, got = _req("GET", f"{base}/v2/service_instances/i2")
        assert code == 200 and got["service_id"] == "svc-2"
    finally:
        broker.stop()


def test_restart_rehydrates_from_store():
    """A broker restarted over the same store keeps serving records
    its predecessor provisioned (review r3 finding)."""
    cfg = _store()
    b1 = BrokerServer(config_store=cfg)
    assert b1.provision("i1", {"service_id": "svc-1",
                               "plan_id": "plan-1"})[0] == 201
    assert b1.bind("i1", "b1", {"service_id": "svc-1",
                                "plan_id": "plan-1"})[0] == 201

    b2 = BrokerServer(config_store=cfg)   # "restart"
    # idempotent re-provision of the SAME body → 200, not a fresh 201
    assert b2.provision("i1", {"service_id": "svc-1",
                               "plan_id": "plan-1"})[0] == 200
    # conflicting body → 409
    assert b2.provision("i1", {"service_id": "svc-1",
                               "plan_id": "plan-2"})[0] == 409
    # the binding survived too
    assert b2.unbind("i1", "b1")[0] == 200
    assert b2.deprovision("i1")[0] == 200
    assert not cfg.store.list("service-instance")
    assert not cfg.store.list("service-binding")
