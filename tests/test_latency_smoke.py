"""Tier-1 hook for scripts/latency_smoke.py: the CI gate that the
measured wire-to-verdict latency plane works end to end — the C++
wire histogram measures (present, finite, ordered, client-agreeing
p99) under closed-loop load over the real native front, the wire
decode path holds verdict parity with the host oracle over HTTP, the
continuous-batching lane never serves a stale generation across a
live config swap (with the grant revocation observable at the wire),
and a caching MixerClient sees ≥90% hits on repeat traffic. Runs
main() in-process (the introspect_smoke pattern)."""
import importlib.util
import os
import sys


def test_latency_smoke_main():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "latency_smoke.py")
    spec = importlib.util.spec_from_file_location("latency_smoke",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
        rc = mod.main(n_rules=80, n_loop=200)
    finally:
        sys.modules.pop(spec.name, None)
    assert rc == 0
