"""Direct unit tests for the MixerClient check cache (api/client.py).

The mixerclient contract (check_cache.cc semantics): a Check verdict
is reusable for a later request iff every attribute the server
REFERENCED matches — EXACT entries by value, ABSENCE entries by
staying absent — and only within the verdict's valid_duration /
valid_use_count budget. Nearly every API e2e test runs with
enable_check_cache=False (the server-side assertions need every RPC
to land), so the cache itself is pinned here directly: the gRPC stub
is replaced with a counting fake, no server involved.

Also pins the Report delta-coding key-drop flush: the wire protocol
accumulates deltas server-side with no removal marker, so a record
that DROPS a key must flush the in-flight request and start fresh.
"""
import datetime
import time

from istio_tpu.api import MixerClient, mixer_pb2 as pb
from istio_tpu.api.wire import referenced_to_proto
from istio_tpu.attribute.bag import bag_from_mapping


def _response(values, referenced, code=0, ttl_s=60.0, use_count=100):
    """CheckResponse whose ReferencedAttributes mark each item in
    `referenced` EXACT when present in `values`, ABSENCE otherwise
    (exactly what the server builds via referenced_to_proto)."""
    resp = pb.CheckResponse()
    resp.precondition.status.code = code
    resp.precondition.valid_duration.FromTimedelta(
        datetime.timedelta(seconds=ttl_s))
    resp.precondition.valid_use_count = use_count
    resp.precondition.referenced_attributes.CopyFrom(
        referenced_to_proto(frozenset(referenced),
                            bag_from_mapping(values)))
    return resp


class _Rig:
    """MixerClient over a fake unary stub that counts RPCs."""

    def __init__(self, make_response, cache=True):
        self.client = MixerClient("127.0.0.1:1",
                                  enable_check_cache=cache)
        self.calls = 0

        def fake_check(req):
            self.calls += 1
            return make_response(req)

        self.client._check = fake_check

    def close(self):
        self.client.close()


def test_exact_hit_and_value_change_miss():
    rig = _Rig(lambda req: _response({"a": 1}, {"a"}))
    try:
        rig.client.check({"a": 1})
        assert rig.calls == 1
        # identical referenced values → served from cache
        rig.client.check({"a": 1})
        rig.client.check({"a": 1, "unreferenced": "x"})
        assert rig.calls == 1
        # referenced value changed → signature mismatch → RPC
        rig.client.check({"a": 2})
        assert rig.calls == 2
    finally:
        rig.close()


def test_ttl_expiry_evicts():
    rig = _Rig(lambda req: _response({"a": 1}, {"a"}, ttl_s=0.05))
    try:
        rig.client.check({"a": 1})
        rig.client.check({"a": 1})
        assert rig.calls == 1
        time.sleep(0.06)
        rig.client.check({"a": 1})
        assert rig.calls == 2          # expired entry re-fetched
    finally:
        rig.close()


def test_valid_use_count_exhaustion():
    rig = _Rig(lambda req: _response({"a": 1}, {"a"}, use_count=2))
    try:
        rig.client.check({"a": 1})     # RPC 1, entry budget 2
        rig.client.check({"a": 1})     # hit (budget → 1)
        rig.client.check({"a": 1})     # hit (budget → 0)
        assert rig.calls == 1
        rig.client.check({"a": 1})     # spent entry evicted → RPC 2
        assert rig.calls == 2
    finally:
        rig.close()


def test_absence_condition_blocks_reuse():
    # server referenced "b" but the request lacked it → ABSENCE entry
    rig = _Rig(lambda req: _response({"a": 1}, {"a", "b"}))
    try:
        rig.client.check({"a": 1})
        rig.client.check({"a": 1})
        assert rig.calls == 1
        # "b" now present: the ABSENCE condition no longer transfers —
        # the cached verdict must NOT serve this request
        rig.client.check({"a": 1, "b": 9})
        assert rig.calls == 2
        # absent again → original entry still valid
        rig.client.check({"a": 1})
        assert rig.calls == 2
    finally:
        rig.close()


def test_map_key_reference_semantics():
    values = {"request.headers": {"cookie": "session=1"}}
    ref = {("request.headers", "cookie")}
    rig = _Rig(lambda req: _response(values, ref))
    try:
        rig.client.check(values)
        rig.client.check({"request.headers": {"cookie": "session=1",
                                              "other": "x"}})
        assert rig.calls == 1          # referenced KEY value unchanged
        rig.client.check({"request.headers": {"cookie": "session=2"}})
        assert rig.calls == 2          # referenced key changed
    finally:
        rig.close()


def test_quota_requests_bypass_cache():
    rig = _Rig(lambda req: _response({"a": 1}, {"a"}))
    try:
        rig.client.check({"a": 1}, quotas={"rq": 1})
        rig.client.check({"a": 1}, quotas={"rq": 1})
        assert rig.calls == 2          # quota allocs must reach the server
        # and quota responses must not have seeded the cache
        rig.client.check({"a": 1})
        assert rig.calls == 3
        rig.client.check({"a": 1})
        assert rig.calls == 3          # plain check cached normally
    finally:
        rig.close()


def test_disabled_cache_always_rpcs():
    rig = _Rig(lambda req: _response({"a": 1}, {"a"}), cache=False)
    try:
        rig.client.check({"a": 1})
        rig.client.check({"a": 1})
        assert rig.calls == 2
    finally:
        rig.close()


# ---------------------------------------------------------------------------
# Report delta coding: the key-drop flush
# ---------------------------------------------------------------------------

def _report_rig():
    client = MixerClient("127.0.0.1:1", enable_check_cache=False)
    sent = []
    client._report = lambda req: sent.append(req) or pb.ReportResponse()
    return client, sent


def test_report_key_drop_flushes():
    client, sent = _report_rig()
    try:
        # record 2 DROPS key "b": no removal marker exists on the wire,
        # so the client must flush request 1 and start a fresh one
        client.report([{"a": 1, "b": 2}, {"a": 1}])
        assert len(sent) == 2
        assert len(sent[0].attributes) == 1
        assert len(sent[1].attributes) == 1
    finally:
        client.close()


def test_report_consistent_keys_delta_code_into_one_request():
    client, sent = _report_rig()
    try:
        client.report([{"a": 1, "b": 2}, {"a": 1, "b": 3},
                       {"a": 2, "b": 3}])
        assert len(sent) == 1
        assert len(sent[0].attributes) == 3
    finally:
        client.close()
