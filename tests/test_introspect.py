"""Introspect server end-to-end: scrape the admin HTTP surface
in-process while a served check_many burst runs, and assert the
Check() latency decomposition holds together — all six stage
histograms populated, stage sums bounded by end-to-end, live p99
gauge in agreement with a client-side measurement of the same run.

Reference anchors: ControlZ introspection + Mixer's :9093
self-monitoring port (mixer/pkg/server/monitoring.go).
"""
import json
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

from istio_tpu.introspect import IntrospectServer
from istio_tpu.runtime import RuntimeServer, ServerArgs, monitor
from istio_tpu.runtime.monitor import CHECK_STAGES
from istio_tpu.testing import workloads
from istio_tpu.utils import tracing
from tests.test_metrics_exposition import _parse, lint_histograms


@pytest.fixture(scope="module")
def served():
    store = workloads.make_store(24)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=64, buckets=(16, 64),
        default_manifest=workloads.MESH_MANIFEST))
    plan = srv.controller.dispatcher.fused
    assert plan is not None
    plan.prewarm((16, 64))
    intro = IntrospectServer(runtime=srv)
    intro.start()
    try:
        yield srv, intro
    finally:
        intro.close()
        srv.close()
        tracing.shutdown()    # drop the ring-installed global tracer


def _get(intro: IntrospectServer, path: str):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{intro.port}{path}", timeout=10) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


def _get_json(intro: IntrospectServer, path: str):
    status, _, body = _get(intro, path)
    return status, json.loads(body)


def test_scrape_during_check_many_burst(served):
    srv, intro = served
    monitor.reset_latency_window()
    bags = workloads.make_bags(32)
    for _ in range(4):
        results = srv.check_many(bags)
        assert len(results) == 32

    status, ctype, body = _get(intro, "/metrics")
    assert status == 200
    assert ctype.startswith("text/plain")
    text = body.decode()

    # ONE merged exposition: prometheus_client families (runtime
    # counters) AND homegrown families (stage decomposition) in the
    # same blob
    assert "mixer_runtime_resolve_count" in text
    assert "mixer_runtime_config_generation" in text
    assert "mixer_check_stage_seconds_bucket" in text

    samples = _parse(text)
    # all six stage histograms populated by the served burst
    stage_counts = {lb["stage"]: v
                    for lb, v in samples["mixer_check_stage_seconds_count"]
                    if "stage" in lb}
    for stage in CHECK_STAGES:
        assert stage_counts.get(stage, 0) > 0, \
            f"stage {stage!r} not populated: {stage_counts}"

    # monotone: per-batch stage work can never exceed the per-request
    # end-to-end mass it decomposes (each batch carries >= 1 request)
    stage_sums = {lb["stage"]: v
                  for lb, v in samples["mixer_check_stage_seconds_sum"]
                  if "stage" in lb}
    e2e_sum = dict((tuple(lb.items()), v) for lb, v in
                   samples["mixer_check_e2e_seconds_sum"])[()]
    assert sum(stage_sums.values()) <= e2e_sum + 1e-6, \
        f"stage sums {stage_sums} exceed e2e {e2e_sum}"

    # live percentile gauges present and live
    p99 = dict((tuple(lb.items()), v) for lb, v in
               samples["mixer_check_p99_ms"])[()]
    assert p99 > 0.0
    assert "check_p99_under_target" in samples

    # the whole merged blob passes the exposition lint
    lint_histograms(text, expect={"mixer_check_stage_seconds",
                                  "mixer_check_e2e_seconds"})


def test_live_p99_agrees_with_measured(served):
    """The acceptance cross-check, in-process: drive concurrent checks
    through the batcher, measure latency at the caller, and compare
    against the live p99 gauge over the same window."""
    srv, _ = served
    bags = workloads.make_bags(64)
    # warm the batcher path before the measured window
    with ThreadPoolExecutor(max_workers=16) as pool:
        list(pool.map(srv.check, bags[:16]))
    monitor.reset_latency_window()
    lat = []

    def one(bag):
        t0 = time.perf_counter()
        srv.check(bag)
        return time.perf_counter() - t0

    with ThreadPoolExecutor(max_workers=16) as pool:
        lat = list(pool.map(one, bags))
    live = monitor.refresh_latency_gauges()
    assert live["n_window"] >= len(bags)
    measured_p99_ms = float(np.percentile(lat, 99) * 1e3)
    live_p99_ms = live["p99_ms"]
    assert live_p99_ms > 0
    # caller-side wall time >= server-side e2e (enqueue->delivery),
    # and the two p99s must track: generous bound for CI scheduling
    # jitter (bench asserts the tight 20% on real runs)
    assert abs(live_p99_ms - measured_p99_ms) <= \
        0.5 * max(measured_p99_ms, 1.0), \
        f"live p99 {live_p99_ms}ms vs measured {measured_p99_ms}ms"
    # SLO gauge reflects the refreshed window
    assert live["under_target"] == (
        live_p99_ms <= monitor.CHECK_P99_TARGET_MS)


def test_healthz_readyz_config(served):
    srv, intro = served
    status, payload = _get_json(intro, "/healthz")
    assert status == 200
    assert payload["status"] == "ok"
    assert payload["config_generation"] >= 1

    status, payload = _get_json(intro, "/readyz")
    assert status == 200
    assert payload["status"] == "ready"
    assert payload["n_rules"] > 0

    status, payload = _get_json(intro, "/debug/config")
    assert status == 200
    assert payload["fused"] is True
    assert payload["n_rules"] >= 24
    assert payload["buckets"] == [16, 64]
    assert payload["generation"] >= 1


def test_debug_queues_and_cache(served):
    srv, intro = served
    srv.check_many(workloads.make_bags(8))
    status, payload = _get_json(intro, "/debug/queues")
    assert status == 200
    check = payload["check"]
    assert check["depth"] >= 0
    assert check["pipeline"] >= 1
    assert check["buckets"] == [16, 64]
    assert not check["closed"]
    assert "report" in payload            # report coalescer visible too
    stages = payload["latency"]["stages"]
    assert "device_step" in stages and stages["device_step"]["count"] > 0

    status, payload = _get_json(intro, "/debug/cache")
    assert status == 200
    # both prewarmed bucket shapes live in the packer's jit cache
    compile_stats = payload["compile"]
    if compile_stats.get("packer_entries") is not None:
        assert compile_stats["packer_entries"] >= 2
    assert payload.get("interner_values", 1) > 0


def test_debug_traces_and_root_span_parenting(served):
    """API-layer root span satellite: a serve.batch span must share
    its trace with (and parent under) the rpc.check root opened at
    RPC decode, so queue-wait is attributed to a request."""
    srv, intro = served
    tr = tracing.get_tracer()
    assert tr.reporter is not None    # the introspect ring installed it
    with tr.span("rpc.check") as root:
        srv.check(workloads.make_bags(1)[0])
    status, payload = _get_json(intro, "/debug/traces")
    assert status == 200
    spans = payload["spans"]
    batch_spans = [s for s in spans if s["name"] == "serve.batch"
                   and s.get("traceId") == root["traceId"]]
    assert batch_spans, f"no serve.batch under the rpc.check root in " \
                        f"{[s['name'] for s in spans]}"
    assert batch_spans[-1]["parentId"] == root["id"]


def test_debug_rulestats_view(served):
    """/debug/rulestats: drains on demand and serves top-K hot rules
    with per-namespace deny rates, never-hit bookkeeping (with the
    analyzer cross-check flag present) and decision exemplars whose
    trace ids join /debug/traces."""
    srv, intro = served
    # crafted deny traffic: rule 0 (deny action) of make_store(24),
    # through the batcher so exemplars sample the serve.batch span
    from istio_tpu.attribute.bag import bag_from_mapping
    for _ in range(4):
        srv.check(bag_from_mapping({
            "destination.service": "svc0.ns0.svc.cluster.local",
            "source.namespace": "ns9"}))
    status, payload = _get_json(intro, "/debug/rulestats?k=50")
    assert status == 200
    assert payload["drains"] >= 1
    assert payload["rules_tracked"] == 26    # 24 mesh + quota + report
    top = {t["rule"]: t for t in payload["top"]}
    entry = top.get("ns0/rule0")
    assert entry is not None, sorted(top)
    assert entry["hits"] >= 4 and entry["denies"] >= 4
    assert entry["deny_rate_by_namespace"].get("ns0") == 1.0
    assert entry["exemplars"] and entry["exemplars"][0]["trace_id"]
    # never-hit entries carry the analyzer cross-check flag
    assert payload["never_hit"], "some rules never fire in this mix"
    assert all("analyzer_shadowed" in e for e in payload["never_hit"])
    hot = {t["rule"] for t in payload["top"]}
    assert hot.isdisjoint({e["rule"] for e in payload["never_hit"]})
    # the counter families surface on the merged /metrics exposition
    _, _, body = _get(intro, "/metrics")
    text = body.decode()
    assert "mixer_rule_check_hits_total" in text
    assert "mixer_rulestats_drains_total" in text


def test_debug_traces_status_filter(served):
    """?status=failed keeps only spans whose status tag is set and not
    ok — the failure-filter satellite over the check spans' new status
    tags."""
    _, intro = served
    tr = tracing.get_tracer()
    with tr.span("rpc.check") as s_ok:
        s_ok["tags"]["status"] = "ok"
    with tr.span("rpc.check") as s_bad:
        s_bad["tags"]["status"] = "7"
    status, payload = _get_json(intro, "/debug/traces?status=failed")
    assert status == 200
    statuses = {(s["tags"] or {}).get("status")
                for s in payload["spans"]}
    assert "7" in statuses and "ok" not in statuses
    status, payload = _get_json(intro, "/debug/traces?status=7")
    assert {(s["tags"] or {}).get("status")
            for s in payload["spans"]} == {"7"}


def test_close_without_start_does_not_hang():
    """shutdown() blocks on serve_forever()'s event — close() on a
    never-started server (a pre-start failure's cleanup path, e.g. the
    smoke script's finally block) must return, not deadlock."""
    prev = tracing.get_tracer()
    intro = IntrospectServer()
    intro.close()                      # would hang before the guard
    assert tracing.get_tracer() is prev    # ring restored too


def test_ring_enable_disable_restores_tracer():
    """enable_ring/disable_ring must unwind cleanly: a closed
    introspect server leaves no span construction on the hot path and
    create/close cycles never stack dead rings."""
    prev = tracing.get_tracer()
    ring = tracing.enable_ring(8)
    installed = tracing.get_tracer()
    assert installed is not prev and installed.reporter is not None
    with installed.span("probe"):
        pass
    assert ring.snapshot()[-1]["name"] == "probe"
    tracing.disable_ring(ring)
    assert tracing.get_tracer() is prev
    # non-LIFO close order: disabling the earlier ring leaves the
    # later owner's stack alone; disabling the later one then unwinds
    # PAST the already-closed earlier ring back to the base tracer
    r1 = tracing.enable_ring(8)
    r2 = tracing.enable_ring(8)
    tracing.disable_ring(r1)            # r2 still owns the stack
    assert tracing.get_tracer()._ring is r2
    with tracing.get_tracer().span("while-r1-closed"):
        pass
    assert not r1.snapshot()            # closed ring records nothing
    assert r2.snapshot()[-1]["name"] == "while-r1-closed"
    tracing.disable_ring(r2)
    assert tracing.get_tracer() is prev


def test_unknown_path_404(served):
    _, intro = served
    try:
        _get(intro, "/nope")
        raise AssertionError("expected HTTP 404")
    except urllib.error.HTTPError as exc:
        assert exc.code == 404
        assert b"/metrics" in exc.read()
