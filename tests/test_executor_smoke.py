"""Tier-1 hook for scripts/executor_smoke.py: the CI gate that the
adapter-executor plane isolates, bounds and accounts host adapter
work — a chaos-wedged adapter over the real gRPC front never holds a
request past its deadline, degradation is typed and counted, the
bulkhead protects sibling handlers, /debug/executor agrees over real
HTTP, the lane breaker recovers, and the OPA scenario holds oracle
parity. Runs main() in-process (the introspect_smoke pattern)."""
import importlib.util
import os
import sys


def test_executor_smoke_main():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "executor_smoke.py")
    spec = importlib.util.spec_from_file_location("executor_smoke",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
        rc = mod.main(n_rules=60, n_checks=24)
    finally:
        sys.modules.pop(spec.name, None)
    assert rc == 0
