"""Unit tests for the tail-latency forensics plane
(istio_tpu/runtime/forensics.py): ring bounds + typed drop counters,
threshold gating, tape stage/host-wait attribution, event-timeline
overlap + coalescing, the thread-stack dump, and the introspect
/debug/traces ?min_ms= / ?trace= filters. The module-level RECORDER /
EVENTS singletons are process-global — every test restores defaults
so sibling suites (and the smoke) see a clean recorder."""
from __future__ import annotations

import contextlib
import json
import time
import urllib.request

from istio_tpu.runtime import forensics, monitor


@contextlib.contextmanager
def _recorder(threshold_ms=100.0, capacity=8, adaptive=False):
    rec = forensics.RECORDER
    try:
        rec.configure(enabled=True, threshold_ms=threshold_ms,
                      adaptive=adaptive, capacity=capacity)
        rec.reset()
        yield rec
    finally:
        rec.configure(enabled=True, threshold_ms=0.0,
                      adaptive=False, capacity=256)
        rec.reset()


def _capture_one(rec, e2e_s=0.5, stages=(("device_step", 0.3),)):
    rec.batch_begin()
    for stage, s in stages:
        rec.stage_mark(stage, s)
    rec.note_batch(e2e_s, 4, {"traceId": "t1"})


def test_threshold_gates_capture():
    with _recorder(threshold_ms=100.0) as rec:
        base = monitor.forensics_counters()["slow_captured"]
        _capture_one(rec, e2e_s=0.05)      # under: silent
        assert rec.snapshot()["retained"] == 0
        _capture_one(rec, e2e_s=0.5)       # over: one exemplar
        snap = rec.snapshot()
        assert snap["retained"] == 1
        assert monitor.forensics_counters()["slow_captured"] \
            == base + 1
        ex = snap["slowest"][0]
        assert ex["e2e_ms"] == 500.0
        assert ex["trace_id"] == "t1"
        assert ex["traces_link"] == "/debug/traces?trace=t1"


def test_ring_bound_and_typed_drops():
    with _recorder(threshold_ms=1.0, capacity=4) as rec:
        base = monitor.forensics_counters()["dropped"]["slow"]
        for i in range(6):
            _capture_one(rec, e2e_s=0.01 * (i + 1))
        snap = rec.snapshot(top_k=16)
        assert snap["retained"] == 4
        assert monitor.forensics_counters()["dropped"]["slow"] \
            == base + 2
        # top-K is slowest-first over the RETAINED (recent) window
        e2es = [e["e2e_ms"] for e in snap["slowest"]]
        assert e2es == sorted(e2es, reverse=True)


def test_tape_attributes_stages_and_host_waits():
    with _recorder(threshold_ms=100.0) as rec:
        rec.batch_begin()
        rec.stage_mark("queue_wait", 0.05)
        rec.stage_mark("device_step", 0.1)
        rec.host_wait("mq.istio-system", 0.4)
        rec.note_batch(0.6, 2, None)
        ex = rec.snapshot()["slowest"][0]
        assert ex["stages_ms"]["host:mq.istio-system"] == 400.0
        assert ex["top_stage"] == "host:mq.istio-system"
        assert ex["stages_ms"]["device_step"] == 100.0


def test_disabled_recorder_is_silent_and_clears_tape():
    with _recorder(threshold_ms=1.0) as rec:
        rec.batch_begin()
        rec.stage_mark("device_step", 0.2)
        rec.configure(enabled=False)
        rec.batch_begin()              # disabled: clears the tape
        rec.stage_mark("device_step", 9.9)
        rec.note_batch(9.9, 1, None)
        assert rec.snapshot()["retained"] == 0
        rec.configure(enabled=True)


def test_wire_decode_premark_joins_next_batch():
    with _recorder(threshold_ms=10.0) as rec:
        rec.note_wire_decode(0.025)
        rec.batch_begin()
        rec.stage_mark("device_step", 0.05)
        rec.note_batch(0.2, 1, None)
        ex = rec.snapshot()["slowest"][0]
        assert ex["stages_ms"]["wire_decode"] == 25.0


def test_event_overlap_and_pre_window():
    ring = forensics.EventTimeline(capacity=32)
    t0 = time.perf_counter()
    ring.record("config_publish", generation=7)
    # an event 0.5s in the "past" of a request that starts now must
    # still annotate it (the pre-window); one 5s back must not
    with ring._lock:
        ring._buf[0]["t"] = t0 - 0.5
    ring.record("breaker", name="device", to="open")
    with ring._lock:
        ring._buf[1]["t"] = t0 - 5.0
    got = ring.overlapping(t0, t0 + 0.01, pre_s=1.0)
    kinds = [e["kind"] for e in got]
    assert kinds == ["config_publish"]


def test_event_coalescing_and_drop_counter():
    base = monitor.forensics_counters()["dropped"]["events"]
    ring = forensics.EventTimeline(capacity=8)
    for _ in range(5):
        ring.record("quota_flush", coalesce_s=10.0, items=3)
    assert len(ring) == 1
    ev = ring.snapshot()[0]
    assert ev["n"] == 5
    assert ev["detail"]["items"] == 15   # numeric fields accumulate
    for i in range(10):
        ring.record(f"kind{i}")
    assert len(ring) == 8
    assert monitor.forensics_counters()["dropped"]["events"] \
        == base + 3   # 1 coalesced + 10 distinct into capacity 8


def test_event_coalescing_never_masks_identity():
    """A provider_refresh FAILURE inside the coalesce window of a
    success (or a different provider) must stay its own entry — the
    diagnostic identity is the ring's whole point."""
    ring = forensics.EventTimeline(capacity=8)
    ring.record("provider_refresh", coalesce_s=10.0,
                provider="a", ok=True)
    ring.record("provider_refresh", coalesce_s=10.0,
                provider="a", ok=False)
    ring.record("provider_refresh", coalesce_s=10.0,
                provider="b", ok=False)
    ring.record("provider_refresh", coalesce_s=10.0,
                provider="b", ok=False)
    evs = ring.snapshot()
    assert [(e["detail"]["provider"], e["detail"]["ok"], e["n"])
            for e in evs] == \
        [("a", True, 1), ("a", False, 1), ("b", False, 2)]


def test_adaptive_threshold_never_below_base():
    with _recorder(threshold_ms=50.0, adaptive=True) as rec:
        # empty/fast window: the adaptive threshold floors at base
        assert rec.threshold_s() >= 0.05


def test_thread_stacks_names_this_thread():
    import threading
    dump = forensics.thread_stacks()
    assert dump["n_threads"] >= 1
    names = {t["name"] for t in dump["threads"]}
    assert threading.current_thread().name in names
    assert all(t["stack"] for t in dump["threads"])


def test_capture_profile_fail_soft_or_artifact(tmp_path):
    out = forensics.capture_profile(str(tmp_path), 0.1)
    if out.get("available"):
        assert out["n_files"] >= 1 and out["bytes_total"] > 0
    else:
        assert "error" in out


def test_traces_min_ms_and_trace_filters():
    from istio_tpu.introspect import IntrospectServer
    from istio_tpu.utils import tracing

    intro = IntrospectServer(runtime=None)
    try:
        port = intro.start()
        tr = tracing.get_tracer()
        tr.emit("fast.span", 0.001)
        with tr.span("slow.root") as root:
            tr.emit("slow.child", 0.5)
        time.sleep(0.05)

        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}",
                    timeout=10) as r:
                return json.load(r)

        spans = get("/debug/traces?min_ms=100")["spans"]
        assert spans and all(s["duration"] >= 100_000
                             for s in spans)
        assert not any(s["name"] == "fast.span" for s in spans)
        tid = root["traceId"]
        spans = get(f"/debug/traces?trace={tid}")["spans"]
        assert spans and all(s["traceId"] == tid for s in spans)
    finally:
        intro.close()


def test_debug_slow_and_events_serve_without_runtime():
    from istio_tpu.introspect import IntrospectServer

    with _recorder(threshold_ms=10.0) as rec:
        _capture_one(rec, e2e_s=0.3)
        forensics.record_event("config_publish", generation=1)
        intro = IntrospectServer(runtime=None)
        try:
            port = intro.start()

            def get(path):
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}{path}",
                        timeout=10) as r:
                    return json.load(r)

            slow = get("/debug/slow?k=4")
            assert slow["retained"] == 1
            assert slow["slowest"][0]["e2e_ms"] == 300.0
            ev = get("/debug/events?kind=config_publish&n=4")
            assert ev["events"]
            assert all(e["kind"] == "config_publish"
                       for e in ev["events"])
            th = get("/debug/threads")
            assert th["n_threads"] >= 1
        finally:
            intro.close()


def test_debug_events_type_and_since_filters():
    """/debug/events ?type= (alias of ?kind=) and ?since_s= narrow
    the timeline to one event class inside a recency window — the
    incident-forensics query ("what audit violations in the last
    minute") must not require client-side filtering."""
    from istio_tpu.introspect import IntrospectServer

    forensics.EVENTS.reset()
    forensics.record_event("audit_violation", invariant="x")
    forensics.record_event("config_publish", generation=9)
    intro = IntrospectServer(runtime=None)
    try:
        port = intro.start()

        def get(path):
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}",
                    timeout=10) as r:
                return json.load(r)

        # ?type= behaves exactly like ?kind=
        ev = get("/debug/events?type=audit_violation")
        assert ev["events"]
        assert all(e["kind"] == "audit_violation"
                   for e in ev["events"])
        # a generous window keeps both events; a zero-width one
        # (since the future) drops everything
        ev = get("/debug/events?since_s=60")
        kinds = {e["kind"] for e in ev["events"]}
        assert {"audit_violation", "config_publish"} <= kinds
        ev = get("/debug/events?since_s=0")
        assert ev["events"] == []
        # both filters compose
        ev = get("/debug/events?type=config_publish&since_s=60")
        assert ev["events"]
        assert all(e["kind"] == "config_publish"
                   for e in ev["events"])
        # a malformed since_s is ignored, not a 500
        ev = get("/debug/events?since_s=bogus&type=config_publish")
        assert all(e["kind"] == "config_publish"
                   for e in ev["events"])
    finally:
        intro.close()
        forensics.EVENTS.reset()
