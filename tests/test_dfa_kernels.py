"""DFA device-kernel parity: all three evaluation tiers (flat gather,
dense one-hot MXU, block-diagonal one-hot MXU) must agree with the
host automaton on mixed pattern banks — the blocked tier is only
reachable past the dense size gate in production, so it needs direct
coverage (r4 review finding)."""
import numpy as np

from istio_tpu.ops import bytes_ops
from istio_tpu.ops.regex_dfa import (compile_regex, dfa_matches_host,
                                     pack_dfas, pack_dfas_classes,
                                     pack_dfas_onehot,
                                     pack_dfas_onehot_blocked)

PATS = ([f"^/api/v{k}/" for k in range(6)] +
        [r"items/[0-9]+", r"^/x$", r"a+b*c", r"(foo|bar)baz"])
SUBJECTS = [b"/api/v3/items/77", b"/x", b"/xx", b"", b"aac", b"abc",
            b"ac", b"/items/123", b"zzz", b"/api/v9/x", b"foobaz",
            b"xbarbazy"]


def _tensors():
    L = 32
    data = np.zeros((len(SUBJECTS), L), np.uint8)
    lens = np.zeros(len(SUBJECTS), np.int32)
    for i, s in enumerate(SUBJECTS):
        data[i, :len(s)] = np.frombuffer(s, np.uint8)
        lens[i] = len(s)
    return data, lens


def test_all_dfa_tiers_match_host_oracle():
    dfas = [compile_regex(p) for p in PATS]
    data, lens = _tensors()
    want = np.asarray([[dfa_matches_host(d, s) for d in dfas]
                       for s in SUBJECTS])

    trans, accept = pack_dfas(dfas)
    gather = np.asarray(bytes_ops.dfa_match_many(data, lens, trans,
                                                 accept))
    np.testing.assert_array_equal(gather, want)

    classes = pack_dfas_classes(dfas)
    dense = np.asarray(bytes_ops.dfa_match_many_onehot(
        data, lens, pack_dfas_onehot(dfas, classes)))
    np.testing.assert_array_equal(dense, want)

    blocked = np.asarray(bytes_ops.dfa_match_many_onehot_blocked(
        data, lens, pack_dfas_onehot_blocked(dfas, classes)))
    np.testing.assert_array_equal(blocked, want)
