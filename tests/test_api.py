"""istio.mixer.v1 gRPC e2e: a real grpcio server + client over
localhost, dictionary-compressed attributes both ways, quota loop in
Check, delta-coded Report, and client-side check caching driven by
ReferencedAttributes (the mixerclient contract).

Reference pattern: mixer/pkg/mockapi + mixer/pkg/api tests.
"""
import datetime

import pytest

from istio_tpu.api import MixerClient, MixerGrpcServer, mixer_pb2 as pb
from istio_tpu.api.wire import bag_to_compressed, compressed_to_dict
from istio_tpu.models.policy_engine import (NOT_FOUND, OK,
                                            PERMISSION_DENIED)
from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs


def _store() -> MemStore:
    s = MemStore()
    s.set(("handler", "istio-system", "wl"), {
        "adapter": "list", "params": {"overrides": ["v1", "v2"]}})
    s.set(("handler", "istio-system", "mq"), {
        "adapter": "memquota",
        "params": {"quotas": [{"name": "rq.istio-system",
                               "max_amount": 3,
                               "valid_duration_s": 600.0}]}})
    s.set(("instance", "istio-system", "ver"), {
        "template": "listentry",
        "params": {"value": 'source.labels["version"] | "none"'}})
    s.set(("instance", "istio-system", "rq"), {
        "template": "quota", "params": {"dimensions": {}}})
    s.set(("rule", "istio-system", "r"), {
        "match": "",
        "actions": [{"handler": "wl", "instances": ["ver"]},
                    {"handler": "mq", "instances": ["rq"]}]})
    return s


@pytest.fixture(scope="module")
def rig():
    runtime = RuntimeServer(_store(), ServerArgs(batch_window_s=0.001,
                                                 max_batch=64))
    server = MixerGrpcServer(runtime)
    port = server.start()
    client = MixerClient(f"127.0.0.1:{port}", enable_check_cache=False)
    cached = MixerClient(f"127.0.0.1:{port}", enable_check_cache=True)
    yield runtime, server, client, cached
    client.close(); cached.close()
    server.stop(); runtime.close()


def test_wire_roundtrip():
    now = datetime.datetime(2018, 1, 7, tzinfo=datetime.timezone.utc)
    values = {
        "source.ip": b"\x00" * 10 + b"\xff\xff" + bytes([10, 0, 0, 1]),
        "request.path": "/reviews/1",          # local word value
        "request.size": 1234,
        "request.time": now,
        "response.duration": datetime.timedelta(milliseconds=20),
        "connection.mtls": True,
        "request.headers": {":path": "/reviews/1", "cookie": "x=1"},
    }
    msg = bag_to_compressed(values)
    # canonical names ride the global dictionary, not message words
    assert "request.path" not in msg.words and ":path" not in msg.words
    assert "cookie" not in msg.words      # header words are global too
    assert "/reviews/1" in msg.words      # payload strings are local
    assert compressed_to_dict(msg) == values


def test_check_allow_and_deny(rig):
    _, _, client, _ = rig
    ok = client.check({"destination.service": "a.b.svc",
                       "source.labels": {"version": "v1"}})
    assert ok.precondition.status.code == OK
    assert ok.precondition.valid_use_count > 0
    bad = client.check({"destination.service": "a.b.svc",
                        "source.labels": {"version": "v7"}})
    assert bad.precondition.status.code == NOT_FOUND
    assert "rejected" in bad.precondition.status.message


def test_check_quota_loop(rig):
    _, _, client, _ = rig
    r = client.check({"destination.service": "q.b.svc",
                      "source.labels": {"version": "v1"}},
                     quotas={"rq": 2})
    assert r.quotas["rq"].granted_amount == 2
    r2 = client.check({"destination.service": "q.b.svc",
                       "source.labels": {"version": "v1"}},
                      quotas={"rq": 5})
    assert r2.quotas["rq"].granted_amount == 1    # best-effort remainder
    # dedup replay: same dedup_id returns the original grant
    r3 = client.check({"destination.service": "q.b.svc",
                       "source.labels": {"version": "v1"}},
                      quotas={"rq": 2}, dedup_id="same-rpc")
    r4 = client.check({"destination.service": "q.b.svc",
                       "source.labels": {"version": "v1"}},
                      quotas={"rq": 2}, dedup_id="same-rpc")
    assert r3.quotas["rq"].granted_amount == \
        r4.quotas["rq"].granted_amount


def test_referenced_attributes_on_wire(rig):
    _, _, client, _ = rig
    r = client.check({"destination.service": "a.b.svc",
                      "source.labels": {"version": "v1"}})
    ref = r.precondition.referenced_attributes
    assert len(ref.attribute_matches) > 0
    conds = {m.condition for m in ref.attribute_matches}
    assert pb.ReferencedAttributes.EXACT in conds


def test_client_check_cache(rig):
    runtime, _, _, cached = rig
    values = {"destination.service": "cache.b.svc",
              "source.labels": {"version": "v1"}}
    r1 = cached.check(values)
    before = runtime.controller.dispatcher  # count via monitor is global;
    r2 = cached.check(values)               # identical → served from cache
    assert r2 is r1
    # different referenced value → miss
    r3 = cached.check({"destination.service": "cache.b.svc",
                       "source.labels": {"version": "v2"}})
    assert r3 is not r1


def test_report_delta_coding(rig):
    runtime, _, client, _ = rig
    store = runtime.controller.store
    store.set(("handler", "istio-system", "prom2"), {
        "adapter": "prometheus",
        "params": {"metrics": [{"name": "bytes.istio-system",
                                "kind": "COUNTER",
                                "label_names": ["dest"]}]}})
    store.set(("instance", "istio-system", "bytes"), {
        "template": "metric",
        "params": {"value": "response.size",
                   "dimensions": {"dest": "destination.service"}}})
    store.set(("rule", "istio-system", "tally2"), {
        "match": "",
        "actions": [{"handler": "prom2", "instances": ["bytes"]}]})
    import time
    deadline = time.time() + 15   # debounce + rebuild (+ plan build)
    while time.time() < deadline:
        if "prom2.istio-system" in runtime.controller.dispatcher.handlers:
            break
        time.sleep(0.05)
    client.report([
        {"destination.service": "d1.ns.svc", "response.size": 100,
         "source.labels": {"version": "v1"}},
        {"destination.service": "d1.ns.svc", "response.size": 50,
         "source.labels": {"version": "v1"}},   # delta: only size changes
        {"destination.service": "d2.ns.svc", "response.size": 7,
         "source.labels": {"version": "v1"}},
    ])
    handler = runtime.controller.dispatcher.handlers["prom2.istio-system"]
    assert handler.registry.get_sample_value(
        "istio_tpu_bytes_istio_system_total",
        {"dest": "d1.ns.svc"}) == 150.0
    assert handler.registry.get_sample_value(
        "istio_tpu_bytes_istio_system_total",
        {"dest": "d2.ns.svc"}) == 7.0


def test_batch_check_matches_unary(rig):
    """BatchCheck (the shim protocol) answers each bag exactly as the
    unary Check would — same status codes, same referenced attributes —
    with arbitrary batch sizes (server pads to its bucket shapes)."""
    _, _, client, _ = rig
    bags = [{"destination.service": "a.b.svc",
             "source.labels": {"version": "v1" if i % 3 else "v9"}}
            for i in range(7)]
    batch = client.batch_check(bags)
    assert len(batch) == 7
    for values, resp in zip(bags, batch):
        unary = client.check(values)
        assert resp.precondition.status.code == \
            unary.precondition.status.code
        assert resp.precondition.referenced_attributes == \
            unary.precondition.referenced_attributes


def test_batch_check_oversize_chunks(rig):
    """A batch larger than the biggest serving bucket is answered in
    bucket-sized chunks (never an arbitrary over-bucket device shape),
    and an empty batch costs no device step."""
    _, _, client, _ = rig
    bags = [{"destination.service": "a.b.svc",
             "source.labels": {"version": "v1" if i % 2 else "v9"}}
            for i in range(70)]   # rig max_batch=64 → 64 + 6 chunks
    resps = client.batch_check(bags)
    assert [r.precondition.status.code for r in resps] == \
        [5 if i % 2 == 0 else 0 for i in range(70)]
    assert client.batch_check([]) == []


def test_batch_check_aio():
    from istio_tpu.api.grpc_server import MixerAioGrpcServer
    runtime = RuntimeServer(_store(), ServerArgs(batch_window_s=0.001,
                                                 max_batch=64))
    server = MixerAioGrpcServer(runtime)
    port = server.start()
    client = MixerClient(f"127.0.0.1:{port}", enable_check_cache=False)
    try:
        resps = client.batch_check(
            [{"source.labels": {"version": "v1" if i % 2 else "v9"}}
             for i in range(6)])
        codes = [r.precondition.status.code for r in resps]
        assert codes == [5, 0, 5, 0, 5, 0]
    finally:
        client.close(); server.stop(); runtime.close()


def test_aio_server_check_parity():
    """MixerAioGrpcServer serves the same Check semantics as the sync
    front — handlers await the batcher instead of blocking a thread."""
    from istio_tpu.api.grpc_server import MixerAioGrpcServer
    runtime = RuntimeServer(_store(), ServerArgs(batch_window_s=0.001,
                                                 max_batch=64))
    server = MixerAioGrpcServer(runtime)
    port = server.start()
    client = MixerClient(f"127.0.0.1:{port}", enable_check_cache=False)
    try:
        ok = client.check({"source.labels": {"version": "v1"}})
        assert ok.precondition.status.code == 0
        assert len(ok.precondition.referenced_attributes
                   .attribute_matches) >= 1
        bad = client.check({"source.labels": {"version": "v9"}})
        assert bad.precondition.status.code == 5      # NOT_FOUND
        # concurrent checks coalesce without holding handler threads
        import threading
        codes = []
        def call(i):
            r = client.check({"source.labels": {"version":
                                                "v1" if i % 2 else "v9"}})
            codes.append(r.precondition.status.code)
        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(16)]
        for t in threads: t.start()
        for t in threads: t.join(timeout=30)
        assert sorted(codes) == [0] * 8 + [5] * 8
    finally:
        client.close(); server.stop(); runtime.close()


def test_traceparent_joins_root_span_and_status_tag(rig):
    """W3C traceparent satellite: a client-sent traceparent header
    becomes the rpc.check root's trace/parent ids (exemplar trace ids
    join the client's trace), and every check span carries a `status`
    tag (ok / google.rpc code) for /debug/traces filtering."""
    import grpc

    from istio_tpu.api.wire import bag_to_compressed
    from istio_tpu.utils import tracing

    runtime, server, _, _ = rig
    mem, restore = tracing.capture("api-test")
    try:
        ch = grpc.insecure_channel(f"127.0.0.1:{server.port}")
        check = ch.unary_unary(
            "/istio.mixer.v1.Mixer/Check",
            request_serializer=pb.CheckRequest.SerializeToString,
            response_deserializer=pb.CheckResponse.FromString)
        tid = "4bf92f3577b34da6a3ce929d0e0e4736"
        sid = "00f067aa0ba902b7"
        req = pb.CheckRequest()
        req.attributes.CopyFrom(bag_to_compressed(
            {"source.labels": {"version": "v1"}}))
        ok = check(req, timeout=30,
                   metadata=(("traceparent", f"00-{tid}-{sid}-01"),))
        assert ok.precondition.status.code == 0
        req2 = pb.CheckRequest()
        req2.attributes.CopyFrom(bag_to_compressed(
            {"source.labels": {"version": "v9"}}))
        bad = check(req2, timeout=30)
        assert bad.precondition.status.code == 5
        ch.close()
    finally:
        restore()
    roots = [s for s in mem.spans if s["name"] == "rpc.check"]
    joined = [s for s in roots if s["traceId"] == tid]
    assert joined, "traceparent did not join the rpc.check root"
    assert joined[0]["parentId"] == sid
    assert joined[0]["tags"].get("status") == "ok"
    # the denied RPC (no traceparent) self-generates ids but tags its
    # google.rpc code
    assert any(s["traceId"] != tid and s["tags"].get("status") == "5"
               for s in roots)
