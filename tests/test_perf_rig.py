"""Perf-rig integrity — the load generator cannot report a silent zero.

VERDICT r2 weak #1: BENCH_r02 recorded served_n_requests=0 with rc=0
because the measurement window was anchored at parent wall-clock before
the spawned worker had even imported grpc. The rig now uses the
reference's attach pattern (mixer/pkg/perf/clientserver.go:30-90 —
clients register with the controller; load begins after attach), and
run_load raises PerfError instead of returning zeros.
"""
import pytest

from istio_tpu.attribute.bag import bag_from_mapping  # noqa: F401
from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs
from istio_tpu.testing import perf


def _tiny_store() -> MemStore:
    s = MemStore()
    s.set(("handler", "istio-system", "deny"), {
        "adapter": "denier", "params": {"status_code": 7}})
    s.set(("instance", "istio-system", "nothing"), {
        "template": "checknothing", "params": {}})
    s.set(("rule", "istio-system", "r0"), {
        "match": 'request.path.startsWith("/admin")',
        "actions": [{"handler": "deny", "instances": ["nothing"]}]})
    return s


@pytest.fixture(scope="module")
def aio_server():
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from istio_tpu.api.grpc_server import MixerAioGrpcServer

    srv = RuntimeServer(_tiny_store(), ServerArgs(batch_window_s=0.001))
    g = MixerAioGrpcServer(srv)
    port = g.start()
    yield port
    g.stop()
    srv.close()


def test_run_load_measures_real_requests(aio_server):
    """Happy path: readiness barrier, then a window with traffic in it."""
    payloads = perf.make_check_payloads(
        [{"request.path": "/ok"}, {"request.path": "/admin/x"}])
    report = perf.run_load(f"127.0.0.1:{aio_server}", payloads,
                           n_record=200, n_procs=1, concurrency=4,
                           warmup_s=0.2)
    assert report.n_requests > 0
    assert report.n_requests + report.n_errors == 200
    assert report.checks_per_sec > 0
    assert report.p99_ms >= report.p50_ms > 0


def test_run_load_raises_when_attach_fails(aio_server):
    """A worker that cannot complete its first RPC aborts the run with
    PerfError — never a zero-valued PerfReport."""
    with pytest.raises(perf.PerfError):
        perf.run_load(f"127.0.0.1:{aio_server}",
                      [b"\xff\xff\xff\xff garbage protobuf"],
                      n_record=20, n_procs=1, concurrency=2,
                      warmup_s=0.1)
