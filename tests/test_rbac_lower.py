"""RBAC device lowering conformance — fused NFA vs the host adapter.

The rbac policy compiles to pseudo-rule rows in the device ruleset
(compiler/rbac_lower.py → models/policy_engine.RbacSpec); the host
adapter (adapters/rbac.py, mirroring mixer/adapter/rbac/rbac.go:181)
is the semantics oracle. Device and host verdicts must agree
field-by-field over a property-rich corpus, including instance
evaluation errors (missing attributes → INTERNAL on both paths).
"""
import pytest

from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.models.policy_engine import (INTERNAL, OK,
                                            PERMISSION_DENIED)
from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs
from istio_tpu.runtime.config import SnapshotBuilder


def _store() -> MemStore:
    s = MemStore()
    s.set(("handler", "istio-system", "authzh"), {
        "adapter": "rbac", "params": {"caching_ttl_s": 42.0}})
    s.set(("instance", "istio-system", "authz"), {
        "template": "authorization",
        "params": {
            "subject": {
                "user": "source.user",
                "groups": 'source.labels["group"] | ""',
                "properties": {
                    "version": 'source.labels["version"] | "none"'}},
            "action": {
                "namespace": "destination.namespace",
                "service": "destination.service",
                "method": "request.method",
                "path": "request.path",
                "properties": {
                    "version": 'request.headers["version"] | ""'}}}})
    s.set(("rule", "istio-system", "authz-rule"), {
        "match": "",    # always matches
        "actions": [{"handler": "authzh", "instances": ["authz"]}]})

    # ServiceRoles (namespace "default")
    s.set(("servicerole", "default", "viewer"), {"rules": [
        {"services": ["*"], "methods": ["GET"], "paths": ["/data/*"]}]})
    s.set(("servicerole", "default", "admin"), {"rules": [
        {"services": ["books.default.svc.cluster.local"],
         "constraints": [{"key": "version", "values": ["v1", "v2"]}]}]})
    s.set(("servicerole", "prod", "prodview"), {"rules": [
        {"services": ["*.prod.svc.cluster.local"], "methods": ["GET"],
         "paths": []}]})

    # ServiceRoleBindings
    s.set(("servicerolebinding", "default", "viewer-b"), {
        "roleRef": {"kind": "ServiceRole", "name": "viewer"},
        "subjects": [{"user": "alice"}, {"group": "eng"}]})
    s.set(("servicerolebinding", "default", "admin-b"), {
        "roleRef": {"kind": "ServiceRole", "name": "admin"},
        "subjects": [{"user": "bob",
                      "properties": {"version": "v1"}}]})
    s.set(("servicerolebinding", "prod", "prod-b"), {
        "roleRef": {"kind": "ServiceRole", "name": "prodview"},
        "subjects": [{"user": "*"}]})
    return s


def _bags():
    cases = [
        # 1: alice GET /data/1 in default → viewer allow
        {"source.user": "alice", "destination.namespace": "default",
         "destination.service": "books.default.svc.cluster.local",
         "request.method": "GET", "request.path": "/data/1"},
        # 2: alice POST → method miss → deny
        {"source.user": "alice", "destination.namespace": "default",
         "destination.service": "books.default.svc.cluster.local",
         "request.method": "POST", "request.path": "/data/1"},
        # 3: group eng via subject.groups → allow
        {"source.user": "zed", "source.labels": {"group": "eng"},
         "destination.namespace": "default",
         "destination.service": "x.default.svc.cluster.local",
         "request.method": "GET", "request.path": "/data/zz"},
        # 4: bob admin with property v1 + constraint header v2 → allow
        {"source.user": "bob", "source.labels": {"version": "v1"},
         "destination.namespace": "default",
         "destination.service": "books.default.svc.cluster.local",
         "request.method": "DELETE", "request.path": "/any",
         "request.headers": {"version": "v2"}},
        # 5: bob wrong subject property → deny
        {"source.user": "bob", "source.labels": {"version": "v9"},
         "destination.namespace": "default",
         "destination.service": "books.default.svc.cluster.local",
         "request.method": "DELETE", "request.path": "/any",
         "request.headers": {"version": "v2"}},
        # 6: bob right property, constraint value miss → deny
        {"source.user": "bob", "source.labels": {"version": "v1"},
         "destination.namespace": "default",
         "destination.service": "books.default.svc.cluster.local",
         "request.method": "DELETE", "request.path": "/any",
         "request.headers": {"version": "v9"}},
        # 7: prod namespace wildcard-user suffix-service → allow
        {"source.user": "nobody", "destination.namespace": "prod",
         "destination.service": "api.prod.svc.cluster.local",
         "request.method": "GET", "request.path": "/x"},
        # 8: prod suffix miss → deny
        {"source.user": "nobody", "destination.namespace": "prod",
         "destination.service": "api.staging.svc.cluster.local",
         "request.method": "GET", "request.path": "/x"},
        # 9: unknown namespace → no bindings → deny
        {"source.user": "alice", "destination.namespace": "nowhere",
         "destination.service": "x.y.svc.cluster.local",
         "request.method": "GET", "request.path": "/data/1"},
        # 10: missing source.user (no fallback) → instance error →
        #     INTERNAL on both paths
        {"destination.namespace": "default",
         "destination.service": "books.default.svc.cluster.local",
         "request.method": "GET", "request.path": "/data/1"},
        # 11: missing destination.namespace → instance error
        {"source.user": "alice",
         "destination.service": "books.default.svc.cluster.local",
         "request.method": "GET", "request.path": "/data/1"},
        # 12: path prefix boundary: /data exactly (prefix "/data/"
        #     requires the slash) → deny
        {"source.user": "alice", "destination.namespace": "default",
         "destination.service": "books.default.svc.cluster.local",
         "request.method": "GET", "request.path": "/data"},
    ]
    return [bag_from_mapping(c) for c in cases]


@pytest.fixture(scope="module")
def servers():
    fused = RuntimeServer(_store(), ServerArgs(batch_window_s=0.001,
                                               fused=True))
    generic = RuntimeServer(_store(), ServerArgs(batch_window_s=0.001,
                                                 fused=False))
    yield fused, generic
    fused.close()
    generic.close()


def test_policy_fully_lowered(servers):
    fused, _ = servers
    plan = fused.controller.dispatcher.fused
    snap = fused.controller.dispatcher.snapshot
    assert plan is not None
    assert plan.rbac_rules, "rbac action did not fuse"
    assert not plan.host_actions, f"host overlay: {plan.host_actions}"
    groups = list(snap.rbac_groups.values())
    assert len(groups) == 1
    g = groups[0]
    assert g.lowered, g.reason
    # viewer(1 role rule × 2 subjects) + admin(1×1) + prod(1×1)
    assert len(g.allow_rows) == 4
    assert g.guard_row >= 0
    # pseudo-rules live past the config rules
    assert snap.n_config_rules == 1
    assert snap.ruleset.n_rules == 1 + 4 + 1


def test_fused_matches_host_adapter(servers):
    fused, generic = servers
    bags = _bags()
    rf = fused.check_many(bags)
    rg = generic.check_many(bags)
    for i, (a, b) in enumerate(zip(rf, rg)):
        assert a.status_code == b.status_code, \
            f"case {i + 1}: fused={a.status_code} host={b.status_code}" \
            f" ({b.status_message})"
        assert a.valid_duration_s == pytest.approx(b.valid_duration_s), \
            f"case {i + 1}"
        assert a.valid_use_count == b.valid_use_count, f"case {i + 1}"
        assert a.referenced == b.referenced, f"case {i + 1}"


def test_expected_statuses(servers):
    fused, _ = servers
    r = fused.check_many(_bags())
    expect = [OK, PERMISSION_DENIED, OK, OK, PERMISSION_DENIED,
              PERMISSION_DENIED, OK, PERMISSION_DENIED,
              PERMISSION_DENIED, INTERNAL, INTERNAL, PERMISSION_DENIED]
    got = [x.status_code for x in r]
    assert got == expect
    # denial message parity with rbac.go:241
    assert r[1].status_message == "RBAC: permission denied"
    # handler caching_ttl_s rides the verdict
    assert r[0].valid_duration_s == pytest.approx(5.0)  # min(default 5, 42)


def test_unfusable_policy_stays_on_host():
    """A non-STRING property expression is outside the lowerable subset
    — the group must fall back to the host adapter, not diverge."""
    s = _store()
    s.set(("instance", "istio-system", "authz"), {
        "template": "authorization",
        "params": {
            "subject": {"user": "source.user",
                        "properties": {"size": "request.size"}},
            "action": {"namespace": "destination.namespace",
                       "service": "destination.service",
                       "method": "request.method",
                       "path": "request.path"}}})
    s.set(("servicerolebinding", "default", "viewer-b"), {
        "roleRef": {"kind": "ServiceRole", "name": "viewer"},
        "subjects": [{"user": "alice",
                      "properties": {"size": "100"}}]})
    srv = RuntimeServer(s, ServerArgs(batch_window_s=0.001, fused=True))
    try:
        snap = srv.controller.dispatcher.snapshot
        plan = srv.controller.dispatcher.fused
        g = list(snap.rbac_groups.values())[0]
        assert not g.lowered
        assert "STRING" in g.reason
        assert plan.host_actions, "unfusable rbac must host-overlay"
        # and the host path still serves it: alice with size=100 allowed
        resp = srv.check_many([bag_from_mapping(
            {"source.user": "alice", "request.size": 100,
             "destination.namespace": "default",
             "destination.service": "b.default.svc.cluster.local",
             "request.method": "GET", "request.path": "/data/1"})])[0]
        assert resp.status_code == OK
    finally:
        srv.close()


def test_non_string_config_values_keep_host_parity():
    """Raw-compare parity (review r3): a non-string binding user
    (unquoted YAML number) never binds on the host — the lowering must
    not stringify it into a match; non-string role patterns would
    adapter-panic on the host, so they refuse to lower entirely."""
    s = _store()
    s.set(("servicerolebinding", "default", "intuser-b"), {
        "roleRef": {"kind": "ServiceRole", "name": "viewer"},
        "subjects": [{"user": 123}]})
    fused = RuntimeServer(s, ServerArgs(batch_window_s=0.001,
                                        fused=True))
    generic = RuntimeServer(s, ServerArgs(batch_window_s=0.001,
                                          fused=False))
    try:
        bag = bag_from_mapping(
            {"source.user": "123", "destination.namespace": "default",
             "destination.service": "b.default.svc.cluster.local",
             "request.method": "GET", "request.path": "/data/1"})
        a = fused.check_many([bag])[0]
        b = generic.check_many([bag])[0]
        assert a.status_code == b.status_code == PERMISSION_DENIED
    finally:
        fused.close()
        generic.close()
    # non-string role pattern → whole group stays on the host overlay
    s2 = _store()
    s2.set(("servicerole", "default", "viewer"), {"rules": [
        {"services": [42], "methods": ["GET"], "paths": []}]})
    srv = RuntimeServer(s2, ServerArgs(batch_window_s=0.001,
                                       fused=True))
    try:
        g = list(srv.controller.dispatcher.snapshot
                 .rbac_groups.values())[0]
        assert not g.lowered and "pattern" in g.reason
    finally:
        srv.close()


def test_non_fused_builder_skips_pseudo_rules():
    """fused=False servers never pay for pseudo-rule compilation."""
    srv = RuntimeServer(_store(), ServerArgs(batch_window_s=0.001,
                                             fused=False))
    try:
        snap = srv.controller.dispatcher.snapshot
        assert snap.rbac_groups == {}
        assert snap.ruleset.n_rules == len(snap.rules)
    finally:
        srv.close()


def test_lowering_shapes_directly():
    """Unit: pattern forms + constant folding in the synthesized ASTs."""
    from istio_tpu.compiler.rbac_lower import lower_rbac
    from istio_tpu.expr.checker import AttributeDescriptorFinder
    from istio_tpu.attribute.types import ValueType as V
    from istio_tpu.expr.parser import parse

    finder = AttributeDescriptorFinder({
        "source.user": V.STRING, "destination.service": V.STRING,
        "destination.namespace": V.STRING, "request.method": V.STRING,
        "request.path": V.STRING})
    inst = {"subject": {"user": parse("source.user")},
            "action": {"namespace": parse("destination.namespace"),
                       "service": parse("destination.service"),
                       "method": parse("request.method"),
                       "path": parse("request.path")}}
    roles = [{"namespace": "ns1", "name": "r",
              "rules": [{"services": ["*"], "methods": ["GET", "POST"],
                         "paths": ["/api/*", "*.html"]}]}]
    bindings = [{"namespace": "ns1", "name": "b",
                 "roleRef": {"name": "r"},
                 "subjects": [{"user": "u1"}, {"user": "*"}]}]
    low = lower_rbac(roles, bindings, inst, finder)
    assert low.n_triples == 2
    assert len(low.allow_asts) == 2
    assert low.guard_ast is not None
    text = str(low.allow_asts[0])
    # services ["*"] folds away; methods/paths stay as LORs
    assert "LOR" in text and "startsWith" in text and "endsWith" in text

    # an omitted instance field folds the clause against ""
    inst_no_user = {"subject": {},
                    "action": {"namespace": parse(
                        "destination.namespace"),
                        "service": parse("destination.service"),
                        "method": parse("request.method"),
                        "path": parse("request.path")}}
    low2 = lower_rbac(roles, bindings, inst_no_user, finder)
    # subject user "u1" vs constant "" → triple dropped; "*" stays
    assert len(low2.allow_asts) == 1
