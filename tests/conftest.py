"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding
paths (Mesh/pjit/shard_map) are exercised hermetically. Real-TPU runs
happen only in bench.py. See istio_tpu/platform.py for why plain
JAX_PLATFORMS=cpu is not enough in this container.
"""
from istio_tpu.platform import force_cpu_platform

force_cpu_platform(8)
