"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding
paths (Mesh/pjit/shard_map) are exercised hermetically. Real-TPU runs
happen only in bench.py.

NOTE: this environment injects an `axon` TPU-tunnel PJRT plugin via
sitecustomize *before* pytest starts, and that plugin pins
jax_platforms="axon,cpu"; plain JAX_PLATFORMS=cpu in the env is not
enough. Updating the config key here — before any backend is
initialized — reliably selects the hermetic CPU platform.
"""
import os

xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
