"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding
paths (Mesh/pjit/shard_map) are exercised hermetically. Real-TPU runs
happen only in bench.py. See istio_tpu/platform.py for why plain
JAX_PLATFORMS=cpu is not enough in this container.

Also points JAX's persistent compilation cache at the repo-local
`.jax_cache/` (the same dir bench.py uses; entries are keyed by HLO +
platform, so sharing is safe). The suite builds near-identical engines
in dozens of modules — each fresh Engine re-traces the same programs,
and without the disk cache every one is a full XLA compile. With it,
duplicate compiles are disk hits both within one run and across runs.
Tests that assert on cache behavior (test_delta_compile, delta_smoke)
save and restore this config around their own private cache dirs.
"""
import os

from istio_tpu.platform import force_cpu_platform

force_cpu_platform(8)

from istio_tpu.compiler.cache import configure_persistent_cache

configure_persistent_cache(
    os.path.join(os.path.dirname(__file__), os.pardir, ".jax_cache"))
