"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding
paths (Mesh/pjit/shard_map) are exercised hermetically, per the driver
contract. Real-TPU runs happen only in bench.py.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()
