"""Multi-chip SERVING conformance (VERDICT r2 item 4).

A RuntimeServer with ServerArgs(mesh_shape=(dp, mp)) jits the snapshot
engine under the dp×mp sharding layout (parallel/mesh.py) — requests
shard over dp, rule rows over mp — and must produce verdicts identical
to the single-device server, all the way from gRPC wire bytes in. Runs
on the 8-virtual-CPU platform (tests/conftest.py).
"""
import pytest

from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs
from tests.test_fused_serving import _bags, _store


@pytest.fixture(scope="module")
def pair():
    plain = RuntimeServer(_store(), ServerArgs(batch_window_s=0.001))
    mesh = RuntimeServer(_store(), ServerArgs(batch_window_s=0.001,
                                              mesh_shape=(4, 2),
                                              buckets=(16, 64, 256)))
    yield plain, mesh
    plain.close()
    mesh.close()


def test_mesh_server_matches_single_device(pair):
    plain, mesh = pair
    bags = _bags()
    # check_many bypasses the batcher: pad to a dp-divisible count
    while len(bags) % 4:
        bags.append(bag_from_mapping({"request.path": "/pad"}))
    rp = plain.check_many(bags)
    rm = mesh.check_many(bags)
    for i, (a, b) in enumerate(zip(rp, rm)):
        assert a.status_code == b.status_code, f"case {i}"
        assert a.valid_duration_s == pytest.approx(b.valid_duration_s)
        assert a.valid_use_count == b.valid_use_count, i
        assert a.referenced == b.referenced, i


def test_mesh_serving_at_scale_10k_rules():
    """mp sharding where it actually matters (VERDICT r3 weak #8): a
    10k-rule snapshot's rule rows split across mp=2 shards (5k+ rows
    each — far beyond a trivial slice), and the sharded engine's
    verdicts must equal the single-device engine's on a mixed batch."""
    from istio_tpu.testing import workloads

    store = workloads.make_store(10_000)
    plain = RuntimeServer(store, ServerArgs(
        batch_window_s=0.001,
        default_manifest=workloads.MESH_MANIFEST))
    mesh = RuntimeServer(workloads.make_store(10_000), ServerArgs(
        batch_window_s=0.001, mesh_shape=(4, 2), buckets=(64,),
        default_manifest=workloads.MESH_MANIFEST))
    try:
        n_rules = plain.controller.dispatcher.snapshot.ruleset.n_rules
        assert n_rules >= 10_000
        bags = workloads.make_bags(64, seed=21)
        rp = plain.check_many(bags)
        rm = mesh.check_many(bags)
        statuses = {r.status_code for r in rp}
        assert len(statuses) > 1          # mixed verdicts, not all-OK
        for i, (a, b) in enumerate(zip(rp, rm)):
            assert a.status_code == b.status_code, f"case {i}"
            assert a.valid_use_count == b.valid_use_count, i
            assert a.referenced == b.referenced, i
    finally:
        plain.close()
        mesh.close()


def test_batch_check_over_mesh_server(pair):
    """The BatchCheck shim RPC through the SHARDED server: per-item
    verdicts equal the single-device server's (the shim protocol and
    the dp×mp serving layout compose)."""
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from istio_tpu.api import MixerClient, MixerGrpcServer

    plain, mesh = pair
    g = MixerGrpcServer(mesh)
    port = g.start()
    client = MixerClient(f"127.0.0.1:{port}", enable_check_cache=False)
    try:
        cases = [{"request.path": f"/admin/{i}"} if i % 2 else
                 {"request.path": f"/ok/{i}"} for i in range(10)]
        got = [r.precondition.status.code
               for r in client.batch_check(cases)]
        want = [r.status_code for r in plain.check_many(
            [bag_from_mapping(c) for c in cases])]
        assert got == want
    finally:
        client.close()
        g.stop()


def test_mesh_server_over_grpc(pair):
    """gRPC wire in → batcher (bucket padding) → SHARDED step →
    response; verdicts equal the single-device server's."""
    grpc = pytest.importorskip("grpc")  # noqa: F841
    from istio_tpu.api import MixerClient, MixerGrpcServer

    plain, mesh = pair
    g = MixerGrpcServer(mesh)
    port = g.start()
    client = MixerClient(f"127.0.0.1:{port}",
                         enable_check_cache=False)
    try:
        cases = [
            {"request.path": "/admin/keys"},
            {"request.path": "/ratings/1"},
            {"destination.service":
                 "ratings.default.svc.cluster.local",
             "source.namespace": "evil"},
            {"connection.mtls": True,
             "request.headers": {"user-agent": "badbot"}},
        ]
        want = [r.status_code for r in plain.check_many(
            [bag_from_mapping(c) for c in cases])]
        got = [client.check(c).precondition.status.code for c in cases]
        assert got == want
    finally:
        client.close()
        g.stop()


def test_mesh_requires_divisible_buckets():
    with pytest.raises(ValueError, match="divisible"):
        RuntimeServer(_store(), ServerArgs(mesh_shape=(4, 2),
                                           buckets=(6, 64)))
