"""Rule-level telemetry (runtime/rulestats.py): on-device per-rule
accumulators drained to exact counts, host-fallback patching, padding
hygiene, decision exemplars, and config-swap continuity.

The exactness bar (ISSUE 4): drained per-rule hit/deny/error counts
must EQUAL an independent oracle recount of the served traffic —
telemetry is a measurement, not an estimate. The recount helper lives
in scripts/rulestats_smoke.py (shared with the CI gate) and walks the
compiler's SnapshotOracle + the snapshot's fused action metadata.
"""
import importlib.util
import os
import sys

import numpy as np
import pytest

from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs
from istio_tpu.testing import workloads
from istio_tpu.utils import tracing


def _smoke():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "rulestats_smoke.py")
    spec = importlib.util.spec_from_file_location(
        "rulestats_smoke_helpers", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _server(store, **kw):
    args = dict(batch_window_s=0.0005, max_batch=32, buckets=(8, 32),
                rulestats_drain_s=0.0,   # manual drains: deterministic
                default_manifest=workloads.MESH_MANIFEST)
    args.update(kw)
    return RuntimeServer(store, ServerArgs(**args))


def _names(snapshot):
    return [f"{r.namespace}/{r.name}" if r.namespace else r.name
            for r in snapshot.rules]


@pytest.mark.parametrize("seed", [3, 11])
def test_drained_counts_match_oracle_recount(seed):
    """Property test over testing/corpus-style seeded workloads: serve
    a mix of random + crafted (deny-triggering) traffic, drain, and
    compare every rule's hit/deny/error counts to the oracle recount
    EXACTLY — including rules that never fired."""
    mod = _smoke()
    srv = _server(workloads.make_store(20, seed=seed))
    try:
        dicts = mod.make_traffic(20, 24, seed)
        bags = [bag_from_mapping(d) for d in dicts]
        srv.check_many(bags)
        srv.rulestats.drain()
        got = srv.rulestats.counts()
        snap = srv.controller.dispatcher.snapshot
        plan = srv.controller.dispatcher.fused
        hits, denies, errors = mod.oracle_recount(snap, plan, bags)
        assert hits, "traffic must exercise rules"
        assert denies, "traffic must trigger denies"
        for ridx, name in enumerate(_names(snap)):
            g = got.get(name, {"hits": 0, "denies": 0, "errors": 0})
            assert (g["hits"], g["denies"], g["errors"]) == \
                (hits.get(ridx, 0), denies.get(ridx, 0),
                 errors.get(ridx, 0)), f"rule {name}"
    finally:
        srv.close()


def test_padding_rows_never_counted():
    """Bucket padding (PadBags) must be invisible to the counters: the
    same requests served padded-to-bucket and unpadded drain to
    identical per-rule counts."""
    from istio_tpu.runtime.batcher import pad_to_bucket

    mod = _smoke()
    dicts = mod.make_traffic(12, 6, 5)
    srv = _server(workloads.make_store(12, seed=5))
    try:
        bags = [bag_from_mapping(d) for d in dicts]
        # padded entry: 18 real rows pad to the 32 bucket
        srv.check_batch_preprocessed(pad_to_bucket(bags, (8, 32)))
        srv.rulestats.drain()
        padded = srv.rulestats.counts()
        srv.rulestats.reset()
        srv.check_many(bags)
        srv.rulestats.drain()
        plain = srv.rulestats.counts()
        nz = {k: v for k, v in padded.items()
              if v["hits"] or v["denies"] or v["errors"]}
        assert nz, "traffic must hit rules"
        for name, c in padded.items():
            p = plain.get(name, {"hits": 0, "denies": 0, "errors": 0})
            assert (c["hits"], c["denies"], c["errors"]) == \
                (p["hits"], p["denies"], p["errors"]), name
    finally:
        srv.close()


def test_host_fallback_rule_hits_and_errors_counted():
    """Rules whose predicate falls back to the host oracle are
    invisible to the device accumulators; their hits/errors must
    arrive via the dispatcher's overlay patch — and still match the
    oracle recount exactly."""
    mod = _smoke()
    s = MemStore()
    s.set(("handler", "istio-system", "deny"), {
        "adapter": "denier", "params": {"status_code": 7}})
    s.set(("instance", "istio-system", "nothing"), {
        "template": "checknothing", "params": {}})
    # dynamic map key → no device lowering → host-fallback predicate
    s.set(("rule", "istio-system", "dynkey"), {
        "match": 'request.headers[request.method] == "yes"',
        "actions": [{"handler": "deny", "instances": ["nothing"]}]})
    s.set(("rule", "istio-system", "plain"), {
        "match": 'request.path.startsWith("/admin")',
        "actions": [{"handler": "deny", "instances": ["nothing"]}]})
    srv = _server(s)
    try:
        plan = srv.controller.dispatcher.fused
        rs = srv.controller.dispatcher.snapshot.ruleset
        assert rs.host_fallback, "dynkey must be host-fallback"
        bags = [
            bag_from_mapping({"request.method": "GET",
                              "request.headers": {"GET": "yes"},
                              "request.path": "/x"}),   # dynkey hit
            bag_from_mapping({"request.method": "GET",
                              "request.headers": {"GET": "no"},
                              "request.path": "/admin/z"}),  # plain
            bag_from_mapping({"request.path": "/y"}),   # dynkey errs
        ]
        srv.check_many(bags)
        srv.rulestats.drain()
        got = srv.rulestats.counts()
        snap = srv.controller.dispatcher.snapshot
        hits, denies, errors = mod.oracle_recount(snap, plan, bags)
        for ridx, name in enumerate(_names(snap)):
            g = got.get(name, {"hits": 0, "denies": 0, "errors": 0})
            assert (g["hits"], g["denies"], g["errors"]) == \
                (hits.get(ridx, 0), denies.get(ridx, 0),
                 errors.get(ridx, 0)), f"rule {name}"
        fb_name = _names(snap)[sorted(rs.host_fallback)[0]]
        assert got[fb_name]["hits"] == 1
        assert got[fb_name]["errors"] >= 1
    finally:
        srv.close()


def test_exemplars_record_denied_requests_with_trace_ids():
    """Denied rows reservoir-sample into per-rule exemplars carrying
    the decoded attribute bag and the active span's trace id — the
    one-click join from /debug/rulestats to /debug/traces."""
    mem = tracing.MemoryReporter()
    tracing._global = tracing.Tracer(reporter=mem)
    try:
        s = MemStore()
        s.set(("handler", "istio-system", "deny"), {
            "adapter": "denier", "params": {"status_code": 7}})
        s.set(("instance", "istio-system", "nothing"), {
            "template": "checknothing", "params": {}})
        s.set(("rule", "istio-system", "blockadmin"), {
            "match": 'request.path.startsWith("/admin")',
            "actions": [{"handler": "deny", "instances": ["nothing"]}]})
        srv = _server(s)
        try:
            for i in range(10):
                srv.check(bag_from_mapping(
                    {"request.path": f"/admin/{i}"}))
            srv.rulestats.drain()
            snap = srv.rulestats.snapshot(top_k=5)
            top = {t["rule"]: t for t in snap["top"]}
            entry = top["istio-system/blockadmin"]
            assert entry["denies"] == 10
            exs = entry["exemplars"]
            assert exs, "denied traffic must leave exemplars"
            assert len(exs) <= 4, "reservoir must cap at K"
            for ex in exs:
                assert ex["status"] == 7
                assert any("/admin/" in v
                           for v in ex["attributes"].values())
                assert ex["trace_id"], "exemplar must link a trace"
            # the trace id is a real recorded span's trace
            trace_ids = {s_["traceId"] for s_ in mem.spans}
            assert exs[0]["trace_id"] in trace_ids
        finally:
            srv.close()
    finally:
        tracing._global = tracing.NOOP_TRACER


def test_counts_survive_config_swap():
    """attach() drains the outgoing plan before rebinding, so a config
    swap never drops in-flight counts; name-keyed cumulative totals
    carry across revisions."""
    s = MemStore()
    s.set(("handler", "istio-system", "deny"), {
        "adapter": "denier", "params": {"status_code": 7}})
    s.set(("instance", "istio-system", "nothing"), {
        "template": "checknothing", "params": {}})
    s.set(("rule", "istio-system", "r0"), {
        "match": 'request.path.startsWith("/a")',
        "actions": [{"handler": "deny", "instances": ["nothing"]}]})
    srv = _server(s)
    try:
        rev0 = srv.rulestats.revision
        srv.check(bag_from_mapping({"request.path": "/a/1"}))
        # swap WITHOUT draining first: the publish hook must flush the
        # old plan's device accumulators before rebinding
        s.set(("rule", "istio-system", "r1"), {
            "match": 'request.path.startsWith("/b")',
            "actions": [{"handler": "deny", "instances": ["nothing"]}]})
        srv.controller.rebuild()
        assert srv.rulestats.revision != rev0
        got = srv.rulestats.counts()
        assert got["istio-system/r0"]["hits"] == 1
        assert got["istio-system/r0"]["denies"] == 1
        # traffic on the NEW snapshot keeps accumulating by name
        srv.check(bag_from_mapping({"request.path": "/a/2"}))
        srv.rulestats.drain()
        assert srv.rulestats.counts()["istio-system/r0"]["hits"] == 2
    finally:
        srv.close()


def test_generation_tags_advance_per_drain():
    srv = _server(workloads.make_store(6, seed=1))
    try:
        plan = srv.controller.dispatcher.fused
        g0 = plan.telemetry.generation
        srv.rulestats.drain()
        srv.rulestats.drain()
        assert plan.telemetry.generation == g0 + 2
        assert srv.rulestats.drains >= 2
    finally:
        srv.close()


def test_telemetry_disabled_serves_without_accumulators():
    srv = _server(workloads.make_store(6, seed=1),
                  rule_telemetry=False)
    try:
        assert srv.controller.dispatcher.fused.telemetry is None
        r = srv.check(bag_from_mapping({"request.path": "/x"}))
        assert r is not None
        assert srv.rulestats.drain() is None
        snap = srv.rulestats.snapshot()
        assert snap["top"] == []
    finally:
        srv.close()


def test_never_hit_shadow_crosscheck_ambiguity_guard():
    """snapshot(shadowed=...) matches the analyzer's BARE rule names
    against qualified never-hit names — but only when the bare name is
    unique in the snapshot, so a same-named rule in another namespace
    is never marked provably dead."""
    from istio_tpu.runtime import rulestats
    from istio_tpu.utils.metrics import Registry

    agg = rulestats.RuleStatsAggregator(
        metrics=rulestats.register_families(Registry()))

    class _Rule:
        def __init__(self, name, ns):
            self.name, self.namespace = name, ns

    class _Snap:
        rules = [_Rule("allow", "ns-a"), _Rule("allow", "ns-b"),
                 _Rule("dead", "ns-a")]
        revision = 1

        class ruleset:
            ns_ids = {"": 0}

    class _Dispatcher:
        snapshot = _Snap()
        fused = None

    agg.attach(_Dispatcher())
    view = agg.snapshot(shadowed={"allow", "dead"})
    flags = {e["rule"]: e["analyzer_shadowed"]
             for e in view["never_hit"]}
    assert flags["ns-a/dead"] is True          # unique bare name
    assert flags["ns-a/allow"] is False        # ambiguous: two rules
    assert flags["ns-b/allow"] is False
