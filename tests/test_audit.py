"""Unit tests for the mesh audit plane (istio_tpu/runtime/audit.py):
AuditCheck verdict semantics, the time-AND-count stuck detector, the
test-only counter seams, the injection ledger's coalescing /
matching / expiry, the grant watermark, the device-pool audit view,
the discovery scope-pair derivation and the fused /debug/slo
scorecard. The heavier end-to-end path (real fronts, chaos, HTTP)
lives in scripts/audit_smoke.py."""
from __future__ import annotations

import time

import pytest

from istio_tpu.runtime import forensics, monitor
from istio_tpu.runtime.audit import (AuditCheck, AuditPlane,
                                     InjectionLedger, SEAMS)
from istio_tpu.testing import workloads


@pytest.fixture(autouse=True)
def _clean_seams():
    SEAMS.reset()
    yield
    SEAMS.reset()


@pytest.fixture(scope="module")
def srv():
    from istio_tpu.runtime import RuntimeServer, ServerArgs

    s = RuntimeServer(workloads.make_store(8), ServerArgs(
        batch_window_s=0.0005, max_batch=8, buckets=(4, 8),
        check_grants=True,            # grant_coherence enabled leg
        default_manifest=workloads.MESH_MANIFEST))
    yield s
    s.close()


def test_audit_check_as_dict_shape():
    chk = AuditCheck("report_conservation", evidence={"x": 1},
                     note="n")
    d = chk.as_dict()
    assert d["name"] == "report_conservation"
    assert d["status"] == "ok" and d["evidence"] == {"x": 1}
    assert set(d) == {"name", "status", "evidence", "generation",
                      "wall", "note"}


def test_negative_residue_violates_immediately():
    """A negative ledger (more exported than accepted) is an
    impossible state — no stuck window applies."""
    aud = AuditPlane(None)
    SEAMS.report_accepted_skew = -(
        monitor.report_conservation()["accepted"] + 3)
    chk = aud._report_conservation()
    assert chk.status == "violated"
    assert chk.evidence["in_flight"] < 0


def test_stuck_promotion_needs_count_and_time():
    """A frozen residue must be BOTH stuck_after evaluations old and
    stuck_floor_s seconds old before it is promoted to violated —
    back-to-back manual evaluations or one slow in-deadline request
    must read degraded, not violated."""
    aud = AuditPlane(None, stuck_after=3, stuck_floor_s=0.4)
    SEAMS.report_accepted_skew = 5
    # count satisfied quickly, time floor not yet
    for _ in range(4):
        chk = aud._report_conservation()
    assert chk.status == "degraded", chk.as_dict()
    assert chk.evidence["stuck_evaluations"] >= 3
    time.sleep(0.45)
    chk = aud._report_conservation()
    assert chk.status == "violated"
    assert chk.evidence["frozen_s"] >= 0.4
    # clearing the skew clears the stuck state
    SEAMS.reset()
    chk = aud._report_conservation()
    assert chk.status == "ok"


def test_check_accounting_typed_residue_is_ok():
    """A steady decode/response residue covered by typed rejections
    is the rejected-RPC shape, not a leak."""
    aud = AuditPlane(None, stuck_after=2, stuck_floor_s=0.05)
    rc = monitor.resilience_counters()
    typed = (rc["shed_total"] + rc["expired_total"]
             + rc["cancelled_shed_total"])
    SEAMS.check_decoded_skew = typed + 1 \
        - monitor.serving_counters()["in_flight"]
    aud._check_accounting()
    time.sleep(0.1)
    chk = aud._check_accounting()
    assert chk.status == "violated"     # 1 beyond the typed cover
    SEAMS.check_decoded_skew -= 1
    aud._check_accounting()
    time.sleep(0.1)
    chk = aud._check_accounting()
    assert chk.status == "ok"
    if typed:   # residue == typed → the covered-rejection shape
        assert "typed rejections" in chk.note


def test_injection_ledger_coalesces_and_matches_by_event():
    led = InjectionLedger(coalesce_s=5.0)
    led.note("device")
    led.note("device")                  # coalesces into one record
    forensics.record_event("breaker", name="device")
    out = led.evaluate(window_s=30.0)
    assert out["matched"] == 2 and out["unexplained"] == 0
    assert out["rate"] == 1.0
    recs = [r for r in out["records"] if r["kind"] == "device"]
    assert len(recs) == 1 and recs[0]["n"] == 2
    assert recs[0]["matched_by"] == "event:breaker device"


def test_injection_ledger_expires_unmatched():
    led = InjectionLedger()
    led.note("oracle")                  # nothing will explain it
    time.sleep(0.05)
    out = led.evaluate(window_s=0.01)
    assert out["unexplained"] == 1 and out["matched"] == 0
    assert out["rate"] == 0.0
    # a fresh ledger is vacuously explainable again
    led.reset()
    assert led.evaluate(window_s=1.0)["rate"] == 1.0


def test_grant_watermark_and_coherence(srv):
    aud = srv.audit
    wm = srv.grants.watermark()
    assert set(wm) == {"generation", "revocations", "grants_issued",
                      "issued_at_generation"}
    assert wm["issued_at_generation"] <= wm["generation"]
    chk = aud._grant_coherence()
    assert chk.status == "ok" and chk.evidence["enabled"]
    # the seam pushes issued_at beyond the watermark: a grant
    # apparently minted from a generation that never existed
    SEAMS.grant_issue_skew = wm["generation"] + 10
    chk = aud._grant_coherence()
    assert chk.status == "violated"
    assert "watermark" in chk.note


def test_plane_agreement_seam_detects_divergence(srv):
    aud = srv.audit
    chk = aud._plane_agreement()
    assert chk.status == "ok", chk.as_dict()
    SEAMS.plane_pairs_extra = [
        ("seam-pair", 'source.service == "a"',
         'source.service == "b"')]
    chk = aud._plane_agreement()
    assert chk.status == "violated"
    assert any(f["code"] == "plane-divergence"
               for f in chk.evidence["findings"])
    # clearing the seam re-proves agreement (fresh digest, no memo)
    SEAMS.reset()
    chk = aud._plane_agreement()
    assert chk.status == "ok"


def test_routing_disabled_on_monolithic(srv):
    chk = srv.audit._routing_conservation()
    assert chk.status == "ok"
    assert chk.evidence == {"enabled": False}


def test_device_pool_audit_view(srv):
    pools = getattr(srv.controller, "device_quotas", {})
    if not pools:
        pytest.skip("workload carries no device quota pool")
    view = next(iter(pools.values())).audit_view()
    assert view["negative_cells"] == 0
    assert view["over_cap_cells"] == 0
    assert view["nonzero_beyond_keymap"] == 0
    assert view["n_used"] <= view["n_buckets"]


def test_discovery_scope_pairs_agree():
    from istio_tpu.pilot.discovery import DiscoveryService

    registry, store, nodes, meta = workloads.make_discovery_world(
        n_services=12, n_namespaces=3, replicas=2, source_ns=2,
        seed=3)
    ds = DiscoveryService(registry, store)
    try:
        pairs = ds._snapshot.scope_audit_pairs()
        assert pairs
        for _name, served, compiled in pairs:
            assert served == compiled
    finally:
        ds.stop()


def test_slo_scorecard_verdict_fusion():
    from istio_tpu.runtime import slo

    assert slo._worst(["ok", "no_data"]) == "ok"
    assert slo._worst(["ok", "miss"]) == "miss"
    assert slo._worst(["no_data"]) == "no_data"
    card = slo.scorecard(monitor, forensics)
    assert set(card["planes"]) == {"check_wire", "report_export",
                                   "discovery_push", "quota_flush",
                                   "audit"}
    assert card["planes"]["audit"]["verdict"] == "no_data"
    # an unhealthy audit snapshot forces a miss
    card = slo.scorecard(monitor, forensics, audit={
        "healthy": False, "explainability": {"rate": 1.0},
        "checks": [{"name": "report_conservation",
                    "status": "violated"}]})
    assert card["planes"]["audit"]["verdict"] == "miss"
    assert card["overall"] == "miss"
    assert card["planes"]["audit"]["violated"] == \
        ["report_conservation"]


def test_audit_plane_snapshot_and_evaluate(srv):
    snap = srv.audit.evaluate()
    assert snap["enabled"] and snap["evaluations"] >= 1
    assert [c["name"] for c in snap["checks"]] == list(
        monitor.AUDIT_INVARIANTS)
    assert snap["healthy"] is True
    assert 0.0 <= snap["explainability"]["rate"] <= 1.0
