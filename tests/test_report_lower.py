"""REPORT instance construction on device (VERDICT r4 item 3).

The reference evaluates metric/logentry field expressions per record
through the same IL hot loop as Check predicates
(mixer/template/template.gen.go ProcessReport,
mixer/pkg/runtime/dispatcher/dispatcher.go:194); here those field
expressions compile into the fused packed step
(runtime/report_lower.py) and adapters must receive instances
FIELD-FOR-FIELD equal to the host InstanceBuilder.build path — across
value types, `|` defaults, map-derived reads, runtime (ephemeral)
values, absent-attribute error aborts, and mixed fused/host-built
instance sets."""
import datetime

import pytest

from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs


class CaptureHandler:
    """Stands in for a built adapter: records (template, instances)."""

    def __init__(self) -> None:
        self.calls: list[tuple[str, list[dict]]] = []

    def handle_report(self, template: str, instances: list[dict]) -> None:
        self.calls.append((template, [dict(i) for i in instances]))

    def flat(self) -> list[dict]:
        return [i for _, insts in self.calls for i in insts]


def _store() -> MemStore:
    s = MemStore()
    s.set(("handler", "istio-system", "sink"), {
        "adapter": "noop", "params": {}})
    # every lowerable field shape in one metric: INT64 value, string /
    # int / bool / defaulted / map-derived dimensions
    s.set(("instance", "istio-system", "m"), {
        "template": "metric",
        "params": {
            "value": "response.size",
            "dimensions": {
                "svc": "destination.service",
                "code": "response.code",
                "is_get": 'request.method == "GET"',
                "user": 'source.user | "anon"',
                "path": 'request.headers["path"]',
            },
            "monitored_resource_type": '"UNSPECIFIED"'}})
    # timestamp/duration-typed fields + defaulted map read
    s.set(("instance", "istio-system", "lg"), {
        "template": "logentry",
        "params": {
            "severity": '"info"',
            "timestamp": "request.time",
            "variables": {
                "dur": "response.duration",
                "host": 'request.headers["host"] | "unknown"'}}})
    # UNLOWERABLE: a bare STRING_MAP field value has no device view
    # (tensor_expr HostFallback) — this instance must keep the host
    # build while m/lg ride the device, in the same report() call
    s.set(("instance", "istio-system", "raw"), {
        "template": "logentry",
        "params": {"variables": {"hdrs": "request.headers"}}})
    s.set(("rule", "istio-system", "tally"), {
        "match": "",
        "actions": [{"handler": "sink", "instances": ["m", "lg", "raw"]}]})
    # predicate-gated + namespace-scoped report rules
    s.set(("rule", "istio-system", "gets-only"), {
        "match": 'request.method == "GET"',
        "actions": [{"handler": "sink", "instances": ["lg"]}]})
    s.set(("rule", "prod", "prod-extra"), {
        "match": "",
        "actions": [{"handler": "sink.istio-system",
                     "instances": ["m.istio-system"]}]})
    return s


def _bags():
    t0 = datetime.datetime(2018, 3, 1, 12, 0, 0,
                           tzinfo=datetime.timezone.utc)
    return [bag_from_mapping(c) for c in (
        # full row: every attribute present (path/host are RUNTIME
        # values → per-batch ephemeral intern ids on the device)
        {"destination.service": "a.default.svc", "response.size": 512,
         "response.code": 200, "request.method": "GET",
         "source.user": "alice", "request.time": t0,
         "response.duration": datetime.timedelta(milliseconds=12),
         "request.headers": {"path": "/api/v1", "host": "a.com"}},
        # defaults exercised: no source.user, no host header
        {"destination.service": "b.default.svc", "response.size": 1,
         "response.code": 404, "request.method": "POST",
         "request.time": t0,
         "response.duration": datetime.timedelta(seconds=1),
         "request.headers": {"path": "/login"}},
        # ABSENT response.size → metric value errors → m aborted
        # (host EvalError path) while lg still lands
        {"destination.service": "c.default.svc",
         "response.code": 500, "request.method": "GET",
         "request.time": t0,
         "response.duration": datetime.timedelta(0),
         "request.headers": {"path": "/x", "host": "c.com"}},
        # prod namespace: the prod-extra rule fires too
        {"destination.service": "d.prod.svc", "response.size": 9,
         "response.code": 200, "request.method": "PUT",
         "source.user": "bob", "request.time": t0,
         "response.duration": datetime.timedelta(milliseconds=3),
         "request.headers": {"path": "/y", "host": "d.com"}},
    )]


def _run(fused: bool, buckets=(4,)) -> CaptureHandler:
    srv = RuntimeServer(_store(), ServerArgs(fused=fused, max_batch=4,
                                             buckets=buckets))
    try:
        d = srv.controller.dispatcher
        assert (d.fused is not None) == fused
        cap = CaptureHandler()
        d.handlers["sink.istio-system"] = cap
        d.report(_bags())
        return cap
    finally:
        srv.close()


def test_instances_lowered_and_split():
    """m and lg compile onto the device; raw (bare STRING_MAP field)
    keeps the host build."""
    srv = RuntimeServer(_store(), ServerArgs(fused=True))
    try:
        rl = srv.controller.dispatcher.fused.report_lowering
        assert rl is not None
        assert set(rl.specs) == {"m.istio-system", "lg.istio-system"}
        assert rl.host_instances == {"raw.istio-system"}
        # metric: 1 value + 5 dimensions + severity? no — m has 6
        # exprs (value + 5 dims; monitored_resource_type is a
        # CONSTANT after parse... it is an expr const → compiled);
        # lg: severity + timestamp + 2 variables
        assert rl.n_fields == len(
            rl.specs["m.istio-system"].fields) + len(
            rl.specs["lg.istio-system"].fields)
    finally:
        srv.close()


def test_report_instance_parity_fused_vs_generic():
    fused, generic = _run(True), _run(False)
    assert fused.flat() == generic.flat()
    # sanity on the shape of what adapters saw: bag 2 dropped m
    # (absent value attr), bag 3 added the prod rule's second m
    names = [i["name"] for i in generic.flat()]
    assert names.count("m.istio-system") == 4   # bags 0, 1, 3, 3(prod)
    assert names.count("lg.istio-system") == 6  # bags 0..3 + GET rows
    assert names.count("raw.istio-system") == 4


def test_report_parity_across_chunking():
    """A 4-bag report through 2-buckets chunks (2+2) on the fused path;
    global record indexing into the sealed planes must hold."""
    fused, generic = _run(True, buckets=(2,)), _run(False)
    assert fused.flat() == generic.flat()


def test_materialized_values_exact():
    """Spot-check decoded values: types survive the id round-trip
    (int64 value, bool dim, defaulted string, ephemeral map read)."""
    cap = _run(True)
    m0 = next(i for i in cap.flat() if i["name"] == "m.istio-system")
    assert m0["value"] == 512 and isinstance(m0["value"], int)
    assert m0["dimensions"] == {
        "svc": "a.default.svc", "code": 200, "is_get": True,
        "user": "alice", "path": "/api/v1"}
    assert m0["monitored_resource_type"] == "UNSPECIFIED"
    lg = [i for i in cap.flat() if i["name"] == "lg.istio-system"]
    assert lg[0]["severity"] == "info"
    assert lg[0]["timestamp"] == datetime.datetime(
        2018, 3, 1, 12, 0, 0, tzinfo=datetime.timezone.utc)
    assert lg[0]["variables"]["dur"] == datetime.timedelta(
        milliseconds=12)
    # defaulted map read on bag 1
    hosts = sorted(i["variables"]["host"] for i in lg)
    assert "unknown" in hosts and "a.com" in hosts
    raw = [i for i in cap.flat() if i["name"] == "raw.istio-system"]
    assert raw[0]["variables"]["hdrs"] == {"path": "/api/v1",
                                           "host": "a.com"}


def test_report_coalescing_across_calls():
    """RuntimeServer.report rides the report batcher: records from
    CONCURRENT calls coalesce into shared padded device trips, and
    every caller's adapter effects still land exactly once."""
    from concurrent.futures import ThreadPoolExecutor

    from istio_tpu.runtime import monitor

    srv = RuntimeServer(_store(), ServerArgs(fused=True, max_batch=8,
                                             buckets=(8,),
                                             batch_window_s=0.01))
    try:
        d = srv.controller.dispatcher
        cap = CaptureHandler()
        d.handlers["sink.istio-system"] = cap
        rows0 = int(monitor.REPORT_BATCH_SIZE._sum.get())
        bags = _bags()
        with ThreadPoolExecutor(max_workers=8) as pool:
            list(pool.map(lambda b: srv.report([b]), bags * 4))
        # 4 copies of the 4-bag set: every per-bag effect lands
        names = [i["name"] for i in cap.flat()]
        assert names.count("m.istio-system") == 16
        assert names.count("lg.istio-system") == 24
        assert names.count("raw.istio-system") == 16
        # and they rode the REPORT coalescer (batches observed)
        assert int(monitor.REPORT_BATCH_SIZE._sum.get()) - rows0 == 16
    finally:
        srv.close()


def test_absent_value_aborts_instance_like_host():
    """bag 2 has no response.size: the metric instance must be ABSENT
    from the adapter call on both paths (EvalError abort), and the
    same rule's other instances still land."""
    for fused in (True, False):
        cap = _run(fused)
        by_bag_c = [i for i in cap.flat()
                    if i.get("dimensions", {}).get("svc")
                    == "c.default.svc"]
        assert by_bag_c == [], fused
        lg_c = [i for i in cap.flat()
                if i["name"] == "lg.istio-system"]
        assert len(lg_c) == 6, fused


def _zero_map_store() -> MemStore:
    """A metric with a ZERO-ENTRY dimensions map and a logentry with
    an empty variables map: the host build materializes the empty
    container ({}), and the device path must too (InstanceSpec
    containers are created before fields for exactly this case)."""
    s = MemStore()
    s.set(("handler", "istio-system", "sink"), {
        "adapter": "noop", "params": {}})
    s.set(("instance", "istio-system", "zm"), {
        "template": "metric",
        "params": {"value": "response.size", "dimensions": {}}})
    s.set(("instance", "istio-system", "zl"), {
        "template": "logentry",
        "params": {"severity": '"info"', "variables": {}}})
    s.set(("rule", "istio-system", "tally"), {
        "match": "",
        "actions": [{"handler": "sink", "instances": ["zm", "zl"]}]})
    return s


def test_zero_entry_map_containers_parity():
    """Zero-entry map containers appear as {} on BOTH paths — a
    device-built instance omitting the empty map would diverge from
    every adapter that reads instance['dimensions'] unconditionally."""
    flats = {}
    for fused in (True, False):
        srv = RuntimeServer(_zero_map_store(),
                            ServerArgs(fused=fused, max_batch=4,
                                       buckets=(4,)))
        try:
            d = srv.controller.dispatcher
            if fused:
                rl = d.fused.report_lowering
                assert rl is not None and "zm.istio-system" in rl.specs
            cap = CaptureHandler()
            d.handlers["sink.istio-system"] = cap
            d.report([bag_from_mapping(
                {"destination.service": "a.default.svc",
                 "response.size": 7})])
            flats[fused] = cap.flat()
        finally:
            srv.close()
    assert flats[True] == flats[False]
    zm = next(i for i in flats[True] if i["name"] == "zm.istio-system")
    assert zm["dimensions"] == {}
    zl = next(i for i in flats[True] if i["name"] == "zl.istio-system")
    assert zl["variables"] == {}


def test_seeded_instance_parity_property():
    """Property-style sweep: seeded request mixes through the mixed
    lowerable/unlowerable config must produce adapter instances
    IDENTICAL (==, covering types and nesting) to the InstanceBuilder
    host oracle — the satellite's fused-vs-host report parity gate."""
    from istio_tpu.testing import workloads

    t0 = datetime.datetime(2018, 3, 1, 12, 0, 0,
                           tzinfo=datetime.timezone.utc)
    for seed in (5, 11):
        dicts = workloads.make_request_dicts(12, seed=seed)
        for j, d in enumerate(dicts):
            # the report attrs the _store() instances read; every 3rd
            # row keeps response.size ABSENT (the metric value expr
            # errors → EvalError row-abort parity is exercised)
            if j % 3:
                d["response.size"] = 100 + j
            d["response.code"] = 200 if j % 2 else 404
            d["request.time"] = t0
            d["response.duration"] = datetime.timedelta(
                milliseconds=j)
            d["request.headers"] = {"path": f"/p{j}",
                                    **({"host": f"h{j}.com"}
                                       if j % 2 else {})}
        bags = [bag_from_mapping(d) for d in dicts]
        flats = {}
        for fused in (True, False):
            srv = RuntimeServer(_store(),
                                ServerArgs(fused=fused, max_batch=8,
                                           buckets=(8,)))
            try:
                d = srv.controller.dispatcher
                cap = CaptureHandler()
                d.handlers["sink.istio-system"] = cap
                d.report(bags)
                flats[fused] = cap.flat()
            finally:
                srv.close()
        assert flats[True] == flats[False], f"seed {seed}"
