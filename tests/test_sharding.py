"""Sharded serving plane (istio_tpu/sharding) — planner properties,
exact sharded-vs-monolithic parity, host-overlay pinning, config-swap
continuity, quota routing across shard boundaries, replica routing,
and the telemetry fan. The 100k-rule scale gate lives in
tests/test_shard_smoke.py; these pin the SEMANTICS at unit scale."""
import numpy as np
import pytest

from istio_tpu.adapters.sdk import QuotaArgs
from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.runtime import RuntimeServer, ServerArgs
from istio_tpu.sharding import (ShardPlan, plan_shards,
                                predict_rule_costs)
from istio_tpu.sharding.planner import HOST_FALLBACK_COST
from istio_tpu.testing import workloads


# ---------------------------------------------------------------- plan

def _fleet_preds(n=2000, ns=64, seed=3):
    return workloads.make_fleet_rules(n, ns, seed=seed)


def test_plan_covers_every_rule_exactly_once():
    preds = _fleet_preds()
    plan = plan_shards(preds, workloads.MESH_FINDER, 4)
    seen = {}
    for k, idxs in enumerate(plan.shard_rules):
        assert idxs == sorted(idxs)          # global order per bank
        for i in idxs:
            seen.setdefault(i, []).append(k)
    assert sorted(seen) == list(range(len(preds)))
    for i, shards in seen.items():
        ns = preds[i].namespace
        if ns:
            # namespace-scoped rule: exactly its namespace's one bank
            assert shards == [plan.ns_to_shard[ns]], (i, ns)
        else:
            # global rule: replicated into every bank
            assert shards == list(range(plan.n_shards))


def test_plan_balances_skewed_namespaces():
    preds = _fleet_preds()
    plan = plan_shards(preds, workloads.MESH_FINDER, 4)
    bal = plan.balance()
    # the fleet namespace sizes are Zipf-skewed by design; LPT packing
    # must still land within a modest envelope of perfect balance
    assert bal["max_over_mean_cost"] <= 1.5, bal
    assert bal["min_over_mean_cost"] >= 0.5, bal
    # a naive round-robin over namespaces does measurably worse on
    # cost spread than LPT, or the planner is not earning its keep
    costs = np.asarray(predict_rule_costs(preds,
                                          workloads.MESH_FINDER))
    ns_names = sorted({p.namespace for p in preds if p.namespace})
    rr_cost = np.zeros(4)
    for j, ns in enumerate(ns_names):
        rr_cost[j % 4] += sum(
            costs[i] for i, p in enumerate(preds)
            if p.namespace == ns)
    assert max(plan.shard_cost) <= rr_cost.max() + 1e-9


def test_plan_deterministic_and_stable_hash_routing():
    preds = _fleet_preds(400, 16, seed=9)
    a = plan_shards(preds, workloads.MESH_FINDER, 3)
    b = plan_shards(preds, workloads.MESH_FINDER, 3)
    assert a.ns_to_shard == b.ns_to_shard
    assert a.shard_rules == b.shard_rules
    # unknown namespaces route stably (crc32, not PYTHONHASHSEED)
    assert a.shard_of("never-seen-ns") == b.shard_of("never-seen-ns")
    assert 0 <= a.shard_of("never-seen-ns") < 3
    # known namespaces route to their assigned bank
    for ns, k in a.ns_to_shard.items():
        assert a.shard_of(ns) == k


def test_cost_model_prices_host_fallback():
    from istio_tpu.compiler.ruleset import Rule
    preds = [
        Rule(name="eq", match='request.method == "GET"',
             namespace="a"),
        # dynamic pattern argument: no constant DFA, host fallback
        Rule(name="dyn",
             match='"x".matches(request.path) || '
                   'match(request.path, request.host)',
             namespace="a"),
    ]
    costs = predict_rule_costs(preds, workloads.MESH_FINDER)
    assert costs[0] > 0
    assert costs[0] < HOST_FALLBACK_COST


def test_costs_from_ruleset_matches_standalone_model():
    """The publish path prices rules from the retained compiled
    decomposition (costs_from_ruleset — no second parse/DNF pass at
    100k rules); it must agree exactly with the standalone
    predict_rule_costs model, or swap-time plans drift from the
    tested balance properties."""
    from istio_tpu.compiler.ruleset import compile_ruleset
    from istio_tpu.sharding.planner import costs_from_ruleset

    preds = _fleet_preds(600, 24, seed=12)
    rs = compile_ruleset(preds, workloads.MESH_FINDER, jit=False)
    a = predict_rule_costs(preds, workloads.MESH_FINDER)
    b = costs_from_ruleset(rs, workloads.MESH_FINDER)
    assert np.allclose(a, b[:len(preds)])


# ------------------------------------------------- serving parity

N_RULES = 240


@pytest.fixture(scope="module")
def pair():
    """plain (monolithic) vs sharded+replicated servers over the SAME
    config — make_store's full action mix incl. host-overlay list
    shapes (case-insensitive / provider-refreshed / dynamic-regex)
    and host-fallback predicates, so parity covers the overlay path."""
    kw = dict(batch_window_s=0.001, buckets=(16, 64), max_batch=64,
              default_manifest=workloads.MESH_MANIFEST)
    plain = RuntimeServer(
        workloads.make_store(N_RULES, host_overlay_every=10, seed=5),
        ServerArgs(**kw))
    sharded = RuntimeServer(
        workloads.make_store(N_RULES, host_overlay_every=10, seed=5),
        ServerArgs(shards=3, replicas=2, **kw))
    yield plain, sharded
    plain.close()
    sharded.close()


def _mixed_bags(n=64, seed=6):
    return [bag_from_mapping(d)
            for d in workloads.make_request_dicts(n, seed=seed)]


def test_sharded_matches_monolithic_exactly(pair):
    plain, sharded = pair
    assert sharded._sharded["mode"] == "sharded"
    bags = _mixed_bags()
    rp = plain.check_many(bags)
    rs = sharded.check_many(bags)
    for i, (a, b) in enumerate(zip(rp, rs)):
        assert a.status_code == b.status_code, f"row {i}"
        assert a.status_message == b.status_message, f"row {i}"
        assert a.valid_duration_s == pytest.approx(
            b.valid_duration_s), f"row {i}"
        assert a.valid_use_count == b.valid_use_count, f"row {i}"
        assert a.referenced == b.referenced, f"row {i}"
        # deny attribution folds back to GLOBAL rule indices
        assert a.deny_rule == b.deny_rule, f"row {i}"


def test_sharded_through_replica_front(pair):
    plain, sharded = pair
    bags = _mixed_bags(48, seed=8)
    want = plain.check_many(bags)
    futs = [sharded.batcher.submit(b) for b in bags]
    got = [f.result() for f in futs]
    for i, (a, b) in enumerate(zip(want, got)):
        assert a.status_code == b.status_code, f"row {i}"
        assert a.referenced == b.referenced, f"row {i}"
    # zero misroutes, exact row conservation across lanes
    routed = sum(n for r in sharded.batcher.routers
                 for n in r.rows_routed.values())
    assert routed >= len(bags)
    assert sum(r.misrouted for r in sharded.batcher.routers) == 0


def test_host_overlay_rules_pinned_to_home_shard(pair):
    _, sharded = pair
    state = sharded._sharded
    plan: ShardPlan = state["plan"]
    snap = sharded.controller.dispatcher.snapshot
    pinned = 0
    for bank in state["banks"]:
        fused = bank.dispatcher.fused
        for local in fused.host_actions:
            gidx = int(bank.local_to_global[local])
            ns = snap.ruleset.rules[gidx].namespace
            # a host-overlay rule compiles into exactly its
            # namespace's bank (global rules are replicated, so only
            # namespace-scoped ones pin)
            if ns:
                assert plan.ns_to_shard[ns] == bank.shard_id
                pinned += 1
    assert pinned > 0, "workload lost its host-overlay rules"


def test_unknown_namespace_serves_global_rules_only(pair):
    plain, sharded = pair
    bag = bag_from_mapping({
        "destination.service": "svc0.nowhere-ns.svc.cluster.local",
        "source.user": "anon", "request.method": "GET"})
    a = plain.check_many([bag])[0]
    b = sharded.check_many([bag])[0]
    assert a.status_code == b.status_code
    assert a.referenced == b.referenced


def test_sticky_lane_routing(pair):
    _, sharded = pair
    rr = sharded.batcher
    bags = _mixed_bags(32, seed=11)
    lanes = {}
    for bag in bags:
        ns = bag.get("destination.service")[0].split(".")[1]
        lane = rr.lane_of(bag)
        assert lanes.setdefault(ns, lane) == lane, \
            "namespace bounced between lanes"
    assert len(set(lanes.values())) > 1, \
        "all namespaces collapsed onto one lane"


def test_rulestats_fan_across_banks(pair):
    """Per-rule telemetry from every bank merges into the one
    aggregator, name-keyed, matching an oracle recount of hits."""
    from istio_tpu.sharding import oracle_check_statuses

    plain, sharded = pair
    sharded.rulestats.drain()
    base = {k: dict(v) for k, v in
            sharded.rulestats.counts().items()}
    bags = _mixed_bags(40, seed=13)
    sharded.check_many(bags)
    sharded.rulestats.drain()
    got = sharded.rulestats.counts()
    snap = sharded.controller.dispatcher.snapshot
    expected = oracle_check_statuses(
        snap, sharded.controller.dispatcher.fused, bags)
    names = snap.qualified_rule_names()
    want_hits: dict[str, int] = {}
    for row in expected:
        for ridx in row["active"]:
            want_hits[names[ridx]] = want_hits.get(names[ridx], 0) + 1
    for name, n in want_hits.items():
        prev = base.get(name, {}).get("hits", 0)
        assert got[name]["hits"] - prev == n, name


def test_router_chunks_over_bucket_batches():
    """A lane batch larger than the banks' largest prewarmed bucket
    must chunk (never run an un-prewarmed shape), and still return
    every row in order."""
    srv = RuntimeServer(
        workloads.make_fleet_store(90, 6, seed=3),
        ServerArgs(batch_window_s=0.001, buckets=(8,), max_batch=32,
                   shards=2, replicas=1,
                   default_manifest=workloads.MESH_MANIFEST))
    try:
        bags = [bag_from_mapping(d) for d in
                workloads.make_fleet_traffic(32, 90, 6, seed=3)]
        got = srv.check_many(bags)
        assert len(got) == len(bags)
        from istio_tpu.sharding import oracle_check_statuses
        want = oracle_check_statuses(
            srv.controller.dispatcher.snapshot,
            srv.controller.dispatcher.fused, bags)
        for i, (g, w) in enumerate(zip(got, want)):
            assert g.status_code == w["status"], f"row {i}"
            assert g.deny_rule == w["deny_rule"], f"row {i}"
    finally:
        srv.close()


def test_bank_device_fault_degrades_to_oracle_not_error():
    """A transient device-step fault inside a bank must be absorbed by
    the bank's OWN resilience wrap (retry → breaker → the bank's CPU
    oracle) — every request still answers CORRECTLY, none surfaces a
    raw internal error. The monolithic path's contract
    (tests/test_resilience.py), per bank."""
    from istio_tpu.runtime.resilience import CHAOS
    from istio_tpu.sharding import oracle_check_statuses

    srv = RuntimeServer(
        workloads.make_fleet_store(90, 6, seed=6),
        ServerArgs(batch_window_s=0.001, buckets=(16,), max_batch=16,
                   shards=2, replicas=2, device_retry=False,
                   default_manifest=workloads.MESH_MANIFEST))
    try:
        banks = srv._sharded["banks"]
        assert all(b.checker is not None for b in banks)
        bags = [bag_from_mapping(d) for d in
                workloads.make_fleet_traffic(16, 90, 6, seed=6)]
        srv.check_many(bags)                # warm every bank shape
        CHAOS.reset()
        CHAOS.device_failures = 2           # fault the next 2 steps
        try:
            futs = [srv.batcher.submit(b) for b in bags]
            got = [f.result() for f in futs]   # no raised futures
        finally:
            injected = CHAOS.injected_device
            CHAOS.reset()
        assert injected > 0, "chaos seam never fired in a bank step"
        want = oracle_check_statuses(
            srv.controller.dispatcher.snapshot,
            srv.controller.dispatcher.fused, bags)
        for i, (g, w) in enumerate(zip(got, want)):
            assert g.status_code == w["status"], f"row {i}"
    finally:
        srv.close()


def test_config_swap_continuity():
    """A config swap rebuilds the banks and every lane serves the NEW
    snapshot — no dropped requests, no stale verdicts."""
    store = workloads.make_fleet_store(120, 8, seed=2)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.001, buckets=(16,), max_batch=16,
        shards=2, replicas=2,
        default_manifest=workloads.MESH_MANIFEST))
    try:
        traffic = workloads.make_fleet_traffic(24, 120, 8, seed=2)
        bags = [bag_from_mapping(d) for d in traffic]
        before = srv.check_many(bags)
        assert any(r.status_code == 0 for r in before)
        rev0 = srv._sharded["revision"]
        # swap: a fresh GLOBAL deny-everything rule — every request
        # must now answer non-OK through every bank (a lower-index
        # rule's own non-OK status may still win the combine, so the
        # pin is "nothing stays OK", not one specific code)
        store.set(("rule", "istio-system", "deny-world"), {
            "match": "",
            "actions": [{"handler": "denyall",
                         "instances": ["nothing"]}]})
        srv.controller.rebuild()
        assert srv._sharded["revision"] > rev0
        futs = [srv.batcher.submit(b) for b in bags]
        after = [f.result() for f in futs]
        assert all(r.status_code != 0 for r in after), \
            [r.status_code for r in after[:8]]
        assert len(after) == len(before)
        assert sum(r.misrouted for r in srv.batcher.routers) == 0
    finally:
        srv.close()


def test_global_quota_rule_routes_to_shared_pool():
    """A default-namespace quota rule replicates into every bank, but
    allocation happens ONCE per request from the one controller-owned
    pool — grants match the monolithic server exactly."""
    kw = dict(batch_window_s=0.001, buckets=(16,), max_batch=16,
              default_manifest=workloads.MESH_MANIFEST)
    plain = RuntimeServer(
        workloads.make_fleet_store(60, 6, seed=4, with_quota=True),
        ServerArgs(**kw))
    sharded = RuntimeServer(
        workloads.make_fleet_store(60, 6, seed=4, with_quota=True),
        ServerArgs(shards=3, replicas=2, **kw))
    try:
        # every bank carries the replicated global quota rule
        for bank in sharded._sharded["banks"]:
            assert any(r.name == "quota-rule"
                       for r in bank.snapshot.rules)
            assert bank.dispatcher.fused.quota_actions
        traffic = workloads.make_fleet_traffic(12, 60, 6, seed=4)
        for d in traffic:
            bag_p = bag_from_mapping(d)
            bag_s = bag_from_mapping(d)
            rp = plain.check_many([bag_p])[0]
            rs = sharded.check_many([bag_s])[0]
            args = QuotaArgs(quota_amount=3)
            qp = plain.quota_fused(bag_p, "rq.istio-system", args, rp)
            qs = sharded.quota_fused(bag_s, "rq.istio-system", args,
                                     rs)
            gp = qp.result() if hasattr(qp, "result") else qp
            gs = qs.result() if hasattr(qs, "result") else qs
            assert gp is not None and gs is not None
            assert gs.granted_amount == gp.granted_amount
        # the sharded server used ONE pool for all banks
        pools = {id(p) for p in sharded.controller.device_quotas
                 .values()}
        assert len(pools) == 1
    finally:
        plain.close()
        sharded.close()


def test_instep_quota_refused_under_sharding():
    srv = RuntimeServer(
        workloads.make_fleet_store(30, 4, seed=1, with_quota=True),
        ServerArgs(batch_window_s=0.001, buckets=(16,), max_batch=16,
                   shards=2, quota_in_step=True,
                   default_manifest=workloads.MESH_MANIFEST))
    try:
        # the merged check+quota program cannot span banks: sharded
        # serving must refuse the in-step path (classic defer serves)
        assert srv.instep_quota_target() is None
    finally:
        srv.close()


def test_rbac_snapshot_falls_back_to_replica_only():
    """Device-lowered rbac pseudo-rules reference absolute ruleset
    rows — such snapshots refuse to shard and serve replica-only,
    verdict-identical to the monolithic path."""
    kw = dict(batch_window_s=0.001, buckets=(16,), max_batch=16)
    plain = RuntimeServer(workloads.make_rbac_store(40), ServerArgs(**kw))
    sharded = RuntimeServer(workloads.make_rbac_store(40),
                            ServerArgs(shards=2, replicas=2, **kw))
    try:
        st = sharded._sharded
        assert st["mode"] == "replica-only"
        assert "pseudo-rule" in st["fallback_reason"]
        dicts = workloads.make_rbac_request_dicts(24)
        bags_p = [bag_from_mapping(d) for d in dicts]
        bags_s = [bag_from_mapping(d) for d in dicts]
        rp = plain.check_many(bags_p)
        rs = sharded.check_many(bags_s)
        for i, (a, b) in enumerate(zip(rp, rs)):
            assert a.status_code == b.status_code, f"row {i}"
    finally:
        plain.close()
        sharded.close()


def test_debug_shards_view_zero_shaped_and_live():
    import json
    import urllib.request

    from istio_tpu.introspect import IntrospectServer

    srv = RuntimeServer(
        workloads.make_fleet_store(40, 4, seed=8),
        ServerArgs(batch_window_s=0.001, buckets=(16,), max_batch=16,
                   shards=2, replicas=2,
                   default_manifest=workloads.MESH_MANIFEST))
    intro = IntrospectServer(runtime=srv)
    try:
        port = intro.start()

        def view():
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/shards",
                    timeout=30) as r:
                return json.loads(r.read().decode())

        v = view()   # before any traffic: zero-shaped, never an error
        assert v["enabled"] and v["mode"] == "sharded"
        assert sum(v["rows_per_shard"].values()) == 0
        assert v["misrouted"] == 0
        assert len(v["banks"]) == 2
        assert all(b["bank_bytes"] > 0 for b in v["banks"])
        assert len(v["replicas"]) == 2
        for rep in v["replicas"]:
            assert rep["batch_latency"]["batches"] >= 0
        bags = [bag_from_mapping(d) for d in
                workloads.make_fleet_traffic(16, 40, 4, seed=8)]
        futs = [srv.batcher.submit(b) for b in bags]
        [f.result() for f in futs]
        v = view()
        assert sum(v["rows_per_shard"].values()) == len(bags)
        assert v["last_decision"]["balance"]["n_shards"] == 2
    finally:
        intro.close()
        srv.close()


def test_monolithic_server_reports_shards_disabled():
    import json
    import urllib.request

    from istio_tpu.introspect import IntrospectServer

    srv = RuntimeServer(
        workloads.make_fleet_store(20, 4, seed=1),
        ServerArgs(batch_window_s=0.001, buckets=(16,), max_batch=16,
                   default_manifest=workloads.MESH_MANIFEST))
    intro = IntrospectServer(runtime=srv)
    try:
        port = intro.start()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/shards",
                timeout=30) as r:
            v = json.loads(r.read().decode())
        assert v == {"enabled": False}
    finally:
        intro.close()
        srv.close()
