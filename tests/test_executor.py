"""Adapter-executor plane (runtime/executor.py): bulkheads, deadline
bounds, per-handler breakers, maintenance lane, typed-rejection
conservation — ISSUE 12's wedged-adapter chaos suite."""
from __future__ import annotations

import threading
import time

import pytest

from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.runtime import RuntimeServer, ServerArgs
from istio_tpu.runtime import monitor
from istio_tpu.runtime.resilience import CHAOS
from istio_tpu.testing import workloads

UNAVAILABLE = 14

CI = "cilist.istio-system"
PROV = "provlist.istio-system"


@pytest.fixture(autouse=True)
def _chaos_clean():
    CHAOS.reset()
    yield
    CHAOS.reset()


def _server(store, **kw):
    args = dict(batch_window_s=0.0005, max_batch=16, buckets=(8, 16),
                default_manifest=workloads.MESH_MANIFEST)
    args.update(kw)
    return RuntimeServer(store, ServerArgs(**args))


def _overlay_bag(i: int, n_services: int = 30) -> object:
    """A bag matching make_store(host_overlay_every=5) rule `i`
    (i % 5 == 2 rules carry a host list action; k = (i//5) % 3 picks
    cilist / provlist / dynpat)."""
    return bag_from_mapping({
        "destination.service":
            f"svc{i % n_services}.ns{i % 23}.svc.cluster.local",
        "source.namespace": "ns2",
        "request.method": "GET",
        # k==7 rules gate on request.path.startsWith("/api/v{i%3}/")
        "request.path": f"/api/v{i % 3}/items",
    })


def _counters_delta(before: dict, key: str = "outcomes") -> dict:
    after = monitor.host_action_counters()
    return {k: after[key][k] - before[key].get(k, 0)
            for k in after[key]}


def test_wedged_adapter_bulkhead_and_recovery():
    """THE chaos scenario: one handler wedged under load — other
    adapters' throughput unaffected (bulkhead), affected rules resolve
    via the fail policy within the deadline, the lane breaker opens,
    then half-open-probes closed on recovery, and the typed-rejection
    conservation stays EXACT."""
    store = workloads.make_store(60, host_overlay_every=5)
    srv = _server(store, host_breaker_failures=2,
                  host_breaker_reset_s=0.3)
    try:
        base = monitor.host_action_counters()
        ci_bag = _overlay_bag(2)      # k=0 → cilist
        prov_bag = _overlay_bag(7)    # k=1 → provlist
        # clean baseline verdicts
        clean_ci = srv.check(ci_bag).status_code
        clean_prov = srv.check(prov_bag).status_code

        CHAOS.wedge_adapter(CI)
        deadline_s = 0.4
        # wedged-handler requests: answered WITHIN the deadline with
        # the fail-closed verdict, never held by the wedged backend
        walls = []
        for _ in range(3):
            t0 = time.perf_counter()
            r = srv.check(ci_bag,
                          deadline=time.perf_counter() + deadline_s)
            walls.append(time.perf_counter() - t0)
            assert r.status_code == UNAVAILABLE
        assert max(walls) < deadline_s + 0.35, walls
        # bulkhead: the OTHER handler's lane is untouched — fast, and
        # verdicts unchanged
        t0 = time.perf_counter()
        assert srv.check(prov_bag).status_code == clean_prov
        assert time.perf_counter() - t0 < deadline_s
        # breaker: 2 overruns tripped the cilist lane open; further
        # actions short-circuit (breaker_open) without queueing
        lane = srv.executor.lane(CI)
        assert lane.breaker.state == "open"
        r = srv.check(ci_bag,
                      deadline=time.perf_counter() + deadline_s)
        assert r.status_code == UNAVAILABLE
        d = _counters_delta(base)
        assert d["overrun"] >= 2
        assert d["breaker_open"] >= 1

        # recovery: unwedge, wait out the reset window — the next
        # action is the half-open probe, closes the breaker, and the
        # verdict returns to the clean baseline
        CHAOS.unwedge_adapter(CI)
        time.sleep(0.35)
        assert srv.check(ci_bag).status_code == clean_ci
        assert lane.breaker.state == "closed"
    finally:
        CHAOS.reset()
        srv.close()
    # EXACT conservation: every submitted action resolved with exactly
    # one outcome (late completions counted separately, never twice)
    hc = monitor.host_action_counters()
    assert hc["exact"], hc
    assert hc["submitted"] - base["submitted"] == \
        sum(_counters_delta(base).values())


def test_bulkhead_overflow_sheds_typed_with_deadline():
    """A wedged lane's queue fills to its cap; further submits shed
    typed (outcome=shed → fail policy) instantly, never block, and
    the batch folds in roughly one action-timeout window."""
    store = workloads.make_store(60, host_overlay_every=5)
    srv = _server(store, executor_queue_cap=1, executor_workers=1,
                  host_breaker_failures=100,
                  host_action_timeout_ms=300.0)
    try:
        base = monitor.host_action_counters()
        CHAOS.wedge_adapter(CI)
        ci_bags = [_overlay_bag(2) for _ in range(8)]
        t0 = time.perf_counter()
        out = srv.check_many(ci_bags)
        wall = time.perf_counter() - t0
        assert all(r.status_code == UNAVAILABLE for r in out)
        # 8 actions: 1 running + 1 queued wait out the 300ms action
        # timeout, 6 shed instantly at the cap — the batch folds in
        # roughly one timeout window, not 8
        assert wall < 2.5, wall
        d = _counters_delta(base)
        assert d["shed"] >= 5, d
        assert d["shed"] + d["overrun"] + d["expired"] == 8, d
    finally:
        CHAOS.reset()
        srv.close()
    assert monitor.host_action_counters()["exact"]


def test_adapter_errors_keep_safedispatch_parity_and_trip_breaker():
    """Injected adapter exceptions: one retry, then the action's own
    INTERNAL verdict (safeDispatch parity — oracle-identical), and
    consecutive failures trip the lane breaker."""
    store = workloads.make_store(60, host_overlay_every=5)
    srv = _server(store, host_breaker_failures=3,
                  host_breaker_reset_s=60.0)
    try:
        base = monitor.host_action_counters()
        bag = _overlay_bag(2)
        clean = srv.check(bag).status_code
        CHAOS.adapter_failures[CI] = 10 ** 6   # every attempt fails
        sts = [srv.check(bag).status_code for _ in range(3)]
        # INTERNAL (13): the adapter-panic shape, not the fail policy
        assert sts == [13, 13, 13], sts
        assert srv.executor.lane(CI).breaker.state == "open"
        # open breaker → fail policy (closed → UNAVAILABLE)
        assert srv.check(bag).status_code == UNAVAILABLE
        d = _counters_delta(base)
        assert d["error"] == 3 and d["breaker_open"] == 1, d
        # retries happened (one per failed action)
        hc = monitor.host_action_counters()
        assert hc["retries"] - base["retries"] == 3
        CHAOS.reset()
        srv.executor.lane(CI).breaker.record_success()  # force close
        assert srv.check(bag).status_code == clean
    finally:
        CHAOS.reset()
        srv.close()


def test_fail_open_policy_answers_ok_with_short_ttl():
    store = workloads.make_store(60, host_overlay_every=5)
    srv = _server(store, host_fail_policy="open",
                  host_action_timeout_ms=100.0)
    try:
        CHAOS.wedge_adapter(CI)
        r = srv.check(_overlay_bag(2))
        assert r.status_code == 0
        # the policy-bypass window must close with the outage
        assert r.valid_duration_s <= 1.0
        assert r.valid_use_count == 1
    finally:
        CHAOS.reset()
        srv.close()


def test_deadline_inherited_from_request_bounds_host_actions():
    """Deadline propagation end to end: the batcher's min-deadline
    reaches the executor fold, so a wedged adapter can never hold a
    request past its own budget."""
    store = workloads.make_store(60, host_overlay_every=5)
    srv = _server(store)
    try:
        CHAOS.wedge_adapter(CI)
        t0 = time.perf_counter()
        r = srv.check(_overlay_bag(2),
                      deadline=time.perf_counter() + 0.25)
        wall = time.perf_counter() - t0
        assert r.status_code == UNAVAILABLE
        assert wall < 0.25 + 0.35, wall
    finally:
        CHAOS.reset()
        srv.close()


def test_ns_invisible_fallback_pairs_skipped():
    """Satellite regression: _overlay_active must not host_eval a
    (bag, rule) pair whose namespace can never see the rule — a slow
    fallback predicate is only paid by traffic that could match it,
    and error accounting stays oracle-identical (visible-only)."""
    from istio_tpu.runtime.store import MemStore

    s = MemStore()
    s.set(("handler", "nsa", "deny"), {
        "adapter": "denier", "params": {"status_code": 7}})
    s.set(("instance", "nsa", "nothing"), {
        "template": "checknothing", "params": {}})
    # dynamic map key → host-fallback predicate, namespaced to nsa
    s.set(("rule", "nsa", "dynkey"), {
        "match": 'request.headers[request.method] == "yes"',
        "actions": [{"handler": "deny", "instances": ["nothing"]}]})
    srv = _server(s)
    try:
        d = srv.controller.dispatcher
        rs = d.snapshot.ruleset
        assert rs.host_fallback, "dynkey must be host-fallback"
        calls = []
        real = rs.host_eval

        def spy(ridx, bag):
            calls.append(ridx)
            return real(ridx, bag)

        rs.host_eval = spy
        try:
            vis = bag_from_mapping({
                "destination.service": "x.nsa.svc.cluster.local",
                "request.method": "GET",
                "request.headers": {"GET": "yes"}})
            invis = bag_from_mapping({
                "destination.service": "x.nsb.svc.cluster.local",
                "request.method": "GET",
                "request.headers": {"GET": "yes"}})
            out = d.check([vis, invis, invis])
            # only the VISIBLE row paid a host_eval
            assert len(calls) == 1, calls
            # verdicts oracle-identical
            oracle = d.check_host_oracle([vis, invis, invis])
            assert [r.status_code for r in out] == \
                [r.status_code for r in oracle] == [7, 0, 0]
            # invisible errored pairs: no RESOLVE_ERRORS movement
            calls.clear()
            err0 = monitor.RESOLVE_ERRORS._value.get()
            bad = bag_from_mapping({
                "destination.service": "x.nsb.svc.cluster.local"})
            d.check([bad])   # would error in dynkey — but invisible
            assert calls == []
            assert monitor.RESOLVE_ERRORS._value.get() == err0
        finally:
            rs.host_eval = real
    finally:
        srv.close()


def test_list_provider_refresh_failure_keeps_last_good(tmp_path):
    """Satellite: a failing file:// provider keeps serving the last
    good list, the refresh counter pair moves, and the failure is
    visible in refresh stats."""
    from istio_tpu.adapters.list_adapter import ListHandler

    p = tmp_path / "allow.txt"
    p.write_text("ns0\nns2\n")
    h = ListHandler({"provider_url": f"file://{p}",
                     "refresh_interval_s": 60.0}, env=None)
    assert h.handle_check("listentry", {"value": "ns2"}).ok
    t0 = int(monitor.LIST_REFRESH_TOTAL._value.get())
    f0 = int(monitor.LIST_REFRESH_FAILURES._value.get())

    from istio_tpu.runtime.executor import (AdapterExecutor,
                                            ExecutorConfig)
    ex = AdapterExecutor(ExecutorConfig())
    try:
        ex.register_refreshables({"lh.ns": h})
        p.unlink()   # provider now fails
        assert ex.refresh_now("lh.ns")
        # last good list keeps serving
        assert h.handle_check("listentry", {"value": "ns2"}).ok
        assert not h.handle_check("listentry", {"value": "ns1"}).ok
        assert int(monitor.LIST_REFRESH_TOTAL._value.get()) == t0 + 1
        assert int(monitor.LIST_REFRESH_FAILURES._value.get()) == \
            f0 + 1
        st = h.refresh_stats()
        assert st["refresh_failures"] == 1
        assert st["last_refresh_error"]
        snap = ex.snapshot()
        m = snap["maintenance"]["lh.ns"]
        assert m["refresh_failures"] == 1 and m["refresh_total"] == 1
        # provider restored → next refresh picks up the new list
        p.write_text("ns1\n")
        assert ex.refresh_now("lh.ns")
        assert h.handle_check("listentry", {"value": "ns1"}).ok
        assert h.refresh_stats()["last_refresh_error"] is None
    finally:
        ex.close()


def test_maintenance_scheduler_drives_periodic_refresh():
    from istio_tpu.runtime.executor import (AdapterExecutor,
                                            ExecutorConfig)

    pulls = []

    class H:
        refresh_interval_s = 0.05
        _provider = staticmethod(lambda: [])

        def refresh(self):
            pulls.append(time.monotonic())

    ex = AdapterExecutor(ExecutorConfig(maintenance_tick_s=0.01))
    try:
        ex.register_refreshables({"h.ns": H()})
        deadline = time.monotonic() + 3.0
        while len(pulls) < 2 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(pulls) >= 2, "scheduler never fired"
    finally:
        ex.close()


def test_opa_scenario_oracle_parity_and_verdicts():
    """The rego/OPA engine as a first-class overlay scenario: real
    allow AND deny verdicts, exactly matching the generic host oracle
    path (the executor changes where adapter work runs, never what it
    answers)."""
    store = workloads.make_opa_store(42)
    srv = _server(store)
    try:
        bags = [bag_from_mapping(x)
                for x in workloads.make_opa_requests(24, 42)]
        d = srv.controller.dispatcher
        fused = d.check(bags)
        oracle = d.check_host_oracle(bags)
        sts = [r.status_code for r in fused]
        assert [r.status_code for r in oracle] == sts
        assert 7 in sts and 0 in sts, sts   # both verdicts exercised
        hc = monitor.host_action_counters()
        assert hc["by_handler"]["opah.istio-system"]["outcomes"][
            "ok"] >= len(bags) // 2
    finally:
        srv.close()


def test_shared_quota_dedup_across_replicas():
    """memquota over one shared QuotaBackend behind two server
    replicas, allocations through the executor's mq lane: a dedup_id
    retried on the OTHER replica replays the original grant, and the
    global window is conserved under concurrency."""
    from istio_tpu.adapters.memquota import QuotaBackend
    from istio_tpu.adapters.sdk import QuotaArgs

    backend = QuotaBackend()
    a = _server(workloads.make_shared_quota_store(backend,
                                                  max_amount=32))
    b = _server(workloads.make_shared_quota_store(backend,
                                                  max_amount=32))
    try:
        bag = bag_from_mapping({
            "source.user": "u1",
            "destination.service": "x.ns0.svc.cluster.local"})
        r1 = a.quota(bag, "rq.istio-system",
                     QuotaArgs(quota_amount=5, dedup_id="d-1"))
        r2 = b.quota(bag, "rq.istio-system",
                     QuotaArgs(quota_amount=5, dedup_id="d-1"))
        assert (r1.granted_amount, r2.granted_amount) == (5, 5)
        assert backend.dedup["d-1"][0] == 5   # ONE real allocation

        # concurrent best-effort allocs across both replicas: total
        # real grants never exceed the shared window (32 - 5 = 27)
        granted = []
        lock = threading.Lock()

        def worker(srv, n):
            for i in range(n):
                r = srv.quota(bag, "rq.istio-system",
                              QuotaArgs(quota_amount=3,
                                        best_effort=True))
                with lock:
                    granted.append(r.granted_amount)

        ts = [threading.Thread(target=worker, args=(s, 10))
              for s in (a, b)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert sum(granted) == 27, granted
        hc = monitor.host_action_counters()
        assert hc["by_handler"]["mq.istio-system"]["outcomes"]["ok"] \
            >= 22
    finally:
        a.close()
        b.close()


def test_inline_path_parity_when_executor_disabled():
    """host_executor=False restores the pre-executor inline loop —
    verdict-identical on the same traffic (the behavioral oracle)."""
    store = workloads.make_store(60, host_overlay_every=5)
    srv_ex = _server(store)
    srv_in = _server(workloads.make_store(60, host_overlay_every=5),
                     host_executor=False)
    try:
        assert srv_in.executor is None
        assert srv_in.controller.dispatcher.executor is None
        bags = [_overlay_bag(i) for i in (2, 7, 12, 22, 32, 42)]
        out_ex = [r.status_code for r in
                  srv_ex.controller.dispatcher.check(bags)]
        out_in = [r.status_code for r in
                  srv_in.controller.dispatcher.check(bags)]
        assert out_ex == out_in
    finally:
        srv_ex.close()
        srv_in.close()


def test_abandon_keeps_conservation_exact_without_breaker_blame():
    """A fold unwinding past submitted actions (exception between
    submit and claim) must account every action exactly once — and
    must NOT charge the adapter's breaker for the fold's failure."""
    from istio_tpu.runtime.executor import (AdapterExecutor,
                                            ExecutorConfig)

    ex = AdapterExecutor(ExecutorConfig())
    try:
        base = monitor.host_action_counters()
        # the wedge is LANE-wide, so the completing action must live
        # on its own lane
        CHAOS.wedge_adapter("h.ns")
        running = ex.submit("h.ns", lambda: "never",
                            lambda p, r: None)
        done = ex.submit("ok.ns", lambda: "fast", lambda p, r: None)
        claimed = ex.resolve(done)   # normally claimed by the fold
        assert claimed == "fast"
        # the fold dies here: abandon both (claimed one is a no-op)
        ex.abandon(done)
        ex.abandon(running)
        hc = monitor.host_action_counters()
        assert hc["exact"], hc
        d = {k: hc["outcomes"][k] - base["outcomes"][k]
             for k in hc["outcomes"]}
        assert d == {"ok": 1, "error": 0, "shed": 0, "expired": 1,
                     "overrun": 0, "breaker_open": 0}, d
        # the adapter is not blamed for the fold's exception
        assert ex.lane("h.ns").breaker.state == "closed"
    finally:
        CHAOS.reset()
        ex.close()


def test_quota_adapter_call_bounded_by_server_default_deadline():
    """RuntimeServer.quota inherits the server default deadline when
    the caller passes none — a wedged shared-quota backend cannot
    hold a front thread unbounded."""
    from istio_tpu.adapters.sdk import QuotaArgs

    srv = _server(workloads.make_shared_quota_store(max_amount=8),
                  default_check_deadline_ms=250.0)
    try:
        bag = bag_from_mapping({
            "source.user": "u1",
            "destination.service": "x.ns0.svc.cluster.local"})
        CHAOS.wedge_adapter("mq.istio-system")
        t0 = time.perf_counter()
        r = srv.quota(bag, "rq.istio-system",
                      QuotaArgs(quota_amount=2))
        wall = time.perf_counter() - t0
        assert wall < 0.25 + 0.35, wall
        # fail-closed: granted nothing, typed UNAVAILABLE
        assert (r.granted_amount, r.status_code) == (0, UNAVAILABLE)
    finally:
        CHAOS.reset()
        srv.close()


def test_executor_survives_config_swap_with_breaker_state():
    """Lanes (and their breakers) persist across config republishes —
    a wedged handler stays short-circuited through a swap instead of
    re-paying the failure budget in-band."""
    store = workloads.make_store(60, host_overlay_every=5)
    srv = _server(store, host_breaker_failures=1,
                  host_breaker_reset_s=60.0,
                  host_action_timeout_ms=100.0)
    try:
        CHAOS.wedge_adapter(CI)
        srv.check(_overlay_bag(2))   # overrun → breaker opens
        assert srv.executor.lane(CI).breaker.state == "open"
        # republish (quiet edit + explicit rebuild)
        store.set(("rule", "ns1", "rule1"), {
            "match": 'destination.service == "zz.ns1.svc.cluster.local"',
            "actions": [{"handler": "denyall.istio-system",
                         "instances": []}]})
        srv.controller.rebuild()
        assert srv.controller.dispatcher.executor is srv.executor
        assert srv.executor.lane(CI).breaker.state == "open"
        r = srv.check(_overlay_bag(2))
        assert r.status_code == UNAVAILABLE   # still short-circuited
    finally:
        CHAOS.reset()
        srv.close()
