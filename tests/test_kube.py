"""Kubernetes-shaped L2, hermetic (VERDICT r1 item 5): CRD config
backend, kube service registry, pilot CRD client, ingress controller,
admission validation, and the SA-secret controller — all over the
in-process FakeKubeCluster, reacting to live watch events.

Reference anchors: mixer/pkg/config/crd/store.go, pilot/pkg/
serviceregistry/kube/controller.go, pilot/pkg/config/kube/crd/client.go,
pilot/pkg/config/kube/ingress/, pilot/pkg/kube/admit/admit.go,
security/pkg/pki/ca/controller/secret.go.
"""
import base64

import pytest

from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.kube import (AdmissionDenied, CrdStore, FakeKubeCluster,
                            IngressController, KubeConfigStore,
                            KubeServiceRegistry,
                            register_istio_admission)
from istio_tpu.models.policy_engine import OK, PERMISSION_DENIED
from istio_tpu.pilot.model import Config, ConfigMeta, MemoryConfigStore
from istio_tpu.runtime import RuntimeServer, ServerArgs


def _svc(name, ns="default", ports=None, cluster_ip="10.0.0.1"):
    return {"kind": "Service",
            "metadata": {"name": name, "namespace": ns},
            "spec": {"clusterIP": cluster_ip,
                     "ports": ports or [{"name": "http", "port": 80}]}}


def _endpoints(name, ns="default", ips=(), port=8080, port_name="http"):
    return {"kind": "Endpoints",
            "metadata": {"name": name, "namespace": ns},
            "subsets": [{"addresses": [{"ip": ip} for ip in ips],
                         "ports": [{"name": port_name, "port": port}]}]}


def _pod(name, ip, ns="default", labels=None, sa=""):
    return {"kind": "Pod",
            "metadata": {"name": name, "namespace": ns,
                         "labels": labels or {}},
            "spec": {"serviceAccountName": sa},
            "status": {"podIP": ip}}


# ---------------------------------------------------------------------------
# kube service registry
# ---------------------------------------------------------------------------

def test_kube_registry_conversion_and_watch():
    cluster = FakeKubeCluster()
    cluster.create(_svc("reviews", ports=[
        {"name": "http", "port": 9080},
        {"name": "grpc-status", "port": 9090},
        {"name": "metrics", "port": 15090}]))
    cluster.create(_endpoints("reviews", ips=["10.1.0.4"], port=9080))
    cluster.create(_pod("reviews-v1-x", "10.1.0.4",
                        labels={"app": "reviews", "version": "v1"},
                        sa="bookinfo-reviews"))
    reg = KubeServiceRegistry(cluster)

    svcs = reg.services()
    assert [s.hostname for s in svcs] == [
        "reviews.default.svc.cluster.local"]
    protos = {p.name: p.protocol for p in svcs[0].ports}
    assert protos == {"http": "HTTP", "grpc-status": "GRPC",
                      "metrics": "TCP"}   # bare name → TCP

    insts = reg.instances("reviews.default.svc.cluster.local", ("http",))
    assert len(insts) == 1
    assert insts[0].endpoint.address == "10.1.0.4"
    assert insts[0].endpoint.port == 9080
    assert insts[0].labels == {"app": "reviews", "version": "v1"}
    assert insts[0].service_account == \
        "spiffe://cluster.local/ns/default/sa/bookinfo-reviews"
    assert reg.get_istio_service_accounts(
        "reviews.default.svc.cluster.local", ("http",)) == [
        "spiffe://cluster.local/ns/default/sa/bookinfo-reviews"]

    # label-selected subset + host_instances
    assert reg.instances("reviews.default.svc.cluster.local",
                         labels={"version": "v2"}) == []
    assert len(reg.host_instances({"10.1.0.4"})) >= 1

    # live watch: a new service fires handlers and appears in reads
    events = []
    reg.append_service_handler(lambda svc, ev: events.append((svc.hostname,
                                                              ev)))
    cluster.create(_svc("ratings"))
    assert ("ratings.default.svc.cluster.local", "add") in events
    assert reg.get_service("ratings.default.svc.cluster.local")
    cluster.delete("Service", "default", "ratings")
    assert reg.get_service("ratings.default.svc.cluster.local") is None


# ---------------------------------------------------------------------------
# pilot CRD config client
# ---------------------------------------------------------------------------

def test_kube_config_store_watch_and_write():
    cluster = FakeKubeCluster()
    store = KubeConfigStore(cluster)
    seen = []
    store.register_handler(lambda c, ev: seen.append((c.meta.name, ev)))

    # write path (istioctl flow) → cluster → watch → cache
    store.create(Config(meta=ConfigMeta(type="route-rule", name="r1",
                                        namespace="default"),
                        spec={"destination": {"service": "x"},
                              "precedence": 1}))
    assert ("r1", "add") in seen
    assert store.get("route-rule", "r1", "default").spec["precedence"] == 1

    # out-of-band cluster write (kubectl flow) also lands in the cache
    cluster.create({"kind": "v1alpha2-route-rule",
                    "metadata": {"name": "vs", "namespace": "default"},
                    "spec": {"hosts": ["x"], "http": []}})
    assert store.list("v1alpha2-route-rule")[0].meta.name == "vs"

    store.delete("route-rule", "r1", "default")
    assert ("r1", "delete") in seen
    assert store.get("route-rule", "r1", "default") is None

    # invalid spec is rejected client-side before the cluster sees it
    with pytest.raises(Exception):
        store.create(Config(meta=ConfigMeta(type="route-rule", name="bad",
                                            namespace="default"),
                            spec={}))


# ---------------------------------------------------------------------------
# admission
# ---------------------------------------------------------------------------

def test_admission_rejects_bad_config():
    cluster = FakeKubeCluster()
    register_istio_admission(cluster)
    with pytest.raises(AdmissionDenied):
        cluster.create({"kind": "route-rule",
                        "metadata": {"name": "bad", "namespace": "d"},
                        "spec": {}})   # no destination
    with pytest.raises(AdmissionDenied):
        cluster.create({"kind": "rule",
                        "metadata": {"name": "bad", "namespace": "d"},
                        "spec": {"match": "@@@not an expression@@@",
                                 "actions": []}})
    with pytest.raises(AdmissionDenied):
        cluster.create({"kind": "handler",
                        "metadata": {"name": "h", "namespace": "d"},
                        "spec": {}})   # no adapter
    # valid writes pass
    cluster.create({"kind": "rule",
                    "metadata": {"name": "ok", "namespace": "d"},
                    "spec": {"match": 'source.namespace == "x"',
                             "actions": []}})


# ---------------------------------------------------------------------------
# mixer boots from cluster CRDs and reacts to watch events
# ---------------------------------------------------------------------------

def test_mixs_boots_from_cluster_crds():
    cluster = FakeKubeCluster()
    register_istio_admission(cluster)
    cluster.create({"kind": "handler",
                    "metadata": {"name": "denyall",
                                 "namespace": "istio-system"},
                    "spec": {"adapter": "denier",
                             "params": {"status_code": PERMISSION_DENIED}}})
    cluster.create({"kind": "instance",
                    "metadata": {"name": "nothing",
                                 "namespace": "istio-system"},
                    "spec": {"template": "checknothing", "params": {}}})
    cluster.create({"kind": "rule",
                    "metadata": {"name": "deny-admin",
                                 "namespace": "istio-system"},
                    "spec": {"match": 'request.path.startsWith("/admin")',
                             "actions": [{"handler": "denyall",
                                          "instances": ["nothing"]}]}})

    srv = RuntimeServer(CrdStore(cluster),
                        ServerArgs(batch_window_s=0.001))
    try:
        deny = srv.check(bag_from_mapping({"request.path": "/admin/x"}))
        assert deny.status_code == PERMISSION_DENIED
        ok = srv.check(bag_from_mapping({"request.path": "/ok"}))
        assert ok.status_code == OK

        # live config change via the cluster → debounced rebuild
        cluster.create({"kind": "rule",
                        "metadata": {"name": "deny-secret",
                                     "namespace": "istio-system"},
                        "spec": {
                            "match": 'request.path.startsWith("/secret")',
                            "actions": [{"handler": "denyall",
                                         "instances": ["nothing"]}]}})
        import time
        # generous: the debounced rebuild recompiles the snapshot and
        # jits fresh serving shapes — near-instant alone, but a loaded
        # 1-core CI box has exceeded 10s (observed flake)
        deadline = time.time() + 30
        while time.time() < deadline:
            r = srv.check(bag_from_mapping({"request.path": "/secret/x"}))
            if r.status_code == PERMISSION_DENIED:
                break
            time.sleep(0.05)
        else:
            raise AssertionError("CRD watch change never took effect")
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# ingress controller
# ---------------------------------------------------------------------------

def test_ingress_controller_emits_rules():
    cluster = FakeKubeCluster()
    store = MemoryConfigStore()
    IngressController(cluster, store)
    cluster.create({
        "kind": "Ingress",
        "metadata": {"name": "gw", "namespace": "default",
                     "annotations": {
                         "kubernetes.io/ingress.class": "istio"}},
        "spec": {"rules": [{
            "host": "bookinfo.example.com",
            "http": {"paths": [
                {"path": "/productpage",
                 "backend": {"serviceName": "productpage",
                             "servicePort": 9080}},
                {"path": "/static*",
                 "backend": {"serviceName": "productpage",
                             "servicePort": 9080}},
            ]}}]}})
    rules = store.list("ingress-rule")
    assert len(rules) == 2
    dests = {r.spec["destination"]["service"] for r in rules}
    assert dests == {"productpage.default.svc.cluster.local"}
    exact = next(r for r in rules
                 if r.spec["match"]["request"]["headers"]["uri"]
                 .get("exact"))
    assert exact.spec["match"]["request"]["headers"]["authority"] == \
        {"exact": "bookinfo.example.com"}

    # non-istio class ingresses are ignored; deletion retracts rules
    cluster.create({
        "kind": "Ingress",
        "metadata": {"name": "other", "namespace": "default",
                     "annotations": {
                         "kubernetes.io/ingress.class": "nginx"}},
        "spec": {"backend": {"serviceName": "x", "servicePort": 80}}})
    assert len(store.list("ingress-rule")) == 2
    cluster.delete("Ingress", "default", "gw")
    assert store.list("ingress-rule") == []


def test_ingress_status_syncer_writes_lb_status():
    """status.go analog: the syncer writes the gateway address into
    status.loadBalancer.ingress of watched Ingress resources (IP →
    `ip`, name → `hostname`), skips foreign classes, is idempotent
    (the self-triggered MODIFIED event terminates), and re-syncs a
    resource whose status was wiped by an update."""
    from istio_tpu.kube import IngressStatusSyncer

    cluster = FakeKubeCluster()
    # pre-existing ingress: the watch replay must sync it too
    cluster.create({
        "kind": "Ingress",
        "metadata": {"name": "pre", "namespace": "default"},
        "spec": {"backend": {"serviceName": "a", "servicePort": 80}}})
    IngressStatusSyncer(cluster, "203.0.113.7")
    got = cluster.get("Ingress", "default", "pre")
    assert got["status"]["loadBalancer"]["ingress"] == \
        [{"ip": "203.0.113.7"}]

    cluster.create({
        "kind": "Ingress",
        "metadata": {"name": "gw", "namespace": "default",
                     "annotations": {
                         "kubernetes.io/ingress.class": "istio"}},
        "spec": {"backend": {"serviceName": "b", "servicePort": 80}}})
    got = cluster.get("Ingress", "default", "gw")
    assert got["status"]["loadBalancer"]["ingress"] == \
        [{"ip": "203.0.113.7"}]
    rv_after_sync = got["metadata"]["resourceVersion"]

    # foreign class: never touched
    cluster.create({
        "kind": "Ingress",
        "metadata": {"name": "other", "namespace": "default",
                     "annotations": {
                         "kubernetes.io/ingress.class": "nginx"}},
        "spec": {"backend": {"serviceName": "x", "servicePort": 80}}})
    assert "status" not in cluster.get("Ingress", "default", "other")

    # idempotence: a status-only touch must not loop resourceVersions
    assert cluster.get("Ingress", "default", "gw")["metadata"][
        "resourceVersion"] == rv_after_sync

    # a spec update that drops status gets re-synced by the syncer
    cluster.update({
        "kind": "Ingress",
        "metadata": {"name": "gw", "namespace": "default",
                     "annotations": {
                         "kubernetes.io/ingress.class": "istio"}},
        "spec": {"backend": {"serviceName": "c", "servicePort": 81}}})
    got = cluster.get("Ingress", "default", "gw")
    assert got["status"]["loadBalancer"]["ingress"] == \
        [{"ip": "203.0.113.7"}]

    # hostname addresses write the hostname field (status.go shape)
    cluster2 = FakeKubeCluster()
    IngressStatusSyncer(cluster2, "gw.example.com")
    cluster2.create({
        "kind": "Ingress",
        "metadata": {"name": "h", "namespace": "default"},
        "spec": {"backend": {"serviceName": "y", "servicePort": 80}}})
    assert cluster2.get("Ingress", "default", "h")["status"][
        "loadBalancer"]["ingress"] == \
        [{"hostname": "gw.example.com"}]


# ---------------------------------------------------------------------------
# SA → workload-cert secrets
# ---------------------------------------------------------------------------

def test_service_account_secret_controller():
    # the SA-secret controller needs the PKI stack; containers without
    # `cryptography` keep the REST of this module's coverage (config
    # watch, registries, admission) instead of dying at collection
    pytest.importorskip("cryptography")
    from istio_tpu.kube import ServiceAccountSecretController
    from istio_tpu.security import IstioCA
    from istio_tpu.security.pki import load_cert, san_uris, verify_chain

    cluster = FakeKubeCluster()
    ca = IstioCA.new_self_signed({})
    ServiceAccountSecretController(cluster, ca)
    cluster.create({"kind": "ServiceAccount",
                    "metadata": {"name": "bookinfo-productpage",
                                 "namespace": "default"}})
    secret = cluster.get("Secret", "default",
                         "istio.bookinfo-productpage.default")
    assert secret is not None and secret["type"] == "istio.io/key-and-cert"
    cert = base64.b64decode(secret["data"]["cert-chain.pem"])
    root = base64.b64decode(secret["data"]["root-cert.pem"])
    assert verify_chain(cert, root)
    assert san_uris(load_cert(cert)) == [
        "spiffe://cluster.local/ns/default/sa/bookinfo-productpage"]

    cluster.delete("ServiceAccount", "default", "bookinfo-productpage")
    assert cluster.get("Secret", "default",
                       "istio.bookinfo-productpage.default") is None


# ---------------------------------------------------------------------------
# pilot-discovery boots from the cluster (registry + CRD config)
# ---------------------------------------------------------------------------

def test_pilot_discovery_from_cluster():
    import json

    from istio_tpu.pilot.discovery import DiscoveryService

    cluster = FakeKubeCluster()
    cluster.create(_svc("productpage", ports=[
        {"name": "http", "port": 9080}]))
    cluster.create(_endpoints("productpage", ips=["10.1.0.7"], port=9080))
    cluster.create(_pod("productpage-v1", "10.1.0.7",
                        labels={"app": "productpage"}))
    reg = KubeServiceRegistry(cluster)
    config = KubeConfigStore(cluster)
    ds = DiscoveryService(reg, config)

    eps = json.loads(ds.list_endpoints(
        "productpage.default.svc.cluster.local|http"))
    assert eps["hosts"][0]["ip_address"] == "10.1.0.7"

    # a cluster event invalidates the whole discovery cache
    assert ds.cache_size > 0
    cluster.create(_svc("details", cluster_ip="10.0.0.9"))
    assert ds.cache_size == 0
    eps2 = json.loads(ds.list_endpoints(
        "details.default.svc.cluster.local|http"))
    assert eps2["hosts"] == []


def test_sidecar_injection_webhook():
    """Mutating admission (inject/webhook.go role): pods created on
    the cluster come back with the sidecar injected, respecting the
    per-pod annotation opt-out."""
    from istio_tpu.kube.admission import register_sidecar_injector

    cluster = FakeKubeCluster()
    register_sidecar_injector(cluster, namespaces=("default",))
    created = cluster.create(_pod("web-1", "10.0.0.5"))
    names = [c["name"] for c in created["spec"]["containers"]]
    assert "istio-proxy" in names
    assert created["metadata"]["annotations"][
        "sidecar.istio.io/status"] == "injected"
    assert created["spec"]["initContainers"]

    # opt-out annotation wins
    opt_out = _pod("web-2", "10.0.0.6")
    opt_out["metadata"]["annotations"] = {
        "sidecar.istio.io/inject": "false"}
    created2 = cluster.create(opt_out)
    assert all(c["name"] != "istio-proxy"
               for c in created2["spec"].get("containers", ()))

    # other namespaces untouched
    created3 = cluster.create(_pod("web-3", "10.0.0.7", ns="prod"))
    assert all(c["name"] != "istio-proxy"
               for c in created3["spec"].get("containers", ()))
