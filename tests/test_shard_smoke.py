"""Tier-1 hook for scripts/shard_smoke.py: the CI gate that a
SEEDED ≥100k-rule fleet snapshot compiles into namespace shards and
serves through the replica-parallel router over a real gRPC front
with EXACT SnapshotOracle parity, zero dropped/misrouted rows, sane
LPT balance, and an agreeing /debug/shards view. Runs main()
in-process at the FULL 100k scale — the capacity claim IS the gate
(ROADMAP item 3's done-bar), not a scaled-down stand-in."""
import importlib.util
import os
import sys


def _load():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "shard_smoke.py")
    spec = importlib.util.spec_from_file_location("shard_smoke", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def test_shard_smoke_main_100k():
    mod = _load()
    try:
        rc = mod.main(n_rules=100_000, n_namespaces=512, shards=8,
                      replicas=2, n_checks=48)
    finally:
        sys.modules.pop("shard_smoke", None)
    assert rc == 0
