"""Multi-chip sharding: the dp×mp-sharded fused check step must produce
bit-identical verdicts to the single-device step, on an 8-virtual-device
CPU mesh (conftest.py forces xla_force_host_platform_device_count=8)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from istio_tpu.parallel.mesh import MeshSpec, shard_engine_check
from istio_tpu.testing import workloads


@pytest.mark.parametrize("dp,mp", [(8, 1), (4, 2), (2, 4)])
def test_sharded_check_matches_unsharded(dp, mp):
    if len(jax.devices()) < dp * mp:
        pytest.skip("needs 8 devices")
    engine = workloads.make_engine(n_rules=64, jit=False)
    b = 2 * dp
    bags = workloads.make_bags(b)
    batch = engine.tensorizer.tensorize(bags)
    req_ns = workloads.make_request_ns(engine, b)

    ref_v, ref_counts = engine.raw_step(engine.params, batch, req_ns,
                                        engine.quota_counts)

    mesh = MeshSpec(dp=dp, mp=mp).build()
    step = shard_engine_check(mesh, engine)
    v, counts = step(engine.params, batch, req_ns, engine.quota_counts)

    np.testing.assert_array_equal(np.asarray(v.status),
                                  np.asarray(ref_v.status))
    np.testing.assert_array_equal(np.asarray(v.matched),
                                  np.asarray(ref_v.matched))
    np.testing.assert_array_equal(np.asarray(counts),
                                  np.asarray(ref_counts))
    # rules really live on the mp axis
    assert v.matched.sharding.spec == jax.sharding.PartitionSpec("dp", "mp")


# ---------------------------------------------------------------------------
# sequence-parallel DFA matching (long-context byte path)
# ---------------------------------------------------------------------------

def test_sequence_parallel_dfa_matches_oracle():
    """A 1KB string sharded over 8 virtual devices must match exactly
    like the single-device DFA and the host regex."""
    import re
    from jax.sharding import Mesh
    from istio_tpu.ops.bytes_ops import dfa_match
    from istio_tpu.ops.regex_dfa import compile_regex
    from istio_tpu.parallel.seq_match import sharded_dfa_match

    devices = np.array(jax.devices("cpu")[:8])
    mesh = Mesh(devices, ("sp",))

    rng = np.random.default_rng(7)
    chunk = 128
    total = 8 * chunk
    # needle fully inside one chunk, straddling a chunk boundary,
    # absent, at the very end, and empty-tail rows
    base = rng.integers(97, 123, total, dtype=np.uint8)
    s1 = base.copy(); s1[300:309] = np.frombuffer(b"needle-42", np.uint8)
    s2 = base.copy(); s2[chunk - 4:chunk + 5] = np.frombuffer(
        b"needle-42", np.uint8)
    s3 = base.copy()
    s4 = base.copy(); s4[total - 9:] = np.frombuffer(b"needle-42",
                                                    np.uint8)
    subjects = np.stack([s1, s2, s3, s4])
    lens = np.array([total, total, total, total - 40], np.int32)

    for pattern in ("needle-[0-9]+", "^[a-z]", "xyzzy$"):
        dfa = compile_regex(pattern)
        data = subjects.reshape(4, 8, chunk)
        got = np.asarray(sharded_dfa_match(
            mesh, "sp", data, lens, dfa.transitions, dfa.accept))
        # single-device reference over the full rows
        want_dev = np.asarray(dfa_match(
            jnp.asarray(subjects), jnp.asarray(lens),
            jnp.asarray(dfa.transitions), jnp.asarray(dfa.accept)))
        want_re = np.array([
            re.search(pattern,
                      subjects[i, :lens[i]].tobytes().decode("latin1"))
            is not None for i in range(4)])
        np.testing.assert_array_equal(got, want_dev)
        np.testing.assert_array_equal(got, want_re)
        # several chunks PER DEVICE: 16 chunks over the 8-way axis
        data16 = subjects.reshape(4, 16, chunk // 2)
        got16 = np.asarray(sharded_dfa_match(
            mesh, "sp", data16, lens, dfa.transitions, dfa.accept))
        np.testing.assert_array_equal(got16, want_re)


def test_chunk_transition_map_composes():
    """Map composition over split halves equals one scan over the
    whole string (the associativity the sharding relies on)."""
    from istio_tpu.ops.regex_dfa import compile_regex
    from istio_tpu.parallel.seq_match import (chunk_transition_map,
                                              compose_maps)

    dfa = compile_regex("ab+c")
    text = b"zzabbbczz"
    row = np.frombuffer(text, np.uint8)[None, :]
    full = chunk_transition_map(jnp.asarray(row),
                                jnp.asarray([len(text)], np.int32),
                                jnp.asarray(dfa.transitions))
    left, right = row[:, :4], row[:, 4:]
    m1 = chunk_transition_map(jnp.asarray(left),
                              jnp.asarray([4], np.int32),
                              jnp.asarray(dfa.transitions))
    m2 = chunk_transition_map(jnp.asarray(right),
                              jnp.asarray([len(text) - 4], np.int32),
                              jnp.asarray(dfa.transitions))
    composed = compose_maps(jnp.stack([m1, m2]))
    np.testing.assert_array_equal(np.asarray(full), np.asarray(composed))
