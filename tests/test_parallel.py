"""Multi-chip sharding: the dp×mp-sharded fused check step must produce
bit-identical verdicts to the single-device step, on an 8-virtual-device
CPU mesh (conftest.py forces xla_force_host_platform_device_count=8)."""
import jax
import numpy as np
import pytest

from istio_tpu.parallel.mesh import MeshSpec, shard_engine_check
from istio_tpu.testing import workloads


@pytest.mark.parametrize("dp,mp", [(8, 1), (4, 2), (2, 4)])
def test_sharded_check_matches_unsharded(dp, mp):
    if len(jax.devices()) < dp * mp:
        pytest.skip("needs 8 devices")
    engine = workloads.make_engine(n_rules=64, jit=False)
    b = 2 * dp
    bags = workloads.make_bags(b)
    batch = engine.tensorizer.tensorize(bags)
    req_ns = workloads.make_request_ns(engine, b)

    ref_v, ref_counts = engine.raw_step(engine.params, batch, req_ns,
                                        engine.quota_counts)

    mesh = MeshSpec(dp=dp, mp=mp).build()
    step = shard_engine_check(mesh, engine)
    v, counts = step(engine.params, batch, req_ns, engine.quota_counts)

    np.testing.assert_array_equal(np.asarray(v.status),
                                  np.asarray(ref_v.status))
    np.testing.assert_array_equal(np.asarray(v.matched),
                                  np.asarray(ref_v.matched))
    np.testing.assert_array_equal(np.asarray(counts),
                                  np.asarray(ref_counts))
    # rules really live on the mp axis
    assert v.matched.sharding.spec == jax.sharding.PartitionSpec("dp", "mp")
