"""Tier-1 hook for scripts/hotpath_lint.py: the AST lint that bans
host-sync calls (`.item()`, un-annotated `np.asarray` pulls,
`float(<call>)`, blocking I/O) inside the serving batch-build/step
sections of runtime/{batcher,dispatcher,fused}.py. Two assertions:
the repo's hot sections are clean (every deliberate boundary crossing
carries its `# hotpath: sync-ok` pragma), and the lint actually
DETECTS each banned pattern on a synthetic module — a gate that can't
fail is no gate."""
import importlib.util
import os
import sys

import pytest


@pytest.fixture(scope="module")
def lint():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "hotpath_lint.py")
    spec = importlib.util.spec_from_file_location("hotpath_lint", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
        yield mod
    finally:
        sys.modules.pop(spec.name, None)


def test_repo_hot_sections_clean(lint):
    assert lint.main() == 0


BAD = '''
import numpy as np
import time

class Worker:
    def hot(self, dev, xs):
        a = dev.item()                      # sync
        b = np.asarray(dev)                 # un-annotated pull
        c = float(dev.sum())                # cast over a call
        time.sleep(0.1)                     # blocking
        print("log")                        # blocking
        ok = np.asarray([1, 2, 3])          # list literal: allowed
        annotated = np.asarray(dev)         # hotpath: sync-ok
        return a, b, c, ok, annotated

    def cold(self, dev):
        return np.asarray(dev)              # not a hot function
'''


def test_lint_detects_banned_patterns(lint):
    vs = lint.lint_source(BAD, frozenset({"Worker.hot"}), "bad.py")
    messages = [v.message for v in vs]
    assert any(".item()" in m for m in messages)
    assert any("pulls device buffers" in m for m in messages)
    assert any("float(<call>)" in m for m in messages)
    assert any("time.sleep" in m for m in messages)
    assert any("print" in m for m in messages)
    # pragma'd + list-literal + cold-function calls stay silent
    assert all(v.func == "Worker.hot" for v in vs)
    assert len([m for m in messages if "pulls device" in m]) == 1


def test_lint_config_tracks_renames(lint):
    # a hot name that no longer exists must fail the gate loudly
    vs = lint.lint_source("def other(): pass",
                          frozenset({"Worker.gone"}), "x.py")
    assert vs == []          # lint_source only checks existing defs
    # main()-level missing-function detection is covered by running
    # main() against the real tree in test_repo_hot_sections_clean
