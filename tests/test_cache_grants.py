"""Server-issued check-cache grants (ISSUE 13): GrantPolicy unit
semantics, grant-clamped serving TTLs, a MixerClient seeing ≥90%
cache hits on repeat traffic, and REVOCATION — a config delta that
flips the cached verdict drops the TTL floor within one generation,
so the stale client verdict dies inside its (shortened) budget."""
import time

import pytest

from istio_tpu.api import MixerClient, MixerGrpcServer
from istio_tpu.models.policy_engine import OK, PERMISSION_DENIED
from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs
from istio_tpu.runtime.grants import GrantPolicy

DENY_PATH = {"destination.service": "web.prod.svc.cluster.local",
             "request.path": "/admin/keys"}
OPEN_PATH = {"destination.service": "web.prod.svc.cluster.local",
             "request.path": "/api/items"}


def _store() -> MemStore:
    s = MemStore()
    s.set(("handler", "istio-system", "denyadmin"), {
        "adapter": "denier",
        "params": {"status_code": PERMISSION_DENIED,
                   "status_message": "admin is off limits",
                   "valid_duration_s": 600.0,
                   "valid_use_count": 100000}})
    s.set(("instance", "istio-system", "nothing"), {
        "template": "checknothing", "params": {}})
    s.set(("rule", "istio-system", "r-deny"), {
        "match": 'request.path.startsWith("/admin")',
        "actions": [{"handler": "denyadmin",
                     "instances": ["nothing"]}]})
    return s


# ---------------------------------------------------------------------------
# policy unit semantics
# ---------------------------------------------------------------------------


def test_policy_floor_ramp_cap_and_quantum():
    p = GrantPolicy(ttl_floor_s=1.0, ttl_cap_s=5.0,
                    ttl_ramp_per_s=2.0, quantum_s=0.0)
    ttl0, uses0 = p.grant("ns1")
    assert ttl0 == pytest.approx(1.0, abs=0.1), \
        "fresh policy starts at the floor"
    assert uses0 >= p.use_floor
    # fake age by rewinding the change instant
    p._global_change -= 10.0
    ttl1, uses1 = p.grant("ns1")
    assert ttl1 == 5.0, "ramp must saturate at the cap"
    assert uses1 == p.use_cap
    # quantization: ages within one quantum emit IDENTICAL grants
    # (response memos and parity surfaces rely on step-stable TTLs)
    q = GrantPolicy(quantum_s=0.5)
    q._global_change -= 0.2
    a = q.grant("x")
    q._global_change -= 0.2     # still inside the first quantum
    assert q.grant("x") == a


def test_policy_per_namespace_revocation():
    p = GrantPolicy(ttl_floor_s=1.0, ttl_cap_s=5.0,
                    ttl_ramp_per_s=2.0, quantum_s=0.0)
    p._global_change -= 100.0
    assert p.grant("a")[0] == 5.0 and p.grant("b")[0] == 5.0
    p.on_publish({"a"})         # delta touched only namespace a
    ttl_a, _ = p.grant("a")
    ttl_b, _ = p.grant("b")
    assert ttl_a == pytest.approx(1.0, abs=0.1), \
        "changed namespace drops to the floor"
    assert ttl_b == 5.0, "untouched namespace keeps its grant"
    p.on_publish(None)          # unattributed publish: revoke all
    assert p.grant("b")[0] == pytest.approx(1.0, abs=0.1)
    assert p.generation == 2
    st = p.stats()
    assert st["revocations"] == 2 and st["grants_issued"] >= 5


# ---------------------------------------------------------------------------
# served grants + client cache e2e
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def rig():
    srv = RuntimeServer(_store(), ServerArgs(
        batch_window_s=0.001, max_batch=64,
        check_grants=True,
        grant_ttl_floor_s=0.3, grant_ttl_cap_s=1.2,
        grant_ttl_ramp_per_s=2.0))
    front = MixerGrpcServer(srv)
    port = front.start()
    yield srv, port
    front.stop()
    srv.close()


def test_serving_emits_grant_clamped_ttls(rig):
    srv, port = rig
    client = MixerClient(f"127.0.0.1:{port}",
                         enable_check_cache=False)
    try:
        ok = client.check(dict(OPEN_PATH))
        assert ok.precondition.status.code == OK
        ttl = ok.precondition.valid_duration.ToTimedelta() \
            .total_seconds()
        assert 0.3 <= ttl <= 1.2, \
            f"grant must clamp the TTL into [floor, cap], got {ttl}"
        assert 0 < ok.precondition.valid_use_count <= 10000
        deny = client.check(dict(DENY_PATH))
        assert deny.precondition.status.code == PERMISSION_DENIED
        dttl = deny.precondition.valid_duration.ToTimedelta() \
            .total_seconds()
        assert dttl <= 1.2, \
            "the denier's 600s TTL must be grant-clamped too " \
            "(a cached DENY must be revocable)"
    finally:
        client.close()


def test_client_cache_hit_rate_ge_90pct(rig):
    srv, port = rig
    client = MixerClient(f"127.0.0.1:{port}", enable_check_cache=True)
    try:
        client.check(dict(OPEN_PATH))          # prime
        n = 200
        for _ in range(n):
            r = client.check(dict(OPEN_PATH))
            assert r.precondition.status.code == OK
        stats = client.cache_stats
        total = stats["hits"] + stats["misses"]
        rate = stats["hits"] / max(total, 1)
        assert rate >= 0.90, f"cache stats {stats}: hit rate {rate}"
    finally:
        client.close()


def test_delta_revokes_flipped_verdict_within_one_generation(rig):
    """The revocation leg end to end: a caching client holds a DENY
    verdict; a config delta deletes the deny rule (flipping the
    verdict to OK). The grant policy revokes on the delta's publish,
    so (a) the flip is OBSERVED by the client within the pre-delta
    TTL cap of the new generation going live — the stale grant
    cannot outlive one generation — and (b) responses served by the
    new generation carry the TTL floor."""
    srv, port = rig
    client = MixerClient(f"127.0.0.1:{port}", enable_check_cache=True)
    # cache-bypassing probe client, created (and its channel warmed)
    # BEFORE the delta so the post-revocation TTL read below lands
    # inside the first grant age quantum even on a loaded box
    raw = MixerClient(f"127.0.0.1:{port}", enable_check_cache=False)
    store = srv.controller.store
    try:
        raw.check(dict(OPEN_PATH))
        deny = client.check(dict(DENY_PATH))
        assert deny.precondition.status.code == PERMISSION_DENIED
        # cached: an immediate re-check must not cross the wire
        wire0 = client.cache_stats["misses"]
        assert client.check(dict(DENY_PATH)) \
            .precondition.status.code == PERMISSION_DENIED
        assert client.cache_stats["misses"] == wire0, \
            "deny verdict must be cacheable for this test to bite"
        gen0 = srv.grants.generation
        rev0 = srv.controller.dispatcher.snapshot.revision
        store.delete(("rule", "istio-system", "r-deny"))
        # wait for the delta generation to go LIVE (dispatcher swap
        # AND the grant revocation that follows it)
        deadline = time.time() + 30.0
        while time.time() < deadline:
            if srv.controller.dispatcher.snapshot.revision != rev0 \
                    and srv.grants.generation > gen0:
                break
            time.sleep(0.02)
        t_live = time.time()
        assert srv.grants.generation > gen0, \
            "publish must revoke (GrantPolicy.on_publish)"
        # (b) first: responses served by the new generation carry the
        # TTL floor (checked with the pre-warmed cache-bypassing
        # client IMMEDIATELY after the revocation, inside the first
        # age quantum)
        fresh = raw.check(dict(OPEN_PATH))
        ttl = fresh.precondition.valid_duration.ToTimedelta() \
            .total_seconds()
        # bounded by the policy's quantized ramp at the OBSERVED
        # revocation age (floor exactly when inside the first
        # quantum; a loaded runner that slips a quantum still gets a
        # tight, honest bound instead of a race)
        g = srv.grants
        age_q = (g.stats()["global_age_s"] // g.quantum_s) \
            * g.quantum_s
        allowed = min(g.ttl_cap_s,
                      g.ttl_floor_s + age_q * g.ttl_ramp_per_s)
        assert ttl <= allowed + 0.05, \
            f"post-delta grant {ttl} exceeds revoked ramp bound " \
            f"{allowed} (revocation broken)"
        # (a) the caching client must observe the FLIP within the
        # pre-delta TTL cap (1.2s) of the generation going live: its
        # cached entry was granted at most cap seconds of budget
        flipped_at = None
        while time.time() < t_live + 1.2 + 1.0:
            r = client.check(dict(DENY_PATH))
            if r.precondition.status.code == OK:
                flipped_at = time.time()
                break
            time.sleep(0.05)
        assert flipped_at is not None, \
            "stale DENY outlived the revocation window"
        assert flipped_at - t_live <= 1.2 + 1.0
    finally:
        raw.close()
        client.close()
