"""Tier-1 hook for scripts/delta_smoke.py: the CI gate that config
churn is delta-compiled on a sharded snapshot — a one-namespace
constant edit republishes by rebuilding exactly one bank (the other
K-1 carried as the same objects under a byte-stable plan), the probe
flip proves the delta took effect, the sharded path stays EXACTLY
oracle-parity over the real gRPC front before and after, and a
simulated restart with the warm persistent XLA cache serves with
zero cache misses. Runs main() in-process at the issue's platform
scale (100k rules tpu / 4k cpu — resolved inside main())."""
import importlib.util
import os
import sys


def _load():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "delta_smoke.py")
    spec = importlib.util.spec_from_file_location("delta_smoke", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def test_delta_smoke_main():
    mod = _load()
    try:
        rc = mod.main()
    finally:
        sys.modules.pop("delta_smoke", None)
    assert rc == 0
