"""Attribute bags + dictionary-compressed wire codec round-trips
(reference behavior: mixer/pkg/attribute bag_test/mutableBag tests)."""
import datetime

from istio_tpu.attribute.bag import DictBag, MutableBag, TrackingBag
from istio_tpu.attribute.compressed import (CompressedAttributes, decode,
                                            decode_deltas, encode)
from istio_tpu.attribute.global_dict import GLOBAL_WORD_INDEX, GLOBAL_WORD_LIST


def test_global_dictionary_protocol_constants():
    # wire-compat anchors: canonical words at fixed indices
    assert len(GLOBAL_WORD_LIST) == 169
    assert GLOBAL_WORD_LIST[0] == "source.ip"
    assert "request.headers" in GLOBAL_WORD_INDEX
    assert GLOBAL_WORD_INDEX[GLOBAL_WORD_LIST[42]] == 42


def test_mutable_bag_overlay_and_merge():
    parent = DictBag({"a": 1, "b": 2})
    child = MutableBag(parent)
    child.set("b", 20)
    child.set("c", 30)
    assert child.get("a") == (1, True)
    assert child.get("b") == (20, True)
    assert child.get("c") == (30, True)
    assert sorted(child.names()) == ["a", "b", "c"]

    # preserve_merge must NOT clobber existing values
    child.preserve_merge(DictBag({"a": 99, "d": 4}))
    assert child.get("a") == (1, True)
    assert child.get("d") == (4, True)


def test_tracking_bag_records_conditions():
    tb = TrackingBag(DictBag({"x": 1}))
    tb.get("x")
    tb.get("missing")
    tb.track_map_key("request.headers", "host", True)
    refs = tb.referenced()
    assert refs[("x", "")] == "EXACT"
    assert refs[("missing", "")] == "ABSENCE"
    assert refs[("request.headers", "host")] == "EXACT"
    assert tb.referenced_names() == ["missing", "request.headers[host]", "x"]


def test_wire_roundtrip_all_types():
    now = datetime.datetime(2026, 1, 2, 3, 4, 5,
                            tzinfo=datetime.timezone.utc)
    values = {
        "source.ip": b"\x0a\x00\x00\x01",           # global word
        "source.name": "productpage",                # global word, string val
        "request.size": 1234,
        "custom.double": 2.5,                        # message word
        "custom.flag": True,
        "request.time": now,
        "response.duration": datetime.timedelta(milliseconds=150),
        "request.headers": {"host": "example.com", "x-custom": "v"},
    }
    ca = encode(DictBag(values))
    # global words must NOT appear in the per-message word list
    assert "source.ip" not in ca.words
    assert "custom.double" in ca.words
    bag = decode(ca)
    for k, v in values.items():
        got, ok = bag.get(k)
        assert ok, k
        assert got == v, k


def test_delta_decoding_report_stream():
    r1 = encode(DictBag({"a.one": 1, "a.two": "x"}))
    r2 = encode(DictBag({"a.one": 2}))  # delta: only the changed attr
    bags = decode_deltas([r1, r2])
    assert bags[0].get("a.one") == (1, True)
    assert bags[1].get("a.one") == (2, True)
    assert bags[1].get("a.two") == ("x", True)  # carried forward


def test_utils_smoke():
    from istio_tpu.utils.cache import LRUCache, TTLCache
    from istio_tpu.utils.metrics import Registry

    lru = LRUCache(2)
    lru.set("a", 1)
    lru.set("b", 2)
    lru.get("a")
    lru.set("c", 3)          # evicts b
    assert lru.get("b") is None
    assert lru.get("a") == 1

    clock = [0.0]
    ttl = TTLCache(10.0, clock=lambda: clock[0])
    ttl.set("k", "v")
    assert ttl.get("k") == "v"
    clock[0] = 11.0
    assert ttl.get("k") is None

    reg = Registry()
    c = reg.counter("checks_total")
    c.inc(5, adapter="denier")
    h = reg.histogram("check_seconds")
    h.observe(0.0004)
    text = reg.expose_text()
    assert 'checks_total{adapter="denier"} 5.0' in text
    assert "check_seconds_bucket" in text
