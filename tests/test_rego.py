"""Rego-subset evaluator vs the reference opa adapter's own policy
corpus (mixer/adapter/opa/opa_test.go:180-340)."""
import pytest

from istio_tpu.adapters.rego import RegoEngine, RegoError, parse_module

BUCKET_POLICY = """package mixerauthz
    policy = [
      {
        "rule": {
          "verbs": [
            "storage.buckets.get"
          ],
          "users": [
            "bucket-admins"
          ]
        }
      }
    ]

    default allow = false

    allow = true {
      rule = policy[_].rule
      input.subject.user = rule.users[_]
      input.action.method = rule.verbs[_]
    }"""


EXAMPLE = """
    package example
    import data.service_graph
    import data.org_chart

    # Deny request by default.
    default allow = false

    # Allow request if...
    allow {
        service_graph.allow  # service graph policy allows, and...
        org_chart.allow      # org chart policy allows.
    }
"""

ORG_CHART = """
    package org_chart

    parsed_path = p {
        trim(input.action.path, "/", trimmed)
        split(trimmed, "/", p)
    }

    employees = {
        "bob": {"manager": "janet", "roles": ["engineering"]},
        "alice": {"manager": "janet", "roles": ["engineering"]},
        "janet": {"roles": ["engineering"]},
        "ken": {"roles": ["hr"]},
    }

    # Allow access to non-sensitive APIs.
    allow { not is_sensitive_api }

    is_sensitive_api {
        parsed_path[0] = "reviews"
    }

    allow {
        parsed_path = ["reviews", user]
        input.subject.user = user
    }

    allow {
        parsed_path = ["reviews", user]
        input.subject.user = employees[user].manager
    }

    allow {
        is_hr
    }

    is_hr {
        employees[input.subject.user].roles[_] = "hr"
    }
"""

SERVICE_GRAPH = """
    package service_graph

    service_graph = {
        "landing_page": ["details", "reviews"],
        "reviews": ["ratings"],
    }

    default allow = false

    allow {
        input.action.properties.target = "landing_page"
    }

    allow {
        allowed_targets = service_graph[input.action.properties.source]
        input.action.properties.target = allowed_targets[_]
    }
"""


def test_bucket_admin_policy():
    eng = RegoEngine([BUCKET_POLICY])
    allow = eng.query("data.mixerauthz.allow", {
        "subject": {"user": "bucket-admins"},
        "action": {"method": "storage.buckets.get"}})
    assert allow is True
    deny = eng.query("data.mixerauthz.allow", {
        "subject": {"user": "someone-else"},
        "action": {"method": "storage.buckets.get"}})
    assert deny is False
    deny2 = eng.query("data.mixerauthz.allow", {
        "subject": {"user": "bucket-admins"},
        "action": {"method": "storage.buckets.delete"}})
    assert deny2 is False


@pytest.fixture(scope="module")
def example_engine():
    return RegoEngine([EXAMPLE, ORG_CHART, SERVICE_GRAPH])


def _q(eng, user, source, target, path):
    return eng.query("data.example.allow", {
        "subject": {"user": user},
        "action": {"path": path,
                   "properties": {"source": source, "target": target}}})


def test_example_service_graph_and_org_chart(example_engine):
    eng = example_engine
    # landing_page target is always allowed by service graph; /health
    # is a non-sensitive API
    assert _q(eng, "bob", "gateway", "landing_page", "/health") is True
    # landing_page → reviews edge exists; non-sensitive path
    assert _q(eng, "bob", "landing_page", "reviews", "/health") is True
    # no edge details → ratings
    assert _q(eng, "bob", "details", "ratings", "/health") is False
    # sensitive API: /reviews/bob readable by bob himself
    assert _q(eng, "bob", "landing_page", "reviews",
              "/reviews/bob") is True
    # ...and by bob's manager janet
    assert _q(eng, "janet", "landing_page", "reviews",
              "/reviews/bob") is True
    # ...but not by alice (peer, not manager)
    assert _q(eng, "alice", "landing_page", "reviews",
              "/reviews/bob") is False
    # HR sees everything
    assert _q(eng, "ken", "landing_page", "reviews",
              "/reviews/bob") is True


def test_parse_errors_reported():
    with pytest.raises(RegoError, match="rego_parse_error"):
        parse_module("package p\n@@@")
    with pytest.raises(RegoError):
        RegoEngine([""])
    with pytest.raises(RegoError, match="rego_parse_error"):
        # the reference's invalid-syntax case: a rule assignment with
        # a dangling body brace
        RegoEngine(["package mixerauthz\nallow = true {"])


def test_rule_semantics():
    eng = RegoEngine(["""package t
        default d = false
        d { input.x = 1 }
        const = "k"
        multi { input.a = 1 }
        multi { input.b = 2 }
        val = v { split(input.s, ",", parts); parts[1] = v }
    """])
    assert eng.query("data.t.d", {"x": 1}) is True
    assert eng.query("data.t.d", {"x": 2}) is False
    assert eng.query("data.t.const", {}) == "k"
    assert eng.query("data.t.multi", {"b": 2}) is True
    assert eng.query("data.t.multi", {"c": 3}) is None   # undefined
    assert eng.query("data.t.val", {"s": "a,b,c"}) == "b"


def test_negation_and_builtins():
    eng = RegoEngine(["""package t
        allow { not blocked }
        blocked { input.user = "evil" }
        pre { startswith(input.path, "/api") }
        low = out { lower(input.name, out) }
        n = c { count(input.items, c) }
    """])
    assert eng.query("data.t.allow", {"user": "good"}) is True
    assert eng.query("data.t.allow", {"user": "evil"}) is None
    assert eng.query("data.t.pre", {"path": "/api/x"}) is True
    assert eng.query("data.t.low", {"name": "ABC"}) == "abc"
    assert eng.query("data.t.n", {"items": [1, 2, 3]}) == 3


def test_recursion_guard():
    eng = RegoEngine(["package t\na { b }\nb { a }"])
    with pytest.raises(RegoError, match="recursion"):
        eng.query("data.t.a", {})


def test_complete_rule_conflict_raises():
    """OPA eval_conflict_error semantics (ADVICE r2): two successful
    definitions with disagreeing values must error (fail-closed in the
    opa adapter), never silently return the first."""
    eng = RegoEngine(["""package t
        v = 1 { input.x = 1 }
        v = 2 { input.x = 1 }
        agree = 1 { input.x = 1 }
        agree = 1 { input.y = 2 }
    """])
    with pytest.raises(RegoError, match="conflict"):
        eng.query("data.t.v", {"x": 1})
    # conflicts ACROSS BINDINGS of one body are conflicts too:
    # p = x { x = input.arr[_] } over [1, 2] has two values in OPA
    eng_b = RegoEngine(["package t\np = x { x = input.arr[_] }"])
    with pytest.raises(RegoError, match="conflict"):
        eng_b.query("data.t.p", {"arr": [1, 2]})
    assert eng_b.query("data.t.p", {"arr": [3, 3]}) == 3
    # agreeing values are not a conflict
    assert eng.query("data.t.agree", {"x": 1, "y": 2}) == 1
    # only one definition fires: no conflict either
    eng2 = RegoEngine(["""package t
        v = 1 { input.x = 1 }
        v = 2 { input.x = 2 }
    """])
    assert eng2.query("data.t.v", {"x": 2}) == 2


def test_rule_memoization_is_per_query():
    """Cross-rule references re-use the memoized value inside one query
    but never leak it across queries with different inputs."""
    eng = RegoEngine(["""package t
        base = v { split(input.s, ",", parts); parts[0] = v }
        a { base = "x" }
        b { base = "x"; a }
    """])
    assert eng.query("data.t.b", {"s": "x,y"}) is True
    assert eng.query("data.t.b", {"s": "z,y"}) is None


# ---------------------------------------------------------------------------
# opa adapter integration (opa.go HandleAuthorization semantics)
# ---------------------------------------------------------------------------

def _opa(config):
    from istio_tpu.adapters.opa import OpaBuilder
    from istio_tpu.adapters.sdk import Env
    b = OpaBuilder(config, Env("test"))
    errs = b.validate()
    assert not errs, errs
    return b.build()


def test_opa_adapter_rego_mode():
    h = _opa({"policies": [BUCKET_POLICY],
              "check_method": "data.mixerauthz.allow"})
    ok = h.handle_check("authorization", {
        "subject": {"user": "bucket-admins"},
        "action": {"method": "storage.buckets.get"}})
    assert ok.status_code == 0
    deny = h.handle_check("authorization", {
        "subject": {"user": "stranger"},
        "action": {"method": "storage.buckets.get"}})
    assert deny.status_code == 7
    assert "opa: request was rejected" in deny.status_message


def test_opa_adapter_example_corpus():
    h = _opa({"policies": [EXAMPLE, ORG_CHART, SERVICE_GRAPH],
              "check_method": "data.example.allow"})

    def check(user, source, target, path):
        return h.handle_check("authorization", {
            "subject": {"user": user},
            "action": {"path": path,
                       "properties": {"source": source,
                                      "target": target}}}).status_code

    assert check("bob", "gateway", "landing_page", "/health") == 0
    assert check("janet", "landing_page", "reviews", "/reviews/bob") == 0
    assert check("alice", "landing_page", "reviews", "/reviews/bob") == 7


def test_opa_adapter_bad_policy_fails_closed():
    """opa.go:218-221: a config error serves fail-close (or fail-open
    when configured), matching the reference's hasConfigError path."""
    from istio_tpu.adapters.opa import OpaBuilder, OpaHandler
    from istio_tpu.adapters.sdk import Env
    b = OpaBuilder({"policies": ["package p\nallow = true {"]},
                   Env("test"))
    errs = b.validate()
    assert errs and "rego_parse_error" in errs[0]
    # handler built anyway (runtime keeps serving) → every check denied
    h = OpaHandler({"policies": ["package p\nallow = true {"]})
    assert h.handle_check("authorization", {}).status_code == 7
    h2 = OpaHandler({"policies": ["package p\nallow = true {"],
                     "fail_close": False})
    assert h2.handle_check("authorization", {}).status_code == 0


def test_opa_adapter_expression_mode_still_works():
    h = _opa({"policies": ['subject.user == "admin"']})
    ok = h.handle_check("authorization", {"subject": {"user": "admin"}})
    assert ok.status_code == 0
    deny = h.handle_check("authorization", {"subject": {"user": "bob"}})
    assert deny.status_code == 7


def test_opa_rego_detection_and_method_validation():
    from istio_tpu.adapters.opa import OpaBuilder
    from istio_tpu.adapters.sdk import Env
    # comment-leading Rego is still Rego
    h = _opa({"policies": ["# admins only\n" + BUCKET_POLICY],
              "check_method": "data.mixerauthz.allow"})
    assert h.handle_check("authorization", {
        "subject": {"user": "bucket-admins"},
        "action": {"method": "storage.buckets.get"}}).status_code == 0
    # a typo'd check_method is a CONFIG error, not a runtime mystery
    b = OpaBuilder({"policies": [BUCKET_POLICY],
                    "check_method": "data.mixerauth.allow"}, Env("t"))
    errs = b.validate()
    assert errs and "unknown package" in errs[0]
    b2 = OpaBuilder({"policies": [BUCKET_POLICY],
                     "check_method": "data.mixerauthz.alow"}, Env("t"))
    errs2 = b2.validate()
    assert errs2 and "no rule" in errs2[0]
