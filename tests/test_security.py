"""Security e2e: CA bootstrap/persistence, CSR signing with SPIFFE
SANs, secret controller, CSR gRPC service + retrying client, node
agent rotation (reference: security/pkg tests +
security/tests/integration certificateRotationTest)."""
import datetime
import time

import pytest

from istio_tpu.secure.backend import available_backends

if not available_backends():
    pytest.skip("istio_tpu.security needs a PKI backend "
                "(cryptography or the openssl CLI)",
                allow_module_level=True)

from istio_tpu.security import (IstioCA, generate_csr, generate_key,
                                key_cert_pair_ok, load_cert, san_uris,
                                parse_spiffe, spiffe_id)
from istio_tpu.security.ca import (CAError, IstioCAOptions,
                                   SecretController, CA_SECRET_NAME)
from istio_tpu.security.ca_service import (CAClient, CAGrpcServer,
                                           NodeAgent)
from istio_tpu.security.pki import key_to_pem, not_after, verify_chain


def test_spiffe_roundtrip():
    ident = spiffe_id("default", "bookinfo-productpage")
    assert ident == ("spiffe://cluster.local/ns/default"
                     "/sa/bookinfo-productpage")
    assert parse_spiffe(ident) == ("cluster.local", "default",
                                   "bookinfo-productpage")


def test_self_signed_ca_persistence_and_sign():
    secrets: dict = {}
    ca = IstioCA.new_self_signed(secrets)
    assert CA_SECRET_NAME in secrets
    # second boot reuses the persisted root (ca.go:82 reuse branch)
    ca2 = IstioCA.new_self_signed(secrets)
    assert ca2.get_root_certificate() == ca.get_root_certificate()

    key = generate_key()
    ident = spiffe_id("ns1", "sa1")
    cert_pem = ca.sign(generate_csr(key, ident))
    assert san_uris(load_cert(cert_pem)) == [ident]
    assert key_cert_pair_ok(key_to_pem(key), cert_pem)
    assert verify_chain(cert_pem, ca.get_root_certificate())


def test_ttl_clamp():
    ca = IstioCA.new_self_signed(
        {}, opts=IstioCAOptions(
            max_cert_ttl=datetime.timedelta(hours=1)))
    key = generate_key()
    csr = generate_csr(key, spiffe_id("a", "b"))
    with pytest.raises(CAError):
        ca.sign(csr, datetime.timedelta(days=30))
    cert = ca.sign(csr, datetime.timedelta(minutes=30))
    remaining = not_after(cert) - datetime.datetime.now(
        datetime.timezone.utc)
    assert remaining < datetime.timedelta(hours=1)


def test_secret_controller():
    secrets: dict = {}
    ca = IstioCA.new_self_signed({})
    ctl = SecretController(ca, secrets)
    ctl.on_service_account("default", "productpage")
    name = "istio.productpage.default"
    assert name in secrets
    blob = secrets[name]
    assert blob["identity"] == \
        "spiffe://cluster.local/ns/default/sa/productpage"
    assert key_cert_pair_ok(blob["key.pem"], blob["cert-chain.pem"])
    # idempotent on repeat add; removed on delete
    ctl.on_service_account("default", "productpage")
    assert len(secrets) == 1
    ctl.on_service_account("default", "productpage", event="delete")
    assert name not in secrets


def _claimed_identity_authenticator(cred_type: str,
                                    cred: bytes) -> str | None:
    """Test authenticator: the credential IS the caller identity (the
    same-id authorizer still constrains what it may sign)."""
    return cred.decode() if cred else None


@pytest.fixture()
def ca_rig():
    ca = IstioCA.new_self_signed({})
    # TLS serving (default) with a CA-signed serving cert; the client
    # verifies against the CA root
    server = CAGrpcServer(ca, authenticator=_claimed_identity_authenticator)
    port = server.start()
    client = CAClient(f"127.0.0.1:{port}",
                      root_cert_pem=ca.get_root_certificate())
    yield ca, client
    client.close()
    server.stop()


def test_csr_grpc_roundtrip(ca_rig):
    ca, client = ca_rig
    key = generate_key()
    ident = spiffe_id("default", "node-agent-test")
    resp = client.sign_csr(generate_csr(key, ident), ttl_minutes=45,
                           credential=ident.encode())
    assert resp.is_approved, resp.status_message
    assert san_uris(load_cert(bytes(resp.signed_cert))) == [ident]
    assert bytes(resp.cert_chain) == ca.get_root_certificate()


def test_csr_authorization_rejected(ca_rig):
    """ADVICE r1 high: a caller must not obtain a cert for an identity
    other than its own (server.go:74 authorize-before-sign)."""
    _, client = ca_rig
    key = generate_key()
    victim = spiffe_id("istio-system", "istio-pilot")
    attacker = spiffe_id("default", "compromised-workload")
    resp = client.sign_csr(generate_csr(key, victim),
                           credential=attacker.encode())
    assert not resp.is_approved
    assert "authorization failed" in resp.status_message


def test_csr_dns_san_impersonation_rejected(ca_rig):
    """A workload must not obtain a cert carrying DNS=istio-ca (the CA's
    TLS identity) even when its URI SAN is its own: every SAN the signed
    cert would carry needs authorization."""
    from istio_tpu.security.pki import generate_csr as gen
    _, client = ca_rig
    ident = spiffe_id("default", "sneaky")
    csr = gen(generate_key(), ident, dns_names=("istio-ca",))
    resp = client.sign_csr(csr, credential=ident.encode())
    assert not resp.is_approved
    assert "authorization failed" in resp.status_message


def test_csr_without_identities_rejected(ca_rig):
    """A SAN-free CSR must not be vacuously authorized."""
    _, client = ca_rig
    # identity=None builds a bare CSR through the backend seam (runs
    # on either PKI backend, unlike the old direct-cryptography build)
    bare = generate_csr(generate_key(), None, org="x")
    resp = client.sign_csr(bare, credential=b"spiffe://c/ns/a/sa/b")
    assert not resp.is_approved
    assert "no identities" in resp.status_message


def test_csr_authentication_rejected():
    ca = IstioCA.new_self_signed({})
    from istio_tpu.security.ca_service import allow_any_identity_authorizer
    server = CAGrpcServer(
        ca, authenticator=lambda t, c: "id" if c == b"good" else None,
        authorizer=allow_any_identity_authorizer, insecure_port=True)
    port = server.start()
    client = CAClient(f"127.0.0.1:{port}")
    try:
        key = generate_key()
        csr = generate_csr(key, spiffe_id("a", "b"))
        ok = client.sign_csr(csr, credential=b"good")
        assert ok.is_approved
        bad = client.sign_csr(csr, credential=b"evil")
        assert not bad.is_approved
        assert "authentication" in bad.status_message
    finally:
        client.close()
        server.stop()


def test_cert_authenticator_onprem_flow():
    """Full onprem loop: a workload bootstrapped with a CA-signed cert
    renews itself using that cert as the credential; a cert signed by a
    DIFFERENT root is rejected (security/pkg/platform/onprem.go)."""
    from istio_tpu.security.ca_service import cert_authenticator
    ca = IstioCA.new_self_signed({})
    ident = spiffe_id("default", "vm-workload")
    boot_key = generate_key()
    boot_cert = ca.sign(generate_csr(boot_key, ident))

    server = CAGrpcServer(ca, authenticator=cert_authenticator(
        ca.get_root_certificate()))
    port = server.start()
    client = CAClient(f"127.0.0.1:{port}",
                      root_cert_pem=ca.get_root_certificate())
    try:
        renew = client.sign_csr(generate_csr(generate_key(), ident),
                                credential=boot_cert)
        assert renew.is_approved, renew.status_message

        other_ca = IstioCA.new_self_signed({})
        rogue_cert = other_ca.sign(
            generate_csr(generate_key(), ident))
        rejected = client.sign_csr(generate_csr(generate_key(), ident),
                                   credential=rogue_cert)
        assert not rejected.is_approved
        assert "authentication" in rejected.status_message
    finally:
        client.close()
        server.stop()


def test_node_agent_rotation(ca_rig):
    _, client = ca_rig
    bundles = []
    ident = spiffe_id("default", "vm-workload")
    agent = NodeAgent(client, ident,
                      on_certs=lambda k, c, r: bundles.append((k, c, r)),
                      ttl_minutes=1,   # rotate at ~30s — force manually
                      credential=ident.encode())
    agent.rotate_once()
    agent.rotate_once()
    assert agent.rotations == 2 and len(bundles) == 2
    (key_pem, cert_pem, root_pem) = bundles[-1]
    assert key_cert_pair_ok(key_pem, cert_pem)
    assert verify_chain(cert_pem, root_pem)
