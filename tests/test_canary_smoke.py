"""Tier-1 hook for scripts/canary_smoke.py: the CI gate that the
config canary (istio_tpu/canary) vetoes every seeded divergent swap in
gate mode — with the planted rule named under the planted divergence
kind and status-flip exemplars oracle-confirmed — publishes
identical-semantics swaps with zero reported divergences, keeps the
old dispatcher serving base semantics after a veto, and agrees across
the warn-mode / introspect / CLI / admission surfaces. Runs main()
in-process (the analyze_smoke pattern; the script stays runnable
standalone under JAX_PLATFORMS=cpu)."""
import importlib.util
import os
import sys


def test_canary_smoke_main():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "canary_smoke.py")
    spec = importlib.util.spec_from_file_location("canary_smoke", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
        rc = mod.main(seed=20260803)
    finally:
        sys.modules.pop(spec.name, None)
    assert rc == 0
