"""Template framework + adapter inventory behavior.

Mirrors the reference's per-template/per-adapter unit tests
(mixer/template/*/template.gen_test.go patterns, adapter *_test.go)."""
import datetime

import pytest

from istio_tpu.adapters.registry import adapter_registry, load_inventory
from istio_tpu.adapters.sdk import (AdapterUnavailable, Env, QuotaArgs)
from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.attribute.types import ValueType as V
from istio_tpu.expr.checker import AttributeDescriptorFinder
from istio_tpu.models.policy_engine import (NOT_FOUND, OK,
                                            PERMISSION_DENIED,
                                            RESOURCE_EXHAUSTED)
from istio_tpu.templates import InstanceBuilder, TemplateError, registry
from istio_tpu.templates.framework import infer_types
from istio_tpu.testing.corpus import CORPUS_MANIFEST

load_inventory()
FINDER = AttributeDescriptorFinder(CORPUS_MANIFEST)
ENV = Env("test")


def _build(adapter: str, config: dict):
    info = adapter_registry.get(adapter)
    b = info.builder(config, ENV)
    errs = b.validate()
    assert not errs, errs
    return b.build()


# ---------------------------------------------------------------- templates

def test_inventory_parity():
    assert registry.names() == ["apikey", "authorization", "checknothing",
                                "kubernetes", "listentry", "logentry",
                                "metric", "quota", "reportnothing",
                                "servicecontrolreport", "tracespan"]
    assert sorted(adapter_registry.names()) == [
        "circonus", "denier", "fluentd", "kubernetesenv", "list",
        "memquota", "noop", "opa", "prometheus", "rbac",
        "servicecontrol", "stackdriver", "statsd", "stdio"]


def test_listentry_instance():
    ib = InstanceBuilder(registry.get("listentry"), "staticversion",
                         {"value": 'source.labels["version"] | "unknown"'},
                         FINDER)
    inst = ib.build(bag_from_mapping(
        {"source.labels": {"version": "v1"}}))
    assert inst == {"name": "staticversion", "value": "v1"}
    inst = ib.build(bag_from_mapping({"source.labels": {}}))
    assert inst["value"] == "unknown"


def test_metric_instance_with_dynamic_value_and_dimensions():
    ib = InstanceBuilder(registry.get("metric"), "requestcount", {
        "value": "request.size",
        "dimensions": {"service": "destination.service",
                       "protocol": 'context.protocol | "http"'}},
        FINDER)
    assert ib.inferred["value"] == V.INT64
    inst = ib.build(bag_from_mapping(
        {"request.size": 7, "destination.service": "a.b"}))
    assert inst["value"] == 7
    assert inst["dimensions"] == {"service": "a.b", "protocol": "http"}


def test_authorization_subject_action():
    ib = InstanceBuilder(registry.get("authorization"), "authinfo", {
        "subject": {"user": 'source.name | ""'},
        "action": {"namespace": 'destination.namespace | "default"',
                   "service": "destination.service",
                   "method": 'context.protocol',
                   "properties": {"version": 'source.labels["version"] | ""'}}},
        FINDER)
    inst = ib.build(bag_from_mapping({
        "destination.service": "svc", "context.protocol": "GET",
        "source.labels": {"version": "v2"}}))
    assert inst["subject"] == {"user": ""}
    assert inst["action"]["namespace"] == "default"
    assert inst["action"]["properties"] == {"version": "v2"}


def test_template_type_mismatch_rejected():
    with pytest.raises(TemplateError):
        infer_types(registry.get("listentry"),
                    {"value": "request.size"}, FINDER)   # INT64 ≠ STRING
    with pytest.raises(TemplateError):
        infer_types(registry.get("listentry"),
                    {"nope": '"x"'}, FINDER)
    with pytest.raises(TemplateError):
        infer_types(registry.get("listentry"), {}, FINDER)  # required


# ---------------------------------------------------------------- adapters

def test_denier():
    h = _build("denier", {"status_code": PERMISSION_DENIED})
    r = h.handle_check("checknothing", {"name": "i"})
    assert r.status_code == PERMISSION_DENIED
    q = h.handle_quota("quota", {"name": "q"}, QuotaArgs(quota_amount=5))
    assert q.granted_amount == 0


def test_list_whitelist_strings():
    h = _build("list", {"overrides": ["v1", "v2"]})
    assert h.handle_check("listentry", {"value": "v1"}).ok
    r = h.handle_check("listentry", {"value": "v9"})
    assert r.status_code == NOT_FOUND


def test_list_blacklist_cidr():
    h = _build("list", {"entry_type": "IP_ADDRESSES", "blacklist": True,
                        "overrides": ["10.0.0.0/8"]})
    assert h.handle_check("listentry",
                          {"value": "10.1.2.3"}).status_code \
        == PERMISSION_DENIED
    assert h.handle_check("listentry", {"value": "192.168.1.1"}).ok
    # 16-byte v4-mapped bytes form (the interned IP representation)
    mapped = b"\x00" * 10 + b"\xff\xff" + bytes([10, 9, 9, 9])
    assert h.handle_check("listentry",
                          {"value": mapped}).status_code \
        == PERMISSION_DENIED


def test_list_regex_and_file_provider(tmp_path):
    p = tmp_path / "allow.txt"
    p.write_text("^/api/.*\n^/healthz$\n")
    h = _build("list", {"entry_type": "REGEX",
                        "provider_url": f"file://{p}"})
    assert h.handle_check("listentry", {"value": "/api/v1"}).ok
    assert not h.handle_check("listentry", {"value": "/admin"}).ok


def test_memquota_window_and_dedup():
    now = [0.0]
    from istio_tpu.adapters.memquota import MemQuotaHandler
    h = MemQuotaHandler({"quotas": [
        {"name": "rate", "max_amount": 3, "valid_duration_s": 10.0}]},
        ENV, clock=lambda: now[0])
    inst = {"name": "rate", "dimensions": {"u": "alice"}}
    assert h.handle_quota("quota", inst,
                          QuotaArgs(quota_amount=2)).granted_amount == 2
    # dedup: same id returns the same grant without consuming
    r1 = h.handle_quota("quota", inst,
                        QuotaArgs(quota_amount=1, dedup_id="d1"))
    r2 = h.handle_quota("quota", inst,
                        QuotaArgs(quota_amount=1, dedup_id="d1"))
    assert r1.granted_amount == 1 and r2.granted_amount == 1
    # window full: all-or-nothing fails, best-effort grants 0
    r = h.handle_quota("quota", inst,
                       QuotaArgs(quota_amount=2, best_effort=False))
    assert r.granted_amount == 0 and r.status_code == RESOURCE_EXHAUSTED
    # other dimensions have their own cell
    other = {"name": "rate", "dimensions": {"u": "bob"}}
    assert h.handle_quota("quota", other,
                          QuotaArgs(quota_amount=3)).granted_amount == 3
    # window expiry frees budget
    now[0] = 11.0
    assert h.handle_quota("quota", inst,
                          QuotaArgs(quota_amount=3)).granted_amount == 3


def test_rbac():
    h = _build("rbac", {
        "roles": [{"name": "viewer", "namespace": "ns1", "rules": [
            {"services": ["products.*"], "methods": ["GET"],
             "paths": ["/products*"]}]}],
        "bindings": [{"name": "b1", "namespace": "ns1",
                      "roleRef": {"name": "viewer"},
                      "subjects": [{"user": "alice"}]}]})
    ok = h.handle_check("authorization", {
        "subject": {"user": "alice"},
        "action": {"namespace": "ns1", "service": "products.ns1",
                   "method": "GET", "path": "/products/1"}})
    assert ok.status_code == OK
    deny = h.handle_check("authorization", {
        "subject": {"user": "bob"},
        "action": {"namespace": "ns1", "service": "products.ns1",
                   "method": "GET", "path": "/products/1"}})
    assert deny.status_code == PERMISSION_DENIED
    wrong_method = h.handle_check("authorization", {
        "subject": {"user": "alice"},
        "action": {"namespace": "ns1", "service": "products.ns1",
                   "method": "DELETE", "path": "/products/1"}})
    assert wrong_method.status_code == PERMISSION_DENIED


def test_opa_expression_policies():
    h = _build("opa", {"policies": [
        'action.method == "GET" && action.path.startsWith("/public/")',
        'subject.user == "admin"']})
    assert h.handle_check("authorization", {
        "subject": {"user": "joe"},
        "action": {"method": "GET", "path": "/public/x"}}).ok
    assert h.handle_check("authorization", {
        "subject": {"user": "admin"},
        "action": {"method": "DELETE", "path": "/private"}}).ok
    assert not h.handle_check("authorization", {
        "subject": {"user": "joe"},
        "action": {"method": "DELETE", "path": "/private"}}).ok


def test_stdio_and_prometheus(capsys):
    h = _build("stdio", {})
    h.handle_report("logentry", [{
        "name": "accesslog", "severity": "warning",
        "timestamp": datetime.datetime(2018, 1, 1),
        "variables": {"url": "/x", "code": 200}}])
    h.handle_report("metric", [{"name": "m", "value": 3,
                                "dimensions": {"svc": "a"}}])
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 2 and '"url": "/x"' in out[0]

    ph = _build("prometheus", {"metrics": [
        {"name": "requestcount", "kind": "COUNTER",
         "label_names": ["service"]}]})
    ph.handle_report("metric", [
        {"name": "requestcount", "value": 2,
         "dimensions": {"service": "a.b"}},
        {"name": "requestcount", "value": 3,
         "dimensions": {"service": "a.b"}}])
    sample = ph.registry.get_sample_value(
        "istio_tpu_requestcount_total", {"service": "a.b"})
    assert sample == 5.0


def test_statsd_lines():
    import socket
    recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    recv.bind(("127.0.0.1", 0))
    recv.settimeout(2.0)
    port = recv.getsockname()[1]
    h = _build("statsd", {"port": port, "prefix": "istio.",
                          "metrics": [{"name": "reqs", "type": "COUNTER",
                                       "name_template": "by_${svc}"}]})
    h.handle_report("metric", [{"name": "reqs", "value": 4,
                                "dimensions": {"svc": "web"}}])
    data = recv.recvfrom(1024)[0]
    assert data == b"istio.by_web:4|c"
    h.close(); recv.close()


def test_fluentd_msgpack_roundtrippable():
    from istio_tpu.adapters.fluentd import msgpack_encode
    enc = msgpack_encode(["tag", 123, {"k": "v", "n": 7}])
    assert enc[0] == 0x93            # fixarray(3)
    assert b"\xa3tag" in enc and b"\xa1k\xa1v" in enc


def test_kubernetesenv_apa():
    h = _build("kubernetesenv", {"pods": {
        "productpage.default": {
            "pod_name": "productpage-v1-abc", "namespace": "default",
            "labels": {"app": "productpage"}, "pod_ip": "10.0.0.5",
            "service_account_name": "sa-pp"}}})
    out = h.generate_attributes("kubernetes", {
        "source_uid": "kubernetes://productpage.default"})
    assert out["source_pod_name"] == "productpage-v1-abc"
    out2 = h.generate_attributes("kubernetes",
                                 {"destination_ip": "10.0.0.5"})
    assert out2["destination_namespace"] == "default"


def test_kubernetesenv_informer_source():
    """InformerPodSource tracks live pod churn on the in-process API
    server (kubernetesenv/cache.go contract)."""
    from istio_tpu.kube.fake import FakeKubeCluster

    cluster = FakeKubeCluster()
    cluster.create({"kind": "Pod",
                    "metadata": {"name": "reviews-v2-xyz",
                                 "namespace": "default",
                                 "labels": {"app": "reviews"}},
                    "spec": {"serviceAccountName": "sa-reviews"},
                    "status": {"podIP": "10.0.0.9",
                               "hostIP": "172.16.0.2"}})
    h = _build("kubernetesenv", {"cluster": cluster})
    out = h.generate_attributes("kubernetes", {
        "source_uid": "kubernetes://reviews-v2-xyz.default"})
    assert out["source_pod_name"] == "reviews-v2-xyz"
    assert out["source_service"] == "reviews"
    assert out["source_host_ip"] == "172.16.0.2"
    assert out["source_service_account_name"] == "sa-reviews"

    # pod created AFTER the handler: informer picks it up by watch
    cluster.create({"kind": "Pod",
                    "metadata": {"name": "ratings-v1-abc",
                                 "namespace": "prod",
                                 "labels": {"app": "ratings"}},
                    "status": {"podIP": "10.0.0.10"}})
    out2 = h.generate_attributes("kubernetes",
                                 {"destination_ip": "10.0.0.10"})
    assert out2["destination_namespace"] == "prod"

    # deletion evicts both indexes
    cluster.delete("Pod", "prod", "ratings-v1-abc")
    assert h.generate_attributes(
        "kubernetes", {"destination_ip": "10.0.0.10"}) == {}


def test_circonus_aggregation_and_flush():
    """circonus.go HandleMetric semantics: counter increments, gauge
    last-write, distribution histogram bins; flush produces the
    httptrap payload via the transport seam."""
    sent = []
    h = _build("circonus", {
        "submission_url": "https://trap.example/module/httptrap/x/y",
        "submission_interval_s": 3600,    # flush manually
        "metrics": [{"name": "reqs", "type": "counter"},
                    {"name": "inflight", "type": "gauge"},
                    {"name": "latency", "type": "distribution"}],
        "transport": lambda url, payload: sent.append((url, payload))})
    try:
        h.handle_report("metric", [
            {"name": "reqs", "value": 1}, {"name": "reqs", "value": 1},
            {"name": "inflight", "value": 3}, {"name": "inflight", "value": 7},
            {"name": "latency", "value": 0.0034},
            {"name": "latency", "value": 0.0036},
            {"name": "unconfigured", "value": 9}])
        h._flush()
    finally:
        h.close()
    url, payload = sent[0]
    assert url.startswith("https://trap.example")
    assert payload["reqs"] == {"_type": "L", "_value": 2}
    assert payload["inflight"] == {"_type": "n", "_value": 7.0}
    # both samples land in the same log-linear bin H[+34e-04]..H[+36e-04]
    assert payload["latency"]["_type"] == "h"
    assert sum(int(s.split("=")[1])
               for s in payload["latency"]["_value"]) == 2
    assert "unconfigured" not in payload


def test_circonus_validate():
    info = adapter_registry.get("circonus")
    b = info.builder({"submission_url": "not a url",
                      "submission_interval_s": 0.2}, ENV)
    errs = b.validate()
    assert any("submission_url" in e for e in errs)
    assert any("submission_interval_s" in e for e in errs)


def test_stackdriver_metrics_merge_and_distribution():
    """metric.go + merge.go: per-push-window merge of same-series
    points; DELTA → CUMULATIVE; distribution bucketing with
    under/overflow (distribution.go)."""
    sent = []
    h = _build("stackdriver", {
        "project_id": "proj-1",
        "push_interval_s": 3600,
        "metric_info": {
            "request_count": {"kind": "DELTA", "value": "INT64"},
            "inflight": {"kind": "GAUGE", "value": "INT64"},
            "latency": {"kind": "DELTA", "value": "DISTRIBUTION",
                        "buckets": {"explicit":
                                    {"bounds": [0.01, 0.1, 1.0]}}}},
        "transport": lambda m, batch: sent.append((m, batch))})
    try:
        h.handle_report("metric", [
            {"name": "request_count", "value": 1,
             "dimensions": {"svc": "web"}},
            {"name": "request_count", "value": 1,
             "dimensions": {"svc": "web"}},
            {"name": "request_count", "value": 1,
             "dimensions": {"svc": "db"}},
            {"name": "latency", "value": 0.05, "dimensions": {}},
            {"name": "latency", "value": 5.0, "dimensions": {}},
            {"name": "inflight", "value": 3, "dimensions": {}},
            {"name": "inflight", "value": 7, "dimensions": {}},
            {"name": "skipped", "value": 1}])
        h._metrics.flush()
    finally:
        h.close()
    method, batch = sent[0]
    assert method == "monitoring.createTimeSeries"
    by_labels = {ts["metric"]["labels"].get("svc"): ts for ts in batch
                 if ts["metric"]["type"].endswith("request_count")}
    assert by_labels["web"]["points"][0]["value"]["int64Value"] == 2
    assert by_labels["db"]["points"][0]["value"]["int64Value"] == 1
    assert all(ts["metricKind"] == "CUMULATIVE" for ts in batch
               if not ts["metric"]["type"].endswith("inflight"))
    # gauge: last write wins, not summed
    gauge = next(ts for ts in batch
                 if ts["metric"]["type"].endswith("inflight"))
    assert gauge["points"][0]["value"]["int64Value"] == 7
    dist = [ts for ts in batch if ts["metric"]["type"].endswith("latency")]
    dv = dist[0]["points"][0]["value"]["distributionValue"]
    # 0.05 → bucket 1 (between 0.01 and 0.1); 5.0 → overflow bucket 3
    assert dv["count"] == 2 and dv["bucketCounts"] == [0, 1, 0, 1]


def test_stackdriver_logs_and_traces():
    sent = []
    h = _build("stackdriver", {
        "project_id": "proj-1", "push_interval_s": 3600,
        "log_info": {"accesslog": {
            "payload_template": "{method} {path}",
            "http_mapping": {"requestMethod": "method",
                             "status": "code"}}},
        "transport": lambda m, batch: sent.append((m, batch))})
    try:
        h.handle_report("logentry", [
            {"name": "accesslog", "severity": "warning",
             "variables": {"method": "GET", "path": "/x", "code": 200}}])
        h.handle_report("tracespan", [
            {"trace_id": "t1", "span_id": "s1", "span_name": "op",
             "span_tags": {"k": "v"}}])
        h._logs.flush(); h._traces.flush()
    finally:
        h.close()
    logs = dict(sent)["logging.writeLogEntries"]
    assert logs[0]["severity"] == "WARNING"
    assert logs[0]["textPayload"] == "GET /x"
    assert logs[0]["httpRequest"] == {"requestMethod": "GET",
                                      "status": 200}
    spans = dict(sent)["cloudtrace.batchWriteSpans"]
    assert spans[0]["displayName"] == "op"
    assert "traces/t1/spans/s1" in spans[0]["name"]


SC_CONFIG = {
    "service_configs": [{"mesh_service_name": "svc.default",
                         "google_service_name": "api.example.com",
                         "quotas": [{"name": "ratelimit",
                                     "expiration_s": 10}]}],
    "runtime_config": {"check_result_expiration_s": 30}}


def test_servicecontrol_check_cache_and_errors():
    """checkprocessor.go: empty key → INVALID_ARGUMENT; responses
    cached; CheckError code mapping."""
    calls = []

    def transport(method, service, payload):
        calls.append((method, service))
        if payload["operation"]["consumerId"].endswith("bad"):
            return {"checkErrors": [{"code": "API_KEY_INVALID",
                                     "detail": "nope"}]}
        return {}

    h = _build("servicecontrol", {**SC_CONFIG, "transport": transport})
    missing = h.handle_check("apikey", {"api_key": "", "api_operation": "op"})
    assert missing.status_code == 3           # INVALID_ARGUMENT
    ok = h.handle_check("apikey", {"api_key": "k1", "api_operation": "op"})
    assert ok.ok and ok.valid_duration_s == 30
    again = h.handle_check("apikey", {"api_key": "k1", "api_operation": "op"})
    assert again.ok and len(calls) == 1       # served from cache
    bad = h.handle_check("apikey", {"api_key": "bad", "api_operation": "op"})
    assert bad.status_code == 3 and "API_KEY_INVALID" in bad.status_message
    # no transport → fail closed, not crash
    h2 = _build("servicecontrol", SC_CONFIG)
    gated = h2.handle_check("apikey", {"api_key": "k", "api_operation": "op"})
    assert gated.status_code == 14            # UNAVAILABLE


def test_servicecontrol_report_operation():
    """reportbuilder.go: metric value sets from the supported-metric
    table + endpoints_log entry."""
    from istio_tpu.adapters.servicecontrol import build_operation
    op = build_operation({
        # servicecontrolreport template field names (builtin.py)
        "api_operation": "ListShelves", "api_key": "k1",
        "api_protocol": "http", "response_code": 403,
        "request_time": 1_700_000_000.0, "response_time": 1_700_000_000.25,
        "response_latency": datetime.timedelta(milliseconds=250),
        "request_bytes": 300,
        "request_method": "GET", "request_path": "/shelves"})
    names = {m["metricName"] for m in op["metricValueSets"]}
    assert "serviceruntime.googleapis.com/api/producer/request_count" \
        in names
    assert ("serviceruntime.googleapis.com/api/consumer/request_count"
            in names)                          # api_key present
    count = next(m for m in op["metricValueSets"]
                 if m["metricName"].endswith("producer/request_count"))
    labels = count["metricValues"][0]["labels"]
    assert labels["/response_code"] == "403"
    assert labels["/response_code_class"] == "4xx"
    latencies = next(m for m in op["metricValueSets"]
                     if m["metricName"].endswith("producer/"
                                                 "backend_latencies"))
    assert latencies["metricValues"][0]["distributionValue"]["count"] == 1
    log = op["logEntries"][0]
    assert log["severity"] == "ERROR"
    assert log["structPayload"]["error_cause"] == "AUTH"
    assert log["structPayload"]["url"] == "/shelves"
    assert log["structPayload"]["http_method"] == "GET"
    assert log["structPayload"]["request_latency_in_ms"] == 250
    assert op["consumerId"] == "api_key:k1"


def test_servicecontrol_quota():
    """quotaprocessor.go: allocate request shape + granted amount from
    the allocation-result metric; exhaustion → RESOURCE_EXHAUSTED."""
    requests = []

    def transport(method, service, payload):
        requests.append((method, payload))
        op = payload["allocateOperation"]
        if op["consumerId"].endswith("poor"):
            return {"allocateErrors": [{"code": "RESOURCE_EXHAUSTED",
                                        "detail": "out"}]}
        return {"quotaMetrics": [{
            "metricName": ("serviceruntime.googleapis.com/api/consumer/"
                           "quota_used_count"),
            "metricValues": [{"labels": {"/quota_name": "ratelimit"},
                              "int64Value": 5}]}]}

    h = _build("servicecontrol", {**SC_CONFIG, "transport": transport})
    inst = {"name": "ratelimit",
            "dimensions": {"api_key": "k1", "api_operation": "op"}}
    res = h.handle_quota("quota", inst, QuotaArgs(quota_amount=10))
    assert res.granted_amount == 5 and res.valid_duration_s == 10
    assert requests[0][1]["allocateOperation"]["quotaMode"] == "BEST_EFFORT"
    poor = {"name": "ratelimit",
            "dimensions": {"api_key": "poor", "api_operation": "op"}}
    denied = h.handle_quota("quota", poor,
                            QuotaArgs(quota_amount=10, best_effort=False))
    assert denied.granted_amount == 0
    assert denied.status_code == RESOURCE_EXHAUSTED
    unknown = h.handle_quota("quota", {"name": "nope", "dimensions": {}},
                             QuotaArgs())
    assert unknown.status_code == 3
