"""Template framework + adapter inventory behavior.

Mirrors the reference's per-template/per-adapter unit tests
(mixer/template/*/template.gen_test.go patterns, adapter *_test.go)."""
import datetime

import pytest

from istio_tpu.adapters.registry import adapter_registry, load_inventory
from istio_tpu.adapters.sdk import (AdapterUnavailable, Env, QuotaArgs)
from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.attribute.types import ValueType as V
from istio_tpu.expr.checker import AttributeDescriptorFinder
from istio_tpu.models.policy_engine import (NOT_FOUND, OK,
                                            PERMISSION_DENIED,
                                            RESOURCE_EXHAUSTED)
from istio_tpu.templates import InstanceBuilder, TemplateError, registry
from istio_tpu.templates.framework import infer_types
from istio_tpu.testing.corpus import CORPUS_MANIFEST

load_inventory()
FINDER = AttributeDescriptorFinder(CORPUS_MANIFEST)
ENV = Env("test")


def _build(adapter: str, config: dict):
    info = adapter_registry.get(adapter)
    b = info.builder(config, ENV)
    errs = b.validate()
    assert not errs, errs
    return b.build()


# ---------------------------------------------------------------- templates

def test_inventory_parity():
    assert registry.names() == ["apikey", "authorization", "checknothing",
                                "kubernetes", "listentry", "logentry",
                                "metric", "quota", "reportnothing",
                                "tracespan"]
    assert sorted(adapter_registry.names()) == [
        "circonus", "denier", "fluentd", "kubernetesenv", "list",
        "memquota", "noop", "opa", "prometheus", "rbac",
        "servicecontrol", "stackdriver", "statsd", "stdio"]


def test_listentry_instance():
    ib = InstanceBuilder(registry.get("listentry"), "staticversion",
                         {"value": 'source.labels["version"] | "unknown"'},
                         FINDER)
    inst = ib.build(bag_from_mapping(
        {"source.labels": {"version": "v1"}}))
    assert inst == {"name": "staticversion", "value": "v1"}
    inst = ib.build(bag_from_mapping({"source.labels": {}}))
    assert inst["value"] == "unknown"


def test_metric_instance_with_dynamic_value_and_dimensions():
    ib = InstanceBuilder(registry.get("metric"), "requestcount", {
        "value": "request.size",
        "dimensions": {"service": "destination.service",
                       "protocol": 'context.protocol | "http"'}},
        FINDER)
    assert ib.inferred["value"] == V.INT64
    inst = ib.build(bag_from_mapping(
        {"request.size": 7, "destination.service": "a.b"}))
    assert inst["value"] == 7
    assert inst["dimensions"] == {"service": "a.b", "protocol": "http"}


def test_authorization_subject_action():
    ib = InstanceBuilder(registry.get("authorization"), "authinfo", {
        "subject": {"user": 'source.name | ""'},
        "action": {"namespace": 'destination.namespace | "default"',
                   "service": "destination.service",
                   "method": 'context.protocol',
                   "properties": {"version": 'source.labels["version"] | ""'}}},
        FINDER)
    inst = ib.build(bag_from_mapping({
        "destination.service": "svc", "context.protocol": "GET",
        "source.labels": {"version": "v2"}}))
    assert inst["subject"] == {"user": ""}
    assert inst["action"]["namespace"] == "default"
    assert inst["action"]["properties"] == {"version": "v2"}


def test_template_type_mismatch_rejected():
    with pytest.raises(TemplateError):
        infer_types(registry.get("listentry"),
                    {"value": "request.size"}, FINDER)   # INT64 ≠ STRING
    with pytest.raises(TemplateError):
        infer_types(registry.get("listentry"),
                    {"nope": '"x"'}, FINDER)
    with pytest.raises(TemplateError):
        infer_types(registry.get("listentry"), {}, FINDER)  # required


# ---------------------------------------------------------------- adapters

def test_denier():
    h = _build("denier", {"status_code": PERMISSION_DENIED})
    r = h.handle_check("checknothing", {"name": "i"})
    assert r.status_code == PERMISSION_DENIED
    q = h.handle_quota("quota", {"name": "q"}, QuotaArgs(quota_amount=5))
    assert q.granted_amount == 0


def test_list_whitelist_strings():
    h = _build("list", {"overrides": ["v1", "v2"]})
    assert h.handle_check("listentry", {"value": "v1"}).ok
    r = h.handle_check("listentry", {"value": "v9"})
    assert r.status_code == NOT_FOUND


def test_list_blacklist_cidr():
    h = _build("list", {"entry_type": "IP_ADDRESSES", "blacklist": True,
                        "overrides": ["10.0.0.0/8"]})
    assert h.handle_check("listentry",
                          {"value": "10.1.2.3"}).status_code \
        == PERMISSION_DENIED
    assert h.handle_check("listentry", {"value": "192.168.1.1"}).ok
    # 16-byte v4-mapped bytes form (the interned IP representation)
    mapped = b"\x00" * 10 + b"\xff\xff" + bytes([10, 9, 9, 9])
    assert h.handle_check("listentry",
                          {"value": mapped}).status_code \
        == PERMISSION_DENIED


def test_list_regex_and_file_provider(tmp_path):
    p = tmp_path / "allow.txt"
    p.write_text("^/api/.*\n^/healthz$\n")
    h = _build("list", {"entry_type": "REGEX",
                        "provider_url": f"file://{p}"})
    assert h.handle_check("listentry", {"value": "/api/v1"}).ok
    assert not h.handle_check("listentry", {"value": "/admin"}).ok


def test_memquota_window_and_dedup():
    now = [0.0]
    from istio_tpu.adapters.memquota import MemQuotaHandler
    h = MemQuotaHandler({"quotas": [
        {"name": "rate", "max_amount": 3, "valid_duration_s": 10.0}]},
        ENV, clock=lambda: now[0])
    inst = {"name": "rate", "dimensions": {"u": "alice"}}
    assert h.handle_quota("quota", inst,
                          QuotaArgs(quota_amount=2)).granted_amount == 2
    # dedup: same id returns the same grant without consuming
    r1 = h.handle_quota("quota", inst,
                        QuotaArgs(quota_amount=1, dedup_id="d1"))
    r2 = h.handle_quota("quota", inst,
                        QuotaArgs(quota_amount=1, dedup_id="d1"))
    assert r1.granted_amount == 1 and r2.granted_amount == 1
    # window full: all-or-nothing fails, best-effort grants 0
    r = h.handle_quota("quota", inst,
                       QuotaArgs(quota_amount=2, best_effort=False))
    assert r.granted_amount == 0 and r.status_code == RESOURCE_EXHAUSTED
    # other dimensions have their own cell
    other = {"name": "rate", "dimensions": {"u": "bob"}}
    assert h.handle_quota("quota", other,
                          QuotaArgs(quota_amount=3)).granted_amount == 3
    # window expiry frees budget
    now[0] = 11.0
    assert h.handle_quota("quota", inst,
                          QuotaArgs(quota_amount=3)).granted_amount == 3


def test_rbac():
    h = _build("rbac", {
        "roles": [{"name": "viewer", "namespace": "ns1", "rules": [
            {"services": ["products.*"], "methods": ["GET"],
             "paths": ["/products*"]}]}],
        "bindings": [{"name": "b1", "namespace": "ns1",
                      "roleRef": {"name": "viewer"},
                      "subjects": [{"user": "alice"}]}]})
    ok = h.handle_check("authorization", {
        "subject": {"user": "alice"},
        "action": {"namespace": "ns1", "service": "products.ns1",
                   "method": "GET", "path": "/products/1"}})
    assert ok.status_code == OK
    deny = h.handle_check("authorization", {
        "subject": {"user": "bob"},
        "action": {"namespace": "ns1", "service": "products.ns1",
                   "method": "GET", "path": "/products/1"}})
    assert deny.status_code == PERMISSION_DENIED
    wrong_method = h.handle_check("authorization", {
        "subject": {"user": "alice"},
        "action": {"namespace": "ns1", "service": "products.ns1",
                   "method": "DELETE", "path": "/products/1"}})
    assert wrong_method.status_code == PERMISSION_DENIED


def test_opa_expression_policies():
    h = _build("opa", {"policies": [
        'action.method == "GET" && action.path.startsWith("/public/")',
        'subject.user == "admin"']})
    assert h.handle_check("authorization", {
        "subject": {"user": "joe"},
        "action": {"method": "GET", "path": "/public/x"}}).ok
    assert h.handle_check("authorization", {
        "subject": {"user": "admin"},
        "action": {"method": "DELETE", "path": "/private"}}).ok
    assert not h.handle_check("authorization", {
        "subject": {"user": "joe"},
        "action": {"method": "DELETE", "path": "/private"}}).ok


def test_stdio_and_prometheus(capsys):
    h = _build("stdio", {})
    h.handle_report("logentry", [{
        "name": "accesslog", "severity": "warning",
        "timestamp": datetime.datetime(2018, 1, 1),
        "variables": {"url": "/x", "code": 200}}])
    h.handle_report("metric", [{"name": "m", "value": 3,
                                "dimensions": {"svc": "a"}}])
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 2 and '"url": "/x"' in out[0]

    ph = _build("prometheus", {"metrics": [
        {"name": "requestcount", "kind": "COUNTER",
         "label_names": ["service"]}]})
    ph.handle_report("metric", [
        {"name": "requestcount", "value": 2,
         "dimensions": {"service": "a.b"}},
        {"name": "requestcount", "value": 3,
         "dimensions": {"service": "a.b"}}])
    sample = ph.registry.get_sample_value(
        "istio_tpu_requestcount_total", {"service": "a.b"})
    assert sample == 5.0


def test_statsd_lines():
    import socket
    recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    recv.bind(("127.0.0.1", 0))
    recv.settimeout(2.0)
    port = recv.getsockname()[1]
    h = _build("statsd", {"port": port, "prefix": "istio.",
                          "metrics": [{"name": "reqs", "type": "COUNTER",
                                       "name_template": "by_${svc}"}]})
    h.handle_report("metric", [{"name": "reqs", "value": 4,
                                "dimensions": {"svc": "web"}}])
    data = recv.recvfrom(1024)[0]
    assert data == b"istio.by_web:4|c"
    h.close(); recv.close()


def test_fluentd_msgpack_roundtrippable():
    from istio_tpu.adapters.fluentd import msgpack_encode
    enc = msgpack_encode(["tag", 123, {"k": "v", "n": 7}])
    assert enc[0] == 0x93            # fixarray(3)
    assert b"\xa3tag" in enc and b"\xa1k\xa1v" in enc


def test_kubernetesenv_apa():
    h = _build("kubernetesenv", {"pods": {
        "productpage.default": {
            "pod_name": "productpage-v1-abc", "namespace": "default",
            "labels": {"app": "productpage"}, "pod_ip": "10.0.0.5",
            "service_account_name": "sa-pp"}}})
    out = h.generate_attributes("kubernetes", {
        "source_uid": "kubernetes://productpage.default"})
    assert out["source_pod_name"] == "productpage-v1-abc"
    out2 = h.generate_attributes("kubernetes",
                                 {"destination_ip": "10.0.0.5"})
    assert out2["destination_namespace"] == "default"


def test_saas_stubs_gated():
    h = _build("stackdriver", {})
    with pytest.raises(AdapterUnavailable):
        h.handle_report("metric", [{"name": "m", "value": 1}])
    # with an injected transport the stub forwards
    seen = []
    h2 = _build("servicecontrol",
                {"transport": lambda k, t, p: seen.append((k, t))})
    h2.handle_report("metric", [{"name": "m", "value": 1}])
    assert seen == [("report", "metric")]
