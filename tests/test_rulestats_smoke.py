"""Tier-1 hook for scripts/rulestats_smoke.py: the CI gate that
rule-level telemetry keeps being a measurement — served checks through
the real grpc (and, toolchain permitting, native) fronts drain
per-rule counts that EXACTLY equal an oracle recount, the
/debug/rulestats view and the adapter export agree with the
aggregator, and denied requests leave trace-linked exemplars. Runs
main() in-process (the introspect_smoke pattern: a subprocess would
pay a second jax import for no extra coverage; the script stays
runnable standalone under JAX_PLATFORMS=cpu)."""
import importlib.util
import os
import sys


def _load():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "rulestats_smoke.py")
    spec = importlib.util.spec_from_file_location("rulestats_smoke",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    spec.loader.exec_module(mod)
    return mod


def test_rulestats_smoke_main():
    mod = _load()
    try:
        rc = mod.main(n_rules=18, n_checks=16)
    finally:
        sys.modules.pop("rulestats_smoke", None)
    assert rc == 0
