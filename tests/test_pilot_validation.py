"""Table-driven config validation tests toward the reference's
rejection set (pilot/pkg/model/validation.go, ~2,500 LoC of checks).
Each case is (kind, spec, expected-error-substring | None)."""
import pytest

from istio_tpu.pilot.model import IstioConfigTypes, ValidationError

DEST = {"destination": {"name": "reviews"}}

CASES = [
    # ---- route-rule: required fields ----
    ("route-rule", {}, "destination required"),
    ("route-rule", DEST, None),
    # weights
    ("route-rule", {**DEST, "route": [{"labels": {"v": "1"}, "weight": 60},
                                      {"labels": {"v": "2"},
                                       "weight": 30}]},
     "weights sum to 90"),
    ("route-rule", {**DEST, "route": [{"labels": {"v": "1"}, "weight": 60},
                                      {"labels": {"v": "2"},
                                       "weight": 40}]}, None),
    ("route-rule", {**DEST, "route": [{"weight": -5}]}, "weight must be"),
    ("route-rule", {**DEST, "route": [{"weight": 120}]}, "weight must be"),
    ("route-rule", {**DEST, "route": [{"weight": 55}]},
     "single-route weight"),
    ("route-rule", {**DEST, "route": [{"labels": {"v": "1"}}]}, None),
    # conflicting / unknown match schemes
    ("route-rule", {**DEST, "match": {"request": {"headers": {
        "uri": {"exact": "/a", "prefix": "/b"}}}}}, "conflicting schemes"),
    ("route-rule", {**DEST, "match": {"request": {"headers": {
        "uri": {"suffix": "/a"}}}}}, "unknown scheme"),
    ("route-rule", {**DEST, "match": {"request": {"headers": {
        "cookie": {"regex": ".*"}}}}}, None),
    # redirect exclusivity
    ("route-rule", {**DEST, "redirect": {"uri": "/new"},
                    "route": [{"weight": 100}]}, "mutually exclusive"),
    ("route-rule", {**DEST, "redirect": {"uri": "/new"},
                    "httpFault": {"abort": {"percent": 50}}},
     "cannot carry httpFault"),
    ("route-rule", {**DEST, "redirect": {"uri": "/new"}}, None),
    # fault percentages / status / durations
    ("route-rule", {**DEST, "httpFault": {"abort": {
        "percent": 150, "httpStatus": 500}}}, "out of [0, 100]"),
    ("route-rule", {**DEST, "httpFault": {"abort": {
        "percent": 50, "httpStatus": 99}}}, "httpStatus 99 invalid"),
    ("route-rule", {**DEST, "httpFault": {"delay": {
        "percent": 50, "fixedDelay": "abc"}}}, "bad duration"),
    ("route-rule", {**DEST, "httpFault": {"delay": {
        "percent": 50, "fixedDelay": "5s"}}}, None),
    # timeout / retries / precedence
    ("route-rule", {**DEST, "httpReqTimeout": {"simpleTimeout": {
        "timeout": "-3s"}}}, "negative duration"),
    ("route-rule", {**DEST, "httpReqRetries": {"simpleRetry": {
        "attempts": -1}}}, "negative retry"),
    ("route-rule", {**DEST, "precedence": -2}, "negative precedence"),
    ("route-rule", {**DEST, "mirror": "not-a-message"}, "mirror must be"),
    # ---- v1alpha2 ----
    ("v1alpha2-route-rule", {"http": []}, "hosts required"),
    ("v1alpha2-route-rule", {"hosts": ["a"], "http": [
        {"route": [{"destination": {"host": "a"}, "weight": 30},
                   {"destination": {"host": "b"}, "weight": 30}]}]},
     "weights sum to 60"),
    ("v1alpha2-route-rule", {"hosts": ["a"], "http": [
        {"route": [{"weight": 100}]}]}, "needs destination"),
    # ---- destination-policy ----
    ("destination-policy", {}, "destination required"),
    ("destination-policy", {**DEST, "loadBalancing": {
        "name": "MAGIC"}}, "unknown LB policy"),
    ("destination-policy", {**DEST, "circuitBreaker": {"simpleCb": {
        "maxConnections": -1}}}, "negative maxConnections"),
    ("destination-policy", {**DEST, "circuitBreaker": {"simpleCb": {
        "sleepWindow": "xyz"}}}, "bad duration"),
    ("destination-policy", {**DEST, "loadBalancing": {
        "name": "LEAST_CONN"}}, None),
    # ---- destination-rule ----
    ("destination-rule", {"host": "x", "subsets": [
        {"labels": {"v": "1"}}]}, "subset needs a name"),
    ("destination-rule", {"host": "x", "subsets": [
        {"name": "a", "labels": {"v": "1"}},
        {"name": "a", "labels": {"v": "2"}}]}, "duplicate subset"),
    ("destination-rule", {"host": "x", "subsets": [
        {"name": "a"}]}, "needs labels"),
    # ---- gateway ----
    ("gateway", {}, "servers required"),
    ("gateway", {"servers": [{"hosts": ["*"]}]}, "needs a port"),
    ("gateway", {"servers": [{"port": {"number": 70000},
                              "hosts": ["*"]}]}, "out of [1, 65535]"),
    ("gateway", {"servers": [{"port": {"number": 443}}]}, "needs hosts"),
    ("gateway", {"servers": [{"port": {"number": 443},
                              "hosts": ["*"]}]}, None),
    # ---- egress-rule ----
    ("egress-rule", {"destination": {"service": "a.*.com"},
                     "ports": [{"port": 80}]}, "leading label"),
    ("egress-rule", {"destination": {"service": "ex.com"},
                     "ports": [{"port": 0}]}, "out of [1, 65535]"),
    ("egress-rule", {"destination": {"service": "ex.com"},
                     "ports": [{"port": 80, "protocol": "quic"}]},
     "unsupported protocol"),
    ("egress-rule", {"destination": {"service": "*.ex.com"},
                     "ports": [{"port": 80, "protocol": "http"}]}, None),
    # ---- ingress-rule ----
    ("ingress-rule", {"destination": {"service": "x"}, "port": 99999},
     "out of [1, 65535]"),
    ("ingress-rule", {"destination": {"service": "x"}, "port": "http"},
     None),
]


@pytest.mark.parametrize("kind,spec,err", CASES,
                         ids=[f"{k}-{i}" for i, (k, _, e)
                              in enumerate(CASES)])
def test_validation(kind, spec, err):
    schema = IstioConfigTypes[kind]
    if err is None:
        schema.validate(spec)
    else:
        with pytest.raises(ValidationError) as exc:
            schema.validate(spec)
        assert err in str(exc.value)
