"""Telemetry ingestion plane (the REPORT half of Mixer's API):
ack-after-enqueue admission, bounded-coalescer typed overflow, and —
the plane's correctness invariant — EXACT record conservation
(accepted == adapter-exported + typed-rejected) across normal
serving, overload, RuntimeServer.shutdown drains (the PR 7 quiesce
ordering: admission → pump → device → flush → join extends to the
report coalescer) and config swaps."""
import threading
import time

import pytest

from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs
from istio_tpu.runtime import monitor
from istio_tpu.testing import workloads


class SinkHandler:
    """Counts records; optionally blocks (wedging the coalescer)."""

    def __init__(self, block: threading.Event | None = None):
        self.block = block
        self.records = 0
        self._lock = threading.Lock()

    def handle_report(self, template, instances) -> None:
        if self.block is not None:
            self.block.wait(timeout=30)
        with self._lock:
            self.records += len(instances)


def _mesh_server(**kw) -> RuntimeServer:
    defaults = dict(batch_window_s=0.0005, max_batch=8, buckets=(4, 8),
                    default_manifest=workloads.MESH_MANIFEST)
    defaults.update(kw)
    return RuntimeServer(workloads.make_store(8), ServerArgs(**defaults))


def _sink(srv: RuntimeServer,
          block: threading.Event | None = None) -> SinkHandler:
    h = SinkHandler(block=block)
    srv.controller.dispatcher.handlers["prom.istio-system"] = h
    return h


def _drain_cons(base: dict, deadline_s: float = 20.0) -> dict:
    end = time.time() + deadline_s
    cons = monitor.report_conservation(since=base)
    while time.time() < end:
        cons = monitor.report_conservation(since=base)
        if cons["in_flight"] == 0:
            break
        time.sleep(0.01)
    return cons


def _bags(n: int, seed: int = 2):
    return [bag_from_mapping(d)
            for d in workloads.make_request_dicts(n, seed=seed)]


def test_conservation_exact_through_coalescer():
    """N records through submit_report all export; accepted ==
    exported + rejected exactly, and the adapter saw every record."""
    srv = _mesh_server()
    try:
        sink = _sink(srv)
        base = monitor.report_conservation()
        futs = srv.submit_report(_bags(20))
        assert len(futs) == 20
        cons = _drain_cons(base)
        assert cons["accepted"] == 20
        assert cons["exported"] == 20
        assert cons["rejected_total"] == 0
        assert cons["exact"] and cons["in_flight"] == 0
        assert sink.records == 20
    finally:
        srv.close()


def test_ack_after_enqueue_is_nonblocking():
    """submit_report returns BEFORE the device trip: with the adapter
    wedged, admission must still come back immediately (the native
    pump acks on it) — and every record still resolves once freed."""
    block = threading.Event()
    srv = _mesh_server()
    try:
        sink = _sink(srv, block=block)
        base = monitor.report_conservation()
        t0 = time.perf_counter()
        futs = srv.submit_report(_bags(4))
        enq = time.perf_counter() - t0
        # admission is queue-put + accounting only; a second means it
        # waited out the wedged dispatch
        assert enq < 1.0, f"submit_report blocked {enq:.3f}s"
        assert not any(f.done() for f in futs)
        block.set()
        cons = _drain_cons(base)
        assert cons["exported"] == 4 and cons["exact"]
        assert sink.records == 4
    finally:
        block.set()
        srv.close()


def test_overflow_sheds_typed_resource_exhausted():
    """A full bounded coalescer sheds ResourceExhaustedError (typed,
    mapped to RESOURCE_EXHAUSTED on every front) and the sheds are
    conservation-counted as queue_full — nothing silently dropped."""
    from istio_tpu.runtime.resilience import ResourceExhaustedError

    block = threading.Event()
    srv = _mesh_server(report_queue_cap=3, pipeline=1, max_batch=4,
                       buckets=(4,))
    try:
        sink = _sink(srv, block=block)
        base = monitor.report_conservation()
        shed = None
        all_futs = []
        for _ in range(40):
            futs = srv.submit_report(_bags(2))
            all_futs += futs
            shed = next((f.exception() for f in futs
                         if f.done() and f.exception()), None)
            if shed is not None:
                break
            time.sleep(0.01)
        assert isinstance(shed, ResourceExhaustedError), shed
        block.set()
        cons = _drain_cons(base)
        assert cons["exact"] and cons["in_flight"] == 0
        assert cons["rejected"]["queue_full"] > 0
        assert cons["accepted"] == \
            cons["exported"] + cons["rejected_total"]
        # the adapter saw exactly the exported records
        assert sink.records == cons["exported"]
        # drop reasons surfaced for /debug/report
        drops = monitor.report_counters()["recent_drops"]
        assert any(d["reason"] == "queue_full" for d in drops)
    finally:
        block.set()
        srv.close()


def test_no_record_dropped_across_shutdown_drain():
    """The quiesce ordering extends to the report coalescer: records
    in flight at shutdown() either export (drained) or typed-reject
    (leftovers past the deadline) — the conservation ledger balances
    exactly either way, never a silent drop."""
    block = threading.Event()
    srv = _mesh_server(pipeline=1)
    sink = _sink(srv, block=block)
    base = monitor.report_conservation()
    futs = srv.submit_report(_bags(12))
    assert len(futs) == 12

    def release():
        time.sleep(0.3)
        block.set()

    t = threading.Thread(target=release, daemon=True)
    t.start()
    srv.shutdown(deadline=10.0)
    t.join()
    cons = _drain_cons(base, deadline_s=5.0)
    assert cons["accepted"] == 12
    assert cons["exact"] and cons["in_flight"] == 0, cons
    assert cons["exported"] + cons["rejected_total"] == 12
    # post-quiesce submits shed typed UNAVAILABLE, counted too
    futs2 = srv.submit_report(_bags(1))
    assert futs2[0].exception() is not None
    cons2 = monitor.report_conservation(since=base)
    assert cons2["accepted"] == 13 and cons2["exact"]


def test_no_record_dropped_across_config_swap():
    """Records submitted around an atomic config publish all resolve
    and the ledger stays exact — a swap must not orphan in-flight
    report batches (the old dispatcher's batches run to completion)."""
    store = workloads.make_store(8)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=8, buckets=(4, 8),
        default_manifest=workloads.MESH_MANIFEST))
    try:
        _sink(srv)
        rev0 = srv.controller.dispatcher.snapshot.revision
        base = monitor.report_conservation()
        bags = _bags(24)
        futs = []
        futs += srv.submit_report(bags[:8])
        # trigger a rebuild + publish mid-stream
        store.set(("rule", "istio-system", "swap-marker"), {
            "match": 'request.method == "PATCH"',
            "actions": [{"handler": "denyall",
                         "instances": ["nothing"]}]})
        futs += srv.submit_report(bags[8:16])
        deadline = time.time() + 20
        while time.time() < deadline and \
                srv.controller.dispatcher.snapshot.revision == rev0:
            time.sleep(0.02)
        assert srv.controller.dispatcher.snapshot.revision != rev0
        futs += srv.submit_report(bags[16:])
        cons = _drain_cons(base)
        assert cons["accepted"] == 24
        assert cons["exact"] and cons["in_flight"] == 0, cons
        assert cons["exported"] + cons["rejected_total"] == 24
        for f in futs:
            assert f.done()
    finally:
        srv.close()


def test_audit_types_conservation_across_config_swap():
    """The swap-exactness guarantee above, but TYPED: the mesh audit
    plane's report_conservation invariant (runtime/audit.py) judges
    the ledger around a mid-batch config publish. Mid-flight it may
    read degraded (records legitimately in transit) but never
    violated; once drained it must settle back to ok with
    accepted == exported + typed_rejected exactly — the regression
    this pins is a swap silently orphaning in-flight report batches,
    which previously only surfaced as a loud shutdown log line."""
    store = workloads.make_store(8)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=8, buckets=(4, 8),
        default_manifest=workloads.MESH_MANIFEST))
    try:
        _sink(srv)
        aud = srv.audit
        assert aud is not None  # on by default

        def rc_check(snap):
            return next(c for c in snap["checks"]
                        if c["name"] == "report_conservation")

        pre = rc_check(aud.evaluate())
        # conservation is a process-global invariant: a dirty ledger
        # here means some OTHER path already leaked — fail loudly
        assert pre["status"] == "ok", pre

        rev0 = srv.controller.dispatcher.snapshot.revision
        base = monitor.report_conservation()
        bags = _bags(24)
        futs = srv.submit_report(bags[:8])
        store.set(("rule", "istio-system", "swap-marker"), {
            "match": 'request.method == "PATCH"',
            "actions": [{"handler": "denyall",
                         "instances": ["nothing"]}]})
        futs += srv.submit_report(bags[8:16])
        deadline = time.time() + 20
        while time.time() < deadline and \
                srv.controller.dispatcher.snapshot.revision == rev0:
            # mid-swap, in-flight records are at worst degraded —
            # "violated" would mean the auditor thinks the swap is
            # dropping records while they are merely in transit
            assert rc_check(aud.evaluate())["status"] != "violated"
            time.sleep(0.02)
        assert srv.controller.dispatcher.snapshot.revision != rev0
        futs += srv.submit_report(bags[16:])
        cons = _drain_cons(base)
        assert cons["accepted"] == 24

        post = rc_check(aud.evaluate())
        assert post["status"] == "ok", post
        assert post["evidence"]["in_flight"] == 0
        assert post["evidence"]["accepted"] == \
            post["evidence"]["exported"] + \
            post["evidence"]["rejected_total"]
        for f in futs:
            assert f.done()
    finally:
        srv.close()


def test_coalesce_wait_feeds_report_not_check_stages():
    """The report batcher's queue-wait lands in the REPORT pipeline's
    coalesce_wait — never in the Check decomposition's queue_wait
    (the live p99 / SLO gauges are judged on check stages only)."""
    srv = _mesh_server()
    try:
        _sink(srv)
        check_base = monitor.stage_baseline()
        rep_base = monitor.report_stage_baseline()
        cons_base = monitor.report_conservation()
        futs = srv.submit_report(_bags(6))
        _drain_cons(cons_base)
        for f in futs:
            f.result(timeout=20)
        rep = monitor.report_latency_snapshot(since=rep_base)["stages"]
        assert rep.get("coalesce_wait", {}).get("count", 0) > 0
        chk = monitor.latency_snapshot(since=check_base)["stages"]
        assert chk.get("queue_wait", {}).get("count", 0) == 0
    finally:
        srv.close()


def test_inline_path_conserves_without_coalescer():
    """report_batching=False (inline dispatch) keeps the same ledger:
    accepted == exported, no futures involved."""
    srv = _mesh_server(report_batching=False)
    try:
        sink = _sink(srv)
        base = monitor.report_conservation()
        futs = srv.submit_report(_bags(5))
        assert futs == []
        cons = monitor.report_conservation(since=base)
        assert cons["accepted"] == 5 and cons["exported"] == 5
        assert cons["exact"] and sink.records == 5
    finally:
        srv.close()


def test_report_families_present_in_exposition():
    """Zero-series doctrine: the report counter families and the
    stage histogram expose from the first scrape — every rejection
    reason pre-touched, the histogram's zero ladder emitted (PR 1's
    promtext conformance contract extended to the report plane)."""
    import prometheus_client

    from istio_tpu.utils.metrics import default_registry
    from tests.test_metrics_exposition import lint_histograms

    text = default_registry.expose_text()
    lint_histograms(text, expect={"mixer_report_stage_seconds"})
    assert "mixer_report_template_records_total" in text
    assert "mixer_report_exporter_records_total" in text
    prom = prometheus_client.generate_latest(
        monitor.REGISTRY).decode()
    assert "mixer_report_records_accepted_total" in prom
    assert "mixer_report_records_exported_total" in prom
    for reason in monitor.REPORT_REJECT_REASONS:
        assert f'reason="{reason}"' in prom, reason


def test_debug_report_view_serves_and_agrees():
    """/debug/report over real HTTP: zero-shaped on an idle server,
    and in agreement with the live conservation counters after
    traffic."""
    import json
    import urllib.request

    from istio_tpu.introspect import IntrospectServer

    srv = _mesh_server()
    intro = IntrospectServer(runtime=srv)
    try:
        port = intro.start()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/report",
                timeout=20) as r:
            view = json.loads(r.read().decode())
        for key in ("stages", "conservation", "coalescer", "policy",
                    "templates", "exporters", "recent_drops"):
            assert key in view, key
        assert view["coalescer"]["max_queue"] == 16 * 8
        _sink(srv)
        base = monitor.report_conservation()
        srv.report(_bags(4))
        _drain_cons(base)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/report",
                timeout=20) as r:
            view = json.loads(r.read().decode())
        live = monitor.report_conservation()
        assert view["conservation"]["accepted"] == live["accepted"]
        assert view["conservation"]["exported"] == live["exported"]
    finally:
        intro.close()
        srv.close()


def test_native_report_ack_after_enqueue():
    """The native pump acks a Report after ENQUEUE and never blocks
    its take loop on a device trip; records conserve exactly across
    the wire. Skipped when the C++ toolchain is unavailable."""
    from istio_tpu.api.client import MixerClient

    try:
        from istio_tpu.api.native_server import NativeMixerServer
        srv = _mesh_server()
        native = NativeMixerServer(srv, pumps=1)
    except Exception as exc:   # toolchain missing
        pytest.skip(f"native toolchain unavailable: {exc}")
    client = None
    try:
        sink = _sink(srv)
        port = native.start()
        client = MixerClient(f"127.0.0.1:{port}",
                             enable_check_cache=False)
        base = monitor.report_conservation()
        dicts = workloads.make_request_dicts(18, seed=4)
        for lo in range(0, 18, 6):
            client.report(dicts[lo:lo + 6])
        cons = _drain_cons(base)
        assert cons["accepted"] == 18
        assert cons["exported"] == 18 and cons["exact"], cons
        assert sink.records == 18
        # rpc.report wire counters mirrored into the shared registry
        counters = monitor.report_counters()
        assert counters["rpcs_decoded"] >= 3
    finally:
        if client is not None:
            client.close()
        native.stop()
        srv.close()
