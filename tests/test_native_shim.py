"""Native C++ wire→tensor shim conformance: byte-for-byte equality with
the Python Tensorizer on randomized wire batches, intern-table mirror
consistency, and a throughput sanity check."""
import datetime
import time

import numpy as np
import pytest

from istio_tpu.api.wire import bag_to_compressed
from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.attribute.types import ValueType as V
from istio_tpu.compiler.layout import InternTable, Tensorizer, build_layout
from istio_tpu.expr.checker import AttributeDescriptorFinder

try:
    from istio_tpu.native import NativeBuildError, NativeTensorizer, \
        ensure_built
    ensure_built()
    HAVE_NATIVE = True
except Exception as exc:      # toolchain missing → skip, not fail
    HAVE_NATIVE = False
    SKIP_REASON = str(exc)

pytestmark = pytest.mark.skipif(not HAVE_NATIVE,
                                reason="native shim unavailable")

MANIFEST = {
    "destination.service": V.STRING, "source.namespace": V.STRING,
    "source.ip": V.IP_ADDRESS, "request.size": V.INT64,
    "request.time": V.TIMESTAMP, "response.duration": V.DURATION,
    "connection.mtls": V.BOOL, "request.path": V.STRING,
    "request.headers": V.STRING_MAP, "score": V.DOUBLE,
}


def _world(seed=0, n=64):
    rng = np.random.default_rng(seed)
    dicts = []
    for i in range(n):
        d = {
            "destination.service":
                f"svc{rng.integers(0, 9)}.ns{i % 5}.svc.cluster.local",
            "request.size": int(rng.integers(0, 1 << 40)),
            "connection.mtls": bool(rng.random() < 0.5),
        }
        if rng.random() < 0.8:
            d["source.namespace"] = f"ns{rng.integers(0, 6)}"
        if rng.random() < 0.6:
            d["request.path"] = f"/api/v{i % 3}/items/{i}"
        if rng.random() < 0.5:
            d["request.headers"] = {"cookie": f"u={i % 7}",
                                    ":authority": "web"}
        if rng.random() < 0.5:
            d["source.ip"] = b"\x00" * 10 + b"\xff\xff" + \
                bytes(rng.integers(0, 255, 4, dtype=np.uint8).tolist())
        if rng.random() < 0.4:
            d["request.time"] = datetime.datetime(
                2018, 1, int(rng.integers(1, 28)), 12, 0, 5,
                tzinfo=datetime.timezone.utc)
        if rng.random() < 0.4:
            d["response.duration"] = datetime.timedelta(
                milliseconds=int(rng.integers(1, 5000)))
        if rng.random() < 0.3:
            d["score"] = float(np.round(rng.random(), 6))
        dicts.append(d)
    return dicts


def _rig():
    finder = AttributeDescriptorFinder(MANIFEST)
    layout = build_layout(
        MANIFEST,
        derived_keys=[("request.headers", "cookie"),
                      ("request.headers", ":authority")],
        byte_sources=["request.path", ("request.headers", "cookie")])
    interner = InternTable()
    # pre-seed some compile-time constants (the engine does this)
    for v in ("svc0.ns0.svc.cluster.local", "GET", 42):
        interner.intern(v)
    return layout, interner


def test_numeric_order_key_byte_slots_match_python():
    """Ordered comparisons read 8-byte order keys from the byte planes
    (layout.order_key_bytes); the shim must emit IDENTICAL bytes for
    INT64/DOUBLE/DURATION/TIMESTAMP slots — including the NaN (empty)
    and malformed-payload (len-1) markers — or device `<`/`>` verdicts
    would differ by ingest path."""
    layout = build_layout(
        MANIFEST,
        byte_sources=["request.size", "score", "response.duration",
                      "request.time", "request.path"])
    interner = InternTable()
    native = NativeTensorizer(layout, interner)
    dicts = _world(seed=5, n=96)
    dicts += [
        {"request.size": -(1 << 40), "score": -0.0},
        {"score": float("nan"), "request.size": 0},
        {"score": 1.5e308, "request.size": (1 << 62)},
        {"response.duration": datetime.timedelta(microseconds=1)},
    ]
    records = [bag_to_compressed(d).SerializeToString() for d in dicts]
    got = native.tensorize_wire(records)
    want = Tensorizer(layout, interner).tensorize(
        [bag_from_mapping(d) for d in dicts])
    np.testing.assert_array_equal(np.asarray(got.str_lens),
                                  np.asarray(want.str_lens))
    np.testing.assert_array_equal(np.asarray(got.str_bytes),
                                  np.asarray(want.str_bytes))
    # malformed: a STRING value arriving under the numeric attr name
    from istio_tpu.api import mixer_pb2 as pb
    req = pb.CompressedAttributes()
    req.words.append("request.size")   # message-local word 0
    req.words.append("junk")           # message-local word 1
    req.strings[0] = 1                 # request.size = "junk" (STRING)
    got2 = native.tensorize_wire([req.SerializeToString()])
    bcol = layout.byte_slots["request.size"]
    assert int(np.asarray(got2.str_lens)[0, bcol]) == 1  # error marker


def test_wire_conformance_vs_python_tensorizer():
    layout, interner = _rig()
    native = NativeTensorizer(layout, interner)
    dicts = _world(n=128)
    records = [bag_to_compressed(d).SerializeToString() for d in dicts]

    got = native.tensorize_wire(records)
    oracle = Tensorizer(layout, interner, hash_slots="all").tensorize(
        [bag_from_mapping(d) for d in dicts])

    # constants share exact non-negative ids; runtime values get
    # per-batch ephemeral ids whose DECODED values must agree; within
    # each batch the id ↔ value mapping must be a bijection
    gi, oi = np.asarray(got.ids), np.asarray(oracle.ids)
    gp = np.asarray(got.present)
    assert gi.shape == oi.shape
    from istio_tpu.compiler.layout import _normalize, stable_hash31
    id_to_val: dict[int, tuple] = {}
    val_to_id: dict[tuple, int] = {}
    for r in range(gi.shape[0]):
        for c in range(gi.shape[1]):
            if not gp[r, c]:
                continue
            a, b = int(gi[r, c]), int(oi[r, c])
            va = _normalize(got.value_of(a, interner))
            if a >= 0 or b >= 0:
                assert a == b, (r, c, a, b)
            else:
                assert va == _normalize(oracle.value_of(b, interner)), \
                    (r, c)
            # bijection: same id ⇔ same value across the whole batch
            assert id_to_val.setdefault(a, va) == va, (r, c, a)
            assert val_to_id.setdefault(va, a) == a, (r, c, va)
            # the stable hash plane matches the python formula
            assert int(np.asarray(got.hash_ids)[r, c]) == \
                stable_hash31(got.value_of(a, interner)), (r, c)
    np.testing.assert_array_equal(np.asarray(got.present),
                                  np.asarray(oracle.present))
    np.testing.assert_array_equal(np.asarray(got.hash_ids) * gp,
                                  np.asarray(oracle.hash_ids) *
                                  np.asarray(oracle.present))
    np.testing.assert_array_equal(np.asarray(got.map_present),
                                  np.asarray(oracle.map_present))
    np.testing.assert_array_equal(np.asarray(got.str_bytes),
                                  np.asarray(oracle.str_bytes))
    np.testing.assert_array_equal(np.asarray(got.str_lens),
                                  np.asarray(oracle.str_lens))


def test_repeated_batches_share_interns():
    layout, interner = _rig()
    native = NativeTensorizer(layout, interner)
    recs = [bag_to_compressed(d).SerializeToString()
            for d in _world(seed=1, n=16)]
    b1 = native.tensorize_wire(recs)
    size_after_first = len(interner)
    b2 = native.tensorize_wire(recs)      # same values → no new ids
    assert len(interner) == size_after_first
    np.testing.assert_array_equal(np.asarray(b1.ids),
                                  np.asarray(b2.ids))


def test_intern_table_bounded_by_flush():
    """ADVICE r1: distinct runtime values must not grow the shared
    intern table, and the shim's own table flushes at the threshold
    while in-flight batches keep resolving their values."""
    layout, interner = _rig()
    native = NativeTensorizer(layout, interner)
    native._flush_threshold = 32
    size0 = len(interner)
    batches = []
    for seed in range(4):
        dicts = _world(seed=seed, n=32)
        recs = [bag_to_compressed(d).SerializeToString() for d in dicts]
        batches.append(native.tensorize_wire(recs))
    assert len(interner) == size0          # python table: zero growth
    # shim table flushed at least once (runtime entries dropped)
    assert len(native._runtime_values) <= 3 * native._flush_threshold
    # earlier batches still resolve their ephemeral ids
    first = batches[0]
    ids = np.asarray(first.ids)
    present = np.asarray(first.present)
    r, c = np.argwhere(ids < 0)[0]
    assert present[r, c]
    assert first.value_of(int(ids[r, c]), interner) is not None


def test_parse_error_reported():
    layout, interner = _rig()
    native = NativeTensorizer(layout, interner)
    with pytest.raises(ValueError, match="parse failure"):
        native.tensorize_wire([b"\xff\xff\xff\xff garbage"])


def _timed(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def test_throughput_exceeds_python():
    layout, interner = _rig()
    native = NativeTensorizer(layout, interner)
    dicts = _world(seed=2, n=512)
    records = [bag_to_compressed(d).SerializeToString() for d in dicts]
    bags = [bag_from_mapping(d) for d in dicts]
    native.tensorize_wire(records)        # warm interns

    # best-of-N on both sides: scheduler noise from other tests'
    # background threads must not fail a relative-speed assertion
    t_native = min(
        _timed(lambda: native.tensorize_wire(records)) for _ in range(5))
    py = Tensorizer(layout, interner)
    t_py = min(_timed(lambda: py.tensorize(bags)) for _ in range(5))
    speedup = t_py / t_native
    # require 2×; typically far higher — and the python figure EXCLUDES
    # its share of wire decode. (3× flaked at 2.78× under full-suite
    # load on a 1-core box after the python tensorizer got faster —
    # ADVICE r2; the margin guards "native is pointless", not a perf SLO)
    assert speedup > 2, f"native only {speedup:.1f}× python"
