"""Secure serving plane units (istio_tpu/secure): the PkiBackend seam,
WorkloadIdentity lifecycle + executor maintenance-lane registration,
ServingCerts hot rotation, SPIFFE extraction, the identity axis of the
grant plane, the client-side principal cache fold, and the permissive
and native-TLS-lane front postures the strict-mode smoke
(scripts/mtls_smoke.py) doesn't cover."""
from __future__ import annotations

import time
import types

import grpc
import pytest

from istio_tpu.secure.backend import available_backends

if not available_backends():
    pytest.skip("secure plane needs a PKI backend (cryptography or "
                "the openssl CLI)", allow_module_level=True)

from istio_tpu.api.client import MixerClient
from istio_tpu.api.grpc_server import MixerGrpcServer
from istio_tpu.runtime import MemStore, RuntimeServer, ServerArgs
from istio_tpu.runtime import monitor
from istio_tpu.secure.identity import WorkloadIdentity
from istio_tpu.secure.mtls import ServingCerts, spiffe_identity_from_pem
from istio_tpu.security import IstioCA, pki, spiffe_id

WEB = spiffe_id("default", "web")


@pytest.fixture(scope="module")
def ca():
    return IstioCA.new_self_signed({})


class InProcessCA:
    """CAClient-shaped duck signing straight through an IstioCA — the
    WorkloadIdentity units don't need the gRPC hop."""

    def __init__(self, ca, fail: bool = False, reject: bool = False):
        self.ca = ca
        self.fail = fail
        self.reject = reject
        self.calls = 0

    def sign_csr(self, csr_pem, credential=b"", credential_type="",
                 ttl_minutes=0):
        self.calls += 1
        if self.fail:
            raise ConnectionError("CA down")
        if self.reject:
            return types.SimpleNamespace(
                is_approved=False, signed_cert=b"", cert_chain=b"",
                status_message="authorization failed")
        import datetime
        cert = self.ca.sign(csr_pem, datetime.timedelta(
            minutes=ttl_minutes) if ttl_minutes else None)
        return types.SimpleNamespace(
            is_approved=True, signed_cert=cert,
            cert_chain=self.ca.get_root_certificate(),
            status_message="")


def _serving(ca, dns=("mixer.local",)):
    key = pki.generate_key()
    cert = ca.sign(pki.generate_csr(
        key, spiffe_id("istio-system", "mixer"), dns_names=dns))
    return ServingCerts(pki.key_to_pem(key), cert,
                        ca.get_root_certificate())


# -- backend seam ------------------------------------------------------

def test_backend_seam_reports_a_live_backend():
    names = available_backends()
    assert names
    assert set(names) <= {"cryptography", "openssl"}


def test_backend_pem_interops_with_tls_stack(ca):
    """The active backend's PEM output must parse back through the
    seam (subject, SANs, TTL) — the byte-compatibility contract."""
    key = pki.generate_key()
    cert = ca.sign(pki.generate_csr(key, WEB, dns_names=("web.local",)),
                   __import__("datetime").timedelta(minutes=7))
    assert pki.san_uris(cert) == [WEB]
    assert "web.local" in pki.san_dns(cert)
    remaining = (pki.not_after(cert)
                 - __import__("datetime").datetime.now(
                     __import__("datetime").timezone.utc)
                 ).total_seconds()
    assert 0 < remaining < 10 * 60


# -- ServingCerts ------------------------------------------------------

def test_serving_certs_rotation_bumps_generation(ca):
    certs = _serving(ca)
    assert certs.generation == 1
    key2 = pki.generate_key()
    cert2 = ca.sign(pki.generate_csr(
        key2, spiffe_id("istio-system", "mixer"),
        dns_names=("mixer.local",)))
    gen = certs.rotate(pki.key_to_pem(key2), cert2)
    assert gen == 2
    k, c, r, g = certs.bundle()
    assert (k, c, g) == (pki.key_to_pem(key2), cert2, 2)
    assert r == ca.get_root_certificate()    # root carried over


def test_serving_certs_context_memoized_per_generation(ca):
    certs = _serving(ca)
    c1 = certs.ssl_server_context()
    assert certs.ssl_server_context() is c1
    assert certs.ssl_server_context(require_client_cert=True) is not c1
    key2 = pki.generate_key()
    certs.rotate(pki.key_to_pem(key2), ca.sign(pki.generate_csr(
        key2, spiffe_id("istio-system", "mixer"))))
    assert certs.ssl_server_context() is not c1


def test_spiffe_identity_extraction(ca):
    key = pki.generate_key()
    cert = ca.sign(pki.generate_csr(key, WEB))
    assert spiffe_identity_from_pem(cert) == WEB
    bare = ca.sign(pki.generate_csr(pki.generate_key(), None, org="x"))
    assert spiffe_identity_from_pem(bare) is None


# -- WorkloadIdentity lifecycle ---------------------------------------

def test_identity_issue_and_rotate(ca):
    seen = []
    wi = WorkloadIdentity(InProcessCA(ca), WEB, ttl_minutes=5,
                          on_rotate=(seen.append,))
    assert wi.due()                      # no bundle yet
    key_pem, cert_pem, root_pem = wi.ensure()
    assert pki.san_uris(cert_pem) == [WEB]
    assert root_pem == ca.get_root_certificate()
    assert wi.generation == 1 and not wi.due()
    assert wi.ensure() == (key_pem, cert_pem, root_pem)  # cached
    wi.rotate()
    assert wi.generation == 2 and wi.rotations == 1
    assert len(seen) == 2 and seen[1][1] != cert_pem
    stats = wi.stats()
    assert stats["identity"] == WEB and stats["failures"] == 0
    assert stats["remaining_ttl_s"] > 0


def test_identity_failure_paths_are_counted(ca):
    base = monitor.identity_counters()["events"]["issue"]["failed"]
    wi = WorkloadIdentity(InProcessCA(ca, fail=True), WEB)
    with pytest.raises(ConnectionError):
        wi.ensure()
    assert wi.failures == 1 and "ConnectionError" in wi.last_error
    rej = WorkloadIdentity(InProcessCA(ca, reject=True), WEB)
    with pytest.raises(RuntimeError, match="CSR rejected"):
        rej.ensure()
    now = monitor.identity_counters()["events"]["issue"]["failed"]
    assert now >= base + 2


def test_identity_refresh_rotates_when_due(ca):
    client = InProcessCA(ca)
    # rotation_fraction=1.0: due the instant a bundle exists — every
    # maintenance tick rotates (the soak cadence trick)
    wi = WorkloadIdentity(client, WEB, ttl_minutes=5,
                          rotation_fraction=1.0)
    wi.refresh()                         # no bundle -> issue
    assert wi.generation == 1 and wi.rotations == 0
    wi.refresh()                         # due -> rotate
    assert wi.generation == 2 and wi.rotations == 1
    calm = WorkloadIdentity(client, WEB, ttl_minutes=5,
                            rotation_fraction=0.1)
    calm.refresh()
    calm.refresh()                       # fresh cert: not due
    assert calm.generation == 1


def test_identity_rides_executor_maintenance_lane(ca):
    srv = RuntimeServer(MemStore(), ServerArgs(batch_window_s=0.001))
    try:
        assert srv.executor is not None
        wi = WorkloadIdentity(InProcessCA(ca), WEB, ttl_minutes=5,
                              rotation_fraction=1.0,
                              refresh_interval_s=0.05)
        srv.executor.register_refreshable("workload_identity", wi)
        deadline = time.time() + 10
        while wi.generation < 2 and time.time() < deadline:
            time.sleep(0.05)
        assert wi.generation >= 2        # issued AND rotated by lane
        # a config republish rebuilds the registry; the persistent
        # refreshable must survive it
        srv.executor.register_refreshables({})
        gen = wi.generation
        deadline = time.time() + 10
        while wi.generation == gen and time.time() < deadline:
            time.sleep(0.05)
        assert wi.generation > gen
    finally:
        srv.close()


# -- identity axis of the grant plane ---------------------------------

def test_identity_grant_fold():
    srv = RuntimeServer(MemStore(), ServerArgs(batch_window_s=0.001,
                                               check_grants=True))
    try:
        g = srv.grants
        ttl, uses = g.identity_grant(WEB)
        assert (ttl, uses) == (g.ttl_cap_s, g.use_cap)   # never rotated
        g.on_identity_rotate(WEB)
        ttl, _ = g.identity_grant(WEB)
        assert ttl <= g.ttl_floor_s + 0.5
        st = g.stats()
        assert st["identity_revocations"] == 1
        assert st["identities_tracked"] == 1
    finally:
        srv.close()


def test_client_signature_folds_principal(ca):
    from istio_tpu.api import mixer_pb2 as pb
    key = pki.generate_key()
    cert = ca.sign(pki.generate_csr(key, WEB))
    cl = MixerClient("127.0.0.1:1", root_cert_pem=b"-----BEGIN "
                     b"CERTIFICATE-----\n-----END CERTIFICATE-----\n",
                     key_pem=pki.key_to_pem(key), cert_pem=cert)
    try:
        assert cl._identity == WEB
        sig = cl._signature(pb.ReferencedAttributes(), {})
        assert sig[0] == ("__peer_identity__", None, WEB)
        cl._cache[("x",)] = ["entry"]
        cl.set_identity(WEB)             # same principal: cache kept
        assert cl._cache
        cl.set_identity(spiffe_id("default", "other"))
        assert not cl._cache             # principal changed: dropped
        assert cl._signature(pb.ReferencedAttributes(), {})[0][2] \
            == spiffe_id("default", "other")
    finally:
        cl.close()


# -- front postures the strict smoke doesn't cover --------------------

def test_permissive_front_encrypts_without_identity(ca):
    """Permissive: TLS encryption, client certs never requested, and
    therefore NO identity attributes are injected (connection.mtls
    stays honest — see secure/mtls.py docstring)."""
    certs = _serving(ca)
    store = MemStore()
    store.set(("handler", "istio-system", "denyall"), {
        "adapter": "denier", "params": {"status_message": "rbac"}})
    store.set(("instance", "istio-system", "nothing"), {
        "template": "checknothing", "params": {}})
    store.set(("rule", "istio-system", "deny-identified"), {
        "match": '(source.user | "") != ""',
        "actions": [{"handler": "denyall",
                     "instances": ["nothing"]}]})
    srv = RuntimeServer(store, ServerArgs(batch_window_s=0.001))
    front = MixerGrpcServer(srv, tls=certs, mtls_mode="permissive")
    cl = None
    try:
        base_auth = monitor.identity_counters()[
            "authenticated_checks_total"]
        port = front.start()
        cl = MixerClient(f"127.0.0.1:{port}",
                         enable_check_cache=False,
                         root_cert_pem=ca.get_root_certificate(),
                         server_name="mixer.local")
        resp = cl.check({"destination.service": "a.default.svc"})
        # no injected source.user -> the deny-identified rule is idle
        assert resp.precondition.status.code == 0
        assert monitor.identity_counters()[
            "authenticated_checks_total"] == base_auth
    finally:
        if cl is not None:
            cl.close()
        front.stop()
        srv.close()


def test_strict_front_requires_serving_certs():
    srv = RuntimeServer(MemStore(), ServerArgs(batch_window_s=0.001))
    try:
        with pytest.raises(ValueError, match="certs"):
            MixerGrpcServer(srv, tls=None, mtls_mode="strict")
        with pytest.raises(ValueError, match="mtls"):
            MixerGrpcServer(srv, tls=None, mtls_mode="bogus")
    finally:
        srv.close()


def test_native_front_tls_lane(ca):
    """The native h2 front serves through the stdlib-ssl terminating
    lane: strict handshakes verify the workload cert, cert-less peers
    never reach the pump, and a rotation applies to new accepts."""
    certs = _serving(ca)
    from istio_tpu.api.native_server import NativeMixerServer
    srv = RuntimeServer(MemStore(), ServerArgs(batch_window_s=0.001))
    native = NativeMixerServer(srv, tls=certs, mtls_mode="strict")
    cl = anon = None
    try:
        native.start()
        assert native.secure_port
        key = pki.generate_key()
        cert = ca.sign(pki.generate_csr(key, WEB))
        cl = MixerClient(f"127.0.0.1:{native.secure_port}",
                         enable_check_cache=False,
                         root_cert_pem=ca.get_root_certificate(),
                         key_pem=pki.key_to_pem(key), cert_pem=cert,
                         server_name="mixer.local")
        resp = cl.check({"destination.service": "a.default.svc"})
        assert resp.precondition.status.code == 0
        anon = MixerClient(f"127.0.0.1:{native.secure_port}",
                           enable_check_cache=False,
                           root_cert_pem=ca.get_root_certificate(),
                           server_name="mixer.local")
        with pytest.raises(grpc.RpcError) as exc:
            anon.check({"destination.service": "a.default.svc"})
        assert exc.value.code() == grpc.StatusCode.UNAVAILABLE
        assert native.tls_lane_stats()["handshake_failures"] >= 1
    finally:
        for c in (cl, anon):
            if c is not None:
                c.close()
        native.stop()
        srv.close()
