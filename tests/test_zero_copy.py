"""Zero-copy decode path (ISSUE 13): the C++ wire decoder writes
straight into persistent, page-aligned slot-tensor staging buffers —
these tests pin (a) the staging-ring lifecycle contract (rotation,
zero-on-reuse, LRU cap, alignment, bool-view aliasing) with NO
toolchain dependency, and (b) byte-exact parity of the staged decode
vs the Python `compiler/layout.Tensorizer` fallback across seeded
manifests including long strings that overflow a byte tier, map
attributes, and absent-attribute defaults (toolchain-gated like
test_native_shim — the fallback stays the conformance oracle)."""
import datetime

import numpy as np
import pytest

from istio_tpu.attribute.types import ValueType as V
from istio_tpu.compiler.layout import (InternTable, Tensorizer,
                                       build_layout)
from istio_tpu.native.tensorizer import NativeTensorizer

try:
    from istio_tpu.native import ensure_built
    ensure_built()
    HAVE_NATIVE = True
except Exception:      # toolchain missing → parity half skips
    HAVE_NATIVE = False

MANIFEST = {
    "destination.service": V.STRING, "source.namespace": V.STRING,
    "request.size": V.INT64, "request.path": V.STRING,
    "request.headers": V.STRING_MAP, "request.time": V.TIMESTAMP,
    "score": V.DOUBLE,
}


def _layout(max_str_len=32):
    return build_layout(
        MANIFEST,
        derived_keys=[("request.headers", "cookie"),
                      ("request.headers", ":authority")],
        byte_sources=["request.path", ("request.headers", "cookie")],
        max_str_len=max_str_len)


def _ring_only(layout, depth=4) -> NativeTensorizer:
    """A NativeTensorizer with ONLY the staging machinery live (no
    C++ shim handle) — the ring contract is pure python and must be
    testable in environments without the protoc toolchain."""
    t = NativeTensorizer.__new__(NativeTensorizer)
    t.layout = layout
    t.staging_depth = depth
    t._staging = {}
    t._staged_decodes = 0
    t._h = None              # __del__ guard
    return t


# ---------------------------------------------------------------------------
# staging-ring lifecycle (no toolchain needed)
# ---------------------------------------------------------------------------


def test_aligned_zeros_page_aligned_and_shaped():
    for shape, dtype in (((7, 3), np.int32), ((5, 2, 32), np.uint8),
                         ((4, 0), np.int32)):
        a = NativeTensorizer._aligned_zeros(shape, dtype)
        assert a.shape == shape and a.dtype == dtype
        assert not a.any()
        if a.nbytes:
            assert a.ctypes.data % 4096 == 0, "staging must be " \
                "page-aligned (DMA-mappable without a bounce copy)"


def test_ring_rotation_and_reuse_bound():
    """Consecutive decodes of one shape get DISTINCT buffer slots up
    to staging_depth; slot K is reused (and zeroed) exactly at decode
    K+depth — the reuse bound the serving pipeline relies on."""
    t = _ring_only(_layout(), depth=3)
    sets = [t._buffers_for(8) for _ in range(3)]
    ptrs = [s["ids"].ctypes.data for s in sets]
    assert len(set(ptrs)) == 3, "slots within the depth must not alias"
    # dirty slot 0, then rotate back to it: must come back zeroed
    sets[0]["ids"][...] = 7
    sets[0]["str_bytes"][...] = 9
    s4 = t._buffers_for(8)
    assert s4["ids"].ctypes.data == ptrs[0], "round-robin reuse"
    assert not s4["ids"].any() and not s4["str_bytes"].any(), \
        "reused slot must be zeroed before the shim writes"
    assert t.staging_stats()["staged_decodes"] == 4
    assert t.staging_stats()["shapes"] == {8: 3}


def test_ring_lru_cap_evicts_coldest_shape():
    """The shape→ring map is LRU-bounded: a new shape past
    _STAGING_SHAPES evicts the least-recently-used ring (so warmup's
    arbitrary sizes can never permanently pin the rings away from
    the hot bucket shapes), a re-used shape moves to the MRU end,
    and an evicted shape's old buffers are NOT reused when it comes
    back — in-flight batches keep them alive untouched."""
    cap = NativeTensorizer._STAGING_SHAPES
    t = _ring_only(_layout(), depth=2)
    first = t._buffers_for(1)           # shape 1 = the LRU candidate
    for n in range(2, cap + 1):
        t._buffers_for(n)
    t._buffers_for(2)                   # touch: 2 becomes MRU
    t._buffers_for(99)                  # over the cap: evicts shape 1
    shapes = set(t.staging_stats()["shapes"])
    assert 1 not in shapes and 99 in shapes and 2 in shapes
    # shape 1 re-admitted later: fresh buffers, never the old slot
    # (which an in-flight batch may still be reading)
    first["ids"][...] = 7
    again = t._buffers_for(1)
    assert again["ids"].ctypes.data != first["ids"].ctypes.data
    assert not again["ids"].any()
    assert (first["ids"] == 7).all(), \
        "eviction must never clobber a live buffer"


def test_bool_views_alias_staging_bytes():
    """The presence planes returned to the engine are dtype VIEWS of
    the staging bytes (zero copies), shaped like the python
    tensorizer's bool planes."""
    t = _ring_only(_layout())
    s = t._buffers_for(4)
    v = s["present_u8"].view(bool)
    assert v.dtype == bool and v.shape == s["present_u8"].shape
    s["present_u8"][1, 0] = 1
    assert bool(v[1, 0]), "view must alias the staging buffer"


# ---------------------------------------------------------------------------
# byte-exact parity vs the python tensorizer (toolchain-gated)
# ---------------------------------------------------------------------------

pytestmark_parity = pytest.mark.skipif(
    not HAVE_NATIVE, reason="native shim toolchain unavailable")


def _worlds(seed: int, n: int, max_str_len: int) -> list[dict]:
    """Seeded request dicts stressing the decode corners the parity
    gate owes: long strings OVERFLOWING the byte tier (truncation
    contract), map attributes (derived + byte pair slots), and
    absent-attribute defaults (rows missing most of the manifest)."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        d = {}
        r = rng.random()
        if r < 0.25:     # absent-attribute rows: only one attr set
            d["request.size"] = int(rng.integers(0, 1 << 30))
        else:
            d["destination.service"] = \
                f"svc{rng.integers(0, 5)}.ns{i % 3}.svc.cluster.local"
            if rng.random() < 0.7:
                # every third long path OVERFLOWS max_str_len — the
                # slice/truncation parity leg
                length = int(rng.integers(1, max_str_len * 3))
                d["request.path"] = "/" + "x" * length
            if rng.random() < 0.6:
                d["request.headers"] = {
                    "cookie": "c" * int(rng.integers(1,
                                                     max_str_len * 2)),
                    ":authority": f"web{i % 4}"}
            if rng.random() < 0.4:
                d["score"] = float(np.round(rng.random(), 6))
            if rng.random() < 0.3:
                d["request.time"] = datetime.datetime(
                    2018, 3, int(rng.integers(1, 28)), 6, 0, 1,
                    tzinfo=datetime.timezone.utc)
        out.append(d)
    return out


@pytestmark_parity
@pytest.mark.parametrize("seed,max_str_len", [(0, 32), (1, 32),
                                              (2, 16), (3, 64)])
def test_staged_decode_parity_vs_python_fallback(seed, max_str_len):
    """Property: for seeded worlds over seeded layouts, the staged
    zero-copy decode is BYTE-EXACT vs the python tensorizer on every
    plane — including repeat decodes through the same ring slots
    (batch k and batch k+depth land in the same buffers)."""
    from istio_tpu.api.wire import bag_to_compressed
    from istio_tpu.attribute.bag import bag_from_mapping

    layout = _layout(max_str_len=max_str_len)
    interner = InternTable()
    native = NativeTensorizer(layout, interner, staging_depth=3)
    oracle = Tensorizer(layout, interner)
    # MORE batches than the ring depth: every slot gets dirtied by an
    # earlier batch and must decode later batches byte-identically
    for k in range(5):
        dicts = _worlds(seed * 10 + k, 24, max_str_len)
        records = [bag_to_compressed(d).SerializeToString()
                   for d in dicts]
        got = native.tensorize_wire(records)
        want = oracle.tensorize([bag_from_mapping(d) for d in dicts])
        np.testing.assert_array_equal(np.asarray(got.present),
                                      np.asarray(want.present),
                                      err_msg=f"batch {k} present")
        np.testing.assert_array_equal(np.asarray(got.map_present),
                                      np.asarray(want.map_present),
                                      err_msg=f"batch {k} map_present")
        np.testing.assert_array_equal(np.asarray(got.str_bytes),
                                      np.asarray(want.str_bytes),
                                      err_msg=f"batch {k} str_bytes")
        np.testing.assert_array_equal(np.asarray(got.str_lens),
                                      np.asarray(want.str_lens),
                                      err_msg=f"batch {k} str_lens")
        # ids: constants share exact non-negative ids; ephemeral
        # (negative) ids must DECODE to the same value
        gi, oi = np.asarray(got.ids), np.asarray(want.ids)
        gp = np.asarray(got.present)
        from istio_tpu.compiler.layout import _normalize
        for r, c in zip(*np.nonzero(gp)):
            a, b = int(gi[r, c]), int(oi[r, c])
            if a >= 0 or b >= 0:
                assert a == b, (k, r, c)
            else:
                assert _normalize(got.value_of(a, interner)) == \
                    _normalize(want.value_of(b, interner)), (k, r, c)
    stats = native.staging_stats()
    assert stats["staged_decodes"] == 5
    assert stats["shapes"] == {24: 3}, "ring must have rotated"


@pytestmark_parity
def test_staged_batches_do_not_alias_within_depth():
    """Two in-flight batches (the pipeline bound) must never share
    buffers — batch N's planes stay intact while batch N+1 decodes."""
    from istio_tpu.api.wire import bag_to_compressed

    layout = _layout()
    native = NativeTensorizer(layout, InternTable(), staging_depth=4)
    rec_a = [bag_to_compressed(
        {"destination.service": "a.ns1.svc"}).SerializeToString()] * 4
    rec_b = [bag_to_compressed(
        {"request.size": 7}).SerializeToString()] * 4
    ba = native.tensorize_wire(rec_a)
    snapshot = np.asarray(ba.present).copy()
    bb = native.tensorize_wire(rec_b)
    assert np.asarray(ba.present).ctypes.data != \
        np.asarray(bb.present).ctypes.data
    np.testing.assert_array_equal(np.asarray(ba.present), snapshot,
                                  err_msg="batch A mutated by batch B")
