"""Golden-file tests for the envoy config generator.

Reference pattern: pilot/pkg/proxy/envoy/config_test.go + testdata/
*.json — generated config is compared byte-for-byte against checked-in
goldens so accidental drift is caught; regenerate with
REFRESH_GOLDENS=1 after intentional changes (the reference's refresh
flag in pilot/test/util).

The fixture mesh exercises every generator feature: weighted routes,
faults, CB/outlier policies, mirror/CORS/retries/websocket, TCP/Mongo/
Redis ports, egress rules (exact + wildcard), ingress rules, and
JWKS-backed auth clusters — for sidecar, ingress, and router nodes.
"""
import json
import os

import pytest

from istio_tpu.pilot.discovery import DiscoveryService
from istio_tpu.pilot.model import (Config, ConfigMeta, MemoryConfigStore,
                                   Port, Service)
from istio_tpu.pilot.registry import MemoryRegistry

GOLDEN_DIR = os.path.join(os.path.dirname(__file__), "testdata", "envoy")
REFRESH = os.environ.get("REFRESH_GOLDENS") == "1"

SIDECAR = "sidecar~10.1.0.7~productpage-v1.default~cluster.local"
INGRESS = "ingress~10.3.0.1~istio-ingress.istio-system~cluster.local"
ROUTER = "router~10.4.0.1~istio-router.istio-system~cluster.local"


def _fixture():
    reg = MemoryRegistry()
    reg.add_service(
        Service(hostname="productpage.default.svc.cluster.local",
                address="10.0.0.1",
                ports=(Port("http", 9080, "HTTP"),)),
        endpoints=[("10.1.0.7", {"app": "productpage"})])
    reg.add_service(
        Service(hostname="reviews.default.svc.cluster.local",
                address="10.0.0.2",
                ports=(Port("http", 9080, "HTTP"),
                       Port("grpc-status", 9090, "GRPC"))),
        endpoints=[("10.1.0.8", {"app": "reviews", "version": "v1"}),
                   ("10.1.0.9", {"app": "reviews", "version": "v2"})])
    reg.add_service(
        Service(hostname="mongodb.default.svc.cluster.local",
                address="10.0.0.3",
                ports=(Port("mongo", 27017, "MONGO"),)))
    reg.add_service(
        Service(hostname="redis.default.svc.cluster.local",
                address="10.0.0.4",
                ports=(Port("redis", 6379, "REDIS"),)))

    store = MemoryConfigStore()
    cfgs = [
        # weighted split + retry + mirror + CORS + websocket
        Config(meta=ConfigMeta(type="route-rule", name="reviews-split",
                               namespace="default"),
               spec={"destination": {"service":
                                     "reviews.default.svc.cluster.local"},
                     "precedence": 2,
                     "route": [{"labels": {"version": "v1"}, "weight": 80},
                               {"labels": {"version": "v2"},
                                "weight": 20}],
                     "httpReqRetries": {"simpleRetry": {"attempts": 3}},
                     "mirror": {"labels": {"version": "v2"}},
                     "corsPolicy": {"allowOrigin": ["*"],
                                    "allowMethods": ["GET", "POST"]},
                     "websocketUpgrade": True}),
        # fault injection scoped by a header match
        Config(meta=ConfigMeta(type="route-rule", name="ratings-abort",
                               namespace="default"),
               spec={"destination": {"service":
                                     "productpage.default.svc.cluster.local"},
                     "precedence": 1,
                     "match": {"request": {"headers": {
                         "cookie": {"regex": "^(.*?;)?(user=jason)(;.*)?$"
                                    }}}},
                     "httpFault": {"abort": {"percent": 100,
                                             "httpStatus": 500},
                                   "delay": {"percent": 50,
                                             "fixedDelay": "5s"}}}),
        # destination policy: CB + outlier + LB
        Config(meta=ConfigMeta(type="destination-policy", name="reviews-cb",
                               namespace="default"),
               spec={"destination": {"service":
                                     "reviews.default.svc.cluster.local"},
                     "loadBalancing": {"name": "LEAST_CONN"},
                     "circuitBreaker": {"simpleCb": {
                         "maxConnections": 100,
                         "httpMaxPendingRequests": 32,
                         "httpConsecutiveErrors": 5,
                         "httpDetectionInterval": "10s",
                         "sleepWindow": "30s"}}}),
        # egress: exact + wildcard
        Config(meta=ConfigMeta(type="egress-rule", name="httpbin-egress",
                               namespace="default"),
               spec={"destination": {"service": "httpbin.org"},
                     "ports": [{"port": 9080, "protocol": "http"}]}),
        Config(meta=ConfigMeta(type="egress-rule", name="wildcard-egress",
                               namespace="default"),
               spec={"destination": {"service": "*.googleapis.com"},
                     "ports": [{"port": 9080, "protocol": "http"}]}),
        # ingress rules (what the kube ingress controller emits)
        Config(meta=ConfigMeta(type="ingress-rule", name="gw-1-0",
                               namespace="default"),
               spec={"destination": {"service":
                                     "productpage.default.svc.cluster.local"},
                     "port": 9080,
                     "match": {"request": {"headers": {
                         "authority": {"exact": "bookinfo.example.com"},
                         "uri": {"exact": "/productpage"}}}}}),
        Config(meta=ConfigMeta(type="ingress-rule", name="gw-1-1",
                               namespace="default"),
               spec={"destination": {"service":
                                     "reviews.default.svc.cluster.local"},
                     "port": "http",
                     "match": {"request": {"headers": {
                         "uri": {"prefix": "/reviews/"}}}}}),
        # auth policy with JWKS endpoints
        Config(meta=ConfigMeta(type="end-user-authentication-policy-spec",
                               name="jwt-example", namespace="default"),
               spec={"jwts": [{"issuer": "https://accounts.example.com",
                               "jwksUri":
                                   "https://accounts.example.com/certs",
                               "audiences": ["bookinfo"]},
                              {"issuer": "testing@secure.istio.io",
                               "jwksUri":
                                   "http://keys.local:8080/jwks.json"}]}),
    ]
    for c in cfgs:
        store.create(c)
    mesh = {"mixer_address": "istio-mixer.istio-system:9091",
            "zipkin_address": "zipkin.istio-system:9411",
            "node_uid": "kubernetes://productpage-v1.default",
            "ingress_tls": {"cert_chain_file": "/etc/certs/tls.crt",
                            "private_key_file": "/etc/certs/tls.key"}}
    return DiscoveryService(reg, store, mesh)


def _check_golden(name: str, payload: bytes) -> None:
    path = os.path.join(GOLDEN_DIR, name)
    pretty = json.dumps(json.loads(payload), indent=2,
                        sort_keys=True) + "\n"
    if REFRESH or not os.path.exists(path):
        os.makedirs(GOLDEN_DIR, exist_ok=True)
        with open(path, "w", encoding="utf-8") as f:
            f.write(pretty)
        if not REFRESH:
            pytest.skip(f"golden {name} created; rerun to compare")
        return
    with open(path, encoding="utf-8") as f:
        want = f.read()
    assert pretty == want, (
        f"{name} drifted from golden (REFRESH_GOLDENS=1 to regenerate)")


@pytest.fixture(scope="module")
def ds():
    return _fixture()


def test_golden_sidecar_listeners(ds):
    _check_golden("lds_sidecar.json", ds.list_listeners("istio", SIDECAR))


def test_golden_sidecar_clusters(ds):
    _check_golden("cds_sidecar.json", ds.list_clusters("istio", SIDECAR))


def test_golden_sidecar_routes(ds):
    _check_golden("rds_9080_sidecar.json",
                  ds.list_routes("9080", "istio", SIDECAR))


def test_golden_ingress_listeners(ds):
    _check_golden("lds_ingress.json", ds.list_listeners("istio", INGRESS))


def test_golden_ingress_routes(ds):
    _check_golden("rds_ingress.json",
                  ds.list_routes("80", "istio", INGRESS))


def test_golden_router_listeners(ds):
    _check_golden("lds_router.json", ds.list_listeners("istio", ROUTER))


def test_goldens_pass_strict_v1_schema(ds):
    """Every emitted document validates against the strict resources.go
    field/type/enum schema (pilot/envoy_schema.py) — the structural
    stand-in for the reference's Envoy-binary-in-the-loop validation
    (mixer/test/client/env/envoy.go; no Envoy ships in this image)."""
    from istio_tpu.pilot import envoy_schema as es

    for node in (SIDECAR, INGRESS, ROUTER):
        lds = json.loads(ds.list_listeners("istio", node))
        es.validate_listeners(lds["listeners"])
        cds = json.loads(ds.list_clusters("istio", node))
        es.validate_clusters(cds["clusters"])
    for port, node in (("9080", SIDECAR), ("80", INGRESS)):
        es.validate_route_config(
            json.loads(ds.list_routes(port, "istio", node)))


def test_schema_rejects_malformed_shapes():
    """Invalid listener/cluster shapes FAIL (VERDICT r2 item 8)."""
    import pytest as _pytest

    from istio_tpu.pilot import envoy_schema as es

    ok_listener = {
        "address": "tcp://0.0.0.0:80", "name": "http_0.0.0.0_80",
        "bind_to_port": True,
        "filters": [{"type": "read", "name": "tcp_proxy",
                     "config": {"stat_prefix": "tcp",
                                "route_config": {"routes": [
                                    {"cluster": "c"}]}}}]}
    es.validate(ok_listener, "Listener")
    bad = [
        # missing required bind_to_port
        {k: v for k, v in ok_listener.items() if k != "bind_to_port"},
        # unknown field (generator typo)
        dict(ok_listener, bindToPort=True),
        # unknown network filter name
        dict(ok_listener, filters=[{"type": "read", "name": "nope",
                                    "config": {}}]),
        # wrong type for address
        dict(ok_listener, address=80),
    ]
    for i, b in enumerate(bad):
        with _pytest.raises(es.EnvoySchemaError):
            es.validate(b, "Listener")

    ok_cluster = {"name": "c", "connect_timeout_ms": 1000,
                  "type": "strict_dns", "lb_type": "round_robin",
                  "hosts": [{"url": "tcp://10.0.0.1:80"}]}
    es.validate(ok_cluster, "Cluster")
    with _pytest.raises(es.EnvoySchemaError):   # enum violation
        es.validate(dict(ok_cluster, lb_type="fastest"), "Cluster")
    with _pytest.raises(es.EnvoySchemaError):   # bool-as-int
        es.validate(dict(ok_cluster, connect_timeout_ms=True),
                    "Cluster")
    # route invariants
    with _pytest.raises(es.EnvoySchemaError):   # both cluster forms
        es.validate({"prefix": "/", "timeout_ms": 0, "cluster": "a",
                     "weighted_clusters": {"clusters": [
                         {"name": "a", "weight": 100}]}}, "HTTPRoute")
    with _pytest.raises(es.EnvoySchemaError):   # two matchers
        es.validate({"prefix": "/", "path": "/x", "timeout_ms": 0,
                     "cluster": "a"}, "HTTPRoute")


def test_feature_assertions(ds):
    """Structural spot checks so the goldens can't fossilize a bug."""
    cds = json.loads(ds.list_clusters("istio", SIDECAR))
    names = {c["name"] for c in cds["clusters"]}
    assert "egress.httpbin.org|9080" in names
    assert "egress.*.googleapis.com|9080" in names
    assert "jwks.accounts.example.com|443" in names
    assert "jwks.keys.local|8080" in names
    jwks = next(c for c in cds["clusters"]
                if c["name"] == "jwks.accounts.example.com|443")
    assert "ssl_context" in jwks
    wild = next(c for c in cds["clusters"]
                if c["name"] == "egress.*.googleapis.com|9080")
    assert wild["type"] == "original_dst"
    cb = next(c for c in cds["clusters"]
              if c["name"].startswith(
                  "out.reviews.default.svc.cluster.local|http"))
    assert cb["circuit_breakers"]["default"]["max_connections"] == 100
    assert cb["outlier_detection"]["consecutive_5xx"] == 5
    assert cb["lb_type"] == "least_request"

    lds = json.loads(ds.list_listeners("istio", SIDECAR))
    by_name = {l["name"]: l for l in lds["listeners"]}
    assert by_name["tcp_0.0.0.0_27017"]["filters"][0]["name"] == \
        "mongo_proxy"
    assert by_name["redis_0.0.0.0_6379"]["filters"][0]["name"] == \
        "redis_proxy"
    # egress-only port still gets an HTTP listener riding RDS
    assert "http_0.0.0.0_9080" in by_name

    rds = json.loads(ds.list_routes("9080", "istio", SIDECAR))
    vh_names = {v["name"] for v in rds["virtual_hosts"]}
    assert "egress|httpbin.org|9080" in vh_names
    assert "egress|*.googleapis.com|9080" in vh_names

    ing = json.loads(ds.list_routes("80", "istio", INGRESS))
    hosts = {v["name"]: v for v in ing["virtual_hosts"]}
    assert "ingress|bookinfo.example.com" in hosts
    assert "ingress|*" in hosts
    exact = hosts["ingress|bookinfo.example.com"]["routes"][0]
    assert exact["path"] == "/productpage"
    assert exact["cluster"].startswith("out.productpage")

    ingress_lds = json.loads(ds.list_listeners("istio", INGRESS))
    assert {l["name"] for l in ingress_lds["listeners"]} == \
        {"ingress_80", "ingress_443"}
    assert "ssl_context" in next(
        l for l in ingress_lds["listeners"] if l["name"] == "ingress_443")

    router_lds = json.loads(ds.list_listeners("istio", ROUTER))
    assert all(not l["name"].startswith("http_10.")
               for l in router_lds["listeners"])   # no inbound
