"""Tier-1 hook for scripts/lifecycle_smoke.py: the CI gate that the
stack is restartable — 50× native start/stop cycles (with deliberate
double-stops) leak nothing and drop nothing, SIGTERM under live
traffic runs the ordered graceful shutdown and exits 0 (a negative
returncode would mean the abort-on-teardown class PR 7 removed), and
a config swap storm never pauses serving. Runs main() in-process (the
chaos_smoke pattern — the SIGTERM phase spawns its one subprocess
internally); the script stays runnable standalone under
JAX_PLATFORMS=cpu."""
import importlib.util
import os
import sys


def test_lifecycle_smoke_main():
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "lifecycle_smoke.py")
    spec = importlib.util.spec_from_file_location("lifecycle_smoke",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[spec.name] = mod
    try:
        spec.loader.exec_module(mod)
        rc = mod.main(cycles=50, swaps=4, traffic_s=0.6)
    finally:
        sys.modules.pop(spec.name, None)
    assert rc == 0
