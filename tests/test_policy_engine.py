"""PolicyEngine fused-step semantics: denier, listentry, quota, TTL
combine, and referenced-attribute bitmaps (reference behaviors:
dispatcher.combineResults dispatcher.go:322, denier.go, list.go:68,
memquota.go:107)."""
import numpy as np

from istio_tpu.attribute.bag import bag_from_mapping
from istio_tpu.compiler.ruleset import Rule
from istio_tpu.expr.checker import AttributeDescriptorFinder
from istio_tpu.models.policy_engine import (DenySpec, ListEntrySpec,
                                            NOT_FOUND, OK,
                                            PERMISSION_DENIED, PolicyEngine,
                                            QuotaSpec, RESOURCE_EXHAUSTED)
from istio_tpu.testing.corpus import CORPUS_MANIFEST

FINDER = AttributeDescriptorFinder(CORPUS_MANIFEST)


def _run(engine, bag_dicts, ns=None):
    bags = [bag_from_mapping(d) for d in bag_dicts]
    batch = engine.tensorizer.tensorize(bags)
    req_ns = np.zeros(len(bags), np.int32) if ns is None else np.asarray(ns)
    return engine.check(batch, req_ns)


def test_denier_path():
    rules = [Rule(name="deny-user", match='request.user == "evil"')]
    eng = PolicyEngine(rules, FINDER,
                       deny=[DenySpec(rule=0, valid_duration_s=7.0,
                                      valid_use_count=42)])
    v = _run(eng, [{"request.user": "evil"}, {"request.user": "good"}, {}])
    assert v.status.tolist() == [PERMISSION_DENIED, OK, OK]
    assert float(v.valid_duration_s[0]) == 7.0
    assert int(v.valid_use_count[0]) == 42
    # non-denied requests keep "infinite" TTLs (runtime clamps to defaults)
    assert float(v.valid_duration_s[1]) > 1e30


def test_whitelist_and_blacklist():
    rules = [Rule(name="wl", match=""), Rule(name="bl", match="")]
    eng = PolicyEngine(
        rules, FINDER,
        lists=[ListEntrySpec(rule=0, value_attr="source.namespace",
                             entries=["ns-a", "ns-b"]),
               ListEntrySpec(rule=1, value_attr="request.user",
                             entries=["bad"], blacklist=True)])
    v = _run(eng, [
        {"source.namespace": "ns-a", "request.user": "ok"},   # both pass
        {"source.namespace": "ns-z", "request.user": "ok"},   # wl denies
        {"source.namespace": "ns-b", "request.user": "bad"},  # bl denies
    ])
    # host-adapter parity: whitelist miss → NOT_FOUND, blacklist hit →
    # PERMISSION_DENIED (adapters/list_adapter.py)
    assert v.status.tolist() == [OK, NOT_FOUND, PERMISSION_DENIED]


def test_list_absent_value_is_internal():
    """Absent checked attribute on an ACTIVE list rule → INTERNAL with
    default TTLs, exactly like the host path (instance build EvalError
    → _safe_check → CheckResult(INTERNAL); r4 parity fix — the device
    previously failed open)."""
    from istio_tpu.models.policy_engine import INTERNAL
    rules = [Rule(name="wl", match="")]
    eng = PolicyEngine(rules, FINDER,
                       lists=[ListEntrySpec(rule=0, value_attr="request.user",
                                            entries=["alice"])])
    v = _run(eng, [{}, {"request.user": "alice"}])
    assert v.status.tolist() == [INTERNAL, OK]
    assert float(v.valid_duration_s[0]) == 5.0    # CheckResult default
    # the device TTL-fold constants must track the adapter SDK's
    # CheckResult defaults (host _combine parity; they can't share a
    # module without an import cycle)
    from istio_tpu.adapters.sdk import (DEFAULT_VALID_DURATION_S,
                                        DEFAULT_VALID_USE_COUNT)
    from istio_tpu.models.policy_engine import DEFAULT_DUR, DEFAULT_USES
    assert float(DEFAULT_DUR) == DEFAULT_VALID_DURATION_S
    assert int(DEFAULT_USES) == DEFAULT_VALID_USE_COUNT


def test_quota_fixed_window():
    rules = [Rule(name="q", match="")]
    eng = PolicyEngine(rules, FINDER,
                       quotas=[QuotaSpec(rule=0, key_attr="request.user",
                                         max_amount=3)])
    # 5 requests from same key in one batch: 3 granted, 2 exhausted
    v = _run(eng, [{"request.user": "u"}] * 5)
    assert sorted(v.status.tolist()) == [OK, OK, OK,
                                         RESOURCE_EXHAUSTED,
                                         RESOURCE_EXHAUSTED]
    # next batch: window still consumed
    v2 = _run(eng, [{"request.user": "u"}, {"request.user": "other"}])
    assert v2.status.tolist() == [RESOURCE_EXHAUSTED, OK]
    eng.reset_quota()
    v3 = _run(eng, [{"request.user": "u"}])
    assert v3.status.tolist() == [OK]


def test_quota_bucket_stable_across_batches():
    """Quota buckets key on a stable content hash, not on intern or
    ephemeral ids: the same runtime key must hit the same bucket no
    matter what order values were first observed in (a sequential
    per-batch id would let a consumed window be evaded by reordering)."""
    rules = [Rule(name="q", match="")]
    eng = PolicyEngine(rules, FINDER,
                       quotas=[QuotaSpec(rule=0, key_attr="request.user",
                                         max_amount=2)])
    # "u" is first in batch 1...
    v1 = _run(eng, [{"request.user": "u"}, {"request.user": "u"}])
    assert v1.status.tolist() == [OK, OK]
    # ...but second in batch 2, behind two fresh keys: still exhausted
    v2 = _run(eng, [{"request.user": "a"}, {"request.user": "b"},
                    {"request.user": "u"}])
    assert v2.status.tolist()[2] == RESOURCE_EXHAUSTED
    assert v2.status.tolist()[:2] == [OK, OK]


def test_denied_requests_do_not_consume_quota():
    """Quota runs only after a successful precondition check
    (grpcServer.go:188-230): a denied request must not take tokens."""
    rules = [Rule(name="deny", match='request.user == "evil"'),
             Rule(name="q", match="")]
    eng = PolicyEngine(rules, FINDER, deny=[DenySpec(rule=0)],
                       quotas=[QuotaSpec(rule=1, key_attr="source.namespace",
                                         max_amount=1)])
    v = _run(eng, [{"request.user": "evil", "source.namespace": "ns"},
                   {"request.user": "good", "source.namespace": "ns"}])
    assert v.status.tolist() == [PERMISSION_DENIED, OK]


def test_namespace_scoping():
    rules = [Rule(name="deny-ns1", match="", namespace="ns1")]
    eng = PolicyEngine(rules, FINDER, deny=[DenySpec(rule=0)])
    ns1 = eng.ruleset.namespace_id("ns1")
    other = eng.ruleset.namespace_id("absent-ns")
    v = _run(eng, [{}, {}], ns=[ns1, other])
    assert v.status.tolist() == [PERMISSION_DENIED, OK]


def test_referenced_attribute_bitmap():
    rules = [Rule(name="r", match='request.user == "x"')]
    eng = PolicyEngine(rules, FINDER, deny=[DenySpec(rule=0)])
    v = _run(eng, [{"request.user": "x"}])
    col = eng.ruleset.layout.slot_of("request.user")
    assert bool(v.referenced[0, col])


def test_ttl_combine_takes_min():
    rules = [Rule(name="a", match=""), Rule(name="b", match="")]
    eng = PolicyEngine(rules, FINDER,
                       deny=[DenySpec(rule=0, valid_duration_s=9.0),
                             DenySpec(rule=1, valid_duration_s=2.0)])
    v = _run(eng, [{}])
    assert float(v.valid_duration_s[0]) == 2.0
