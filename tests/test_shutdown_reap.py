"""Regression for the start/stop-cycle teardown race: background
prewarm/warm threads must be stop-flag-checked and REAPED before
shutdown returns, and a post-close store event must not resurrect a
rebuild. The original failure mode was a background prewarm thread
racing interpreter/device teardown (flaky XLA segfault at process
exit under repeated server cycles)."""
import threading
import time

from istio_tpu.runtime import RuntimeServer, ServerArgs
from istio_tpu.testing import workloads

PREWARM_NAMES = ("prewarm-initial", "prewarm-swap")


def _prewarm_threads():
    return [t for t in threading.enumerate()
            if t.is_alive() and t.name in PREWARM_NAMES]


def test_cycle_reaps_prewarm_threads():
    """Three build→churn→shutdown cycles: after every shutdown no
    prewarm/warm thread may still be alive."""
    for cycle in range(3):
        store = workloads.make_store(12, seed=cycle)
        srv = RuntimeServer(store, ServerArgs(
            batch_window_s=0.0005, max_batch=8, buckets=(8,),
            audit=False, default_manifest=workloads.MESH_MANIFEST))
        try:
            # kick the debounced rebuild path so a swap-warm thread
            # actually exists when shutdown lands
            key = ("rule", "istio-system", "report-all")
            spec = store.get(key)
            if spec is not None:
                store.set(key, dict(spec))
            time.sleep(0.08)
        finally:
            srv.shutdown(deadline=5.0)
            srv.close()
        leftover = _prewarm_threads()
        assert not leftover, (
            f"cycle {cycle}: prewarm threads survived shutdown: "
            f"{[t.name for t in leftover]}")


def test_post_close_store_event_does_not_rebuild():
    """A store mutation after close() must be a no-op: the controller
    refuses rebuilds once closing (the _closing guard), so no fresh
    dispatcher generation appears."""
    store = workloads.make_store(12, seed=7)
    srv = RuntimeServer(store, ServerArgs(
        batch_window_s=0.0005, max_batch=8, buckets=(8,),
        audit=False, default_manifest=workloads.MESH_MANIFEST))
    ctrl = srv.controller
    srv.shutdown(deadline=5.0)
    srv.close()
    gen_before = ctrl.dispatcher
    key = ("rule", "istio-system", "report-all")
    spec = store.get(key)
    assert spec is not None
    store.set(key, dict(spec))
    time.sleep(0.3)     # > debounce_s: a live controller would rebuild
    assert ctrl.dispatcher is gen_before, \
        "store event after close still rebuilt the dispatcher"
    assert not _prewarm_threads()
